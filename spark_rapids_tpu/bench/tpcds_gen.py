"""SF-scalable TPC-DS-shaped data generator (column-pruned, parquet).

Generates the tables the 20-query slice uses — store_sales, catalog_sales,
web_sales, date_dim, time_dim, item, customer, customer_address, store,
customer_demographics, household_demographics, promotion — with
dsdgen-like row counts, key ranges, null fractions, and surrogate-key
conventions (d_date_sk epoch 2415022 = 1900-01-01, store_sales ~2.88M
rows/SF).  Columns are pruned to those the queries touch; distributions
are synthetic (deterministic numpy, seeded), NOT dsdgen bit-exact — this
measures engine speed, not dsdgen conformance.  Reference harness:
TpcdsLikeSpark.scala (explicit schemas + csv-to-parquet conversion),
docs/benchmarks.md:104-147.
"""
from __future__ import annotations

import os
import zlib
from typing import Sequence

import numpy as np

__all__ = ["generate_tpcds", "table_row_counts", "TABLES"]

TABLES = ("date_dim", "time_dim", "item", "customer", "customer_address",
          "store", "customer_demographics", "household_demographics",
          "promotion", "warehouse", "ship_mode", "reason", "income_band",
          "call_center", "web_site", "web_page", "catalog_page",
          "inventory", "store_sales", "store_returns",
          "catalog_sales", "catalog_returns", "web_sales", "web_returns")

#: bump when generated schemas change; tables regenerate on mismatch
_SCHEMA_VERSION = "v6"

#: returns tables are sampled FROM their parent's rows so that joins on
#: (item_sk, ticket/order number) actually match (dsdgen links them the
#: same way); generated right after the parent from its in-memory data
_RETURNS_PARENT = {"store_returns": "store_sales",
                   "catalog_returns": "catalog_sales",
                   "web_returns": "web_sales"}

_DATE_SK_EPOCH = 2415022            # dsdgen: d_date_sk of 1900-01-01
_DATE_DIM_DAYS = 73049              # 1900-01-01 .. 2099-12-31
_SALES_DATE_LO = 35794              # days(1998-01-01 - 1900-01-01)
_SALES_DATE_HI = 37985              # days(2003-12-31 - 1900-01-01)
_UNIX_EPOCH_OFF = 25567             # days(1970-01-01 - 1900-01-01)

_CATEGORIES = ["Books", "Children", "Electronics", "Home", "Jewelry",
               "Men", "Music", "Shoes", "Sports", "Women"]
_CLASSES = ["accent", "bedding", "birdal", "blinds/shades", "classical",
            "computers", "curtains/drapes", "decor", "dresses", "earings",
            "fiction", "fragrances", "furniture", "glassware", "history",
            "infants", "jewelry boxes", "kids", "maternity", "mattresses",
            "mens", "musical", "mystery", "pants", "pendants", "pop",
            "reference", "rock", "romance", "rugs", "scanners", "shirts",
            "swimwear", "tables", "wallpaper", "womens"]
_STATES = ["AL", "AK", "AZ", "AR", "CA", "CO", "CT", "DE", "FL", "GA",
           "HI", "ID", "IL", "IN", "IA", "KS", "KY", "LA", "ME", "MD",
           "MA", "MI", "MN", "MS", "MO", "MT", "NE", "NV", "NH", "NJ",
           "NM", "NY", "NC", "ND", "OH", "OK", "OR", "PA", "RI", "SC",
           "SD", "TN", "TX", "UT", "VT", "VA", "WA", "WV", "WI", "WY"]
_FIRST = ["James", "Mary", "John", "Patricia", "Robert", "Jennifer",
          "Michael", "Linda", "William", "Elizabeth", "David", "Barbara"]
_LAST = ["Smith", "Johnson", "Williams", "Brown", "Jones", "Garcia",
         "Miller", "Davis", "Rodriguez", "Martinez", "Hernandez", "Lopez"]


def table_row_counts(sf: float) -> dict[str, int]:
    """dsdgen-like scaling: fact tables linear in SF; dimensions
    sublinear (item SF1=18k, customer SF1=100k)."""
    sf = max(sf, 0.001)
    n_cust = max(200, int(100_000 * sf ** 0.7))
    n_ss = max(1000, int(2_880_000 * sf))
    n_cs = max(500, int(1_440_000 * sf))
    n_ws = max(250, int(720_000 * sf))
    return {
        "date_dim": _DATE_DIM_DAYS,
        "time_dim": 86_400,
        "item": max(100, int(18_000 * sf ** 0.5)),
        "customer": n_cust,
        "customer_address": max(100, n_cust // 2),
        "store": max(4, int(12 * sf ** 0.5)),
        "customer_demographics": max(500, int(50_000 * sf ** 0.5)),
        "household_demographics": 7_200,
        "promotion": max(30, int(300 * sf ** 0.5)),
        "warehouse": max(2, int(5 * sf ** 0.5)),
        "ship_mode": 20,
        "reason": 35,
        "income_band": 20,
        "call_center": max(2, int(6 * sf ** 0.25)),
        "web_site": max(2, int(30 * sf ** 0.25)),
        "web_page": max(10, int(60 * sf ** 0.25)),
        "catalog_page": max(100, int(11_000 * sf ** 0.25)),
        # dsdgen inventory is (items x warehouses x weeks); sampled to a
        # bench-sized subset that still exercises the same join/agg shapes
        "inventory": max(5000, int(1_200_000 * sf)),
        "store_sales": n_ss,
        "store_returns": max(100, n_ss // 10),
        "catalog_sales": n_cs,
        "catalog_returns": max(50, n_cs // 10),
        "web_sales": n_ws,
        "web_returns": max(25, n_ws // 10),
    }


def _gen_date_dim(counts) -> dict[str, np.ndarray]:
    days = np.arange(_DATE_DIM_DAYS, dtype=np.int64)
    dates = np.datetime64("1900-01-01") + days
    y = dates.astype("datetime64[Y]").astype(int) + 1970
    m = dates.astype("datetime64[M]").astype(int) % 12 + 1
    dom = (dates - dates.astype("datetime64[M]")).astype(int) + 1
    dow = (days + 1) % 7            # 1900-01-01 was a Monday; 0 = Sunday
    day_names = np.array(["Sunday", "Monday", "Tuesday", "Wednesday",
                          "Thursday", "Friday", "Saturday"], dtype=object)
    q = ((m - 1) // 3 + 1)
    return {
        "d_date_sk": (days + _DATE_SK_EPOCH).astype(np.int32),
        "d_date": (days - _UNIX_EPOCH_OFF).astype(np.int32),  # DateType
        "d_year": y.astype(np.int32),
        "d_moy": m.astype(np.int32),
        "d_dom": dom.astype(np.int32),
        "d_dow": dow.astype(np.int32),
        "d_month_seq": ((y - 1900) * 12 + (m - 1)).astype(np.int32),
        "d_qoy": q.astype(np.int32),
        # weeks start Sunday (dow 0); 1900-01-01 (Monday) is in week 1
        "d_week_seq": ((days + 1) // 7 + 1).astype(np.int32),
        "d_day_name": day_names[dow],
        "d_quarter_name": np.array([f"{yy}Q{qq}" for yy, qq in zip(y, q)],
                                   dtype=object),
    }


def _gen_time_dim(_counts) -> dict[str, np.ndarray]:
    secs = np.arange(86_400, dtype=np.int64)
    return {
        "t_time_sk": secs.astype(np.int32),
        "t_time": secs.astype(np.int32),  # seconds since midnight (dsdgen)
        "t_hour": (secs // 3600).astype(np.int32),
        "t_minute": ((secs // 60) % 60).astype(np.int32),
        # dsdgen meal-time bands; NULL outside them
        "t_meal_time": np.where(
            (secs >= 6 * 3600) & (secs < 9 * 3600), "breakfast",
            np.where((secs >= 12 * 3600) & (secs < 14 * 3600), "lunch",
                     np.where((secs >= 17 * 3600) & (secs < 21 * 3600),
                              "dinner", None))).astype(object),
    }


def _with_nulls(rng, arr: np.ndarray, frac: float) -> np.ndarray:
    """Object array with ~frac nulls (None)."""
    out = arr.astype(object)
    if frac > 0:
        out[rng.random(len(arr)) < frac] = None
    return out


def _gen_item(rng, n: int) -> dict[str, np.ndarray]:
    brand_id = rng.integers(1001001, 1010016, n).astype(np.int32)
    cat_idx = rng.integers(0, len(_CATEGORIES), n)
    cls_idx = rng.integers(0, len(_CLASSES), n)
    manu = rng.integers(1, 1001, n).astype(np.int32)
    return {
        "i_item_sk": np.arange(1, n + 1, dtype=np.int32),
        "i_item_id": np.array([f"AAAAAAAA{k:08d}" for k in range(1, n + 1)],
                              dtype=object),
        "i_item_desc": np.array(
            [f"desc {k} {_CLASSES[c]}" for k, c in enumerate(cls_idx)],
            dtype=object),
        "i_brand_id": brand_id,
        "i_brand": np.array([f"Brand#{b % 100}" for b in brand_id],
                            dtype=object),
        "i_class_id": (cls_idx + 1).astype(np.int32),
        "i_class": np.array([_CLASSES[i] for i in cls_idx], dtype=object),
        "i_category_id": (cat_idx + 1).astype(np.int32),
        "i_category": _with_nulls(
            rng, np.array([_CATEGORIES[i] for i in cat_idx], dtype=object),
            0.005),
        "i_current_price": _with_nulls(
            rng, np.round(rng.uniform(0.09, 99.99, n), 2), 0.01),
        "i_manufact_id": manu,
        "i_manufact": np.array([f"manufact#{v}" for v in manu], dtype=object),
        "i_manager_id": rng.integers(1, 101, n).astype(np.int32),
        "i_size": np.array([("small", "medium", "large", "extra large",
                             "economy", "N/A", "petite")[v]
                            for v in rng.integers(0, 7, n)], dtype=object),
        "i_color": np.array([("red", "blue", "green", "yellow", "pale",
                              "chiffon", "smoke", "orchid", "peach",
                              "saddle", "powder", "burnished")[v]
                             for v in rng.integers(0, 12, n)], dtype=object),
        "i_units": np.array([("Each", "Dozen", "Case", "Pallet", "Gross",
                              "Oz", "Lb", "Ton")[v]
                             for v in rng.integers(0, 8, n)], dtype=object),
        "i_product_name": np.array([f"product{k}" for k in range(1, n + 1)],
                                   dtype=object),
        "i_wholesale_cost": np.round(rng.uniform(0.05, 80.0, n), 2),
    }


def _gen_customer(rng, n: int, n_addr: int, n_cdemo: int,
                  n_hdemo: int) -> dict[str, np.ndarray]:
    return {
        "c_customer_sk": np.arange(1, n + 1, dtype=np.int32),
        "c_customer_id": np.array(
            [f"AAAAAAAA{k:08d}" for k in range(1, n + 1)], dtype=object),
        "c_current_addr_sk": _with_nulls(
            rng, rng.integers(1, n_addr + 1, n).astype(np.int32), 0.01),
        "c_current_cdemo_sk": _with_nulls(
            rng, rng.integers(1, n_cdemo + 1, n).astype(np.int32), 0.01),
        "c_current_hdemo_sk": _with_nulls(
            rng, rng.integers(1, n_hdemo + 1, n).astype(np.int32), 0.01),
        "c_first_name": _with_nulls(
            rng, np.array([_FIRST[i] for i in
                           rng.integers(0, len(_FIRST), n)], dtype=object),
            0.01),
        "c_last_name": _with_nulls(
            rng, np.array([_LAST[i] for i in
                           rng.integers(0, len(_LAST), n)], dtype=object),
            0.01),
        "c_salutation": _with_nulls(
            rng, np.array([("Mr.", "Mrs.", "Ms.", "Dr.", "Miss", "Sir")[v]
                           for v in rng.integers(0, 6, n)], dtype=object),
            0.01),
        "c_preferred_cust_flag": _with_nulls(
            rng, np.array([("Y", "N")[v] for v in rng.integers(0, 2, n)],
                          dtype=object), 0.03),
        "c_birth_year": _with_nulls(
            rng, rng.integers(1924, 1993, n).astype(np.int32), 0.02),
        "c_birth_month": _with_nulls(
            rng, rng.integers(1, 13, n).astype(np.int32), 0.02),
        "c_birth_day": _with_nulls(
            rng, rng.integers(1, 29, n).astype(np.int32), 0.02),
        "c_birth_country": _with_nulls(
            rng, np.array([("UNITED STATES", "CANADA", "MEXICO", "FRANCE",
                            "GERMANY", "JAPAN", "BRAZIL", "INDIA")[v]
                           for v in rng.integers(0, 8, n)], dtype=object),
            0.02),
        "c_first_sales_date_sk": _with_nulls(
            rng, (rng.integers(_SALES_DATE_LO - 1500, _SALES_DATE_HI - 300,
                               n) + _DATE_SK_EPOCH).astype(np.int32), 0.03),
        "c_first_shipto_date_sk": _with_nulls(
            rng, (rng.integers(_SALES_DATE_LO - 1400, _SALES_DATE_HI - 200,
                               n) + _DATE_SK_EPOCH).astype(np.int32), 0.03),
        "c_email_address": np.array(
            [f"user{k}@example.com" for k in range(1, n + 1)], dtype=object),
        # dsdgen leaves c_login almost entirely NULL
        "c_login": _with_nulls(
            rng, np.array([f"login{k}" for k in range(1, n + 1)],
                          dtype=object), 0.95),
        # StringType in the reference schema (TpcdsLikeSpark.scala:442)
        "c_last_review_date": _with_nulls(
            rng, np.array([str(_DATE_SK_EPOCH + int(v)) for v in
                           rng.integers(_SALES_DATE_LO, _SALES_DATE_HI, n)],
                          dtype=object), 0.05),
    }


def _gen_customer_address(rng, n: int) -> dict[str, np.ndarray]:
    return {
        "ca_address_sk": np.arange(1, n + 1, dtype=np.int32),
        "ca_state": _with_nulls(
            rng, np.array([_STATES[i] for i in
                           rng.integers(0, len(_STATES), n)], dtype=object),
            0.01),
        "ca_city": np.array([f"City{v:03d}" for v in
                             rng.integers(0, 400, n)], dtype=object),
        "ca_county": np.array([f"County{v:03d}" for v in
                               rng.integers(0, 200, n)], dtype=object),
        "ca_zip": np.array([f"{v:05d}" for v in
                            rng.integers(10000, 99999, n)], dtype=object),
        "ca_gmt_offset": rng.choice([-10.0, -9.0, -8.0, -7.0, -6.0, -5.0],
                                    n),
        "ca_country": _with_nulls(
            rng, np.array(["United States"] * n, dtype=object), 0.005),
        "ca_street_number": np.array([f"{v}" for v in
                                      rng.integers(1, 1000, n)],
                                     dtype=object),
        "ca_street_name": np.array([f"Street{v:03d}" for v in
                                    rng.integers(0, 300, n)], dtype=object),
        "ca_street_type": _with_nulls(
            rng, np.array([("Street", "Ave", "Blvd", "Ct", "Dr", "Ln")[v]
                           for v in rng.integers(0, 6, n)], dtype=object),
            0.01),
        "ca_suite_number": _with_nulls(
            rng, np.array([f"Suite {v}" for v in rng.integers(0, 100, n)],
                          dtype=object), 0.01),
        "ca_location_type": _with_nulls(
            rng, np.array([("apartment", "condo", "single family")[v]
                           for v in rng.integers(0, 3, n)], dtype=object),
            0.01),
    }


def _gen_store(rng, n: int) -> dict[str, np.ndarray]:
    return {
        "s_store_sk": np.arange(1, n + 1, dtype=np.int32),
        "s_store_id": np.array([f"AAAAAAAA{k:08d}" for k in range(1, n + 1)],
                               dtype=object),
        "s_store_name": np.array(
            [["ought", "able", "pri", "ese", "anti", "cally", "ation",
              "eing"][k % 8] for k in range(n)], dtype=object),
        "s_state": np.array([_STATES[i] for i in
                             rng.integers(0, 10, n)], dtype=object),
        "s_county": np.array([f"County{v:03d}" for v in
                              rng.integers(0, 30, n)], dtype=object),
        "s_city": np.array([f"City{v:03d}" for v in
                            rng.integers(0, 40, n)], dtype=object),
        "s_company_id": rng.integers(1, 7, n).astype(np.int32),
        "s_company_name": np.array(["Unknown"] * n, dtype=object),
        "s_gmt_offset": np.array([(-8.0, -7.0, -6.0, -5.0)[k % 4]
                                  for k in range(n)]),
        "s_number_employees": rng.integers(200, 301, n).astype(np.int32),
        "s_floor_space": rng.integers(5_000_000, 10_000_000,
                                      n).astype(np.int32),
        "s_market_id": rng.integers(1, 11, n).astype(np.int32),
        "s_zip": np.array([f"{v:05d}" for v in
                           rng.integers(10000, 99999, n)], dtype=object),
        "s_street_number": np.array([f"{v}" for v in
                                     rng.integers(1, 1000, n)], dtype=object),
        "s_street_name": np.array([f"Street{v:03d}" for v in
                                   rng.integers(0, 300, n)], dtype=object),
        "s_street_type": np.array([("Street", "Ave", "Blvd", "Ct")[k % 4]
                                   for k in range(n)], dtype=object),
        "s_suite_number": np.array([f"Suite {v}" for v in
                                    rng.integers(0, 100, n)], dtype=object),
    }


def _gen_customer_demographics(rng, n: int) -> dict[str, np.ndarray]:
    eds = ["Primary", "Secondary", "College", "2 yr Degree", "4 yr Degree",
           "Advanced Degree", "Unknown"]
    return {
        "cd_demo_sk": np.arange(1, n + 1, dtype=np.int32),
        "cd_gender": np.array([("M", "F")[v] for v in
                               rng.integers(0, 2, n)], dtype=object),
        "cd_marital_status": np.array(
            [("M", "S", "D", "W", "U")[v] for v in rng.integers(0, 5, n)],
            dtype=object),
        "cd_education_status": np.array(
            [eds[v] for v in rng.integers(0, len(eds), n)], dtype=object),
        "cd_purchase_estimate": (rng.integers(1, 21, n) * 500).astype(
            np.int32),
        "cd_credit_rating": np.array(
            [("Low Risk", "Good", "High Risk", "Unknown")[v]
             for v in rng.integers(0, 4, n)], dtype=object),
        "cd_dep_count": rng.integers(0, 7, n).astype(np.int32),
        "cd_dep_employed_count": rng.integers(0, 7, n).astype(np.int32),
        "cd_dep_college_count": rng.integers(0, 7, n).astype(np.int32),
    }


def _gen_household_demographics(rng, n: int) -> dict[str, np.ndarray]:
    return {
        "hd_demo_sk": np.arange(1, n + 1, dtype=np.int32),
        "hd_dep_count": rng.integers(0, 10, n).astype(np.int32),
        "hd_vehicle_count": rng.integers(-1, 5, n).astype(np.int32),
        "hd_buy_potential": np.array(
            [(">10000", "5001-10000", "1001-5000", "501-1000", "0-500",
              "Unknown")[v] for v in rng.integers(0, 6, n)], dtype=object),
        "hd_income_band_sk": rng.integers(1, 21, n).astype(np.int32),
    }


def _gen_warehouse(rng, n: int) -> dict[str, np.ndarray]:
    return {
        "w_warehouse_sk": np.arange(1, n + 1, dtype=np.int32),
        "w_warehouse_name": np.array([f"Warehouse {k}" for k in
                                      range(1, n + 1)], dtype=object),
        "w_warehouse_sq_ft": rng.integers(50_000, 1_000_000,
                                          n).astype(np.int32),
        "w_city": np.array([f"City{v:03d}" for v in
                            rng.integers(0, 40, n)], dtype=object),
        "w_county": np.array([f"County{v:03d}" for v in
                              rng.integers(0, 30, n)], dtype=object),
        "w_state": np.array([_STATES[i] for i in rng.integers(0, 10, n)],
                            dtype=object),
        "w_country": np.array(["United States"] * n, dtype=object),
    }


def _gen_ship_mode(rng, n: int) -> dict[str, np.ndarray]:
    types = ("EXPRESS", "NEXT DAY", "OVERNIGHT", "REGULAR", "TWO DAY")
    carriers = ("UPS", "FEDEX", "AIRBORNE", "USPS", "DHL", "TBS", "ZHOU",
                "LATVIAN", "DIAMOND", "BARIAN")
    return {
        "sm_ship_mode_sk": np.arange(1, n + 1, dtype=np.int32),
        "sm_type": np.array([types[k % len(types)] for k in range(n)],
                            dtype=object),
        "sm_carrier": np.array([carriers[k % len(carriers)]
                                for k in range(n)], dtype=object),
        "sm_code": np.array([("AIR", "SURFACE", "SEA", "LIBRARY")[k % 4]
                             for k in range(n)], dtype=object),
    }


def _gen_reason(rng, n: int) -> dict[str, np.ndarray]:
    return {
        "r_reason_sk": np.arange(1, n + 1, dtype=np.int32),
        "r_reason_desc": np.array(
            [f"reason {k}" for k in range(1, n + 1)], dtype=object),
    }


def _gen_income_band(rng, n: int) -> dict[str, np.ndarray]:
    sk = np.arange(1, n + 1, dtype=np.int32)
    return {
        "ib_income_band_sk": sk,
        "ib_lower_bound": ((sk - 1) * 10_000).astype(np.int32),
        "ib_upper_bound": (sk * 10_000 - 1).astype(np.int32),
    }


def _gen_call_center(rng, n: int) -> dict[str, np.ndarray]:
    return {
        "cc_call_center_sk": np.arange(1, n + 1, dtype=np.int32),
        "cc_call_center_id": np.array(
            [f"AAAAAAAA{k:08d}" for k in range(1, n + 1)], dtype=object),
        "cc_name": np.array([f"call center {k}" for k in range(1, n + 1)],
                            dtype=object),
        "cc_manager": np.array(
            [f"{_FIRST[rng.integers(0, len(_FIRST))]} "
             f"{_LAST[rng.integers(0, len(_LAST))]}" for _ in range(n)],
            dtype=object),
        "cc_county": np.array([f"County{v:03d}" for v in
                               rng.integers(0, 30, n)], dtype=object),
    }


def _gen_web_site(rng, n: int) -> dict[str, np.ndarray]:
    return {
        "web_site_sk": np.arange(1, n + 1, dtype=np.int32),
        "web_site_id": np.array(
            [f"AAAAAAAA{k:08d}" for k in range(1, n + 1)], dtype=object),
        "web_name": np.array([f"site_{k % 30}" for k in range(n)],
                             dtype=object),
        "web_company_name": np.array(
            [("pri", "ought", "able", "ese", "anti", "cally")[k % 6]
             for k in range(n)], dtype=object),
    }


def _gen_web_page(rng, n: int) -> dict[str, np.ndarray]:
    return {
        "wp_web_page_sk": np.arange(1, n + 1, dtype=np.int32),
        "wp_char_count": rng.integers(100, 8_000, n).astype(np.int32),
    }


def _gen_catalog_page(rng, n: int) -> dict[str, np.ndarray]:
    return {
        "cp_catalog_page_sk": np.arange(1, n + 1, dtype=np.int32),
        "cp_catalog_page_id": np.array(
            [f"AAAAAAAA{k:08d}" for k in range(1, n + 1)], dtype=object),
    }


def _gen_inventory(rng, n: int, counts) -> dict[str, np.ndarray]:
    # weekly snapshot dates across the sales window (dsdgen convention);
    # (date, item, warehouse) triples sampled instead of the full cross
    # product (bench-sized; the join/agg shapes are what matter)
    weeks = np.arange(_SALES_DATE_LO, _SALES_DATE_HI + 1, 7, dtype=np.int64)
    return {
        "inv_date_sk": (rng.choice(weeks, n)
                        + _DATE_SK_EPOCH).astype(np.int32),
        "inv_item_sk": rng.integers(1, counts["item"] + 1,
                                    n).astype(np.int32),
        "inv_warehouse_sk": rng.integers(1, counts["warehouse"] + 1,
                                         n).astype(np.int32),
        "inv_quantity_on_hand": _with_nulls(
            rng, rng.integers(0, 1_000, n).astype(np.int32), 0.02),
    }


def _gen_promotion(rng, n: int) -> dict[str, np.ndarray]:
    yn = lambda frac: np.array(  # noqa: E731
        [("Y" if v else "N") for v in rng.random(n) < frac], dtype=object)
    return {
        "p_promo_sk": np.arange(1, n + 1, dtype=np.int32),
        "p_channel_email": yn(0.1),
        "p_channel_event": yn(0.15),
        "p_channel_dmail": yn(0.1),
        "p_channel_tv": yn(0.1),
    }


def _sales_common(rng, n, counts, prefix):
    qty = rng.integers(1, 101, n).astype(np.int32)
    price = np.round(np.exp(rng.normal(2.5, 1.0, n)).clip(0.01, 300.0), 2)
    wholesale = np.round(price * rng.uniform(0.3, 0.9, n), 2)
    ext = np.round(price * qty, 2)
    return qty, price, wholesale, ext


def _gen_store_sales(rng, n: int, counts) -> dict[str, np.ndarray]:
    qty, price, wholesale, ext = _sales_common(rng, n, counts, "ss")
    return {
        "ss_sold_date_sk": _with_nulls(
            rng, (rng.integers(_SALES_DATE_LO, _SALES_DATE_HI + 1, n)
                  + _DATE_SK_EPOCH).astype(np.int32), 0.02),
        "ss_sold_time_sk": _with_nulls(
            rng, rng.integers(0, 86_400, n).astype(np.int32), 0.02),
        "ss_item_sk": rng.integers(1, counts["item"] + 1, n).astype(np.int32),
        "ss_customer_sk": _with_nulls(
            rng, rng.integers(1, counts["customer"] + 1, n).astype(np.int32),
            0.04),
        "ss_cdemo_sk": _with_nulls(
            rng, rng.integers(1, counts["customer_demographics"] + 1,
                              n).astype(np.int32), 0.04),
        "ss_hdemo_sk": _with_nulls(
            rng, rng.integers(1, counts["household_demographics"] + 1,
                              n).astype(np.int32), 0.04),
        "ss_store_sk": _with_nulls(
            rng, rng.integers(1, counts["store"] + 1, n).astype(np.int32),
            0.02),
        "ss_promo_sk": _with_nulls(
            rng, rng.integers(1, counts["promotion"] + 1, n).astype(np.int32),
            0.02),
        "ss_ticket_number": rng.integers(1, max(n // 3, 2),
                                         n).astype(np.int64),
        "ss_addr_sk": _with_nulls(
            rng, rng.integers(1, counts["customer_address"] + 1,
                              n).astype(np.int32), 0.03),
        "ss_quantity": qty,
        "ss_list_price": np.round(price * rng.uniform(1.0, 1.5, n), 2),
        "ss_sales_price": price,
        "ss_ext_sales_price": ext,
        "ss_ext_list_price": np.round(price * rng.uniform(1.0, 1.5, n)
                                      * qty, 2),
        "ss_ext_discount_amt": np.round(
            ext * rng.choice([0.0, 0.0, 0.05, 0.2], n), 2),
        "ss_ext_tax": np.round(ext * 0.08, 2),
        "ss_wholesale_cost": wholesale,
        "ss_ext_wholesale_cost": np.round(wholesale * qty, 2),
        "ss_coupon_amt": np.round(
            ext * rng.choice([0.0, 0.0, 0.0, 0.1, 0.3], n), 2),
        "ss_net_paid": np.round(ext * rng.uniform(0.7, 1.0, n), 2),
        "ss_net_paid_inc_tax": np.round(ext * 1.08, 2),
        "ss_net_profit": np.round(ext - wholesale * qty, 2),
    }


def _gen_catalog_sales(rng, n: int, counts) -> dict[str, np.ndarray]:
    qty, price, wholesale, ext = _sales_common(rng, n, counts, "cs")
    sold = (rng.integers(_SALES_DATE_LO, _SALES_DATE_HI + 1, n)
            + _DATE_SK_EPOCH).astype(np.int64)
    return {
        "cs_sold_date_sk": _with_nulls(rng, sold.astype(np.int32), 0.02),
        "cs_sold_time_sk": _with_nulls(
            rng, rng.integers(0, 86_400, n).astype(np.int32), 0.02),
        "cs_ship_date_sk": _with_nulls(
            rng, (sold + rng.integers(1, 120, n)).astype(np.int32), 0.02),
        "cs_item_sk": rng.integers(1, counts["item"] + 1, n).astype(np.int32),
        "cs_order_number": rng.integers(1, max(n // 2, 2),
                                        n).astype(np.int64),
        "cs_bill_customer_sk": _with_nulls(
            rng, rng.integers(1, counts["customer"] + 1, n).astype(np.int32),
            0.03),
        "cs_bill_cdemo_sk": _with_nulls(
            rng, rng.integers(1, counts["customer_demographics"] + 1,
                              n).astype(np.int32), 0.03),
        "cs_bill_hdemo_sk": _with_nulls(
            rng, rng.integers(1, counts["household_demographics"] + 1,
                              n).astype(np.int32), 0.03),
        "cs_bill_addr_sk": _with_nulls(
            rng, rng.integers(1, counts["customer_address"] + 1,
                              n).astype(np.int32), 0.03),
        "cs_ship_customer_sk": _with_nulls(
            rng, rng.integers(1, counts["customer"] + 1, n).astype(np.int32),
            0.03),
        "cs_ship_addr_sk": _with_nulls(
            rng, rng.integers(1, counts["customer_address"] + 1,
                              n).astype(np.int32), 0.03),
        "cs_ship_mode_sk": _with_nulls(
            rng, rng.integers(1, counts["ship_mode"] + 1,
                              n).astype(np.int32), 0.02),
        "cs_warehouse_sk": _with_nulls(
            rng, rng.integers(1, counts["warehouse"] + 1,
                              n).astype(np.int32), 0.02),
        "cs_call_center_sk": _with_nulls(
            rng, rng.integers(1, counts["call_center"] + 1,
                              n).astype(np.int32), 0.02),
        "cs_catalog_page_sk": _with_nulls(
            rng, rng.integers(1, counts["catalog_page"] + 1,
                              n).astype(np.int32), 0.02),
        "cs_promo_sk": _with_nulls(
            rng, rng.integers(1, counts["promotion"] + 1, n).astype(np.int32),
            0.02),
        "cs_quantity": qty,
        "cs_list_price": np.round(price * rng.uniform(1.0, 1.5, n), 2),
        "cs_sales_price": price,
        "cs_ext_sales_price": ext,
        "cs_ext_list_price": np.round(price * rng.uniform(1.0, 1.5, n)
                                      * qty, 2),
        "cs_ext_discount_amt": np.round(
            ext * rng.choice([0.0, 0.0, 0.05, 0.2], n), 2),
        "cs_ext_ship_cost": np.round(ext * rng.uniform(0.01, 0.1, n), 2),
        "cs_wholesale_cost": wholesale,
        "cs_ext_wholesale_cost": np.round(wholesale * qty, 2),
        "cs_coupon_amt": np.round(
            ext * rng.choice([0.0, 0.0, 0.0, 0.1, 0.3], n), 2),
        "cs_net_paid": np.round(ext * rng.uniform(0.7, 1.0, n), 2),
        "cs_net_paid_inc_tax": np.round(ext * 1.08, 2),
        "cs_net_profit": np.round(ext - wholesale * qty, 2),
    }


def _gen_web_sales(rng, n: int, counts) -> dict[str, np.ndarray]:
    qty, price, wholesale, ext = _sales_common(rng, n, counts, "ws")
    sold = (rng.integers(_SALES_DATE_LO, _SALES_DATE_HI + 1, n)
            + _DATE_SK_EPOCH).astype(np.int64)
    return {
        "ws_sold_date_sk": _with_nulls(rng, sold.astype(np.int32), 0.02),
        "ws_sold_time_sk": _with_nulls(
            rng, rng.integers(0, 86_400, n).astype(np.int32), 0.02),
        "ws_ship_date_sk": _with_nulls(
            rng, (sold + rng.integers(1, 120, n)).astype(np.int32), 0.02),
        "ws_item_sk": rng.integers(1, counts["item"] + 1, n).astype(np.int32),
        "ws_order_number": rng.integers(1, max(n // 2, 2),
                                        n).astype(np.int64),
        "ws_bill_customer_sk": _with_nulls(
            rng, rng.integers(1, counts["customer"] + 1, n).astype(np.int32),
            0.03),
        "ws_bill_addr_sk": _with_nulls(
            rng, rng.integers(1, counts["customer_address"] + 1,
                              n).astype(np.int32), 0.03),
        "ws_ship_addr_sk": _with_nulls(
            rng, rng.integers(1, counts["customer_address"] + 1,
                              n).astype(np.int32), 0.03),
        "ws_web_site_sk": _with_nulls(
            rng, rng.integers(1, counts["web_site"] + 1,
                              n).astype(np.int32), 0.02),
        "ws_web_page_sk": _with_nulls(
            rng, rng.integers(1, counts["web_page"] + 1,
                              n).astype(np.int32), 0.02),
        "ws_ship_mode_sk": _with_nulls(
            rng, rng.integers(1, counts["ship_mode"] + 1,
                              n).astype(np.int32), 0.02),
        "ws_promo_sk": _with_nulls(
            rng, rng.integers(1, counts["promotion"] + 1, n).astype(np.int32),
            0.02),
        "ws_warehouse_sk": _with_nulls(
            rng, rng.integers(1, counts["warehouse"] + 1,
                              n).astype(np.int32), 0.02),
        "ws_ship_customer_sk": _with_nulls(
            rng, rng.integers(1, counts["customer"] + 1, n).astype(np.int32),
            0.03),
        "ws_ship_hdemo_sk": _with_nulls(
            rng, rng.integers(1, counts["household_demographics"] + 1,
                              n).astype(np.int32), 0.03),
        "ws_quantity": qty,
        "ws_list_price": np.round(price * rng.uniform(1.0, 1.5, n), 2),
        "ws_sales_price": price,
        "ws_ext_sales_price": ext,
        "ws_ext_list_price": np.round(price * rng.uniform(1.0, 1.5, n)
                                      * qty, 2),
        "ws_ext_discount_amt": np.round(
            ext * rng.choice([0.0, 0.0, 0.05, 0.2], n), 2),
        "ws_ext_ship_cost": np.round(ext * rng.uniform(0.01, 0.1, n), 2),
        "ws_wholesale_cost": wholesale,
        "ws_ext_wholesale_cost": np.round(wholesale * qty, 2),
        "ws_net_paid": np.round(ext * rng.uniform(0.7, 1.0, n), 2),
        "ws_net_profit": np.round(ext - wholesale * qty, 2),
    }


def _pick(col, idx):
    """Sample parent column values at row indices ``idx`` (object arrays
    keep their Nones)."""
    return np.asarray(col)[idx]


def _ret_date_col(rng, ret_date: np.ndarray, null_frac: float):
    """returned_date_sk column: sentinel 0 (parent sold date was NULL)
    becomes None — dsdgen emits NULL there, and a non-null 0 would be
    unjoinable-but-countable in IS NULL / outer-join queries."""
    out = ret_date.astype(object)
    out[ret_date == 0] = None
    return _with_nulls(rng, out, null_frac)


def _returns_common(rng, parent: dict, n: int, item_col: str,
                    date_col: str, qty_col: str, price_col: str):
    """Sample n parent rows; returned date = sold date + U(1,90) days,
    return qty <= sold qty, amounts derived from the parent price."""
    pn = len(parent[item_col])
    idx = rng.choice(pn, size=min(n, pn), replace=False)
    idx.sort()
    sold = parent[date_col]
    sold_days = np.array([0 if v is None else int(v) for v in
                          np.asarray(sold, dtype=object)[idx]]
                         if np.asarray(sold).dtype == object
                         else np.asarray(sold)[idx], dtype=np.int64)
    ret_date = np.where(sold_days > 0,
                        sold_days + rng.integers(1, 91, len(idx)),
                        0).astype(np.int64)
    qty = np.asarray(parent[qty_col])[idx].astype(np.int64)
    rqty = rng.integers(1, np.maximum(qty, 1) + 1).astype(np.int32)
    price = np.asarray(parent[price_col])[idx].astype(np.float64)
    amt = np.round(price * rqty, 2)
    return idx, ret_date, rqty, amt


def _gen_store_returns(rng, counts, parent: dict) -> dict[str, np.ndarray]:
    n = counts["store_returns"]
    idx, ret_date, rqty, amt = _returns_common(
        rng, parent, n, "ss_item_sk",
        "ss_sold_date_sk", "ss_quantity", "ss_sales_price")
    return {
        "sr_returned_date_sk": _ret_date_col(rng, ret_date, 0.02),
        "sr_item_sk": _pick(parent["ss_item_sk"], idx).astype(np.int32),
        "sr_ticket_number": _pick(parent["ss_ticket_number"],
                                  idx).astype(np.int64),
        "sr_customer_sk": _pick(parent["ss_customer_sk"], idx),
        "sr_cdemo_sk": _pick(parent["ss_cdemo_sk"], idx),
        "sr_store_sk": _pick(parent["ss_store_sk"], idx),
        "sr_reason_sk": _with_nulls(
            rng, rng.integers(1, counts["reason"] + 1,
                              len(idx)).astype(np.int32), 0.02),
        "sr_return_quantity": _with_nulls(rng, rqty, 0.02),
        "sr_return_amt": amt,
        "sr_net_loss": np.round(amt * rng.uniform(0.3, 1.1, len(idx)), 2),
        "sr_fee": np.round(rng.uniform(0.5, 100.0, len(idx)), 2),
        "sr_refunded_cash": np.round(amt * rng.uniform(0.0, 1.0, len(idx)),
                                     2),
        "sr_return_amt_inc_tax": np.round(amt * 1.08, 2),
    }


def _gen_catalog_returns(rng, counts, parent: dict) -> dict[str, np.ndarray]:
    n = counts["catalog_returns"]
    idx, ret_date, rqty, amt = _returns_common(
        rng, parent, n, "cs_item_sk",
        "cs_sold_date_sk", "cs_quantity", "cs_sales_price")
    return {
        "cr_returned_date_sk": _ret_date_col(rng, ret_date, 0.02),
        "cr_item_sk": _pick(parent["cs_item_sk"], idx).astype(np.int32),
        "cr_order_number": _pick(parent["cs_order_number"],
                                 idx).astype(np.int64),
        "cr_returning_customer_sk": _pick(parent["cs_bill_customer_sk"],
                                          idx),
        "cr_refunded_customer_sk": _pick(parent["cs_bill_customer_sk"], idx),
        "cr_returning_addr_sk": _with_nulls(
            rng, rng.integers(1, counts["customer_address"] + 1,
                              len(idx)).astype(np.int32), 0.03),
        "cr_call_center_sk": _pick(parent["cs_call_center_sk"], idx),
        "cr_catalog_page_sk": _pick(parent["cs_catalog_page_sk"], idx),
        "cr_reason_sk": _with_nulls(
            rng, rng.integers(1, counts["reason"] + 1,
                              len(idx)).astype(np.int32), 0.02),
        "cr_return_quantity": _with_nulls(rng, rqty, 0.02),
        "cr_return_amount": amt,
        "cr_return_amt_inc_tax": np.round(amt * 1.08, 2),
        "cr_net_loss": np.round(amt * rng.uniform(0.3, 1.1, len(idx)), 2),
        "cr_refunded_cash": np.round(amt * rng.uniform(0.0, 0.6, len(idx)),
                                     2),
        "cr_reversed_charge": np.round(
            amt * rng.uniform(0.0, 0.3, len(idx)), 2),
        "cr_store_credit": np.round(amt * rng.uniform(0.0, 0.3, len(idx)),
                                    2),
    }


def _gen_web_returns(rng, counts, parent: dict) -> dict[str, np.ndarray]:
    n = counts["web_returns"]
    idx, ret_date, rqty, amt = _returns_common(
        rng, parent, n, "ws_item_sk",
        "ws_sold_date_sk", "ws_quantity", "ws_sales_price")
    return {
        "wr_returned_date_sk": _ret_date_col(rng, ret_date, 0.02),
        "wr_item_sk": _pick(parent["ws_item_sk"], idx).astype(np.int32),
        "wr_order_number": _pick(parent["ws_order_number"],
                                 idx).astype(np.int64),
        "wr_returning_customer_sk": _pick(parent["ws_bill_customer_sk"],
                                          idx),
        "wr_refunded_customer_sk": _pick(parent["ws_bill_customer_sk"], idx),
        "wr_returning_addr_sk": _with_nulls(
            rng, rng.integers(1, counts["customer_address"] + 1,
                              len(idx)).astype(np.int32), 0.03),
        "wr_refunded_addr_sk": _with_nulls(
            rng, rng.integers(1, counts["customer_address"] + 1,
                              len(idx)).astype(np.int32), 0.03),
        "wr_refunded_cdemo_sk": _with_nulls(
            rng, rng.integers(1, counts["customer_demographics"] + 1,
                              len(idx)).astype(np.int32), 0.03),
        "wr_returning_cdemo_sk": _with_nulls(
            rng, rng.integers(1, counts["customer_demographics"] + 1,
                              len(idx)).astype(np.int32), 0.03),
        "wr_web_page_sk": _pick(parent["ws_web_page_sk"], idx),
        "wr_reason_sk": _with_nulls(
            rng, rng.integers(1, counts["reason"] + 1,
                              len(idx)).astype(np.int32), 0.02),
        "wr_return_quantity": _with_nulls(rng, rqty, 0.02),
        "wr_return_amt": amt,
        "wr_fee": np.round(rng.uniform(0.5, 100.0, len(idx)), 2),
        "wr_refunded_cash": np.round(amt * rng.uniform(0.0, 1.0, len(idx)),
                                     2),
        "wr_net_loss": np.round(amt * rng.uniform(0.3, 1.1, len(idx)), 2),
    }


_GENERATORS = {
    "date_dim": lambda rng, counts: _gen_date_dim(counts),
    "time_dim": lambda rng, counts: _gen_time_dim(counts),
    "item": lambda rng, counts: _gen_item(rng, counts["item"]),
    "customer": lambda rng, counts: _gen_customer(
        rng, counts["customer"], counts["customer_address"],
        counts["customer_demographics"],
        counts["household_demographics"]),
    "customer_address": lambda rng, counts: _gen_customer_address(
        rng, counts["customer_address"]),
    "store": lambda rng, counts: _gen_store(rng, counts["store"]),
    "customer_demographics": lambda rng, counts: _gen_customer_demographics(
        rng, counts["customer_demographics"]),
    "household_demographics": lambda rng, counts:
        _gen_household_demographics(rng, counts["household_demographics"]),
    "promotion": lambda rng, counts: _gen_promotion(rng, counts["promotion"]),
    "warehouse": lambda rng, counts: _gen_warehouse(
        rng, counts["warehouse"]),
    "ship_mode": lambda rng, counts: _gen_ship_mode(
        rng, counts["ship_mode"]),
    "reason": lambda rng, counts: _gen_reason(rng, counts["reason"]),
    "income_band": lambda rng, counts: _gen_income_band(
        rng, counts["income_band"]),
    "call_center": lambda rng, counts: _gen_call_center(
        rng, counts["call_center"]),
    "web_site": lambda rng, counts: _gen_web_site(rng, counts["web_site"]),
    "web_page": lambda rng, counts: _gen_web_page(rng, counts["web_page"]),
    "catalog_page": lambda rng, counts: _gen_catalog_page(
        rng, counts["catalog_page"]),
    "inventory": lambda rng, counts: _gen_inventory(
        rng, counts["inventory"], counts),
    "store_sales": lambda rng, counts: _gen_store_sales(
        rng, counts["store_sales"], counts),
    "catalog_sales": lambda rng, counts: _gen_catalog_sales(
        rng, counts["catalog_sales"], counts),
    "web_sales": lambda rng, counts: _gen_web_sales(
        rng, counts["web_sales"], counts),
}

_RETURNS_GENERATORS = {
    "store_returns": _gen_store_returns,
    "catalog_returns": _gen_catalog_returns,
    "web_returns": _gen_web_returns,
}


def _write_parquet(path: str, data: dict, rows_per_file: int,
                   date_cols: Sequence[str] = ()) -> None:
    import pyarrow as pa
    import pyarrow.parquet as pq
    os.makedirs(path, exist_ok=True)
    n = len(next(iter(data.values())))
    cols = {}
    for name, arr in data.items():
        if name in date_cols:
            cols[name] = pa.array(np.asarray(arr, dtype=np.int32),
                                  type=pa.int32()).cast(pa.date32())
        elif arr.dtype == object:
            base = next((x for x in arr if x is not None), 0)
            if isinstance(base, str):
                cols[name] = pa.array(list(arr), type=pa.string())
            elif isinstance(base, float):
                cols[name] = pa.array(
                    [None if x is None else float(x) for x in arr],
                    type=pa.float64())
            else:
                cols[name] = pa.array(
                    [None if x is None else int(x) for x in arr],
                    type=pa.int32())
        else:
            cols[name] = pa.array(arr)
    table = pa.table(cols)
    nfiles = max(1, -(-n // rows_per_file))
    for i in range(nfiles):
        part = table.slice(i * rows_per_file,
                           min(rows_per_file, n - i * rows_per_file))
        pq.write_table(part, os.path.join(path, f"part-{i:05d}.parquet"))


def generate_tpcds(data_dir: str, sf: float = 0.01, seed: int = 42,
                   tables: Sequence[str] = TABLES,
                   rows_per_file: int = 1 << 20) -> dict[str, int]:
    """Generate the pruned TPC-DS tables under ``data_dir/<table>/``.

    Returns {table: rows}.  Skips tables already generated at the current
    schema version (marker file); regenerates on version mismatch.
    """
    counts = table_row_counts(sf)
    # returns rows are sampled from their parent's rows, so the on-disk
    # parent must match THIS (sf, seed) — the marker encodes all three
    # (a schema-only marker let a different seed/sf regenerate returns
    # that join to nothing)
    stamp = f"_{_SCHEMA_VERSION}_sf{sf:g}_seed{seed}"
    written = {}

    def _needs_gen(t: str) -> bool:
        return not os.path.exists(os.path.join(data_dir, t, stamp))

    # parent sales data kept in memory only between a parent and its
    # returns table (the returns rows are sampled from the parent's)
    parents: dict[str, dict] = {}
    for t in tables:
        out = os.path.join(data_dir, t)
        written[t] = counts[t]
        if not _needs_gen(t):
            continue
        if os.path.isdir(out):
            import shutil
            shutil.rmtree(out)
        rng = np.random.default_rng(seed + zlib.crc32(t.encode()) % 1000)
        if t in _RETURNS_GENERATORS:
            pname = _RETURNS_PARENT[t]
            parent = parents.pop(pname, None)
            if parent is None:
                # parent already on disk from an earlier run at the SAME
                # (version, sf, seed): deterministic, so regenerate it in
                # memory for sampling
                prng = np.random.default_rng(
                    seed + zlib.crc32(pname.encode()) % 1000)
                parent = _GENERATORS[pname](prng, counts)
            data = _RETURNS_GENERATORS[t](rng, counts, parent)
            del parent
        else:
            data = _GENERATORS[t](rng, counts)
            retname = next((r for r, p in _RETURNS_PARENT.items()
                            if p == t), None)
            # hold the parent in memory only if its returns table is
            # about to be generated in this run (else multi-GB of object
            # arrays would sit resident for the rest of the loop)
            if retname in tables and _needs_gen(retname):
                parents[t] = data
        _write_parquet(out, data, rows_per_file,
                       date_cols=("d_date",) if t == "date_dim" else ())
        with open(os.path.join(out, stamp), "w") as f:
            f.write(stamp + "\n")
    return written

"""TPC-DS queries, full-suite tranche 4 (q1-q99 gap fill, part 3 of 4).

Channel-union profit reports, EXISTS-family demographics, bucket
cross-joins, and correlated-count item queries.  Same house rules as
tpcds_queries2.py (reference: TpcdsLikeSpark.scala:911-4330).
"""
from __future__ import annotations

import os

from spark_rapids_tpu import types as T
from spark_rapids_tpu.expr.aggregates import (Average, Count, CountDistinct,
                                              CountStar, Max, Min, Sum)
from spark_rapids_tpu.expr.conditional import CaseWhen, Coalesce, If
from spark_rapids_tpu.expr.core import col, lit
from spark_rapids_tpu.expr.predicates import In, Or
from spark_rapids_tpu.expr.strings import Concat, Substring, Upper

__all__ = ["QUERIES4"]


def _t(session, data_dir: str, table: str, columns=None):
    return session.read_parquet(os.path.join(data_dir, table),
                                columns=columns)


def _date_sk(y: int, m: int, d: int) -> int:
    import datetime as _dt
    return 2415022 + (_dt.date(y, m, d) - _dt.date(1900, 1, 1)).days


# ---------------------------------------------------------------------------
# q5: channel sales/returns rollup
# ---------------------------------------------------------------------------

def q5(session, data_dir: str):
    """TPC-DS q5: 14-day sales/returns/profit per channel, ROLLUP."""
    lo = _date_sk(2000, 8, 23)
    dd = _t(session, data_dir, "date_dim", ["d_date_sk"]) \
        .where((col("d_date_sk") >= lit(lo))
               & (col("d_date_sk") <= lit(lo + 14)))

    def leg(frame, sk, date, sales, profit, ret, loss):
        """Normalize a sales or returns frame to the salesreturns
        shape."""
        return frame.select(
            col(sk).alias("unit_sk"), col(date).alias("date_sk"),
            (col(sales) if sales else lit(0.0)).alias("sales_price"),
            (col(profit) if profit else lit(0.0)).alias("profit"),
            (col(ret) if ret else lit(0.0)).alias("return_amt"),
            (col(loss) if loss else lit(0.0)).alias("net_loss"))

    ss = leg(_t(session, data_dir, "store_sales",
                ["ss_store_sk", "ss_sold_date_sk", "ss_ext_sales_price",
                 "ss_net_profit"]),
             "ss_store_sk", "ss_sold_date_sk", "ss_ext_sales_price",
             "ss_net_profit", None, None)
    sr = leg(_t(session, data_dir, "store_returns",
                ["sr_store_sk", "sr_returned_date_sk", "sr_return_amt",
                 "sr_net_loss"]),
             "sr_store_sk", "sr_returned_date_sk", None, None,
             "sr_return_amt", "sr_net_loss")
    st = _t(session, data_dir, "store", ["s_store_sk", "s_store_id"])
    ssr = ss.union(sr).join(dd, on=[("date_sk", "d_date_sk")]) \
        .join(st, on=[("unit_sk", "s_store_sk")]) \
        .group_by("s_store_id").agg(
            Sum(col("sales_price")).alias("sales"),
            Sum(col("profit")).alias("profit"),
            Sum(col("return_amt")).alias("returns"),
            Sum(col("net_loss")).alias("profit_loss"))

    cs = leg(_t(session, data_dir, "catalog_sales",
                ["cs_catalog_page_sk", "cs_sold_date_sk",
                 "cs_ext_sales_price", "cs_net_profit"]),
             "cs_catalog_page_sk", "cs_sold_date_sk",
             "cs_ext_sales_price", "cs_net_profit", None, None)
    cr = leg(_t(session, data_dir, "catalog_returns",
                ["cr_catalog_page_sk", "cr_returned_date_sk",
                 "cr_return_amount", "cr_net_loss"]),
             "cr_catalog_page_sk", "cr_returned_date_sk", None, None,
             "cr_return_amount", "cr_net_loss")
    cp = _t(session, data_dir, "catalog_page",
            ["cp_catalog_page_sk", "cp_catalog_page_id"])
    csr = cs.union(cr).join(dd, on=[("date_sk", "d_date_sk")]) \
        .join(cp, on=[("unit_sk", "cp_catalog_page_sk")]) \
        .group_by("cp_catalog_page_id").agg(
            Sum(col("sales_price")).alias("sales"),
            Sum(col("profit")).alias("profit"),
            Sum(col("return_amt")).alias("returns"),
            Sum(col("net_loss")).alias("profit_loss"))

    ws_s = leg(_t(session, data_dir, "web_sales",
                  ["ws_web_site_sk", "ws_sold_date_sk",
                   "ws_ext_sales_price", "ws_net_profit"]),
               "ws_web_site_sk", "ws_sold_date_sk", "ws_ext_sales_price",
               "ws_net_profit", None, None)
    # web returns ride the originating sale's web site (LEFT OUTER to
    # web_sales in the reference)
    wr_raw = _t(session, data_dir, "web_returns",
                ["wr_returned_date_sk", "wr_item_sk", "wr_order_number",
                 "wr_return_amt", "wr_net_loss"])
    ws_map = _t(session, data_dir, "web_sales",
                ["ws_item_sk", "ws_order_number", "ws_web_site_sk"]) \
        .select(col("ws_item_sk").alias("m_item_sk"),
                col("ws_order_number").alias("m_order_number"),
                col("ws_web_site_sk").alias("m_web_site_sk"))
    wr = wr_raw.join(ws_map, on=[("wr_item_sk", "m_item_sk"),
                                 ("wr_order_number", "m_order_number")],
                     how="left") \
        .select(col("m_web_site_sk").alias("unit_sk"),
                col("wr_returned_date_sk").alias("date_sk"),
                lit(0.0).alias("sales_price"), lit(0.0).alias("profit"),
                col("wr_return_amt").alias("return_amt"),
                col("wr_net_loss").alias("net_loss"))
    web = _t(session, data_dir, "web_site", ["web_site_sk", "web_site_id"])
    wsr = ws_s.union(wr).join(dd, on=[("date_sk", "d_date_sk")]) \
        .join(web, on=[("unit_sk", "web_site_sk")]) \
        .group_by("web_site_id").agg(
            Sum(col("sales_price")).alias("sales"),
            Sum(col("profit")).alias("profit"),
            Sum(col("return_amt")).alias("returns"),
            Sum(col("net_loss")).alias("profit_loss"))

    def channel(frame, label, id_prefix, id_col):
        return frame.select(
            lit(label).alias("channel"),
            Concat(lit(id_prefix), col(id_col)).alias("id"),
            col("sales"), col("returns"),
            (col("profit") - col("profit_loss")).alias("profit"))

    u = channel(ssr, "store channel", "store", "s_store_id") \
        .union(channel(csr, "catalog channel", "catalog_page",
                       "cp_catalog_page_id")) \
        .union(channel(wsr, "web channel", "web_site", "web_site_id"))
    return u.rollup("channel", "id").agg(
        Sum(col("sales")).alias("sales"),
        Sum(col("returns")).alias("returns"),
        Sum(col("profit")).alias("profit")) \
        .order_by(("channel", True), ("id", True)).limit(100)


# ---------------------------------------------------------------------------
# q8: preferred-customer zips
# ---------------------------------------------------------------------------

_Q8_ZIPS = [
    "24128", "76232", "65084", "87816", "83926", "77556", "20548", "26231",
    "43848", "15126", "91137", "61265", "98294", "25782", "17920", "18426",
    "98235", "40081", "84093", "28577", "55565", "17183", "54601", "67897",
    "22752", "86284", "18376", "38607", "45200", "21756", "29741", "96765",
    "23932", "89360", "29839", "25989", "28898", "91068", "72550", "10390",
    "18845", "47770", "82636", "41367", "76638", "86198", "81312", "37126",
    "39192", "88424", "72175", "81426", "53672", "10445", "42666", "66864",
    "66708", "41248", "48583", "82276", "18842", "78890", "49448", "14089",
    "38122", "34425", "79077", "19849", "43285", "39861", "66162", "77610",
    "13695", "99543", "83444", "83041", "12305", "57665", "68341", "25003",
    "57834", "62878", "49130", "81096", "18840", "27700", "23470", "50412",
    "21195", "16021", "76107", "71954", "68309", "18119", "98359", "64544",
    "10336", "86379", "27068", "39736", "98569", "28915", "24206", "56529",
    "57647", "54917", "42961", "91110", "63981", "14922", "36420", "23006",
    "67467", "32754", "30903", "20260", "31671", "51798", "72325", "85816",
    "68621", "13955", "36446", "41766", "68806", "16725", "15146", "22744",
    "35850", "88086", "51649", "18270", "52867", "39972", "96976", "63792",
    "11376", "94898", "13595", "10516", "90225", "58943", "39371", "94945",
    "28587", "96576", "57855", "28488", "26105", "83933", "25858", "34322",
    "44438", "73171", "30122", "34102", "22685", "71256", "78451", "54364",
    "13354", "45375", "40558", "56458", "28286", "45266", "47305", "69399",
    "83921", "26233", "11101", "15371", "69913", "35942", "15882", "25631",
    "24610", "44165", "99076", "33786", "70738", "26653", "14328", "72305",
    "62496", "22152", "10144", "64147", "48425", "14663", "21076", "18799",
    "30450", "63089", "81019", "68893", "24996", "51200", "51211", "45692",
    "92712", "70466", "79994", "22437", "25280", "38935", "71791", "73134",
    "56571", "14060", "19505", "72425", "56575", "74351", "68786", "51650",
    "20004", "18383", "76614", "11634", "18906", "15765", "41368", "73241",
    "76698", "78567", "97189", "28545", "76231", "75691", "22246", "51061",
    "90578", "56691", "68014", "51103", "94167", "57047", "14867", "73520",
    "15734", "63435", "25733", "35474", "24676", "94627", "53535", "17879",
    "15559", "53268", "59166", "11928", "59402", "33282", "45721", "43933",
    "68101", "33515", "36634", "71286", "19736", "58058", "55253", "67473",
    "41918", "19515", "36495", "19430", "22351", "77191", "91393", "49156",
    "50298", "87501", "18652", "53179", "18767", "63193", "23968", "65164",
    "68880", "21286", "72823", "58470", "67301", "13394", "31016", "70372",
    "67030", "40604", "24317", "45748", "39127", "26065", "77721", "31029",
    "31880", "60576", "24671", "45549", "13376", "50016", "33123", "19769",
    "22927", "97789", "46081", "72151", "15723", "46136", "51949", "68100",
    "96888", "64528", "14171", "79777", "28709", "11489", "25103", "32213",
    "78668", "22245", "15798", "27156", "37930", "62971", "21337", "51622",
    "67853", "10567", "38415", "15455", "58263", "42029", "60279", "37125",
    "56240", "88190", "50308", "26859", "64457", "89091", "82136", "62377",
    "36233", "63837", "58078", "17043", "30010", "60099", "28810", "98025",
    "29178", "87343", "73273", "30469", "64034", "39516", "86057", "21309",
    "90257", "67875", "40162", "11356", "73650", "61810", "72013", "30431",
    "22461", "19512", "13375", "55307", "30625", "83849", "68908", "26689",
    "96451", "38193", "46820", "88885", "84935", "69035", "83144", "47537",
    "56616", "94983", "48033", "69952", "25486", "61547", "27385", "61860",
    "58048", "56910", "16807", "17871", "35258", "31387", "35458", "35576"]


def q8(session, data_dir: str):
    """TPC-DS q8: store profit for stores whose zip-2 prefix matches
    qualifying customer zips (INTERSECT of list and preferred-heavy
    zips)."""
    ca = _t(session, data_dir, "customer_address",
            ["ca_address_sk", "ca_zip"])
    z1 = ca.select(Substring(col("ca_zip"), lit(1), lit(5)).alias("zip")) \
        .where(In(col("zip"), [lit(z) for z in _Q8_ZIPS]))
    cu = _t(session, data_dir, "customer",
            ["c_current_addr_sk", "c_preferred_cust_flag"]) \
        .where(col("c_preferred_cust_flag") == lit("Y")) \
        .select(col("c_current_addr_sk"))
    z2 = ca.join(cu, on=[("ca_address_sk", "c_current_addr_sk")]) \
        .with_column("zip", Substring(col("ca_zip"), lit(1), lit(5))) \
        .group_by("zip") \
        .agg(CountStar().alias("cnt")) \
        .where(col("cnt") > lit(10)).select(col("zip"))
    zips = z1.intersect(z2) \
        .select(Substring(col("zip"), lit(1), lit(2)).alias("zip2")) \
        .distinct()
    dd = _t(session, data_dir, "date_dim",
            ["d_date_sk", "d_qoy", "d_year"]) \
        .where((col("d_qoy") == lit(2)) & (col("d_year") == lit(1998))) \
        .select(col("d_date_sk"))
    st = _t(session, data_dir, "store",
            ["s_store_sk", "s_store_name", "s_zip"]) \
        .with_column("s_zip2", Substring(col("s_zip"), lit(1), lit(2)))
    ss = _t(session, data_dir, "store_sales",
            ["ss_store_sk", "ss_sold_date_sk", "ss_net_profit"])
    return ss.join(dd, on=[("ss_sold_date_sk", "d_date_sk")]) \
        .join(st, on=[("ss_store_sk", "s_store_sk")]) \
        .join(zips, on=[("s_zip2", "zip2")], how="semi") \
        .group_by("s_store_name") \
        .agg(Sum(col("ss_net_profit")).alias("profit")) \
        .order_by(("s_store_name", True)).limit(100)


# ---------------------------------------------------------------------------
# q9: quantity-bucket report (scalar subqueries, eagerly folded)
# ---------------------------------------------------------------------------

def q9(session, data_dir: str):
    """TPC-DS q9: avg discount or net-paid per quantity bucket, chosen
    by bucket count.  The five scalar subqueries stay IN the plan as
    1-row aggregates combined by cross join (an eager .collect() at
    build time would move the whole fact-table cost outside the
    benchmarked execution)."""
    ss = _t(session, data_dir, "store_sales",
            ["ss_quantity", "ss_ext_discount_amt", "ss_net_paid"])
    bounds = [(1, 20, 74129), (21, 40, 122840), (41, 60, 56580),
              (61, 80, 10097), (81, 100, 165306)]
    cur = _t(session, data_dir, "reason", ["r_reason_sk"]) \
        .where(col("r_reason_sk") == lit(1))
    outs = []
    for i, (lo, hi, thresh) in enumerate(bounds):
        b = ss.where((col("ss_quantity") >= lit(lo))
                     & (col("ss_quantity") <= lit(hi))) \
            .agg(CountStar().alias(f"_cnt{i}"),
                 Average(col("ss_ext_discount_amt")).alias(f"_d{i}"),
                 Average(col("ss_net_paid")).alias(f"_p{i}"))
        cur = cur.join(b, how="cross")
        outs.append(If(col(f"_cnt{i}") > lit(thresh),
                       col(f"_d{i}"), col(f"_p{i}"))
                    .alias(f"bucket{i + 1}"))
    return cur.select(*outs)


# ---------------------------------------------------------------------------
# exists-family demographics: q10 / q35
# ---------------------------------------------------------------------------

def _active_customers(session, data_dir, d_filter):
    """Union of customer keys active in store + (web or catalog) within
    the window: EXISTS ss AND (EXISTS ws OR EXISTS cs)."""
    dd = d_filter(_t(session, data_dir, "date_dim",
                     ["d_date_sk", "d_year", "d_moy", "d_qoy"])) \
        .select(col("d_date_sk"))
    ss = _t(session, data_dir, "store_sales",
            ["ss_customer_sk", "ss_sold_date_sk"]) \
        .join(dd, on=[("ss_sold_date_sk", "d_date_sk")]) \
        .select(col("ss_customer_sk").alias("k"))
    ws = _t(session, data_dir, "web_sales",
            ["ws_bill_customer_sk", "ws_sold_date_sk"]) \
        .join(dd, on=[("ws_sold_date_sk", "d_date_sk")]) \
        .select(col("ws_bill_customer_sk").alias("k"))
    cs = _t(session, data_dir, "catalog_sales",
            ["cs_ship_customer_sk", "cs_sold_date_sk"]) \
        .join(dd, on=[("cs_sold_date_sk", "d_date_sk")]) \
        .select(col("cs_ship_customer_sk").alias("k"))
    return ss, ws.union(cs)


def q10(session, data_dir: str):
    """TPC-DS q10: demographics counts for county customers active in
    store and web-or-catalog, 2002 H1."""
    ss_keys, other_keys = _active_customers(
        session, data_dir,
        lambda dd: dd.where((col("d_year") == lit(2002))
                            & (col("d_moy") >= lit(1))
                            & (col("d_moy") <= lit(4))))
    cu = _t(session, data_dir, "customer",
            ["c_customer_sk", "c_current_addr_sk", "c_current_cdemo_sk"])
    ca = _t(session, data_dir, "customer_address",
            ["ca_address_sk", "ca_county"]) \
        .where(In(col("ca_county"),
                  [lit(c) for c in
                   ("Rush County", "Toole County", "Jefferson County",
                    "Dona Ana County", "La Porte County")])) \
        .select(col("ca_address_sk"))
    cd = _t(session, data_dir, "customer_demographics")
    keys = ["cd_gender", "cd_marital_status", "cd_education_status",
            "cd_purchase_estimate", "cd_credit_rating", "cd_dep_count",
            "cd_dep_employed_count", "cd_dep_college_count"]
    base = cu.join(ca, on=[("c_current_addr_sk", "ca_address_sk")]) \
        .join(ss_keys, on=[("c_customer_sk", "k")], how="semi") \
        .join(other_keys, on=[("c_customer_sk", "k")], how="semi") \
        .join(cd, on=[("c_current_cdemo_sk", "cd_demo_sk")])
    aggs = [CountStar().alias(f"cnt{i}") for i in range(1, 7)]
    return base.group_by(*keys).agg(*aggs) \
        .order_by(*[(k, True) for k in keys]).limit(100)


def q35(session, data_dir: str):
    """TPC-DS q35: demographics stats for customers active in store and
    web-or-catalog, 2002 Q1-Q3."""
    ss_keys, other_keys = _active_customers(
        session, data_dir,
        lambda dd: dd.where((col("d_year") == lit(2002))
                            & (col("d_qoy") < lit(4))))
    cu = _t(session, data_dir, "customer",
            ["c_customer_sk", "c_current_addr_sk", "c_current_cdemo_sk"])
    ca = _t(session, data_dir, "customer_address",
            ["ca_address_sk", "ca_state"])
    cd = _t(session, data_dir, "customer_demographics")
    base = cu.join(ca, on=[("c_current_addr_sk", "ca_address_sk")]) \
        .join(ss_keys, on=[("c_customer_sk", "k")], how="semi") \
        .join(other_keys, on=[("c_customer_sk", "k")], how="semi") \
        .join(cd, on=[("c_current_cdemo_sk", "cd_demo_sk")])
    keys = ["ca_state", "cd_gender", "cd_marital_status", "cd_dep_count",
            "cd_dep_employed_count", "cd_dep_college_count"]
    return base.group_by(*keys).agg(
        CountStar().alias("cnt1"),
        Min(col("cd_dep_count")).alias("min1"),
        Max(col("cd_dep_count")).alias("max1"),
        Average(col("cd_dep_count")).alias("avg1"),
        CountStar().alias("cnt2"),
        Min(col("cd_dep_employed_count")).alias("min2"),
        Max(col("cd_dep_employed_count")).alias("max2"),
        Average(col("cd_dep_employed_count")).alias("avg2"),
        CountStar().alias("cnt3"),
        Min(col("cd_dep_college_count")).alias("min3"),
        Max(col("cd_dep_college_count")).alias("max3"),
        Average(col("cd_dep_college_count")).alias("avg3")) \
        .order_by(*[(k, True) for k in keys]).limit(100)


# ---------------------------------------------------------------------------
# q28: list-price buckets cross-join
# ---------------------------------------------------------------------------

def q28(session, data_dir: str):
    """TPC-DS q28: six price-bucket stats cross-joined into one row."""
    ss = _t(session, data_dir, "store_sales",
            ["ss_quantity", "ss_list_price", "ss_coupon_amt",
             "ss_wholesale_cost"])
    buckets = [
        (0, 5, 8, 459, 57), (6, 10, 90, 2323, 31), (11, 15, 142, 12214, 79),
        (16, 20, 135, 6071, 38), (21, 25, 122, 836, 17),
        (26, 30, 154, 7326, 7)]
    out = None
    for i, (qlo, qhi, lp, ca_, wc) in enumerate(buckets, 1):
        b = ss.where(
            (col("ss_quantity") >= lit(qlo)) & (col("ss_quantity") <= lit(qhi))
            & (((col("ss_list_price") >= lit(float(lp)))
                & (col("ss_list_price") <= lit(float(lp + 10))))
               | ((col("ss_coupon_amt") >= lit(float(ca_)))
                  & (col("ss_coupon_amt") <= lit(float(ca_ + 1000))))
               | ((col("ss_wholesale_cost") >= lit(float(wc)))
                  & (col("ss_wholesale_cost") <= lit(float(wc + 20)))))) \
            .agg(Average(col("ss_list_price")).alias(f"b{i}_lp"),
                 Count(col("ss_list_price")).alias(f"b{i}_cnt"),
                 CountDistinct(col("ss_list_price")).alias(f"b{i}_cntd"))
        out = b if out is None else out.join(b, how="cross")
    return out.limit(100)


# ---------------------------------------------------------------------------
# q34 / q45 / q46
# ---------------------------------------------------------------------------

def q34(session, data_dir: str):
    """TPC-DS q34: 15-20 item tickets for high-dependency households."""
    ss = _t(session, data_dir, "store_sales",
            ["ss_sold_date_sk", "ss_store_sk", "ss_hdemo_sk",
             "ss_customer_sk", "ss_ticket_number"])
    dt = _t(session, data_dir, "date_dim",
            ["d_date_sk", "d_dom", "d_year"]) \
        .where((((col("d_dom") >= lit(1)) & (col("d_dom") <= lit(3)))
                | ((col("d_dom") >= lit(25)) & (col("d_dom") <= lit(28))))
               & In(col("d_year"), [lit(1999), lit(2000), lit(2001)])) \
        .select(col("d_date_sk"))
    st = _t(session, data_dir, "store", ["s_store_sk", "s_county"]) \
        .where(col("s_county") == lit("Williamson County")) \
        .select(col("s_store_sk"))
    hd = _t(session, data_dir, "household_demographics",
            ["hd_demo_sk", "hd_buy_potential", "hd_vehicle_count",
             "hd_dep_count"]) \
        .where(Or(col("hd_buy_potential") == lit(">10000"),
                  col("hd_buy_potential") == lit("unknown"))
               & (col("hd_vehicle_count") > lit(0))
               & (If(col("hd_vehicle_count") > lit(0),
                     col("hd_dep_count").cast(T.DoubleType())
                     / col("hd_vehicle_count"), lit(None)) > lit(1.2))) \
        .select(col("hd_demo_sk"))
    cu = _t(session, data_dir, "customer",
            ["c_customer_sk", "c_last_name", "c_first_name",
             "c_salutation", "c_preferred_cust_flag"])
    grouped = ss.join(dt, on=[("ss_sold_date_sk", "d_date_sk")]) \
        .join(st, on=[("ss_store_sk", "s_store_sk")]) \
        .join(hd, on=[("ss_hdemo_sk", "hd_demo_sk")]) \
        .group_by("ss_ticket_number", "ss_customer_sk") \
        .agg(CountStar().alias("cnt")) \
        .where((col("cnt") >= lit(15)) & (col("cnt") <= lit(20)))
    return grouped.join(cu, on=[("ss_customer_sk", "c_customer_sk")]) \
        .select(col("c_last_name"), col("c_first_name"),
                col("c_salutation"), col("c_preferred_cust_flag"),
                col("ss_ticket_number"), col("cnt")) \
        .order_by(("c_last_name", True), ("c_first_name", True),
                  ("c_salutation", True), ("c_preferred_cust_flag", False),
                  ("ss_ticket_number", True))


def q45(session, data_dir: str):
    """TPC-DS q45: web sales by customer zip/city, zip list OR item
    subquery."""
    ids_rows = _t(session, data_dir, "item",
                  ["i_item_sk", "i_item_id"]) \
        .where(In(col("i_item_sk"),
                  [lit(k) for k in (2, 3, 5, 7, 11, 13, 17, 19, 23, 29)])) \
        .select(col("i_item_id")).collect()
    ids = sorted({r[0] for r in ids_rows}) or ["<none>"]
    zips = ["85669", "86197", "88274", "83405", "86475", "85392", "85460",
            "80348", "81792"]
    ws = _t(session, data_dir, "web_sales",
            ["ws_bill_customer_sk", "ws_item_sk", "ws_sold_date_sk",
             "ws_sales_price"])
    cu = _t(session, data_dir, "customer",
            ["c_customer_sk", "c_current_addr_sk"])
    ca = _t(session, data_dir, "customer_address",
            ["ca_address_sk", "ca_zip", "ca_city"])
    it = _t(session, data_dir, "item", ["i_item_sk", "i_item_id"])
    dt = _t(session, data_dir, "date_dim",
            ["d_date_sk", "d_qoy", "d_year"]) \
        .where((col("d_qoy") == lit(2)) & (col("d_year") == lit(2001))) \
        .select(col("d_date_sk"))
    return ws.join(cu, on=[("ws_bill_customer_sk", "c_customer_sk")]) \
        .join(ca, on=[("c_current_addr_sk", "ca_address_sk")]) \
        .join(it, on=[("ws_item_sk", "i_item_sk")]) \
        .join(dt, on=[("ws_sold_date_sk", "d_date_sk")]) \
        .where(Or(In(Substring(col("ca_zip"), lit(1), lit(5)),
                     [lit(z) for z in zips]),
                  In(col("i_item_id"), [lit(i) for i in ids]))) \
        .group_by("ca_zip", "ca_city") \
        .agg(Sum(col("ws_sales_price")).alias("sum_price")) \
        .order_by(("ca_zip", True), ("ca_city", True)).limit(100)


def q46(session, data_dir: str):
    """TPC-DS q46: weekend ticket totals where bought city differs from
    current city."""
    ss = _t(session, data_dir, "store_sales",
            ["ss_sold_date_sk", "ss_store_sk", "ss_hdemo_sk", "ss_addr_sk",
             "ss_customer_sk", "ss_ticket_number", "ss_coupon_amt",
             "ss_net_profit"])
    dt = _t(session, data_dir, "date_dim",
            ["d_date_sk", "d_dow", "d_year"]) \
        .where(In(col("d_dow"), [lit(6), lit(0)])
               & In(col("d_year"), [lit(1999), lit(2000), lit(2001)])) \
        .select(col("d_date_sk"))
    st = _t(session, data_dir, "store", ["s_store_sk", "s_city"]) \
        .where(In(col("s_city"), [lit("Fairview"), lit("Midway")])) \
        .select(col("s_store_sk"))
    hd = _t(session, data_dir, "household_demographics",
            ["hd_demo_sk", "hd_dep_count", "hd_vehicle_count"]) \
        .where(Or(col("hd_dep_count") == lit(4),
                  col("hd_vehicle_count") == lit(3))) \
        .select(col("hd_demo_sk"))
    ca = _t(session, data_dir, "customer_address",
            ["ca_address_sk", "ca_city"])
    grouped = ss.join(dt, on=[("ss_sold_date_sk", "d_date_sk")]) \
        .join(st, on=[("ss_store_sk", "s_store_sk")]) \
        .join(hd, on=[("ss_hdemo_sk", "hd_demo_sk")]) \
        .join(ca, on=[("ss_addr_sk", "ca_address_sk")]) \
        .group_by("ss_ticket_number", "ss_customer_sk", "ss_addr_sk",
                  "ca_city") \
        .agg(Sum(col("ss_coupon_amt")).alias("amt"),
             Sum(col("ss_net_profit")).alias("profit")) \
        .select(col("ss_ticket_number"), col("ss_customer_sk"),
                col("ca_city").alias("bought_city"), col("amt"),
                col("profit"))
    cu = _t(session, data_dir, "customer",
            ["c_customer_sk", "c_current_addr_sk", "c_first_name",
             "c_last_name"])
    ca2 = _t(session, data_dir, "customer_address",
             ["ca_address_sk", "ca_city"]) \
        .select(col("ca_address_sk").alias("cur_addr_sk"),
                col("ca_city").alias("ca_city"))
    return grouped.join(cu, on=[("ss_customer_sk", "c_customer_sk")]) \
        .join(ca2, on=[("c_current_addr_sk", "cur_addr_sk")]) \
        .where(~(col("ca_city") == col("bought_city"))) \
        .select(col("c_last_name"), col("c_first_name"), col("ca_city"),
                col("bought_city"), col("ss_ticket_number"), col("amt"),
                col("profit")) \
        .order_by(("c_last_name", True), ("c_first_name", True),
                  ("ca_city", True), ("bought_city", True),
                  ("ss_ticket_number", True)) \
        .limit(100)


# ---------------------------------------------------------------------------
# q41: item-variant correlated count
# ---------------------------------------------------------------------------

def q41(session, data_dir: str):
    """TPC-DS q41: product names of manufacturers with matching item
    variants (correlated count > 0 -> semi join on manufacturer)."""
    it = _t(session, data_dir, "item")

    def band(cat, colors, units, sizes):
        return ((col("i_category") == lit(cat))
                & In(col("i_color"), [lit(c) for c in colors])
                & In(col("i_units"), [lit(u) for u in units])
                & In(col("i_size"), [lit(s) for s in sizes]))

    variants = Or(
        Or(Or(band("Women", ("powder", "khaki"), ("Ounce", "Oz"),
                   ("medium", "extra large")),
              band("Women", ("brown", "honeydew"), ("Bunch", "Ton"),
                   ("N/A", "small"))),
           Or(band("Men", ("floral", "deep"), ("N/A", "Dozen"),
                   ("petite", "large")),
              band("Men", ("light", "cornflower"), ("Box", "Pound"),
                   ("medium", "extra large")))),
        Or(Or(band("Women", ("midnight", "snow"), ("Pallet", "Gross"),
                   ("medium", "extra large")),
              band("Women", ("cyan", "papaya"), ("Cup", "Dram"),
                   ("N/A", "small"))),
           Or(band("Men", ("orange", "frosted"), ("Each", "Tbl"),
                   ("petite", "large")),
              band("Men", ("forest", "ghost"), ("Lb", "Bundle"),
                   ("medium", "extra large")))))
    manufs = it.where(variants).select(col("i_manufact").alias("vm")) \
        .distinct()
    return it.where((col("i_manufact_id") >= lit(738))
                    & (col("i_manufact_id") <= lit(778))) \
        .join(manufs, on=[("i_manufact", "vm")], how="semi") \
        .select(col("i_product_name")).distinct() \
        .order_by(("i_product_name", True)).limit(100)


# ---------------------------------------------------------------------------
# q44: best/worst items by store profit rank
# ---------------------------------------------------------------------------

def q44(session, data_dir: str):
    """TPC-DS q44: rank items by avg net profit in store 4, pair best
    with worst."""
    from spark_rapids_tpu.expr.window import (Rank, WindowExpression,
                                              WindowSpec)
    ss = _t(session, data_dir, "store_sales",
            ["ss_item_sk", "ss_store_sk", "ss_addr_sk", "ss_net_profit"])
    store4 = ss.where(col("ss_store_sk") == lit(4))
    # baseline: avg profit of null-address rows — kept IN the plan as a
    # 1-row grand aggregate cross-joined into the ranking input (an
    # eager .collect() would move fact-table work outside the
    # benchmarked execution)
    base = store4.where(col("ss_addr_sk").is_null()) \
        .agg(Average(col("ss_net_profit")).alias("_base"))
    v1 = store4.group_by("ss_item_sk") \
        .agg(Average(col("ss_net_profit")).alias("rank_col")) \
        .join(base, how="cross") \
        .where(col("rank_col") >
               lit(0.9) * Coalesce(col("_base"), lit(0.0))) \
        .select(col("ss_item_sk"), col("rank_col"))
    asc = WindowExpression(Rank(), WindowSpec(
        order_by=((col("rank_col"), True),)))
    desc = WindowExpression(Rank(), WindowSpec(
        order_by=((col("rank_col"), False),)))
    up = v1.select(col("ss_item_sk").alias("item_sk_a"),
                   asc.alias("rnk")).where(col("rnk") < lit(11))
    dn = v1.select(col("ss_item_sk").alias("item_sk_d"),
                   desc.alias("rnk_d")).where(col("rnk_d") < lit(11))
    i1 = _t(session, data_dir, "item",
            ["i_item_sk", "i_product_name"]) \
        .select(col("i_item_sk").alias("i1_sk"),
                col("i_product_name").alias("best_performing"))
    i2 = _t(session, data_dir, "item",
            ["i_item_sk", "i_product_name"]) \
        .select(col("i_item_sk").alias("i2_sk"),
                col("i_product_name").alias("worst_performing"))
    return up.join(dn, on=[("rnk", "rnk_d")]) \
        .join(i1, on=[("item_sk_a", "i1_sk")]) \
        .join(i2, on=[("item_sk_d", "i2_sk")]) \
        .select(col("rnk"), col("best_performing"),
                col("worst_performing")) \
        .order_by(("rnk", True)).limit(100)


# ---------------------------------------------------------------------------
# q49: worst return ratios per channel
# ---------------------------------------------------------------------------

def _return_ratios(session, data_dir, channel, sales_tbl, returns_tbl,
                   cols):
    from spark_rapids_tpu.expr.window import (Rank, WindowExpression,
                                              WindowSpec)
    (s_item, s_order, s_qty, s_paid, s_profit, s_date,
     r_item, r_order, r_qty, r_amt) = cols
    dd = _t(session, data_dir, "date_dim",
            ["d_date_sk", "d_year", "d_moy"]) \
        .where((col("d_year") == lit(2001)) & (col("d_moy") == lit(12))) \
        .select(col("d_date_sk"))
    sales = _t(session, data_dir, sales_tbl,
               [s_item, s_order, s_qty, s_paid, s_profit, s_date]) \
        .where((col(s_profit) > lit(1.0)) & (col(s_paid) > lit(0.0))
               & (col(s_qty) > lit(0)))
    rets = _t(session, data_dir, returns_tbl,
              [r_item, r_order, r_qty, r_amt]) \
        .where(col(r_amt) > lit(10000.0))
    j = sales.join(rets, on=[(s_order, r_order), (s_item, r_item)]) \
        .join(dd, on=[(s_date, "d_date_sk")]) \
        .group_by(s_item).agg(
            (Sum(Coalesce(col(r_qty), lit(0))).cast(T.DoubleType())
             / Sum(Coalesce(col(s_qty), lit(0))).cast(T.DoubleType()))
            .alias("return_ratio"),
            (Sum(Coalesce(col(r_amt), lit(0.0)))
             / Sum(Coalesce(col(s_paid), lit(0.0))))
            .alias("currency_ratio"))
    rr = WindowExpression(Rank(), WindowSpec(
        order_by=((col("return_ratio"), True),)))
    cr = WindowExpression(Rank(), WindowSpec(
        order_by=((col("currency_ratio"), True),)))
    ranked = j.select(lit(channel).alias("channel"),
                      col(s_item).alias("item"), col("return_ratio"),
                      rr.alias("return_rank"), cr.alias("currency_rank"))
    return ranked.where(Or(col("return_rank") <= lit(10),
                           col("currency_rank") <= lit(10)))


def q49(session, data_dir: str):
    """TPC-DS q49: worst return ratios across the three channels."""
    web = _return_ratios(
        session, data_dir, "web", "web_sales", "web_returns",
        ("ws_item_sk", "ws_order_number", "ws_quantity", "ws_net_paid",
         "ws_net_profit", "ws_sold_date_sk",
         "wr_item_sk", "wr_order_number", "wr_return_quantity",
         "wr_return_amt"))
    cat = _return_ratios(
        session, data_dir, "catalog", "catalog_sales", "catalog_returns",
        ("cs_item_sk", "cs_order_number", "cs_quantity", "cs_net_paid",
         "cs_net_profit", "cs_sold_date_sk",
         "cr_item_sk", "cr_order_number", "cr_return_quantity",
         "cr_return_amount"))
    sto = _return_ratios(
        session, data_dir, "store", "store_sales", "store_returns",
        ("ss_item_sk", "ss_ticket_number", "ss_quantity", "ss_net_paid",
         "ss_net_profit", "ss_sold_date_sk",
         "sr_item_sk", "sr_ticket_number", "sr_return_quantity",
         "sr_return_amt"))
    return web.union(cat).union(sto).distinct() \
        .order_by(("channel", True), ("return_rank", True),
                  ("currency_rank", True)) \
        .limit(100)


# ---------------------------------------------------------------------------
# q54: maternity follow-up revenue segments
# ---------------------------------------------------------------------------

def q54(session, data_dir: str):
    """TPC-DS q54: revenue segments of customers who bought Women/
    maternity items in Dec 1998."""
    dd = _t(session, data_dir, "date_dim",
            ["d_date_sk", "d_moy", "d_year", "d_month_seq"])
    target = dd.where((col("d_moy") == lit(12))
                      & (col("d_year") == lit(1998))) \
        .select(col("d_date_sk"))
    cs = _t(session, data_dir, "catalog_sales",
            ["cs_sold_date_sk", "cs_bill_customer_sk", "cs_item_sk"]) \
        .select(col("cs_sold_date_sk").alias("sold_date_sk"),
                col("cs_bill_customer_sk").alias("customer_sk"),
                col("cs_item_sk").alias("item_sk"))
    ws = _t(session, data_dir, "web_sales",
            ["ws_sold_date_sk", "ws_bill_customer_sk", "ws_item_sk"]) \
        .select(col("ws_sold_date_sk").alias("sold_date_sk"),
                col("ws_bill_customer_sk").alias("customer_sk"),
                col("ws_item_sk").alias("item_sk"))
    it = _t(session, data_dir, "item",
            ["i_item_sk", "i_category", "i_class"]) \
        .where((col("i_category") == lit("Women"))
               & (col("i_class") == lit("maternity"))) \
        .select(col("i_item_sk"))
    cu = _t(session, data_dir, "customer",
            ["c_customer_sk", "c_current_addr_sk"])
    my_customers = cs.union(ws) \
        .join(target, on=[("sold_date_sk", "d_date_sk")], how="semi") \
        .join(it, on=[("item_sk", "i_item_sk")], how="semi") \
        .join(cu, on=[("customer_sk", "c_customer_sk")]) \
        .select(col("c_customer_sk"), col("c_current_addr_sk")) \
        .distinct()
    seq_rows = dd.where((col("d_year") == lit(1998))
                        & (col("d_moy") == lit(12))) \
        .select(col("d_month_seq")).limit(1).collect()
    base_seq = seq_rows[0][0]
    window = dd.where((col("d_month_seq") >= lit(base_seq + 1))
                      & (col("d_month_seq") <= lit(base_seq + 3))) \
        .select(col("d_date_sk"))
    ca = _t(session, data_dir, "customer_address",
            ["ca_address_sk", "ca_county", "ca_state"])
    st = _t(session, data_dir, "store", ["s_county", "s_state"]) \
        .select(col("s_county"), col("s_state")).distinct()
    ss = _t(session, data_dir, "store_sales",
            ["ss_sold_date_sk", "ss_customer_sk", "ss_ext_sales_price"])
    revenue = my_customers \
        .join(ca, on=[("c_current_addr_sk", "ca_address_sk")]) \
        .join(st, on=[("ca_county", "s_county"),
                      ("ca_state", "s_state")], how="semi") \
        .join(ss, on=[("c_customer_sk", "ss_customer_sk")]) \
        .join(window, on=[("ss_sold_date_sk", "d_date_sk")], how="semi") \
        .group_by("c_customer_sk") \
        .agg(Sum(col("ss_ext_sales_price")).alias("revenue"))
    segments = revenue.select(
        (col("revenue") / lit(50.0)).cast(T.IntegerType())
        .alias("segment"))
    return segments.group_by("segment") \
        .agg(CountStar().alias("num_customers")) \
        .with_column("segment_base", col("segment") * lit(50)) \
        .order_by(("segment", True), ("num_customers", True)).limit(100)


# ---------------------------------------------------------------------------
# q56: color-item tri-channel totals
# ---------------------------------------------------------------------------

def q56(session, data_dir: str):
    """TPC-DS q56: slate/blanched/burnished item revenue across
    channels, gmt -5, Feb 2001."""
    ids_rows = _t(session, data_dir, "item",
                  ["i_item_id", "i_color"]) \
        .where(In(col("i_color"),
                  [lit(c) for c in ("slate", "blanched", "burnished")])) \
        .select(col("i_item_id")).distinct().collect()
    ids = sorted(r[0] for r in ids_rows) or ["<none>"]
    dd = _t(session, data_dir, "date_dim",
            ["d_date_sk", "d_year", "d_moy"]) \
        .where((col("d_year") == lit(2001)) & (col("d_moy") == lit(2))) \
        .select(col("d_date_sk"))
    ca = _t(session, data_dir, "customer_address",
            ["ca_address_sk", "ca_gmt_offset"]) \
        .where(col("ca_gmt_offset") == lit(-5.0)) \
        .select(col("ca_address_sk"))
    it = _t(session, data_dir, "item", ["i_item_sk", "i_item_id"]) \
        .where(In(col("i_item_id"), [lit(i) for i in ids]))

    def chan(sales, date_c, item_c, addr_c, price_c):
        return sales.join(dd, on=[(date_c, "d_date_sk")]) \
            .join(it, on=[(item_c, "i_item_sk")]) \
            .join(ca, on=[(addr_c, "ca_address_sk")]) \
            .group_by("i_item_id") \
            .agg(Sum(col(price_c)).alias("total_sales"))

    ss = chan(_t(session, data_dir, "store_sales",
                 ["ss_sold_date_sk", "ss_item_sk", "ss_addr_sk",
                  "ss_ext_sales_price"]),
              "ss_sold_date_sk", "ss_item_sk", "ss_addr_sk",
              "ss_ext_sales_price")
    cs = chan(_t(session, data_dir, "catalog_sales",
                 ["cs_sold_date_sk", "cs_item_sk", "cs_bill_addr_sk",
                  "cs_ext_sales_price"]),
              "cs_sold_date_sk", "cs_item_sk", "cs_bill_addr_sk",
              "cs_ext_sales_price")
    ws = chan(_t(session, data_dir, "web_sales",
                 ["ws_sold_date_sk", "ws_item_sk", "ws_bill_addr_sk",
                  "ws_ext_sales_price"]),
              "ws_sold_date_sk", "ws_item_sk", "ws_bill_addr_sk",
              "ws_ext_sales_price")
    return ss.union(cs).union(ws).group_by("i_item_id") \
        .agg(Sum(col("total_sales")).alias("total_sales")) \
        .order_by(("total_sales", True)).limit(100)


# ---------------------------------------------------------------------------
# q58: items selling evenly across channels in one week
# ---------------------------------------------------------------------------

def q58(session, data_dir: str):
    """TPC-DS q58: items with balanced revenue across the three channels
    for the week of 2000-01-03."""
    target_sk = _date_sk(2000, 1, 3)
    dd_all = _t(session, data_dir, "date_dim",
                ["d_date_sk", "d_date", "d_week_seq"])
    wk_rows = dd_all.where(col("d_date_sk") == lit(target_sk)) \
        .select(col("d_week_seq")).limit(1).collect()
    wk = wk_rows[0][0]
    week_dates = dd_all.where(col("d_week_seq") == lit(wk)) \
        .select(col("d_date_sk"))
    it = _t(session, data_dir, "item", ["i_item_sk", "i_item_id"])

    def rev(sales, item_c, date_c, price_c, name):
        return sales.join(week_dates, on=[(date_c, "d_date_sk")],
                          how="semi") \
            .join(it, on=[(item_c, "i_item_sk")]) \
            .group_by("i_item_id") \
            .agg(Sum(col(price_c)).alias(name)) \
            .select(col("i_item_id").alias(f"{name}_id"), col(name))

    ss = rev(_t(session, data_dir, "store_sales",
                ["ss_item_sk", "ss_sold_date_sk", "ss_ext_sales_price"]),
             "ss_item_sk", "ss_sold_date_sk", "ss_ext_sales_price",
             "ss_item_rev")
    cs = rev(_t(session, data_dir, "catalog_sales",
                ["cs_item_sk", "cs_sold_date_sk", "cs_ext_sales_price"]),
             "cs_item_sk", "cs_sold_date_sk", "cs_ext_sales_price",
             "cs_item_rev")
    ws = rev(_t(session, data_dir, "web_sales",
                ["ws_item_sk", "ws_sold_date_sk", "ws_ext_sales_price"]),
             "ws_item_sk", "ws_sold_date_sk", "ws_ext_sales_price",
             "ws_item_rev")
    j = ss.join(cs, on=[("ss_item_rev_id", "cs_item_rev_id")]) \
        .join(ws, on=[("ss_item_rev_id", "ws_item_rev_id")])
    between = lambda a, b: ((col(a) >= lit(0.9) * col(b))
                            & (col(a) <= lit(1.1) * col(b)))
    avg3 = ((col("ss_item_rev") + col("cs_item_rev") + col("ws_item_rev"))
            / lit(3.0))
    return j.where(between("ss_item_rev", "cs_item_rev")
                   & between("ss_item_rev", "ws_item_rev")
                   & between("cs_item_rev", "ss_item_rev")
                   & between("cs_item_rev", "ws_item_rev")
                   & between("ws_item_rev", "ss_item_rev")
                   & between("ws_item_rev", "cs_item_rev")) \
        .select(col("ss_item_rev_id").alias("item_id"),
                col("ss_item_rev"),
                (col("ss_item_rev") / (col("ss_item_rev")
                                       + col("cs_item_rev")
                                       + col("ws_item_rev")) / lit(3.0)
                 * lit(100.0)).alias("ss_dev"),
                col("cs_item_rev"),
                (col("cs_item_rev") / (col("ss_item_rev")
                                       + col("cs_item_rev")
                                       + col("ws_item_rev")) / lit(3.0)
                 * lit(100.0)).alias("cs_dev"),
                col("ws_item_rev"),
                (col("ws_item_rev") / (col("ss_item_rev")
                                       + col("cs_item_rev")
                                       + col("ws_item_rev")) / lit(3.0)
                 * lit(100.0)).alias("ws_dev"),
                avg3.alias("average")) \
        .order_by(("item_id", True), ("ss_item_rev", True)).limit(100)


# ---------------------------------------------------------------------------
# q76: null-leg channel counts
# ---------------------------------------------------------------------------

def q76(session, data_dir: str):
    """TPC-DS q76: sales recorded with NULL keys per channel."""
    it = _t(session, data_dir, "item", ["i_item_sk", "i_category"])
    dd = _t(session, data_dir, "date_dim",
            ["d_date_sk", "d_year", "d_qoy"])

    def leg(sales, null_c, date_c, item_c, price_c, label):
        return sales.where(col(null_c).is_null()) \
            .join(dd, on=[(date_c, "d_date_sk")]) \
            .join(it, on=[(item_c, "i_item_sk")]) \
            .select(lit(label).alias("channel"),
                    col("d_year"), col("d_qoy"), col("i_category"),
                    col(price_c).alias("ext_sales_price"))

    ss = leg(_t(session, data_dir, "store_sales",
                ["ss_store_sk", "ss_sold_date_sk", "ss_item_sk",
                 "ss_ext_sales_price"]),
             "ss_store_sk", "ss_sold_date_sk", "ss_item_sk",
             "ss_ext_sales_price", "store")
    ws = leg(_t(session, data_dir, "web_sales",
                ["ws_ship_customer_sk", "ws_sold_date_sk", "ws_item_sk",
                 "ws_ext_sales_price"]),
             "ws_ship_customer_sk", "ws_sold_date_sk", "ws_item_sk",
             "ws_ext_sales_price", "web")
    cs = leg(_t(session, data_dir, "catalog_sales",
                ["cs_ship_addr_sk", "cs_sold_date_sk", "cs_item_sk",
                 "cs_ext_sales_price"]),
             "cs_ship_addr_sk", "cs_sold_date_sk", "cs_item_sk",
             "cs_ext_sales_price", "catalog")
    return ss.union(ws).union(cs) \
        .group_by("channel", "d_year", "d_qoy", "i_category") \
        .agg(CountStar().alias("sales_cnt"),
             Sum(col("ext_sales_price")).alias("sales_amt")) \
        .order_by(("channel", True), ("d_year", True), ("d_qoy", True),
                  ("i_category", True)) \
        .limit(100)


# ---------------------------------------------------------------------------
# q83: returned-quantity three-way comparison
# ---------------------------------------------------------------------------

def q83(session, data_dir: str):
    """TPC-DS q83: return quantities per item across channels for three
    specific weeks."""
    dates = [_date_sk(2000, 6, 30), _date_sk(2000, 9, 27),
             _date_sk(2000, 11, 17)]
    dd_all = _t(session, data_dir, "date_dim",
                ["d_date_sk", "d_week_seq"])
    wk_rows = dd_all.where(In(col("d_date_sk"),
                              [lit(d) for d in dates])) \
        .select(col("d_week_seq")).distinct().collect()
    weeks = sorted(r[0] for r in wk_rows)
    week_dates = dd_all.where(In(col("d_week_seq"),
                                 [lit(w) for w in weeks])) \
        .select(col("d_date_sk"))
    it = _t(session, data_dir, "item", ["i_item_sk", "i_item_id"])

    def rets(tbl, item_c, date_c, qty_c, name):
        return _t(session, data_dir, tbl, [item_c, date_c, qty_c]) \
            .join(week_dates, on=[(date_c, "d_date_sk")], how="semi") \
            .join(it, on=[(item_c, "i_item_sk")]) \
            .group_by("i_item_id") \
            .agg(Sum(col(qty_c)).alias(name)) \
            .select(col("i_item_id").alias(f"{name}_id"), col(name))

    sr = rets("store_returns", "sr_item_sk", "sr_returned_date_sk",
              "sr_return_quantity", "sr_item_qty")
    cr = rets("catalog_returns", "cr_item_sk", "cr_returned_date_sk",
              "cr_return_quantity", "cr_item_qty")
    wr = rets("web_returns", "wr_item_sk", "wr_returned_date_sk",
              "wr_return_quantity", "wr_item_qty")
    j = sr.join(cr, on=[("sr_item_qty_id", "cr_item_qty_id")]) \
        .join(wr, on=[("sr_item_qty_id", "wr_item_qty_id")])
    total = (col("sr_item_qty") + col("cr_item_qty")
             + col("wr_item_qty")).cast(T.DoubleType())
    return j.select(
        col("sr_item_qty_id").alias("item_id"), col("sr_item_qty"),
        (col("sr_item_qty") / total / lit(3.0) * lit(100.0))
        .alias("sr_dev"),
        col("cr_item_qty"),
        (col("cr_item_qty") / total / lit(3.0) * lit(100.0))
        .alias("cr_dev"),
        col("wr_item_qty"),
        (col("wr_item_qty") / total / lit(3.0) * lit(100.0))
        .alias("wr_dev"),
        (total / lit(3.0)).alias("average")) \
        .order_by(("item_id", True), ("sr_item_qty", True)).limit(100)


# ---------------------------------------------------------------------------
# q84 / q85 / q86
# ---------------------------------------------------------------------------

def q84(session, data_dir: str):
    """TPC-DS q84: Edgewood customers in an income band with store
    returns."""
    cu = _t(session, data_dir, "customer",
            ["c_customer_sk", "c_customer_id", "c_first_name",
             "c_last_name", "c_current_addr_sk", "c_current_cdemo_sk",
             "c_current_hdemo_sk"])
    ca = _t(session, data_dir, "customer_address",
            ["ca_address_sk", "ca_city"]) \
        .where(col("ca_city") == lit("Edgewood")) \
        .select(col("ca_address_sk"))
    hd = _t(session, data_dir, "household_demographics",
            ["hd_demo_sk", "hd_income_band_sk"])
    ib = _t(session, data_dir, "income_band") \
        .where((col("ib_lower_bound") >= lit(38128))
               & (col("ib_upper_bound") <= lit(38128 + 50000))) \
        .select(col("ib_income_band_sk"))
    sr = _t(session, data_dir, "store_returns", ["sr_cdemo_sk"]) \
        .select(col("sr_cdemo_sk"))
    cd = _t(session, data_dir, "customer_demographics", ["cd_demo_sk"])
    name = Concat(Coalesce(col("c_last_name"), lit("")), lit(", "),
                  Coalesce(col("c_first_name"), lit("")))
    return cu.join(ca, on=[("c_current_addr_sk", "ca_address_sk")],
                   how="semi") \
        .join(hd, on=[("c_current_hdemo_sk", "hd_demo_sk")]) \
        .join(ib, on=[("hd_income_band_sk", "ib_income_band_sk")],
              how="semi") \
        .join(cd, on=[("c_current_cdemo_sk", "cd_demo_sk")]) \
        .join(sr, on=[("cd_demo_sk", "sr_cdemo_sk")], how="semi") \
        .select(col("c_customer_id").alias("customer_id"),
                name.alias("customername")) \
        .order_by(("customer_id", True)).limit(100)


def q85(session, data_dir: str):
    """TPC-DS q85: web-return reasons under demographic/state/profit
    bands."""
    ws = _t(session, data_dir, "web_sales",
            ["ws_item_sk", "ws_order_number", "ws_web_page_sk",
             "ws_sold_date_sk", "ws_quantity", "ws_sales_price",
             "ws_net_profit"])
    wr = _t(session, data_dir, "web_returns",
            ["wr_item_sk", "wr_order_number", "wr_refunded_cdemo_sk",
             "wr_returning_cdemo_sk", "wr_refunded_addr_sk",
             "wr_reason_sk", "wr_fee", "wr_refunded_cash"])
    wp = _t(session, data_dir, "web_page", ["wp_web_page_sk"])
    dd = _t(session, data_dir, "date_dim", ["d_date_sk", "d_year"]) \
        .where(col("d_year") == lit(2000)).select(col("d_date_sk"))
    cd1 = _t(session, data_dir, "customer_demographics",
             ["cd_demo_sk", "cd_marital_status", "cd_education_status"]) \
        .select(col("cd_demo_sk").alias("cd1_sk"),
                col("cd_marital_status").alias("cd1_ms"),
                col("cd_education_status").alias("cd1_es"))
    cd2 = _t(session, data_dir, "customer_demographics",
             ["cd_demo_sk", "cd_marital_status", "cd_education_status"]) \
        .select(col("cd_demo_sk").alias("cd2_sk"),
                col("cd_marital_status").alias("cd2_ms"),
                col("cd_education_status").alias("cd2_es"))
    ca = _t(session, data_dir, "customer_address",
            ["ca_address_sk", "ca_country", "ca_state"]) \
        .where(col("ca_country") == lit("United States"))
    re = _t(session, data_dir, "reason", ["r_reason_sk", "r_reason_desc"])
    demo = Or(Or(
        (col("cd1_ms") == lit("M")) & (col("cd1_es") == lit("Advanced Degree"))
        & (col("ws_sales_price") >= lit(100.0))
        & (col("ws_sales_price") <= lit(150.0)),
        (col("cd1_ms") == lit("S")) & (col("cd1_es") == lit("College"))
        & (col("ws_sales_price") >= lit(50.0))
        & (col("ws_sales_price") <= lit(100.0))),
        (col("cd1_ms") == lit("W")) & (col("cd1_es") == lit("2 yr Degree"))
        & (col("ws_sales_price") >= lit(150.0))
        & (col("ws_sales_price") <= lit(200.0)))
    addr = Or(Or(
        In(col("ca_state"), [lit(s) for s in ("IN", "OH", "NJ")])
        & (col("ws_net_profit") >= lit(100.0))
        & (col("ws_net_profit") <= lit(200.0)),
        In(col("ca_state"), [lit(s) for s in ("WI", "CT", "KY")])
        & (col("ws_net_profit") >= lit(150.0))
        & (col("ws_net_profit") <= lit(300.0))),
        In(col("ca_state"), [lit(s) for s in ("LA", "IA", "AR")])
        & (col("ws_net_profit") >= lit(50.0))
        & (col("ws_net_profit") <= lit(250.0)))
    base = ws.join(wr, on=[("ws_item_sk", "wr_item_sk"),
                           ("ws_order_number", "wr_order_number")]) \
        .join(wp, on=[("ws_web_page_sk", "wp_web_page_sk")], how="semi") \
        .join(dd, on=[("ws_sold_date_sk", "d_date_sk")]) \
        .join(cd1, on=[("wr_refunded_cdemo_sk", "cd1_sk")]) \
        .join(cd2, on=[("wr_returning_cdemo_sk", "cd2_sk")]) \
        .join(ca, on=[("wr_refunded_addr_sk", "ca_address_sk")]) \
        .where((col("cd1_ms") == col("cd2_ms"))
               & (col("cd1_es") == col("cd2_es")) & demo & addr) \
        .join(re, on=[("wr_reason_sk", "r_reason_sk")])
    return base.group_by("r_reason_desc").agg(
        Average(col("ws_quantity").cast(T.DoubleType())).alias("avg_qty"),
        Average(col("wr_refunded_cash")).alias("avg_cash"),
        Average(col("wr_fee")).alias("avg_fee")) \
        .with_column("reason", Substring(col("r_reason_desc"), lit(1),
                                         lit(20))) \
        .select(col("reason"), col("avg_qty"), col("avg_cash"),
                col("avg_fee")) \
        .order_by(("reason", True), ("avg_qty", True), ("avg_cash", True),
                  ("avg_fee", True)) \
        .limit(100)


def q86(session, data_dir: str):
    """TPC-DS q86: web net-paid ROLLUP(category, class) with rank."""
    from spark_rapids_tpu.expr.core import grouping_id
    from spark_rapids_tpu.expr.window import (Rank, WindowExpression,
                                              WindowSpec)
    dd = _t(session, data_dir, "date_dim",
            ["d_date_sk", "d_month_seq"]) \
        .where((col("d_month_seq") >= lit(1200))
               & (col("d_month_seq") <= lit(1211))) \
        .select(col("d_date_sk"))
    ws = _t(session, data_dir, "web_sales",
            ["ws_sold_date_sk", "ws_item_sk", "ws_net_paid"])
    it = _t(session, data_dir, "item",
            ["i_item_sk", "i_category", "i_class"])
    base = ws.join(dd, on=[("ws_sold_date_sk", "d_date_sk")]) \
        .join(it, on=[("ws_item_sk", "i_item_sk")]) \
        .rollup("i_category", "i_class") \
        .agg(Sum(col("ws_net_paid")).alias("total_sum"),
             grouping_id().alias("lochierarchy"))
    rank = WindowExpression(
        Rank(), WindowSpec(
            partition_by=(col("lochierarchy"), col("i_category")),
            order_by=((col("total_sum"), False),)))
    return base.select(col("total_sum"), col("i_category"), col("i_class"),
                       col("lochierarchy"),
                       rank.alias("rank_within_parent")) \
        .order_by(("lochierarchy", False), ("i_category", True),
                  ("rank_within_parent", True)) \
        .limit(100)


QUERIES4 = {"q5": q5, "q8": q8, "q9": q9, "q10": q10, "q28": q28,
            "q34": q34, "q35": q35, "q41": q41, "q44": q44, "q45": q45,
            "q46": q46, "q49": q49, "q54": q54, "q56": q56, "q58": q58,
            "q76": q76, "q83": q83, "q84": q84, "q85": q85, "q86": q86}

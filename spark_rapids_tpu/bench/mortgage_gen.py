"""Synthetic Fannie-Mae-shaped mortgage data generator.

Reference: the mortgage benchmark reads the public Fannie Mae
single-family loan CSVs — pipe-delimited, headerless, quarter derived
from the file name ``Performance_2003Q4.txt_0``
(MortgageSpark.scala ReadPerformanceCsv/ReadAcquisitionCsv +
GetQuarterFromCsvFileName).  This generator emits the same shapes
deterministically: one acquisition row per loan and a monthly
performance history per loan with a delinquency progression, so the
delinquency-window ETL has real transitions to find.

``sf`` = thousands of loans (sf=1 -> 1000 loans, ~24k performance
rows).
"""
from __future__ import annotations

import os

import numpy as np

__all__ = ["generate_mortgage", "SELLERS", "perf_schema", "acq_schema"]

SELLERS = [
    "WELLS FARGO BANK, N.A.", "JPMORGAN CHASE BANK, NATIONAL ASSOCIATION",
    "BANK OF AMERICA, N.A.", "CITIMORTGAGE, INC.", "QUICKEN LOANS INC.",
    "USAA FEDERAL SAVINGS BANK", "FLAGSTAR BANK, FSB", "OTHER",
    "PNC BANK, N.A.", "SUNTRUST MORTGAGE INC.", "AMTRUST BANK",
    "METLIFE BANK, NA", "GMAC MORTGAGE, LLC",
]

_QUARTERS = ["2003Q1", "2003Q2", "2003Q3", "2003Q4"]


def perf_schema():
    from spark_rapids_tpu import types as T
    f = T.StructField
    return T.Schema([
        f("loan_id", T.LongType()),
        f("monthly_reporting_period", T.StringType()),
        f("servicer", T.StringType()),
        f("interest_rate", T.DoubleType()),
        f("current_actual_upb", T.DoubleType()),
        f("loan_age", T.DoubleType()),
        f("remaining_months_to_legal_maturity", T.DoubleType()),
        f("adj_remaining_months_to_maturity", T.DoubleType()),
        f("maturity_date", T.StringType()),
        f("msa", T.DoubleType()),
        f("current_loan_delinquency_status", T.IntegerType()),
        f("mod_flag", T.StringType()),
        f("zero_balance_code", T.StringType()),
        f("zero_balance_effective_date", T.StringType()),
        f("last_paid_installment_date", T.StringType()),
        f("foreclosed_after", T.StringType()),
        f("disposition_date", T.StringType()),
        f("foreclosure_costs", T.DoubleType()),
        f("prop_preservation_and_repair_costs", T.DoubleType()),
        f("asset_recovery_costs", T.DoubleType()),
        f("misc_holding_expenses", T.DoubleType()),
        f("holding_taxes", T.DoubleType()),
        f("net_sale_proceeds", T.DoubleType()),
        f("credit_enhancement_proceeds", T.DoubleType()),
        f("repurchase_make_whole_proceeds", T.StringType()),
        f("other_foreclosure_proceeds", T.DoubleType()),
        f("non_interest_bearing_upb", T.DoubleType()),
        f("principal_forgiveness_upb", T.StringType()),
        f("repurchase_make_whole_proceeds_flag", T.StringType()),
        f("servicing_activity_indicator", T.StringType()),
    ])


def acq_schema():
    from spark_rapids_tpu import types as T
    f = T.StructField
    return T.Schema([
        f("loan_id", T.LongType()),
        f("orig_channel", T.StringType()),
        f("seller_name", T.StringType()),
        f("orig_interest_rate", T.DoubleType()),
        f("orig_upb", T.IntegerType()),
        f("orig_loan_term", T.IntegerType()),
        f("orig_date", T.StringType()),
        f("first_pay_date", T.StringType()),
        f("orig_ltv", T.DoubleType()),
        f("orig_cltv", T.DoubleType()),
        f("num_borrowers", T.DoubleType()),
        f("dti", T.DoubleType()),
        f("borrower_credit_score", T.DoubleType()),
        f("first_home_buyer", T.StringType()),
        f("loan_purpose", T.StringType()),
        f("property_type", T.StringType()),
        f("num_units", T.IntegerType()),
        f("occupancy_status", T.StringType()),
        f("property_state", T.StringType()),
        f("zip", T.IntegerType()),
        f("mortgage_insurance_percent", T.DoubleType()),
        f("product_type", T.StringType()),
        f("coborrow_credit_score", T.DoubleType()),
        f("mortgage_insurance_type", T.DoubleType()),
        f("relocation_mortgage_indicator", T.StringType()),
    ])


def _fmt(v) -> str:
    if v is None:
        return ""
    if isinstance(v, float):
        return f"{v:.3f}"
    return str(v)


def generate_mortgage(data_dir: str, sf: float = 1.0,
                      seed: int = 7) -> None:
    """Write perf/Performance_<Q>.txt_0 and acq/Acquisition_<Q>.txt_0."""
    if os.path.exists(os.path.join(data_dir, "_DONE")):
        return
    rng = np.random.default_rng(seed)
    n_loans = max(int(1000 * sf), 40)
    os.makedirs(os.path.join(data_dir, "perf"), exist_ok=True)
    os.makedirs(os.path.join(data_dir, "acq"), exist_ok=True)
    per_q = n_loans // len(_QUARTERS)
    loan_id = 100000
    for q in _QUARTERS:
        year = int(q[:4])
        qn = int(q[-1])
        with open(os.path.join(data_dir, "acq",
                               f"Acquisition_{q}.txt_0"), "w") as fa, \
             open(os.path.join(data_dir, "perf",
                               f"Performance_{q}.txt_0"), "w") as fp:
            for _ in range(per_q):
                loan_id += 1
                rate = round(float(rng.uniform(2.5, 8.5)), 3)
                upb = int(rng.integers(50, 800)) * 1000
                term = int(rng.choice([180, 240, 360]))
                orig_month = int(rng.integers(1, 4)) + (qn - 1) * 3
                acq = [loan_id, rng.choice(["R", "C", "B"]),
                       rng.choice(SELLERS), rate, upb, term,
                       f"{orig_month:02d}/{year}",
                       f"{(orig_month % 12) + 1:02d}/{year}",
                       round(float(rng.uniform(40, 97)), 1),
                       round(float(rng.uniform(40, 99)), 1),
                       float(rng.integers(1, 3)),
                       round(float(rng.uniform(10, 60)), 1),
                       float(rng.integers(550, 830)),
                       rng.choice(["Y", "N"]), rng.choice(["P", "C", "R"]),
                       rng.choice(["SF", "PU", "CO"]),
                       int(rng.integers(1, 5)), rng.choice(["P", "S", "I"]),
                       rng.choice(["CA", "TX", "NY", "FL", "WA", "CO"]),
                       int(rng.integers(10000, 99999)),
                       round(float(rng.uniform(0, 35)), 1), "FRM",
                       float(rng.integers(550, 830)) if rng.random() < .4
                       else None,
                       float(rng.integers(1, 3)) if rng.random() < .3
                       else None,
                       rng.choice(["Y", "N"])]
                fa.write("|".join(_fmt(v) for v in acq) + "\n")
                # monthly history: delinquency ratchets up for some loans
                months = int(rng.integers(6, 30))
                delinquent_from = months - int(rng.integers(1, 8)) \
                    if rng.random() < 0.25 else None
                upb_left = float(upb)
                for t in range(months):
                    m = (orig_month - 1 + t) % 12 + 1
                    y = year + (orig_month - 1 + t) // 12
                    status = 0
                    if delinquent_from is not None and t >= delinquent_from:
                        status = min(t - delinquent_from + 1, 9)
                    upb_left = max(upb_left - float(upb) / term, 0.0)
                    perf = [loan_id, f"{m:02d}/01/{y}",
                            rng.choice(["A", "B", ""]),
                            rate, round(upb_left, 2), float(t),
                            float(term - t), float(term - t),
                            f"{m:02d}/{y + term // 12}",
                            float(rng.integers(10000, 50000)),
                            status, rng.choice(["Y", "N"]), "", "",
                            "", "", "", None, None, None, None, None,
                            None, None, "", None, None, "", "", "Y"]
                    fp.write("|".join(_fmt(v) for v in perf) + "\n")
    with open(os.path.join(data_dir, "_DONE"), "w") as f:
        f.write("ok\n")

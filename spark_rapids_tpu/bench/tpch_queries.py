"""TPC-H queries as DataFrame code (the TpchLikeSpark.scala pattern).

All 22 queries, ported with the same house rules as the TPC-DS suite
(reference: integration_tests/.../tpch/TpchLikeSpark.scala:293-1140):
scalar subqueries fold eagerly, EXISTS/IN become semi/anti joins,
correlated aggregates become group-by + join.
"""
from __future__ import annotations

import datetime as _dt
import os

from spark_rapids_tpu import types as T
from spark_rapids_tpu.expr.aggregates import (Average, Count, CountDistinct,
                                              CountStar, Max, Min, Sum)
from spark_rapids_tpu.expr.conditional import CaseWhen, Coalesce, If
from spark_rapids_tpu.expr.core import col, lit
from spark_rapids_tpu.expr.datetime_ops import Year
from spark_rapids_tpu.expr.predicates import In, Or
from spark_rapids_tpu.expr.strings import Substring

__all__ = ["TPCH_QUERIES", "build_tpch_query"]


def _t(session, data_dir: str, table: str, columns=None):
    return session.read_parquet(os.path.join(data_dir, table),
                                columns=columns)


def _d(y, m, d):
    return lit(_dt.date(y, m, d))


def _disc_price():
    return col("l_extendedprice") * (lit(1.0) - col("l_discount"))


def q1(session, data_dir: str):
    """TPC-H q1: pricing summary report."""
    li = _t(session, data_dir, "lineitem",
            ["l_returnflag", "l_linestatus", "l_quantity",
             "l_extendedprice", "l_discount", "l_tax", "l_shipdate"])
    return li.where(col("l_shipdate") <= _d(1998, 9, 2)) \
        .group_by("l_returnflag", "l_linestatus") \
        .agg(Sum(col("l_quantity")).alias("sum_qty"),
             Sum(col("l_extendedprice")).alias("sum_base_price"),
             Sum(_disc_price()).alias("sum_disc_price"),
             Sum(_disc_price() * (lit(1.0) + col("l_tax")))
             .alias("sum_charge"),
             Average(col("l_quantity")).alias("avg_qty"),
             Average(col("l_extendedprice")).alias("avg_price"),
             Average(col("l_discount")).alias("avg_disc"),
             CountStar().alias("count_order")) \
        .order_by(("l_returnflag", True), ("l_linestatus", True))


def q2(session, data_dir: str):
    """TPC-H q2: minimum-cost European supplier per brass part."""
    pt = _t(session, data_dir, "part",
            ["p_partkey", "p_mfgr", "p_size", "p_type"]) \
        .where((col("p_size") == lit(15))
               & col("p_type").like("%BRASS"))
    sup = _t(session, data_dir, "supplier",
             ["s_suppkey", "s_acctbal", "s_name", "s_address", "s_phone",
              "s_comment", "s_nationkey"])
    ps = _t(session, data_dir, "partsupp",
            ["ps_partkey", "ps_suppkey", "ps_supplycost"])
    na = _t(session, data_dir, "nation",
            ["n_nationkey", "n_name", "n_regionkey"])
    re = _t(session, data_dir, "region",
            ["r_regionkey", "r_name"]) \
        .where(col("r_name") == lit("EUROPE")).select(col("r_regionkey"))
    europe = ps.join(sup, on=[("ps_suppkey", "s_suppkey")]) \
        .join(na, on=[("s_nationkey", "n_nationkey")]) \
        .join(re, on=[("n_regionkey", "r_regionkey")], how="semi")
    min_cost = europe.group_by("ps_partkey") \
        .agg(Min(col("ps_supplycost")).alias("min_cost")) \
        .select(col("ps_partkey").alias("mc_partkey"), col("min_cost"))
    return europe.join(min_cost, on=[("ps_partkey", "mc_partkey")]) \
        .where(col("ps_supplycost") == col("min_cost")) \
        .join(pt, on=[("ps_partkey", "p_partkey")]) \
        .select(col("s_acctbal"), col("s_name"), col("n_name"),
                col("ps_partkey").alias("p_partkey"), col("p_mfgr"),
                col("s_address"), col("s_phone"), col("s_comment")) \
        .order_by(("s_acctbal", False), ("n_name", True),
                  ("s_name", True), ("p_partkey", True)) \
        .limit(100)


def q3(session, data_dir: str):
    """TPC-H q3: unshipped orders revenue."""
    cu = _t(session, data_dir, "customer",
            ["c_custkey", "c_mktsegment"]) \
        .where(col("c_mktsegment") == lit("BUILDING")) \
        .select(col("c_custkey"))
    od = _t(session, data_dir, "orders",
            ["o_orderkey", "o_custkey", "o_orderdate", "o_shippriority"]) \
        .where(col("o_orderdate") < _d(1995, 3, 15))
    li = _t(session, data_dir, "lineitem",
            ["l_orderkey", "l_extendedprice", "l_discount", "l_shipdate"]) \
        .where(col("l_shipdate") > _d(1995, 3, 15))
    return li.join(od, on=[("l_orderkey", "o_orderkey")]) \
        .join(cu, on=[("o_custkey", "c_custkey")], how="semi") \
        .group_by("l_orderkey", "o_orderdate", "o_shippriority") \
        .agg(Sum(_disc_price()).alias("revenue")) \
        .select(col("l_orderkey"), col("revenue"), col("o_orderdate"),
                col("o_shippriority")) \
        .order_by(("revenue", False), ("o_orderdate", True)).limit(10)


def q4(session, data_dir: str):
    """TPC-H q4: order priority checking."""
    od = _t(session, data_dir, "orders",
            ["o_orderkey", "o_orderdate", "o_orderpriority"]) \
        .where((col("o_orderdate") >= _d(1993, 7, 1))
               & (col("o_orderdate") < _d(1993, 10, 1)))
    late = _t(session, data_dir, "lineitem",
              ["l_orderkey", "l_commitdate", "l_receiptdate"]) \
        .where(col("l_commitdate") < col("l_receiptdate")) \
        .select(col("l_orderkey"))
    return od.join(late, on=[("o_orderkey", "l_orderkey")], how="semi") \
        .group_by("o_orderpriority") \
        .agg(CountStar().alias("order_count")) \
        .order_by(("o_orderpriority", True))


def q5(session, data_dir: str):
    """TPC-H q5: local supplier volume in ASIA, 1994."""
    cu = _t(session, data_dir, "customer", ["c_custkey", "c_nationkey"])
    od = _t(session, data_dir, "orders",
            ["o_orderkey", "o_custkey", "o_orderdate"]) \
        .where((col("o_orderdate") >= _d(1994, 1, 1))
               & (col("o_orderdate") < _d(1995, 1, 1)))
    li = _t(session, data_dir, "lineitem",
            ["l_orderkey", "l_suppkey", "l_extendedprice", "l_discount"])
    sup = _t(session, data_dir, "supplier",
             ["s_suppkey", "s_nationkey"])
    na = _t(session, data_dir, "nation",
            ["n_nationkey", "n_name", "n_regionkey"])
    re = _t(session, data_dir, "region",
            ["r_regionkey", "r_name"]) \
        .where(col("r_name") == lit("ASIA")).select(col("r_regionkey"))
    return li.join(od, on=[("l_orderkey", "o_orderkey")]) \
        .join(cu, on=[("o_custkey", "c_custkey")]) \
        .join(sup, on=[("l_suppkey", "s_suppkey")]) \
        .where(col("c_nationkey") == col("s_nationkey")) \
        .join(na, on=[("s_nationkey", "n_nationkey")]) \
        .join(re, on=[("n_regionkey", "r_regionkey")], how="semi") \
        .group_by("n_name") \
        .agg(Sum(_disc_price()).alias("revenue")) \
        .order_by(("revenue", False))


def q6(session, data_dir: str):
    """TPC-H q6: forecasting revenue change."""
    li = _t(session, data_dir, "lineitem",
            ["l_extendedprice", "l_discount", "l_shipdate", "l_quantity"])
    return li.where((col("l_shipdate") >= _d(1994, 1, 1))
                    & (col("l_shipdate") < _d(1995, 1, 1))
                    & (col("l_discount") >= lit(0.05))
                    & (col("l_discount") <= lit(0.07))
                    & (col("l_quantity") < lit(24.0))) \
        .agg(Sum(col("l_extendedprice") * col("l_discount"))
             .alias("revenue"))


def q7(session, data_dir: str):
    """TPC-H q7: volume shipping FRANCE<->GERMANY."""
    sup = _t(session, data_dir, "supplier", ["s_suppkey", "s_nationkey"])
    li = _t(session, data_dir, "lineitem",
            ["l_orderkey", "l_suppkey", "l_shipdate", "l_extendedprice",
             "l_discount"]) \
        .where((col("l_shipdate") >= _d(1995, 1, 1))
               & (col("l_shipdate") <= _d(1996, 12, 31)))
    od = _t(session, data_dir, "orders", ["o_orderkey", "o_custkey"])
    cu = _t(session, data_dir, "customer", ["c_custkey", "c_nationkey"])
    n1 = _t(session, data_dir, "nation", ["n_nationkey", "n_name"]) \
        .where(In(col("n_name"), [lit("FRANCE"), lit("GERMANY")])) \
        .select(col("n_nationkey").alias("n1_key"),
                col("n_name").alias("supp_nation"))
    n2 = _t(session, data_dir, "nation", ["n_nationkey", "n_name"]) \
        .where(In(col("n_name"), [lit("FRANCE"), lit("GERMANY")])) \
        .select(col("n_nationkey").alias("n2_key"),
                col("n_name").alias("cust_nation"))
    j = li.join(sup, on=[("l_suppkey", "s_suppkey")]) \
        .join(od, on=[("l_orderkey", "o_orderkey")]) \
        .join(cu, on=[("o_custkey", "c_custkey")]) \
        .join(n1, on=[("s_nationkey", "n1_key")]) \
        .join(n2, on=[("c_nationkey", "n2_key")]) \
        .where(~(col("supp_nation") == col("cust_nation")))
    return j.with_column("l_year", Year(col("l_shipdate"))) \
        .group_by("supp_nation", "cust_nation", "l_year") \
        .agg(Sum(_disc_price()).alias("revenue")) \
        .order_by(("supp_nation", True), ("cust_nation", True),
                  ("l_year", True))


def q8(session, data_dir: str):
    """TPC-H q8: national market share of BRAZIL in AMERICA."""
    pt = _t(session, data_dir, "part", ["p_partkey", "p_type"]) \
        .where(col("p_type") == lit("ECONOMY ANODIZED STEEL")) \
        .select(col("p_partkey"))
    li = _t(session, data_dir, "lineitem",
            ["l_partkey", "l_suppkey", "l_orderkey", "l_extendedprice",
             "l_discount"])
    od = _t(session, data_dir, "orders",
            ["o_orderkey", "o_custkey", "o_orderdate"]) \
        .where((col("o_orderdate") >= _d(1995, 1, 1))
               & (col("o_orderdate") <= _d(1996, 12, 31)))
    cu = _t(session, data_dir, "customer", ["c_custkey", "c_nationkey"])
    sup = _t(session, data_dir, "supplier", ["s_suppkey", "s_nationkey"])
    n1 = _t(session, data_dir, "nation",
            ["n_nationkey", "n_regionkey"]) \
        .select(col("n_nationkey").alias("n1_key"),
                col("n_regionkey"))
    re = _t(session, data_dir, "region",
            ["r_regionkey", "r_name"]) \
        .where(col("r_name") == lit("AMERICA")).select(col("r_regionkey"))
    n2 = _t(session, data_dir, "nation", ["n_nationkey", "n_name"]) \
        .select(col("n_nationkey").alias("n2_key"),
                col("n_name").alias("nation"))
    j = li.join(pt, on=[("l_partkey", "p_partkey")], how="semi") \
        .join(od, on=[("l_orderkey", "o_orderkey")]) \
        .join(cu, on=[("o_custkey", "c_custkey")]) \
        .join(n1, on=[("c_nationkey", "n1_key")]) \
        .join(re, on=[("n_regionkey", "r_regionkey")], how="semi") \
        .join(sup, on=[("l_suppkey", "s_suppkey")]) \
        .join(n2, on=[("s_nationkey", "n2_key")]) \
        .with_column("o_year", Year(col("o_orderdate"))) \
        .with_column("volume", _disc_price())
    return j.group_by("o_year").agg(
        (Sum(If(col("nation") == lit("BRAZIL"), col("volume"),
                lit(0.0))) / Sum(col("volume"))).alias("mkt_share")) \
        .order_by(("o_year", True))


def q9(session, data_dir: str):
    """TPC-H q9: product-type profit by nation and year."""
    pt = _t(session, data_dir, "part", ["p_partkey", "p_name"]) \
        .where(col("p_name").contains("green")).select(col("p_partkey"))
    li = _t(session, data_dir, "lineitem",
            ["l_partkey", "l_suppkey", "l_orderkey", "l_quantity",
             "l_extendedprice", "l_discount"])
    ps = _t(session, data_dir, "partsupp",
            ["ps_partkey", "ps_suppkey", "ps_supplycost"])
    od = _t(session, data_dir, "orders", ["o_orderkey", "o_orderdate"])
    sup = _t(session, data_dir, "supplier", ["s_suppkey", "s_nationkey"])
    na = _t(session, data_dir, "nation", ["n_nationkey", "n_name"])
    j = li.join(pt, on=[("l_partkey", "p_partkey")], how="semi") \
        .join(ps, on=[("l_partkey", "ps_partkey"),
                      ("l_suppkey", "ps_suppkey")]) \
        .join(od, on=[("l_orderkey", "o_orderkey")]) \
        .join(sup, on=[("l_suppkey", "s_suppkey")]) \
        .join(na, on=[("s_nationkey", "n_nationkey")]) \
        .with_column("o_year", Year(col("o_orderdate"))) \
        .with_column("amount", _disc_price()
                     - col("ps_supplycost") * col("l_quantity"))
    return j.group_by("n_name", "o_year") \
        .agg(Sum(col("amount")).alias("sum_profit")) \
        .select(col("n_name").alias("nation"), col("o_year"),
                col("sum_profit")) \
        .order_by(("nation", True), ("o_year", False))


def q10(session, data_dir: str):
    """TPC-H q10: returned item reporting."""
    cu = _t(session, data_dir, "customer",
            ["c_custkey", "c_name", "c_acctbal", "c_address", "c_phone",
             "c_comment", "c_nationkey"])
    od = _t(session, data_dir, "orders",
            ["o_orderkey", "o_custkey", "o_orderdate"]) \
        .where((col("o_orderdate") >= _d(1993, 10, 1))
               & (col("o_orderdate") < _d(1994, 1, 1)))
    li = _t(session, data_dir, "lineitem",
            ["l_orderkey", "l_returnflag", "l_extendedprice",
             "l_discount"]) \
        .where(col("l_returnflag") == lit("R"))
    na = _t(session, data_dir, "nation", ["n_nationkey", "n_name"])
    return li.join(od, on=[("l_orderkey", "o_orderkey")]) \
        .join(cu, on=[("o_custkey", "c_custkey")]) \
        .join(na, on=[("c_nationkey", "n_nationkey")]) \
        .group_by("c_custkey", "c_name", "c_acctbal", "c_phone", "n_name",
                  "c_address", "c_comment") \
        .agg(Sum(_disc_price()).alias("revenue")) \
        .select(col("c_custkey"), col("c_name"), col("revenue"),
                col("c_acctbal"), col("n_name"), col("c_address"),
                col("c_phone"), col("c_comment")) \
        .order_by(("revenue", False)).limit(20)


def q11(session, data_dir: str):
    """TPC-H q11: important stock identification (GERMANY)."""
    sup = _t(session, data_dir, "supplier", ["s_suppkey", "s_nationkey"])
    na = _t(session, data_dir, "nation", ["n_nationkey", "n_name"]) \
        .where(col("n_name") == lit("GERMANY")) \
        .select(col("n_nationkey"))
    ps = _t(session, data_dir, "partsupp",
            ["ps_partkey", "ps_suppkey", "ps_supplycost", "ps_availqty"])
    german = ps.join(sup, on=[("ps_suppkey", "s_suppkey")]) \
        .join(na, on=[("s_nationkey", "n_nationkey")], how="semi")
    total_rows = german.agg(
        Sum(col("ps_supplycost") * col("ps_availqty")).alias("t")) \
        .collect()
    threshold = float(total_rows[0][0] or 0.0) * 0.0001
    return german.group_by("ps_partkey") \
        .agg(Sum(col("ps_supplycost") * col("ps_availqty"))
             .alias("value")) \
        .where(col("value") > lit(threshold)) \
        .order_by(("value", False))


def q12(session, data_dir: str):
    """TPC-H q12: shipping modes and order priority."""
    od = _t(session, data_dir, "orders",
            ["o_orderkey", "o_orderpriority"])
    li = _t(session, data_dir, "lineitem",
            ["l_orderkey", "l_shipmode", "l_commitdate", "l_receiptdate",
             "l_shipdate"]) \
        .where(In(col("l_shipmode"), [lit("MAIL"), lit("SHIP")])
               & (col("l_commitdate") < col("l_receiptdate"))
               & (col("l_shipdate") < col("l_commitdate"))
               & (col("l_receiptdate") >= _d(1994, 1, 1))
               & (col("l_receiptdate") < _d(1995, 1, 1)))
    high = In(col("o_orderpriority"), [lit("1-URGENT"), lit("2-HIGH")])
    return li.join(od, on=[("l_orderkey", "o_orderkey")]) \
        .group_by("l_shipmode") \
        .agg(Sum(If(high, lit(1), lit(0))).alias("high_line_count"),
             Sum(If(high, lit(0), lit(1))).alias("low_line_count")) \
        .order_by(("l_shipmode", True))


def q13(session, data_dir: str):
    """TPC-H q13: customer distribution by order count."""
    cu = _t(session, data_dir, "customer", ["c_custkey"])
    od = _t(session, data_dir, "orders",
            ["o_orderkey", "o_custkey", "o_comment"]) \
        .where(~col("o_comment").like("%special%requests%")) \
        .select(col("o_custkey"), col("o_orderkey"))
    counts = cu.join(od, on=[("c_custkey", "o_custkey")], how="left") \
        .group_by("c_custkey") \
        .agg(Count(col("o_orderkey")).alias("c_count"))
    return counts.group_by("c_count") \
        .agg(CountStar().alias("custdist")) \
        .order_by(("custdist", False), ("c_count", False))


def q14(session, data_dir: str):
    """TPC-H q14: promotion effect."""
    li = _t(session, data_dir, "lineitem",
            ["l_partkey", "l_shipdate", "l_extendedprice", "l_discount"]) \
        .where((col("l_shipdate") >= _d(1995, 9, 1))
               & (col("l_shipdate") < _d(1995, 10, 1)))
    pt = _t(session, data_dir, "part", ["p_partkey", "p_type"])
    j = li.join(pt, on=[("l_partkey", "p_partkey")])
    return j.agg(
        (lit(100.0)
         * Sum(If(col("p_type").like("PROMO%"), _disc_price(), lit(0.0)))
         / Sum(_disc_price())).alias("promo_revenue"))


def q15(session, data_dir: str):
    """TPC-H q15: top supplier by quarterly revenue."""
    li = _t(session, data_dir, "lineitem",
            ["l_suppkey", "l_shipdate", "l_extendedprice", "l_discount"]) \
        .where((col("l_shipdate") >= _d(1996, 1, 1))
               & (col("l_shipdate") < _d(1996, 4, 1)))
    revenue = li.group_by("l_suppkey") \
        .agg(Sum(_disc_price()).alias("total_revenue"))
    max_rows = revenue.agg(Max(col("total_revenue")).alias("m")).collect()
    max_rev = float(max_rows[0][0] or 0.0)
    sup = _t(session, data_dir, "supplier",
             ["s_suppkey", "s_name", "s_address", "s_phone"])
    return revenue.where(col("total_revenue") == lit(max_rev)) \
        .join(sup, on=[("l_suppkey", "s_suppkey")]) \
        .select(col("s_suppkey"), col("s_name"), col("s_address"),
                col("s_phone"), col("total_revenue")) \
        .order_by(("s_suppkey", True))


def q16(session, data_dir: str):
    """TPC-H q16: parts/supplier relationship (excl. complaints)."""
    pt = _t(session, data_dir, "part",
            ["p_partkey", "p_brand", "p_type", "p_size"]) \
        .where(~(col("p_brand") == lit("Brand#45"))
               & ~col("p_type").like("MEDIUM POLISHED%")
               & In(col("p_size"), [lit(v) for v in
                                    (49, 14, 23, 45, 19, 3, 36, 9)]))
    bad = _t(session, data_dir, "supplier",
             ["s_suppkey", "s_comment"]) \
        .where(col("s_comment").like("%Customer%Complaints%")) \
        .select(col("s_suppkey"))
    ps = _t(session, data_dir, "partsupp", ["ps_partkey", "ps_suppkey"])
    return ps.join(pt, on=[("ps_partkey", "p_partkey")]) \
        .join(bad, on=[("ps_suppkey", "s_suppkey")], how="anti") \
        .group_by("p_brand", "p_type", "p_size") \
        .agg(CountDistinct(col("ps_suppkey")).alias("supplier_cnt")) \
        .order_by(("supplier_cnt", False), ("p_brand", True),
                  ("p_type", True), ("p_size", True))


def q17(session, data_dir: str):
    """TPC-H q17: small-quantity-order revenue."""
    pt = _t(session, data_dir, "part",
            ["p_partkey", "p_brand", "p_container"]) \
        .where((col("p_brand") == lit("Brand#23"))
               & (col("p_container") == lit("MED BOX"))) \
        .select(col("p_partkey"))
    li = _t(session, data_dir, "lineitem",
            ["l_partkey", "l_quantity", "l_extendedprice"])
    avg_qty = li.group_by("l_partkey") \
        .agg((Average(col("l_quantity")) * lit(0.2)).alias("qty_thresh")) \
        .select(col("l_partkey").alias("aq_partkey"), col("qty_thresh"))
    return li.join(pt, on=[("l_partkey", "p_partkey")], how="semi") \
        .join(avg_qty, on=[("l_partkey", "aq_partkey")]) \
        .where(col("l_quantity") < col("qty_thresh")) \
        .agg((Sum(col("l_extendedprice")) / lit(7.0)).alias("avg_yearly"))


def q18(session, data_dir: str):
    """TPC-H q18: large-volume customers."""
    li = _t(session, data_dir, "lineitem", ["l_orderkey", "l_quantity"])
    big = li.group_by("l_orderkey") \
        .agg(Sum(col("l_quantity")).alias("q")) \
        .where(col("q") > lit(300.0)) \
        .select(col("l_orderkey").alias("big_orderkey"))
    od = _t(session, data_dir, "orders",
            ["o_orderkey", "o_custkey", "o_orderdate", "o_totalprice"])
    cu = _t(session, data_dir, "customer", ["c_custkey", "c_name"])
    return li.join(big, on=[("l_orderkey", "big_orderkey")], how="semi") \
        .join(od, on=[("l_orderkey", "o_orderkey")]) \
        .join(cu, on=[("o_custkey", "c_custkey")]) \
        .group_by("c_name", "c_custkey", "o_orderkey", "o_orderdate",
                  "o_totalprice") \
        .agg(Sum(col("l_quantity")).alias("sum_qty")) \
        .order_by(("o_totalprice", False), ("o_orderdate", True)) \
        .limit(100)


def q19(session, data_dir: str):
    """TPC-H q19: discounted revenue (three brand/container bands)."""
    li = _t(session, data_dir, "lineitem",
            ["l_partkey", "l_quantity", "l_extendedprice", "l_discount",
             "l_shipmode", "l_shipinstruct"]) \
        .where(In(col("l_shipmode"), [lit("AIR"), lit("AIR REG")])
               & (col("l_shipinstruct") == lit("DELIVER IN PERSON")))
    pt = _t(session, data_dir, "part",
            ["p_partkey", "p_brand", "p_container", "p_size"])
    j = li.join(pt, on=[("l_partkey", "p_partkey")])

    def band(brand, containers, qlo, slo, shi):
        return ((col("p_brand") == lit(brand))
                & In(col("p_container"), [lit(c) for c in containers])
                & (col("l_quantity") >= lit(float(qlo)))
                & (col("l_quantity") <= lit(float(qlo + 10)))
                & (col("p_size") >= lit(slo)) & (col("p_size") <= lit(shi)))

    cond = Or(Or(
        band("Brand#12", ("SM CASE", "SM BOX", "SM PACK", "SM PKG"),
             1, 1, 5),
        band("Brand#23", ("MED BAG", "MED BOX", "MED PKG", "MED PACK"),
             10, 1, 10)),
        band("Brand#34", ("LG CASE", "LG BOX", "LG PACK", "LG PKG"),
             20, 1, 15))
    return j.where(cond).agg(Sum(_disc_price()).alias("revenue"))


def q20(session, data_dir: str):
    """TPC-H q20: potential part promotion (CANADA forest parts)."""
    pt = _t(session, data_dir, "part", ["p_partkey", "p_name"]) \
        .where(col("p_name").like("forest%")).select(col("p_partkey"))
    li = _t(session, data_dir, "lineitem",
            ["l_partkey", "l_suppkey", "l_quantity", "l_shipdate"]) \
        .where((col("l_shipdate") >= _d(1994, 1, 1))
               & (col("l_shipdate") < _d(1995, 1, 1)))
    half_qty = li.group_by("l_partkey", "l_suppkey") \
        .agg((Sum(col("l_quantity")) * lit(0.5)).alias("half_qty")) \
        .select(col("l_partkey").alias("hq_partkey"),
                col("l_suppkey").alias("hq_suppkey"), col("half_qty"))
    ps = _t(session, data_dir, "partsupp",
            ["ps_partkey", "ps_suppkey", "ps_availqty"])
    good_supp = ps.join(pt, on=[("ps_partkey", "p_partkey")], how="semi") \
        .join(half_qty, on=[("ps_partkey", "hq_partkey"),
                            ("ps_suppkey", "hq_suppkey")]) \
        .where(col("ps_availqty").cast(T.DoubleType()) > col("half_qty")) \
        .select(col("ps_suppkey"))
    sup = _t(session, data_dir, "supplier",
             ["s_suppkey", "s_name", "s_address", "s_nationkey"])
    na = _t(session, data_dir, "nation", ["n_nationkey", "n_name"]) \
        .where(col("n_name") == lit("CANADA")).select(col("n_nationkey"))
    return sup.join(good_supp, on=[("s_suppkey", "ps_suppkey")],
                    how="semi") \
        .join(na, on=[("s_nationkey", "n_nationkey")], how="semi") \
        .select(col("s_name"), col("s_address")) \
        .order_by(("s_name", True))


def q21(session, data_dir: str):
    """TPC-H q21: suppliers who kept orders waiting."""
    li = _t(session, data_dir, "lineitem",
            ["l_orderkey", "l_suppkey", "l_receiptdate", "l_commitdate"])
    late = li.where(col("l_receiptdate") > col("l_commitdate"))
    multi = li.group_by("l_orderkey") \
        .agg(CountDistinct(col("l_suppkey")).alias("nsupp")) \
        .where(col("nsupp") >= lit(2)) \
        .select(col("l_orderkey").alias("multi_ok"))
    single_late = late.group_by("l_orderkey") \
        .agg(CountDistinct(col("l_suppkey")).alias("nlate")) \
        .where(col("nlate") == lit(1)) \
        .select(col("l_orderkey").alias("single_late_ok"))
    od = _t(session, data_dir, "orders",
            ["o_orderkey", "o_orderstatus"]) \
        .where(col("o_orderstatus") == lit("F")).select(col("o_orderkey"))
    sup = _t(session, data_dir, "supplier",
             ["s_suppkey", "s_name", "s_nationkey"])
    na = _t(session, data_dir, "nation", ["n_nationkey", "n_name"]) \
        .where(col("n_name") == lit("SAUDI ARABIA")) \
        .select(col("n_nationkey"))
    return late.join(od, on=[("l_orderkey", "o_orderkey")], how="semi") \
        .join(multi, on=[("l_orderkey", "multi_ok")], how="semi") \
        .join(single_late, on=[("l_orderkey", "single_late_ok")],
              how="semi") \
        .join(sup, on=[("l_suppkey", "s_suppkey")]) \
        .join(na, on=[("s_nationkey", "n_nationkey")], how="semi") \
        .group_by("s_name").agg(CountStar().alias("numwait")) \
        .order_by(("numwait", False), ("s_name", True)).limit(100)


def q22(session, data_dir: str):
    """TPC-H q22: global sales opportunity."""
    codes = ("13", "31", "23", "29", "30", "18", "17")
    cu = _t(session, data_dir, "customer",
            ["c_custkey", "c_phone", "c_acctbal"]) \
        .with_column("cntrycode", Substring(col("c_phone"), lit(1),
                                            lit(2))) \
        .where(In(col("cntrycode"), [lit(c) for c in codes]))
    avg_rows = cu.where(col("c_acctbal") > lit(0.0)) \
        .agg(Average(col("c_acctbal")).alias("a")).collect()
    avg_bal = float(avg_rows[0][0] or 0.0)
    od = _t(session, data_dir, "orders", ["o_custkey"]) \
        .select(col("o_custkey"))
    return cu.where(col("c_acctbal") > lit(avg_bal)) \
        .join(od, on=[("c_custkey", "o_custkey")], how="anti") \
        .group_by("cntrycode") \
        .agg(CountStar().alias("numcust"),
             Sum(col("c_acctbal")).alias("totacctbal")) \
        .order_by(("cntrycode", True))


TPCH_QUERIES = {f"q{i}": fn for i, fn in enumerate(
    (q1, q2, q3, q4, q5, q6, q7, q8, q9, q10, q11, q12, q13, q14, q15,
     q16, q17, q18, q19, q20, q21, q22), start=1)}


def build_tpch_query(name: str, session, data_dir: str):
    return TPCH_QUERIES[name](session, data_dir)

"""TPC-DS queries, full-suite tranche 3 (q1-q99 gap fill, part 2 of 3).

Inventory, sales+returns three-way joins, shipping-lag pivots, and the
exists/not-exists shipping queries.  Same house rules as
tpcds_queries2.py (reference: TpcdsLikeSpark.scala:1561-4700).
"""
from __future__ import annotations

import os

from spark_rapids_tpu.expr.aggregates import (Average, Count, CountDistinct,
                                              CountStar, Max, Min, Sum,
                                              stddev_samp)
from spark_rapids_tpu.expr.conditional import CaseWhen, Coalesce, If
from spark_rapids_tpu import types as T
from spark_rapids_tpu.expr.core import col, lit
from spark_rapids_tpu.expr.predicates import In, Or
from spark_rapids_tpu.expr.strings import Substring

__all__ = ["QUERIES3"]


def _t(session, data_dir: str, table: str, columns=None):
    return session.read_parquet(os.path.join(data_dir, table),
                                columns=columns)


def _date_sk(y: int, m: int, d: int) -> int:
    import datetime as _dt
    return 2415022 + (_dt.date(y, m, d) - _dt.date(1900, 1, 1)).days


# ---------------------------------------------------------------------------
# sales -> store_returns -> catalog re-purchase chains: q17 / q25 / q29
# ---------------------------------------------------------------------------

def _sales_returns_catalog(session, data_dir, d1_where, d2_where, d3_where,
                           aggs):
    """Shared q17/q25/q29 spine: store sale -> its return -> follow-up
    catalog purchase by the same customer for the same item."""
    ss = _t(session, data_dir, "store_sales",
            ["ss_sold_date_sk", "ss_item_sk", "ss_customer_sk",
             "ss_store_sk", "ss_ticket_number", "ss_quantity",
             "ss_net_profit"])
    sr = _t(session, data_dir, "store_returns",
            ["sr_returned_date_sk", "sr_item_sk", "sr_customer_sk",
             "sr_ticket_number", "sr_return_quantity", "sr_net_loss"])
    cs = _t(session, data_dir, "catalog_sales",
            ["cs_sold_date_sk", "cs_item_sk", "cs_bill_customer_sk",
             "cs_quantity", "cs_net_profit"])
    dd = _t(session, data_dir, "date_dim",
            ["d_date_sk", "d_moy", "d_year", "d_quarter_name"])
    d1 = d1_where(dd).select(col("d_date_sk").alias("d1_sk"))
    d2 = d2_where(dd).select(col("d_date_sk").alias("d2_sk"))
    d3 = d3_where(dd).select(col("d_date_sk").alias("d3_sk"))
    st = _t(session, data_dir, "store",
            ["s_store_sk", "s_store_id", "s_store_name", "s_state"])
    it = _t(session, data_dir, "item",
            ["i_item_sk", "i_item_id", "i_item_desc"])
    base = ss.join(d1, on=[("ss_sold_date_sk", "d1_sk")]) \
        .join(sr, on=[("ss_customer_sk", "sr_customer_sk"),
                      ("ss_item_sk", "sr_item_sk"),
                      ("ss_ticket_number", "sr_ticket_number")]) \
        .join(d2, on=[("sr_returned_date_sk", "d2_sk")]) \
        .join(cs, on=[("sr_customer_sk", "cs_bill_customer_sk"),
                      ("sr_item_sk", "cs_item_sk")]) \
        .join(d3, on=[("cs_sold_date_sk", "d3_sk")]) \
        .join(st, on=[("ss_store_sk", "s_store_sk")]) \
        .join(it, on=[("ss_item_sk", "i_item_sk")])
    return base


def q17(session, data_dir: str):
    """TPC-DS q17: quantity stats (count/avg/stddev) across the
    sale->return->catalog chain, 2001Q1-Q3."""
    qs = ["2001Q1", "2001Q2", "2001Q3"]
    base = _sales_returns_catalog(
        session, data_dir,
        lambda dd: dd.where(col("d_quarter_name") == lit("2001Q1")),
        lambda dd: dd.where(In(col("d_quarter_name"),
                               [lit(q) for q in qs])),
        lambda dd: dd.where(In(col("d_quarter_name"),
                               [lit(q) for q in qs])),
        None)
    return base.group_by("i_item_id", "i_item_desc", "s_state").agg(
        Count(col("ss_quantity")).alias("store_sales_quantitycount"),
        Average(col("ss_quantity")).alias("store_sales_quantityave"),
        stddev_samp(col("ss_quantity")).alias("store_sales_quantitystdev"),
        (stddev_samp(col("ss_quantity")) / Average(col("ss_quantity")))
        .alias("store_sales_quantitycov"),
        Count(col("sr_return_quantity")).alias("sr_quantitycount"),
        Average(col("sr_return_quantity")).alias("sr_quantityave"),
        stddev_samp(col("sr_return_quantity")).alias("sr_quantitystdev"),
        (stddev_samp(col("sr_return_quantity"))
         / Average(col("sr_return_quantity"))).alias("sr_quantitycov"),
        Count(col("cs_quantity")).alias("cs_quantitycount"),
        Average(col("cs_quantity")).alias("cs_quantityave"),
        (stddev_samp(col("cs_quantity")) / Average(col("cs_quantity")))
        .alias("cs_quantitystdev"),
        (stddev_samp(col("cs_quantity")) / Average(col("cs_quantity")))
        .alias("cs_quantitycov")) \
        .order_by(("i_item_id", True), ("i_item_desc", True),
                  ("s_state", True)) \
        .limit(100)


def q25(session, data_dir: str):
    """TPC-DS q25: profit/loss totals across the chain, Apr-Oct 2001."""
    base = _sales_returns_catalog(
        session, data_dir,
        lambda dd: dd.where((col("d_moy") == lit(4))
                            & (col("d_year") == lit(2001))),
        lambda dd: dd.where((col("d_moy") >= lit(4))
                            & (col("d_moy") <= lit(10))
                            & (col("d_year") == lit(2001))),
        lambda dd: dd.where((col("d_moy") >= lit(4))
                            & (col("d_moy") <= lit(10))
                            & (col("d_year") == lit(2001))),
        None)
    return base.group_by("i_item_id", "i_item_desc", "s_store_id",
                         "s_store_name").agg(
        Sum(col("ss_net_profit")).alias("store_sales_profit"),
        Sum(col("sr_net_loss")).alias("store_returns_loss"),
        Sum(col("cs_net_profit")).alias("catalog_sales_profit")) \
        .order_by(("i_item_id", True), ("i_item_desc", True),
                  ("s_store_id", True), ("s_store_name", True)) \
        .limit(100)


def q29(session, data_dir: str):
    """TPC-DS q29: quantity totals across the chain, Sep 1999 + 3yr."""
    base = _sales_returns_catalog(
        session, data_dir,
        lambda dd: dd.where((col("d_moy") == lit(9))
                            & (col("d_year") == lit(1999))),
        lambda dd: dd.where((col("d_moy") >= lit(9))
                            & (col("d_moy") <= lit(12))
                            & (col("d_year") == lit(1999))),
        lambda dd: dd.where(In(col("d_year"),
                               [lit(1999), lit(2000), lit(2001)])),
        None)
    return base.group_by("i_item_id", "i_item_desc", "s_store_id",
                         "s_store_name").agg(
        Sum(col("ss_quantity")).alias("store_sales_quantity"),
        Sum(col("sr_return_quantity")).alias("store_returns_quantity"),
        Sum(col("cs_quantity")).alias("catalog_sales_quantity")) \
        .order_by(("i_item_id", True), ("i_item_desc", True),
                  ("s_store_id", True), ("s_store_name", True)) \
        .limit(100)


# ---------------------------------------------------------------------------
# inventory: q21 / q22 / q37 / q82 / q39
# ---------------------------------------------------------------------------

def q21(session, data_dir: str):
    """TPC-DS q21: warehouse inventory before/after a pivot date."""
    pivot = _date_sk(2000, 3, 11)
    dd = _t(session, data_dir, "date_dim", ["d_date_sk"]) \
        .where((col("d_date_sk") >= lit(pivot - 30))
               & (col("d_date_sk") <= lit(pivot + 30)))
    it = _t(session, data_dir, "item",
            ["i_item_sk", "i_item_id", "i_current_price"]) \
        .where((col("i_current_price") >= lit(0.99))
               & (col("i_current_price") <= lit(1.49)))
    wh = _t(session, data_dir, "warehouse",
            ["w_warehouse_sk", "w_warehouse_name"])
    inv = _t(session, data_dir, "inventory")
    g = inv.join(dd, on=[("inv_date_sk", "d_date_sk")]) \
        .join(it, on=[("inv_item_sk", "i_item_sk")]) \
        .join(wh, on=[("inv_warehouse_sk", "w_warehouse_sk")]) \
        .group_by("w_warehouse_name", "i_item_id").agg(
            Sum(If(col("inv_date_sk") < lit(pivot),
                   col("inv_quantity_on_hand"), lit(0)))
            .alias("inv_before"),
            Sum(If(col("inv_date_sk") >= lit(pivot),
                   col("inv_quantity_on_hand"), lit(0)))
            .alias("inv_after"))
    ratio = If(col("inv_before") > lit(0),
               col("inv_after").cast(T.DoubleType()) / col("inv_before"),
               lit(None))
    return g.where((ratio >= lit(2.0 / 3.0)) & (ratio <= lit(3.0 / 2.0))) \
        .order_by(("w_warehouse_name", True), ("i_item_id", True)) \
        .limit(100)


def q22(session, data_dir: str):
    """TPC-DS q22: average quantity-on-hand ROLLUP over the item
    hierarchy."""
    dd = _t(session, data_dir, "date_dim",
            ["d_date_sk", "d_month_seq"]) \
        .where((col("d_month_seq") >= lit(1200))
               & (col("d_month_seq") <= lit(1211))) \
        .select(col("d_date_sk"))
    it = _t(session, data_dir, "item",
            ["i_item_sk", "i_product_name", "i_brand", "i_class",
             "i_category"])
    wh = _t(session, data_dir, "warehouse", ["w_warehouse_sk"])
    inv = _t(session, data_dir, "inventory")
    return inv.join(dd, on=[("inv_date_sk", "d_date_sk")]) \
        .join(it, on=[("inv_item_sk", "i_item_sk")]) \
        .join(wh, on=[("inv_warehouse_sk", "w_warehouse_sk")]) \
        .rollup("i_product_name", "i_brand", "i_class", "i_category") \
        .agg(Average(col("inv_quantity_on_hand")).alias("qoh")) \
        .order_by(("qoh", True), ("i_product_name", True),
                  ("i_brand", True), ("i_class", True),
                  ("i_category", True)) \
        .limit(100)


def _inventory_pricerange(session, data_dir, lo_price, hi_price, start,
                          manufact_ids, demand_tbl, demand_item):
    lo = _date_sk(*start)
    dd = _t(session, data_dir, "date_dim", ["d_date_sk"]) \
        .where((col("d_date_sk") >= lit(lo))
               & (col("d_date_sk") <= lit(lo + 60)))
    it = _t(session, data_dir, "item",
            ["i_item_sk", "i_item_id", "i_item_desc", "i_current_price",
             "i_manufact_id"]) \
        .where((col("i_current_price") >= lit(lo_price))
               & (col("i_current_price") <= lit(hi_price))
               & In(col("i_manufact_id"),
                    [lit(m) for m in manufact_ids]))
    inv = _t(session, data_dir, "inventory") \
        .where((col("inv_quantity_on_hand") >= lit(100))
               & (col("inv_quantity_on_hand") <= lit(500)))
    demand = _t(session, data_dir, demand_tbl, [demand_item]) \
        .select(col(demand_item).alias("dem_item_sk"))
    return it.join(inv, on=[("i_item_sk", "inv_item_sk")]) \
        .join(dd, on=[("inv_date_sk", "d_date_sk")]) \
        .join(demand, on=[("i_item_sk", "dem_item_sk")], how="semi") \
        .group_by("i_item_id", "i_item_desc", "i_current_price").agg() \
        .order_by(("i_item_id", True)).limit(100)


def q37(session, data_dir: str):
    """TPC-DS q37: catalog-demanded items in stock (price band)."""
    return _inventory_pricerange(session, data_dir, 68.0, 98.0,
                                 (2000, 2, 1), [677, 940, 694, 808],
                                 "catalog_sales", "cs_item_sk")


def q82(session, data_dir: str):
    """TPC-DS q82: store-demanded items in stock (price band)."""
    return _inventory_pricerange(session, data_dir, 62.0, 92.0,
                                 (2000, 5, 25), [129, 270, 821, 423],
                                 "store_sales", "ss_item_sk")


def _q39_inv(session, data_dir):
    dd = _t(session, data_dir, "date_dim",
            ["d_date_sk", "d_year", "d_moy"]) \
        .where(col("d_year") == lit(2001))
    it = _t(session, data_dir, "item", ["i_item_sk"])
    wh = _t(session, data_dir, "warehouse",
            ["w_warehouse_sk", "w_warehouse_name"])
    inv = _t(session, data_dir, "inventory")
    g = inv.join(dd, on=[("inv_date_sk", "d_date_sk")]) \
        .join(it, on=[("inv_item_sk", "i_item_sk")]) \
        .join(wh, on=[("inv_warehouse_sk", "w_warehouse_sk")]) \
        .group_by("w_warehouse_name", "w_warehouse_sk", "i_item_sk",
                  "d_moy") \
        .agg(stddev_samp(col("inv_quantity_on_hand")).alias("stdev"),
             Average(col("inv_quantity_on_hand")).alias("mean"))
    g = g.where(If(col("mean") == lit(0.0), lit(0.0),
                   col("stdev") / col("mean")) > lit(1.0))
    cov = If(col("mean") == lit(0.0), lit(None),
             col("stdev") / col("mean"))
    return g.select(col("w_warehouse_sk"), col("i_item_sk"), col("d_moy"),
                    col("mean"), cov.alias("cov"))


def q39(session, data_dir: str):
    """TPC-DS q39a: warehouse/item months with high inventory variance,
    month 1 self-joined to month 2."""
    inv = _q39_inv(session, data_dir)
    inv1 = inv.where(col("d_moy") == lit(1)) \
        .select(col("w_warehouse_sk").alias("w1"),
                col("i_item_sk").alias("i1"),
                col("d_moy").alias("moy1"),
                col("mean").alias("mean1"), col("cov").alias("cov1"))
    inv2 = inv.where(col("d_moy") == lit(2)) \
        .select(col("w_warehouse_sk").alias("w2"),
                col("i_item_sk").alias("i2"),
                col("d_moy").alias("moy2"),
                col("mean").alias("mean2"), col("cov").alias("cov2"))
    return inv1.join(inv2, on=[("i1", "i2"), ("w1", "w2")]) \
        .select(col("w1"), col("i1"), col("moy1"), col("mean1"),
                col("cov1"), col("w2"), col("i2"), col("moy2"),
                col("mean2"), col("cov2")) \
        .order_by(("w1", True), ("i1", True), ("moy1", True),
                  ("mean1", True), ("cov1", True), ("moy2", True),
                  ("mean2", True), ("cov2", True))


# ---------------------------------------------------------------------------
# q40: catalog sales +/- returns around a pivot date
# ---------------------------------------------------------------------------

def q40(session, data_dir: str):
    """TPC-DS q40: catalog sales net of refunds, before/after pivot."""
    pivot = _date_sk(2000, 3, 11)
    dd = _t(session, data_dir, "date_dim", ["d_date_sk"]) \
        .where((col("d_date_sk") >= lit(pivot - 30))
               & (col("d_date_sk") <= lit(pivot + 30)))
    cs = _t(session, data_dir, "catalog_sales",
            ["cs_sold_date_sk", "cs_item_sk", "cs_order_number",
             "cs_warehouse_sk", "cs_sales_price"])
    cr = _t(session, data_dir, "catalog_returns",
            ["cr_order_number", "cr_item_sk", "cr_refunded_cash"])
    it = _t(session, data_dir, "item",
            ["i_item_sk", "i_item_id", "i_current_price"]) \
        .where((col("i_current_price") >= lit(0.99))
               & (col("i_current_price") <= lit(1.49)))
    wh = _t(session, data_dir, "warehouse",
            ["w_warehouse_sk", "w_state"])
    net = col("cs_sales_price") - Coalesce(col("cr_refunded_cash"),
                                           lit(0.0))
    return cs.join(cr, on=[("cs_order_number", "cr_order_number"),
                           ("cs_item_sk", "cr_item_sk")], how="left") \
        .join(wh, on=[("cs_warehouse_sk", "w_warehouse_sk")]) \
        .join(it, on=[("cs_item_sk", "i_item_sk")]) \
        .join(dd, on=[("cs_sold_date_sk", "d_date_sk")]) \
        .group_by("w_state", "i_item_id").agg(
            Sum(If(col("cs_sold_date_sk") < lit(pivot), net, lit(0.0)))
            .alias("sales_before"),
            Sum(If(col("cs_sold_date_sk") >= lit(pivot), net, lit(0.0)))
            .alias("sales_after")) \
        .order_by(("w_state", True), ("i_item_id", True)).limit(100)


# ---------------------------------------------------------------------------
# shipping-lag pivots: q62 / q99 / q50
# ---------------------------------------------------------------------------

def _lag_buckets(ship_col, sold_col):
    lag = col(ship_col) - col(sold_col)
    return [
        Sum(If(lag <= lit(30), lit(1), lit(0))).alias("d30"),
        Sum(If((lag > lit(30)) & (lag <= lit(60)), lit(1), lit(0)))
        .alias("d60"),
        Sum(If((lag > lit(60)) & (lag <= lit(90)), lit(1), lit(0)))
        .alias("d90"),
        Sum(If((lag > lit(90)) & (lag <= lit(120)), lit(1), lit(0)))
        .alias("d120"),
        Sum(If(lag > lit(120), lit(1), lit(0))).alias("dmore"),
    ]


def q62(session, data_dir: str):
    """TPC-DS q62: web shipping-lag buckets by warehouse/mode/site."""
    dd = _t(session, data_dir, "date_dim",
            ["d_date_sk", "d_month_seq"]) \
        .where((col("d_month_seq") >= lit(1200))
               & (col("d_month_seq") <= lit(1211))) \
        .select(col("d_date_sk"))
    ws = _t(session, data_dir, "web_sales",
            ["ws_ship_date_sk", "ws_sold_date_sk", "ws_warehouse_sk",
             "ws_ship_mode_sk", "ws_web_site_sk"])
    wh = _t(session, data_dir, "warehouse",
            ["w_warehouse_sk", "w_warehouse_name"])
    sm = _t(session, data_dir, "ship_mode", ["sm_ship_mode_sk", "sm_type"])
    web = _t(session, data_dir, "web_site", ["web_site_sk", "web_name"])
    return ws.join(dd, on=[("ws_ship_date_sk", "d_date_sk")]) \
        .join(wh, on=[("ws_warehouse_sk", "w_warehouse_sk")]) \
        .join(sm, on=[("ws_ship_mode_sk", "sm_ship_mode_sk")]) \
        .join(web, on=[("ws_web_site_sk", "web_site_sk")]) \
        .with_column("wname", Substring(col("w_warehouse_name"),
                                        lit(1), lit(20))) \
        .group_by("wname", "sm_type", "web_name") \
        .agg(*_lag_buckets("ws_ship_date_sk", "ws_sold_date_sk")) \
        .order_by(("wname", True), ("sm_type", True), ("web_name", True)) \
        .limit(100)


def q99(session, data_dir: str):
    """TPC-DS q99: catalog shipping-lag buckets by warehouse/mode/call
    center."""
    dd = _t(session, data_dir, "date_dim",
            ["d_date_sk", "d_month_seq"]) \
        .where((col("d_month_seq") >= lit(1200))
               & (col("d_month_seq") <= lit(1211))) \
        .select(col("d_date_sk"))
    cs = _t(session, data_dir, "catalog_sales",
            ["cs_ship_date_sk", "cs_sold_date_sk", "cs_warehouse_sk",
             "cs_ship_mode_sk", "cs_call_center_sk"])
    wh = _t(session, data_dir, "warehouse",
            ["w_warehouse_sk", "w_warehouse_name"])
    sm = _t(session, data_dir, "ship_mode", ["sm_ship_mode_sk", "sm_type"])
    cc = _t(session, data_dir, "call_center",
            ["cc_call_center_sk", "cc_name"])
    return cs.join(dd, on=[("cs_ship_date_sk", "d_date_sk")]) \
        .join(wh, on=[("cs_warehouse_sk", "w_warehouse_sk")]) \
        .join(sm, on=[("cs_ship_mode_sk", "sm_ship_mode_sk")]) \
        .join(cc, on=[("cs_call_center_sk", "cc_call_center_sk")]) \
        .with_column("wname", Substring(col("w_warehouse_name"),
                                        lit(1), lit(20))) \
        .group_by("wname", "sm_type", "cc_name") \
        .agg(*_lag_buckets("cs_ship_date_sk", "cs_sold_date_sk")) \
        .order_by(("wname", True), ("sm_type", True), ("cc_name", True)) \
        .limit(100)


def q50(session, data_dir: str):
    """TPC-DS q50: return-lag buckets per store, returns in Aug 2001."""
    ss = _t(session, data_dir, "store_sales",
            ["ss_sold_date_sk", "ss_item_sk", "ss_customer_sk",
             "ss_ticket_number", "ss_store_sk"])
    sr = _t(session, data_dir, "store_returns",
            ["sr_returned_date_sk", "sr_item_sk", "sr_customer_sk",
             "sr_ticket_number"])
    d2 = _t(session, data_dir, "date_dim",
            ["d_date_sk", "d_year", "d_moy"]) \
        .where((col("d_year") == lit(2001)) & (col("d_moy") == lit(8))) \
        .select(col("d_date_sk"))
    st = _t(session, data_dir, "store",
            ["s_store_sk", "s_store_name", "s_company_id",
             "s_street_number", "s_street_name", "s_street_type",
             "s_suite_number", "s_city", "s_county", "s_state", "s_zip"])
    keys = ["s_store_name", "s_company_id", "s_street_number",
            "s_street_name", "s_street_type", "s_suite_number", "s_city",
            "s_county", "s_state", "s_zip"]
    return ss.join(sr, on=[("ss_ticket_number", "sr_ticket_number"),
                           ("ss_item_sk", "sr_item_sk"),
                           ("ss_customer_sk", "sr_customer_sk")]) \
        .join(d2, on=[("sr_returned_date_sk", "d_date_sk")]) \
        .join(st, on=[("ss_store_sk", "s_store_sk")]) \
        .group_by(*keys) \
        .agg(*_lag_buckets("sr_returned_date_sk", "ss_sold_date_sk")) \
        .order_by(*[(k, True) for k in keys]).limit(100)


# ---------------------------------------------------------------------------
# exists / not-exists shipping: q16 / q94 / q95
# ---------------------------------------------------------------------------

def _multi_warehouse_orders(sales, order_col, wh_col):
    """Orders shipped from more than one warehouse (the EXISTS
    same-order-different-warehouse subquery)."""
    return sales.group_by(order_col) \
        .agg(CountDistinct(col(wh_col)).alias("wh_cnt")) \
        .where(col("wh_cnt") >= lit(2)) \
        .select(col(order_col).alias("mw_order"))


def q16(session, data_dir: str):
    """TPC-DS q16: catalog orders shipped from multiple warehouses with
    no returns, GA, 60-day window."""
    lo = _date_sk(2002, 2, 1)
    dd = _t(session, data_dir, "date_dim", ["d_date_sk"]) \
        .where((col("d_date_sk") >= lit(lo))
               & (col("d_date_sk") <= lit(lo + 60)))
    cs = _t(session, data_dir, "catalog_sales",
            ["cs_ship_date_sk", "cs_ship_addr_sk", "cs_call_center_sk",
             "cs_order_number", "cs_warehouse_sk", "cs_ext_ship_cost",
             "cs_net_profit"])
    ca = _t(session, data_dir, "customer_address",
            ["ca_address_sk", "ca_state"]) \
        .where(col("ca_state") == lit("GA")).select(col("ca_address_sk"))
    cc = _t(session, data_dir, "call_center",
            ["cc_call_center_sk", "cc_county"]) \
        .where(col("cc_county") == lit("Williamson County")) \
        .select(col("cc_call_center_sk"))
    mw = _multi_warehouse_orders(
        _t(session, data_dir, "catalog_sales",
           ["cs_order_number", "cs_warehouse_sk"]),
        "cs_order_number", "cs_warehouse_sk")
    cr = _t(session, data_dir, "catalog_returns", ["cr_order_number"]) \
        .select(col("cr_order_number"))
    return cs.join(dd, on=[("cs_ship_date_sk", "d_date_sk")]) \
        .join(ca, on=[("cs_ship_addr_sk", "ca_address_sk")]) \
        .join(cc, on=[("cs_call_center_sk", "cc_call_center_sk")]) \
        .join(mw, on=[("cs_order_number", "mw_order")], how="semi") \
        .join(cr, on=[("cs_order_number", "cr_order_number")],
              how="anti") \
        .agg(CountDistinct(col("cs_order_number")).alias("order_count"),
             Sum(col("cs_ext_ship_cost")).alias("total_shipping_cost"),
             Sum(col("cs_net_profit")).alias("total_net_profit"))


def _web_ship_report(session, data_dir, returns_semi: bool):
    """q94 (anti returns) / q95 (semi returned multi-warehouse)."""
    lo = _date_sk(1999, 2, 1)
    dd = _t(session, data_dir, "date_dim", ["d_date_sk"]) \
        .where((col("d_date_sk") >= lit(lo))
               & (col("d_date_sk") <= lit(lo + 60)))
    ws = _t(session, data_dir, "web_sales",
            ["ws_ship_date_sk", "ws_ship_addr_sk", "ws_web_site_sk",
             "ws_order_number", "ws_warehouse_sk", "ws_ext_ship_cost",
             "ws_net_profit"])
    ca = _t(session, data_dir, "customer_address",
            ["ca_address_sk", "ca_state"]) \
        .where(col("ca_state") == lit("IL")).select(col("ca_address_sk"))
    web = _t(session, data_dir, "web_site",
             ["web_site_sk", "web_company_name"]) \
        .where(col("web_company_name") == lit("pri")) \
        .select(col("web_site_sk"))
    mw = _multi_warehouse_orders(
        _t(session, data_dir, "web_sales",
           ["ws_order_number", "ws_warehouse_sk"]),
        "ws_order_number", "ws_warehouse_sk")
    wr = _t(session, data_dir, "web_returns", ["wr_order_number"]) \
        .select(col("wr_order_number"))
    base = ws.join(dd, on=[("ws_ship_date_sk", "d_date_sk")]) \
        .join(ca, on=[("ws_ship_addr_sk", "ca_address_sk")]) \
        .join(web, on=[("ws_web_site_sk", "web_site_sk")]) \
        .join(mw, on=[("ws_order_number", "mw_order")], how="semi")
    if returns_semi:
        # q95: order must ALSO appear among returned multi-warehouse
        # orders
        returned_mw = wr.join(mw, on=[("wr_order_number", "mw_order")],
                              how="semi")
        base = base.join(returned_mw,
                         on=[("ws_order_number", "wr_order_number")],
                         how="semi")
    else:
        base = base.join(wr, on=[("ws_order_number", "wr_order_number")],
                         how="anti")
    return base.agg(
        CountDistinct(col("ws_order_number")).alias("order_count"),
        Sum(col("ws_ext_ship_cost")).alias("total_shipping_cost"),
        Sum(col("ws_net_profit")).alias("total_net_profit"))


def q94(session, data_dir: str):
    """TPC-DS q94: multi-warehouse web orders with no returns."""
    return _web_ship_report(session, data_dir, returns_semi=False)


def q95(session, data_dir: str):
    """TPC-DS q95: multi-warehouse web orders that were returned."""
    return _web_ship_report(session, data_dir, returns_semi=True)


# ---------------------------------------------------------------------------
# q90 / q91 / q93
# ---------------------------------------------------------------------------

def q90(session, data_dir: str):
    """TPC-DS q90: web AM/PM sales-count ratio."""
    def count_hours(alias, h_lo, h_hi):
        ws = _t(session, data_dir, "web_sales",
                ["ws_sold_time_sk", "ws_ship_hdemo_sk", "ws_web_page_sk"])
        hd = _t(session, data_dir, "household_demographics",
                ["hd_demo_sk", "hd_dep_count"]) \
            .where(col("hd_dep_count") == lit(6)).select(col("hd_demo_sk"))
        td = _t(session, data_dir, "time_dim", ["t_time_sk", "t_hour"]) \
            .where((col("t_hour") >= lit(h_lo))
                   & (col("t_hour") <= lit(h_hi))) \
            .select(col("t_time_sk"))
        wp = _t(session, data_dir, "web_page",
                ["wp_web_page_sk", "wp_char_count"]) \
            .where((col("wp_char_count") >= lit(5000))
                   & (col("wp_char_count") <= lit(5200))) \
            .select(col("wp_web_page_sk"))
        return ws.join(hd, on=[("ws_ship_hdemo_sk", "hd_demo_sk")]) \
            .join(td, on=[("ws_sold_time_sk", "t_time_sk")]) \
            .join(wp, on=[("ws_web_page_sk", "wp_web_page_sk")]) \
            .agg(CountStar().alias(alias))

    am = count_hours("amc", 8, 9)
    pm = count_hours("pmc", 19, 20)
    return am.join(pm, how="cross") \
        .select((col("amc").cast(T.DoubleType())
                 / col("pmc").cast(T.DoubleType())).alias("am_pm_ratio")) \
        .order_by(("am_pm_ratio", True)).limit(100)


def q91(session, data_dir: str):
    """TPC-DS q91: call-center losses from returns by demographic."""
    cc = _t(session, data_dir, "call_center",
            ["cc_call_center_sk", "cc_call_center_id", "cc_name",
             "cc_manager"])
    cr = _t(session, data_dir, "catalog_returns",
            ["cr_call_center_sk", "cr_returned_date_sk",
             "cr_returning_customer_sk", "cr_net_loss"])
    dd = _t(session, data_dir, "date_dim",
            ["d_date_sk", "d_year", "d_moy"]) \
        .where((col("d_year") == lit(1998)) & (col("d_moy") == lit(11))) \
        .select(col("d_date_sk"))
    cu = _t(session, data_dir, "customer",
            ["c_customer_sk", "c_current_cdemo_sk", "c_current_hdemo_sk",
             "c_current_addr_sk"])
    ca = _t(session, data_dir, "customer_address",
            ["ca_address_sk", "ca_gmt_offset"]) \
        .where(col("ca_gmt_offset") == lit(-7.0)) \
        .select(col("ca_address_sk"))
    cd = _t(session, data_dir, "customer_demographics",
            ["cd_demo_sk", "cd_marital_status", "cd_education_status"]) \
        .where(Or((col("cd_marital_status") == lit("M"))
                  & (col("cd_education_status") == lit("Unknown")),
                  (col("cd_marital_status") == lit("W"))
                  & (col("cd_education_status")
                     == lit("Advanced Degree"))))
    hd = _t(session, data_dir, "household_demographics",
            ["hd_demo_sk", "hd_buy_potential"]) \
        .where(col("hd_buy_potential").like("Unknown%")) \
        .select(col("hd_demo_sk"))
    return cr.join(cc, on=[("cr_call_center_sk", "cc_call_center_sk")]) \
        .join(dd, on=[("cr_returned_date_sk", "d_date_sk")]) \
        .join(cu, on=[("cr_returning_customer_sk", "c_customer_sk")]) \
        .join(cd, on=[("c_current_cdemo_sk", "cd_demo_sk")]) \
        .join(hd, on=[("c_current_hdemo_sk", "hd_demo_sk")]) \
        .join(ca, on=[("c_current_addr_sk", "ca_address_sk")]) \
        .group_by("cc_call_center_id", "cc_name", "cc_manager",
                  "cd_marital_status", "cd_education_status") \
        .agg(Sum(col("cr_net_loss")).alias("returns_loss")) \
        .select(col("cc_call_center_id").alias("call_center"),
                col("cc_name").alias("call_center_name"),
                col("cc_manager").alias("manager"),
                col("returns_loss")) \
        .order_by(("returns_loss", False))


def q93(session, data_dir: str):
    """TPC-DS q93: actual sales after 'reason 28' returns."""
    ss = _t(session, data_dir, "store_sales",
            ["ss_item_sk", "ss_ticket_number", "ss_customer_sk",
             "ss_quantity", "ss_sales_price"])
    sr = _t(session, data_dir, "store_returns",
            ["sr_item_sk", "sr_ticket_number", "sr_reason_sk",
             "sr_return_quantity"])
    re = _t(session, data_dir, "reason",
            ["r_reason_sk", "r_reason_desc"]) \
        .where(col("r_reason_desc") == lit("reason 28")) \
        .select(col("r_reason_sk"))
    act = If(col("sr_return_quantity").is_not_null(),
             (col("ss_quantity") - col("sr_return_quantity"))
             * col("ss_sales_price"),
             col("ss_quantity") * col("ss_sales_price"))
    return ss.join(sr, on=[("ss_item_sk", "sr_item_sk"),
                           ("ss_ticket_number", "sr_ticket_number")],
                   how="left") \
        .join(re, on=[("sr_reason_sk", "r_reason_sk")], how="semi") \
        .group_by("ss_customer_sk") \
        .agg(Sum(act).alias("sumsales")) \
        .order_by(("sumsales", True), ("ss_customer_sk", True)).limit(100)


# ---------------------------------------------------------------------------
# q18: catalog demographics rollup
# ---------------------------------------------------------------------------

def q18(session, data_dir: str):
    """TPC-DS q18: catalog averages ROLLUP(item, country, state,
    county)."""
    cs = _t(session, data_dir, "catalog_sales",
            ["cs_sold_date_sk", "cs_item_sk", "cs_bill_cdemo_sk",
             "cs_bill_customer_sk", "cs_quantity", "cs_list_price",
             "cs_coupon_amt", "cs_sales_price", "cs_net_profit"])
    cd1 = _t(session, data_dir, "customer_demographics",
             ["cd_demo_sk", "cd_gender", "cd_education_status",
              "cd_dep_count"]) \
        .where((col("cd_gender") == lit("F"))
               & (col("cd_education_status") == lit("Unknown"))) \
        .select(col("cd_demo_sk"), col("cd_dep_count"))
    cd2 = _t(session, data_dir, "customer_demographics",
             ["cd_demo_sk"]) \
        .select(col("cd_demo_sk").alias("cd2_demo_sk"))
    cu = _t(session, data_dir, "customer",
            ["c_customer_sk", "c_current_cdemo_sk", "c_current_addr_sk",
             "c_birth_month", "c_birth_year"]) \
        .where(In(col("c_birth_month"),
                  [lit(m) for m in (1, 6, 8, 9, 12, 2)]))
    ca = _t(session, data_dir, "customer_address",
            ["ca_address_sk", "ca_country", "ca_state", "ca_county"]) \
        .where(In(col("ca_state"),
                  [lit(s) for s in ("MS", "IN", "ND", "OK", "NM", "VA")]))
    dd = _t(session, data_dir, "date_dim", ["d_date_sk", "d_year"]) \
        .where(col("d_year") == lit(1998)).select(col("d_date_sk"))
    it = _t(session, data_dir, "item", ["i_item_sk", "i_item_id"])
    base = cs.join(dd, on=[("cs_sold_date_sk", "d_date_sk")]) \
        .join(it, on=[("cs_item_sk", "i_item_sk")]) \
        .join(cd1, on=[("cs_bill_cdemo_sk", "cd_demo_sk")]) \
        .join(cu, on=[("cs_bill_customer_sk", "c_customer_sk")]) \
        .join(cd2, on=[("c_current_cdemo_sk", "cd2_demo_sk")]) \
        .join(ca, on=[("c_current_addr_sk", "ca_address_sk")])
    return base.rollup("i_item_id", "ca_country", "ca_state", "ca_county") \
        .agg(Average(col("cs_quantity").cast(T.DoubleType())).alias("agg1"),
             Average(col("cs_list_price")).alias("agg2"),
             Average(col("cs_coupon_amt")).alias("agg3"),
             Average(col("cs_sales_price")).alias("agg4"),
             Average(col("cs_net_profit")).alias("agg5"),
             Average(col("c_birth_year").cast(T.DoubleType())).alias("agg6"),
             Average(col("cd_dep_count").cast(T.DoubleType())).alias("agg7")) \
        .order_by(("ca_country", True), ("ca_state", True),
                  ("ca_county", True), ("i_item_id", True)) \
        .limit(100)


QUERIES3 = {"q16": q16, "q17": q17, "q18": q18, "q21": q21, "q22": q22,
            "q25": q25, "q29": q29, "q37": q37, "q39": q39, "q40": q40,
            "q50": q50, "q62": q62, "q82": q82, "q90": q90, "q91": q91,
            "q93": q93, "q94": q94, "q95": q95, "q99": q99}

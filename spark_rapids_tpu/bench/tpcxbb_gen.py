"""TPCx-BB ("big bench") data: the TPC-DS tables plus the three
clickstream/review/marketprice tables the BigBench queries add.

Reference: TpcxbbLikeSpark.scala reads the BigBench data model —
the retail tables shared with TPC-DS plus ``web_clickstreams``
(views + purchases), ``product_reviews`` (rating + text), and
``item_marketprices`` (competitor price windows).  The generator
reuses :func:`generate_tpcds` for the shared tables and emits the
three extras with the correlations the queries probe (clicks follow
items/users, purchases mark wcs_sales_sk non-null, review ratings
cluster per item, marketprice windows bracket real sold dates).
"""
from __future__ import annotations

import os

import numpy as np

from spark_rapids_tpu.bench.tpcds_gen import (_DATE_SK_EPOCH,
                                              _write_parquet,
                                              generate_tpcds,
                                              table_row_counts)

__all__ = ["generate_tpcxbb", "tpcxbb_row_counts"]

_WORDS = ("great product fast shipping works as described would buy again "
          "poor quality broke after a week disappointed returned it "
          "average okay for the price decent value excellent service "
          "terrible support never again love it highly recommend").split()


def tpcxbb_row_counts(sf: float) -> dict[str, int]:
    base = table_row_counts(sf)
    return {
        "web_clickstreams": int(base["store_sales"] * 2.5),
        "product_reviews": max(int(base["item"] * 1.5), 100),
        "item_marketprices": max(int(base["item"] * 0.6), 60),
    }


_SCHEMA_VERSION = 1


def generate_tpcxbb(data_dir: str, sf: float = 0.01,
                    seed: int = 42, rows_per_file: int = 250_000) -> None:
    generate_tpcds(data_dir, sf=sf, seed=seed,
                   rows_per_file=rows_per_file)
    # the marker encodes (schema version, sf, seed) and stale extras
    # dirs are removed before regeneration — same discipline as
    # generate_tpcds (a bare marker kept SF1 clickstreams alive under
    # an SF0.01 regeneration: silently inconsistent joins)
    import shutil
    done = os.path.join(data_dir, "_TPCXBB_DONE")
    stamp = f"v{_SCHEMA_VERSION} sf={sf:g} seed={seed}"
    if os.path.exists(done) and open(done).read().strip() == stamp:
        return
    for t in ("web_clickstreams", "product_reviews",
              "item_marketprices"):
        shutil.rmtree(os.path.join(data_dir, t), ignore_errors=True)
    rng = np.random.default_rng(seed + 7)
    base = table_row_counts(sf)
    counts = tpcxbb_row_counts(sf)
    n_item = base["item"]
    n_cust = base["customer"]

    # -- web_clickstreams: views + purchases over real users/items ------
    n = counts["web_clickstreams"]
    user = rng.integers(1, n_cust + 1, n).astype(np.int32)
    # ~8% anonymous sessions (null user)
    user_obj = user.astype(object)
    user_obj[rng.random(n) < 0.08] = None
    sales = np.empty(n, dtype=object)
    is_buy = rng.random(n) < 0.1          # 10% of clicks are purchases
    sales[:] = None
    sales[is_buy] = rng.integers(1, n // 10 + 2,
                                 int(is_buy.sum())).astype(np.int32)
    _write_parquet(os.path.join(data_dir, "web_clickstreams"), {
        "wcs_click_date_sk": (rng.integers(36890, 37620, n)
                              + _DATE_SK_EPOCH).astype(np.int32),
        "wcs_click_time_sk": rng.integers(0, 86400, n).astype(np.int32),
        "wcs_item_sk": rng.integers(1, n_item + 1, n).astype(np.int32),
        "wcs_user_sk": user_obj,
        "wcs_sales_sk": sales,
    }, rows_per_file)

    # -- product_reviews: per-item rating clusters + text ---------------
    n = counts["product_reviews"]
    item = rng.integers(1, n_item + 1, n).astype(np.int32)
    item_bias = (item % 5).astype(np.float64)  # per-item rating level
    rating = np.clip(np.round(1 + item_bias + rng.normal(0, 1, n)),
                     1, 5).astype(np.int32)
    content = np.array(
        [" ".join(rng.choice(_WORDS, size=rng.integers(5, 15)))
         for _ in range(n)], dtype=object)
    item_obj = item.astype(object)
    item_obj[rng.random(n) < 0.02] = None   # a few unattributed reviews
    _write_parquet(os.path.join(data_dir, "product_reviews"), {
        "pr_review_sk": np.arange(1, n + 1, dtype=np.int64),
        "pr_review_date": (rng.integers(36890, 37620, n)
                           + _DATE_SK_EPOCH).astype(np.int64),
        "pr_review_rating": rating,
        "pr_item_sk": item_obj,
        "pr_user_sk": rng.integers(1, n_cust + 1, n).astype(np.int32),
        "pr_review_content": content,
    }, rows_per_file)

    # -- item_marketprices: competitor price windows --------------------
    n = counts["item_marketprices"]
    imp_item = rng.integers(1, n_item + 1, n).astype(np.int32)
    # plant windows for the Q24 anchor item (item 100 exists at every
    # scale factor; the reference anchors on 10000, which only exists
    # at SF >= ~0.1 — documented deviation in tpcxbb_queries.q24)
    if n > 10:
        imp_item[:3] = min(100, n_item)
    start = (rng.integers(36890, 37500, n)
             + _DATE_SK_EPOCH).astype(np.int32)
    _write_parquet(os.path.join(data_dir, "item_marketprices"), {
        "imp_sk": np.arange(1, n + 1, dtype=np.int64),
        "imp_item_sk": imp_item,
        "imp_competitor": np.array(
            [f"comp_{i % 7}" for i in range(n)], dtype=object),
        "imp_competitor_price": np.round(
            rng.uniform(0.5, 120.0, n), 2),
        "imp_start_date": start,
        "imp_end_date": (start + rng.integers(10, 90, n)).astype(np.int32),
    }, rows_per_file)
    with open(done, "w") as f:
        f.write(stamp + "\n")

"""TPC-DS queries, full-suite tranche 2 (q1-q99 gap fill, part 1 of 3).

DataFrame ports of the TPC-DS query definitions the reference ships as
SQL text (integration_tests/.../tpcds/TpcdsLikeSpark.scala:720-4700).
House rules (same as tpcds_queries.py):
  - scalar subqueries are evaluated eagerly and folded as literals (the
    plan shape Spark produces after subquery execution);
  - EXISTS / IN-subquery become semi joins, NOT EXISTS becomes anti;
  - correlated aggregate subqueries become group-by + join (Spark's
    RewriteCorrelatedScalarSubquery does the same);
  - SQL UNION (distinct) is union() + distinct(); UNION ALL is union().
"""
from __future__ import annotations

import os

from spark_rapids_tpu.expr.aggregates import (Average, Count, CountDistinct,
                                              CountStar, Max, Min, Sum,
                                              stddev_samp)
from spark_rapids_tpu.expr.arithmetic import Abs
from spark_rapids_tpu.expr.conditional import CaseWhen, Coalesce, If
from spark_rapids_tpu import types as T
from spark_rapids_tpu.expr.core import col, lit
from spark_rapids_tpu.expr.math_ops import Round
from spark_rapids_tpu.expr.predicates import In, Or
from spark_rapids_tpu.expr.strings import Concat, Substring, Upper

__all__ = ["QUERIES2"]


def _t(session, data_dir: str, table: str, columns=None):
    return session.read_parquet(os.path.join(data_dir, table),
                                columns=columns)


def _date_sk(y: int, m: int, d: int) -> int:
    import datetime as _dt
    return 2415022 + (_dt.date(y, m, d) - _dt.date(1900, 1, 1)).days


# ---------------------------------------------------------------------------
# customer-total-return family: q1 / q30 / q81
# ---------------------------------------------------------------------------

def _total_return_outliers(session, data_dir, ctr, key_col, state_filter):
    """Shared q1/q30/q81 spine: rows whose total return exceeds 1.2x the
    per-group average (correlated subquery -> group-by + join)."""
    avg_by_grp = ctr.group_by("ctr_grp") \
        .agg((Average(col("ctr_total_return")) * lit(1.2)).alias("ctr_avg")) \
        .select(col("ctr_grp").alias("avg_grp"), col("ctr_avg"))
    return ctr.join(avg_by_grp, on=[("ctr_grp", "avg_grp")]) \
        .where(col("ctr_total_return") > col("ctr_avg"))


def q1(session, data_dir: str):
    """TPC-DS q1: customers returning > 1.2x the store average, TN
    stores, year 2000."""
    dd = _t(session, data_dir, "date_dim", ["d_date_sk", "d_year"]) \
        .where(col("d_year") == lit(2000)).select(col("d_date_sk"))
    sr = _t(session, data_dir, "store_returns",
            ["sr_returned_date_sk", "sr_customer_sk", "sr_store_sk",
             "sr_return_amt"])
    ctr = sr.join(dd, on=[("sr_returned_date_sk", "d_date_sk")]) \
        .group_by("sr_customer_sk", "sr_store_sk") \
        .agg(Sum(col("sr_return_amt")).alias("ctr_total_return")) \
        .select(col("sr_customer_sk").alias("ctr_customer_sk"),
                col("sr_store_sk").alias("ctr_grp"),
                col("ctr_total_return"))
    out = _total_return_outliers(session, data_dir, ctr, "ctr_grp", None)
    st = _t(session, data_dir, "store", ["s_store_sk", "s_state"]) \
        .where(col("s_state") == lit("TN")).select(col("s_store_sk"))
    cu = _t(session, data_dir, "customer", ["c_customer_sk", "c_customer_id"])
    return out.join(st, on=[("ctr_grp", "s_store_sk")]) \
        .join(cu, on=[("ctr_customer_sk", "c_customer_sk")]) \
        .select(col("c_customer_id")) \
        .order_by(("c_customer_id", True)).limit(100)


def q30(session, data_dir: str):
    """TPC-DS q30: web-return outliers by state, GA customers."""
    dd = _t(session, data_dir, "date_dim", ["d_date_sk", "d_year"]) \
        .where(col("d_year") == lit(2002)).select(col("d_date_sk"))
    ca = _t(session, data_dir, "customer_address",
            ["ca_address_sk", "ca_state"])
    wr = _t(session, data_dir, "web_returns",
            ["wr_returned_date_sk", "wr_returning_customer_sk",
             "wr_returning_addr_sk", "wr_return_amt"])
    ctr = wr.join(dd, on=[("wr_returned_date_sk", "d_date_sk")]) \
        .join(ca, on=[("wr_returning_addr_sk", "ca_address_sk")]) \
        .group_by("wr_returning_customer_sk", "ca_state") \
        .agg(Sum(col("wr_return_amt")).alias("ctr_total_return")) \
        .select(col("wr_returning_customer_sk").alias("ctr_customer_sk"),
                col("ca_state").alias("ctr_grp"), col("ctr_total_return"))
    out = _total_return_outliers(session, data_dir, ctr, "ctr_grp", None)
    cu = _t(session, data_dir, "customer",
            ["c_customer_sk", "c_customer_id", "c_salutation",
             "c_first_name", "c_last_name", "c_preferred_cust_flag",
             "c_birth_day", "c_birth_month", "c_birth_year",
             "c_birth_country", "c_login", "c_email_address",
             "c_last_review_date", "c_current_addr_sk"])
    ca2 = _t(session, data_dir, "customer_address",
             ["ca_address_sk", "ca_state"]) \
        .where(col("ca_state") == lit("GA")) \
        .select(col("ca_address_sk").alias("ca2_address_sk"))
    cols = [col(c) for c in
            ("c_customer_id", "c_salutation", "c_first_name", "c_last_name",
             "c_preferred_cust_flag", "c_birth_day", "c_birth_month",
             "c_birth_year", "c_birth_country", "c_login",
             "c_email_address", "c_last_review_date")]
    return out.join(cu, on=[("ctr_customer_sk", "c_customer_sk")]) \
        .join(ca2, on=[("c_current_addr_sk", "ca2_address_sk")]) \
        .select(*cols, col("ctr_total_return")) \
        .order_by(*[(c.name, True) for c in cols],
                  ("ctr_total_return", True)) \
        .limit(100)


def q81(session, data_dir: str):
    """TPC-DS q81: catalog-return outliers by state, GA customers, with
    full address."""
    dd = _t(session, data_dir, "date_dim", ["d_date_sk", "d_year"]) \
        .where(col("d_year") == lit(2000)).select(col("d_date_sk"))
    ca = _t(session, data_dir, "customer_address",
            ["ca_address_sk", "ca_state"])
    cr = _t(session, data_dir, "catalog_returns",
            ["cr_returned_date_sk", "cr_returning_customer_sk",
             "cr_returning_addr_sk", "cr_return_amt_inc_tax"])
    ctr = cr.join(dd, on=[("cr_returned_date_sk", "d_date_sk")]) \
        .join(ca, on=[("cr_returning_addr_sk", "ca_address_sk")]) \
        .group_by("cr_returning_customer_sk", "ca_state") \
        .agg(Sum(col("cr_return_amt_inc_tax")).alias("ctr_total_return")) \
        .select(col("cr_returning_customer_sk").alias("ctr_customer_sk"),
                col("ca_state").alias("ctr_grp"), col("ctr_total_return"))
    out = _total_return_outliers(session, data_dir, ctr, "ctr_grp", None)
    cu = _t(session, data_dir, "customer",
            ["c_customer_sk", "c_customer_id", "c_salutation",
             "c_first_name", "c_last_name", "c_current_addr_sk"])
    ca2 = _t(session, data_dir, "customer_address")
    ca2 = ca2.where(col("ca_state") == lit("GA")).select(
        col("ca_address_sk").alias("ca2_address_sk"),
        col("ca_street_number"), col("ca_street_name"),
        col("ca_street_type"), col("ca_suite_number"), col("ca_city"),
        col("ca_county"), col("ca_state"), col("ca_zip"), col("ca_country"),
        col("ca_gmt_offset"), col("ca_location_type"))
    names = ["c_customer_id", "c_salutation", "c_first_name", "c_last_name",
             "ca_street_number", "ca_street_name", "ca_street_type",
             "ca_suite_number", "ca_city", "ca_county", "ca_state",
             "ca_zip", "ca_country", "ca_gmt_offset", "ca_location_type"]
    return out.join(cu, on=[("ctr_customer_sk", "c_customer_sk")]) \
        .join(ca2, on=[("c_current_addr_sk", "ca2_address_sk")]) \
        .select(*[col(n) for n in names], col("ctr_total_return")) \
        .order_by(*[(n, True) for n in names], ("ctr_total_return", True)) \
        .limit(100)


# ---------------------------------------------------------------------------
# year-over-year customer families: q4 / q11 / q74
# ---------------------------------------------------------------------------

def _year_total(session, data_dir, sales, cust_col, date_col, total_expr,
                year, tag, extra_cols=()):
    """One year_total branch: per-customer yearly total for one channel,
    pinned to one year (the reference builds one CTE and filters it six
    ways; filter pushdown yields exactly these per-year branches)."""
    cu_cols = ["c_customer_sk", "c_customer_id", "c_first_name",
               "c_last_name"] + list(extra_cols)
    cu = _t(session, data_dir, "customer", cu_cols)
    dd = _t(session, data_dir, "date_dim", ["d_date_sk", "d_year"]) \
        .where(col("d_year") == lit(year)).select(col("d_date_sk"))
    keys = ["c_customer_id", "c_first_name", "c_last_name"] + \
        list(extra_cols)
    g = sales.join(dd, on=[(date_col, "d_date_sk")]) \
        .join(cu, on=[(cust_col, "c_customer_sk")]) \
        .group_by(*keys) \
        .agg(total_expr.alias("year_total"))
    ren = [col(k).alias(f"{tag}_{k}") for k in keys] + \
        [col("year_total").alias(f"{tag}_total")]
    return g.select(*ren)


def _yoy_query(session, data_dir, channels, year, select_flag):
    """Shared spine of q4 (3 channels) / q11 (2 channels, flag col) /
    q74 (2 channels, net_paid): first/second-year totals per channel,
    joined on customer id; growth-ratio comparisons filter the rows."""
    frames = {}
    for tag, (sales_fn, cust_col, date_col, total_fn) in channels.items():
        for yr, suffix in ((year, "1"), (year + 1, "2")):
            extra = ("c_preferred_cust_flag",) if (
                select_flag and tag == "s" and suffix == "2") else ()
            frames[tag + suffix] = _year_total(
                session, data_dir, sales_fn(), cust_col, date_col,
                total_fn(), yr, tag + suffix, extra_cols=extra)
    first_tags = [t + "1" for t in channels]
    base = frames["s1"].where(col("s1_total") > lit(0.0))
    for t in channels:
        if t == "s":
            continue
        base = base.join(
            frames[t + "1"].where(col(f"{t}1_total") > lit(0.0)),
            on=[("s1_c_customer_id", f"{t}1_c_customer_id")])
    for t in channels:
        base = base.join(frames[t + "2"],
                         on=[("s1_c_customer_id", f"{t}2_c_customer_id")])
    other = [t for t in channels if t != "s"]
    cond = None
    for t in other:
        c = (col(f"{t}2_total") / col(f"{t}1_total")) > \
            (col("s2_total") / col("s1_total"))
        cond = c if cond is None else cond & c
    out_cols = [col("s2_c_customer_id").alias("customer_id"),
                col("s2_c_first_name").alias("customer_first_name"),
                col("s2_c_last_name").alias("customer_last_name")]
    if select_flag:
        out_cols.append(col("s2_c_preferred_cust_flag")
                        .alias("customer_preferred_cust_flag"))
    res = base.where(cond).select(*out_cols)
    orders = [(c.name, True) for c in out_cols]
    return res.order_by(*orders).limit(100)


def q4(session, data_dir: str):
    """TPC-DS q4: customers growing faster in catalog than store AND web
    (three-channel year-over-year)."""
    def ss():
        return _t(session, data_dir, "store_sales",
                  ["ss_sold_date_sk", "ss_customer_sk", "ss_ext_list_price",
                   "ss_ext_wholesale_cost", "ss_ext_discount_amt",
                   "ss_ext_sales_price"])

    def cs():
        return _t(session, data_dir, "catalog_sales",
                  ["cs_sold_date_sk", "cs_bill_customer_sk",
                   "cs_ext_list_price", "cs_ext_wholesale_cost",
                   "cs_ext_discount_amt", "cs_ext_sales_price"])

    def ws():
        return _t(session, data_dir, "web_sales",
                  ["ws_sold_date_sk", "ws_bill_customer_sk",
                   "ws_ext_list_price", "ws_ext_wholesale_cost",
                   "ws_ext_discount_amt", "ws_ext_sales_price"])

    def tot(p):
        return lambda: Sum(((col(f"{p}_ext_list_price")
                             - col(f"{p}_ext_wholesale_cost")
                             - col(f"{p}_ext_discount_amt"))
                            + col(f"{p}_ext_sales_price")) / lit(2.0))

    channels = {
        "s": (ss, "ss_customer_sk", "ss_sold_date_sk", tot("ss")),
        "c": (cs, "cs_bill_customer_sk", "cs_sold_date_sk", tot("cs")),
        "w": (ws, "ws_bill_customer_sk", "ws_sold_date_sk", tot("ws")),
    }
    # q4 compares c-growth > s-growth and c-growth > w-growth
    frames = {}
    for tag, (sales_fn, cust_col, date_col, total_fn) in channels.items():
        for yr, suffix in ((2001, "1"), (2002, "2")):
            extra = ("c_preferred_cust_flag",) if (
                tag == "s" and suffix == "2") else ()
            frames[tag + suffix] = _year_total(
                session, data_dir, sales_fn(), cust_col, date_col,
                total_fn(), yr, tag + suffix, extra_cols=extra)
    base = frames["s1"].where(col("s1_total") > lit(0.0)) \
        .join(frames["c1"].where(col("c1_total") > lit(0.0)),
              on=[("s1_c_customer_id", "c1_c_customer_id")]) \
        .join(frames["w1"].where(col("w1_total") > lit(0.0)),
              on=[("s1_c_customer_id", "w1_c_customer_id")]) \
        .join(frames["s2"], on=[("s1_c_customer_id", "s2_c_customer_id")]) \
        .join(frames["c2"], on=[("s1_c_customer_id", "c2_c_customer_id")]) \
        .join(frames["w2"], on=[("s1_c_customer_id", "w2_c_customer_id")])
    cond = ((col("c2_total") / col("c1_total"))
            > (col("s2_total") / col("s1_total"))) \
        & ((col("c2_total") / col("c1_total"))
           > (col("w2_total") / col("w1_total")))
    out_cols = [col("s2_c_customer_id").alias("customer_id"),
                col("s2_c_first_name").alias("customer_first_name"),
                col("s2_c_last_name").alias("customer_last_name"),
                col("s2_c_preferred_cust_flag")
                .alias("customer_preferred_cust_flag")]
    return base.where(cond).select(*out_cols) \
        .order_by(*[(c.name, True) for c in out_cols]).limit(100)


def q11(session, data_dir: str):
    """TPC-DS q11: customers whose web growth beats store growth."""
    def ss():
        return _t(session, data_dir, "store_sales",
                  ["ss_sold_date_sk", "ss_customer_sk",
                   "ss_ext_list_price", "ss_ext_discount_amt"])

    def ws():
        return _t(session, data_dir, "web_sales",
                  ["ws_sold_date_sk", "ws_bill_customer_sk",
                   "ws_ext_list_price", "ws_ext_discount_amt"])

    channels = {
        "s": (ss, "ss_customer_sk", "ss_sold_date_sk",
              lambda: Sum(col("ss_ext_list_price")
                          - col("ss_ext_discount_amt"))),
        "w": (ws, "ws_bill_customer_sk", "ws_sold_date_sk",
              lambda: Sum(col("ws_ext_list_price")
                          - col("ws_ext_discount_amt"))),
    }
    return _yoy_query(session, data_dir, channels, 2001, select_flag=True)


def q74(session, data_dir: str):
    """TPC-DS q74: net-paid year-over-year, web growth beats store."""
    def ss():
        return _t(session, data_dir, "store_sales",
                  ["ss_sold_date_sk", "ss_customer_sk", "ss_net_paid"])

    def ws():
        return _t(session, data_dir, "web_sales",
                  ["ws_sold_date_sk", "ws_bill_customer_sk", "ws_net_paid"])

    channels = {
        "s": (ss, "ss_customer_sk", "ss_sold_date_sk",
              lambda: Sum(col("ss_net_paid"))),
        "w": (ws, "ws_bill_customer_sk", "ws_sold_date_sk",
              lambda: Sum(col("ws_net_paid"))),
    }
    return _yoy_query(session, data_dir, channels, 2001, select_flag=False)


# ---------------------------------------------------------------------------
# weekly pivots: q2 / q59
# ---------------------------------------------------------------------------

def _dow_pivot(joined, price_col):
    """sum(case d_day_name = X then price end) for the seven days."""
    def day(n):
        return Sum(CaseWhen([(col("d_day_name") == lit(n),
                              col(price_col))], lit(None)))
    return [day("Sunday").alias("sun_sales"), day("Monday").alias("mon_sales"),
            day("Tuesday").alias("tue_sales"),
            day("Wednesday").alias("wed_sales"),
            day("Thursday").alias("thu_sales"),
            day("Friday").alias("fri_sales"),
            day("Saturday").alias("sat_sales")]


def q2(session, data_dir: str):
    """TPC-DS q2: week-over-year day-of-week sales ratios (web+catalog)."""
    ws = _t(session, data_dir, "web_sales",
            ["ws_sold_date_sk", "ws_ext_sales_price"]) \
        .select(col("ws_sold_date_sk").alias("sold_date_sk"),
                col("ws_ext_sales_price").alias("sales_price"))
    cs = _t(session, data_dir, "catalog_sales",
            ["cs_sold_date_sk", "cs_ext_sales_price"]) \
        .select(col("cs_sold_date_sk").alias("sold_date_sk"),
                col("cs_ext_sales_price").alias("sales_price"))
    wscs = ws.union(cs)
    dd = _t(session, data_dir, "date_dim",
            ["d_date_sk", "d_week_seq", "d_day_name"])
    wswscs = wscs.join(dd, on=[("sold_date_sk", "d_date_sk")]) \
        .group_by("d_week_seq").agg(*_dow_pivot(None, "sales_price"))
    dy = _t(session, data_dir, "date_dim", ["d_week_seq", "d_year"])
    names = ["sun", "mon", "tue", "wed", "thu", "fri", "sat"]
    y = wswscs.join(dy.where(col("d_year") == lit(2001))
                    .select(col("d_week_seq").alias("y_week")),
                    on=[("d_week_seq", "y_week")]) \
        .select(col("d_week_seq").alias("d_week_seq1"),
                *[col(f"{n}_sales").alias(f"{n}_sales1") for n in names])
    z = wswscs.join(dy.where(col("d_year") == lit(2002))
                    .select(col("d_week_seq").alias("z_week")),
                    on=[("d_week_seq", "z_week")]) \
        .select((col("d_week_seq") - lit(53)).cast(T.IntegerType())
                .alias("d_week_seq2m"),
                *[col(f"{n}_sales").alias(f"{n}_sales2") for n in names])
    return y.join(z, on=[("d_week_seq1", "d_week_seq2m")]) \
        .select(col("d_week_seq1"),
                *[Round(col(f"{n}_sales1") / col(f"{n}_sales2"), 2)
                  .alias(f"r_{n}") for n in names]) \
        .order_by(("d_week_seq1", True))


def q59(session, data_dir: str):
    """TPC-DS q59: store week-over-year day-of-week ratios."""
    ss = _t(session, data_dir, "store_sales",
            ["ss_sold_date_sk", "ss_store_sk", "ss_sales_price"])
    dd = _t(session, data_dir, "date_dim",
            ["d_date_sk", "d_week_seq", "d_day_name"])
    wss = ss.join(dd, on=[("ss_sold_date_sk", "d_date_sk")]) \
        .group_by("d_week_seq", "ss_store_sk") \
        .agg(*_dow_pivot(None, "ss_sales_price"))
    st = _t(session, data_dir, "store",
            ["s_store_sk", "s_store_id", "s_store_name"])
    dm = _t(session, data_dir, "date_dim", ["d_week_seq", "d_month_seq"])
    names = ["sun", "mon", "tue", "wed", "thu", "fri", "sat"]
    y = wss.join(st, on=[("ss_store_sk", "s_store_sk")]) \
        .join(dm.where((col("d_month_seq") >= lit(1212))
                       & (col("d_month_seq") <= lit(1223)))
              .select(col("d_week_seq").alias("y_week")),
              on=[("d_week_seq", "y_week")]) \
        .select(col("s_store_name").alias("s_store_name1"),
                col("d_week_seq").alias("d_week_seq1"),
                col("s_store_id").alias("s_store_id1"),
                *[col(f"{n}_sales").alias(f"{n}_sales1") for n in names])
    x = wss.join(st, on=[("ss_store_sk", "s_store_sk")]) \
        .join(dm.where((col("d_month_seq") >= lit(1224))
                       & (col("d_month_seq") <= lit(1235)))
              .select(col("d_week_seq").alias("x_week")),
              on=[("d_week_seq", "x_week")]) \
        .select(col("s_store_id").alias("s_store_id2"),
                (col("d_week_seq") - lit(52)).cast(T.IntegerType())
                .alias("d_week_seq2m"),
                *[col(f"{n}_sales").alias(f"{n}_sales2") for n in names])
    return y.join(x, on=[("s_store_id1", "s_store_id2"),
                         ("d_week_seq1", "d_week_seq2m")]) \
        .select(col("s_store_name1"), col("s_store_id1"),
                col("d_week_seq1"),
                *[(col(f"{n}_sales1") / col(f"{n}_sales2"))
                  .alias(f"r_{n}") for n in names]) \
        .order_by(("s_store_name1", True), ("s_store_id1", True),
                  ("d_week_seq1", True)) \
        .limit(100)


# ---------------------------------------------------------------------------
# distinct-customer set ops: q38 / q87 / q97
# ---------------------------------------------------------------------------

def _cust_dates(session, data_dir, sales, cust_col, date_col):
    cu = _t(session, data_dir, "customer",
            ["c_customer_sk", "c_last_name", "c_first_name"])
    dd = _t(session, data_dir, "date_dim",
            ["d_date_sk", "d_date", "d_month_seq"]) \
        .where((col("d_month_seq") >= lit(1200))
               & (col("d_month_seq") <= lit(1211)))
    return sales.join(dd, on=[(date_col, "d_date_sk")]) \
        .join(cu, on=[(cust_col, "c_customer_sk")]) \
        .select(col("c_last_name"), col("c_first_name"), col("d_date")) \
        .distinct()


def _three_channel_cust_dates(session, data_dir):
    ss = _t(session, data_dir, "store_sales",
            ["ss_sold_date_sk", "ss_customer_sk"])
    cs = _t(session, data_dir, "catalog_sales",
            ["cs_sold_date_sk", "cs_bill_customer_sk"])
    ws = _t(session, data_dir, "web_sales",
            ["ws_sold_date_sk", "ws_bill_customer_sk"])
    a = _cust_dates(session, data_dir, ss, "ss_customer_sk",
                    "ss_sold_date_sk")
    b = _cust_dates(session, data_dir, cs, "cs_bill_customer_sk",
                    "cs_sold_date_sk")
    c = _cust_dates(session, data_dir, ws, "ws_bill_customer_sk",
                    "ws_sold_date_sk")
    return a, b, c


def q38(session, data_dir: str):
    """TPC-DS q38: count of customers active in all three channels
    (INTERSECT)."""
    a, b, c = _three_channel_cust_dates(session, data_dir)
    return a.intersect(b).intersect(c).agg(CountStar().alias("cnt"))


def q87(session, data_dir: str):
    """TPC-DS q87: store-only shoppers (EXCEPT chain) count."""
    a, b, c = _three_channel_cust_dates(session, data_dir)
    return a.subtract(b).subtract(c).agg(CountStar().alias("cnt"))


def q97(session, data_dir: str):
    """TPC-DS q97: store/catalog shopper overlap via FULL OUTER JOIN."""
    dd = _t(session, data_dir, "date_dim",
            ["d_date_sk", "d_month_seq"]) \
        .where((col("d_month_seq") >= lit(1200))
               & (col("d_month_seq") <= lit(1211))) \
        .select(col("d_date_sk"))
    ssci = _t(session, data_dir, "store_sales",
              ["ss_sold_date_sk", "ss_customer_sk", "ss_item_sk"]) \
        .join(dd, on=[("ss_sold_date_sk", "d_date_sk")]) \
        .group_by("ss_customer_sk", "ss_item_sk").agg() \
        .select(col("ss_customer_sk").alias("s_customer_sk"),
                col("ss_item_sk").alias("s_item_sk"))
    csci = _t(session, data_dir, "catalog_sales",
              ["cs_sold_date_sk", "cs_bill_customer_sk", "cs_item_sk"]) \
        .join(dd, on=[("cs_sold_date_sk", "d_date_sk")]) \
        .group_by("cs_bill_customer_sk", "cs_item_sk").agg() \
        .select(col("cs_bill_customer_sk").alias("c_customer_sk"),
                col("cs_item_sk").alias("c_item_sk"))
    j = ssci.join(csci, on=[("s_customer_sk", "c_customer_sk"),
                            ("s_item_sk", "c_item_sk")], how="full")
    return j.agg(
        Sum(If(col("s_customer_sk").is_not_null()
               & col("c_customer_sk").is_null(), lit(1), lit(0)))
        .alias("store_only"),
        Sum(If(col("s_customer_sk").is_null()
               & col("c_customer_sk").is_not_null(), lit(1), lit(0)))
        .alias("catalog_only"),
        Sum(If(col("s_customer_sk").is_not_null()
               & col("c_customer_sk").is_not_null(), lit(1), lit(0)))
        .alias("store_and_catalog"))


# ---------------------------------------------------------------------------
# quarterly county growth: q31
# ---------------------------------------------------------------------------

def q31(session, data_dir: str):
    """TPC-DS q31: counties where web growth outpaces store growth across
    2000 Q1->Q2->Q3."""
    ca = _t(session, data_dir, "customer_address",
            ["ca_address_sk", "ca_county"])
    dd = _t(session, data_dir, "date_dim",
            ["d_date_sk", "d_qoy", "d_year"]) \
        .where(col("d_year") == lit(2000))

    def chan(sales, date_col, addr_col, price_col, name):
        return sales.join(dd, on=[(date_col, "d_date_sk")]) \
            .join(ca, on=[(addr_col, "ca_address_sk")]) \
            .group_by("ca_county", "d_qoy") \
            .agg(Sum(col(price_col)).alias(name))

    ss = chan(_t(session, data_dir, "store_sales",
                 ["ss_sold_date_sk", "ss_addr_sk", "ss_ext_sales_price"]),
              "ss_sold_date_sk", "ss_addr_sk", "ss_ext_sales_price",
              "store_sales")
    ws = chan(_t(session, data_dir, "web_sales",
                 ["ws_sold_date_sk", "ws_bill_addr_sk",
                  "ws_ext_sales_price"]),
              "ws_sold_date_sk", "ws_bill_addr_sk", "ws_ext_sales_price",
              "web_sales")

    def leg(frame, q, name, val):
        return frame.where(col("d_qoy") == lit(q)) \
            .select(col("ca_county").alias(f"{name}_county"),
                    col(val).alias(name))

    j = leg(ss, 1, "ss1", "store_sales") \
        .join(leg(ss, 2, "ss2", "store_sales"),
              on=[("ss1_county", "ss2_county")]) \
        .join(leg(ss, 3, "ss3", "store_sales"),
              on=[("ss1_county", "ss3_county")]) \
        .join(leg(ws, 1, "ws1", "web_sales"),
              on=[("ss1_county", "ws1_county")]) \
        .join(leg(ws, 2, "ws2", "web_sales"),
              on=[("ss1_county", "ws2_county")]) \
        .join(leg(ws, 3, "ws3", "web_sales"),
              on=[("ss1_county", "ws3_county")])
    return j.where(((col("ws2") / col("ws1")) > (col("ss2") / col("ss1")))
                   & ((col("ws3") / col("ws2"))
                      > (col("ss3") / col("ss2")))) \
        .select(col("ss1_county").alias("ca_county"), lit(2000).alias("d_year"),
                (col("ws2") / col("ws1")).alias("web_q1_q2_increase"),
                (col("ss2") / col("ss1")).alias("store_q1_q2_increase"),
                (col("ws3") / col("ws2")).alias("web_q2_q3_increase"),
                (col("ss3") / col("ss2")).alias("store_q2_q3_increase")) \
        .order_by(("ca_county", True))


# ---------------------------------------------------------------------------
# excess-discount: q32 / q92
# ---------------------------------------------------------------------------

def _excess_discount(session, data_dir, sales_tbl, item_col, date_col,
                     disc_col, manufact_id, start):
    lo = _date_sk(*start)
    hi = lo + 90
    dd = _t(session, data_dir, "date_dim", ["d_date_sk"]) \
        .where((col("d_date_sk") >= lit(lo)) & (col("d_date_sk") <= lit(hi)))
    sales = _t(session, data_dir, sales_tbl, [date_col, item_col, disc_col])
    windowed = sales.join(dd, on=[(date_col, "d_date_sk")])
    avg_disc = windowed.group_by(item_col) \
        .agg((Average(col(disc_col)) * lit(1.3)).alias("disc_thresh")) \
        .select(col(item_col).alias("avg_item_sk"), col("disc_thresh"))
    it = _t(session, data_dir, "item", ["i_item_sk", "i_manufact_id"]) \
        .where(col("i_manufact_id") == lit(manufact_id)) \
        .select(col("i_item_sk"))
    return windowed.join(it, on=[(item_col, "i_item_sk")]) \
        .join(avg_disc, on=[(item_col, "avg_item_sk")]) \
        .where(col(disc_col) > col("disc_thresh")) \
        .agg(Sum(col(disc_col)).alias("excess_discount_amount"))


def q32(session, data_dir: str):
    """TPC-DS q32: catalog excess discount amount."""
    return _excess_discount(session, data_dir, "catalog_sales",
                            "cs_item_sk", "cs_sold_date_sk",
                            "cs_ext_discount_amt", 977, (2000, 1, 27))


def q92(session, data_dir: str):
    """TPC-DS q92: web excess discount amount."""
    return _excess_discount(session, data_dir, "web_sales",
                            "ws_item_sk", "ws_sold_date_sk",
                            "ws_ext_discount_amt", 350, (2000, 1, 27))


QUERIES2 = {"q1": q1, "q2": q2, "q4": q4, "q11": q11, "q30": q30,
            "q31": q31, "q32": q32, "q38": q38, "q59": q59, "q74": q74,
            "q81": q81, "q87": q87, "q92": q92, "q97": q97}

"""TPC-DS-like benchmark harness: data generator, queries, runner.

Reference: integration_tests/src/main/scala/com/nvidia/spark/rapids/tests/
tpcds/TpcdsLikeSpark.scala (queries as DataFrame code with explicit
schemas), BenchmarkRunner.scala (CLI runner), BenchUtils.scala
(per-iteration JSON reports).
"""
from spark_rapids_tpu.bench.tpcds_gen import generate_tpcds
from spark_rapids_tpu.bench.tpcds_queries import QUERIES, build_query

__all__ = ["generate_tpcds", "QUERIES", "build_query"]

"""Multi-stream throughput benchmark: the serving-tier metric.

The official TPC-DS/TPC-H *throughput test* runs N concurrent query
streams, each executing the full query set in a DISTINCT permutation,
and scores queries-per-hour — the number a serving tier is actually
judged on (ROADMAP item 4 names it as the tracked BENCH metric; the
reference's BenchmarkRunner measures single-stream power runs only).

This runner drives ONE engine session with N concurrent streams, each
stream a tenant (``collect(tenant="streamK")``), so the measurement
exercises the whole serving tier at once: weighted-fair admission
(exec/lifecycle.py), the cross-query result/fragment cache
(exec/result_cache.py — identical queries across streams coalesce or
hit), and the memory governor under real concurrency.  Per-stream
results are verified against the host oracle every run — a throughput
number from wrong rows is worthless.

Reported per stream count N: wall seconds, queries-per-hour, speedup
vs the 1-stream run, and the observability block's cache-hit
(``result_cache_hits`` / ``_coalesced`` / ``_fragment_hits``) and
fairness (``admission.tenant.<t>.admitted``, per-tenant query counts)
counter movement.  All N-stream runs are WARM (a priming pass
populates the compile and result caches first), so the curve measures
steady-state serving, not first-compile cost; ``qph_cold`` on the
N=1 rung records the cold number for contrast.
"""
from __future__ import annotations

import threading
import time

__all__ = ["run_throughput"]


def _percentiles(snap: "dict | None") -> "dict | None":
    """p50/p95/p99 (seconds) from one histogram snapshot delta."""
    if not snap or not snap.get("count"):
        return None
    from spark_rapids_tpu.obs.registry import histogram_percentile
    out = {f"p{q}": round(histogram_percentile(snap, q), 6)
           for q in (50, 95, 99)}
    out["count"] = snap["count"]
    return out


def _build_and_collect(session, build_query, name, data_dir, tenant):
    """One query start-to-rows on the device backend.  Plans are built
    fresh per execution: AQE installs runtime filters ON the scan exec
    nodes, so concurrent streams must not share one DataFrame's plan."""
    df = build_query(name, session, data_dir)
    return df.collect(tenant=tenant)


def _oracle_rows(session, build_query, name, data_dir):
    from spark_rapids_tpu.bench.runner import _collect_rows
    return _collect_rows(build_query(name, session, data_dir), "host")


def run_throughput(data_dir: str, sf: float, streams=(1, 2, 4, 8),
                   queries=("q3", "q13", "q18"), suite: str = "tpch",
                   session_conf: dict | None = None, generate: bool = True,
                   verify: bool = True) -> dict:
    """Run the multi-stream throughput ladder; returns the full report.

    ``streams`` is the ladder of concurrent stream counts; each stream
    runs every query once, in a permutation rotated by its stream index
    (distinct permutations per the official throughput-test shape), as
    tenant ``stream<K>``.  ``ok`` is the AND of every per-stream
    row-set verification against the host oracle."""
    from spark_rapids_tpu.bench.runner import _rows_match
    from spark_rapids_tpu.obs.registry import get_registry
    from spark_rapids_tpu.session import TpuSession
    if suite == "tpch":
        from spark_rapids_tpu.bench.tpch_gen import generate_tpch as gen
        from spark_rapids_tpu.bench.tpch_queries import (
            build_tpch_query as build_query)
    else:
        from spark_rapids_tpu.bench.tpcds_gen import generate_tpcds as gen
        from spark_rapids_tpu.bench.tpcds_queries import build_query

    if generate:
        gen(data_dir, sf=sf)

    conf = dict(session_conf or {})
    # every stream gets weight 1 unless the caller says otherwise: the
    # throughput test measures aggregate QpH under FAIR sharing
    conf.setdefault("spark.rapids.sql.admission.maxConcurrentQueries",
                    max(streams))
    session = TpuSession(conf)
    reg = get_registry()
    report: dict = {"suite": suite, "sf": sf, "queries": list(queries),
                    "streams": [], "ok": True}
    try:
        oracle = {}
        if verify:
            for q in queries:
                oracle[q] = _oracle_rows(session, build_query, q, data_dir)

        # priming pass: one device run per query, timed — this is the
        # honest COLD single-stream number, and it warms the compile
        # cache + result cache for every WARM rung below
        t0 = time.perf_counter()
        for q in queries:
            rows = _build_and_collect(session, build_query, q, data_dir,
                                      "prime")
            if verify and not _rows_match(rows, oracle[q]):
                report["ok"] = False
                report["error"] = f"priming run: {q} rows != oracle"
                return report
        cold_wall = time.perf_counter() - t0
        report["qph_cold_1stream"] = round(
            len(queries) * 3600.0 / cold_wall, 1)

        base_qph = None
        for n in streams:
            before = reg.snapshot()
            errors: list[str] = []
            mismatches: list[str] = []

            def stream(k: int):
                # distinct permutation per stream: rotate by stream
                # index (the official throughput test's per-stream
                # ordering requirement, shaped to any query count)
                order = [queries[(i + k) % len(queries)]
                         for i in range(len(queries))]
                for q in order:
                    try:
                        rows = _build_and_collect(
                            session, build_query, q, data_dir,
                            f"stream{k}")
                    # enginelint: disable=RL001 (stream worker thread: terminal errors included — every failure is recorded in the report and fails its ok flag; raising here would only kill the thread silently)
                    except Exception as e:
                        errors.append(f"stream{k}/{q}: "
                                      f"{type(e).__name__}: {e}")
                        return
                    if verify and not _rows_match(rows, oracle[q]):
                        mismatches.append(f"stream{k}/{q}")

            threads = [threading.Thread(target=stream, args=(k,),
                                        name=f"tput-stream{k}")
                       for k in range(n)]
            t0 = time.perf_counter()
            for t in threads:
                t.start()
            for t in threads:
                t.join()
            wall = time.perf_counter() - t0
            delta = reg.delta(before)
            moved = delta["counters"]
            hists = delta.get("histograms", {})
            total = n * len(queries)
            qph = total * 3600.0 / wall if wall > 0 else 0.0
            rung = {
                "streams": n,
                "queries_run": total,
                "wall_s": round(wall, 4),
                "qph": round(qph, 1),
                # the SLO numbers QpH alone hides: this rung's query
                # latency distribution, aggregate and per stream, from
                # the histogram movement during the rung
                "latency": _percentiles(hists.get("query.wall_seconds")),
                "stream_latency": {
                    t: _percentiles(snap) for t, snap in sorted(
                        (k[len("query.tenant."):-len(".wall_seconds")], v)
                        for k, v in hists.items()
                        if k.startswith("query.tenant.stream")
                        and k.endswith(".wall_seconds"))},
                "histograms": hists,
                "cache": {k: moved[k] for k in sorted(moved)
                          if k.startswith("result_cache")},
                "fairness": {k: moved[k] for k in sorted(moved)
                             if k.startswith("admission")
                             or k in ("queries_executed",
                                      "queries_admitted",
                                      "queries_rejected")},
            }
            if base_qph is None and n == 1:
                base_qph = qph
            if base_qph:
                rung["speedup_vs_1stream"] = round(qph / base_qph, 3)
            if errors:
                rung["errors"] = errors[:5]
                report["ok"] = False
            if mismatches:
                rung["mismatches"] = mismatches[:5]
                report["ok"] = False
            report["streams"].append(rung)
    finally:
        session.shutdown()
    return report

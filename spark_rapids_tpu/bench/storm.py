"""Mixed-tenant storm benchmark: the control plane's proof of worth.

Three tenants share one session under a deliberate device bottleneck
(``spark.rapids.sql.concurrentTpuTasks=1``, result cache off so every
query really executes):

* ``web``   — light queries (q6), latency-sensitive, strict SLO.
* ``etl``   — medium string-heavy queries (q13: NOT LIKE over order
  comments), a looser SLO.
* ``batch`` — a storm of heavy queries (q18) from many threads whose
  own SLO is unmeetable under its self-inflicted queueing: the tenant
  the control plane must quarantine.

The storm runs with the cost-attribution plane ON
(``spark.rapids.obs.profile.enabled``), and the closed-loop section of
the report carries the per-tenant metering deltas (device-seconds et
al.) the run accrued — the storm doubles as the metering plane's
mixed-tenant soak.

SLOs are SELF-CALIBRATED from solo walls measured on this machine
(``slo = a*solo_tenant + b*solo_batch``), so the benchmark measures
scheduling behavior, not the host's absolute speed.  Latency is scored
client-side (wall of each ``collect`` as the tenant observed it,
queueing included) over the steady-state window — the first
``warmup_s`` of each run is discarded equally everywhere, so closed-
loop runs get no credit for the pre-shed transient and fixed runs
none of the blame for compile warmup.

The grid of FIXED configurations (maxConcurrentQueries x workers,
control plane off) is scored against the same SLOs as the CLOSED-LOOP
run (control plane on).  The claim under test: every fixed point
misses at least one tenant's p99 SLO, while the closed loop meets the
SLOs of the well-behaved tenants by shedding exactly the violator —
``admission.tenant.<t>.rejected`` stays zero for web/etl.
"""
from __future__ import annotations

import math
import threading
import time

__all__ = ["run_storm"]

#: (tenant, query, threads, think_s) — the storm shape
DEFAULT_TENANTS = (
    ("web", "q6", 2, 0.02),
    # q13 keeps one string-heavy rung in the storm (NOT LIKE over
    # o_comment): host-decoded string work meters differently from the
    # numeric rungs, which is exactly what per-tenant attribution must
    # keep separated
    ("etl", "q13", 1, 0.05),
    ("batch", "q18", 6, 0.0),
)

#: fixed-configuration grid: (maxConcurrentQueries, workers); workers
#: > 1 runs the cluster runtime (spark.rapids.cluster.mode=local[N])
DEFAULT_GRID = tuple((mc, w) for w in (1, 2, 4) for mc in (2, 4, 8))


def _p99(walls: "list[float]") -> "float | None":
    if not walls:
        return None
    s = sorted(walls)
    return s[max(0, math.ceil(0.99 * len(s)) - 1)]


def _base_conf(extra: "dict | None" = None) -> dict:
    conf = {
        # ONE device slot: the storm's contention is deterministic, not
        # a function of how many cores the host happens to have
        "spark.rapids.sql.concurrentTpuTasks": "1",
        # a cache hit bypasses admission — with the storm re-running
        # identical queries, caching would dissolve the very queueing
        # under measurement
        "spark.rapids.sql.resultCache.enabled": "false",
        "spark.rapids.sql.admission.maxQueuedQueries": "64",
        # cost attribution on: the storm report carries per-tenant
        # metering deltas, and the profiled hot path soaks under real
        # multi-tenant contention
        "spark.rapids.obs.profile.enabled": "true",
    }
    conf.update(extra or {})
    return conf


def _run_storm_window(session, build_query, data_dir, tenants,
                      duration_s: float, warmup_s: float) -> dict:
    """Drive the storm against one session; returns per-tenant
    client-observed steady-state walls, shed counts, and errors."""
    from spark_rapids_tpu.exec.lifecycle import QueryRejected
    samples: dict = {t[0]: [] for t in tenants}
    sheds: dict = {t[0]: 0 for t in tenants}
    errors: list = []
    lock = threading.Lock()
    t_origin = time.perf_counter()
    t_end = t_origin + duration_s

    def worker(tenant: str, qname: str, think: float):
        while time.perf_counter() < t_end:
            df = build_query(qname, session, data_dir)
            t0 = time.perf_counter()
            try:
                df.collect(tenant=tenant)
            except QueryRejected:
                # the shed path: rejected fast at admission, by design.
                # A rejected client backs off before retrying — the
                # sleep models that, and keeps the reject loop from
                # burning host CPU rebuilding plans at full tilt
                with lock:
                    sheds[tenant] += 1
                time.sleep(0.2)
                continue
            # enginelint: disable=RL001 (bench worker thread: any engine failure is recorded in the report and fails the rung)
            except Exception as e:
                with lock:
                    errors.append(f"{tenant}/{qname}: "
                                  f"{type(e).__name__}: {e}")
                return
            wall = time.perf_counter() - t0
            with lock:
                samples[tenant].append((t0 - t_origin, wall))
            if think:
                time.sleep(think)

    threads = []
    for tenant, qname, n, think in tenants:
        for _ in range(n):
            threads.append(threading.Thread(
                target=worker, args=(tenant, qname, think),
                name=f"storm-{tenant}"))
    for t in threads:
        t.start()
    for t in threads:
        t.join()

    out = {"tenants": {}, "errors": errors[:5]}
    for tenant, _q, _n, _think in tenants:
        all_walls = [w for _s, w in samples[tenant]]
        steady = [w for s, w in samples[tenant] if s >= warmup_s]
        out["tenants"][tenant] = {
            "queries": len(all_walls),
            "steady_queries": len(steady),
            "shed": sheds[tenant],
            "p99_s": (None if _p99(steady) is None
                      else round(_p99(steady), 4)),
            "p99_all_s": (None if _p99(all_walls) is None
                          else round(_p99(all_walls), 4)),
        }
    return out


def _score(window: dict, slos: dict) -> dict:
    """met/missed per SLO'd tenant against steady-state p99.  A tenant
    with NO steady samples at all is a miss unless it was shed (a shed
    tenant is quarantined, not served badly)."""
    met, missed = {}, []
    for tenant, slo in slos.items():
        info = window["tenants"].get(tenant) or {}
        p99 = info.get("p99_s")
        if p99 is None:
            ok = bool(info.get("shed"))
        else:
            ok = p99 <= slo
        met[tenant] = ok
        if not ok:
            missed.append(tenant)
    return {"met": met, "missed": missed}


def run_storm(data_dir: str, sf: float, *,
              tenants=DEFAULT_TENANTS,
              grid=DEFAULT_GRID,
              duration_s: float = 6.0,
              warmup_s: "float | None" = None,
              suite: str = "tpch",
              generate: bool = True,
              verify: bool = True) -> dict:
    """Run the mixed-tenant storm: calibrate, sweep the fixed grid,
    then the closed loop.  Returns the full report; ``ok`` is True iff
    every fixed configuration missed at least one tenant SLO while the
    closed loop met every non-storm SLO, shed only the storm tenant,
    and rejected nobody else."""
    from spark_rapids_tpu.bench.runner import _collect_rows, _rows_match
    from spark_rapids_tpu.obs.registry import get_registry
    from spark_rapids_tpu.session import TpuSession
    if suite != "tpch":
        raise ValueError("storm bench is TPC-H shaped")
    from spark_rapids_tpu.bench.tpch_gen import generate_tpch
    from spark_rapids_tpu.bench.tpch_queries import build_tpch_query

    if generate:
        generate_tpch(data_dir, sf=sf)
    if warmup_s is None:
        # floor covers the controller's reaction time: ~4 ticks at the
        # 0.25s closed-loop interval to shed, plus the in-flight drain
        warmup_s = max(2.0, 0.35 * duration_s)

    report: dict = {"suite": suite, "sf": sf, "duration_s": duration_s,
                    "warmup_s": warmup_s, "ok": True}

    # -- calibration: solo walls, warm (2nd run), oracle-verified -----
    solo: dict = {}
    session = TpuSession(_base_conf())
    try:
        for tenant, qname, _n, _think in tenants:
            df = build_tpch_query(qname, session, data_dir)
            if verify:
                rows = df.collect(tenant=tenant)
                if not _rows_match(rows, _collect_rows(
                        build_tpch_query(qname, session, data_dir),
                        "host")):
                    report["ok"] = False
                    report["error"] = f"calibration: {qname} != oracle"
                    return report
            else:
                df.collect(tenant=tenant)
            t0 = time.perf_counter()
            build_tpch_query(qname, session, data_dir).collect(
                tenant=tenant)
            solo[tenant] = time.perf_counter() - t0
    finally:
        session.shutdown()
    report["solo_wall_s"] = {t: round(w, 4) for t, w in solo.items()}

    # self-calibrated SLOs: once shedding engages, a served query may
    # queue behind at most ONE in-flight storm query, one query of
    # each OTHER served tenant, and one of its own siblings — the
    # single-device worst case with the storm quarantined.  Each term
    # gets 2x headroom because solo walls are measured on an idle
    # host: under the storm the same work shares host CPU with the
    # rejected tenant's retry loop and the control loop itself.  The
    # storm tenant's own SLO is unmeetable under its 6-way self-flood
    # by construction.
    def _served_slo(tenant: str) -> float:
        cross = sum(w for t, w in solo.items()
                    if t not in (tenant, "batch"))
        return max(0.05,
                   2.0 * (2.0 * solo[tenant] + cross)
                   + 1.2 * solo["batch"])

    slos = {
        "web": _served_slo("web"),
        "etl": _served_slo("etl"),
        "batch": max(0.02, 1.2 * solo["batch"]),
    }
    report["slo_s"] = {t: round(s, 4) for t, s in slos.items()}
    served = [t for t in slos if t != "batch"]

    # -- fixed grid: control plane OFF ---------------------------------
    fixed = []
    all_fixed_missed = True
    for mc, workers in grid:
        conf = _base_conf({
            "spark.rapids.sql.admission.maxConcurrentQueries": str(mc)})
        if workers > 1:
            conf["spark.rapids.cluster.mode"] = f"local[{workers}]"
        rung: dict = {"max_concurrent": mc, "workers": workers}
        try:
            session = TpuSession(conf)
            try:
                window = _run_storm_window(session, build_tpch_query,
                                           data_dir, tenants,
                                           duration_s, warmup_s)
            finally:
                session.shutdown()
            rung.update(window)
            rung.update(_score(window, slos))
            if window["errors"]:
                rung["missed"] = sorted(set(rung["missed"]) | {"error"})
        # enginelint: disable=RL001 (a fixed rung that cannot even run is recorded as such; the sweep continues)
        except Exception as e:
            rung["error"] = f"{type(e).__name__}: {e}"
            rung["missed"] = ["error"]
        fixed.append(rung)
        if not rung.get("missed"):
            all_fixed_missed = False
    report["fixed"] = fixed
    report["all_fixed_missed"] = all_fixed_missed

    # -- closed loop: control plane ON ----------------------------------
    # the closed loop STARTS conservative (mc=2): fewer storm queries
    # are in flight when the shed lands, so the drain transient clears
    # before the steady-state window opens; AIMD owns opening it up
    conf = _base_conf({
        "spark.rapids.sql.admission.maxConcurrentQueries": "2",
        "spark.rapids.control.enabled": "true",
        "spark.rapids.control.intervalSeconds": "0.25",
        # routing needs a history dir; the storm measures the
        # admission/SLO loop, so keep the run hermetic
        "spark.rapids.control.route.enabled": "false",
    })
    for tenant, slo in slos.items():
        conf[f"spark.rapids.control.slo.{tenant}.p99Seconds"] = \
            f"{slo:.6f}"
    reg = get_registry()
    before = reg.snapshot()["counters"]
    from spark_rapids_tpu.obs.metering import get_meter
    meter_before = {t: dict(u) for t, u in
                    get_meter().snapshot()["tenants"].items()}
    session = TpuSession(conf)
    try:
        window = _run_storm_window(session, build_tpch_query, data_dir,
                                   tenants, duration_s, warmup_s)
        control_status = (session._control.status()
                          if session._control is not None else None)
    finally:
        session.shutdown()
    after = reg.snapshot()["counters"]
    moved = {k: after[k] - before.get(k, 0) for k in after
             if after[k] != before.get(k, 0)}
    closed: dict = {"max_concurrent_initial": 2}
    closed.update(window)
    closed.update(_score(window, slos))
    closed["counters"] = {
        k: v for k, v in sorted(moved.items())
        if k.startswith(("admission.tenant.", "control"))}
    # per-tenant resource attribution over the closed-loop window
    # (obs/metering.py): what each tenant's served queries actually
    # cost while the controller was arbitrating between them
    meter_after = get_meter().snapshot()["tenants"]
    closed["metering"] = {
        t: {m: round(u.get(m, 0.0)
                     - meter_before.get(t, {}).get(m, 0.0), 6)
            for m in ("device_seconds", "hbm_byte_seconds",
                      "scan_bytes", "queries")}
        for t, u in sorted(meter_after.items())}
    if control_status:
        closed["decisions"] = control_status.get("decisions")
    report["closed"] = closed

    # -- verdict --------------------------------------------------------
    storm_shed = closed["tenants"]["batch"]["shed"] > 0
    served_met = all(closed["met"].get(t) for t in served)
    served_clean = all(
        moved.get(f"admission.tenant.{t}.rejected", 0) == 0
        for t in served)
    margin = min((slos[t] / closed["tenants"][t]["p99_s"]
                  for t in served
                  if closed["tenants"][t].get("p99_s")), default=0.0)
    report["closed_slo_margin"] = round(margin, 3)
    report["storm_tenant_shed"] = storm_shed
    report["served_tenants_clean"] = served_clean
    report["ok"] = (report["ok"] and all_fixed_missed and served_met
                    and storm_shed and served_clean
                    and not closed["errors"])
    if not report["ok"] and "error" not in report:
        why = []
        if not all_fixed_missed:
            why.append("a fixed configuration met every SLO")
        if not served_met:
            why.append(f"closed loop missed {closed['missed']}")
        if not storm_shed:
            why.append("storm tenant was never shed")
        if not served_clean:
            why.append("a served tenant was rejected")
        if closed["errors"]:
            why.append(f"closed-loop errors: {closed['errors']}")
        report["error"] = "; ".join(why)
    return report

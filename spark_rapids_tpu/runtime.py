"""Process-level device runtime: persistent XLA compilation cache.

The reference's hot loop has zero per-batch compilation (every kernel is a
pre-built libcudf entry point, SURVEY.md §3.3).  The XLA analog spends real
wall time in ``lowered.compile()`` — tens of seconds per program when the
backend is a remote/tunneled TPU with remote compile — so the engine turns
on JAX's persistent compilation cache: each (program, capacity-bucket)
compiles once per machine, ever.  Subsequent sessions and processes load
the serialized executable in milliseconds.

Reference analog: the CUDA build ships precompiled fatbins in libcudf; the
TPU build's "precompiled kernels" are this cache directory.
"""
from __future__ import annotations

import os

from spark_rapids_tpu.conf import ConfEntry, register, _bool

__all__ = ["enable_compilation_cache", "ensure_runtime",
           "widen_thread_stacks"]

def _cache_mode(v) -> str:
    s = str(v).strip().lower()
    if s in ("auto",):
        return "auto"
    return "true" if _bool(v) else "false"


COMPILATION_CACHE_ENABLED = register(ConfEntry(
    "spark.rapids.tpu.compilationCache.enabled", "auto",
    "Persistent XLA compilation cache so each kernel capacity bucket "
    "compiles once per machine (reference: libcudf ships precompiled "
    "kernels; XLA must cache its executables instead).  'auto' "
    "(default): on for accelerator backends, where a compile costs a "
    "20-40s tunnel round trip, and OFF for plain XLA:CPU — this XLA "
    "build's cpu_aot_loader re-checks machine features on every cached "
    "load and falsely flags its own entries (+prefer-no-scatter/gather "
    "are compile-time tuning prefs, not cpuinfo flags), burying CI logs "
    "in could-lead-to-SIGILL noise.  'true'/'false' force it.",
    conv=_cache_mode))
COMPILATION_CACHE_DIR = register(ConfEntry(
    "spark.rapids.tpu.compilationCache.dir",
    os.environ.get("SPARK_RAPIDS_TPU_CACHE_DIR",
                   os.path.expanduser("~/.cache/spark_rapids_tpu/xla")),
    "Directory for the persistent XLA compilation cache."))

_enabled_dir: str | None = None
_arrow_pinned = False
_pinned_arena = None
_pinned_borrowers = None       # weakref.WeakSet of current borrowers
_retired_arenas: list = []     # (arena, borrower WeakSet) until drained
import threading as _threading

_pinned_lock = _threading.Lock()
_stacks_widened = False


_cpu_sync_dispatch = False


def sync_cpu_dispatch() -> None:
    """Make XLA:CPU dispatch synchronous.

    With async dispatch (jax's default) an execution keeps running on
    the backend's internal thread pool after the python call returns;
    a compile starting on another engine thread then overlaps it, and
    this XLA build segfaults intermittently inside ``backend_compile``
    under exactly that overlap (same family as the pyarrow pool races
    pinned away in ``pin_arrow_threads``).  Synchronous dispatch closes
    the window; the engine's drain pool supplies the parallelism
    instead, so CPU throughput is unaffected.  Called once, when the
    compile cache first observes the CPU backend.
    """
    global _cpu_sync_dispatch
    if _cpu_sync_dispatch:
        return
    try:
        import jax
        jax.config.update("jax_cpu_enable_async_dispatch", False)
    # enginelint: disable=RL001 (jax may be absent; sync dispatch only matters once it exists)
    except Exception:
        pass
    _cpu_sync_dispatch = True


def widen_thread_stacks(size: int = 64 * 1024 * 1024) -> None:
    """Deepen the stack of engine-created worker threads.

    XLA:CPU compilation (LLVM's recursive optimizer passes) can exceed
    the default 8 MiB pthread stack when a drain worker hits a new
    executable mid-query; the overflow lands as a bare SIGSEGV inside
    ``backend_compile``.  The stack is virtual address space committed
    lazily, so a deep reserve costs nothing.  ``threading.stack_size``
    only applies to threads created AFTER the call, so this runs at
    exec-layer import, before the first drain pool exists.
    """
    global _stacks_widened
    if _stacks_widened:
        return
    try:
        import threading
        threading.stack_size(size)
    except (ValueError, RuntimeError, OverflowError):
        pass
    _stacks_widened = True


def get_pinned_arena(size: int, borrower=None):
    """Process-level pinned staging arena (reference
    allocatePinnedMemory, GpuDeviceManager.scala:264-270: allocated once
    per executor process, not per query).  BufferCatalog shares it when
    pinnedPool.size > 0.

    Growth is by REPLACEMENT (the C++ arena cannot extend its mapping),
    and the replaced arena must outlive its borrowers: a catalog handed
    the old arena holds numpy views whose base pointers reach into the
    old mapping, so letting the ref drop here would run
    ``HostArena.__del__`` -> ``arena_destroy`` and turn every
    outstanding view into a use-after-free.  Replaced arenas are parked
    in ``_retired_arenas`` keyed by a WeakSet of their borrowers and
    only released (closing via ``__del__``) once every borrower has
    been collected.  Callers that may outlive a growth event pass
    themselves as ``borrower``; an untracked borrower set behaves like
    the pre-fix code (immediate replacement) for callers that provably
    don't retain views."""
    global _pinned_arena, _pinned_borrowers
    import weakref
    with _pinned_lock:
        # sweep: a retired arena whose borrowers all drained can close
        _retired_arenas[:] = [(a, s) for a, s in _retired_arenas
                              if len(s) > 0]
        if _pinned_arena is None or _pinned_arena.capacity < size:
            from spark_rapids_tpu.native import HostArena
            if _pinned_arena is not None and _pinned_borrowers and \
                    len(_pinned_borrowers) > 0:
                _retired_arenas.append((_pinned_arena, _pinned_borrowers))
            _pinned_arena = HostArena(size)
            _pinned_borrowers = weakref.WeakSet()
        if borrower is not None:
            if _pinned_borrowers is None:
                _pinned_borrowers = weakref.WeakSet()
            _pinned_borrowers.add(borrower)
        return _pinned_arena


def pin_arrow_threads() -> None:
    """Pin pyarrow's internal compute/IO pools to one thread.

    Empirically required in this runtime: pyarrow compute kernels
    (fill_null/cast/array) segfault intermittently when their internal
    pool runs concurrently with jax CPU execution on other python
    threads.  The engine supplies its own parallelism (drain worker
    pool), so single-threaded pyarrow conversions lose nothing.
    """
    global _arrow_pinned
    if _arrow_pinned:
        return
    try:
        import pyarrow as pa
        pa.set_cpu_count(1)
        pa.set_io_thread_count(1)
    # enginelint: disable=RL001 (pyarrow optional; thread pinning is best-effort)
    except Exception:
        pass
    _arrow_pinned = True


def enable_compilation_cache(cache_dir: str | None = None) -> str | None:
    """Idempotently turn on the persistent compilation cache.

    Call AFTER device initialization (ensure_runtime does): the cache
    dir is fingerprinted on jax.config.jax_platforms, which device init
    pins to the user's requested platform — fingerprinting before that
    can mix local-CPU and tunnel-compiled AOT entries in one dir.
    Returns the cache directory in use (None if disabled via conf/env).
    """
    global _enabled_dir
    cache_dir = cache_dir or COMPILATION_CACHE_DIR.default
    # partition by (XLA_FLAGS, platform, host CPU features): XLA:CPU AOT
    # executables record the compile machine's feature set (AMX/AVX512…)
    # and loading them on a lesser host warns "could lead to SIGILL";
    # virtual-device test meshes similarly must not share entries with
    # the plain backend.  One subdir per distinct compile environment.
    import hashlib
    fp = hashlib.md5()
    # cache-schema version: bump to orphan every entry written under an
    # older fingerprint recipe.  v2 = round-5 purge — dirs fingerprinted
    # before the platform-config fix still held tunnel-compiled AOT
    # entries whose recorded target features (+prefer-no-scatter/gather)
    # mismatch this host and warn "could lead to SIGILL" on every load.
    fp.update(b"cache-schema-v2:")
    fp.update(os.environ.get("XLA_FLAGS", "").encode())
    # the CONFIG value, not the env var: the accelerator site hook
    # rewrites jax_platforms after env processing, so the env string can
    # say "cpu" while programs actually compile for (and on) the tunnel
    # terminal — those AOT entries must not share a dir with true local
    # CPU compiles (observed "+prefer-no-scatter not supported … SIGILL"
    # loads in round 4)
    try:
        import jax
        platforms = jax.config.jax_platforms or os.environ.get(
            "JAX_PLATFORMS", "")
    # enginelint: disable=RL001 (fingerprint falls back to the env var when jax config is unreadable)
    except Exception:
        platforms = os.environ.get("JAX_PLATFORMS", "")
    fp.update(str(platforms).encode())
    try:
        with open("/proc/cpuinfo") as f:
            for line in f:
                if line.startswith("flags"):
                    fp.update(line.encode())
                    break
    except OSError:
        pass
    root = cache_dir
    cache_dir = os.path.join(cache_dir, fp.hexdigest()[:8])
    if _enabled_dir == cache_dir:
        return _enabled_dir
    # purge sibling dirs that lack the current schema marker (written
    # below): those predate the fingerprint recipe and keep resurfacing
    # machine-feature-mismatch AOT loads (VERDICT r4 weak #5).  Dirs for
    # OTHER legit compile environments (cpu vs tunnel) created under the
    # current schema carry the marker and survive.
    _SCHEMA_MARK = ".cache-schema-v2"
    try:
        import re
        import shutil
        for d in os.listdir(root):
            p = os.path.join(root, d)
            # only dirs matching THIS module's 8-hex fingerprint naming:
            # the root is user-configurable, so an unrestricted purge
            # could eat unrelated content under a shared directory
            if re.fullmatch(r"[0-9a-f]{8}", d) and os.path.isdir(p) \
                    and p != cache_dir \
                    and not os.path.exists(os.path.join(p, _SCHEMA_MARK)):
                shutil.rmtree(p, ignore_errors=True)
    except OSError:
        pass
    try:
        import jax
        os.makedirs(cache_dir, exist_ok=True)
        with open(os.path.join(cache_dir, _SCHEMA_MARK), "w"):
            pass
        jax.config.update("jax_compilation_cache_dir", cache_dir)
        # cache everything: even "cheap" programs cost a tunnel round trip
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.0)
        try:
            jax.config.update("jax_persistent_cache_min_entry_size_bytes", -1)
        # enginelint: disable=RL001 (knob name varies across jax versions; the cache works without it)
        except Exception:
            pass  # knob name varies across jax versions
        _enabled_dir = cache_dir
    except (OSError, AttributeError, ValueError) as e:
        import warnings
        warnings.warn(
            f"persistent XLA compilation cache DISABLED ({e}); every "
            "program will recompile per process", RuntimeWarning)
        return None
    return _enabled_dir


def ensure_runtime(conf=None) -> None:
    """Session-start runtime init (reference RapidsExecutorPlugin.init,
    Plugin.scala:124-154): compilation cache + arrow thread pinning +
    fail-fast device acquisition with HBM pool sizing (device.py);
    semaphore wiring lives in memory/catalog.py."""
    pin_arrow_threads()
    settings = getattr(conf, "settings", None) or {}
    # device init FIRST: it pins jax_platforms to the user's requested
    # platform, which the cache fingerprint below depends on
    from spark_rapids_tpu.device import initialize_device
    initialize_device(conf)
    from spark_rapids_tpu.exec.compile_cache import COMPILE_CACHE_DIR
    sql_dir = COMPILE_CACHE_DIR.get(settings)
    if sql_dir:
        # explicit opt-in wins over the auto heuristic: naming a
        # directory means the operator wants warm starts even on XLA:CPU
        enable_compilation_cache(sql_dir)
        return
    mode = COMPILATION_CACHE_ENABLED.get(settings)
    if mode == "auto":
        try:
            import jax
            on = jax.default_backend() != "cpu"
        # enginelint: disable=RL001 (backend probe defaults to cache-off when jax is unavailable)
        except Exception:
            on = False
    else:
        on = mode == "true"
    if on:
        enable_compilation_cache(COMPILATION_CACHE_DIR.get(settings))

"""Process-level device runtime: persistent XLA compilation cache.

The reference's hot loop has zero per-batch compilation (every kernel is a
pre-built libcudf entry point, SURVEY.md §3.3).  The XLA analog spends real
wall time in ``lowered.compile()`` — tens of seconds per program when the
backend is a remote/tunneled TPU with remote compile — so the engine turns
on JAX's persistent compilation cache: each (program, capacity-bucket)
compiles once per machine, ever.  Subsequent sessions and processes load
the serialized executable in milliseconds.

Reference analog: the CUDA build ships precompiled fatbins in libcudf; the
TPU build's "precompiled kernels" are this cache directory.
"""
from __future__ import annotations

import os

from spark_rapids_tpu.conf import ConfEntry, register, _bool

__all__ = ["enable_compilation_cache", "ensure_runtime"]

COMPILATION_CACHE_ENABLED = register(ConfEntry(
    "spark.rapids.tpu.compilationCache.enabled", True,
    "Enable JAX's persistent compilation cache so each kernel capacity "
    "bucket compiles once per machine (reference: libcudf ships "
    "precompiled kernels; XLA must cache its executables instead).",
    conv=_bool))
COMPILATION_CACHE_DIR = register(ConfEntry(
    "spark.rapids.tpu.compilationCache.dir",
    os.environ.get("SPARK_RAPIDS_TPU_CACHE_DIR",
                   os.path.expanduser("~/.cache/spark_rapids_tpu/xla")),
    "Directory for the persistent XLA compilation cache."))

_enabled_dir: str | None = None
_arrow_pinned = False
_pinned_arena = None


def get_pinned_arena(size: int):
    """Process-level pinned staging arena (reference
    allocatePinnedMemory, GpuDeviceManager.scala:264-270: allocated once
    per executor process, not per query).  Grown only, never closed —
    BufferCatalog shares it when pinnedPool.size > 0."""
    global _pinned_arena
    if _pinned_arena is None or _pinned_arena.capacity < size:
        from spark_rapids_tpu.native import HostArena
        _pinned_arena = HostArena(size)
    return _pinned_arena


def pin_arrow_threads() -> None:
    """Pin pyarrow's internal compute/IO pools to one thread.

    Empirically required in this runtime: pyarrow compute kernels
    (fill_null/cast/array) segfault intermittently when their internal
    pool runs concurrently with jax CPU execution on other python
    threads.  The engine supplies its own parallelism (drain worker
    pool), so single-threaded pyarrow conversions lose nothing.
    """
    global _arrow_pinned
    if _arrow_pinned:
        return
    try:
        import pyarrow as pa
        pa.set_cpu_count(1)
        pa.set_io_thread_count(1)
    except Exception:
        pass
    _arrow_pinned = True


def enable_compilation_cache(cache_dir: str | None = None) -> str | None:
    """Idempotently turn on the persistent compilation cache.

    Call AFTER device initialization (ensure_runtime does): the cache
    dir is fingerprinted on jax.config.jax_platforms, which device init
    pins to the user's requested platform — fingerprinting before that
    can mix local-CPU and tunnel-compiled AOT entries in one dir.
    Returns the cache directory in use (None if disabled via conf/env).
    """
    global _enabled_dir
    cache_dir = cache_dir or COMPILATION_CACHE_DIR.default
    # partition by (XLA_FLAGS, platform, host CPU features): XLA:CPU AOT
    # executables record the compile machine's feature set (AMX/AVX512…)
    # and loading them on a lesser host warns "could lead to SIGILL";
    # virtual-device test meshes similarly must not share entries with
    # the plain backend.  One subdir per distinct compile environment.
    import hashlib
    fp = hashlib.md5()
    fp.update(os.environ.get("XLA_FLAGS", "").encode())
    # the CONFIG value, not the env var: the accelerator site hook
    # rewrites jax_platforms after env processing, so the env string can
    # say "cpu" while programs actually compile for (and on) the tunnel
    # terminal — those AOT entries must not share a dir with true local
    # CPU compiles (observed "+prefer-no-scatter not supported … SIGILL"
    # loads in round 4)
    try:
        import jax
        platforms = jax.config.jax_platforms or os.environ.get(
            "JAX_PLATFORMS", "")
    except Exception:
        platforms = os.environ.get("JAX_PLATFORMS", "")
    fp.update(str(platforms).encode())
    try:
        with open("/proc/cpuinfo") as f:
            for line in f:
                if line.startswith("flags"):
                    fp.update(line.encode())
                    break
    except OSError:
        pass
    cache_dir = os.path.join(cache_dir, fp.hexdigest()[:8])
    if _enabled_dir == cache_dir:
        return _enabled_dir
    try:
        import jax
        os.makedirs(cache_dir, exist_ok=True)
        jax.config.update("jax_compilation_cache_dir", cache_dir)
        # cache everything: even "cheap" programs cost a tunnel round trip
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.0)
        try:
            jax.config.update("jax_persistent_cache_min_entry_size_bytes", -1)
        except Exception:
            pass  # knob name varies across jax versions
        _enabled_dir = cache_dir
    except (OSError, AttributeError, ValueError) as e:
        import warnings
        warnings.warn(
            f"persistent XLA compilation cache DISABLED ({e}); every "
            "program will recompile per process", RuntimeWarning)
        return None
    return _enabled_dir


def ensure_runtime(conf=None) -> None:
    """Session-start runtime init (reference RapidsExecutorPlugin.init,
    Plugin.scala:124-154): compilation cache + arrow thread pinning +
    fail-fast device acquisition with HBM pool sizing (device.py);
    semaphore wiring lives in memory/catalog.py."""
    pin_arrow_threads()
    settings = getattr(conf, "settings", None) or {}
    # device init FIRST: it pins jax_platforms to the user's requested
    # platform, which the cache fingerprint below depends on
    from spark_rapids_tpu.device import initialize_device
    initialize_device(conf)
    if COMPILATION_CACHE_ENABLED.get(settings):
        enable_compilation_cache(COMPILATION_CACHE_DIR.get(settings))

"""Cast (reference GpuCast.scala, 861 LoC — ansi off, default mode).

Java/Spark non-ANSI conversion semantics:

* int -> narrower int: two's-complement bit truncation;
* float/double -> integral: truncate toward zero, NaN -> 0, saturate to the
  target's 64-bit range first then bit-narrow (JLS 5.1.3);
* numeric <-> boolean: ``x != 0`` / ``1|0``;
* date <-> timestamp: days*86_400e6 micros (UTC session timezone — the
  reference flags timezone-sensitive casts the same way,
  GpuOverrides tagging for timeZoneId);
* string conversions run on host only (the planner keeps Cast-to/from-string
  off-device for now, like the reference gates string casts behind
  spark.rapids.sql.castStringToFloat.enabled etc., RapidsConf.scala:461-492).
"""
from __future__ import annotations

import numpy as np

from spark_rapids_tpu import types as T
from spark_rapids_tpu.expr.core import Expression, EvalCtx, Val

__all__ = ["Cast", "AnsiCast", "java_double_str"]

_MICROS_PER_DAY = 86_400_000_000


def java_double_str(x: float, float32: bool = False) -> str:
    """Format like Java Double.toString (decimal in [1e-3, 1e7), else
    scientific with 'E')."""
    if np.isnan(x):
        return "NaN"
    if np.isinf(x):
        return "Infinity" if x > 0 else "-Infinity"
    if x == 0:
        return "-0.0" if np.signbit(x) else "0.0"
    ax = abs(x)
    if 1e-3 <= ax < 1e7:
        s = repr(float(np.float32(x))) if float32 else repr(float(x))
        if "e" in s or "E" in s:
            # python switched to sci inside java's decimal window; expand
            s = f"{float(x):f}".rstrip("0")
            if s.endswith("."):
                s += "0"
        elif "." not in s:
            s += ".0"
        return s
    m, e = f"{ax:E}".split("E")
    m = m.rstrip("0").rstrip(".")
    if "." not in m:
        m += ".0"
    exp = int(e)
    return ("-" if x < 0 else "") + f"{m}E{exp}"


class Cast(Expression):
    sql_name = "Cast"

    def __init__(self, child: Expression, to: T.DataType):
        self.children = (child,)
        self.to = to

    def with_new_children(self, children):
        return Cast(children[0], self.to)

    @property
    def dtype(self):
        return self.to

    @property
    def child_type(self) -> T.DataType:
        return self.children[0].dtype

    @property
    def device_supported(self) -> bool:
        return not (isinstance(self.to, T.StringType)
                    ^ isinstance(self.child_type, T.StringType)) \
            or isinstance(self.child_type, T.NullType)

    def __repr__(self):
        return f"cast({self.children[0]!r} as {self.to.name})"

    # ------------------------------------------------------------------
    def _eval(self, vals, ctx: EvalCtx):
        a = vals[0]
        src, dst = a.dtype, self.to
        xp = ctx.xp
        if isinstance(src, T.NullType):
            return ctx.const(None, dst)
        if src == dst:
            return a
        if isinstance(src, T.StringType) or isinstance(dst, T.StringType):
            if ctx.is_device:
                raise NotImplementedError(
                    "string casts are host-only; the planner must not "
                    "schedule them on device")
            return self._eval_string_host(a, ctx)
        validity = a.validity
        d = a.data
        if isinstance(src, T.BooleanType):
            data = d.astype(dst.np_dtype)
        elif isinstance(dst, T.BooleanType):
            data = d != xp.zeros((), d.dtype)
        elif isinstance(src, T.DateType) and isinstance(dst, T.TimestampType):
            data = d.astype(np.int64) * _MICROS_PER_DAY
        elif isinstance(src, T.TimestampType) and isinstance(dst, T.DateType):
            data = (d // _MICROS_PER_DAY).astype(np.int32)
        elif isinstance(src, (T.DateType, T.TimestampType)) \
                or isinstance(dst, (T.DateType, T.TimestampType)):
            # numeric <-> date/timestamp: reinterpret the raw ticks
            # (Spark: timestamp->long is seconds; keep that)
            if isinstance(src, T.TimestampType) and dst.integral:
                data = (d // 1_000_000).astype(dst.np_dtype)
            elif isinstance(src, T.TimestampType) and dst.fractional:
                data = (d.astype(np.float64) / 1e6).astype(dst.np_dtype)
            elif isinstance(dst, T.TimestampType) and src.integral:
                data = d.astype(np.int64) * 1_000_000
            elif isinstance(dst, T.TimestampType) and src.fractional:
                data = (d * 1e6).astype(np.int64)
            elif isinstance(src, T.DateType):
                data = d.astype(dst.np_dtype)
            else:
                data = d.astype(np.int32)
        elif dst.integral and src.fractional:
            data = self._float_to_int(xp, d, dst)
        else:
            data = d.astype(dst.np_dtype)
        return ctx.canonical(data, validity, dst)

    @staticmethod
    def _float_to_int(xp, d, dst: T.DataType):
        """JLS 5.1.3 (d2i/d2l): trunc toward zero, NaN->0, saturate at the
        int range for byte/short/int (then bit-narrow, like Scala .toByte)
        or at the long range for long.

        TPU notes (verified on v5e): trunc(inf) emulates to NaN and
        f64->s32 conversion is off-by-one at the boundary, so non-finite
        values are masked out first and all conversions go through s64
        (exact on TPU) with integer-domain clamping.
        """
        finite = xp.isfinite(d)
        t = xp.trunc(xp.where(finite, d, xp.zeros((), d.dtype)))
        hi = np.float64(2.0 ** 63)
        big_pos = d >= hi          # includes +inf; NaN compares false
        big_neg = d <= -hi
        t = xp.clip(t, -hi, hi)
        with np.errstate(invalid="ignore"):
            as64 = t.astype(np.int64)
        as64 = xp.where(big_pos, np.int64(2 ** 63 - 1), as64)
        as64 = xp.where(big_neg, np.int64(-(2 ** 63)), as64)
        if isinstance(dst, T.LongType):
            return as64
        as64 = xp.clip(as64, np.int64(-(2 ** 31)), np.int64(2 ** 31 - 1))
        return as64.astype(np.int32).astype(dst.np_dtype)

    # ------------------------------------------------------------------
    # host-only string paths (oracle)
    # ------------------------------------------------------------------
    def _eval_string_host(self, a: Val, ctx: EvalCtx):
        src, dst = a.dtype, self.to
        n = ctx.capacity
        if isinstance(dst, T.StringType):
            out = np.empty(n, dtype=object)
            validity = a.validity.copy()
            for i in range(n):
                if not validity[i]:
                    out[i] = None
                    continue
                out[i] = self._value_to_string(a.data[i], src)
            return Val(out, validity, None, dst)
        # string -> typed
        out_np = np.zeros(n, dtype=dst.np_dtype)
        validity = np.zeros(n, dtype=np.bool_)
        for i in range(n):
            if not a.validity[i]:
                continue
            v = self._string_to_value(a.data[i], dst)
            if v is not None:
                out_np[i] = v
                validity[i] = True
        return Val(out_np, validity, None, dst)

    @staticmethod
    def _value_to_string(v, src: T.DataType) -> str:
        import datetime as _dt
        if isinstance(src, T.BooleanType):
            return "true" if v else "false"
        if isinstance(src, T.FloatType):
            return java_double_str(float(v), float32=True)
        if isinstance(src, T.DoubleType):
            return java_double_str(float(v))
        if isinstance(src, T.DateType):
            return (_dt.date(1970, 1, 1) + _dt.timedelta(days=int(v))).isoformat()
        if isinstance(src, T.TimestampType):
            ts = _dt.datetime(1970, 1, 1) + _dt.timedelta(microseconds=int(v))
            s = ts.strftime("%Y-%m-%d %H:%M:%S")
            if ts.microsecond:
                s += f".{ts.microsecond:06d}".rstrip("0")
            return s
        return str(int(v))

    @staticmethod
    def _string_to_value(s: str, dst: T.DataType):
        import datetime as _dt
        s = s.strip()
        try:
            if isinstance(dst, T.BooleanType):
                ls = s.lower()
                if ls in ("t", "true", "y", "yes", "1"):
                    return True
                if ls in ("f", "false", "n", "no", "0"):
                    return False
                return None
            if dst.integral:
                return np.dtype(dst.np_dtype).type(int(s))
            if dst.fractional:
                return np.dtype(dst.np_dtype).type(float(s))
            if isinstance(dst, T.DateType):
                return (_dt.date.fromisoformat(s[:10]) - _dt.date(1970, 1, 1)).days
            if isinstance(dst, T.TimestampType):
                ts = _dt.datetime.fromisoformat(s.replace(" ", "T"))
                return int((ts - _dt.datetime(1970, 1, 1)).total_seconds() * 1e6)
        except (ValueError, OverflowError):
            return None
        return None


class AnsiCast(Cast):
    """ANSI-mode cast (reference GpuCast.scala ansi variants,
    RapidsConf.scala:461-492 incompat flags): overflow or unparseable
    input RAISES instead of wrapping/yielding null.  Host-only — the
    device path has no error channel, exactly why the reference gates
    ansi casts behind incompat flags."""

    sql_name = "AnsiCast"

    def with_new_children(self, children):
        return AnsiCast(children[0], self.to)

    @property
    def device_supported(self) -> bool:
        return False

    def _eval(self, vals, ctx: EvalCtx):
        a = vals[0]
        src, dst = a.dtype, self.to
        if dst.integral and (src.fractional or src.integral):
            info = np.iinfo(dst.np_dtype)
            d = a.data[a.validity]
            bad = (d < info.min) | (d > info.max)
            if src.fractional:
                bad |= ~np.isfinite(d)
            if np.any(bad):
                raise ArithmeticError(
                    f"Casting to {dst.name} causes overflow (ANSI mode)")
        if isinstance(src, T.StringType) and not isinstance(dst, T.StringType):
            for i in range(ctx.capacity):
                if a.validity[i] and \
                        self._string_to_value(a.data[i], dst) is None:
                    raise ValueError(
                        f"invalid input for ANSI cast to {dst.name}: "
                        f"{a.data[i]!r}")
        return super()._eval(vals, ctx)

"""Date/time expressions (reference datetimeExpressions.scala, 575 LoC).

Dates are int32 days since epoch, timestamps int64 micros (UTC session
timezone — the reference likewise only supports UTC-safe operations and
tags the rest off-GPU).  Civil-date decomposition uses the days-from-civil
algorithm (Howard Hinnant) as pure integer ops so the same kernel runs on
numpy and under jax.jit on TPU.
"""
from __future__ import annotations

import numpy as np

from spark_rapids_tpu import types as T
from spark_rapids_tpu.expr.core import Expression, EvalCtx

__all__ = ["Year", "Month", "DayOfMonth", "DayOfWeek", "DayOfYear",
           "Quarter", "Hour", "Minute", "Second", "DateAdd", "DateSub",
           "DateDiff", "ToDate"]

_MICROS_PER_DAY = 86_400_000_000


def civil_from_days(z, xp):
    """days-since-epoch -> (year, month, day), vectorized integer math."""
    z = z.astype(np.int64) + 719468
    # numpy/jax `//` is floor division, so no trunc-division adjustment
    era = z // 146097
    doe = z - era * 146097                                    # [0, 146096]
    yoe = (doe - doe // 1460 + doe // 36524 - doe // 146096) // 365
    y = yoe + era * 400
    doy = doe - (365 * yoe + yoe // 4 - yoe // 100)           # [0, 365]
    mp = (5 * doy + 2) // 153                                 # [0, 11]
    d = doy - (153 * mp + 2) // 5 + 1                         # [1, 31]
    m = xp.where(mp < 10, mp + 3, mp - 9)                     # [1, 12]
    y = xp.where(m <= 2, y + 1, y)
    return y.astype(np.int32), m.astype(np.int32), d.astype(np.int32)


class _DateExtract(Expression):
    def __init__(self, child: Expression):
        self.children = (child,)

    @property
    def dtype(self):
        return T.IntegerType()

    def coerced(self):
        from spark_rapids_tpu.expr.cast import Cast
        c = self.children[0]
        if isinstance(c.dtype, T.TimestampType):
            return type(self)(Cast(c, T.DateType()))
        if isinstance(c.dtype, T.StringType):
            return type(self)(Cast(c, T.DateType()))
        return self

    def _eval(self, vals, ctx):
        a = vals[0]
        y, m, d = civil_from_days(a.data, ctx.xp)
        return ctx.canonical(self._pick(y, m, d, a.data, ctx.xp),
                             a.validity, T.IntegerType())


class Year(_DateExtract):
    sql_name = "Year"

    def _pick(self, y, m, d, days, xp):
        return y


class Month(_DateExtract):
    sql_name = "Month"

    def _pick(self, y, m, d, days, xp):
        return m


class DayOfMonth(_DateExtract):
    sql_name = "DayOfMonth"

    def _pick(self, y, m, d, days, xp):
        return d


class DayOfWeek(_DateExtract):
    """Spark dayofweek: 1 = Sunday ... 7 = Saturday."""
    sql_name = "DayOfWeek"

    def _pick(self, y, m, d, days, xp):
        # 1970-01-01 was a Thursday (dow 5 in Spark's 1=Sunday scheme)
        return ((days.astype(np.int64) + 4) % 7 + 1).astype(np.int32)


class DayOfYear(_DateExtract):
    sql_name = "DayOfYear"

    def _pick(self, y, m, d, days, xp):
        jan1 = days_from_civil(y, xp.ones_like(m), xp.ones_like(d), xp)
        return (days.astype(np.int64) - jan1 + 1).astype(np.int32)


class Quarter(_DateExtract):
    sql_name = "Quarter"

    def _pick(self, y, m, d, days, xp):
        return (m - 1) // 3 + 1


def days_from_civil(y, m, d, xp):
    """(year, month, day) -> days since epoch (Hinnant days_from_civil)."""
    y = y.astype(np.int64)
    m = m.astype(np.int64)
    d = d.astype(np.int64)
    y = y - (m <= 2)
    era = y // 400  # floor division
    yoe = y - era * 400
    mp = xp.where(m > 2, m - 3, m + 9)
    doy = (153 * mp + 2) // 5 + d - 1
    doe = yoe * 365 + yoe // 4 - yoe // 100 + doy
    return era * 146097 + doe - 719468


class _TimeExtract(Expression):
    def __init__(self, child: Expression):
        self.children = (child,)

    @property
    def dtype(self):
        return T.IntegerType()

    def _eval(self, vals, ctx):
        a = vals[0]
        micros_in_day = a.data - (a.data // _MICROS_PER_DAY) * _MICROS_PER_DAY
        secs = micros_in_day // 1_000_000
        return ctx.canonical(self._pick(secs, ctx.xp).astype(np.int32),
                             a.validity, T.IntegerType())


class Hour(_TimeExtract):
    sql_name = "Hour"

    def _pick(self, secs, xp):
        return secs // 3600


class Minute(_TimeExtract):
    sql_name = "Minute"

    def _pick(self, secs, xp):
        return (secs // 60) % 60


class Second(_TimeExtract):
    sql_name = "Second"

    def _pick(self, secs, xp):
        return secs % 60


class DateAdd(Expression):
    sql_name = "DateAdd"

    def __init__(self, start: Expression, days: Expression):
        self.children = (start, days)

    @property
    def dtype(self):
        return T.DateType()

    def _eval(self, vals, ctx):
        a, b = vals
        validity = a.validity & b.validity
        data = (a.data + b.data.astype(np.int32)).astype(np.int32)
        return ctx.canonical(data, validity, T.DateType())


class DateSub(Expression):
    sql_name = "DateSub"

    def __init__(self, start: Expression, days: Expression):
        self.children = (start, days)

    @property
    def dtype(self):
        return T.DateType()

    def _eval(self, vals, ctx):
        a, b = vals
        validity = a.validity & b.validity
        data = (a.data - b.data.astype(np.int32)).astype(np.int32)
        return ctx.canonical(data, validity, T.DateType())


class DateDiff(Expression):
    """datediff(end, start) in days, IntegerType."""
    sql_name = "DateDiff"

    def __init__(self, end: Expression, start: Expression):
        self.children = (end, start)

    @property
    def dtype(self):
        return T.IntegerType()

    def coerced(self):
        from spark_rapids_tpu.expr.cast import Cast
        kids = [Cast(c, T.DateType()) if not isinstance(c.dtype, T.DateType)
                else c for c in self.children]
        return DateDiff(*kids)

    def _eval(self, vals, ctx):
        a, b = vals
        validity = a.validity & b.validity
        return ctx.canonical((a.data - b.data).astype(np.int32), validity,
                             T.IntegerType())


class ToDate(Expression):
    sql_name = "ToDate"

    def __init__(self, child: Expression):
        self.children = (child,)

    def coerced(self):
        from spark_rapids_tpu.expr.cast import Cast
        return Cast(self.children[0], T.DateType())

    @property
    def dtype(self):
        return T.DateType()

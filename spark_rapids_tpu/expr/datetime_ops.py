"""Date/time expressions (reference datetimeExpressions.scala, 575 LoC).

Dates are int32 days since epoch, timestamps int64 micros (UTC session
timezone — the reference likewise only supports UTC-safe operations and
tags the rest off-GPU).  Civil-date decomposition uses the days-from-civil
algorithm (Howard Hinnant) as pure integer ops so the same kernel runs on
numpy and under jax.jit on TPU.
"""
from __future__ import annotations

import numpy as np

from spark_rapids_tpu import types as T
from spark_rapids_tpu.expr.core import Expression, EvalCtx

__all__ = ["Year", "Month", "DayOfMonth", "DayOfWeek", "DayOfYear",
           "Quarter", "Hour", "Minute", "Second", "DateAdd", "DateSub",
           "DateDiff", "ToDate", "AddMonths", "LastDay", "NextDay",
           "TruncDate", "WeekOfYear", "FromUnixTime", "UnixTimestamp",
           "DateFormatClass", "MonthsBetween",
           "ParseDateFixed"]

_MICROS_PER_DAY = 86_400_000_000


def civil_from_days(z, xp):
    """days-since-epoch -> (year, month, day), vectorized integer math."""
    z = z.astype(np.int64) + 719468
    # numpy/jax `//` is floor division, so no trunc-division adjustment
    era = z // 146097
    doe = z - era * 146097                                    # [0, 146096]
    yoe = (doe - doe // 1460 + doe // 36524 - doe // 146096) // 365
    y = yoe + era * 400
    doy = doe - (365 * yoe + yoe // 4 - yoe // 100)           # [0, 365]
    mp = (5 * doy + 2) // 153                                 # [0, 11]
    d = doy - (153 * mp + 2) // 5 + 1                         # [1, 31]
    m = xp.where(mp < 10, mp + 3, mp - 9)                     # [1, 12]
    y = xp.where(m <= 2, y + 1, y)
    return y.astype(np.int32), m.astype(np.int32), d.astype(np.int32)


class _DateExtract(Expression):
    def __init__(self, child: Expression):
        self.children = (child,)

    @property
    def dtype(self):
        return T.IntegerType()

    def coerced(self):
        from spark_rapids_tpu.expr.cast import Cast
        c = self.children[0]
        if isinstance(c.dtype, T.TimestampType):
            return type(self)(Cast(c, T.DateType()))
        if isinstance(c.dtype, T.StringType):
            return type(self)(Cast(c, T.DateType()))
        return self

    def _eval(self, vals, ctx):
        a = vals[0]
        y, m, d = civil_from_days(a.data, ctx.xp)
        return ctx.canonical(self._pick(y, m, d, a.data, ctx.xp),
                             a.validity, T.IntegerType())


class Year(_DateExtract):
    sql_name = "Year"

    def _pick(self, y, m, d, days, xp):
        return y


class Month(_DateExtract):
    sql_name = "Month"

    def _pick(self, y, m, d, days, xp):
        return m


class DayOfMonth(_DateExtract):
    sql_name = "DayOfMonth"

    def _pick(self, y, m, d, days, xp):
        return d


class DayOfWeek(_DateExtract):
    """Spark dayofweek: 1 = Sunday ... 7 = Saturday."""
    sql_name = "DayOfWeek"

    def _pick(self, y, m, d, days, xp):
        # 1970-01-01 was a Thursday (dow 5 in Spark's 1=Sunday scheme)
        return ((days.astype(np.int64) + 4) % 7 + 1).astype(np.int32)


class DayOfYear(_DateExtract):
    sql_name = "DayOfYear"

    def _pick(self, y, m, d, days, xp):
        jan1 = days_from_civil(y, xp.ones_like(m), xp.ones_like(d), xp)
        return (days.astype(np.int64) - jan1 + 1).astype(np.int32)


class Quarter(_DateExtract):
    sql_name = "Quarter"

    def _pick(self, y, m, d, days, xp):
        return (m - 1) // 3 + 1


def days_from_civil(y, m, d, xp):
    """(year, month, day) -> days since epoch (Hinnant days_from_civil)."""
    y = y.astype(np.int64)
    m = m.astype(np.int64)
    d = d.astype(np.int64)
    y = y - (m <= 2)
    era = y // 400  # floor division
    yoe = y - era * 400
    mp = xp.where(m > 2, m - 3, m + 9)
    doy = (153 * mp + 2) // 5 + d - 1
    doe = yoe * 365 + yoe // 4 - yoe // 100 + doy
    return era * 146097 + doe - 719468


class _TimeExtract(Expression):
    def __init__(self, child: Expression):
        self.children = (child,)

    @property
    def dtype(self):
        return T.IntegerType()

    def _eval(self, vals, ctx):
        a = vals[0]
        micros_in_day = a.data - (a.data // _MICROS_PER_DAY) * _MICROS_PER_DAY
        secs = micros_in_day // 1_000_000
        return ctx.canonical(self._pick(secs, ctx.xp).astype(np.int32),
                             a.validity, T.IntegerType())


class Hour(_TimeExtract):
    sql_name = "Hour"

    def _pick(self, secs, xp):
        return secs // 3600


class Minute(_TimeExtract):
    sql_name = "Minute"

    def _pick(self, secs, xp):
        return (secs // 60) % 60


class Second(_TimeExtract):
    sql_name = "Second"

    def _pick(self, secs, xp):
        return secs % 60


class DateAdd(Expression):
    sql_name = "DateAdd"

    def __init__(self, start: Expression, days: Expression):
        self.children = (start, days)

    @property
    def dtype(self):
        return T.DateType()

    def _eval(self, vals, ctx):
        a, b = vals
        validity = a.validity & b.validity
        data = (a.data + b.data.astype(np.int32)).astype(np.int32)
        return ctx.canonical(data, validity, T.DateType())


class DateSub(Expression):
    sql_name = "DateSub"

    def __init__(self, start: Expression, days: Expression):
        self.children = (start, days)

    @property
    def dtype(self):
        return T.DateType()

    def _eval(self, vals, ctx):
        a, b = vals
        validity = a.validity & b.validity
        data = (a.data - b.data.astype(np.int32)).astype(np.int32)
        return ctx.canonical(data, validity, T.DateType())


class DateDiff(Expression):
    """datediff(end, start) in days, IntegerType."""
    sql_name = "DateDiff"

    def __init__(self, end: Expression, start: Expression):
        self.children = (end, start)

    @property
    def dtype(self):
        return T.IntegerType()

    def coerced(self):
        from spark_rapids_tpu.expr.cast import Cast
        kids = [Cast(c, T.DateType()) if not isinstance(c.dtype, T.DateType)
                else c for c in self.children]
        return DateDiff(*kids)

    def _eval(self, vals, ctx):
        a, b = vals
        validity = a.validity & b.validity
        return ctx.canonical((a.data - b.data).astype(np.int32), validity,
                             T.IntegerType())


class ToDate(Expression):
    sql_name = "ToDate"

    def __init__(self, child: Expression):
        self.children = (child,)

    def coerced(self):
        from spark_rapids_tpu.expr.cast import Cast
        return Cast(self.children[0], T.DateType())

    @property
    def dtype(self):
        return T.DateType()


# ---------------------------------------------------------------------------
# round-3 breadth: add_months / months_between / last_day / next_day /
# trunc / weekofyear (device) + from_unixtime / unix_timestamp /
# date_format (string paths host-only)
# (reference datetimeExpressions.scala GpuAddMonths/GpuMonthsBetween/
#  GpuLastDay analogs; string formatting is host-tagged like the
#  reference's timeZoneId-gated expressions)
# ---------------------------------------------------------------------------

def _last_dom(y, m, xp):
    """Last day-of-month for (y, m) vectorized (leap-aware)."""
    lengths = xp.asarray(np.array([31, 28, 31, 30, 31, 30,
                                   31, 31, 30, 31, 30, 31], np.int32))
    base = lengths[m - 1]
    leap = ((y % 4 == 0) & (y % 100 != 0)) | (y % 400 == 0)
    return xp.where((m == 2) & leap, 29, base).astype(np.int32)


class AddMonths(Expression):
    """add_months(date, n): clamps day-of-month to the target month's end
    (Spark semantics)."""

    sql_name = "AddMonths"

    def __init__(self, start: Expression, months: Expression):
        self.children = (start, months)

    @property
    def dtype(self):
        return T.DateType()

    def coerced(self):
        from spark_rapids_tpu.expr.cast import Cast
        s, n = self.children
        if not isinstance(s.dtype, T.DateType):
            s = Cast(s, T.DateType())
        if not isinstance(n.dtype, T.IntegerType):
            n = Cast(n, T.IntegerType())
        return AddMonths(s, n)

    def _eval(self, vals, ctx):
        a, n = vals
        xp = ctx.xp
        y, m, d = civil_from_days(a.data, xp)
        total = (y.astype(np.int64) * 12 + (m - 1)) + n.data.astype(np.int64)
        ny = (total // 12).astype(np.int32)
        nm = (total - ny.astype(np.int64) * 12).astype(np.int32) + 1
        nd = xp.minimum(d, _last_dom(ny, nm, xp))
        validity = a.validity & n.validity
        return ctx.canonical(
            days_from_civil(ny, nm, nd, xp).astype(np.int32), validity,
            T.DateType())


class LastDay(Expression):
    sql_name = "LastDay"

    def __init__(self, child: Expression):
        self.children = (child,)

    @property
    def dtype(self):
        return T.DateType()

    def coerced(self):
        from spark_rapids_tpu.expr.cast import Cast
        c = self.children[0]
        return self if isinstance(c.dtype, T.DateType) \
            else LastDay(Cast(c, T.DateType()))

    def _eval(self, vals, ctx):
        a = vals[0]
        xp = ctx.xp
        y, m, d = civil_from_days(a.data, xp)
        nd = _last_dom(y, m, xp)
        return ctx.canonical(
            days_from_civil(y, m, nd, xp).astype(np.int32), a.validity,
            T.DateType())


_DOW_NAMES = {"MO": 0, "TU": 1, "WE": 2, "TH": 3, "FR": 4, "SA": 5, "SU": 6}


class NextDay(Expression):
    """next_day(date, 'Mon'): first date later than ``date`` falling on
    the given weekday."""

    sql_name = "NextDay"

    def __init__(self, child: Expression, day_of_week: str):
        self.children = (child,)
        self.day_of_week = day_of_week
        key = day_of_week.strip()[:2].upper()
        if key not in _DOW_NAMES:
            raise ValueError(f"bad day of week: {day_of_week!r}")
        self._target = _DOW_NAMES[key]  # Monday=0

    def with_new_children(self, children):
        return NextDay(children[0], self.day_of_week)

    @property
    def dtype(self):
        return T.DateType()

    def coerced(self):
        from spark_rapids_tpu.expr.cast import Cast
        c = self.children[0]
        return self if isinstance(c.dtype, T.DateType) \
            else NextDay(Cast(c, T.DateType()), self.day_of_week)

    def _eval(self, vals, ctx):
        a = vals[0]
        # epoch day 0 = 1970-01-01 = Thursday = 3 (Monday=0)
        dow = (a.data.astype(np.int64) + 3) % 7
        delta = (self._target - dow) % 7
        delta = ctx.xp.where(delta == 0, 7, delta)
        return ctx.canonical((a.data + delta).astype(np.int32), a.validity,
                             T.DateType())


class TruncDate(Expression):
    """trunc(date, fmt): fmt in year|yyyy|yy|quarter|month|mon|mm|week."""

    sql_name = "TruncDate"

    def __init__(self, child: Expression, fmt: str):
        self.children = (child,)
        self.fmt = fmt
        f = fmt.lower()
        if f in ("year", "yyyy", "yy"):
            self._level = "year"
        elif f == "quarter":
            self._level = "quarter"
        elif f in ("month", "mon", "mm"):
            self._level = "month"
        elif f == "week":
            self._level = "week"
        else:
            raise ValueError(f"bad trunc format: {fmt!r}")

    def with_new_children(self, children):
        return TruncDate(children[0], self.fmt)

    @property
    def dtype(self):
        return T.DateType()

    def coerced(self):
        from spark_rapids_tpu.expr.cast import Cast
        c = self.children[0]
        return self if isinstance(c.dtype, T.DateType) \
            else TruncDate(Cast(c, T.DateType()), self.fmt)

    def _eval(self, vals, ctx):
        a = vals[0]
        xp = ctx.xp
        if self._level == "week":  # truncate to Monday
            dow = (a.data.astype(np.int64) + 3) % 7
            data = (a.data - dow).astype(np.int32)
        else:
            y, m, d = civil_from_days(a.data, xp)
            if self._level == "year":
                m = xp.ones_like(m)
            elif self._level == "quarter":
                m = ((m - 1) // 3) * 3 + 1
            data = days_from_civil(y, m, xp.ones_like(d), xp).astype(np.int32)
        return ctx.canonical(data, a.validity, T.DateType())


class WeekOfYear(_DateExtract):
    """ISO-8601 week number (Spark weekofyear)."""

    sql_name = "WeekOfYear"

    def _pick(self, y, m, d, days, xp):
        doy_days = days - days_from_civil(y, xp.ones_like(m),
                                          xp.ones_like(d), xp)
        doy = (doy_days + 1).astype(np.int64)          # 1-based day of year
        dow = ((days.astype(np.int64) + 3) % 7) + 1    # ISO Monday=1

        def p(yy):
            yy = yy.astype(np.int64)
            return (yy + yy // 4 - yy // 100 + yy // 400) % 7

        weeks_in = lambda yy: xp.where(  # noqa: E731
            (p(yy) == 4) | (p(yy - 1) == 3), 53, 52)
        w = (doy - dow + 10) // 7
        w = xp.where(w < 1, weeks_in(y - 1), w)
        w = xp.where((w > 52) & (w > weeks_in(y)), 1, w)
        return w.astype(np.int32)


def _java_fmt_to_strftime(fmt: str) -> str:
    """Translate the common Java SimpleDateFormat patterns to strftime."""
    out = []
    i = 0
    mapping = [("yyyy", "%Y"), ("yy", "%y"), ("MM", "%m"), ("dd", "%d"),
               ("HH", "%H"), ("mm", "%M"), ("ss", "%S"), ("EEEE", "%A"),
               ("EEE", "%a"), ("MMMM", "%B"), ("MMM", "%b"), ("DDD", "%j"),
               ("a", "%p")]
    while i < len(fmt):
        for pat, rep in mapping:
            if fmt.startswith(pat, i):
                out.append(rep)
                i += len(pat)
                break
        else:
            out.append(fmt[i])
            i += 1
    return "".join(out)


class FromUnixTime(Expression):
    """from_unixtime(seconds, fmt) -> formatted string (host-only:
    string formatting has no device kernel, reference gates the same)."""

    sql_name = "FromUnixTime"

    def __init__(self, child: Expression, fmt: str = "yyyy-MM-dd HH:mm:ss"):
        self.children = (child,)
        self.fmt = fmt
        self._strf = _java_fmt_to_strftime(fmt)

    def with_new_children(self, children):
        return FromUnixTime(children[0], self.fmt)

    @property
    def dtype(self):
        return T.StringType()

    @property
    def device_supported(self):
        return False

    def _eval(self, vals, ctx):
        import datetime as _dt
        a = vals[0]
        out = np.empty(ctx.capacity, dtype=object)
        for i in range(ctx.capacity):
            if not a.validity[i]:
                out[i] = None
                continue
            ts = _dt.datetime(1970, 1, 1) + _dt.timedelta(seconds=int(a.data[i]))
            out[i] = ts.strftime(self._strf)
        from spark_rapids_tpu.expr.core import Val
        return Val(out, a.validity, None, T.StringType())


class UnixTimestamp(Expression):
    """unix_timestamp(ts|date|string[, fmt]) -> seconds since epoch.
    Device-supported for timestamp/date inputs; string parsing is
    host-only."""

    sql_name = "UnixTimestamp"

    def __init__(self, child: Expression, fmt: str = "yyyy-MM-dd HH:mm:ss"):
        self.children = (child,)
        self.fmt = fmt
        self._strf = _java_fmt_to_strftime(fmt)

    def with_new_children(self, children):
        return UnixTimestamp(children[0], self.fmt)

    @property
    def dtype(self):
        return T.LongType()

    @property
    def device_supported(self):
        return not isinstance(self.children[0].dtype, T.StringType)

    def _eval(self, vals, ctx):
        a = vals[0]
        if isinstance(a.dtype, T.TimestampType):
            data = a.data // 1_000_000
            return ctx.canonical(data.astype(np.int64), a.validity,
                                 T.LongType())
        if isinstance(a.dtype, T.DateType):
            data = a.data.astype(np.int64) * 86_400
            return ctx.canonical(data, a.validity, T.LongType())
        import datetime as _dt
        out = np.zeros(ctx.capacity, dtype=np.int64)
        validity = np.zeros(ctx.capacity, dtype=np.bool_)
        for i in range(ctx.capacity):
            if not a.validity[i]:
                continue
            try:
                ts = _dt.datetime.strptime(str(a.data[i]), self._strf)
                out[i] = int((ts - _dt.datetime(1970, 1, 1)).total_seconds())
                validity[i] = True
            except ValueError:
                pass
        return ctx.canonical(out, validity, T.LongType())


class DateFormatClass(Expression):
    """date_format(timestamp, fmt) -> string (host-only)."""

    sql_name = "DateFormatClass"

    def __init__(self, child: Expression, fmt: str):
        self.children = (child,)
        self.fmt = fmt
        self._strf = _java_fmt_to_strftime(fmt)

    def with_new_children(self, children):
        return DateFormatClass(children[0], self.fmt)

    @property
    def dtype(self):
        return T.StringType()

    @property
    def device_supported(self):
        return False

    def coerced(self):
        from spark_rapids_tpu.expr.cast import Cast
        c = self.children[0]
        return self if isinstance(c.dtype, T.TimestampType) \
            else DateFormatClass(Cast(c, T.TimestampType()), self.fmt)

    def _eval(self, vals, ctx):
        import datetime as _dt
        a = vals[0]
        out = np.empty(ctx.capacity, dtype=object)
        for i in range(ctx.capacity):
            if not a.validity[i]:
                out[i] = None
                continue
            ts = _dt.datetime(1970, 1, 1) + _dt.timedelta(
                microseconds=int(a.data[i]))
            out[i] = ts.strftime(self._strf)
        from spark_rapids_tpu.expr.core import Val
        return Val(out, a.validity, None, T.StringType())


class MonthsBetween(Expression):
    """months_between(end, start[, roundOff]) over timestamps (Spark:
    whole months when days match or both are month-ends, else +
    (day+time delta)/31; rounded to 8 digits when roundOff)."""

    sql_name = "MonthsBetween"

    def __init__(self, end: Expression, start: Expression,
                 round_off: bool = True):
        self.children = (end, start)
        self.round_off = round_off

    def with_new_children(self, children):
        return MonthsBetween(children[0], children[1], self.round_off)

    @property
    def dtype(self):
        return T.DoubleType()

    def coerced(self):
        from spark_rapids_tpu.expr.cast import Cast
        kids = [c if isinstance(c.dtype, T.TimestampType)
                else Cast(c, T.TimestampType()) for c in self.children]
        return MonthsBetween(*kids, round_off=self.round_off)

    def _eval(self, vals, ctx):
        a, b = vals
        xp = ctx.xp
        validity = a.validity & b.validity

        def parts(v):
            days = v.data // _MICROS_PER_DAY
            sec = (v.data - days * _MICROS_PER_DAY).astype(np.float64) / 1e6
            y, m, d = civil_from_days(days, xp)
            return y.astype(np.int64), m.astype(np.int64), \
                d.astype(np.int64), sec

        y1, m1, d1, s1 = parts(a)
        y2, m2, d2, s2 = parts(b)
        months = ((y1 - y2) * 12 + (m1 - m2)).astype(np.float64)
        last1 = d1 == _last_dom(y1.astype(np.int32), m1.astype(np.int32),
                                xp).astype(np.int64)
        last2 = d2 == _last_dom(y2.astype(np.int32), m2.astype(np.int32),
                                xp).astype(np.int64)
        whole = (d1 == d2) | (last1 & last2)
        sec_per_day = 86_400.0
        frac = ((d1 - d2).astype(np.float64) * sec_per_day + (s1 - s2)) \
            / (31.0 * sec_per_day)
        out = xp.where(whole, months, months + frac)
        if self.round_off:
            out = xp.round(out * 1e8) / 1e8
        return ctx.canonical(out, validity, T.DoubleType())


class ParseDateFixed(Expression):
    """to_date(str, fmt) for FIXED-WIDTH digit formats ("MM/dd/yyyy",
    "MM/yyyy", "yyyy-MM-dd", ...): digits parse straight out of the
    byte matrix at the format's positions in one vectorized device
    program — the mortgage suite's date parsing (reference
    GpuGetTimestamp / specialized to_date paths run these fixed
    formats on device too).  Unparseable rows are null (Spark
    non-ANSI to_date)."""

    sql_name = "ParseDateFixed"

    def __init__(self, child: Expression, fmt: str):
        for tok in ("MM",):
            assert tok in fmt, f"format {fmt!r} needs MM"
        assert "yyyy" in fmt, f"format {fmt!r} needs yyyy"
        self.children = (child,)
        self.fmt = fmt

    def with_new_children(self, children):
        return ParseDateFixed(children[0], self.fmt)

    @property
    def dtype(self):
        return T.DateType()

    def __repr__(self):
        return f"ParseDateFixed({self.children[0]!r}, {self.fmt!r})"

    def _eval(self, vals, ctx):
        import datetime as _dt
        a = vals[0]
        fmt = self.fmt
        if not ctx.is_device:
            py_fmt = fmt.replace("yyyy", "%Y").replace("MM", "%m") \
                        .replace("dd", "%d")
            n = ctx.capacity
            out = np.zeros(n, np.int32)
            validity = np.zeros(n, np.bool_)
            epoch = _dt.date(1970, 1, 1)
            for i in range(n):
                if not a.validity[i]:
                    continue
                sv = a.data[i]
                # fixed-width contract, same as the device branch:
                # strptime alone accepts "1/02/2003" / 2-digit years
                if sv is None or len(sv) != len(fmt):
                    continue
                try:
                    d = _dt.datetime.strptime(sv, py_fmt).date()
                except (ValueError, TypeError):
                    continue
                out[i] = (d - epoch).days
                validity[i] = True
            return ctx.canonical(out, validity, T.DateType())

        xp = ctx.xp
        w = a.data.shape[1]
        flen = len(fmt)

        def at(j):
            return a.data[:, j] if j < w else xp.zeros(
                a.data.shape[0], np.uint8)

        def digits(start, ln):
            val = xp.zeros(a.data.shape[0], np.int32)
            ok = xp.ones(a.data.shape[0], bool)
            for j in range(start, start + ln):
                c = at(j).astype(np.int32)
                ok = ok & (c >= 48) & (c <= 57)
                val = val * 10 + (c - 48)
            return val, ok

        y, ok_y = digits(fmt.index("yyyy"), 4)
        m, ok_m = digits(fmt.index("MM"), 2)
        if "dd" in fmt:
            d, ok_d = digits(fmt.index("dd"), 2)
        else:
            d = xp.ones(a.data.shape[0], np.int32)
            ok_d = xp.ones(a.data.shape[0], bool)
        seps_ok = xp.ones(a.data.shape[0], bool)
        for j, ch in enumerate(fmt):
            if ch not in "yMd":
                seps_ok = seps_ok & (at(j) == ord(ch))
        valid = a.validity & (a.lengths == flen) & ok_y & ok_m & ok_d \
            & seps_ok & (m >= 1) & (m <= 12) & (d >= 1) \
            & (d <= _last_dom(y, xp.clip(m, 1, 12), xp))
        # days-from-civil (Hinnant): exact integer arithmetic, no python
        # date objects on the hot path
        y2 = y - (m <= 2)
        era = xp.floor_divide(y2, 400)
        yoe = y2 - era * 400
        mp = xp.where(m > 2, m - 3, m + 9)
        doy = (153 * mp + 2) // 5 + d - 1
        doe = yoe * 365 + yoe // 4 - yoe // 100 + doy
        days = (era * 146097 + doe - 719468).astype(np.int32)
        return ctx.canonical(xp.where(valid, days, 0), valid, T.DateType())

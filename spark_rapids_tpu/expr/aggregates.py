"""Aggregate function expressions (reference AggregateFunctions.scala:531).

Declarative nodes: they do not evaluate elementwise.  The aggregate execs
(CPU oracle and TPU) lower each into the reference's three-phase shape
(aggregate.scala update/merge/final aggregates):

* ``update_ops``  — per-batch segmented ops over the input column(s);
* ``merge_ops``   — ops combining partial results across batches/partitions;
* ``final_expr``  — expression over the intermediate columns producing the
  result (e.g. Average = sum / count with double division, null on 0 count).

The intermediate layout is one column per update op.
"""
from __future__ import annotations

from spark_rapids_tpu import types as T
from spark_rapids_tpu.expr.core import Expression, BoundReference, Literal

__all__ = ["AggregateFunction", "Sum", "Count", "CountStar", "Min", "Max",
           "Percentile",
           "Average", "First", "Last", "CountDistinct", "stddev_samp",
           "is_aggregate", "has_aggregate"]


class AggregateFunction(Expression):
    """Base for aggregate functions. ``children[0]`` is the input (absent
    for COUNT(*))."""

    #: segmented op names for the update phase, one intermediate column each
    update_ops: tuple[str, ...] = ()
    #: op names merging intermediates (same arity as update_ops)
    merge_ops: tuple[str, ...] = ()

    def __init__(self, child: Expression):
        self.children = (child,)

    @property
    def input(self) -> Expression:
        return self.children[0]

    def intermediate_types(self) -> list[T.DataType]:
        raise NotImplementedError

    def final_expr(self, offsets: list[int]) -> Expression:
        """Expression over intermediate columns bound at ``offsets``."""
        raise NotImplementedError

    def _eval(self, vals, ctx):
        raise TypeError(f"{self.sql_name} must be planned by an aggregate "
                        "exec, not evaluated elementwise")


def is_aggregate(e: Expression) -> bool:
    return isinstance(e, AggregateFunction)


def has_aggregate(e: Expression) -> bool:
    if is_aggregate(e):
        return True
    return any(has_aggregate(c) for c in e.children)


class Sum(AggregateFunction):
    """Spark Sum: long for integral input, double for fractional; null on
    empty/all-null input; integral overflow wraps (non-ANSI)."""
    sql_name = "Sum"
    update_ops = ("sum",)
    merge_ops = ("sum",)

    @property
    def dtype(self):
        return T.LongType() if self.input.dtype.integral else T.DoubleType()

    @property
    def nullable(self):
        return True

    def coerced(self):
        from spark_rapids_tpu.expr.cast import Cast
        t = self.input.dtype
        if t.integral and not isinstance(t, T.LongType):
            return Sum(Cast(self.input, T.LongType()))
        if isinstance(t, T.FloatType):
            return Sum(Cast(self.input, T.DoubleType()))
        if not t.numeric:
            raise TypeError(f"sum over {t}")
        return self

    def intermediate_types(self):
        return [self.dtype]

    def final_expr(self, offsets):
        return BoundReference(offsets[0], self.dtype, True)


class Count(AggregateFunction):
    sql_name = "Count"
    update_ops = ("count",)
    merge_ops = ("sum",)

    @property
    def dtype(self):
        return T.LongType()

    @property
    def nullable(self):
        return False

    def intermediate_types(self):
        return [T.LongType()]

    def final_expr(self, offsets):
        from spark_rapids_tpu.expr.conditional import Coalesce
        return Coalesce(BoundReference(offsets[0], T.LongType(), True),
                        Literal(0, T.LongType()))


class CountStar(Count):
    sql_name = "CountStar"
    update_ops = ("count_star",)

    def __init__(self):
        self.children = ()

    @property
    def input(self):
        return None

    def with_new_children(self, children):
        return self

    def __repr__(self):
        return "count(*)"


class Min(AggregateFunction):
    sql_name = "Min"
    update_ops = ("min",)
    merge_ops = ("min",)

    @property
    def dtype(self):
        return self.input.dtype

    def intermediate_types(self):
        return [self.dtype]

    def final_expr(self, offsets):
        return BoundReference(offsets[0], self.dtype, True)


class Max(AggregateFunction):
    sql_name = "Max"
    update_ops = ("max",)
    merge_ops = ("max",)

    @property
    def dtype(self):
        return self.input.dtype

    def intermediate_types(self):
        return [self.dtype]

    def final_expr(self, offsets):
        return BoundReference(offsets[0], self.dtype, True)


class Average(AggregateFunction):
    """Spark Average: double result = sum/count, null when count == 0."""
    sql_name = "Average"
    update_ops = ("sum", "count")
    merge_ops = ("sum", "sum")

    @property
    def dtype(self):
        return T.DoubleType()

    def coerced(self):
        from spark_rapids_tpu.expr.cast import Cast
        t = self.input.dtype
        if not t.numeric:
            raise TypeError(f"avg over {t}")
        if not isinstance(t, T.DoubleType):
            return Average(Cast(self.input, T.DoubleType()))
        return self

    def intermediate_types(self):
        return [T.DoubleType(), T.LongType()]

    def final_expr(self, offsets):
        from spark_rapids_tpu.expr.arithmetic import Divide
        from spark_rapids_tpu.expr.cast import Cast
        s = BoundReference(offsets[0], T.DoubleType(), True)
        c = BoundReference(offsets[1], T.LongType(), True)
        # Divide yields null when count == 0 (DivModLike) — exactly Spark avg
        return Divide(s, Cast(c, T.DoubleType()))


class CountDistinct(Expression):
    """count(DISTINCT e[, e2, ...]) — a marker rewritten by
    ``GroupedData.agg`` into dedupe-then-count plans (Spark plans the same
    via Expand + two-phase aggregation).  It never reaches an aggregate
    exec directly."""
    sql_name = "CountDistinct"

    def __init__(self, *children: Expression):
        assert children, "count(distinct) needs at least one expression"
        self.children = tuple(children)

    def with_new_children(self, children):
        return CountDistinct(*children)

    @property
    def dtype(self):
        return T.LongType()

    @property
    def nullable(self):
        return False

    def _eval(self, vals, ctx):
        raise TypeError(
            "count(distinct) is only valid directly inside "
            "GroupedData.agg(...), which rewrites it; it cannot be "
            "evaluated elementwise or nested in other expressions")


def stddev_samp(e: Expression) -> Expression:
    """Sample standard deviation as composed aggregates:
    sqrt((sum(x^2) - sum(x)^2/n) / (n-1)); null on empty input, NaN for a
    single row (Spark CentralMomentAgg semantics).  Composed from
    Sum/Count so the three-phase aggregate machinery needs no new op
    (reference expresses stddev over cuDF's M2; here the sum-of-squares
    form keeps the segmented-op set minimal and differential tests
    compare doubles approximately)."""
    from spark_rapids_tpu.expr.cast import Cast
    from spark_rapids_tpu.expr.conditional import If
    from spark_rapids_tpu.expr.math_ops import Sqrt
    from spark_rapids_tpu.expr.predicates import EqualTo
    from spark_rapids_tpu.expr.predicates import LessThan
    d = Cast(e, T.DoubleType())
    n = Count(d)
    nd = Cast(n, T.DoubleType())
    s = Sum(d)
    s2 = Sum(d * d)
    var = (s2 - s * s / nd) / (nd - Literal(1.0, T.DoubleType()))
    # catastrophic cancellation on a constant column can leave var a tiny
    # negative; Spark's M2 form returns exactly 0.0 there, so clamp
    # (LessThan is false for NaN, which passes through untouched)
    zero = Literal(0.0, T.DoubleType())
    var = If(LessThan(var, zero), zero, var)
    return If(EqualTo(n, Literal(1, T.LongType())),
              Literal(float("nan"), T.DoubleType()), Sqrt(var))


class First(AggregateFunction):
    sql_name = "First"
    update_ops = ("first",)
    merge_ops = ("first",)

    def __init__(self, child: Expression, ignore_nulls: bool = False):
        self.children = (child,)
        self.ignore_nulls = ignore_nulls
        if ignore_nulls:
            self.update_ops = ("first_non_null",)
            self.merge_ops = ("first_non_null",)

    def with_new_children(self, children):
        return First(children[0], self.ignore_nulls)

    @property
    def dtype(self):
        return self.input.dtype

    def intermediate_types(self):
        return [self.dtype]

    def final_expr(self, offsets):
        return BoundReference(offsets[0], self.dtype, True)


class Last(AggregateFunction):
    sql_name = "Last"
    update_ops = ("last",)
    merge_ops = ("last",)

    def __init__(self, child: Expression, ignore_nulls: bool = False):
        self.children = (child,)
        self.ignore_nulls = ignore_nulls
        if ignore_nulls:
            self.update_ops = ("last_non_null",)
            self.merge_ops = ("last_non_null",)

    def with_new_children(self, children):
        return Last(children[0], self.ignore_nulls)

    @property
    def dtype(self):
        return self.input.dtype

    def intermediate_types(self):
        return [self.dtype]

    def final_expr(self, offsets):
        return BoundReference(offsets[0], self.dtype, True)


class Percentile(AggregateFunction):
    """Exact percentile with linear interpolation at q*(n-1) (Spark
    Percentile, ObjectHashAggregate-backed in the reference plugin's
    fallback list).  HOLISTIC: there is no mergeable intermediate — the
    planner aggregates the whole input in one pass (exec/aggregate.py
    _holistic), so partial/final split and mesh lowering are refused."""

    sql_name = "Percentile"
    update_ops = ("percentile",)
    merge_ops = ()          # no merge exists: holistic
    requires_complete = True

    def __init__(self, child: Expression, q: float):
        super().__init__(child)
        if not (0.0 <= float(q) <= 1.0):
            raise ValueError(f"percentile fraction must be in [0,1]: {q}")
        self.q = float(q)

    def with_new_children(self, children):
        return Percentile(children[0], self.q)

    @property
    def dtype(self):
        return T.DoubleType()

    def coerced(self):
        from spark_rapids_tpu.expr.cast import Cast
        t = self.input.dtype
        if not t.numeric:
            raise TypeError(f"percentile over {t}")
        if not isinstance(t, T.DoubleType):
            return Percentile(Cast(self.input, T.DoubleType()), self.q)
        return self

    def intermediate_types(self):
        return [T.DoubleType()]

    def final_expr(self, offsets):
        return BoundReference(offsets[0], T.DoubleType(), True)

    def __repr__(self):
        return f"Percentile({self.children[0]!r}, {self.q})"

"""Regular-expression expressions — host-only kernels.

Reference: the regexp family lives behind a shim expr and runs only where
cuDF grew regex support (Spark300Shims.scala:235 registers GpuRLike etc.
per shim); this engine keeps the family host-tagged (device_supported =
False) so the planner schedules the enclosing exec on the CPU oracle —
the same "refuse what can't match" strategy the tagging framework exists
for (SURVEY §7).

Patterns are Java-regex syntax; they are translated approximately to
Python `re` (common constructs are identical — character classes,
quantifiers, groups, anchors).  Known divergences (possessive
quantifiers, \\p{javaX} classes) raise at construction.
"""
from __future__ import annotations

import re

import numpy as np

from spark_rapids_tpu import types as T
from spark_rapids_tpu.expr.core import Expression, EvalCtx, Val

__all__ = ["RLike", "RegExpReplace", "RegExpExtract"]

_UNSUPPORTED = re.compile(r"\*\+|\+\+|\}\+|\\p\{java")


def _compile(pattern: str):
    if _UNSUPPORTED.search(pattern):
        raise ValueError(
            f"Java-regex construct not supported in host regex: {pattern!r}")
    return re.compile(pattern)


class _RegExpBase(Expression):
    @property
    def device_supported(self) -> bool:
        return False  # host-only: planner falls the exec back (explain `!`)


class RLike(_RegExpBase):
    """str RLIKE pattern (unanchored search, Java semantics)."""

    sql_name = "RLike"

    def __init__(self, child: Expression, pattern: str):
        self.children = (child,)
        self.pattern = pattern
        self._re = _compile(pattern)

    def with_new_children(self, children):
        return RLike(children[0], self.pattern)

    @property
    def dtype(self):
        return T.BooleanType()

    def _eval(self, vals, ctx: EvalCtx):
        a = vals[0]
        out = np.zeros(ctx.capacity, dtype=np.bool_)
        for i in range(ctx.capacity):
            if a.validity[i]:
                out[i] = self._re.search(str(a.data[i])) is not None
        return ctx.canonical(out, a.validity, T.BooleanType())

    def __repr__(self):
        return f"RLike({self.children[0]!r}, {self.pattern!r})"


class RegExpReplace(_RegExpBase):
    """regexp_replace(str, pattern, replacement) — replaces ALL matches;
    Java $1 backreferences are translated to Python \\1."""

    sql_name = "RegExpReplace"

    def __init__(self, child: Expression, pattern: str, replacement: str):
        self.children = (child,)
        self.pattern = pattern
        self.replacement = replacement
        self._re = _compile(pattern)
        self._repl = re.sub(r"\$(\d+)", r"\\\1", replacement)

    def with_new_children(self, children):
        return RegExpReplace(children[0], self.pattern, self.replacement)

    @property
    def dtype(self):
        return T.StringType()

    def _eval(self, vals, ctx: EvalCtx):
        a = vals[0]
        out = np.empty(ctx.capacity, dtype=object)
        for i in range(ctx.capacity):
            out[i] = self._re.sub(self._repl, str(a.data[i])) \
                if a.validity[i] else None
        return Val(out, a.validity, None, T.StringType())

    def __repr__(self):
        return (f"RegExpReplace({self.children[0]!r}, {self.pattern!r}, "
                f"{self.replacement!r})")


class RegExpExtract(_RegExpBase):
    """regexp_extract(str, pattern, idx): group ``idx`` of the first
    match; empty string when no match (Spark semantics)."""

    sql_name = "RegExpExtract"

    def __init__(self, child: Expression, pattern: str, idx: int = 1):
        self.children = (child,)
        self.pattern = pattern
        self.idx = idx
        self._re = _compile(pattern)

    def with_new_children(self, children):
        return RegExpExtract(children[0], self.pattern, self.idx)

    @property
    def dtype(self):
        return T.StringType()

    def _eval(self, vals, ctx: EvalCtx):
        a = vals[0]
        out = np.empty(ctx.capacity, dtype=object)
        for i in range(ctx.capacity):
            if not a.validity[i]:
                out[i] = None
                continue
            m = self._re.search(str(a.data[i]))
            if m is None:
                out[i] = ""
            else:
                g = m.group(self.idx)
                out[i] = g if g is not None else ""
        return Val(out, a.validity, None, T.StringType())

    def __repr__(self):
        return (f"RegExpExtract({self.children[0]!r}, {self.pattern!r}, "
                f"{self.idx})")

"""Array (collection) expressions over ArrayType columns.

Reference: complexTypeExtractors — GetArrayItem / GetMapValue
(SURVEY §2.4, sql-plugin complexTypeExtractors) plus the collection
functions Spark exposes (size, array_contains).  Device arrays are
padded element matrices + lengths (columnar/column.py), so every op
here is a dense vectorized kernel — a row-indexed gather
(element_at), a length read (size), or a masked any-compare
(array_contains).
"""
from __future__ import annotations

import numpy as np

from spark_rapids_tpu import types as T
from spark_rapids_tpu.expr.core import Expression, Val

__all__ = ["GetArrayItem", "Size", "ArrayContains", "GetMapValue",
           "MapKeys", "MapValues", "MapLookup"]


class GetMapValue(Expression):
    """map[key] (reference GetMapValue, complexTypeExtractors).

    HOST-ONLY: MapType has no device representation (types.MapType), so
    the planner tags any plan node evaluating this as host — explain
    shows the fallback reason, the reference's degradation model."""

    sql_name = "GetMapValue"

    def __init__(self, child: Expression, key: Expression):
        self.children = (child, key)

    @property
    def dtype(self):
        mt = self.children[0].dtype
        assert isinstance(mt, T.MapType), mt
        return mt.value_type

    @property
    def device_supported(self) -> bool:
        return False

    def _eval(self, vals, ctx):
        assert not ctx.is_device, "GetMapValue is host-only"
        from spark_rapids_tpu.host.batch import HostColumn
        m, k = vals
        vt = self.dtype
        # route through HostColumn.from_values so value types get the
        # engine's encodings (date -> days, timestamp -> micros, arrays
        # -> lists) instead of raw python objects in a typed buffer
        values = [m.data[i].get(k.data[i])
                  if (m.validity[i] and k.validity[i]) else None
                  for i in range(ctx.capacity)]
        hc = HostColumn.from_values(values, vt)
        return Val(hc.data, hc.validity, None, vt)


class GetArrayItem(Expression):
    """arr[index] (0-based ordinal, Spark GetArrayItem semantics):
    null when the input is null, the index is null, or out of range."""

    sql_name = "GetArrayItem"

    def __init__(self, child: Expression, index: Expression):
        self.children = (child, index)

    @property
    def dtype(self):
        at = self.children[0].dtype
        assert isinstance(at, T.ArrayType), at
        return at.element_type

    def _eval(self, vals, ctx):
        arr, idx = vals
        elem = self.dtype
        if not ctx.is_device:
            n = ctx.capacity
            out = np.zeros(n, dtype=elem.np_dtype)
            validity = np.zeros(n, dtype=np.bool_)
            for i in range(n):
                if not (arr.validity[i] and idx.validity[i]):
                    continue
                j = int(idx.data[i])
                a = arr.data[i]
                if 0 <= j < len(a):
                    out[i] = a[j]
                    validity[i] = True
            return ctx.canonical(out, validity, elem)
        xp = ctx.xp
        w = arr.data.shape[1]
        j = idx.data.astype(np.int32)
        in_range = (j >= 0) & (j < arr.lengths)
        validity = arr.validity & idx.validity & in_range
        jc = xp.clip(j, 0, w - 1)
        picked = xp.take_along_axis(arr.data, jc[:, None], axis=1)[:, 0]
        data = xp.where(validity, picked, xp.zeros((), arr.data.dtype))
        return ctx.canonical(data, validity, elem)


class Size(Expression):
    """size(arr): element count; Spark's legacy default returns -1 for
    null input (spark.sql.legacy.sizeOfNull, the 3.0 default the
    reference runs under)."""

    sql_name = "Size"

    def __init__(self, child: Expression):
        self.children = (child,)

    @property
    def dtype(self):
        return T.IntegerType()

    def _eval(self, vals, ctx):
        a = vals[0]
        if not ctx.is_device:
            data = np.array([len(v) if ok else -1
                             for v, ok in zip(a.data, a.validity)], np.int32)
            return ctx.canonical(data, np.ones(ctx.capacity, np.bool_),
                                 T.IntegerType())
        xp = ctx.xp
        data = xp.where(a.validity, a.lengths, -1).astype(np.int32)
        validity = xp.ones(ctx.capacity, bool)
        return ctx.canonical(data, validity, T.IntegerType())


class ArrayContains(Expression):
    """array_contains(arr, value): value is a literal-evaluable child;
    null input array -> null (value nulls likewise)."""

    sql_name = "ArrayContains"

    def __init__(self, child: Expression, value: Expression):
        self.children = (child, value)

    @property
    def dtype(self):
        return T.BooleanType()

    def _eval(self, vals, ctx):
        arr, val = vals
        if not ctx.is_device:
            n = ctx.capacity
            data = np.zeros(n, dtype=np.bool_)
            validity = arr.validity & val.validity
            for i in range(n):
                if validity[i]:
                    data[i] = val.data[i] in arr.data[i]
            return ctx.canonical(data, validity, T.BooleanType())
        xp = ctx.xp
        w = arr.data.shape[1]
        in_len = xp.arange(w, dtype=np.int32)[None, :] < arr.lengths[:, None]
        hit = xp.any((arr.data == val.data[:, None]) & in_len, axis=1)
        validity = arr.validity & val.validity
        data = xp.where(validity, hit, False)
        return ctx.canonical(data, validity, T.BooleanType())


def _encode_elems(values, dtype: T.DataType) -> list:
    """Raw python map keys/values -> the engine's storage encodings for
    an array column (date -> days, timestamp -> micros) — the same
    conversion GetMapValue gets from HostColumn.from_values, applied
    per element."""
    from spark_rapids_tpu.host.batch import HostColumn
    return HostColumn.from_values(list(values), dtype).data.tolist()


class MapKeys(Expression):
    """map_keys(m): the map's keys as an array, deterministic sorted
    order (reference collectionOperations GpuMapKeys; Spark leaves the
    order unspecified — sorted matches this engine's canonical map
    layout).  On raw (host-only) maps this is a host expression; the
    planner's map-decomposition rewrite replaces it with a direct
    reference to the keys array column for the device path."""

    sql_name = "MapKeys"

    def __init__(self, child: Expression):
        self.children = (child,)

    @property
    def dtype(self):
        mt = self.children[0].dtype
        assert isinstance(mt, T.MapType), mt
        return T.ArrayType(mt.key_type)

    @property
    def device_supported(self) -> bool:
        return False

    def _eval(self, vals, ctx):
        assert not ctx.is_device, "MapKeys on raw maps is host-only"
        m = vals[0]
        kt = self.dtype.element_type
        data = np.empty(ctx.capacity, dtype=object)
        for i in range(ctx.capacity):
            data[i] = _encode_elems(sorted(m.data[i]), kt) \
                if m.validity[i] else None
        from spark_rapids_tpu.expr.core import Val
        return Val(data, np.asarray(m.validity, bool), None, self.dtype)


class MapValues(Expression):
    """map_values(m): the map's values as an array, aligned with
    map_keys' sorted key order (reference GpuMapValues)."""

    sql_name = "MapValues"

    def __init__(self, child: Expression):
        self.children = (child,)

    @property
    def dtype(self):
        mt = self.children[0].dtype
        assert isinstance(mt, T.MapType), mt
        return T.ArrayType(mt.value_type)

    @property
    def device_supported(self) -> bool:
        return False

    def _eval(self, vals, ctx):
        assert not ctx.is_device, "MapValues on raw maps is host-only"
        m = vals[0]
        vt = self.dtype.element_type
        data = np.empty(ctx.capacity, dtype=object)
        for i in range(ctx.capacity):
            data[i] = _encode_elems(
                [v for _, v in sorted(m.data[i].items())], vt) \
                if m.validity[i] else None
        from spark_rapids_tpu.expr.core import Val
        return Val(data, np.asarray(m.validity, bool), None, self.dtype)


class MapLookup(Expression):
    """Decomposed-map ``m[key]``: find the key's slot in the aligned
    sorted-keys/values ARRAY column pair and gather the value — the
    device form of GetMapValue after the planner's map-decomposition
    rewrite (reference complexTypeExtractors.scala GetMapValue, which
    the plugin runs as a cuDF LIST binary search; here a masked
    equality + argmax over the static [capacity, max_len] key matrix)."""

    sql_name = "MapLookup"

    def __init__(self, keys_arr: Expression, vals_arr: Expression,
                 key: Expression):
        self.children = (keys_arr, vals_arr, key)

    @property
    def dtype(self):
        at = self.children[1].dtype
        assert isinstance(at, T.ArrayType), at
        return at.element_type

    def _eval(self, vals, ctx):
        keys, vs, k = vals
        elem = self.dtype
        if not ctx.is_device:
            n = ctx.capacity
            out = np.zeros(n, dtype=elem.np_dtype)
            validity = np.zeros(n, dtype=np.bool_)
            for i in range(n):
                if not (keys.validity[i] and k.validity[i]):
                    continue
                row = keys.data[i]
                want = k.data[i]
                for j, kv in enumerate(row):
                    if kv == want:
                        out[i] = vs.data[i][j]
                        validity[i] = True
                        break
            return ctx.canonical(out, validity, elem)
        xp = ctx.xp
        w = keys.data.shape[1]
        in_len = xp.arange(w, dtype=np.int32)[None, :] < keys.lengths[:, None]
        eq = (keys.data == k.data[:, None]) & in_len
        found = xp.any(eq, axis=1)
        idx = xp.argmax(eq, axis=1)
        picked = xp.take_along_axis(vs.data, idx[:, None], axis=1)[:, 0]
        validity = keys.validity & k.validity & found
        data = xp.where(validity, picked, xp.zeros((), vs.data.dtype))
        return ctx.canonical(data, validity, elem)

"""Window expression nodes (declarative, evaluated by WindowExec).

Reference: GpuWindowExpression.scala:169-830 (GpuWindowSpecDefinition,
GpuRowNumber:737, GpuLead:797, GpuLag:811, windowed aggregations).
"""
from __future__ import annotations

from dataclasses import dataclass
from typing import Sequence

from spark_rapids_tpu import types as T
from spark_rapids_tpu.expr.core import Expression, Literal
from spark_rapids_tpu.ops.window import CURRENT_ROW, UNBOUNDED, WindowFrame

__all__ = ["WindowSpec", "WindowExpression", "RowNumber", "Rank",
           "DenseRank", "Lead", "Lag", "WindowFrame", "UNBOUNDED",
           "CURRENT_ROW"]


@dataclass(frozen=True)
class WindowSpec:
    """partition_by: expressions; order_by: (expr, ascending[, nulls_first])
    tuples; frame: None = Spark default (RANGE unbounded..current when
    ordered, else whole partition)."""
    partition_by: tuple = ()
    order_by: tuple = ()
    frame: WindowFrame | None = None

    def resolved_frame(self) -> WindowFrame:
        if self.frame is not None:
            return self.frame
        if self.order_by:
            return WindowFrame("range", UNBOUNDED, CURRENT_ROW)
        return WindowFrame("range", UNBOUNDED, UNBOUNDED)


def window_agg_op(f) -> str:
    """Frame-aggregation op name for an AggregateFunction node."""
    from spark_rapids_tpu.expr import aggregates as A
    if isinstance(f, A.CountStar):
        return "count_star"
    if isinstance(f, A.Sum):
        return "sum"
    if isinstance(f, A.Count):
        return "count"
    if isinstance(f, A.Min):
        return "min"
    if isinstance(f, A.Max):
        return "max"
    if isinstance(f, A.Average):
        return "avg"
    raise ValueError(f"unsupported window aggregate: {f!r}")


class WindowFunction(Expression):
    """Marker base for ranking/offset window functions."""
    children: tuple = ()

    def with_new_children(self, children):
        return self

    @property
    def nullable(self):
        return False


class RowNumber(WindowFunction):
    sql_name = "row_number"

    def __init__(self):
        self.children = ()

    @property
    def dtype(self):
        return T.IntegerType()


class Rank(WindowFunction):
    sql_name = "rank"

    def __init__(self):
        self.children = ()

    @property
    def dtype(self):
        return T.IntegerType()


class DenseRank(WindowFunction):
    sql_name = "dense_rank"

    def __init__(self):
        self.children = ()

    @property
    def dtype(self):
        return T.IntegerType()


class Lead(WindowFunction):
    sql_name = "lead"

    def __init__(self, child: Expression, offset: int = 1,
                 default: Expression | None = None):
        self.children = (child,) if default is None else (child, default)
        self.offset = offset
        self.default = default

    def with_new_children(self, children):
        d = children[1] if len(children) > 1 else None
        return type(self)(children[0], self.offset, d)

    @property
    def dtype(self):
        return self.children[0].dtype

    @property
    def nullable(self):
        return True


class Lag(Lead):
    sql_name = "lag"


class WindowExpression(Expression):
    """function OVER spec."""
    sql_name = "window"

    def __init__(self, function: Expression, spec: WindowSpec):
        self.children = (function,)
        self.function = function
        self.spec = spec

    def with_new_children(self, children):
        return WindowExpression(children[0], self.spec)

    @property
    def dtype(self):
        from spark_rapids_tpu.expr.aggregates import AggregateFunction
        f = self.function
        if isinstance(f, AggregateFunction):
            # windowed agg result types follow the agg (sum->long/double..)
            from spark_rapids_tpu.ops.segmented import AggSpec
            op = window_agg_op(f)
            in_t = f.input.dtype if f.input is not None else T.LongType()
            return AggSpec(op, 0).result_type(in_t)
        return f.dtype

    @property
    def nullable(self):
        return True

    def over(self, spec: WindowSpec) -> "WindowExpression":
        return WindowExpression(self.function, spec)

"""Partition-aware expressions (reference GpuSparkPartitionID /
GpuMonotonicallyIncreasingID in the expression library, SURVEY §2.4).

These need the task's partition id and running row offset, which plain
expression eval doesn't see — ProjectExec detects them, computes an
input column per batch (one tiny jitted program fed by device scalars,
no per-batch retrace) and rewrites the expression to a BoundReference
(exec/basic.py)."""
from __future__ import annotations

from spark_rapids_tpu import types as T
from spark_rapids_tpu.expr.core import Expression

__all__ = ["MonotonicallyIncreasingID", "SparkPartitionID",
           "PartitionAwareExpression"]


class PartitionAwareExpression(Expression):
    """Marker: evaluation requires (partition_id, row_offset)."""

    @property
    def nullable(self):
        return False

    def with_new_children(self, children):
        return self

    def _eval(self, vals, ctx):
        raise ValueError(
            f"{self.sql_name}() is only supported inside select() "
            "projections (ProjectExec hoists it; other operators cannot "
            "supply partition context)")


def reject_partition_aware(exprs, where: str) -> None:
    """Plan-time guard: raise a clear error instead of a runtime crash
    when a partition-aware expression appears outside a projection."""
    for e in exprs:
        if e is None or not isinstance(e, Expression):
            continue
        stack = [e]
        while stack:
            n = stack.pop()
            if isinstance(n, PartitionAwareExpression):
                raise ValueError(
                    f"{n.sql_name}() is not allowed in {where}; compute it "
                    "in a select() first")
            stack.extend(n.children)


class MonotonicallyIncreasingID(PartitionAwareExpression):
    """(partition_id << 33) + row index within the partition — unique and
    monotonically increasing per partition (Spark semantics)."""

    sql_name = "MonotonicallyIncreasingID"

    @property
    def dtype(self):
        return T.LongType()

    def __repr__(self):
        return "monotonically_increasing_id()"


class SparkPartitionID(PartitionAwareExpression):
    sql_name = "SparkPartitionID"

    @property
    def dtype(self):
        return T.IntegerType()

    def __repr__(self):
        return "spark_partition_id()"

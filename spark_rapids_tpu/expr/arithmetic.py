"""Arithmetic expressions (reference arithmetic.scala, 417 LoC).

Spark semantics implemented exactly (non-ANSI mode, like the reference's
default):

* integer add/sub/mul/neg/abs wrap (Java two's-complement; numpy and XLA
  both wrap, so the shared kernel is just the operator);
* ``/`` (Divide) coerces both sides to double and yields NULL when the
  divisor is zero (Spark DivModLike);
* ``%`` (Remainder) follows the dividend's sign (Java ``%``): ``fmod``;
* ``div`` (IntegralDivide) truncates toward zero and yields long.
"""
from __future__ import annotations

from spark_rapids_tpu import types as T
from spark_rapids_tpu.expr.core import Expression, Val, EvalCtx, Literal

__all__ = ["Add", "Subtract", "Multiply", "Divide", "IntegralDivide",
           "Remainder", "UnaryMinus", "Abs", "Least", "Greatest",
           "coerce_pair"]


def coerce_pair(left: Expression, right: Expression,
                target: T.DataType | None = None):
    """Insert casts so both sides share a numeric type (Spark promotion)."""
    from spark_rapids_tpu.expr.cast import Cast
    lt, rt = left.dtype, right.dtype
    if target is None:
        if isinstance(lt, T.NullType):
            target = rt
        elif isinstance(rt, T.NullType):
            target = lt
        elif lt == rt:
            target = lt
        elif lt.numeric and rt.numeric:
            target = T.numeric_promote(lt, rt)
        else:
            raise TypeError(f"cannot coerce {lt} with {rt}")
    if lt != target:
        left = Cast(left, target)
    if rt != target:
        right = Cast(right, target)
    return left, right


class BinaryArithmetic(Expression):
    """Binary numeric op: validity = AND of child validities."""

    def __init__(self, left: Expression, right: Expression):
        self.children = (left, right)

    def coerced(self):
        l, r = coerce_pair(*self.children)
        if not l.dtype.numeric:
            raise TypeError(f"{self.sql_name} requires numeric, got {l.dtype}")
        return type(self)(l, r)

    @property
    def dtype(self):
        return self.children[0].dtype

    def _eval(self, vals, ctx: EvalCtx):
        a, b = vals
        validity = a.validity & b.validity
        data = self._op(a.data, b.data, ctx.xp)
        return ctx.canonical(data, validity, self.dtype)


class Add(BinaryArithmetic):
    sql_name = "Add"

    def _op(self, a, b, xp):
        return a + b


class Subtract(BinaryArithmetic):
    sql_name = "Subtract"

    def _op(self, a, b, xp):
        return a - b


class Multiply(BinaryArithmetic):
    sql_name = "Multiply"

    def _op(self, a, b, xp):
        return a * b


class _DivModLike(BinaryArithmetic):
    """Spark DivModLike: NULL when divisor is zero."""

    def _eval(self, vals, ctx: EvalCtx):
        a, b = vals
        xp = ctx.xp
        zero = xp.zeros((), b.data.dtype)
        nonzero = b.data != zero
        validity = a.validity & b.validity & nonzero
        one = xp.ones((), b.data.dtype)
        safe_b = xp.where(nonzero, b.data, one)
        data = self._op(a.data, safe_b, xp)
        return ctx.canonical(data, validity, self.dtype)


class Divide(_DivModLike):
    sql_name = "Divide"

    def coerced(self):
        l, r = coerce_pair(*self.children, target=T.DoubleType())
        return Divide(l, r)

    @property
    def dtype(self):
        return T.DoubleType()

    def _op(self, a, b, xp):
        return a / b


class IntegralDivide(_DivModLike):
    sql_name = "IntegralDivide"

    def coerced(self):
        l, r = coerce_pair(*self.children, target=T.LongType())
        return IntegralDivide(l, r)

    @property
    def dtype(self):
        return T.LongType()

    def _op(self, a, b, xp):
        # truncate toward zero (Java integer division); xp floor-divides,
        # so bump the quotient by one when signs differ and there is a
        # nonzero remainder
        q = a // b
        r = a - q * b
        adjust = (r != 0) & ((a < 0) != (b < 0))
        return q + adjust.astype(q.dtype)


class Remainder(_DivModLike):
    sql_name = "Remainder"

    def _op(self, a, b, xp):
        if self.dtype.fractional:
            return xp.fmod(a, b)
        # Java %: sign of dividend. xp.mod follows divisor; fix up.
        m = a % b
        wrong = (m != 0) & ((m < 0) != (a < 0))
        return m - xp.where(wrong, b, b - b)


class UnaryMinus(Expression):
    sql_name = "UnaryMinus"

    def __init__(self, child: Expression):
        self.children = (child,)

    @property
    def dtype(self):
        return self.children[0].dtype

    def coerced(self):
        if not self.children[0].dtype.numeric:
            raise TypeError("UnaryMinus requires numeric")
        return self

    def _eval(self, vals, ctx):
        a = vals[0]
        if a.data.dtype.kind == "u":
            data = -a.data
        else:
            data = ctx.xp.negative(a.data)
        return ctx.canonical(data, a.validity, self.dtype)


class Abs(Expression):
    sql_name = "Abs"

    def __init__(self, child: Expression):
        self.children = (child,)

    @property
    def dtype(self):
        return self.children[0].dtype

    def _eval(self, vals, ctx):
        a = vals[0]
        return ctx.canonical(ctx.xp.abs(a.data), a.validity, self.dtype)


class _LeastGreatest(Expression):
    """Spark Least/Greatest: skip nulls; NaN is greatest; null only if all
    inputs null."""

    def __init__(self, *children: Expression):
        self.children = tuple(children)

    def with_new_children(self, children):
        return type(self)(*children)

    def coerced(self):
        target = self.children[0].dtype
        for c in self.children[1:]:
            if c.dtype != target and c.dtype.numeric and target.numeric:
                target = T.numeric_promote(target, c.dtype)
        from spark_rapids_tpu.expr.cast import Cast
        kids = [c if c.dtype == target else Cast(c, target)
                for c in self.children]
        return type(self)(*kids)

    @property
    def dtype(self):
        return self.children[0].dtype

    def _eval(self, vals, ctx):
        xp = ctx.xp
        acc = vals[0]
        data, validity = acc.data, acc.validity
        for v in vals[1:]:
            both = validity & v.validity
            pick_new = xp.where(both, self._better(v.data, data, xp),
                                v.validity & ~validity)
            data = xp.where(pick_new, v.data, data)
            validity = validity | v.validity
        return ctx.canonical(data, validity, self.dtype)


class Least(_LeastGreatest):
    sql_name = "Least"

    def _better(self, new, cur, xp):
        if self.dtype.fractional:
            return (new < cur) | (xp.isnan(cur) & ~xp.isnan(new))
        return new < cur


class Greatest(_LeastGreatest):
    sql_name = "Greatest"

    def _better(self, new, cur, xp):
        if self.dtype.fractional:
            return (new > cur) | (xp.isnan(new) & ~xp.isnan(cur))
        return new > cur

"""Null-handling expressions (reference nullExpressions.scala, 287 LoC:
GpuIsNan, GpuNaNvl, GpuNvl family, GpuNullIf via coalesce/if rewrites).

All elementwise, device-supported; semantics follow Spark:
* isnan(null) = false;
* nanvl(a, b): b when a is NaN, else a (doubles);
* nvl/nvl2/nullif are the standard SQL forms.
"""
from __future__ import annotations

from spark_rapids_tpu import types as T
from spark_rapids_tpu.expr.core import Expression, EvalCtx, Val

__all__ = ["IsNaN", "NaNvl", "Nvl", "Nvl2", "NullIf"]


class IsNaN(Expression):
    sql_name = "IsNaN"

    def __init__(self, child: Expression):
        self.children = (child,)

    @property
    def dtype(self):
        return T.BooleanType()

    @property
    def nullable(self):
        return False

    def _eval(self, vals, ctx: EvalCtx):
        a = vals[0]
        xp = ctx.xp
        if a.dtype.fractional:
            data = xp.isnan(a.data) & a.validity
        else:
            data = xp.zeros(ctx.capacity, dtype=bool)
        return ctx.canonical(data, ctx.row_mask, T.BooleanType())


class NaNvl(Expression):
    sql_name = "NaNvl"

    def __init__(self, left: Expression, right: Expression):
        self.children = (left, right)

    @property
    def dtype(self):
        return self.children[0].dtype

    def coerced(self):
        from spark_rapids_tpu.expr.cast import Cast
        a, b = self.children
        if type(a.dtype) is not type(b.dtype):
            return NaNvl(a, Cast(b, a.dtype))
        return self

    def _eval(self, vals, ctx: EvalCtx):
        a, b = vals
        xp = ctx.xp
        if not a.dtype.fractional:
            return a
        use_b = a.validity & xp.isnan(a.data)
        data = xp.where(use_b, b.data.astype(a.data.dtype), a.data)
        validity = xp.where(use_b, b.validity, a.validity)
        return ctx.canonical(data, validity, a.dtype)


class Nvl(Expression):
    """nvl(a, b) = coalesce(a, b) (reference GpuNvl)."""

    sql_name = "Nvl"

    def __init__(self, left: Expression, right: Expression):
        self.children = (left, right)

    @property
    def dtype(self):
        return self.children[0].dtype

    def coerced(self):
        from spark_rapids_tpu.expr.conditional import Coalesce
        return Coalesce(*self.children).coerced()


class Nvl2(Expression):
    """nvl2(a, b, c): b when a is not null else c (reference GpuNvl2 via
    If(IsNotNull(a), b, c))."""

    sql_name = "Nvl2"

    def __init__(self, a: Expression, b: Expression, c: Expression):
        self.children = (a, b, c)

    @property
    def dtype(self):
        return self.children[1].dtype

    def coerced(self):
        from spark_rapids_tpu.expr.conditional import If
        from spark_rapids_tpu.expr.predicates import IsNotNull
        a, b, c = self.children
        return If(IsNotNull(a), b, c).coerced()


class NullIf(Expression):
    """nullif(a, b): null when a == b else a."""

    sql_name = "NullIf"

    def __init__(self, left: Expression, right: Expression):
        self.children = (left, right)

    @property
    def dtype(self):
        return self.children[0].dtype

    @property
    def nullable(self):
        return True

    def coerced(self):
        from spark_rapids_tpu.expr.conditional import If
        from spark_rapids_tpu.expr.core import Literal
        from spark_rapids_tpu.expr.predicates import EqualTo
        a, b = self.children
        return If(EqualTo(a, b), Literal(None, a.dtype), a).coerced()

"""Math expressions (reference mathExpressions.scala, 361 LoC).

Spark semantics: unary math fns take/return double; ``log``-family returns
NULL for non-positive input (non-ANSI); ``floor``/``ceil`` on double return
LongType; ``round`` is HALF_UP (not banker's rounding).
"""
from __future__ import annotations

import numpy as np

from spark_rapids_tpu import types as T
from spark_rapids_tpu.expr.core import Expression, EvalCtx, Literal

__all__ = ["Sqrt", "Exp", "Log", "Log10", "Log2", "Log1p", "Expm1", "Pow",
           "Floor", "Ceil", "Round", "Signum", "Sin", "Cos", "Tan", "Asin",
           "Acos", "Atan", "Atan2", "Sinh", "Cosh", "Tanh", "ToDegrees",
           "ToRadians", "Rint", "Cbrt"]


class _UnaryDouble(Expression):
    """double -> double elementwise fn."""

    def __init__(self, child: Expression):
        self.children = (child,)

    def coerced(self):
        from spark_rapids_tpu.expr.cast import Cast
        c = self.children[0]
        if not isinstance(c.dtype, T.DoubleType):
            return type(self)(Cast(c, T.DoubleType()))
        return self

    @property
    def dtype(self):
        return T.DoubleType()

    def _eval(self, vals, ctx):
        a = vals[0]
        return ctx.canonical(self._fn(a.data, ctx.xp), a.validity,
                             T.DoubleType())


class Sqrt(_UnaryDouble):
    sql_name = "Sqrt"

    def _fn(self, d, xp):
        with np.errstate(invalid="ignore"):
            return xp.sqrt(d)  # negative -> NaN (Java Math.sqrt)


class Exp(_UnaryDouble):
    sql_name = "Exp"

    def _fn(self, d, xp):
        return xp.exp(d)


class Expm1(_UnaryDouble):
    sql_name = "Expm1"

    def _fn(self, d, xp):
        return xp.expm1(d)


class _LogLike(_UnaryDouble):
    """NULL for input <= 0 (Spark non-ANSI log)."""

    def _eval(self, vals, ctx):
        a = vals[0]
        xp = ctx.xp
        ok = a.data > 0
        validity = a.validity & ok
        safe = xp.where(ok, a.data, xp.ones((), a.data.dtype))
        out = self._fn(safe, xp)
        # log(+inf) = +inf (TPU's emulated f64 log yields NaN on inf)
        out = xp.where(xp.isinf(safe), safe, out)
        return ctx.canonical(out, validity, T.DoubleType())


class Log(_LogLike):
    sql_name = "Log"

    def _fn(self, d, xp):
        return xp.log(d)


class Log10(_LogLike):
    sql_name = "Log10"

    def _fn(self, d, xp):
        return xp.log10(d)


class Log2(_LogLike):
    sql_name = "Log2"

    def _fn(self, d, xp):
        return xp.log2(d)


class Log1p(_UnaryDouble):
    """NULL for input <= -1."""
    sql_name = "Log1p"

    def _eval(self, vals, ctx):
        a = vals[0]
        xp = ctx.xp
        ok = a.data > -1
        validity = a.validity & ok
        safe = xp.where(ok, a.data, xp.zeros((), a.data.dtype))
        return ctx.canonical(xp.log1p(safe), validity, T.DoubleType())


class Pow(Expression):
    sql_name = "Pow"

    def __init__(self, left: Expression, right: Expression):
        self.children = (left, right)

    def coerced(self):
        from spark_rapids_tpu.expr.cast import Cast
        kids = [c if isinstance(c.dtype, T.DoubleType)
                else Cast(c, T.DoubleType()) for c in self.children]
        return Pow(*kids)

    @property
    def dtype(self):
        return T.DoubleType()

    def _eval(self, vals, ctx):
        a, b = vals
        validity = a.validity & b.validity
        with np.errstate(invalid="ignore"):
            data = ctx.xp.power(a.data, b.data)
        return ctx.canonical(data, validity, T.DoubleType())


class Atan2(Expression):
    sql_name = "Atan2"

    def __init__(self, left: Expression, right: Expression):
        self.children = (left, right)

    def coerced(self):
        from spark_rapids_tpu.expr.cast import Cast
        kids = [c if isinstance(c.dtype, T.DoubleType)
                else Cast(c, T.DoubleType()) for c in self.children]
        return Atan2(*kids)

    @property
    def dtype(self):
        return T.DoubleType()

    def _eval(self, vals, ctx):
        a, b = vals
        validity = a.validity & b.validity
        return ctx.canonical(ctx.xp.arctan2(a.data, b.data), validity,
                             T.DoubleType())


class _FloorCeil(Expression):
    """floor/ceil: LongType result for double input; identity for integral."""

    def __init__(self, child: Expression):
        self.children = (child,)

    def coerced(self):
        from spark_rapids_tpu.expr.cast import Cast
        c = self.children[0]
        if isinstance(c.dtype, T.FloatType):
            return type(self)(Cast(c, T.DoubleType()))
        return self

    @property
    def dtype(self):
        return T.LongType() if self.children[0].dtype.fractional \
            else self.children[0].dtype

    def _eval(self, vals, ctx):
        a = vals[0]
        if a.dtype.integral:
            return a
        data = self._fn(a.data, ctx.xp)
        from spark_rapids_tpu.expr.cast import Cast as _C
        data = _C._float_to_int(ctx.xp, data, T.LongType())
        return ctx.canonical(data, a.validity, T.LongType())


class Floor(_FloorCeil):
    sql_name = "Floor"

    def _fn(self, d, xp):
        return xp.floor(d)


class Ceil(_FloorCeil):
    sql_name = "Ceil"

    def _fn(self, d, xp):
        return xp.ceil(d)


class Round(Expression):
    """round(x, scale): HALF_UP (Spark), scale must be a literal int."""
    sql_name = "Round"

    def __init__(self, child: Expression, scale: Expression | int = 0):
        if not isinstance(scale, Expression):
            scale = Literal(int(scale), T.IntegerType())
        self.children = (child, scale)

    @property
    def scale(self) -> int:
        s = self.children[1]
        assert isinstance(s, Literal), "round scale must be literal"
        return int(s.value)

    @property
    def dtype(self):
        return self.children[0].dtype

    def _eval(self, vals, ctx):
        a = vals[0]
        xp = ctx.xp
        s = self.scale
        if a.dtype.integral:
            if s >= 0:
                return a
            p = 10 ** (-s)
            half = p // 2
            sign = xp.where(a.data < 0, -1, 1).astype(a.data.dtype)
            mag = xp.abs(a.data)
            data = ((mag + half) // p * p * sign).astype(a.data.dtype)
            return ctx.canonical(data, a.validity, a.dtype)
        p = np.float64(10.0 ** s)
        mag = xp.abs(a.data)
        # beyond 2^53 (2^24 for f32) there is no fractional part: identity.
        # Also keeps mag*p inside the representable range (TPU f64 emulation
        # overflows earlier than native f64).
        exact = np.float64(2.0 ** 53) if a.data.dtype.itemsize == 8 \
            else np.float64(2.0 ** 24)
        safe_mag = xp.where(mag >= exact, xp.zeros((), a.data.dtype), mag)
        r = xp.floor(safe_mag * p + 0.5) / p
        data = (xp.where(a.data < 0, -r, r)).astype(a.data.dtype)
        data = xp.where(xp.isnan(a.data) | xp.isinf(a.data) | (mag >= exact),
                        a.data, data)
        return ctx.canonical(data, a.validity, a.dtype)


class Signum(_UnaryDouble):
    sql_name = "Signum"

    def _fn(self, d, xp):
        return xp.sign(d)


class Sin(_UnaryDouble):
    sql_name = "Sin"

    def _fn(self, d, xp):
        return xp.sin(d)


class Cos(_UnaryDouble):
    sql_name = "Cos"

    def _fn(self, d, xp):
        return xp.cos(d)


class Tan(_UnaryDouble):
    sql_name = "Tan"

    def _fn(self, d, xp):
        return xp.tan(d)


class Asin(_UnaryDouble):
    sql_name = "Asin"

    def _fn(self, d, xp):
        with np.errstate(invalid="ignore"):
            return xp.arcsin(d)


class Acos(_UnaryDouble):
    sql_name = "Acos"

    def _fn(self, d, xp):
        with np.errstate(invalid="ignore"):
            return xp.arccos(d)


class Atan(_UnaryDouble):
    sql_name = "Atan"

    def _fn(self, d, xp):
        return xp.arctan(d)


class Sinh(_UnaryDouble):
    sql_name = "Sinh"

    def _fn(self, d, xp):
        return xp.sinh(d)


class Cosh(_UnaryDouble):
    sql_name = "Cosh"

    def _fn(self, d, xp):
        return xp.cosh(d)


class Tanh(_UnaryDouble):
    sql_name = "Tanh"

    def _fn(self, d, xp):
        return xp.tanh(d)


class ToDegrees(_UnaryDouble):
    sql_name = "ToDegrees"

    def _fn(self, d, xp):
        return xp.degrees(d)


class ToRadians(_UnaryDouble):
    sql_name = "ToRadians"

    def _fn(self, d, xp):
        return xp.radians(d)


class Rint(_UnaryDouble):
    sql_name = "Rint"

    def _fn(self, d, xp):
        return xp.round(d)  # half-even, like Java Math.rint


class Cbrt(_UnaryDouble):
    sql_name = "Cbrt"

    def _fn(self, d, xp):
        return xp.cbrt(d)

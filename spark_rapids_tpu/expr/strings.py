"""String expressions (reference stringFunctions.scala, 898 LoC).

Device representation is a padded uint8 byte matrix + lengths (see
columnar/column.py).  Kernels are dense VPU-friendly ops:

* Length / Substring are UTF-8 *character* correct (continuation-byte
  masks + cumulative character counts) matching Spark;
* Upper/Lower are ASCII-only on device (flagged incompat in the planner,
  like the reference's incompat string ops);
* Like supports the prefix/suffix/contains patterns on device; general
  patterns are host-only (the reference likewise gates regex behind shims,
  Spark300Shims.scala:235).
"""
from __future__ import annotations

import numpy as np

from spark_rapids_tpu import types as T
from spark_rapids_tpu.expr.core import Expression, EvalCtx, Val, Literal
from spark_rapids_tpu.expr.predicates import _string_pair_device

__all__ = ["Upper", "Lower", "Length", "Substring", "Concat", "StartsWith",
           "EndsWith", "Contains", "Like", "StringTrim", "StringTrimLeft",
           "StringTrimRight", "StringReplace", "ConcatWs", "StringLocate",
           "SubstringIndex", "InitCap", "StringLPad", "StringRPad",
           "StringRepeat", "Hex"]


def _char_starts(data, lengths, xp):
    """bool[n,w]: byte j is the start of a character and inside the string."""
    w = data.shape[1]
    in_range = xp.arange(w, dtype=np.int32)[None, :] < lengths[:, None]
    return ((data & 0xC0) != 0x80) & in_range


class _StringUnary(Expression):
    def __init__(self, child: Expression):
        self.children = (child,)

    @property
    def dtype(self):
        return T.StringType()

    def _eval(self, vals, ctx):
        a = vals[0]
        if not ctx.is_device:
            out = np.empty(ctx.capacity, dtype=object)
            for i in range(ctx.capacity):
                out[i] = self._host_one(a.data[i]) if a.validity[i] else None
            return Val(out, a.validity, None, T.StringType())
        data, lengths = self._device(a, ctx)
        return ctx.canonical(data, a.validity, T.StringType(), lengths)


class Upper(_StringUnary):
    sql_name = "Upper"
    #: ASCII-only on device (host oracle is full unicode) — incompat
    incompat = True

    def _host_one(self, s):
        return s.upper()

    def _device(self, a, ctx):
        xp = ctx.xp
        is_lower = (a.data >= ord("a")) & (a.data <= ord("z"))
        return xp.where(is_lower, a.data - 32, a.data), a.lengths


class Lower(_StringUnary):
    sql_name = "Lower"
    incompat = True

    def _host_one(self, s):
        return s.lower()

    def _device(self, a, ctx):
        xp = ctx.xp
        is_upper = (a.data >= ord("A")) & (a.data <= ord("Z"))
        return xp.where(is_upper, a.data + 32, a.data), a.lengths


class _TrimBase(_StringUnary):
    _left = True
    _right = True

    def _host_one(self, s):
        if self._left and self._right:
            return s.strip(" ")
        return s.lstrip(" ") if self._left else s.rstrip(" ")

    def _device(self, a, ctx):
        xp = ctx.xp
        w = a.data.shape[1]
        j = xp.arange(w, dtype=np.int32)[None, :]
        in_range = j < a.lengths[:, None]
        nonspace = (a.data != 32) & in_range
        any_ns = xp.any(nonspace, axis=1)
        first = xp.where(any_ns, xp.argmax(nonspace, axis=1), 0) \
            if self._left else xp.zeros_like(a.lengths)
        last_rev = xp.argmax(nonspace[:, ::-1], axis=1)
        last = xp.where(any_ns, w - 1 - last_rev, -1) \
            if self._right else a.lengths - 1
        new_len = xp.where(any_ns, xp.maximum(last - first + 1, 0), 0)
        new_len = new_len.astype(np.int32)
        idx = first[:, None] + xp.arange(w, dtype=np.int32)[None, :]
        idx = xp.clip(idx, 0, w - 1)
        shifted = xp.take_along_axis(a.data, idx, axis=1)
        keep = xp.arange(w, dtype=np.int32)[None, :] < new_len[:, None]
        return xp.where(keep, shifted, 0), new_len


class StringTrim(_TrimBase):
    sql_name = "StringTrim"


class StringTrimLeft(_TrimBase):
    sql_name = "StringTrimLeft"
    _right = False


class StringTrimRight(_TrimBase):
    sql_name = "StringTrimRight"
    _left = False


class Length(Expression):
    """Character count (Spark length), IntegerType."""
    sql_name = "Length"

    def __init__(self, child: Expression):
        self.children = (child,)

    @property
    def dtype(self):
        return T.IntegerType()

    def _eval(self, vals, ctx):
        a = vals[0]
        if not ctx.is_device:
            data = np.array([len(s) if v else 0
                             for s, v in zip(a.data, a.validity)], np.int32)
            return ctx.canonical(data, a.validity, T.IntegerType())
        starts = _char_starts(a.data, a.lengths, ctx.xp)
        data = ctx.xp.sum(starts, axis=1).astype(np.int32)
        return ctx.canonical(data, a.validity, T.IntegerType())


class Substring(Expression):
    """Spark substring(str, pos, len): 1-based, pos<=0 counts 0/from-end,
    character-indexed; out-of-range yields '' (not null)."""
    sql_name = "Substring"

    def __init__(self, child: Expression, pos: Expression, length: Expression):
        self.children = (child, pos, length)

    @property
    def dtype(self):
        return T.StringType()

    def _eval(self, vals, ctx):
        a, pos, length = vals
        if not ctx.is_device:
            out = np.empty(ctx.capacity, dtype=object)
            validity = a.validity & pos.validity & length.validity
            for i in range(ctx.capacity):
                if not validity[i]:
                    out[i] = None
                    continue
                out[i] = _substr_host(a.data[i], int(pos.data[i]),
                                      int(length.data[i]))
            return Val(out, validity, None, T.StringType())
        return self._device(a, pos, length, ctx)

    def _device(self, a, pos, length, ctx):
        xp = ctx.xp
        w = a.data.shape[1]
        validity = a.validity & pos.validity & length.validity
        starts = _char_starts(a.data, a.lengths, xp)
        nchars = xp.sum(starts, axis=1).astype(np.int32)
        p = pos.data.astype(np.int32)
        ln = xp.maximum(length.data.astype(np.int32), 0)
        # resolve 1-based / negative positions to 0-based char index
        start_char = xp.where(p > 0, p - 1, xp.where(p < 0, nchars + p, 0))
        neg_clip = xp.where(p < 0, xp.maximum(ln + xp.minimum(nchars + p, 0), 0), ln)
        start_char = xp.clip(start_char, 0, nchars)
        end_char = xp.clip(start_char + neg_clip, 0, nchars)
        # byte offset of char k: position of the (k+1)-th start; k==nchars -> len
        cs = xp.cumsum(starts.astype(np.int32), axis=1)
        def byte_of(k):
            hit = (cs == (k + 1)[:, None]) & starts
            found = xp.any(hit, axis=1)
            return xp.where(found, xp.argmax(hit, axis=1).astype(np.int32),
                            a.lengths)
        sb = byte_of(start_char)
        eb = byte_of(end_char)
        new_len = xp.maximum(eb - sb, 0).astype(np.int32)
        idx = xp.clip(sb[:, None] + xp.arange(w, dtype=np.int32)[None, :],
                      0, w - 1)
        shifted = xp.take_along_axis(a.data, idx, axis=1)
        keep = xp.arange(w, dtype=np.int32)[None, :] < new_len[:, None]
        data = xp.where(keep, shifted, 0)
        return ctx.canonical(data, validity, T.StringType(), new_len)


def _substr_host(s: str, pos: int, ln: int) -> str:
    if ln <= 0:
        return ""
    n = len(s)
    if pos > 0:
        start = pos - 1
    elif pos < 0:
        start = n + pos
    else:
        start = 0
    end = start + ln
    if start < 0:
        start = 0
    return s[start:end] if start < n else ""


class Concat(Expression):
    """concat(s1, s2, ...): null if any input null (Spark concat)."""
    sql_name = "Concat"

    def __init__(self, *children: Expression):
        self.children = tuple(children)

    def with_new_children(self, children):
        return Concat(*children)

    @property
    def dtype(self):
        return T.StringType()

    def _eval(self, vals, ctx):
        xp = ctx.xp
        validity = vals[0].validity
        for v in vals[1:]:
            validity = validity & v.validity
        if not ctx.is_device:
            out = np.empty(ctx.capacity, dtype=object)
            for i in range(ctx.capacity):
                out[i] = "".join(v.data[i] for v in vals) if validity[i] else None
            return Val(out, validity, None, T.StringType())
        acc = vals[0]
        data, lengths = acc.data, acc.lengths
        for v in vals[1:]:
            data, lengths = _concat2_device(data, lengths, v.data, v.lengths, xp)
        return ctx.canonical(data, validity, T.StringType(), lengths)


def _concat2_device(da, la, db, lb, xp):
    from spark_rapids_tpu.columnar.column import round_string_width
    wa, wb = da.shape[1], db.shape[1]
    w = round_string_width(wa + wb)
    n = da.shape[0]
    j = xp.arange(w, dtype=np.int32)[None, :]
    from_a = j < la[:, None]
    ia = xp.broadcast_to(xp.clip(j, 0, wa - 1), (n, w))
    ib = xp.clip(j - la[:, None], 0, wb - 1)
    av = xp.take_along_axis(da, ia, axis=1)
    bv = xp.take_along_axis(db, ib, axis=1)
    new_len = la + lb
    keep = j < new_len[:, None]
    return xp.where(keep, xp.where(from_a, av, bv), 0), new_len


class _StringPredicate(Expression):
    def __init__(self, left: Expression, right: Expression):
        self.children = (left, right)

    @property
    def dtype(self):
        return T.BooleanType()

    def _eval(self, vals, ctx):
        a, b = vals
        validity = a.validity & b.validity
        if not ctx.is_device:
            data = np.array([self._host_one(x, y) if va and vb else False
                             for x, y, va, vb in
                             zip(a.data, b.data, a.validity, b.validity)], bool)
            return ctx.canonical(data, validity, T.BooleanType())
        return ctx.canonical(self._device(a, b, ctx), validity,
                             T.BooleanType())


class StartsWith(_StringPredicate):
    sql_name = "StartsWith"

    def _host_one(self, x, y):
        return x.startswith(y)

    def _device(self, a, b, ctx):
        xp = ctx.xp
        da, db = _string_pair_device(a, b, ctx)
        w = da.shape[1]
        j = xp.arange(w, dtype=np.int32)[None, :]
        within = j < b.lengths[:, None]
        match = xp.all(~within | (da == db), axis=1)
        return match & (a.lengths >= b.lengths)


class EndsWith(_StringPredicate):
    sql_name = "EndsWith"

    def _host_one(self, x, y):
        return x.endswith(y)

    def _device(self, a, b, ctx):
        xp = ctx.xp
        w = max(a.data.shape[1], b.data.shape[1])
        da, db = _string_pair_device(a, b, ctx)
        j = xp.arange(w, dtype=np.int32)[None, :]
        shift = (a.lengths - b.lengths)[:, None]
        idx = xp.clip(j + shift, 0, w - 1)
        tail = xp.take_along_axis(da, idx, axis=1)
        within = j < b.lengths[:, None]
        match = xp.all(~within | (tail == db), axis=1)
        return match & (a.lengths >= b.lengths)


class Contains(_StringPredicate):
    sql_name = "Contains"

    def _host_one(self, x, y):
        return y in x

    def _device(self, a, b, ctx):
        xp = ctx.xp
        da, db = _string_pair_device(a, b, ctx)
        w = da.shape[1]
        n = da.shape[0]
        j = xp.arange(w, dtype=np.int32)[None, :]
        within = j < b.lengths[:, None]
        found = xp.zeros(n, dtype=bool)
        # slide the needle over every start offset (static unroll over width;
        # VPU-dense compare per shift)
        for s in range(w):
            idx = xp.clip(j + s, 0, w - 1)
            win = xp.take_along_axis(da, idx, axis=1)
            m = xp.all(~within | (win == db), axis=1)
            found = found | (m & (s + b.lengths <= a.lengths))
        return found


class Like(Expression):
    """SQL LIKE. Device path handles the common shapes
    (%x, x%, %x%, exact); general patterns are host-only."""
    sql_name = "Like"

    def __init__(self, child: Expression, pattern: str, escape: str = "\\"):
        self.children = (child,)
        self.pattern = pattern
        self.escape = escape

    def with_new_children(self, children):
        return Like(children[0], self.pattern, self.escape)

    @property
    def dtype(self):
        return T.BooleanType()

    @property
    def device_supported(self):
        return self._simple_shape() is not None

    def _simple_shape(self):
        """(kind, needle) for %-only patterns without _ or escapes."""
        p = self.pattern
        if "_" in p or self.escape in p:
            return None
        body = p.strip("%")
        if "%" in body:
            return None
        if p.startswith("%") and p.endswith("%") and len(p) >= 2:
            return ("contains", body)
        if p.endswith("%"):
            return ("prefix", body)
        if p.startswith("%"):
            return ("suffix", body)
        return ("equals", body)

    def _regex(self):
        import re
        out = []
        i = 0
        p = self.pattern
        while i < len(p):
            c = p[i]
            if c == self.escape and i + 1 < len(p):
                out.append(re.escape(p[i + 1]))
                i += 2
                continue
            if c == "%":
                out.append(".*")
            elif c == "_":
                out.append(".")
            else:
                out.append(re.escape(c))
            i += 1
        return re.compile("(?s)^" + "".join(out) + "$")

    def _eval(self, vals, ctx):
        a = vals[0]
        if not ctx.is_device:
            rx = self._regex()
            data = np.array([bool(rx.match(s)) if v else False
                             for s, v in zip(a.data, a.validity)], bool)
            return ctx.canonical(data, a.validity, T.BooleanType())
        shape = self._simple_shape()
        if shape is None:
            raise NotImplementedError("general LIKE is host-only")
        kind, needle = shape
        nv = ctx.const(needle, T.StringType())
        cls = {"contains": Contains, "prefix": StartsWith,
               "suffix": EndsWith}.get(kind)
        if cls is None:  # equals
            from spark_rapids_tpu.expr.predicates import _string_eq
            data = _string_eq(a, nv, ctx)
        else:
            data = cls(None, None)._device(a, nv, ctx)
        return ctx.canonical(data, a.validity, T.BooleanType())


class StringReplace(Expression):
    """replace(str, search, replace) with literal search — host-only for
    now (device literal replace lands with the breadth pass)."""
    sql_name = "StringReplace"

    def __init__(self, child: Expression, search: Expression,
                 replace: Expression):
        self.children = (child, search, replace)

    @property
    def dtype(self):
        return T.StringType()

    @property
    def device_supported(self):
        return False

    def _eval(self, vals, ctx):
        a, s, r = vals
        validity = a.validity & s.validity & r.validity
        out = np.empty(ctx.capacity, dtype=object)
        for i in range(ctx.capacity):
            if validity[i]:
                out[i] = a.data[i].replace(s.data[i], r.data[i]) \
                    if s.data[i] else a.data[i]
            else:
                out[i] = None
        return Val(out, validity, None, T.StringType())


# ---------------------------------------------------------------------------
# round-3 breadth (reference stringFunctions.scala GpuStringLocate /
# GpuConcatWs / GpuSubstringIndex / GpuInitCap / GpuStringLPad/RPad /
# GpuStringRepeat). Device kernels where the byte-matrix layout maps
# cleanly; pad/repeat/initcap are host-tagged (unicode-width semantics).
# ---------------------------------------------------------------------------

class ConcatWs(Expression):
    """concat_ws(sep, s1, s2, ...): null inputs are SKIPPED (no
    separator); result is null only when sep is null."""

    sql_name = "ConcatWs"

    def __init__(self, separator: str, *children: Expression):
        self.children = tuple(children)
        self.separator = separator

    def with_new_children(self, children):
        return ConcatWs(self.separator, *children)

    @property
    def dtype(self):
        return T.StringType()

    @property
    def nullable(self):
        return False

    def _eval(self, vals, ctx):
        xp = ctx.xp
        if not ctx.is_device:
            out = np.empty(ctx.capacity, dtype=object)
            for i in range(ctx.capacity):
                parts = [str(v.data[i]) for v in vals if v.validity[i]]
                out[i] = self.separator.join(parts)
            return Val(out, ctx.row_mask.copy(), None, T.StringType())
        sep = ctx._const_string(self.separator, ctx.row_mask)
        data = xp.zeros((ctx.capacity, 1), np.uint8)
        lengths = xp.zeros(ctx.capacity, np.int32)
        have_any = xp.zeros(ctx.capacity, bool)
        for v in vals:
            need_sep = have_any & v.validity
            sep_len = xp.where(need_sep, sep.lengths, 0)
            data, lengths = _concat2_device(data, lengths, sep.data, sep_len, xp)
            piece_len = xp.where(v.validity, v.lengths, 0)
            data, lengths = _concat2_device(data, lengths, v.data, piece_len, xp)
            have_any = have_any | v.validity
        validity = ctx.row_mask
        return ctx.canonical(data, validity, T.StringType(), lengths)


class StringLocate(Expression):
    """locate(substr, str[, start]): 1-based character position of the
    first occurrence at/after ``start``; 0 when absent; null inputs ->
    null (start is a literal int)."""

    sql_name = "StringLocate"

    def __init__(self, substr: Expression, string: Expression,
                 start: int = 1):
        self.children = (substr, string)
        self.start = start

    def with_new_children(self, children):
        return StringLocate(children[0], children[1], self.start)

    @property
    def dtype(self):
        return T.IntegerType()

    def _eval(self, vals, ctx):
        sub, s = vals
        xp = ctx.xp
        validity = sub.validity & s.validity
        if not ctx.is_device:
            out = np.zeros(ctx.capacity, np.int32)
            for i in range(ctx.capacity):
                if not validity[i]:
                    continue
                if self.start < 1:
                    out[i] = 0
                    continue
                out[i] = str(s.data[i]).find(str(sub.data[i]),
                                             self.start - 1) + 1
            return ctx.canonical(out, validity, T.IntegerType())
        if self.start < 1:
            return ctx.canonical(xp.zeros(ctx.capacity, np.int32), validity,
                                 T.IntegerType())
        w = s.data.shape[1]
        ws = sub.data.shape[1]
        j = xp.arange(w, dtype=np.int32)[None, :]
        # match[i, o] = bytes o..o+sublen match the needle
        match = xp.ones((ctx.capacity, w), bool)
        for k in range(ws):
            idx = xp.clip(j + k, 0, w - 1)
            sv = xp.take_along_axis(s.data, idx, axis=1)
            inside = k < sub.lengths[:, None]
            eq = sv == sub.data[:, k][:, None]
            valid_pos = (j + k) < s.lengths[:, None]
            match = match & xp.where(inside, eq & valid_pos, True)
        match = match & (j + sub.lengths[:, None] <= s.lengths[:, None])
        # character index of each byte + start filter (both char-based)
        starts = _char_starts(s.data, s.lengths, xp)
        char_idx = xp.cumsum(starts.astype(np.int32), axis=1) - 1
        match = match & starts & (char_idx >= (self.start - 1))
        empty = sub.lengths == 0
        found = xp.any(match, axis=1)
        first_byte = xp.argmax(match, axis=1)
        pos = xp.take_along_axis(char_idx, first_byte[:, None],
                                 axis=1)[:, 0] + 1
        nchars = xp.sum(starts, axis=1).astype(np.int32)
        out = xp.where(empty,
                       xp.where(self.start - 1 <= nchars, self.start, 0),
                       xp.where(found, pos, 0)).astype(np.int32)
        return ctx.canonical(out, validity, T.IntegerType())


class SubstringIndex(Expression):
    """substring_index(str, delim, count): prefix up to the count-th
    delimiter (suffix after |count|-th-from-end when count < 0);
    single-byte delimiters on device."""

    sql_name = "SubstringIndex"

    def __init__(self, child: Expression, delim: str, count: int):
        self.children = (child,)
        self.delim = delim
        self.count = count

    def with_new_children(self, children):
        return SubstringIndex(children[0], self.delim, self.count)

    @property
    def dtype(self):
        return T.StringType()

    @property
    def device_supported(self):
        return len(self.delim.encode("utf-8")) == 1

    def _eval(self, vals, ctx):
        a = vals[0]
        xp = ctx.xp
        if not ctx.is_device:
            out = np.empty(ctx.capacity, dtype=object)
            for i in range(ctx.capacity):
                if not a.validity[i]:
                    out[i] = None
                    continue
                s = str(a.data[i])
                c = self.count
                if c == 0 or not self.delim:
                    out[i] = ""
                elif c > 0:
                    out[i] = self.delim.join(s.split(self.delim)[:c])
                else:
                    out[i] = self.delim.join(s.split(self.delim)[c:])
            return Val(out, a.validity, None, T.StringType())
        w = a.data.shape[1]
        d = self.delim.encode("utf-8")[0]
        j = xp.arange(w, dtype=np.int32)[None, :]
        is_d = (a.data == np.uint8(d)) & (j < a.lengths[:, None])
        cum = xp.cumsum(is_d.astype(np.int32), axis=1)
        ndelim = xp.where(a.lengths > 0, cum[:, -1], 0) \
            if w > 0 else xp.zeros(ctx.capacity, np.int32)
        c = self.count
        if c == 0:
            return ctx.canonical(xp.zeros_like(a.data), a.validity,
                                 T.StringType(), xp.zeros_like(a.lengths))
        if c > 0:
            # end before the c-th delimiter (whole string if fewer)
            hit = is_d & (cum == c)
            found = xp.any(hit, axis=1)
            endb = xp.where(found, xp.argmax(hit, axis=1).astype(np.int32),
                            a.lengths)
            new_len = endb
            keep = j < new_len[:, None]
            data = xp.where(keep, a.data, 0)
            return ctx.canonical(data, a.validity, T.StringType(), new_len)
        # c < 0: start after the (ndelim + c)-th delimiter from the left
        k = ndelim + c + 1          # 1-based index of the delimiter
        hit = is_d & (cum == k[:, None])
        found = (k > 0) & xp.any(hit, axis=1)
        startb = xp.where(found,
                          xp.argmax(hit, axis=1).astype(np.int32) + 1, 0)
        new_len = (a.lengths - startb).astype(np.int32)
        idx = xp.clip(startb[:, None] + j, 0, w - 1)
        shifted = xp.take_along_axis(a.data, idx, axis=1)
        keep = j < new_len[:, None]
        data = xp.where(keep, shifted, 0)
        return ctx.canonical(data, a.validity, T.StringType(), new_len)


class _HostOnlyStringUnary(Expression):
    def __init__(self, child: Expression):
        self.children = (child,)

    @property
    def dtype(self):
        return T.StringType()

    @property
    def device_supported(self):
        return False


class InitCap(_HostOnlyStringUnary):
    """initcap: first letter of each word upper, rest lower (host-only:
    Java title-casing is unicode-table driven)."""

    sql_name = "InitCap"

    def _eval(self, vals, ctx):
        a = vals[0]
        out = np.empty(ctx.capacity, dtype=object)
        for i in range(ctx.capacity):
            if not a.validity[i]:
                out[i] = None
                continue
            s = str(a.data[i]).lower()
            out[i] = "".join(
                ch.upper() if k == 0 or s[k - 1] == " " else ch
                for k, ch in enumerate(s))
        return Val(out, a.validity, None, T.StringType())


class _PadBase(Expression):
    def __init__(self, child: Expression, length: int, pad: str = " "):
        self.children = (child,)
        self.length = length
        self.pad = pad

    def with_new_children(self, children):
        return type(self)(children[0], self.length, self.pad)

    @property
    def dtype(self):
        return T.StringType()

    @property
    def device_supported(self):
        return False  # char-width pad semantics are host-only for now

    def _eval(self, vals, ctx):
        a = vals[0]
        out = np.empty(ctx.capacity, dtype=object)
        for i in range(ctx.capacity):
            out[i] = self._pad(str(a.data[i])) if a.validity[i] else None
        return Val(out, a.validity, None, T.StringType())

    def _pad(self, s: str) -> str:
        n = max(self.length, 0)  # Spark: negative pad length -> ''
        if len(s) >= n:
            return s[:n]
        if not self.pad:
            return s
        fill = (self.pad * n)[: n - len(s)]
        return self._join(s, fill)


class StringLPad(_PadBase):
    sql_name = "StringLPad"

    def _join(self, s, fill):
        return fill + s


class StringRPad(_PadBase):
    sql_name = "StringRPad"

    def _join(self, s, fill):
        return s + fill


class StringRepeat(Expression):
    """repeat(str, n) (host-only: output width is data-dependent)."""

    sql_name = "StringRepeat"

    def __init__(self, child: Expression, times: Expression):
        self.children = (child, times)

    @property
    def dtype(self):
        return T.StringType()

    @property
    def device_supported(self):
        return False

    def _eval(self, vals, ctx):
        a, n = vals
        validity = a.validity & n.validity
        out = np.empty(ctx.capacity, dtype=object)
        for i in range(ctx.capacity):
            out[i] = str(a.data[i]) * max(int(n.data[i]), 0) \
                if validity[i] else None
        return Val(out, validity, None, T.StringType())


class Hex(Expression):
    """hex(n): uppercase hex of a long, leading zeros stripped, negative
    values as 16-digit two's complement — Spark Hex semantics
    (reference mathExpressions GpuHex; the mortgage benchmark
    anonymizes loan ids with hex(hash(id))).  Device path builds the
    byte matrix from nibbles in one vectorized program."""

    sql_name = "Hex"

    def __init__(self, child: Expression):
        self.children = (child,)

    @property
    def dtype(self):
        return T.StringType()

    def coerced(self):
        from spark_rapids_tpu.expr.cast import Cast
        t = self.children[0].dtype
        if t.integral and not isinstance(t, T.LongType):
            return Hex(Cast(self.children[0], T.LongType()))
        if not isinstance(t, T.LongType):
            raise TypeError(f"hex over {t} is not supported")
        return self

    def _eval(self, vals, ctx):
        a = vals[0]
        if not ctx.is_device:
            out = np.empty(ctx.capacity, dtype=object)
            for i in range(ctx.capacity):
                out[i] = format(int(a.data[i]) & 0xFFFFFFFFFFFFFFFF,
                                "X") if a.validity[i] else None
            return Val(out, a.validity.copy(), None, T.StringType())
        xp = ctx.xp
        v = a.data.astype(np.int64)
        shifts = xp.arange(60, -1, -4, dtype=np.int64)   # MSB nibble first
        nib = (v[:, None] >> shifts[None, :]) & 0xF
        chars = xp.where(nib < 10, nib + 48, nib + 55).astype(np.uint8)
        nz = nib != 0
        first = xp.argmax(nz, axis=1)
        first = xp.where(xp.any(nz, axis=1), first, 15)
        lengths = (16 - first).astype(np.int32)
        idx = xp.clip(first[:, None] + xp.arange(16)[None, :], 0, 15)
        data = xp.take_along_axis(chars, idx, axis=1)
        data = xp.where(a.validity[:, None], data, 0)
        return ctx.canonical(data, a.validity,
                             T.StringType(),
                             xp.where(a.validity, lengths, 0))

"""Expression IR with dual evaluation: CPU oracle (numpy) and TPU (jax).

The reference implements ~150 GPU expressions as ``GpuExpression.columnarEval``
over cuDF columns (reference GpuExpressions.scala:380 and the registry
GpuOverrides.scala:537-1660).  Here each expression node carries ONE kernel
written against a backend-neutral array namespace (numpy | jax.numpy), so the
CPU oracle and the TPU path share semantics by construction; only
string/variable-width ops branch per backend (object arrays on host, padded
byte matrices on device).
"""
from spark_rapids_tpu.expr.core import (
    Expression, Literal, BoundReference, UnresolvedAttribute, Alias,
    col, lit, bind, eval_host, eval_device, EvalCtx, Val,
)
from spark_rapids_tpu.expr import arithmetic, predicates, conditional, cast  # noqa: F401
from spark_rapids_tpu.expr import strings, datetime_ops, math_ops, hashing  # noqa: F401
from spark_rapids_tpu.expr import aggregates, null_ops, regexp, misc  # noqa: F401
from spark_rapids_tpu.expr import collections  # noqa: F401

__all__ = [
    "Expression", "Literal", "BoundReference", "UnresolvedAttribute", "Alias",
    "col", "lit", "bind", "eval_host", "eval_device", "EvalCtx", "Val",
]

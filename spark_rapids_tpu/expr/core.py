"""Expression tree core: nodes, binding, and the shared eval machinery.

Reference analog: GpuExpressions.scala (base traits), GpuBoundAttribute.scala
(binding named attributes to column ordinals), literals.scala,
namedExpressions.scala.  Unlike the reference (which piggybacks on Catalyst
for analysis), this framework is standalone, so name resolution and numeric
type coercion live here (`bind`).

Evaluation model: an expression evaluates over a list of input ``Val``s (one
per input column) in an ``EvalCtx`` that says which backend is active:

* host oracle — numpy arrays, no padding (capacity == num_rows);
* device — jax arrays of static ``capacity`` with a traced row mask; the
  same kernel code runs under ``jax.jit``.

Both paths share null semantics: a ``Val`` is (data, validity); binary ops
AND the validities unless the op defines otherwise (three-valued logic for
And/Or, null-safe equality, ...).
"""
from __future__ import annotations

from dataclasses import dataclass
from typing import Any, Optional, Sequence

import numpy as np

from spark_rapids_tpu import types as T

__all__ = [
    "Val", "EvalCtx", "Expression", "Literal", "BoundReference",
    "UnresolvedAttribute", "Alias", "col", "lit", "grouping_id", "bind",
    "eval_host", "eval_device",
]


@dataclass
class Val:
    """Backend-neutral column value flowing through expression eval.

    data:     numpy or jax array. For strings: host -> object ndarray of str,
              device -> uint8[capacity, width] padded byte matrix.
    validity: bool array [capacity].
    lengths:  device strings only, int32[capacity]; None on host.
    dtype:    SQL type.
    """
    data: Any
    validity: Any
    lengths: Any
    dtype: T.DataType

    @property
    def is_string(self) -> bool:
        return isinstance(self.dtype, T.StringType)


class EvalCtx:
    """Evaluation context: backend namespace + batch geometry."""

    def __init__(self, xp, is_device: bool, capacity: int, row_mask):
        self.xp = xp                  # numpy or jax.numpy
        self.is_device = is_device
        self.capacity = capacity      # == num_rows on host
        self.row_mask = row_mask      # bool[capacity]: True = real row

    def const(self, value, dtype: T.DataType) -> Val:
        """Broadcast a python scalar (or None) to a full-capacity Val."""
        xp = self.xp
        if value is None:
            validity = xp.zeros(self.capacity, dtype=bool)
            if isinstance(dtype, T.StringType):
                return self._const_string("", validity)
            npdt = dtype.np_dtype
            return Val(xp.zeros(self.capacity, dtype=npdt), validity, None, dtype)
        validity = self.row_mask
        if isinstance(dtype, T.StringType):
            return self._const_string(str(value), validity)
        npdt = dtype.np_dtype
        data = xp.full(self.capacity, value, dtype=npdt)
        data = xp.where(validity, data, xp.zeros((), npdt))
        return Val(data, validity, None, dtype)

    def _const_string(self, s: str, validity) -> Val:
        xp = self.xp
        if not self.is_device:
            data = np.full(self.capacity, s, dtype=object)
            return Val(data, validity, None, T.StringType())
        from spark_rapids_tpu.columnar.column import round_string_width
        bs = s.encode("utf-8")
        w = round_string_width(max(len(bs), 1))
        row = np.zeros(w, dtype=np.uint8)
        row[:len(bs)] = np.frombuffer(bs, dtype=np.uint8)
        data = xp.broadcast_to(xp.asarray(row), (self.capacity, w))
        data = xp.where(validity[:, None], data, 0)
        lengths = xp.where(validity, len(bs), 0).astype("int32")
        return Val(data, validity, lengths, T.StringType())

    def canonical(self, data, validity, dtype: T.DataType, lengths=None) -> Val:
        """Zero data at invalid slots (padding discipline, see columnar/)."""
        xp = self.xp
        var_width = isinstance(dtype, (T.StringType, T.ArrayType))
        if var_width and self.is_device:
            data = xp.where(validity[:, None], data, 0)
            lengths = xp.where(validity, lengths, 0)
            return Val(data, validity, lengths, dtype)
        if var_width:
            return Val(data, validity, None, dtype)
        data = xp.where(validity, data, xp.zeros((), data.dtype))
        return Val(data, validity, None, dtype)


class Expression:
    """Base expression node. Immutable; children in ``self.children``."""

    children: tuple["Expression", ...] = ()
    #: explain/registry name (reference: expression class name in
    #: GpuOverrides registry keys, e.g. spark.rapids.sql.expression.Add)
    sql_name: str = "Expression"

    # -- analysis ----------------------------------------------------------
    @property
    def dtype(self) -> T.DataType:
        raise NotImplementedError(type(self).__name__)

    @property
    def nullable(self) -> bool:
        return any(c.nullable for c in self.children)

    #: False when this node can only run on the host oracle (the planner's
    #: tagging pass checks the whole tree; reference: RapidsMeta
    #: willNotWorkOnGpu, RapidsMeta.scala:66-300)
    @property
    def device_supported(self) -> bool:
        return True

    def with_new_children(self, children: Sequence["Expression"]) -> "Expression":
        """Rebuild this node with new children (default: positional ctor)."""
        return type(self)(*children)

    def coerced(self) -> "Expression":
        """Hook: insert casts after children are bound (type coercion)."""
        return self

    # -- evaluation --------------------------------------------------------
    def eval(self, inputs: list[Val], ctx: EvalCtx) -> Val:
        child_vals = [c.eval(inputs, ctx) for c in self.children]
        return self._eval(child_vals, ctx)

    def _eval(self, vals: list[Val], ctx: EvalCtx) -> Val:
        raise NotImplementedError(type(self).__name__)

    # -- tree utilities ----------------------------------------------------
    def walk(self):
        """Pre-order iterator over this node and all descendants."""
        yield self
        for c in self.children:
            yield from c.walk()

    def transform_up(self, fn) -> "Expression":
        new_children = [c.transform_up(fn) for c in self.children]
        node = self if all(a is b for a, b in zip(new_children, self.children)) \
            else self.with_new_children(new_children)
        return fn(node)

    def references(self) -> set[str]:
        out = set()
        for c in self.children:
            out |= c.references()
        return out

    def __repr__(self) -> str:
        if self.children:
            return f"{self.sql_name}({', '.join(map(repr, self.children))})"
        return self.sql_name

    # -- builder sugar (DataFrame column API) ------------------------------
    def _bin(self, other, cls, flip=False):
        other = other if isinstance(other, Expression) else Literal.infer(other)
        return cls(other, self) if flip else cls(self, other)

    def __add__(self, o):
        from spark_rapids_tpu.expr.arithmetic import Add
        return self._bin(o, Add)

    def __radd__(self, o):
        from spark_rapids_tpu.expr.arithmetic import Add
        return self._bin(o, Add, flip=True)

    def __sub__(self, o):
        from spark_rapids_tpu.expr.arithmetic import Subtract
        return self._bin(o, Subtract)

    def __rsub__(self, o):
        from spark_rapids_tpu.expr.arithmetic import Subtract
        return self._bin(o, Subtract, flip=True)

    def __mul__(self, o):
        from spark_rapids_tpu.expr.arithmetic import Multiply
        return self._bin(o, Multiply)

    def __rmul__(self, o):
        from spark_rapids_tpu.expr.arithmetic import Multiply
        return self._bin(o, Multiply, flip=True)

    def __truediv__(self, o):
        from spark_rapids_tpu.expr.arithmetic import Divide
        return self._bin(o, Divide)

    def __rtruediv__(self, o):
        from spark_rapids_tpu.expr.arithmetic import Divide
        return self._bin(o, Divide, flip=True)

    def __mod__(self, o):
        from spark_rapids_tpu.expr.arithmetic import Remainder
        return self._bin(o, Remainder)

    def __neg__(self):
        from spark_rapids_tpu.expr.arithmetic import UnaryMinus
        return UnaryMinus(self)

    def __eq__(self, o):  # noqa: A003 - expression DSL, not identity
        from spark_rapids_tpu.expr.predicates import EqualTo
        return self._bin(o, EqualTo)

    def __ne__(self, o):
        from spark_rapids_tpu.expr.predicates import EqualTo, Not
        return Not(self._bin(o, EqualTo))

    def __lt__(self, o):
        from spark_rapids_tpu.expr.predicates import LessThan
        return self._bin(o, LessThan)

    def __le__(self, o):
        from spark_rapids_tpu.expr.predicates import LessThanOrEqual
        return self._bin(o, LessThanOrEqual)

    def __gt__(self, o):
        from spark_rapids_tpu.expr.predicates import GreaterThan
        return self._bin(o, GreaterThan)

    def __ge__(self, o):
        from spark_rapids_tpu.expr.predicates import GreaterThanOrEqual
        return self._bin(o, GreaterThanOrEqual)

    def __and__(self, o):
        from spark_rapids_tpu.expr.predicates import And
        return self._bin(o, And)

    def __or__(self, o):
        from spark_rapids_tpu.expr.predicates import Or
        return self._bin(o, Or)

    def __invert__(self):
        from spark_rapids_tpu.expr.predicates import Not
        return Not(self)

    def __hash__(self):
        return id(self)

    def alias(self, name: str) -> "Alias":
        return Alias(self, name)

    def cast(self, dt: T.DataType) -> "Expression":
        from spark_rapids_tpu.expr.cast import Cast
        return Cast(self, dt)

    def is_null(self) -> "Expression":
        from spark_rapids_tpu.expr.predicates import IsNull
        return IsNull(self)

    def is_not_null(self) -> "Expression":
        from spark_rapids_tpu.expr.predicates import IsNotNull
        return IsNotNull(self)

    def isin(self, *values) -> "Expression":
        from spark_rapids_tpu.expr.predicates import In
        return In(self, [v if isinstance(v, Expression) else Literal.infer(v)
                         for v in values])

    def substr(self, pos, length) -> "Expression":
        from spark_rapids_tpu.expr.strings import Substring
        return Substring(self, Literal.infer(pos), Literal.infer(length))

    def startswith(self, s) -> "Expression":
        from spark_rapids_tpu.expr.strings import StartsWith
        return self._bin(s, StartsWith)

    def endswith(self, s) -> "Expression":
        from spark_rapids_tpu.expr.strings import EndsWith
        return self._bin(s, EndsWith)

    def contains(self, s) -> "Expression":
        from spark_rapids_tpu.expr.strings import Contains
        return self._bin(s, Contains)

    def like(self, pattern: str) -> "Expression":
        from spark_rapids_tpu.expr.strings import Like
        return Like(self, pattern)


class Literal(Expression):
    sql_name = "Literal"

    def __init__(self, value, dtype: T.DataType):
        self.value = value
        self._dtype = dtype

    @property
    def dtype(self):
        return self._dtype

    @property
    def nullable(self):
        return self.value is None

    def with_new_children(self, children):
        return self

    @staticmethod
    def infer(v) -> "Literal":
        if isinstance(v, Literal):
            return v
        if v is None:
            return Literal(None, T.NullType())
        if isinstance(v, bool):
            return Literal(v, T.BooleanType())
        if isinstance(v, int):
            # Spark python ints become LongType unless they fit... Spark
            # literalizes python int as LongType; keep that.
            return Literal(v, T.LongType())
        if isinstance(v, float):
            return Literal(v, T.DoubleType())
        if isinstance(v, str):
            return Literal(v, T.StringType())
        if isinstance(v, np.integer):
            return Literal(int(v), T.LongType())
        if isinstance(v, np.floating):
            return Literal(float(v), T.DoubleType())
        import datetime as _dt
        if isinstance(v, _dt.datetime):
            epoch = _dt.datetime(1970, 1, 1, tzinfo=v.tzinfo or _dt.timezone.utc)
            if v.tzinfo is None:
                v = v.replace(tzinfo=_dt.timezone.utc)
            micros = int((v - epoch).total_seconds() * 1_000_000)
            return Literal(micros, T.TimestampType())
        if isinstance(v, _dt.date):
            days = (v - _dt.date(1970, 1, 1)).days
            return Literal(days, T.DateType())
        raise TypeError(f"cannot create literal from {type(v)}")

    def eval(self, inputs, ctx):
        return ctx.const(self.value, self._dtype)

    def __repr__(self):
        return f"lit({self.value!r})"


class BoundReference(Expression):
    """Resolved input-column reference (reference GpuBoundAttribute.scala)."""
    sql_name = "BoundReference"

    def __init__(self, index: int, dtype: T.DataType, nullable: bool = True,
                 name: str = ""):
        self.index = index
        self._dtype = dtype
        self._nullable = nullable
        self.name = name

    @property
    def dtype(self):
        return self._dtype

    @property
    def nullable(self):
        return self._nullable

    def with_new_children(self, children):
        return self

    def references(self):
        return {self.name} if self.name else set()

    def eval(self, inputs, ctx):
        return inputs[self.index]

    def __repr__(self):
        return f"#{self.index}:{self.name or self._dtype.name}"


class UnresolvedAttribute(Expression):
    """Named column before binding (`col("x")`)."""
    sql_name = "UnresolvedAttribute"

    def __init__(self, name: str):
        self.name = name

    @property
    def dtype(self):
        raise TypeError(f"unresolved attribute {self.name!r} has no dtype; "
                        "bind() against a schema first")

    def with_new_children(self, children):
        return self

    def references(self):
        return {self.name}

    def __repr__(self):
        return f"col({self.name!r})"


class Alias(Expression):
    sql_name = "Alias"

    def __init__(self, child: Expression, name: str):
        self.children = (child,)
        self.name = name

    @property
    def child(self):
        return self.children[0]

    @property
    def dtype(self):
        return self.child.dtype

    def with_new_children(self, children):
        return Alias(children[0], self.name)

    def _eval(self, vals, ctx):
        return vals[0]

    def __repr__(self):
        return f"{self.child!r} AS {self.name}"


def col(name: str) -> UnresolvedAttribute:
    return UnresolvedAttribute(name)


def lit(v) -> Literal:
    return Literal.infer(v)


def grouping_id() -> UnresolvedAttribute:
    """The grouping-set id column produced by rollup/cube/grouping_sets
    (Spark's grouping_id(); bit i set = key i was nulled out)."""
    return UnresolvedAttribute("spark_grouping_id")


def output_name(e: Expression) -> str:
    if isinstance(e, Alias):
        return e.name
    if isinstance(e, UnresolvedAttribute):
        return e.name
    if isinstance(e, BoundReference) and e.name:
        return e.name
    return repr(e)


# ---------------------------------------------------------------------------
# Binding & coercion (the standalone analog of Catalyst analysis)
# ---------------------------------------------------------------------------

def bind(expr: Expression, schema: T.Schema) -> Expression:
    """Resolve names to BoundReferences against ``schema``, then run type
    coercion bottom-up (inserting Casts).  Returns a fully-typed tree."""

    def resolve(node: Expression) -> Expression:
        if isinstance(node, UnresolvedAttribute):
            i = schema.index_of(node.name)
            f = schema.fields[i]
            return BoundReference(i, f.data_type, f.nullable, f.name)
        return node.coerced()

    return expr.transform_up(resolve)


def eval_host(expr: Expression, batch) -> "HostColumn":
    """Evaluate a bound expression over a HostBatch -> HostColumn."""
    from spark_rapids_tpu.host.batch import HostColumn
    n = batch.num_rows
    ctx = EvalCtx(np, False, n, np.ones(n, dtype=np.bool_))
    inputs = [Val(c.data, c.validity, None, c.dtype) for c in batch.columns]
    v = expr.eval(inputs, ctx)
    if v.is_string:
        return HostColumn(np.where(v.validity, v.data, None), v.validity, v.dtype)
    return HostColumn(np.asarray(v.data), np.asarray(v.validity), v.dtype)


def eval_device(expr: Expression, batch) -> "DeviceColumn":
    """Evaluate a bound expression over a ColumnBatch -> DeviceColumn.

    Jit-safe: call inside a jitted program over the batch pytree.
    """
    import jax.numpy as jnp
    from spark_rapids_tpu.columnar.column import DeviceColumn
    ctx = EvalCtx(jnp, True, batch.capacity, batch.row_mask())
    inputs = [Val(c.data, c.validity, c.lengths, c.dtype)
              for c in batch.columns]
    v = expr.eval(inputs, ctx)
    v = ctx.canonical(v.data, v.validity, v.dtype, v.lengths)
    return DeviceColumn(v.data, v.validity, v.dtype, v.lengths)

"""Spark-compatible Murmur3 (x86_32, seed 42) hash.

Reference: HashFunctions.scala (GpuMurmur3Hash) — the hash behind
GpuHashPartitioning (GpuHashPartitioning.scala: cudf murmur3 % n).  Bit-exact
parity with Spark's Murmur3Hash is what makes a CPU-written shuffle readable
by the TPU side and vice versa, and makes differential partitioning tests
possible, so this implements org.apache.spark.sql.catalyst.expressions
.Murmur3Hash exactly:

* int/date/bool/byte/short -> hashInt of the 32-bit value;
* long/timestamp -> hashLong; float -> hashInt(bits), double ->
  hashLong(bits), with -0.0 normalized to 0.0;
* string -> hashUnsafeBytes over UTF-8: 4-byte little-endian blocks, then
  remaining bytes one at a time as *signed* ints;
* null -> passes the running seed through unchanged;
* multiple columns chain: h = hash(col_i, h).

All arithmetic is uint32 with wraparound, identical under numpy and XLA.
"""
from __future__ import annotations

import numpy as np

from spark_rapids_tpu import types as T
from spark_rapids_tpu.expr.core import Expression, EvalCtx, Val

__all__ = ["Murmur3Hash", "murmur3_val", "DEFAULT_SEED"]

DEFAULT_SEED = 42

_C1 = np.uint32(0xCC9E2D51)
_C2 = np.uint32(0x1B873593)
_M5 = np.uint32(5)
_MX = np.uint32(0xE6546B64)


def _u32(x, xp):
    return x.astype(np.uint32)


def _rotl(x, n, xp):
    return (x << np.uint32(n)) | (x >> np.uint32(32 - n))


def _mix_k1(k1, xp):
    k1 = k1 * _C1
    k1 = _rotl(k1, 15, xp)
    return k1 * _C2


def _mix_h1(h1, k1, xp):
    h1 = h1 ^ k1
    h1 = _rotl(h1, 13, xp)
    return h1 * _M5 + _MX


def _fmix(h1, length, xp):
    h1 = h1 ^ np.uint32(length) if np.isscalar(length) else h1 ^ length.astype(np.uint32)
    h1 = h1 ^ (h1 >> np.uint32(16))
    h1 = h1 * np.uint32(0x85EBCA6B)
    h1 = h1 ^ (h1 >> np.uint32(13))
    h1 = h1 * np.uint32(0xC2B2AE35)
    return h1 ^ (h1 >> np.uint32(16))


def _hash_int(i32, seed_u32, xp):
    """i32: int32-valued array; seed: uint32 array."""
    k1 = _mix_k1(i32.astype(np.uint32), xp)
    h1 = _mix_h1(seed_u32, k1, xp)
    return _fmix(h1, 4, xp)


def _hash_long(i64, seed_u32, xp):
    low = (i64 & np.int64(0xFFFFFFFF)).astype(np.uint32)
    high = ((i64 >> np.int64(32)) & np.int64(0xFFFFFFFF)).astype(np.uint32)
    h1 = _mix_h1(seed_u32, _mix_k1(low, xp), xp)
    h1 = _mix_h1(h1, _mix_k1(high, xp), xp)
    return _fmix(h1, 8, xp)


def _float_bits(f32, xp):
    # normalize -0.0 to 0.0 (Spark); NaN: Java floatToIntBits canonical NaN
    zero = xp.zeros((), f32.dtype)
    f32 = xp.where(f32 == zero, zero, f32)
    bits = f32.view(np.int32) if xp is np else _jax_bitcast(f32, np.int32)
    canonical = np.int32(0x7FC00000)
    return xp.where(xp.isnan(f32), canonical, bits)


def _double_bits(f64, xp):
    zero = xp.zeros((), f64.dtype)
    f64 = xp.where(f64 == zero, zero, f64)
    if xp is np:
        bits = f64.view(np.int64)
    else:
        # TPU XLA lacks 64-bit bitcast (see ops/sort.py note): split via
        # float64 -> two float32 halves is lossy; instead bitcast through
        # uint32 pairs using jax's dtype view on device buffers is not
        # traceable, so decompose arithmetically: Java doubleToLongBits is
        # sign/exponent/mantissa packing.
        bits = _jax_double_bits(f64)
    canonical = np.int64(0x7FF8000000000000)
    return xp.where(xp.isnan(f64), canonical, bits)


def _jax_bitcast(x, dt):
    import jax.lax as lax
    return lax.bitcast_convert_type(x, dt)


    # exact power-of-two tables (host-built). TPU v5e XLA implements neither
    # 64-bit bitcast-convert nor frexp/ldexp, so the decomposition uses
    # searchsorted over exact boundaries + exact power-of-two multiplies.


_POW2_BOUNDS = 2.0 ** np.arange(-1022, 1024)          # 2^e, e in [-1022,1023]
_POW2_INV = 2.0 ** np.arange(-512, 513).astype(np.float64)  # normal range


def _jax_double_bits(f64):
    """doubleToLongBits without 64-bit bitcast / frexp / ldexp: find the
    exponent by binary search over exact 2^e boundaries, recover the
    mantissa with two exact power-of-two multiplies (each factor normal, so
    no subnormal flush), and repack as int64."""
    import jax.numpy as jnp
    # caller (_double_bits) has already normalized -0.0 to +0.0, so a plain
    # comparison gives the sign (jnp.signbit lowers to a 64-bit bitcast,
    # unsupported on TPU)
    sign = f64 < 0
    af = jnp.abs(f64)
    inf = jnp.isinf(af)
    zero = af == 0
    nan = jnp.isnan(af)
    safe = jnp.where(inf | zero | nan, jnp.float64(1.0), af)
    bounds = jnp.asarray(_POW2_BOUNDS)
    idx = jnp.clip(jnp.searchsorted(bounds, safe, side="right") - 1, 0,
                   len(_POW2_BOUNDS) - 1)
    e = idx.astype(np.int64) - 1022
    # split 2^-e into two normal-range factors so every multiply is exact
    e1 = e // 2
    e2 = e - e1
    inv = jnp.asarray(_POW2_INV)
    m1 = (safe * inv[(-e1 + 512).astype(np.int32)]) \
        * inv[(-e2 + 512).astype(np.int32)]          # in [1, 2)
    mant = ((m1 - 1.0) * np.float64(2.0 ** 52)).astype(np.int64)
    biased = e + 1023
    # subnormal input: biased exponent 0, mantissa = af * 2^1074 (staged as
    # two exact multiplies to stay in range)
    is_sub = af < np.float64(2.0 ** -1022)
    sub_mant = ((af * np.float64(2.0 ** 537)) * np.float64(2.0 ** 537)) \
        .astype(np.int64)
    mant = jnp.where(is_sub, sub_mant, mant)
    biased = jnp.where(is_sub, 0, biased)
    bits = (biased << np.int64(52)) | mant
    bits = jnp.where(zero, np.int64(0), bits)
    bits = jnp.where(inf, np.int64(0x7FF0000000000000), bits)
    return jnp.where(sign, bits | np.int64(-0x8000000000000000), bits)


def _hash_string_host(data, validity, seed_u32):
    out = seed_u32.copy()
    for i in range(len(data)):
        if not validity[i]:
            continue
        bs = data[i].encode("utf-8")
        h = np.uint32(out[i])
        n = len(bs)
        na = n - n % 4
        with np.errstate(over="ignore"):
            for j in range(0, na, 4):
                block = np.uint32(int.from_bytes(bs[j:j + 4], "little"))
                h = _mix_h1(h, _mix_k1(block, np), np)
            for j in range(na, n):
                b = bs[j]
                sb = np.uint32(b if b < 128 else b - 256)  # signed byte
                h = _mix_h1(h, _mix_k1(sb, np), np)
            out[i] = _fmix(h, np.uint32(n), np)
    return out


def _hash_string_device(data, lengths, seed_u32, xp):
    """Vectorized over the padded byte matrix: fold blocks (each row uses
    only its first len//4 blocks), then up to 3 tail bytes."""
    n, w = data.shape
    nblocks_row = lengths // 4
    tail_len = lengths % 4
    h = seed_u32
    d32 = data.astype(np.uint32)
    nblocks = w // 4
    for j in range(nblocks):
        b = (d32[:, 4 * j]
             | (d32[:, 4 * j + 1] << np.uint32(8))
             | (d32[:, 4 * j + 2] << np.uint32(16))
             | (d32[:, 4 * j + 3] << np.uint32(24)))
        mixed = _mix_h1(h, _mix_k1(b, xp), xp)
        h = xp.where(j < nblocks_row, mixed, h)
    base = (nblocks_row * 4).astype(np.int32)
    for t in range(3):
        idx = xp.clip(base + t, 0, w - 1)
        byte = xp.take_along_axis(data, idx[:, None], axis=1)[:, 0]
        signed = xp.where(byte < 128, byte.astype(np.int32),
                          byte.astype(np.int32) - 256)
        mixed = _mix_h1(h, _mix_k1(signed.astype(np.uint32), xp), xp)
        h = xp.where(t < tail_len, mixed, h)
    return _fmix(h, lengths.astype(np.uint32), xp)


def murmur3_val(v: Val, seed_u32, ctx: EvalCtx):
    """Hash one column into the running seed array (uint32[capacity])."""
    xp = ctx.xp
    dt = v.dtype
    if isinstance(dt, T.StringType):
        if ctx.is_device:
            h = _hash_string_device(v.data, v.lengths, seed_u32, xp)
        else:
            h = _hash_string_host(v.data, v.validity, seed_u32)
    elif isinstance(dt, (T.LongType, T.TimestampType)):
        h = _hash_long(v.data, seed_u32, xp)
    elif isinstance(dt, T.DoubleType):
        h = _hash_long(_double_bits(v.data, xp), seed_u32, xp)
    elif isinstance(dt, T.FloatType):
        h = _hash_int(_float_bits(v.data, xp), seed_u32, xp)
    elif isinstance(dt, T.BooleanType):
        h = _hash_int(v.data.astype(np.int32), seed_u32, xp)
    else:  # byte/short/int/date
        h = _hash_int(v.data.astype(np.int32), seed_u32, xp)
    # null columns pass the seed through
    return xp.where(v.validity, h, seed_u32)


class Murmur3Hash(Expression):
    """hash(c1, c2, ...) -> IntegerType, seed 42."""
    sql_name = "Murmur3Hash"

    def __init__(self, *children: Expression, seed: int = DEFAULT_SEED):
        self.children = tuple(children)
        self.seed = seed

    def with_new_children(self, children):
        return Murmur3Hash(*children, seed=self.seed)

    @property
    def dtype(self):
        return T.IntegerType()

    @property
    def nullable(self):
        return False

    def _eval(self, vals, ctx):
        xp = ctx.xp
        h = xp.full(ctx.capacity, np.uint32(self.seed), dtype=np.uint32)
        with np.errstate(over="ignore"):
            for v in vals:
                h = murmur3_val(v, h, ctx)
        return ctx.canonical(h.astype(np.int32), ctx.row_mask,
                             T.IntegerType())

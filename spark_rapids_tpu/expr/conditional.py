"""Conditional expressions: If, CaseWhen, Coalesce, Nvl/NullIf.

Reference: conditionalExpressions.scala (233 LoC), nullExpressions.scala
(287 LoC).  Columnar evaluation computes all branches and selects — the
same strategy the reference uses on GPU.
"""
from __future__ import annotations

from spark_rapids_tpu import types as T
from spark_rapids_tpu.expr.core import Expression, EvalCtx, Val

__all__ = ["If", "CaseWhen", "Coalesce"]


def _select(pred_data, t: Val, f: Val, dtype, ctx: EvalCtx) -> Val:
    """where(pred, t, f) handling string matrices on device."""
    xp = ctx.xp
    if isinstance(dtype, T.ArrayType):
        raise ValueError("conditional selection over array columns is "
                         "not supported")
    validity = xp.where(pred_data, t.validity, f.validity)
    if isinstance(dtype, T.StringType) and ctx.is_device:
        td, fd = t.data, f.data
        wt, wf = td.shape[1], fd.shape[1]
        w = max(wt, wf)
        if wt < w:
            td = xp.pad(td, ((0, 0), (0, w - wt)))
        if wf < w:
            fd = xp.pad(fd, ((0, 0), (0, w - wf)))
        data = xp.where(pred_data[:, None], td, fd)
        lengths = xp.where(pred_data, t.lengths, f.lengths)
        return ctx.canonical(data, validity, dtype, lengths)
    data = xp.where(pred_data, t.data, f.data)
    return ctx.canonical(data, validity, dtype, None)


def _common_type(types: list[T.DataType]) -> T.DataType:
    target = None
    for t in types:
        if isinstance(t, T.NullType):
            continue
        if target is None or t == target:
            target = t
        elif t.numeric and target.numeric:
            target = T.numeric_promote(target, t)
        else:
            raise TypeError(f"no common type for {types}")
    return target if target is not None else T.NullType()


class If(Expression):
    sql_name = "If"

    def __init__(self, pred: Expression, t: Expression, f: Expression):
        self.children = (pred, t, f)

    def coerced(self):
        from spark_rapids_tpu.expr.cast import Cast
        p, t, f = self.children
        target = _common_type([t.dtype, f.dtype])
        if t.dtype != target:
            t = Cast(t, target)
        if f.dtype != target:
            f = Cast(f, target)
        return If(p, t, f)

    @property
    def dtype(self):
        return self.children[1].dtype

    def _eval(self, vals, ctx):
        p, t, f = vals
        cond = p.data & p.validity  # null predicate -> false branch
        return _select(cond, t, f, self.dtype, ctx)


class CaseWhen(Expression):
    """CASE WHEN c1 THEN v1 [WHEN c2 THEN v2 ...] [ELSE e] END.

    Children layout: [c1, v1, c2, v2, ..., (else)] — odd count means an
    else branch is present.
    """
    sql_name = "CaseWhen"

    def __init__(self, branches: list[tuple[Expression, Expression]],
                 else_value: Expression | None = None):
        kids = []
        for c, v in branches:
            kids += [c, v]
        if else_value is not None:
            kids.append(else_value)
        self.children = tuple(kids)
        self._nbranches = len(branches)
        self._has_else = else_value is not None

    def _split(self, seq):
        branches = [(seq[2 * i], seq[2 * i + 1]) for i in range(self._nbranches)]
        els = seq[-1] if self._has_else else None
        return branches, els

    def with_new_children(self, children):
        b, e = self._split(list(children))
        return CaseWhen(b, e)

    def coerced(self):
        from spark_rapids_tpu.expr.cast import Cast
        branches, els = self._split(list(self.children))
        vals = [v for _, v in branches] + ([els] if els is not None else [])
        target = _common_type([v.dtype for v in vals])
        branches = [(c, v if v.dtype == target else Cast(v, target))
                    for c, v in branches]
        if els is not None and els.dtype != target:
            els = Cast(els, target)
        return CaseWhen(branches, els)

    @property
    def dtype(self):
        branches, els = self._split(list(self.children))
        for _, v in branches:
            if not isinstance(v.dtype, T.NullType):
                return v.dtype
        return els.dtype if els is not None else T.NullType()

    def _eval(self, vals, ctx):
        branches, els = self._split(vals)
        xp = ctx.xp
        if els is not None:
            result = els
        else:
            result = ctx.const(None, self.dtype)
        # fold right-to-left so the first matching branch wins
        for cond, val in reversed(branches):
            pred = cond.data & cond.validity
            result = _select(pred, val, result, self.dtype, ctx)
        return result


class Coalesce(Expression):
    """First non-null argument."""
    sql_name = "Coalesce"

    def __init__(self, *children: Expression):
        self.children = tuple(children)

    def with_new_children(self, children):
        return Coalesce(*children)

    def coerced(self):
        from spark_rapids_tpu.expr.cast import Cast
        target = _common_type([c.dtype for c in self.children])
        kids = [c if c.dtype == target else Cast(c, target)
                for c in self.children]
        return Coalesce(*kids)

    @property
    def dtype(self):
        return _common_type([c.dtype for c in self.children])

    def _eval(self, vals, ctx):
        result = vals[-1]
        for v in reversed(vals[:-1]):
            result = _select(v.validity, v, result, self.dtype, ctx)
        return result

"""Predicates: comparisons, boolean logic, null tests, IN.

Reference: predicates.scala (629 LoC), nullExpressions.scala.

Spark float semantics (docs/compatibility.md in the reference; Spark NaN
semantics): NaN = NaN is TRUE, NaN is larger than any other value, and
-0.0 == 0.0.  Three-valued logic for AND/OR.  String comparisons are
byte-lexicographic (UTF-8 order == code-point order).
"""
from __future__ import annotations

from spark_rapids_tpu import types as T
from spark_rapids_tpu.expr.core import Expression, Val, EvalCtx, Literal
from spark_rapids_tpu.expr.arithmetic import coerce_pair

__all__ = ["EqualTo", "EqualNullSafe", "LessThan", "LessThanOrEqual",
           "GreaterThan", "GreaterThanOrEqual", "And", "Or", "Not",
           "IsNull", "IsNotNull", "IsNan", "In"]


# -- shared comparison kernels (Spark total order for floats) ---------------

def compare_eq(a: Val, b: Val, ctx: EvalCtx):
    xp = ctx.xp
    if isinstance(a.dtype, T.ArrayType):
        raise ValueError("array comparisons are not supported; compare "
                         "elements via GetArrayItem/ArrayContains")
    if a.is_string:
        return _string_eq(a, b, ctx)
    if a.dtype.fractional:
        return (a.data == b.data) | (xp.isnan(a.data) & xp.isnan(b.data))
    return a.data == b.data


def compare_lt(a: Val, b: Val, ctx: EvalCtx):
    xp = ctx.xp
    if isinstance(a.dtype, T.ArrayType):
        raise ValueError("array comparisons are not supported; compare "
                         "elements via GetArrayItem/ArrayContains")
    if a.is_string:
        return _string_lt(a, b, ctx)
    if a.dtype.fractional:
        # NaN is the largest value: a < b iff (a<b) or (b is NaN and a isn't)
        return (a.data < b.data) | (xp.isnan(b.data) & ~xp.isnan(a.data))
    return a.data < b.data


def _string_pair_device(a: Val, b: Val, ctx: EvalCtx):
    """Pad both byte matrices to a common width."""
    xp = ctx.xp
    wa, wb = a.data.shape[1], b.data.shape[1]
    w = max(wa, wb)
    da = xp.pad(a.data, ((0, 0), (0, w - wa))) if wa < w else a.data
    db = xp.pad(b.data, ((0, 0), (0, w - wb))) if wb < w else b.data
    return da, db


def _string_eq(a: Val, b: Val, ctx: EvalCtx):
    if not ctx.is_device:
        import numpy as np
        return np.array([x == y for x, y in zip(a.data, b.data)], dtype=bool)
    xp = ctx.xp
    da, db = _string_pair_device(a, b, ctx)
    return xp.all(da == db, axis=1) & (a.lengths == b.lengths)


def _string_lt(a: Val, b: Val, ctx: EvalCtx):
    if not ctx.is_device:
        import numpy as np
        return np.array([(x or "") < (y or "") for x, y in zip(a.data, b.data)],
                        dtype=bool)
    xp = ctx.xp
    da, db = _string_pair_device(a, b, ctx)
    # first differing byte decides; zero padding makes prefixes sort first.
    # Identical byte matrices fall back to a length compare so strings with
    # trailing NUL bytes (indistinguishable from padding) still order as
    # prefix < longer, matching the host oracle.
    diff = da != db
    has_diff = xp.any(diff, axis=1)
    first = xp.argmax(diff, axis=1)
    ab = xp.take_along_axis(da, first[:, None], axis=1)[:, 0]
    bb = xp.take_along_axis(db, first[:, None], axis=1)[:, 0]
    return xp.where(has_diff, ab < bb, a.lengths < b.lengths)


class BinaryComparison(Expression):
    def __init__(self, left: Expression, right: Expression):
        self.children = (left, right)

    def coerced(self):
        l, r = coerce_pair(*self.children)
        return type(self)(l, r)

    @property
    def dtype(self):
        return T.BooleanType()

    def _eval(self, vals, ctx: EvalCtx):
        a, b = vals
        validity = a.validity & b.validity
        return ctx.canonical(self._cmp(a, b, ctx), validity, T.BooleanType())


class EqualTo(BinaryComparison):
    sql_name = "EqualTo"

    def _cmp(self, a, b, ctx):
        return compare_eq(a, b, ctx)


class LessThan(BinaryComparison):
    sql_name = "LessThan"

    def _cmp(self, a, b, ctx):
        return compare_lt(a, b, ctx)


class GreaterThan(BinaryComparison):
    sql_name = "GreaterThan"

    def _cmp(self, a, b, ctx):
        return compare_lt(b, a, ctx)


class LessThanOrEqual(BinaryComparison):
    sql_name = "LessThanOrEqual"

    def _cmp(self, a, b, ctx):
        return compare_lt(a, b, ctx) | compare_eq(a, b, ctx)


class GreaterThanOrEqual(BinaryComparison):
    sql_name = "GreaterThanOrEqual"

    def _cmp(self, a, b, ctx):
        return compare_lt(b, a, ctx) | compare_eq(a, b, ctx)


class EqualNullSafe(BinaryComparison):
    sql_name = "EqualNullSafe"

    @property
    def nullable(self):
        return False

    def _eval(self, vals, ctx: EvalCtx):
        a, b = vals
        both_valid = a.validity & b.validity
        both_null = ~a.validity & ~b.validity & ctx.row_mask
        data = (both_valid & compare_eq(a, b, ctx)) | both_null
        return ctx.canonical(data, ctx.row_mask, T.BooleanType())


class And(Expression):
    """Three-valued AND: F & x = F; T & NULL = NULL."""
    sql_name = "And"

    def __init__(self, left, right):
        self.children = (left, right)

    @property
    def dtype(self):
        return T.BooleanType()

    def _eval(self, vals, ctx):
        a, b = vals
        data = a.data & b.data
        validity = (a.validity & b.validity) | (a.validity & ~a.data) | \
            (b.validity & ~b.data)
        return ctx.canonical(data, validity, T.BooleanType())


class Or(Expression):
    """Three-valued OR: T | x = T; F | NULL = NULL."""
    sql_name = "Or"

    def __init__(self, left, right):
        self.children = (left, right)

    @property
    def dtype(self):
        return T.BooleanType()

    def _eval(self, vals, ctx):
        a, b = vals
        data = a.data | b.data
        validity = (a.validity & b.validity) | (a.validity & a.data) | \
            (b.validity & b.data)
        return ctx.canonical(data, validity, T.BooleanType())


class Not(Expression):
    sql_name = "Not"

    def __init__(self, child):
        self.children = (child,)

    @property
    def dtype(self):
        return T.BooleanType()

    def _eval(self, vals, ctx):
        a = vals[0]
        return ctx.canonical(~a.data, a.validity, T.BooleanType())


class IsNull(Expression):
    sql_name = "IsNull"

    def __init__(self, child):
        self.children = (child,)

    @property
    def dtype(self):
        return T.BooleanType()

    @property
    def nullable(self):
        return False

    def _eval(self, vals, ctx):
        a = vals[0]
        return ctx.canonical(~a.validity & ctx.row_mask, ctx.row_mask,
                             T.BooleanType())


class IsNotNull(Expression):
    sql_name = "IsNotNull"

    def __init__(self, child):
        self.children = (child,)

    @property
    def dtype(self):
        return T.BooleanType()

    @property
    def nullable(self):
        return False

    def _eval(self, vals, ctx):
        a = vals[0]
        return ctx.canonical(a.validity & ctx.row_mask, ctx.row_mask,
                             T.BooleanType())


class IsNan(Expression):
    """Spark IsNaN: false for null input (not null)."""
    sql_name = "IsNaN"

    def __init__(self, child):
        self.children = (child,)

    @property
    def dtype(self):
        return T.BooleanType()

    @property
    def nullable(self):
        return False

    def _eval(self, vals, ctx):
        a = vals[0]
        if not a.dtype.fractional:
            return ctx.const(False, T.BooleanType())
        data = ctx.xp.isnan(a.data) & a.validity
        return ctx.canonical(data, ctx.row_mask, T.BooleanType())


class In(Expression):
    """Spark In: NULL if the value is null, or if there is no match and the
    list contains a null.  Children: (value, item0, item1, ...)."""
    sql_name = "In"

    def __init__(self, value: Expression, items: list[Expression]):
        self.children = (value,) + tuple(items)

    def with_new_children(self, children):
        return In(children[0], list(children[1:]))

    def coerced(self):
        # Spark promotes the value AND the list to a common wider type —
        # narrowing the items instead would wrap and create false matches
        from spark_rapids_tpu.expr.cast import Cast
        target = self.children[0].dtype
        for i in self.children[1:]:
            it = i.dtype
            if isinstance(it, T.NullType) or it == target:
                continue
            if it.numeric and target.numeric:
                target = T.numeric_promote(target, it)
            else:
                raise TypeError(f"IN: cannot compare {target} with {it}")
        kids = [c if c.dtype == target or isinstance(c.dtype, T.NullType)
                else Cast(c, target) for c in self.children]
        return In(kids[0], kids[1:])

    @property
    def dtype(self):
        return T.BooleanType()

    def _eval(self, vals, ctx):
        a = vals[0]
        xp = ctx.xp
        matched = xp.zeros(ctx.capacity, dtype=bool)
        any_null_item = xp.zeros(ctx.capacity, dtype=bool)
        for iv in vals[1:]:
            matched = matched | (compare_eq(a, iv, ctx) & iv.validity
                                 & a.validity)
            any_null_item = any_null_item | ~iv.validity
        validity = a.validity & ctx.row_mask & (matched | ~any_null_item)
        return ctx.canonical(matched, validity, T.BooleanType())

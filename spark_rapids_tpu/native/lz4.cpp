// LZ4 block-format codec (compressor + decompressor).
//
// The reference compresses shuffle/spill buffers on-device with nvcomp's
// LZ4 (NvcompLZ4CompressionCodec.scala:25); the TPU build's staging
// buffers live in host memory, so the codec is host-side C++ — same
// block format, greedy hash-table matcher (the classic LZ4 "fast" level).
//
// Block format: sequences of
//   token: high nibble = literal count (15 => extension bytes follow),
//          low nibble  = match length - 4 (15 => extension bytes follow)
//   <literals> <2-byte little-endian match offset> <match len extension>
// The final sequence is literals-only.  Encoder rules honored: the last
// 5 bytes are always literals; no match starts within 12 bytes of the end.
#include <cstdint>
#include <cstring>
#include <cstddef>

extern "C" {

size_t lz4_compress_bound(size_t n) {
  return n + n / 255 + 16;
}

// Returns compressed size, or -1 if dst is too small.
int64_t lz4_compress(const uint8_t* src, size_t n, uint8_t* dst,
                     size_t dst_cap) {
  const size_t HASH_LOG = 16;
  const size_t HASH_SIZE = 1u << HASH_LOG;
  static thread_local uint32_t table[1u << 16];
  std::memset(table, 0, HASH_SIZE * sizeof(uint32_t));

  const uint8_t* ip = src;
  const uint8_t* const iend = src + n;
  // matches must end >= 5 bytes before the end; candidates need 4+8 bytes
  const uint8_t* const mflimit = (n >= 12) ? iend - 12 : src;
  const uint8_t* anchor = src;
  uint8_t* op = dst;
  uint8_t* const oend = dst + dst_cap;

  auto hash4 = [](const uint8_t* p) -> uint32_t {
    uint32_t v;
    std::memcpy(&v, p, 4);
    return (v * 2654435761u) >> (32 - HASH_LOG);
  };
  auto read32 = [](const uint8_t* p) -> uint32_t {
    uint32_t v;
    std::memcpy(&v, p, 4);
    return v;
  };

  if (n >= 13) {
    table[hash4(ip)] = (uint32_t)(ip - src);
    ip++;
    while (ip < mflimit) {
      // find a 4-byte match
      uint32_t h = hash4(ip);
      const uint8_t* match = src + table[h];
      table[h] = (uint32_t)(ip - src);
      if (match >= ip || (size_t)(ip - match) > 65535 ||
          read32(match) != read32(ip)) {
        ip++;
        continue;
      }
      // extend backwards
      while (ip > anchor && match > src && ip[-1] == match[-1]) {
        ip--;
        match--;
      }
      // emit literals
      size_t lit = (size_t)(ip - anchor);
      uint8_t* token = op++;
      if (op + lit + lit / 255 + 8 > oend) return -1;
      if (lit >= 15) {
        *token = 15u << 4;
        size_t rest = lit - 15;
        while (rest >= 255) { *op++ = 255; rest -= 255; }
        *op++ = (uint8_t)rest;
      } else {
        *token = (uint8_t)(lit << 4);
      }
      std::memcpy(op, anchor, lit);
      op += lit;
      // match length (beyond the 4-byte minimum)
      size_t offset = (size_t)(ip - match);
      const uint8_t* mp = match + 4;
      const uint8_t* p = ip + 4;
      const uint8_t* matchlimit = iend - 5;
      while (p < matchlimit && *p == *mp) { p++; mp++; }
      size_t mlen = (size_t)(p - ip) - 4;
      if (op + 2 + mlen / 255 + 1 > oend) return -1;
      *op++ = (uint8_t)(offset & 0xff);
      *op++ = (uint8_t)(offset >> 8);
      if (mlen >= 15) {
        *token |= 15;
        size_t rest = mlen - 15;
        while (rest >= 255) { *op++ = 255; rest -= 255; }
        *op++ = (uint8_t)rest;
      } else {
        *token |= (uint8_t)mlen;
      }
      ip = p;
      anchor = ip;
      if (ip < mflimit) table[hash4(ip - 2)] = (uint32_t)(ip - 2 - src);
    }
  }
  // trailing literals
  size_t lit = (size_t)(iend - anchor);
  if (op + 1 + lit + lit / 255 + 1 > oend) return -1;
  uint8_t* token = op++;
  if (lit >= 15) {
    *token = 15u << 4;
    size_t rest = lit - 15;
    while (rest >= 255) { *op++ = 255; rest -= 255; }
    *op++ = (uint8_t)rest;
  } else {
    *token = (uint8_t)(lit << 4);
  }
  std::memcpy(op, anchor, lit);
  op += lit;
  return (int64_t)(op - dst);
}

// Returns decompressed size, or -1 on malformed input / overflow.
int64_t lz4_decompress(const uint8_t* src, size_t n, uint8_t* dst,
                       size_t dst_cap) {
  const uint8_t* ip = src;
  const uint8_t* const iend = src + n;
  uint8_t* op = dst;
  uint8_t* const oend = dst + dst_cap;

  while (ip < iend) {
    uint8_t token = *ip++;
    size_t lit = token >> 4;
    if (lit == 15) {
      uint8_t b;
      do {
        if (ip >= iend) return -1;
        b = *ip++;
        lit += b;
      } while (b == 255);
    }
    if (ip + lit > iend || op + lit > oend) return -1;
    std::memcpy(op, ip, lit);
    ip += lit;
    op += lit;
    if (ip >= iend) break;  // final literals-only sequence
    if (ip + 2 > iend) return -1;
    size_t offset = (size_t)ip[0] | ((size_t)ip[1] << 8);
    ip += 2;
    if (offset == 0 || (size_t)(op - dst) < offset) return -1;
    size_t mlen = (token & 15) + 4;
    if ((token & 15) == 15) {
      uint8_t b;
      do {
        if (ip >= iend) return -1;
        b = *ip++;
        mlen += b;
      } while (b == 255);
    }
    if (op + mlen > oend) return -1;
    const uint8_t* match = op - offset;
    for (size_t i = 0; i < mlen; i++) op[i] = match[i];  // may overlap
    op += mlen;
  }
  return (int64_t)(op - dst);
}

}  // extern "C"

"""Native runtime library: build-on-first-use C++ arena via ctypes.

The reference consumes RMM/pinned pools through JNI (SURVEY.md §2.9);
here the host arena + disk spill I/O are C++ (native/arena.cpp) loaded
with ctypes — no pybind11 in this image.  The compiled .so is cached
next to the source and rebuilt when the source changes.
"""
from __future__ import annotations

import ctypes
import glob
import hashlib
import os
import subprocess
import tempfile
import threading

_DIR = os.path.dirname(os.path.abspath(__file__))
_SRCS = [os.path.join(_DIR, "arena.cpp"), os.path.join(_DIR, "lz4.cpp")]


def _so_path() -> str:
    h = hashlib.sha256()
    for src in _SRCS:
        with open(src, "rb") as f:
            h.update(f.read())
    return os.path.join(_DIR, f"_native_{h.hexdigest()[:16]}.so")


def _build(so: str) -> None:
    # unique tmp name + atomic replace: concurrent builders each link
    # their own file and the rename is last-writer-wins, never garbled
    fd, tmp = tempfile.mkstemp(suffix=".so", dir=_DIR)
    os.close(fd)
    try:
        cmd = ["g++", "-O2", "-shared", "-fPIC", "-std=c++17", *_SRCS,
               "-o", tmp]
        subprocess.run(cmd, check=True, capture_output=True)
        os.replace(tmp, so)
    finally:
        if os.path.exists(tmp):
            os.unlink(tmp)
    for stale in glob.glob(os.path.join(_DIR, "_arena_*.so")) + \
            glob.glob(os.path.join(_DIR, "_native_*.so")):
        if stale != so:
            try:
                os.unlink(stale)
            except OSError:
                pass


_lib = None
_load_lock = threading.Lock()


def load() -> ctypes.CDLL:
    """Load (building if needed) the native arena library."""
    global _lib
    with _load_lock:
        if _lib is not None:
            return _lib
        so = _so_path()
        if not os.path.exists(so):
            _build(so)
        _lib = _bind(ctypes.CDLL(so))
        return _lib


def _bind(lib: ctypes.CDLL) -> ctypes.CDLL:
    lib.arena_create.restype = ctypes.c_void_p
    lib.arena_create.argtypes = [ctypes.c_size_t]
    lib.arena_destroy.argtypes = [ctypes.c_void_p]
    lib.arena_alloc.restype = ctypes.c_int64
    lib.arena_alloc.argtypes = [ctypes.c_void_p, ctypes.c_size_t]
    lib.arena_free.restype = ctypes.c_int
    lib.arena_free.argtypes = [ctypes.c_void_p, ctypes.c_int64]
    lib.arena_base.restype = ctypes.POINTER(ctypes.c_uint8)
    lib.arena_base.argtypes = [ctypes.c_void_p]
    lib.arena_capacity.restype = ctypes.c_size_t
    lib.arena_capacity.argtypes = [ctypes.c_void_p]
    lib.arena_used.restype = ctypes.c_size_t
    lib.arena_used.argtypes = [ctypes.c_void_p]
    lib.arena_largest_free.restype = ctypes.c_size_t
    lib.arena_largest_free.argtypes = [ctypes.c_void_p]
    lib.spill_write.restype = ctypes.c_int
    lib.spill_write.argtypes = [ctypes.c_char_p,
                                ctypes.POINTER(ctypes.c_uint8),
                                ctypes.c_size_t]
    lib.spill_read.restype = ctypes.c_int64
    lib.spill_read.argtypes = [ctypes.c_char_p,
                               ctypes.POINTER(ctypes.c_uint8),
                               ctypes.c_size_t]
    lib.lz4_compress_bound.restype = ctypes.c_size_t
    lib.lz4_compress_bound.argtypes = [ctypes.c_size_t]
    lib.lz4_compress.restype = ctypes.c_int64
    lib.lz4_compress.argtypes = [ctypes.POINTER(ctypes.c_uint8),
                                 ctypes.c_size_t,
                                 ctypes.POINTER(ctypes.c_uint8),
                                 ctypes.c_size_t]
    lib.lz4_decompress.restype = ctypes.c_int64
    lib.lz4_decompress.argtypes = [ctypes.POINTER(ctypes.c_uint8),
                                   ctypes.c_size_t,
                                   ctypes.POINTER(ctypes.c_uint8),
                                   ctypes.c_size_t]
    return lib


def lz4_compress(data) -> bytes:
    """LZ4 block-compress a bytes-like buffer (native codec)."""
    import numpy as np
    lib = load()
    src = np.frombuffer(data, dtype=np.uint8)
    bound = lib.lz4_compress_bound(src.size)
    dst = np.empty(bound, dtype=np.uint8)
    n = lib.lz4_compress(
        src.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8)), src.size,
        dst.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8)), bound)
    if n < 0:
        raise ValueError("lz4 compression overflow")
    return dst[:n].tobytes()


def lz4_decompress(data, out_size: int) -> bytes:
    """Decompress an LZ4 block into exactly ``out_size`` bytes."""
    import numpy as np
    lib = load()
    src = np.frombuffer(data, dtype=np.uint8)
    dst = np.empty(out_size, dtype=np.uint8)
    n = lib.lz4_decompress(
        src.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8)), src.size,
        dst.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8)), out_size)
    if n != out_size:
        raise ValueError(f"lz4 decompression failed ({n} != {out_size})")
    return dst.tobytes()


class HostArena:
    """Python handle over the C++ arena: numpy views into arena slices."""

    def __init__(self, capacity_bytes: int):
        import numpy as np
        self._lib = load()
        self._h = self._lib.arena_create(capacity_bytes)
        if not self._h:
            raise MemoryError(f"arena_create({capacity_bytes}) failed")
        base = self._lib.arena_base(self._h)
        cap = self._lib.arena_capacity(self._h)
        self._view = np.ctypeslib.as_array(base, shape=(cap,))
        self.capacity = cap

    def alloc(self, nbytes: int) -> int | None:
        off = self._lib.arena_alloc(self._h, max(nbytes, 1))
        return None if off < 0 else int(off)

    def free(self, offset: int) -> None:
        rc = self._lib.arena_free(self._h, offset)
        if rc != 0:
            raise ValueError(f"double/invalid free at offset {offset}")

    def view(self, offset: int, nbytes: int):
        """uint8 numpy view of an allocated slice (no copy)."""
        if self._view is None:
            raise ValueError("arena is closed")
        return self._view[offset:offset + nbytes]

    @property
    def used(self) -> int:
        return int(self._lib.arena_used(self._h))

    @property
    def largest_free(self) -> int:
        return int(self._lib.arena_largest_free(self._h))

    def _slice_ptr(self, offset: int):
        import ctypes as ct
        if self._view is None:
            raise ValueError("arena is closed")
        return ct.cast(ct.addressof(self._view.ctypes.data_as(
            ct.POINTER(ct.c_uint8)).contents) + offset,
            ct.POINTER(ct.c_uint8))

    def write_to_disk(self, offset: int, nbytes: int, path: str) -> None:
        rc = self._lib.spill_write(path.encode(), self._slice_ptr(offset),
                                   nbytes)
        if rc != 0:
            raise OSError(f"spill_write({path}) failed")

    def read_from_disk(self, offset: int, nbytes: int, path: str) -> None:
        got = self._lib.spill_read(path.encode(), self._slice_ptr(offset),
                                   nbytes)
        if got != nbytes:
            raise OSError(f"spill_read({path}): {got} != {nbytes}")

    def close(self) -> None:
        if self._h:
            # drop the view FIRST: any later access raises instead of
            # dereferencing unmapped pages (SIGSEGV)
            self._view = None
            self._lib.arena_destroy(self._h)
            self._h = None

    def __del__(self):
        try:
            self.close()
        # enginelint: disable=RL001 (interpreter-shutdown __del__: raising here aborts finalization)
        except Exception:
            pass

// Host pinned-arena allocator + spill file I/O.
//
// Native analog of the reference's RMM pool / PinnedMemoryPool
// (GpuDeviceManager.initializeRmm:196-262, allocatePinnedMemory:264-270)
// on the host side: device (HBM) allocation belongs to PJRT/XLA, so the
// framework's own memory runtime manages the HOST spill tier with a real
// arena — one big mmap'd region, first-fit free list with coalescing —
// plus O_DIRECT-free but fsync-correct file spill for the disk tier
// (reference RapidsHostMemoryStore / RapidsDiskStore).
//
// Exposed via a C ABI consumed with ctypes (no pybind11 in this image).

#include <cstdint>
#include <cstdlib>
#include <cstring>
#include <cstdio>
#include <map>
#include <mutex>
#include <new>

#include <sys/mman.h>
#include <fcntl.h>
#include <unistd.h>

namespace {

struct Arena {
    uint8_t* base = nullptr;
    size_t capacity = 0;
    size_t used = 0;
    // free list: offset -> size, kept coalesced
    std::map<size_t, size_t> free_blocks;
    // live allocations: offset -> size
    std::map<size_t, size_t> live;
    std::mutex mu;
};

constexpr size_t kAlign = 64;

size_t align_up(size_t n) { return (n + kAlign - 1) & ~(kAlign - 1); }

}  // namespace

extern "C" {

void* arena_create(size_t bytes) {
    auto* a = new (std::nothrow) Arena();
    if (!a) return nullptr;
    bytes = align_up(bytes);
    // MAP_POPULATE pre-faults so spill copies don't page-fault mid-flight;
    // mlock is best-effort "pinned" (may exceed RLIMIT_MEMLOCK in container)
    void* p = mmap(nullptr, bytes, PROT_READ | PROT_WRITE,
                   MAP_PRIVATE | MAP_ANONYMOUS | MAP_POPULATE, -1, 0);
    if (p == MAP_FAILED) { delete a; return nullptr; }
    (void)mlock(p, bytes);
    a->base = static_cast<uint8_t*>(p);
    a->capacity = bytes;
    a->free_blocks[0] = bytes;
    return a;
}

void arena_destroy(void* h) {
    auto* a = static_cast<Arena*>(h);
    if (!a) return;
    if (a->base) { munlock(a->base, a->capacity); munmap(a->base, a->capacity); }
    delete a;
}

// Returns byte offset into the arena, or -1 when it cannot fit (caller
// spills to the next tier and retries — the DeviceMemoryEventHandler
// pattern, DeviceMemoryEventHandler.scala:42-69).
int64_t arena_alloc(void* h, size_t bytes) {
    auto* a = static_cast<Arena*>(h);
    bytes = align_up(bytes ? bytes : 1);
    std::lock_guard<std::mutex> lock(a->mu);
    for (auto it = a->free_blocks.begin(); it != a->free_blocks.end(); ++it) {
        if (it->second >= bytes) {
            size_t off = it->first;
            size_t rem = it->second - bytes;
            a->free_blocks.erase(it);
            if (rem) a->free_blocks[off + bytes] = rem;
            a->live[off] = bytes;
            a->used += bytes;
            return static_cast<int64_t>(off);
        }
    }
    return -1;
}

int arena_free(void* h, int64_t off64) {
    auto* a = static_cast<Arena*>(h);
    size_t off = static_cast<size_t>(off64);
    std::lock_guard<std::mutex> lock(a->mu);
    auto it = a->live.find(off);
    if (it == a->live.end()) return -1;
    size_t size = it->second;
    a->live.erase(it);
    a->used -= size;
    // insert and coalesce with neighbors
    auto ins = a->free_blocks.emplace(off, size).first;
    if (ins != a->free_blocks.begin()) {
        auto prev = std::prev(ins);
        if (prev->first + prev->second == ins->first) {
            prev->second += ins->second;
            a->free_blocks.erase(ins);
            ins = prev;
        }
    }
    auto next = std::next(ins);
    if (next != a->free_blocks.end() &&
        ins->first + ins->second == next->first) {
        ins->second += next->second;
        a->free_blocks.erase(next);
    }
    return 0;
}

uint8_t* arena_base(void* h) { return static_cast<Arena*>(h)->base; }
size_t arena_capacity(void* h) { return static_cast<Arena*>(h)->capacity; }
size_t arena_used(void* h) { return static_cast<Arena*>(h)->used; }

size_t arena_largest_free(void* h) {
    auto* a = static_cast<Arena*>(h);
    std::lock_guard<std::mutex> lock(a->mu);
    size_t best = 0;
    for (auto& kv : a->free_blocks) best = kv.second > best ? kv.second : best;
    return best;
}

// ---- disk tier ----------------------------------------------------------

// Write [ptr, ptr+bytes) to path. Returns 0 on success.
int spill_write(const char* path, const uint8_t* ptr, size_t bytes) {
    int fd = open(path, O_WRONLY | O_CREAT | O_TRUNC, 0644);
    if (fd < 0) return -1;
    size_t done = 0;
    while (done < bytes) {
        ssize_t w = write(fd, ptr + done, bytes - done);
        if (w <= 0) { close(fd); return -1; }
        done += static_cast<size_t>(w);
    }
    if (fdatasync(fd) != 0) { close(fd); return -1; }
    int rc = close(fd);
    return rc;
}

int64_t spill_read(const char* path, uint8_t* ptr, size_t bytes) {
    int fd = open(path, O_RDONLY);
    if (fd < 0) return -1;
    size_t done = 0;
    while (done < bytes) {
        ssize_t r = read(fd, ptr + done, bytes - done);
        if (r < 0) { close(fd); return -1; }
        if (r == 0) break;
        done += static_cast<size_t>(r);
    }
    close(fd);
    return static_cast<int64_t>(done);
}

}  // extern "C"

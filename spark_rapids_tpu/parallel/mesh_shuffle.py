"""All-to-all hash exchange + distributed aggregation over a device mesh.

TPU-native shuffle data plane (SURVEY.md §5.8).  The reference moves map
output peer-to-peer over UCX tag matching (shuffle-plugin/.../UCX.scala,
RapidsShuffleClient.scala, RapidsShuffleServer.scala); here the exchange
is one XLA `all_to_all` collective inside `shard_map`, so it rides ICI
within a slice and DCN across slices with zero host involvement, and it
fuses with the surrounding kernels in one compiled program.

Design: every device holds a fixed-capacity shard.  A shuffle step is
  1. partition ids per row: Spark-bit-exact murmur3 pmod P
     (reference GpuHashPartitioning.scala),
  2. bucketize: one stable sort by partition id, then scatter into a
     [P, C] send buffer per column (reference Table.contiguousSplit,
     GpuPartitioning.scala:45-52),
  3. `lax.all_to_all` on the [P, C] buffers (+ per-target row counts),
  4. repack the received [P, C] buffers into one [P*C]-capacity batch
     (front-pack permutation — reference concatenates received shuffle
     buffers, RapidsShuffleClient BufferReceiveState).

All shapes are static; row validity travels as counts, so the whole
exchange jits and the compiler overlaps the collective with compute.
"""
from __future__ import annotations

from functools import partial
from typing import Sequence

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, PartitionSpec as P

from spark_rapids_tpu import types as T
from spark_rapids_tpu.columnar.batch import ColumnBatch
from spark_rapids_tpu.columnar.column import DeviceColumn
from spark_rapids_tpu.expr.core import EvalCtx, Val
from spark_rapids_tpu.expr.hashing import murmur3_val, DEFAULT_SEED
from spark_rapids_tpu.ops import kernels as dk
from spark_rapids_tpu.ops.segmented import AggSpec, sorted_group_by
from spark_rapids_tpu.parallel.mesh import local_view, restack, shard_map

__all__ = [
    "partition_ids_for_keys", "make_hash_exchange",
    "make_distributed_groupby", "MERGE_OPS",
    "exchange_local", "exchange_local_checked", "canonicalize",
]


def partition_ids_for_keys(batch: ColumnBatch, key_indices: Sequence[int],
                           num_parts: int) -> jax.Array:
    """int32[capacity]: pmod(murmur3(keys), P) per real row; P for padding.

    Bit-exact with Spark's HashPartitioning(Murmur3Hash) so host- and
    device-partitioned data interleave (reference GpuHashPartitioning).
    """
    cap = batch.capacity
    mask = batch.row_mask()
    ctx = EvalCtx(jnp, True, cap, mask)
    seed = jnp.full(cap, DEFAULT_SEED, dtype=jnp.uint32)
    for ki in key_indices:
        c = batch.columns[ki]
        seed = murmur3_val(Val(c.data, c.validity, c.lengths, c.dtype),
                           seed, ctx)
    h = seed.astype(jnp.int32)
    pid = ((h % num_parts) + num_parts) % num_parts  # Spark pmod
    return jnp.where(mask, pid, num_parts)


def _bucketize(batch: ColumnBatch, part: jax.Array, num_parts: int,
               send_capacity: int | None = None):
    """Split into [P, C] per-column send buffers + int32[P] counts.

    ``send_capacity`` bounds C below the full shard capacity (the
    static worst case where every row targets one destination).  Rows
    beyond a destination's C would scatter out of bounds — the caller
    MUST check the returned counts against C (``exchange_local_checked``
    surfaces an overflow flag) instead of letting ``mode="drop"``
    silently truncate them."""
    cap = batch.capacity
    counts = jnp.sum(part[None, :] == jnp.arange(num_parts, dtype=jnp.int32)[:, None],
                     axis=1, dtype=jnp.int32)
    C = cap if send_capacity is None else min(send_capacity, cap)
    overflow = jnp.any(counts > C)
    order = jnp.argsort(part, stable=True)       # padding (P) sinks to end
    sorted_part = part[order]
    starts = jnp.concatenate(
        [jnp.zeros(1, jnp.int32), jnp.cumsum(counts)[:-1].astype(jnp.int32)])
    rank = jnp.arange(cap, dtype=jnp.int32) - \
        starts[jnp.clip(sorted_part, 0, num_parts - 1)]
    dest = (sorted_part, rank)  # index (P, C); sorted_part==P or rank>=C drops

    send_cols = []
    for c in batch.columns:
        data_s = c.data[order]
        val_s = c.validity[order]
        if c.is_string:
            d = jnp.zeros((num_parts, C, c.max_len), c.data.dtype
                          ).at[dest].set(data_s, mode="drop")
            ln = jnp.zeros((num_parts, C), jnp.int32
                           ).at[dest].set(c.lengths[order], mode="drop")
        else:
            d = jnp.zeros((num_parts, C), c.data.dtype
                          ).at[dest].set(data_s, mode="drop")
            ln = None
        v = jnp.zeros((num_parts, C), jnp.bool_
                      ).at[dest].set(val_s, mode="drop")
        send_cols.append((d, v, ln))
    # clamp so _repack's receive mask never counts rows the bounded
    # buffer could not carry; the overflow flag is the loud signal
    return send_cols, jnp.minimum(counts, C), overflow


def _repack(schema: T.Schema, recv_cols, recv_counts: jax.Array,
            num_parts: int, cap: int) -> ColumnBatch:
    """[P, C] received buffers -> one front-packed [P*C] batch."""
    out_cap = num_parts * cap
    real = (jnp.arange(cap, dtype=jnp.int32)[None, :]
            < recv_counts[:, None]).reshape(out_cap)
    perm = jnp.argsort(~real, stable=True)
    total = jnp.sum(recv_counts, dtype=jnp.int32)
    cols = []
    for f, (d, v, ln) in zip(schema, recv_cols):
        if ln is not None:
            col = DeviceColumn(d.reshape(out_cap, d.shape[-1]),
                               v.reshape(out_cap), f.data_type,
                               ln.reshape(out_cap))
        else:
            col = DeviceColumn(d.reshape(out_cap), v.reshape(out_cap),
                               f.data_type)
        cols.append(col)
    cols = dk.gather_columns(cols, perm, total)
    return ColumnBatch(cols, total, schema)


def exchange_local(batch: ColumnBatch, part: jax.Array, num_parts: int,
                   axis_name: str) -> ColumnBatch:
    """Inside shard_map: all-to-all rows of ``batch`` by ``part`` id.

    Output capacity is P*C (static worst case: every row lands on one
    device).  The reference's analogs of these three phases are
    contiguousSplit -> UCX tag send/recv -> BufferReceiveState reassembly.
    """
    out, _ = exchange_local_checked(batch, part, num_parts, axis_name)
    return out


def exchange_local_checked(batch: ColumnBatch, part: jax.Array,
                           num_parts: int, axis_name: str,
                           send_capacity: int | None = None):
    """``exchange_local`` with a bounded [P, C] send buffer and a loud
    overflow signal.

    Returns ``(batch, overflow)``: ``overflow`` is a device bool that is
    True on any shard where one destination received more than C rows —
    those rows did NOT travel, and the caller must retry at worst-case
    capacity (mesh_exec.py degrades exactly like the OOM split-and-retry
    ladder: detect, never truncate, re-run with room).  With
    ``send_capacity=None`` C is the shard capacity and overflow is
    statically impossible."""
    send_cols, counts, overflow = _bucketize(batch, part, num_parts,
                                             send_capacity)
    a2a = partial(jax.lax.all_to_all, axis_name=axis_name,
                  split_axis=0, concat_axis=0, tiled=True)
    recv_counts = a2a(counts)
    recv_cols = [(a2a(d), a2a(v), a2a(ln) if ln is not None else None)
                 for (d, v, ln) in send_cols]
    C = batch.capacity if send_capacity is None \
        else min(send_capacity, batch.capacity)
    return _repack(batch.schema, recv_cols, recv_counts, num_parts,
                   C), overflow


def canonicalize(batch: ColumnBatch) -> ColumnBatch:
    """Re-zero padding rows after an external num_rows adjustment."""
    mask = batch.row_mask()
    cols = []
    for c in batch.columns:
        v = c.validity & mask
        if c.is_string:
            cols.append(DeviceColumn(jnp.where(v[:, None], c.data, 0), v,
                                     c.dtype, jnp.where(v, c.lengths, 0)))
        else:
            cols.append(DeviceColumn(
                jnp.where(v, c.data, jnp.zeros((), c.data.dtype)), v, c.dtype))
    return ColumnBatch(cols, batch.num_rows, batch.schema)


def make_hash_exchange(mesh: Mesh, schema: T.Schema,
                       key_indices: Sequence[int],
                       axis_name: str = "data"):
    """Jitted sharded-batch -> sharded-batch all-to-all hash exchange."""
    num_parts = mesh.shape[axis_name]

    def step(stacked: ColumnBatch) -> ColumnBatch:
        b = local_view(stacked)
        part = partition_ids_for_keys(b, key_indices, num_parts)
        return restack(exchange_local(b, part, num_parts, axis_name))

    mapped = shard_map(step, mesh=mesh, in_specs=P(axis_name),
                           out_specs=P(axis_name))
    from spark_rapids_tpu.exec.compile_cache import instrument
    return instrument(jax.jit(mapped))


# Merge-side op per update op (reference: CudfAggregate mergeAggregate,
# AggregateFunctions.scala:531 — count merges as sum, etc.).  `avg` is not
# single-column-mergeable: the exec layer decomposes it to sum+count before
# reaching this kernel (HashAggregateExec buffer layout).
MERGE_OPS = {
    "sum": "sum", "count": "sum", "count_star": "sum",
    "min": "min", "max": "max",
    "first": "first", "last": "last",
    "first_non_null": "first_non_null", "last_non_null": "last_non_null",
}


def make_distributed_groupby(mesh: Mesh, schema: T.Schema,
                             key_indices: Sequence[int],
                             specs: Sequence[AggSpec],
                             axis_name: str = "data"):
    """Jitted full distributed aggregation step over the mesh.

    partial local group-by -> all-to-all exchange of partial rows by key
    hash -> final merge group-by.  This is the TPU-shaped version of the
    reference's partial agg / GpuShuffleExchangeExec / final agg plan
    (aggregate.scala modes + GpuHashPartitioning), fused into ONE compiled
    program per device so XLA overlaps the collective with compute.
    """
    num_parts = mesh.shape[axis_name]
    key_indices = list(key_indices)
    for s in specs:
        if s.op not in MERGE_OPS:
            raise ValueError(f"op {s.op} is not mergeable here; decompose "
                             "at the exec layer (e.g. avg -> sum+count)")
    nkeys = len(key_indices)
    partial_keys = list(range(nkeys))
    merge_specs = [AggSpec(MERGE_OPS[s.op], nkeys + i)
                   for i, s in enumerate(specs)]

    def step(stacked: ColumnBatch) -> ColumnBatch:
        b = local_view(stacked)
        part_out = sorted_group_by(b, key_indices, list(specs))
        if nkeys:
            part = partition_ids_for_keys(part_out, partial_keys, num_parts)
        else:
            # grand aggregate: merge on device 0
            part = jnp.where(part_out.row_mask(), 0, num_parts)
        ex = exchange_local(part_out, part, num_parts, axis_name)
        merged = sorted_group_by(ex, partial_keys, merge_specs)
        # merge output columns carry nested names (e.g. sum(sum(x))) but
        # identical types; relabel to the partial (user-facing) schema.
        out = ColumnBatch(merged.columns, merged.num_rows, part_out.schema)
        if not nkeys:
            # only device 0 received rows; suppress identity rows elsewhere
            on0 = jax.lax.axis_index(axis_name) == 0
            out = ColumnBatch(out.columns,
                              jnp.where(on0, out.num_rows, 0), out.schema)
            out = canonicalize(out)
        return restack(out)

    mapped = shard_map(step, mesh=mesh, in_specs=P(axis_name),
                           out_specs=P(axis_name))
    from spark_rapids_tpu.exec.compile_cache import instrument
    return instrument(jax.jit(mapped))

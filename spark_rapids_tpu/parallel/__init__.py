"""Distributed execution over TPU device meshes.

TPU-native replacement for the reference's distribution machinery
(SURVEY.md §2.10): Spark-task data parallelism + all-to-all shuffle over
UCX/RDMA (reference shuffle-plugin/src/main/scala/.../UCX.scala) becomes
data-parallel shards over a `jax.sharding.Mesh` with the exchange lowered
to XLA `all_to_all` collectives riding ICI (DCN across slices, handled by
the same collective via the mesh topology).
"""
from spark_rapids_tpu.parallel.mesh import make_mesh, shard_batches, unshard_batch
from spark_rapids_tpu.parallel.mesh_shuffle import (
    partition_ids_for_keys,
    make_hash_exchange,
    make_distributed_groupby,
)

__all__ = [
    "make_mesh", "shard_batches", "unshard_batch",
    "partition_ids_for_keys", "make_hash_exchange",
    "make_distributed_groupby",
]

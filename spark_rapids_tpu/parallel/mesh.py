"""Device mesh construction and batch sharding.

The unit of distribution is a `ColumnBatch` shard per mesh slot along a
named axis (default ``"data"``) — the TPU analog of one Spark task's
partition living on one executor's GPU (reference
sql-plugin/.../GpuShuffleExchangeExec.scala + RapidsShuffleManager).

A *sharded batch* is an ordinary `ColumnBatch` pytree whose every leaf has
a leading device axis P (``num_rows`` is ``int32[P]``), placed with a
`NamedSharding` so that leaf axis 0 maps onto the mesh axis.  Inside
`shard_map` each device sees leading extent 1; `_local_view` squeezes that
away to recover a plain per-device `ColumnBatch`.
"""
from __future__ import annotations

from typing import Sequence

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from spark_rapids_tpu import types as T
from spark_rapids_tpu.columnar.batch import ColumnBatch
from spark_rapids_tpu.columnar.column import DeviceColumn

# jax moved shard_map out of jax.experimental in 0.6; support both homes.
# The experimental version's replication checker chokes on some
# multi-result primitives (its rule table returns None), so turn it off
# there — it is a static sanity check, not part of program semantics.
try:
    shard_map = jax.shard_map
except AttributeError:  # pragma: no cover - version dependent
    from functools import partial as _partial

    from jax.experimental.shard_map import shard_map as _exp_shard_map
    shard_map = _partial(_exp_shard_map, check_rep=False)

__all__ = ["make_mesh", "shard_batches", "unshard_batch", "split_shards",
           "local_view", "stacked_spec", "shard_map"]


def make_mesh(n_devices: int | None = None, axis_name: str = "data",
              devices: Sequence | None = None) -> Mesh:
    """1-D mesh over the first ``n_devices`` devices (all by default)."""
    devs = list(devices) if devices is not None else jax.devices()
    if n_devices is not None:
        if len(devs) < n_devices:
            raise ValueError(
                f"requested {n_devices} devices, have {len(devs)}")
        devs = devs[:n_devices]
    return Mesh(np.asarray(devs), (axis_name,))


def stacked_spec(axis_name: str = "data") -> P:
    """PartitionSpec prefix for every leaf of a stacked batch."""
    return P(axis_name)


def shard_batches(batches: Sequence[ColumnBatch], mesh: Mesh,
                  axis_name: str = "data") -> ColumnBatch:
    """Stack P per-device batches (same schema+capacity) into one sharded
    batch pytree with leading device axis P placed along ``axis_name``."""
    p = mesh.shape[axis_name]
    if len(batches) != p:
        raise ValueError(f"need {p} shards, got {len(batches)}")
    schema = batches[0].schema
    sharding = NamedSharding(mesh, P(axis_name))
    devs = list(mesh.devices.flat)

    def place(*leaves):
        # build the global array from per-device shards: each leaf is
        # device_put straight to ITS mesh device (a no-op when the
        # shard — e.g. MeshJoinExec probe output — already lives there);
        # a central jnp.stack would both error on mixed committed
        # devices and funnel every shard through one device
        shards = [jax.device_put(leaf[None], d)
                  for leaf, d in zip(leaves, devs)]
        global_shape = (p,) + leaves[0].shape
        return jax.make_array_from_single_device_arrays(
            global_shape, sharding, shards)

    stacked = jax.tree_util.tree_map(place, *batches)
    # tree_map over ColumnBatch pytrees rebuilds a ColumnBatch (schema aux
    # is shared); its num_rows leaf is now int32[P].
    assert isinstance(stacked, ColumnBatch)
    assert stacked.schema == schema
    return stacked


def local_view(stacked: ColumnBatch) -> ColumnBatch:
    """Inside shard_map: squeeze the leading extent-1 device axis."""
    return jax.tree_util.tree_map(lambda x: x[0], stacked)


def restack(local: ColumnBatch) -> ColumnBatch:
    """Inside shard_map: re-add the leading device axis before returning."""
    return jax.tree_util.tree_map(lambda x: x[None], local)


def split_shards(stacked: ColumnBatch) -> list[ColumnBatch]:
    """Split a sharded batch into P per-device ColumnBatches WITHOUT a
    host round trip: each shard's arrays stay committed to the mesh
    device that produced them.  This is the region-boundary exit path —
    ``unshard_batch`` (device_get + re-upload) implicitly funneled every
    mesh output through the default device, re-serializing the
    distributed pipeline at each island boundary.  Downstream per-batch
    operators dispatch on the shard's own device; ``place_shards``
    device affinity keeps re-sharded batches where they already live."""
    leaves, treedef = jax.tree_util.tree_flatten(stacked)
    per_dev: list[list] = []
    for leaf in leaves:
        shards = sorted(leaf.addressable_shards, key=lambda s: s.index[0].start)
        # s.data has the leading extent-1 device axis; [0] squeezes it
        # ON the shard's device (jax keeps slicing on the operand's
        # device, and the result stays committed there)
        per_dev.append([s.data[0] for s in shards])
    p = len(per_dev[0]) if per_dev else 1
    return [jax.tree_util.tree_unflatten(treedef,
                                         [col[i] for col in per_dev])
            for i in range(p)]


def unshard_batch(stacked: ColumnBatch) -> list[ColumnBatch]:
    """Pull a sharded batch back to P host-side ColumnBatch shards."""
    leaves, treedef = jax.tree_util.tree_flatten(stacked)
    host = jax.device_get(leaves)
    p = host[-1].shape[0] if host else 1  # num_rows is int32[P]
    out = []
    for i in range(p):
        out.append(jax.tree_util.tree_unflatten(
            treedef, [jnp.asarray(leaf[i]) for leaf in host]))
    return out

"""Self-driving control plane: telemetry that acts.

PR 15 made every chokepoint observable (registry histograms, /metrics,
query_history.jsonl) and PR 16 made the cluster elastic, but every knob
stayed a static conf — wrong the moment the workload shifts.  This
package closes the loop (ROADMAP item 5): ONE driver-side daemon thread
ticks on ``spark.rapids.control.intervalSeconds``, reads the same
registry deltas an operator would read off ``/metrics``, and actuates
four knobs that already exist:

* **admission autotune** — AIMD on the queue-wait vs query-wall
  histogram deltas moves ``AdmissionController.max_concurrent`` inside
  ``[minConcurrent, maxConcurrent]``; per-tenant p99 SLOs
  (``spark.rapids.control.slo.<tenant>.p99Seconds``) shed ONLY the
  sustained violator's over-share through the existing pressure-hook
  chokepoint (rules.SloTracker).
* **governor watermark adaptation** — the ``spill.io_seconds``
  histogram and grant-stall counters nudge the memory governor's
  high/low watermarks down when the spill tier is slow, so pressure
  backs off earlier (rules.WatermarkRule).
* **history-driven plan routing** — at plan time the query's
  fingerprint is looked up in the bounded in-memory
  :class:`~spark_rapids_tpu.obs.history.HistoryIndex`; plans whose
  observed wall sits below the express threshold skip the AQE/stage
  machinery and the mesh (the express-lane precursor of ROADMAP
  item 2), and plans observed under several mesh shapes route to the
  fastest one.
* **SLO-driven fleet sizing** — sustained aggregate p99-over-SLO with
  a backlog spawns a worker via ``ClusterDriver.add_worker``; a
  sustained idle fleet retires one via ``remove_worker(drain=True)``,
  under minWorkers/maxWorkers with hysteresis and a cooldown
  (rules.FleetRule).

Every decision is bounded (hard clamps per rule), rate-limited (one
actuation per rule per tick, fleet cooldown on top), recorded as a
``control.decision`` trace span + registry counters, idempotent (a
dropped actuation is simply re-derived from fresh signals next tick),
and reversible: with ``spark.rapids.control.enabled=false`` (the
default) this package is NEVER imported — the session gates on the raw
conf string, so plans, confs, and counters are byte-identical to the
static engine (ci/premerge.sh asserts it).
"""
from __future__ import annotations

from spark_rapids_tpu.conf import ConfEntry, register

__all__ = ["CONTROL_ENABLED", "CONTROL_INTERVAL", "ControlLoop",
           "parse_tenant_slos"]

CONTROL_ENABLED = register(ConfEntry(
    "spark.rapids.control.enabled", False,
    "Run the self-driving control loop: one daemon thread ticking on "
    "control.intervalSeconds that autotunes the admission cap (AIMD), "
    "adapts the memory governor's spill watermarks, routes repeated "
    "plans from query history, sheds tenants that persistently violate "
    "their p99 SLO, and sizes the worker fleet. Off (default): the "
    "control package is never imported and every knob stays exactly "
    "its static conf value.",
    conv=lambda v: str(v).lower() in ("true", "1", "yes")))
CONTROL_INTERVAL = register(ConfEntry(
    "spark.rapids.control.intervalSeconds", 1.0,
    "Control-loop tick period in seconds. Each tick reads one registry "
    "delta, merges it into a sliding window of "
    "control.windowTicks deltas, and derives at most one actuation per "
    "rule — the tick period is therefore also the actuation rate "
    "limit.", conv=float))
CONTROL_WINDOW_TICKS = register(ConfEntry(
    "spark.rapids.control.windowTicks", 5,
    "How many tick deltas the controller merges when computing "
    "percentile signals (queue wait, per-tenant p99, spill I/O). "
    "Larger = smoother/slower reactions; smaller = twitchier.",
    conv=int))
CONTROL_ADMISSION_ENABLED = register(ConfEntry(
    "spark.rapids.control.admission.enabled", True,
    "Enable the AIMD admission-cap rule (only meaningful when "
    "control.enabled). Set false to pin "
    "admission.maxConcurrentQueries back to its static conf value.",
    conv=lambda v: str(v).lower() in ("true", "1", "yes")))
CONTROL_ADMISSION_MIN = register(ConfEntry(
    "spark.rapids.control.admission.minConcurrent", 1,
    "Lower clamp for the autotuned admission cap: multiplicative "
    "decrease never drops maxConcurrentQueries below this.", conv=int))
CONTROL_ADMISSION_MAX = register(ConfEntry(
    "spark.rapids.control.admission.maxConcurrent", 16,
    "Upper clamp for the autotuned admission cap: additive increase "
    "never raises maxConcurrentQueries above this.", conv=int))
CONTROL_QUEUE_WAIT_TARGET = register(ConfEntry(
    "spark.rapids.control.admission.queueWaitTargetSeconds", 0.25,
    "Queue-wait p99 (over the signal window) above which the AIMD "
    "rule adds one admission slot — queries are waiting while the "
    "engine is healthy, so concurrency is the bottleneck.",
    conv=float))
CONTROL_SLO_VIOLATION_TICKS = register(ConfEntry(
    "spark.rapids.control.slo.violationTicks", 3,
    "Consecutive ticks a tenant's observed p99 (end-to-end: queue "
    "wait + wall) must exceed its "
    "spark.rapids.control.slo.<tenant>.p99Seconds before its "
    "over-share is shed. Hysteresis against one slow query tripping "
    "a shed.", conv=int))
CONTROL_SLO_RECOVERY_TICKS = register(ConfEntry(
    "spark.rapids.control.slo.recoveryTicks", 3,
    "Consecutive ticks a shed tenant's p99 must sit back under its "
    "SLO (or show no traffic) before the shed is lifted.", conv=int))
CONTROL_GOVERNOR_ENABLED = register(ConfEntry(
    "spark.rapids.control.governor.enabled", True,
    "Enable the spill-watermark adaptation rule (only meaningful when "
    "control.enabled and the memory governor is on). Set false to pin "
    "the governor watermarks to their static conf values.",
    conv=lambda v: str(v).lower() in ("true", "1", "yes")))
CONTROL_SPILL_P99_TARGET = register(ConfEntry(
    "spark.rapids.control.governor.spillP99TargetSeconds", 0.25,
    "spill.io_seconds p99 (over the signal window) above which — or "
    "any grant timeout in the window — the governor's high/low "
    "watermarks are stepped DOWN so spilling starts earlier on the "
    "slow tier; sustained health steps them back toward the conf "
    "values.", conv=float))
CONTROL_WATERMARK_STEP = register(ConfEntry(
    "spark.rapids.control.governor.watermarkStep", 0.05,
    "Occupancy-fraction step the watermark rule moves the governor's "
    "high watermark per actuation (bounded per tick, so adaptation is "
    "rate-limited by the tick period).", conv=float))
CONTROL_WATERMARK_MIN_HIGH = register(ConfEntry(
    "spark.rapids.control.governor.minHighWatermark", 0.50,
    "Lower clamp for the adapted high watermark: the rule never pushes "
    "spilling to start below this occupancy fraction.", conv=float))
CONTROL_ROUTE_ENABLED = register(ConfEntry(
    "spark.rapids.control.route.enabled", True,
    "Enable history-driven plan routing (only meaningful when "
    "control.enabled and obs.history.dir is set): repeated plan "
    "fingerprints with enough observed samples route to the express "
    "lane (below route.expressWallSeconds) or to the fastest mesh "
    "shape seen in history.",
    conv=lambda v: str(v).lower() in ("true", "1", "yes")))
CONTROL_ROUTE_EXPRESS_WALL = register(ConfEntry(
    "spark.rapids.control.route.expressWallSeconds", 0.2,
    "Median observed wall (from query history) below which a repeated "
    "plan takes the express lane: single chip, no AQE stage "
    "boundaries — the per-query planning machinery costs more than "
    "re-planning could save.", conv=float))
CONTROL_ROUTE_MIN_SAMPLES = register(ConfEntry(
    "spark.rapids.control.route.minSamples", 3,
    "FINISHED history samples a plan fingerprint needs before routing "
    "decisions apply to it — one lucky wall must not reroute a "
    "query.", conv=int))
CONTROL_EXPRESS = register(ConfEntry(
    "spark.rapids.control.express", False,
    "Internal marker the plan router stamps on a routed conf: the "
    "prepare() pipeline skips the AQE stage-boundary pass for this "
    "plan. Not meant to be set by hand.",
    conv=lambda v: str(v).lower() in ("true", "1", "yes"),
    internal=True))
CONTROL_FLEET_ENABLED = register(ConfEntry(
    "spark.rapids.control.fleet.enabled", True,
    "Enable SLO-driven fleet sizing (only meaningful when "
    "control.enabled and a cluster is attached): sustained p99-over-"
    "SLO with a backlog adds a worker, a sustained idle fleet drains "
    "one, inside cluster.minWorkers/maxWorkers.",
    conv=lambda v: str(v).lower() in ("true", "1", "yes")))
CONTROL_FLEET_UP_TICKS = register(ConfEntry(
    "spark.rapids.control.fleet.upTicks", 3,
    "Consecutive overloaded ticks (SLO violation or sustained queue "
    "backlog) before one worker is added.", conv=int))
CONTROL_FLEET_DOWN_TICKS = register(ConfEntry(
    "spark.rapids.control.fleet.downTicks", 10,
    "Consecutive idle ticks (no violation, empty queue) before one "
    "worker is drained and retired — deliberately slower than scale-up "
    "so the fleet rides out gaps between bursts.", conv=int))
CONTROL_FLEET_COOLDOWN = register(ConfEntry(
    "spark.rapids.control.fleet.cooldownSeconds", 30.0,
    "Minimum seconds between fleet actuations (either direction): "
    "worker spawn/drain cost dwarfs a tick, so scaling decisions must "
    "not flap at tick rate.", conv=float))

_SLO_PREFIX = "spark.rapids.control.slo."
_SLO_SUFFIX = ".p99Seconds"


def parse_tenant_slos(settings: dict) -> dict:
    """{tenant: p99 seconds} from the dynamic per-tenant keys
    ``spark.rapids.control.slo.<tenant>.p99Seconds`` (the structured
    keys under ``spark.rapids.control.slo.*`` — violationTicks,
    recoveryTicks — are registered entries and never match the
    suffix)."""
    out: dict = {}
    for key, val in settings.items():
        if key.startswith(_SLO_PREFIX) and key.endswith(_SLO_SUFFIX):
            tenant = key[len(_SLO_PREFIX):-len(_SLO_SUFFIX)]
            if tenant:
                try:
                    out[tenant] = float(val)
                except (TypeError, ValueError):
                    continue
    return out


def __getattr__(name):
    # ControlLoop drags in loop.py (and its lazy session wiring) only
    # when actually constructed — importing the package for its confs
    # (docs generation, tests of the pure rules) stays light
    if name == "ControlLoop":
        from spark_rapids_tpu.control.loop import ControlLoop
        return ControlLoop
    raise AttributeError(name)

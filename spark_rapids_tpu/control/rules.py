"""Pure control rules: signals in, bounded decisions out.

Every rule here is engine-free — inputs are plain numbers/dicts the
loop derives from registry snapshot deltas, outputs are
:class:`Decision` values (or None).  tests/test_control.py drives each
rule against synthetic snapshots with no session, no threads, and no
jax; the loop (loop.py) owns the only side effects.

Design invariants shared by every rule:

* **bounded** — every output is clamped to explicit limits
  (min/maxConcurrent, min high watermark, min/maxWorkers); no rule can
  walk a knob to infinity however bad the signals get.
* **hysteresis** — state-changing decisions (shed, scale) require N
  consecutive ticks of the same signal; one noisy delta never flips a
  tenant or a fleet.
* **idempotent** — a decision is derived from the CURRENT signals, not
  from "what I did last tick", so a dropped actuation
  (control.actuate.drop) is simply re-derived next tick and applying
  the same decision twice is a no-op.
"""
from __future__ import annotations

import time

__all__ = ["Decision", "aimd_admission", "SloTracker", "WatermarkRule",
           "FleetRule"]


class Decision:
    """One control actuation: what rule, what it did, and why.  The
    loop traces each as a ``control.decision`` span and keeps the last
    32 for the ``/control`` endpoint."""

    __slots__ = ("rule", "action", "detail", "reason", "applied",
                 "dropped", "unix_s")

    def __init__(self, rule: str, action: str, reason: str,
                 detail: "dict | None" = None):
        self.rule = rule
        self.action = action
        self.reason = reason
        self.detail = dict(detail or {})
        self.applied = False
        self.dropped = False
        self.unix_s = time.time()

    def to_dict(self) -> dict:
        return {"rule": self.rule, "action": self.action,
                "reason": self.reason, "detail": self.detail,
                "applied": self.applied, "dropped": self.dropped,
                "unix_s": round(self.unix_s, 3)}

    def __repr__(self) -> str:
        return (f"Decision({self.rule}:{self.action} {self.detail} — "
                f"{self.reason})")


def aimd_admission(cap: int, *, queue_wait_p99: "float | None",
                   congested: bool, active: int, min_cap: int,
                   max_cap: int,
                   queue_wait_target: float) -> "Decision | None":
    """AIMD on the admission cap.

    Congestion (a grant timeout, a governor shed, or an SLO violation
    in the window) halves the cap — multiplicative decrease, the TCP
    move: back off fast when the engine is visibly hurting.  A healthy
    engine whose queue-wait p99 exceeds the target gains ONE slot —
    additive increase: queries are waiting on admission while nothing
    downstream is saturated, so concurrency is the binding constraint.

    ``cap <= 0`` means unbounded: the rule leaves it alone until the
    first congestion signal, at which point the current active count is
    the best available estimate of a sane ceiling to halve from.
    """
    min_cap = max(1, int(min_cap))
    max_cap = max(min_cap, int(max_cap))
    if cap <= 0:
        if not congested:
            return None
        new = max(min_cap, min(max_cap, max(active, 2 * min_cap) // 2))
        return Decision(
            "admission", "bound", detail={"from": 0, "to": new},
            reason="congestion under an unbounded cap: bounding at "
                   f"half the active set ({active} running)")
    if congested and cap > min_cap:
        new = max(min_cap, cap // 2)
        return Decision(
            "admission", "decrease", detail={"from": cap, "to": new},
            reason="congestion signal in window (grant stall / "
                   "governor shed / SLO violation)")
    if not congested and queue_wait_p99 is not None \
            and queue_wait_p99 > queue_wait_target and cap < max_cap:
        return Decision(
            "admission", "increase", detail={"from": cap, "to": cap + 1},
            reason=f"queue-wait p99 {queue_wait_p99:.3f}s > target "
                   f"{queue_wait_target:g}s with a healthy engine")
    return None


class SloTracker:
    """Per-tenant p99-vs-SLO bookkeeping with shed/restore hysteresis.

    ``observe`` takes {tenant: observed p99 or None (no traffic)} for
    one window and returns the decisions that fired this tick.  A
    tenant is shed only after ``violation_ticks`` CONSECUTIVE
    violating windows, and restored only after ``recovery_ticks``
    consecutive healthy (or silent) ones — so a single straggler
    neither sheds a tenant nor whipsaws one back and forth.

    Offender targeting: under one tenant's storm EVERY tenant's p99
    blows up — the victims violate their SLOs because of the
    offender's queueing, and shedding them too would be collateral
    damage.  So when ``observe`` is given per-tenant demand
    (``tenant_load``, e.g. summed end-to-end seconds in the window), a
    violating tenant is shed only while its demand is at/above its
    fair share of the total — the offender by construction (the
    max-demand violator always qualifies, since max >= mean).  Without
    load data every violator qualifies.

    Restore gating: when given per-tenant rejection pressure
    (``tenant_pressure``, windowed rejected counts), a shed tenant
    whose arrivals are still being rejected accrues no recovery ticks
    — its p99 is quiet only BECAUSE it is shed, and restoring it
    would readmit the storm and re-violate within ticks (a shed/
    restore duty cycle that leaks the storm onto everyone else).
    Recovery starts once the tenant actually backs off.

    The same pressure feed gates NEW sheds: while any shed tenant is
    still hammering admission, the system has not settled into the
    post-shed regime — surviving tenants' p99 windows still hold
    samples that queued behind the offender's in-flight queries (their
    completions land AFTER the shed), so evidence against them is
    contaminated by construction.  No second tenant is shed until
    every already-shed tenant's windowed rejections reach zero."""

    def __init__(self, slos: "dict[str, float]",
                 violation_ticks: int = 3, recovery_ticks: int = 3,
                 shed_cooldown_ticks: int = 0):
        self.slos = {t: float(s) for t, s in slos.items() if s and s > 0}
        self.violation_ticks = max(1, int(violation_ticks))
        self.recovery_ticks = max(1, int(recovery_ticks))
        #: rate limit: ticks after a shed during which no FURTHER
        #: tenant may be shed — long enough (the loop passes
        #: window_ticks + violation_ticks) that the sliding window has
        #: flushed every p99 measured under the pre-shed regime, so a
        #: second shed can only fire on post-shed evidence
        self.shed_cooldown_ticks = max(0, int(shed_cooldown_ticks))
        self._cooldown = 0
        self._violating: dict[str, int] = {}   # consecutive bad ticks
        self._healthy: dict[str, int] = {}     # consecutive good ticks
        self.shed: dict[str, str] = {}         # tenant -> shed reason
        self.last_p99: dict[str, "float | None"] = {}

    @staticmethod
    def _over_fair_share(tenant: str,
                         tenant_load: "dict[str, float] | None") -> bool:
        if tenant_load is None:
            return True
        loaded = {t: v for t, v in tenant_load.items() if v and v > 0}
        total = sum(loaded.values())
        if total <= 0:
            return True
        return loaded.get(tenant, 0.0) >= total / len(loaded)

    def observe(self, tenant_p99: "dict[str, float | None]",
                tenant_load: "dict[str, float] | None" = None,
                tenant_pressure: "dict[str, float] | None" = None
                ) -> "list[Decision]":
        out: list[Decision] = []
        if self._cooldown > 0:
            self._cooldown -= 1
        # post-shed regime not settled while any shed tenant still
        # hammers admission; see class docstring
        settling = any((tenant_pressure or {}).get(t, 0) > 0
                       for t in self.shed)
        for tenant, slo in self.slos.items():
            p99 = tenant_p99.get(tenant)
            self.last_p99[tenant] = p99
            violating = p99 is not None and p99 > slo
            if violating and (tenant in self.shed or
                              self._over_fair_share(tenant, tenant_load)):
                self._violating[tenant] = \
                    self._violating.get(tenant, 0) + 1
                self._healthy[tenant] = 0
            elif violating:
                # a VICTIM: violating, but not driving the load.  Its
                # streak must not accrue — otherwise it sheds the
                # instant the offender's demand drains from the window
                # and its own stale-high p99 briefly makes it the
                # biggest remaining load.  Not healthy either: a
                # victim's suffering still signals congestion upstream.
                self._violating[tenant] = 0
                self._healthy[tenant] = 0
            else:
                if tenant in self.shed and \
                        (tenant_pressure or {}).get(tenant, 0) > 0:
                    # shed, quiet p99 — but still hammering admission
                    # (windowed rejections > 0).  Restoring now would
                    # readmit the storm and re-violate within ticks:
                    # the duty-cycle oscillation this gate exists to
                    # prevent.  Recovery starts when the tenant backs
                    # off.
                    self._healthy[tenant] = 0
                else:
                    self._healthy[tenant] = \
                        self._healthy.get(tenant, 0) + 1
                self._violating[tenant] = 0
            if tenant not in self.shed and self._cooldown == 0 and \
                    not settling and \
                    self._violating[tenant] >= self.violation_ticks:
                reason = (f"tenant {tenant!r} p99 {p99:.3f}s > SLO "
                          f"{slo:g}s for {self._violating[tenant]} "
                          "ticks: shedding its over-share")
                self.shed[tenant] = reason
                self._cooldown = self.shed_cooldown_ticks
                # a shed is a regime change: every OTHER tenant's
                # violation streak was measured under the pre-shed
                # regime (queueing behind this offender), so those
                # streaks restart from fresh windows — without this, a
                # victim sheds moments later on evidence that the shed
                # itself just invalidated
                for other in self.slos:
                    if other != tenant and other not in self.shed:
                        self._violating[other] = 0
                out.append(Decision(
                    "slo", "shed", reason,
                    detail={"tenant": tenant, "p99_s": round(p99, 4),
                            "slo_s": slo}))
            elif tenant in self.shed and \
                    self._healthy[tenant] >= self.recovery_ticks:
                del self.shed[tenant]
                out.append(Decision(
                    "slo", "restore",
                    reason=f"tenant {tenant!r} back under its "
                           f"{slo:g}s SLO for "
                           f"{self._healthy[tenant]} ticks",
                    detail={"tenant": tenant,
                            "p99_s": None if p99 is None
                            else round(p99, 4), "slo_s": slo}))
        return out

    def any_violating(self) -> bool:
        """True while any SLO'd tenant is in a violating streak (even
        a 1-tick one) — the congestion input to AIMD and the fleet
        rule."""
        return any(n > 0 for n in self._violating.values())

    def status(self) -> dict:
        """Per-tenant SLO table for the /control endpoint."""
        return {t: {"slo_s": slo,
                    "p99_s": self.last_p99.get(t),
                    "violating_ticks": self._violating.get(t, 0),
                    "shed": t in self.shed}
                for t, slo in self.slos.items()}


class WatermarkRule:
    """Adapt the governor's high/low spill watermarks to the observed
    spill tier.

    A slow tier (spill-I/O p99 over target, or any grant timeout in
    the window) steps the high watermark DOWN one notch: spilling
    starts earlier, so grant waiters stop piling up behind I/O that
    cannot keep pace.  Only after ``heal_ticks`` consecutive healthy
    windows does it step back UP toward the conf value — never above
    it (the conf is the operator's ceiling, adaptation only retreats
    from it).  The low watermark tracks the high one at the conf's
    own high-low gap."""

    def __init__(self, base_high: float, base_low: float,
                 spill_p99_target: float = 0.25, step: float = 0.05,
                 min_high: float = 0.50, heal_ticks: int = 5):
        self.base_high = float(base_high)
        self.base_low = float(base_low)
        self.gap = max(0.05, self.base_high - self.base_low)
        self.target = float(spill_p99_target)
        self.step = max(0.005, float(step))
        self.min_high = min(float(min_high), self.base_high)
        self.heal_ticks = max(1, int(heal_ticks))
        self.high = self.base_high
        self._healthy = 0

    def observe(self, *, spill_p99: "float | None",
                grant_timeouts: int,
                grant_waits: int) -> "Decision | None":
        slow = (grant_timeouts > 0
                or (spill_p99 is not None and spill_p99 > self.target))
        if slow:
            self._healthy = 0
            new = max(self.min_high, round(self.high - self.step, 4))
            if new >= self.high:
                return None
            old, self.high = self.high, new
            return Decision(
                "governor", "lower", detail={
                    "high_from": old, "high_to": new,
                    "low_to": round(max(0.05, new - self.gap), 4)},
                reason="slow spill tier "
                       f"(spill p99={'-' if spill_p99 is None else format(spill_p99, '.3f')}s, "
                       f"{grant_timeouts} grant timeouts, "
                       f"{grant_waits} grant waits in window)")
        self._healthy += 1
        if self.high < self.base_high and self._healthy >= self.heal_ticks:
            self._healthy = 0
            old = self.high
            self.high = min(self.base_high, round(self.high + self.step, 4))
            return Decision(
                "governor", "raise", detail={
                    "high_from": old, "high_to": self.high,
                    "low_to": round(max(0.05, self.high - self.gap), 4)},
                reason=f"spill tier healthy for {self.heal_ticks} "
                       "ticks: stepping back toward the conf "
                       f"watermark {self.base_high:g}")
        return None

    @property
    def low(self) -> float:
        return round(max(0.05, self.high - self.gap), 4)

    def at_base(self) -> bool:
        return self.high >= self.base_high


class FleetRule:
    """Hysteresis + cooldown around add_worker/remove_worker.

    ``overloaded`` (an SLO violation, or queued arrivals piling up)
    for ``up_ticks`` consecutive ticks asks for one worker; ``idle``
    (no violation, empty queue) for ``down_ticks`` asks to drain one.
    Both directions respect min/max bounds and share one cooldown —
    a spawn costs seconds and a drain migrates map outputs, so the
    fleet must never flap at tick rate.  The caller applies the
    decision; this rule only ever asks for a SINGLE worker per
    actuation, so a lost actuation re-derives harmlessly."""

    def __init__(self, min_workers: int = 1, max_workers: int = 0,
                 up_ticks: int = 3, down_ticks: int = 10,
                 cooldown_s: float = 30.0):
        self.min_workers = max(1, int(min_workers))
        # max_workers=0 mirrors the cluster conf: unbounded
        self.max_workers = int(max_workers)
        self.up_ticks = max(1, int(up_ticks))
        self.down_ticks = max(1, int(down_ticks))
        self.cooldown_s = max(0.0, float(cooldown_s))
        self._over = 0
        self._idle = 0
        self._last_actuation: "float | None" = None

    def observe(self, *, worker_count: int, overloaded: bool,
                idle: bool, now: "float | None" = None
                ) -> "Decision | None":
        now = time.monotonic() if now is None else now
        if overloaded:
            self._over += 1
            self._idle = 0
        elif idle:
            self._idle += 1
            self._over = 0
        else:
            self._over = 0
            self._idle = 0
        in_cooldown = (self._last_actuation is not None
                       and now - self._last_actuation < self.cooldown_s)
        if self._over >= self.up_ticks and not in_cooldown and \
                (self.max_workers <= 0
                 or worker_count < self.max_workers):
            self._over = 0
            self._last_actuation = now
            return Decision(
                "fleet", "add_worker",
                detail={"from": worker_count, "to": worker_count + 1},
                reason=f"overloaded for {self.up_ticks} ticks "
                       "(SLO violation or sustained backlog)")
        if self._idle >= self.down_ticks and not in_cooldown and \
                worker_count > self.min_workers:
            self._idle = 0
            self._last_actuation = now
            return Decision(
                "fleet", "remove_worker",
                detail={"from": worker_count, "to": worker_count - 1},
                reason=f"idle for {self.down_ticks} ticks "
                       "(no violation, empty queue)")
        return None

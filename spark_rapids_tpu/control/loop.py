"""The control loop: one daemon thread that turns telemetry into
actuations.

Each tick reads ONE registry snapshot, diffs it against the previous
tick, and merges the delta into a sliding window
(``control.windowTicks``) so percentile signals are computed over a
few seconds of traffic instead of one twitchy interval.  The pure
rules (rules.py) derive bounded decisions from those signals; this
module owns every side effect — the admission cap, the tenant-shed
map behind the pressure hook, the governor watermark overrides, the
fleet calls, and the plan router — plus the audit surface (decision
deque for ``/control``, ``control.decision`` trace spans, registry
counters).

Chaos points (faults.py): ``control.signal.stale`` freezes the tick's
registry snapshot at the previous one — the loop must keep deriving
sane decisions from stale signals; ``control.actuate.drop`` loses a
derived decision before actuation — harmless because every decision
is re-derived from fresh signals next tick (never replayed from a
queue).
"""
from __future__ import annotations

import functools
import os
import threading
import time
from collections import OrderedDict, deque

from spark_rapids_tpu.control import (
    CONTROL_ADMISSION_ENABLED, CONTROL_ADMISSION_MAX,
    CONTROL_ADMISSION_MIN, CONTROL_FLEET_COOLDOWN, CONTROL_FLEET_DOWN_TICKS,
    CONTROL_FLEET_ENABLED, CONTROL_FLEET_UP_TICKS, CONTROL_GOVERNOR_ENABLED,
    CONTROL_INTERVAL, CONTROL_QUEUE_WAIT_TARGET, CONTROL_ROUTE_ENABLED,
    CONTROL_ROUTE_EXPRESS_WALL, CONTROL_ROUTE_MIN_SAMPLES,
    CONTROL_SLO_RECOVERY_TICKS, CONTROL_SLO_VIOLATION_TICKS,
    CONTROL_SPILL_P99_TARGET, CONTROL_WATERMARK_MIN_HIGH,
    CONTROL_WATERMARK_STEP, CONTROL_WINDOW_TICKS, parse_tenant_slos)
from spark_rapids_tpu.control.rules import (Decision, FleetRule, SloTracker,
                                            WatermarkRule, aimd_admission)
from spark_rapids_tpu.obs.registry import (get_registry,
                                           histogram_percentile,
                                           merge_histogram_snapshots)

__all__ = ["ControlLoop"]


def _merge_window(window, name: str) -> "dict | None":
    """Merge one histogram's deltas across the sliding window."""
    snaps = [d["histograms"][name] for d in window
             if name in d.get("histograms", {})]
    if not snaps:
        return None
    return functools.reduce(merge_histogram_snapshots, snaps)


def _sum_window(window, name: str) -> float:
    return sum(d.get("counters", {}).get(name, 0) for d in window)


class ControlLoop:
    """Driver-side controller bound to one :class:`TpuSession`.

    Construction wires the actuation surfaces (admission pressure
    hook) but moves nothing until :meth:`start`; :meth:`stop` joins
    the thread and RESTORES every knob it touched — a stopped
    controller leaves the engine on its static confs."""

    def __init__(self, session):
        self.session = session
        settings = session.conf.settings
        self.interval = max(0.05, CONTROL_INTERVAL.get(settings))
        self.window_ticks = max(1, CONTROL_WINDOW_TICKS.get(settings))
        self._stop_evt = threading.Event()
        self._thread: "threading.Thread | None" = None
        self._tick_count = 0
        self._prev_snapshot: "dict | None" = None
        self._window: deque = deque(maxlen=self.window_ticks)
        self.decisions: deque = deque(maxlen=32)
        self._lock = threading.Lock()

        # chaos: same registry style as the admission controller's —
        # inert (None) unless spark.rapids.test.faults names a plan
        from spark_rapids_tpu.faults import FaultRegistry
        self.faults = FaultRegistry.from_conf(session.conf)

        # control.decision spans live on a dedicated tracer lane,
        # bounded like every query tracer
        from spark_rapids_tpu.obs.trace import Tracer
        self.tracer = Tracer(query_id="control", max_events=4096)

        # -- admission actuation surface --------------------------------
        self.admission = session._admission_controller()
        self._base_cap = self.admission.max_concurrent
        self.admission_enabled = CONTROL_ADMISSION_ENABLED.get(settings)
        self.min_cap = CONTROL_ADMISSION_MIN.get(settings)
        self.max_cap = CONTROL_ADMISSION_MAX.get(settings)
        self.queue_wait_target = CONTROL_QUEUE_WAIT_TARGET.get(settings)

        # -- per-tenant SLOs + shed via the existing pressure hook ------
        violation_ticks = CONTROL_SLO_VIOLATION_TICKS.get(settings)
        self.slo = SloTracker(
            parse_tenant_slos(settings),
            violation_ticks=violation_ticks,
            recovery_ticks=CONTROL_SLO_RECOVERY_TICKS.get(settings),
            # sheds are rate-limited to one per flushed window: after a
            # shed, every p99 in the sliding window was measured under
            # the PRE-shed regime for window_ticks more ticks — a
            # second shed on that evidence would punish the first
            # shed's victims
            shed_cooldown_ticks=self.window_ticks + violation_ticks)
        self._prev_hook = self.admission.pressure_hook
        # the bound method is captured ONCE: attribute access creates a
        # fresh bound-method object each time, so stop()'s identity
        # check must compare against the exact object installed here
        self._installed_hook = self._pressure_hook
        self.admission.pressure_hook = self._installed_hook

        # -- governor watermark rule ------------------------------------
        self.watermark: "WatermarkRule | None" = None
        self._governor = None
        if CONTROL_GOVERNOR_ENABLED.get(settings):
            from spark_rapids_tpu.memory.governor import (GOVERNOR_ENABLED,
                                                          GOVERNOR_HIGH_WM,
                                                          GOVERNOR_LOW_WM,
                                                          get_governor)
            if GOVERNOR_ENABLED.get(settings):
                self._governor = get_governor()
                self.watermark = WatermarkRule(
                    base_high=GOVERNOR_HIGH_WM.get(settings),
                    base_low=GOVERNOR_LOW_WM.get(settings),
                    spill_p99_target=CONTROL_SPILL_P99_TARGET.get(settings),
                    step=CONTROL_WATERMARK_STEP.get(settings),
                    min_high=CONTROL_WATERMARK_MIN_HIGH.get(settings))

        # -- fleet sizing -----------------------------------------------
        self.fleet: "FleetRule | None" = None
        if CONTROL_FLEET_ENABLED.get(settings):
            self.fleet = FleetRule(
                min_workers=int(settings.get(
                    "spark.rapids.cluster.minWorkers", 1)),
                max_workers=int(settings.get(
                    "spark.rapids.cluster.maxWorkers", 0)),
                up_ticks=CONTROL_FLEET_UP_TICKS.get(settings),
                down_ticks=CONTROL_FLEET_DOWN_TICKS.get(settings),
                cooldown_s=CONTROL_FLEET_COOLDOWN.get(settings))

        # -- history-driven plan routing --------------------------------
        self.route_enabled = CONTROL_ROUTE_ENABLED.get(settings)
        self.express_wall = CONTROL_ROUTE_EXPRESS_WALL.get(settings)
        self.route_min_samples = CONTROL_ROUTE_MIN_SAMPLES.get(settings)
        self._history_index = None
        self._history_path: "str | None" = None
        hist_dir = settings.get("spark.rapids.obs.history.dir")
        if self.route_enabled and hist_dir:
            import os
            from spark_rapids_tpu.obs.history import (HISTORY_FILE,
                                                      HistoryIndex)
            self._history_index = HistoryIndex()
            self._history_path = os.path.join(str(hist_dir), HISTORY_FILE)
        # fingerprint -> overrides dict (LRU): the route audit trail —
        # a fingerprint is logged as a decision only when its route
        # CHANGES, not once per query
        self._routes: OrderedDict = OrderedDict()

    # -- pressure-hook composition -------------------------------------

    def _pressure_hook(self, tenant: str = "default") -> "str | None":
        """The composed admission pressure hook: an SLO-shed tenant
        gets its shed reason (and only that tenant — neighbors see
        None, so ``admission_pressure_spared`` stays clean for them);
        everything else defers to whatever hook was installed before
        (the memory governor's).  The shed reason is a
        :class:`TargetedShed` so admission rejects unconditionally —
        this hook already did the tenant targeting, and the over-share
        spare would re-admit the victim the moment its running queries
        drained.  Delegated (global-pressure) reasons stay plain
        strings and keep their spare semantics."""
        from spark_rapids_tpu.exec.lifecycle import TargetedShed
        reason = self.slo.shed.get(tenant)
        if reason:
            return TargetedShed(reason)
        prev = self._prev_hook
        return prev(tenant) if prev is not None else None

    # -- lifecycle ------------------------------------------------------

    def start(self) -> None:
        if self._thread is not None:
            return
        self._stop_evt.clear()
        self._thread = threading.Thread(
            target=self._run, name="control-loop", daemon=True)
        self._thread.start()

    def stop(self, timeout: float = 5.0) -> None:
        """Stop the loop and RESTORE every actuated knob to its static
        conf value: cap, watermark overrides, pressure hook, sheds.
        Idempotent."""
        self._stop_evt.set()
        t = self._thread
        if t is not None:
            t.join(timeout=timeout)
            self._thread = None
        self._export_trace()
        with self._lock:
            self.slo.shed.clear()
            if self.admission.pressure_hook is self._installed_hook:
                self.admission.pressure_hook = self._prev_hook
            if self.admission_enabled and \
                    self.admission.max_concurrent != self._base_cap:
                self.admission.set_max_concurrent(self._base_cap)
            if self._governor is not None:
                self._governor.set_watermark_overrides(None, None)

    def _export_trace(self) -> None:
        """Write the controller's decision lane as trace_control.json
        next to the query traces — the loop has no ExecCtx.close() to
        piggyback on, so export happens once, at stop."""
        from spark_rapids_tpu.obs.trace import TRACE_DIR, TRACE_ENABLED
        settings = self.session.conf.settings
        out_dir = TRACE_DIR.get(settings)
        if not out_dir or not TRACE_ENABLED.get(settings) \
                or not self.tracer.events_snapshot(last=1):
            return
        try:
            os.makedirs(out_dir, exist_ok=True)
            self.tracer.export(os.path.join(out_dir,
                                            "trace_control.json"))
        # enginelint: disable=RL001 (trace export is best-effort teardown: a full disk must not turn shutdown into a crash)
        except Exception:
            get_registry().inc("control_trace_export_errors")

    @property
    def running(self) -> bool:
        t = self._thread
        return t is not None and t.is_alive()

    def _run(self) -> None:
        reg = get_registry()
        while not self._stop_evt.wait(self.interval):
            try:
                self.tick()
            # enginelint: disable=RL001 (the control loop must outlive any one bad tick; the error is counted and the next tick re-derives from fresh signals)
            except Exception:
                reg.inc("control_tick_errors")

    # -- the tick -------------------------------------------------------

    def tick(self) -> "list[Decision]":
        """One control round: signals -> rules -> actuations.  Public
        so tests can drive the loop deterministically without the
        thread."""
        reg = get_registry()
        self._tick_count += 1
        stale = self.faults is not None and self.faults.check(
            "control.signal.stale", tick=self._tick_count) is not None
        if stale and self._prev_snapshot is not None:
            # frozen signal: diff the previous snapshot against itself
            # (an empty delta) — the rules see "no movement", which
            # must decay toward no-ops, never oscillate
            snap = self._prev_snapshot
            reg.inc("control_signal_stale")
        else:
            snap = reg.snapshot()
        prev, self._prev_snapshot = self._prev_snapshot, snap
        if prev is None:
            # first tick is baseline-only: the registry is process-wide
            # and its all-time cumulative counters are not "movement in
            # this interval" — a controller attached to a long-lived
            # process must not read the whole uptime as a burst of load
            delta = {"counters": {}, "histograms": {}}
        else:
            delta = _delta_between(snap, prev)
        self._window.append(delta)
        signals = self._signals()
        decisions = self._decide(signals)
        applied = []
        for d in decisions:
            if self.faults is not None and self.faults.check(
                    "control.actuate.drop", rule=d.rule,
                    action=d.action) is not None:
                # the decision is lost before actuation — next tick
                # re-derives it from fresh signals (idempotence is the
                # recovery story, not an actuation queue)
                d.dropped = True
                reg.inc("control_decisions_dropped")
                self._record(d)
                continue
            t0 = time.perf_counter()
            self._actuate(d)
            self.tracer.complete(
                "control.decision", "control", t0, time.perf_counter(),
                rule=d.rule, action=d.action, reason=d.reason,
                **{k: v for k, v in d.detail.items()
                   if isinstance(v, (int, float, str, bool))})
            self._record(d)
            applied.append(d)
        self._export_gauges(reg)
        return applied

    def _signals(self) -> dict:
        adm = self.admission
        window = list(self._window)
        qw = _merge_window(window, "admission.queue_wait_seconds")
        # the offender-vs-victim discriminator for the SLO shed must
        # LEAD, not lag: completed-query sums only show a heavy storm
        # after its queries finish (minutes late for minute-long
        # queries), by which time the fast victims dominate the
        # completions and would take the blame.  The admission
        # controller's per-tenant backlog (running + queued, right
        # now) attributes demand the moment it arrives.
        stats = adm.tenant_stats()
        tenant_p99 = {}
        tenant_load = {}
        tenant_pressure = {}
        for tenant in self.slo.slos:
            h = _merge_window(window,
                              f"query.tenant.{tenant}.e2e_seconds")
            tenant_p99[tenant] = histogram_percentile(h, 99)
            st = stats.get(tenant) or {}
            tenant_load[tenant] = float(
                st.get("active", 0) + st.get("queued", 0))
            # windowed rejections: a shed tenant still hammering
            # admission must not be restored on its (forced) silence
            tenant_pressure[tenant] = _sum_window(
                window, f"admission.tenant.{tenant}.rejected")
        spill = _merge_window(window, "spill.io_seconds")
        return {
            "queue_wait_p99": histogram_percentile(qw, 99),
            "tenant_p99": tenant_p99,
            "tenant_load": tenant_load,
            "tenant_pressure": tenant_pressure,
            "spill_p99": histogram_percentile(spill, 99),
            "grant_waits": _sum_window(window, "governor_grant_waits"),
            "grant_timeouts": _sum_window(window,
                                          "governor_grant_timeouts"),
            "governor_sheds": _sum_window(window, "governor_shed_queries"),
            "active": adm.active,
            "queued": adm.queued,
        }

    def _decide(self, signals: dict) -> "list[Decision]":
        out: list[Decision] = []
        # SLO first: its violation streaks feed AIMD's congestion input
        out.extend(self.slo.observe(signals["tenant_p99"],
                                    signals["tenant_load"],
                                    signals["tenant_pressure"]))
        congested = (signals["grant_timeouts"] > 0
                     or signals["governor_sheds"] > 0
                     or self.slo.any_violating())
        if self.admission_enabled:
            d = aimd_admission(
                self.admission.max_concurrent,
                queue_wait_p99=signals["queue_wait_p99"],
                congested=congested, active=signals["active"],
                min_cap=self.min_cap, max_cap=self.max_cap,
                queue_wait_target=self.queue_wait_target)
            if d is not None:
                out.append(d)
        if self.watermark is not None:
            d = self.watermark.observe(
                spill_p99=signals["spill_p99"],
                grant_timeouts=signals["grant_timeouts"],
                grant_waits=signals["grant_waits"])
            if d is not None:
                out.append(d)
        cluster = getattr(self.session, "_cluster_handle", None)
        if self.fleet is not None and cluster is not None:
            overloaded = (self.slo.any_violating()
                          or (signals["queued"] > 0
                              and signals["queue_wait_p99"] is not None
                              and signals["queue_wait_p99"]
                              > self.queue_wait_target))
            idle = not self.slo.any_violating() and \
                signals["queued"] == 0
            d = self.fleet.observe(
                worker_count=len(cluster.schedulable_workers()),
                overloaded=overloaded, idle=idle)
            if d is not None:
                out.append(d)
        return out

    def _actuate(self, d: Decision) -> None:
        if d.rule == "admission":
            self.admission.set_max_concurrent(int(d.detail["to"]))
            d.applied = True
        elif d.rule == "slo":
            # shed/restore actuate through the pressure hook reading
            # self.slo.shed — the tracker already flipped the map, so
            # the "actuation" is making that state visible/auditable
            d.applied = True
        elif d.rule == "governor" and self._governor is not None:
            wm = self.watermark
            self._governor.set_watermark_overrides(wm.high, wm.low)
            d.applied = True
        elif d.rule == "fleet":
            cluster = getattr(self.session, "_cluster_handle", None)
            if cluster is None:
                return
            # enginelint: disable=RL001 (a failed scale actuation is counted and re-derived next tick; it must not kill the loop)
            try:
                if d.action == "add_worker":
                    d.detail["worker_id"] = cluster.add_worker()
                else:
                    wid = cluster.drain_candidate()
                    if wid is None:
                        d.reason += " (no drainable worker)"
                        return
                    d.detail["worker_id"] = wid
                    d.detail.update(cluster.remove_worker(wid, drain=True))
                d.applied = True
            # enginelint: disable=RL001 (control loop runs outside any query: a failed fleet actuation is recorded on the decision and re-derived next tick; no lifecycle exception can transit this thread)
            except Exception as e:
                d.reason += f" (actuation failed: {e})"
                get_registry().inc("control_fleet_errors")

    def _record(self, d: Decision) -> None:
        reg = get_registry()
        reg.inc("control_decisions")
        reg.inc(f"control.decision.{d.rule}.{d.action}")
        with self._lock:
            self.decisions.append(d)

    def _export_gauges(self, reg) -> None:
        reg.set_gauge("control.ticks", self._tick_count)
        reg.set_gauge("control.admission.max_concurrent",
                      self.admission.max_concurrent)
        reg.set_gauge("control.tenants.shed", len(self.slo.shed))
        if self.watermark is not None:
            reg.set_gauge("control.governor.high_watermark",
                          self.watermark.high)

    # -- history-driven plan routing ------------------------------------

    def route_for(self, logical) -> "dict | None":
        """Conf overrides for one plan (or None = run as configured).

        Looks the plan's fingerprint up in the bounded in-memory
        history index: enough FINISHED samples below the express
        threshold routes single-chip with the AQE stage machinery
        skipped; a fingerprint observed under several mesh shapes
        routes to the fastest median.  Pure lookup — never reads the
        history file on the query path (the index refreshes at tick
        cadence)."""
        idx = self._history_index
        if idx is None or logical is None:
            return None
        self._refresh_index()
        fp = self._fingerprint(logical)
        if fp is None:
            return None
        stats = idx.lookup(fp)
        if stats is None or stats["samples"] < self.route_min_samples:
            return None
        overrides: dict = {}
        reason = ""
        wall = stats["median_wall_s"]
        if wall is not None and wall < self.express_wall:
            overrides = {
                "spark.rapids.tpu.mesh.deviceCount": "1",
                "spark.sql.adaptive.enabled": "false",
                "spark.rapids.control.express": "true",
            }
            reason = (f"median wall {wall:.3f}s < express threshold "
                      f"{self.express_wall:g}s over {stats['samples']} "
                      "runs: single chip, no stage machinery")
        elif len(stats["by_mesh"]) > 1:
            best = min(stats["by_mesh"].items(),
                       key=lambda kv: kv[1]["median_wall_s"])
            overrides = {
                "spark.rapids.tpu.mesh.deviceCount": str(best[0])}
            reason = (f"fastest observed mesh shape is {best[0]} "
                      f"devices (median {best[1]['median_wall_s']:.3f}s "
                      f"across shapes {sorted(stats['by_mesh'])})")
        if not overrides:
            return None
        reg = get_registry()
        reg.inc("control_routes")
        reg.inc("control.route.express" if "spark.rapids.control.express"
                in overrides else "control.route.mesh")
        prev = self._routes.get(fp)
        self._routes[fp] = overrides
        self._routes.move_to_end(fp)
        while len(self._routes) > 256:
            self._routes.popitem(last=False)
        if prev != overrides:
            d = Decision("route",
                         "express" if "spark.rapids.control.express"
                         in overrides else "mesh",
                         reason, detail={
                             "fingerprint": fp,
                             "overrides": dict(overrides),
                             # the metering evidence behind the call
                             # (None until the history carries
                             # cost-attribution data, obs/profile.py)
                             "evidence": {
                                 "samples": stats["samples"],
                                 "median_wall_s": round(wall, 6)
                                 if wall is not None else None,
                                 "median_rows":
                                     stats.get("median_rows"),
                                 "median_device_s":
                                     stats.get("median_device_s"),
                             }})
            d.applied = True
            self._record(d)
        return overrides

    def _refresh_index(self) -> None:
        idx, path = self._history_index, self._history_path
        if idx is None or path is None:
            return
        # rate-limited by the index itself (stat + mtime/inode check);
        # the in-process fast path is session._record_history calling
        # note_entry() directly, so refresh only matters for history
        # written by OTHER processes sharing the directory
        idx.refresh_from(path)

    def note_history_entry(self, entry: dict) -> None:
        """In-process fast path: the session just appended a history
        entry — index it without waiting for a file re-read."""
        idx = self._history_index
        if idx is not None:
            idx.note_entry(entry)

    def _fingerprint(self, logical) -> "str | None":
        # enginelint: disable=RL001 (routing is best-effort: an unfingerprintable plan simply runs as configured)
        try:
            from spark_rapids_tpu.exec.compile_cache import fingerprint
            from spark_rapids_tpu.exec.result_cache import _plan_part
            try:
                return fingerprint(_plan_part(logical))
            # enginelint: disable=RL001 (same fallback the history recorder uses for in-memory scans)
            except Exception:
                return fingerprint(repr(logical))
        # enginelint: disable=RL001 (routing is advisory: an unfingerprintable plan routes nowhere, it must never fail the query being planned)
        except Exception:
            return None

    # -- the /control surface -------------------------------------------

    def status(self) -> dict:
        with self._lock:
            decisions = [d.to_dict() for d in self.decisions]
        out = {
            "running": self.running,
            "interval_s": self.interval,
            "ticks": self._tick_count,
            "admission": {
                "enabled": self.admission_enabled,
                "max_concurrent": self.admission.max_concurrent,
                "base_max_concurrent": self._base_cap,
                "bounds": [self.min_cap, self.max_cap],
            },
            "slo": self.slo.status(),
            "shed_tenants": dict(self.slo.shed),
            "decisions": decisions,
        }
        if self.watermark is not None:
            out["governor"] = {
                "high_watermark": self.watermark.high,
                "low_watermark": self.watermark.low,
                "base_high_watermark": self.watermark.base_high,
                "at_base": self.watermark.at_base(),
            }
        if self.fleet is not None:
            cluster = getattr(self.session, "_cluster_handle", None)
            out["fleet"] = {
                "workers": (None if cluster is None
                            else len(cluster.schedulable_workers())),
                "bounds": [self.fleet.min_workers,
                           self.fleet.max_workers],
                "cooldown_s": self.fleet.cooldown_s,
            }
        if self._history_index is not None:
            out["route"] = {
                "express_wall_s": self.express_wall,
                "min_samples": self.route_min_samples,
                "indexed_fingerprints": len(self._history_index),
            }
        return out


def _delta_between(cur: dict, prev: "dict | None") -> dict:
    """Counter/histogram movement between two raw snapshots (the
    registry's ``delta`` re-snapshots internally, which would defeat
    the frozen-signal fault — so the loop diffs snapshots it already
    holds)."""
    from spark_rapids_tpu.obs.registry import delta_histogram_snapshot
    before_c = (prev or {}).get("counters", {})
    counters = {}
    for k, v in cur.get("counters", {}).items():
        d = v - before_c.get(k, 0)
        if d:
            counters[k] = d
    before_h = (prev or {}).get("histograms", {})
    hists = {}
    for k, snap in cur.get("histograms", {}).items():
        d = delta_histogram_snapshot(snap, before_h.get(k))
        if d is not None:
            hists[k] = d
    return {"counters": counters, "histograms": hists}

#!/bin/bash
# Nightly tier: the full sweeps premerge defers.
#
# Reference model: jenkins/spark-tests.sh + the nightly integration
# Jenkinsfiles run every TPC-DS/TPC-H query and the fuzz suites against
# real hardware each night.  Here:
#   * all 99 TPC-DS + all 22 TPC-H queries verified vs the host oracle
#     at SF0.01 (TPCDS_FULL/TPCH_FULL flip the smoke subsets to full
#     sweeps),
#   * the fuzz suites with a fresh random seed,
#   * the cross-process TCP shuffle tests (real second process).
#
# Usage: ci/nightly.sh  (writes artifacts/ci_nightly_<utc-date>.txt)
set -euo pipefail
cd "$(dirname "$0")/.."

STAMP=$(date -u +%Y%m%dT%H%M%SZ)
OUT="artifacts/ci_nightly_${STAMP}.txt"
mkdir -p artifacts

{
  echo "== nightly @ ${STAMP} (commit $(git rev-parse --short HEAD)) =="
  echo "-- static analysis: enginelint --strict --"
  python -m tools.enginelint spark_rapids_tpu/ --strict
  echo "-- full TPC-DS (99) + TPC-H (22) oracle sweeps --"
  TPCDS_FULL=1 TPCH_FULL=1 python -m pytest \
    tests/test_tpcds.py tests/test_tpch.py -q --durations=20
  echo "-- fuzz + transport --"
  python -m pytest tests/test_fuzz.py tests/test_tcp_shuffle.py \
    tests/test_shuffle_transport.py -q
  echo "== nightly PASS =="
} 2>&1 | tee "$OUT"

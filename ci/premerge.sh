#!/bin/bash
# Premerge tier: every change runs this before merging.
#
# Reference model: jenkins/Jenkinsfile-blossom.premerge runs the unit
# suite + a smoke slice of the integration tests per PR, with the full
# sweeps deferred to nightly (jenkins/spark-tests.sh).  Here:
#   * full unit/differential suite on the virtual 8-device CPU mesh
#     (tests/conftest.py forces JAX_PLATFORMS=cpu) — TPC-DS/TPC-H run
#     their smoke query subsets,
#   * API-surface drift gate (tests/test_api_validation.py is part of
#     the suite),
#   * multichip dryrun: the full mesh pipeline compiles + executes on
#     8 virtual devices.
#
# Usage: ci/premerge.sh  (writes artifacts/ci_premerge_<utc-date>.txt)
set -euo pipefail
cd "$(dirname "$0")/.."

STAMP=$(date -u +%Y%m%dT%H%M%SZ)
OUT="artifacts/ci_premerge_${STAMP}.txt"
mkdir -p artifacts

{
  echo "== premerge @ ${STAMP} (commit $(git rev-parse --short HEAD)) =="
  echo "-- unit + differential suite (CPU mesh) --"
  python -m pytest tests/ -q --durations=10
  echo "-- shuffle fault-tolerance chaos suite (seeded, CPU-only) --"
  JAX_PLATFORMS=cpu python -m pytest tests/test_shuffle_fault_tolerance.py -q
  echo "-- OOM chaos suite: TPC-H under memory.oom.until_rows storm --"
  # split-and-retry must return exact-oracle results with nonzero
  # oom_splits, and retry_sync must recover flush-point OOMs with
  # async dispatch (SRT_SYNC_DISPATCH=0 behavior)
  JAX_PLATFORMS=cpu python -m pytest tests/test_oom_chaos.py \
    tests/test_oom_retry.py -q
  echo "-- stage-recovery chaos suite: peer death + spill corruption --"
  # lineage recomputation must return exact-oracle results with nonzero
  # stage_recomputes, and the spill-file leak check must find the spill
  # dir empty after ExecCtx close
  JAX_PLATFORMS=cpu python -m pytest tests/test_recovery_chaos.py \
    tests/test_stage_recovery.py -q
  # the fault registry must be INERT when spark.rapids.test.faults is
  # unset: no registry object, so every injection site is one None check
  JAX_PLATFORMS=cpu python - <<'PY'
from spark_rapids_tpu.conf import TpuConf
from spark_rapids_tpu.faults import FaultRegistry
assert FaultRegistry.from_conf(TpuConf({})) is None, \
    "fault registry must be inert when spark.rapids.test.faults is unset"
assert FaultRegistry.from_conf(None) is None
print("fault registry inert without spark.rapids.test.faults: ok")
PY
  echo "-- multichip dryrun (8 virtual devices) --"
  JAX_PLATFORMS=cpu XLA_FLAGS="--xla_force_host_platform_device_count=8" \
    python -c "import __graft_entry__ as g; g.dryrun_multichip(8); print('dryrun ok')"
  echo "== premerge PASS =="
} 2>&1 | tee "$OUT"

# Machine-feature-mismatch gate (VERDICT r4 weak #5): a cpu_aot_loader
# complaint means a stale/foreign AOT executable was loaded — a SIGILL
# from one would be indistinguishable from a wedged tunnel in CI.
if grep -q "cpu_aot_loader" "$OUT"; then
  echo "== premerge FAIL: cpu_aot_loader machine-feature warnings in log =="
  exit 1
fi

#!/bin/bash
# Premerge tier: every change runs this before merging.
#
# Reference model: jenkins/Jenkinsfile-blossom.premerge runs the unit
# suite + a smoke slice of the integration tests per PR, with the full
# sweeps deferred to nightly (jenkins/spark-tests.sh).  Here:
#   * full unit/differential suite on the virtual 8-device CPU mesh
#     (tests/conftest.py forces JAX_PLATFORMS=cpu) — TPC-DS/TPC-H run
#     their smoke query subsets,
#   * API-surface drift gate (tests/test_api_validation.py is part of
#     the suite),
#   * multichip dryrun: the full mesh pipeline compiles + executes on
#     8 virtual devices.
#
# Usage: ci/premerge.sh  (writes artifacts/ci_premerge_<utc-date>.txt)
set -euo pipefail
cd "$(dirname "$0")/.."

STAMP=$(date -u +%Y%m%dT%H%M%SZ)
OUT="artifacts/ci_premerge_${STAMP}.txt"
mkdir -p artifacts

{
  echo "== premerge @ ${STAMP} (commit $(git rev-parse --short HEAD)) =="
  echo "-- static analysis: enginelint --strict --"
  # source-convention gate (docs/developer-guide.md): zero unsuppressed
  # findings, and every suppression carries a written reason
  python -m tools.enginelint spark_rapids_tpu/ --strict
  echo "-- plan verifier smoke: TPC-H ladder, mesh-8, fusion+AQE --"
  # every ladder plan must verify clean through EVERY rewrite pass
  # (everyPass mode), and the default-mode walk (one pass after the
  # final rewrite) must add <2% to the bench's planning step
  # (build_query + prepare) aggregated across the ladder
  JAX_PLATFORMS=cpu XLA_FLAGS="--xla_force_host_platform_device_count=8" \
    python - <<'PY'
import os, tempfile, time

from spark_rapids_tpu.bench.tpch_gen import generate_tpch
from spark_rapids_tpu.bench.tpch_queries import build_tpch_query
from spark_rapids_tpu.plan.verify import verify_plan
from spark_rapids_tpu.session import TpuSession

d = os.path.join(tempfile.mkdtemp(), "tpch")
generate_tpch(d, sf=0.01)
LADDER = ["q1", "q3", "q6", "q12", "q13", "q18"]
BASE = {"spark.rapids.tpu.mesh.deviceCount": 8,
        "spark.sql.adaptive.shuffledHashJoin.enabled": True}

# 1) zero violations with per-pass verification armed on every query
every = TpuSession({**BASE, "spark.rapids.sql.verify.plan.everyPass": True})
for q in LADDER:
    build_tpch_query(q, every, d)._overridden(quiet=True)
print(f"verifier smoke: {len(LADDER)} ladder plans clean through every pass")

# 2) overhead probe: default-mode verify (one final-pass walk) must add
# <2% to plan-time, aggregated across the ladder (median-of-samples)
s = TpuSession({**BASE, "spark.rapids.sql.verify.plan": False})
tot_plan = tot_verify = 0.0
for q in LADDER:
    df = build_tpch_query(q, s, d)
    for _ in range(30):  # warm tag/expr caches before timing
        df._overridden(quiet=True)
    plans, ts_plan = [], []
    for _ in range(60):
        t0 = time.perf_counter()
        df2 = build_tpch_query(q, s, d)
        ov, meta = df2._overridden(quiet=True)
        ts_plan.append(time.perf_counter() - t0)
        plans.append(meta.exec_node)
    ts_verify = []
    for p in plans:
        t0 = time.perf_counter()
        verify_plan(p, s.conf)  # first verify of a fresh plan
        ts_verify.append(time.perf_counter() - t0)
    ts_plan.sort(); ts_verify.sort()
    med_p, med_v = ts_plan[len(ts_plan)//2], ts_verify[len(ts_verify)//2]
    tot_plan += med_p; tot_verify += med_v
    print(f"  {q}: plan={med_p*1e6:.0f}us verify={med_v*1e6:.1f}us "
          f"({med_v/med_p*100:.2f}%)")
frac = tot_verify / tot_plan
print(f"verifier overhead across ladder: {frac*100:.2f}% of plan-time")
assert frac < 0.02, \
    f"plan verifier adds {frac*100:.2f}% to plan-time (budget: 2%)"
PY
  echo "-- unit + differential suite (CPU mesh) --"
  python -m pytest tests/ -q --durations=10
  echo "-- shuffle fault-tolerance chaos suite (seeded, CPU-only) --"
  JAX_PLATFORMS=cpu python -m pytest tests/test_shuffle_fault_tolerance.py -q
  echo "-- OOM chaos suite: TPC-H under memory.oom.until_rows storm --"
  # split-and-retry must return exact-oracle results with nonzero
  # oom_splits, and retry_sync must recover flush-point OOMs with
  # async dispatch (SRT_SYNC_DISPATCH=0 behavior)
  JAX_PLATFORMS=cpu python -m pytest tests/test_oom_chaos.py \
    tests/test_oom_retry.py -q
  echo "-- stage-recovery chaos suite: peer death + spill corruption --"
  # lineage recomputation must return exact-oracle results with nonzero
  # stage_recomputes, and the spill-file leak check must find the spill
  # dir empty after ExecCtx close
  JAX_PLATFORMS=cpu python -m pytest tests/test_recovery_chaos.py \
    tests/test_stage_recovery.py -q
  # the fault registry must be INERT when spark.rapids.test.faults is
  # unset: no registry object, so every injection site is one None check
  JAX_PLATFORMS=cpu python - <<'PY'
from spark_rapids_tpu.conf import TpuConf
from spark_rapids_tpu.faults import FaultRegistry
assert FaultRegistry.from_conf(TpuConf({})) is None, \
    "fault registry must be inert when spark.rapids.test.faults is unset"
assert FaultRegistry.from_conf(None) is None
print("fault registry inert without spark.rapids.test.faults: ok")
PY
  echo "-- observability gate: traced TPC-H run + schema validation --"
  # a TPC-H query with tracing + metrics on must export a trace and a
  # metrics snapshot that validate against the checked-in schema
  # (ci/obs_schema.json), with every event under ONE query/trace id
  JAX_PLATFORMS=cpu python - <<'PY'
import json, os, sys, tempfile
sys.path.insert(0, "scripts")
from validate_obs import validate, load_schema
d = tempfile.mkdtemp()
trace_dir = os.path.join(d, "traces")
from spark_rapids_tpu.bench.runner import run_benchmark
from spark_rapids_tpu.bench.tpch_gen import generate_tpch
data = os.path.join(d, "tpch")
generate_tpch(data, sf=0.01)
r = run_benchmark(data, 0.01, ["q6"], generate=False, suite="tpch",
                  session_conf={
                      "spark.rapids.obs.trace.enabled": "true",
                      "spark.rapids.obs.trace.dir": trace_dir})[0]
assert r.get("ok") and "error" not in r, r
traces = sorted(os.listdir(trace_dir))
assert traces, "no trace exported"
for t in traces:
    doc = json.load(open(os.path.join(trace_dir, t)))
    errs = validate(doc, load_schema("trace"))
    assert not errs, errs[:5]
    ids = {e["args"]["query_id"] for e in doc["traceEvents"]}
    assert len(ids) == 1, ids
obs = r["observability"]
assert obs["query_id"] and obs["trace_id"] and obs["plan_analyzed"]
# the unified metrics snapshot validates too
from spark_rapids_tpu.conf import TpuConf
from spark_rapids_tpu.exec.core import ExecCtx
from spark_rapids_tpu.obs.registry import query_metrics_snapshot
with ExecCtx(backend="host", conf=TpuConf({})) as ctx:
    errs = validate(query_metrics_snapshot(ctx), load_schema("metrics"))
assert not errs, errs[:5]
print(f"observability gate: {len(traces)} trace(s) schema-valid")
PY
  # disabled-path import discipline: with tracing off, the per-batch hot
  # path must never import the tracer or diagnostics modules (their cost
  # is provably zero, not just "small"); obs.registry is stdlib-only and
  # allowed
  JAX_PLATFORMS=cpu python - <<'PY'
import sys
from spark_rapids_tpu.session import TpuSession
from spark_rapids_tpu import types as T
from spark_rapids_tpu.expr.aggregates import Sum
from spark_rapids_tpu.expr.core import col
s = TpuSession({})
schema = T.Schema([T.StructField("k", T.IntegerType(), True),
                   T.StructField("v", T.LongType(), True)])
df = s.from_pydict({"k": [i % 5 for i in range(200)],
                    "v": list(range(200))}, schema, partitions=2)
assert len(df.group_by("k").agg(Sum(col("v"))).collect()) == 5
for mod in ("spark_rapids_tpu.obs.trace", "spark_rapids_tpu.obs.diag"):
    assert mod not in sys.modules, \
        f"{mod} imported on the tracing-disabled path"
print("disabled path imports no tracer/diagnostics: ok")
PY
  echo "-- query lifecycle gate: admission + cancel + deadline + shutdown --"
  # four concurrent queries through one session bounded to 2 admitted:
  # one is cancelled mid-flight (QueryCancelled), one carries a tiny
  # deadline (QueryDeadlineExceeded), the other two must return EXACT
  # results; after shutdown the session rejects new work and no
  # tpu-task / tpu-shuffle-srv threads are left alive
  JAX_PLATFORMS=cpu python - <<'PY'
import threading
import time

from spark_rapids_tpu import types as T
from spark_rapids_tpu.exec.lifecycle import (QueryCancelled,
                                             QueryDeadlineExceeded,
                                             QueryRejected)
from spark_rapids_tpu.expr.aggregates import Sum
from spark_rapids_tpu.expr.core import col
from spark_rapids_tpu.obs.registry import get_registry
from spark_rapids_tpu.session import TpuSession

s = TpuSession({"spark.rapids.sql.admission.maxConcurrentQueries": 2,
                "spark.rapids.sql.admission.maxQueuedQueries": 8})
schema = T.Schema([T.StructField("k", T.IntegerType(), True),
                   T.StructField("v", T.LongType(), True)])
small = s.from_pydict({"k": [i % 7 for i in range(4000)],
                       "v": list(range(4000))}, schema, partitions=4) \
    .group_by("k").agg(Sum(col("v")))
big = s.from_pydict({"k": [i % 97 for i in range(400000)],
                     "v": list(range(400000))}, schema, partitions=8) \
    .group_by("k").agg(Sum(col("v")))
expected = sorted(small.collect())

results = {}
def run(name, df, timeout=None):
    try:
        results[name] = ("ok", df.collect(timeout=timeout))
    except BaseException as e:
        results[name] = ("err", e)

before = get_registry().snapshot()
threads = [threading.Thread(target=run, args=("victim", big))]
threads[0].start()
deadline = time.monotonic() + 30.0
while not s.active_queries() and time.monotonic() < deadline:
    time.sleep(0.002)
victim_qid, = s.active_queries()
for name, df, tmo in (("deadline", small, 0.0005),
                      ("exact1", small, None), ("exact2", small, None)):
    t = threading.Thread(target=run, args=(name, df, tmo))
    t.start()
    threads.append(t)
assert s.cancel(victim_qid), "victim finished before the cancel landed"
for t in threads:
    t.join(timeout=120.0)
    assert not t.is_alive(), "query did not unwind in time"

kind, val = results["victim"]
assert kind == "err" and isinstance(val, QueryCancelled), results["victim"]
kind, val = results["deadline"]
assert kind == "err" and isinstance(val, QueryDeadlineExceeded), \
    results["deadline"]
for name in ("exact1", "exact2"):
    kind, val = results[name]
    assert kind == "ok" and sorted(val) == expected, (name, kind)
moved = get_registry().delta(before)["counters"]
assert moved.get("queries_cancelled") == 1, moved
assert moved.get("queries_deadline_exceeded") == 1, moved

s.shutdown(drain=True, timeout=60.0)
try:
    small.collect()
    raise SystemExit("collect after shutdown must raise QueryRejected")
except QueryRejected:
    pass
leaked = [t.name for t in threading.enumerate()
          if t.name.startswith(("tpu-task", "tpu-shuffle-srv"))]
assert not leaked, f"leaked engine threads after shutdown: {leaked}"
print("lifecycle gate: cancel/deadline/exact x2 + clean shutdown: ok")
PY
  echo "-- memory governor gate: pressure shed + exact + zero leaked reservations --"
  # four concurrent queries on one session under a small device budget
  # with the shed watermark forced low: at least one NEW admission must
  # be load-shed with QueryRejected while the four run, the four must
  # return EXACT results, the governor_* counters/gauges must be
  # present, and after shutdown(drain=True) the governor holds zero
  # ledgers, zero reservations, and its daemon thread is gone
  JAX_PLATFORMS=cpu python - <<'PY'
import threading
import time

from spark_rapids_tpu import types as T
from spark_rapids_tpu.exec.lifecycle import QueryRejected
from spark_rapids_tpu.expr.aggregates import Sum
from spark_rapids_tpu.expr.core import col
from spark_rapids_tpu.memory.governor import get_governor
from spark_rapids_tpu.obs.registry import get_registry
from spark_rapids_tpu.plan.verify import verify_governor_ledger
from spark_rapids_tpu.session import TpuSession

s = TpuSession({
    "spark.rapids.sql.admission.maxConcurrentQueries": 4,
    "spark.rapids.sql.admission.maxQueuedQueries": 0,
    "spark.rapids.memory.tpu.spillStoreSize": 8 << 20,
    "spark.rapids.memory.governor.shedWatermark": 0.01,
    "spark.rapids.memory.governor.shedHoldSeconds": 0.05,
})
schema = T.Schema([T.StructField("k", T.IntegerType(), True),
                   T.StructField("v", T.LongType(), True)])

def big():
    return s.from_pydict({"k": [i % 97 for i in range(400000)],
                          "v": list(range(400000))}, schema, partitions=8) \
        .group_by("k").agg(Sum(col("v")))

expected = sorted(big().collect())
gov = get_governor()
before = get_registry().snapshot()

results = {}
def run(name, df):
    try:
        results[name] = ("ok", df.collect())
    except BaseException as e:
        results[name] = ("err", e)

threads = [threading.Thread(target=run, args=(f"q{i}", big()))
           for i in range(4)]
for t in threads:
    t.start()

# wait for sustained pressure, then the fifth admission must shed
shed = None
probe = big()
deadline = time.monotonic() + 60.0
while time.monotonic() < deadline and shed is None:
    if gov.admission_pressure() is None:
        time.sleep(0.01)
        continue
    try:
        probe.collect()
    except QueryRejected as e:
        shed = e
assert shed is not None, "no admission was pressure-shed within 60s"
assert "shedWatermark" in str(shed), shed

for t in threads:
    t.join(timeout=180.0)
    assert not t.is_alive(), "query did not finish in time"
for name, (kind, val) in results.items():
    assert kind == "ok" and sorted(val) == expected, (name, kind)

moved = get_registry().delta(before)["counters"]
assert moved.get("governor_pressure_sheds", 0) >= 1, moved
gauges = get_registry().snapshot()["gauges"]
for g in ("governor.device_bytes_total", "governor.reserved_bytes",
          "governor.queries_registered", "governor.budget_bytes"):
    assert g in gauges, (g, sorted(gauges))

s.shutdown(drain=True, timeout=60.0)
assert gov.query_stats() == {}, gov.query_stats()
assert gov.reserved_bytes() == 0, "leaked grant reservation"
verify_governor_ledger(gov)
deadline = time.monotonic() + 5.0
while time.monotonic() < deadline and any(
        t.name == "tpu-mem-governor" for t in threading.enumerate()):
    time.sleep(0.05)
leaked = [t.name for t in threading.enumerate()
          if t.name.startswith(("tpu-task", "tpu-shuffle-srv",
                                "tpu-mem-governor"))]
assert not leaked, f"leaked engine threads after shutdown: {leaked}"
print("governor gate: pressure shed, 4x exact, zero leaked reservations: ok")
PY
  echo "-- fusion + compile-cache gate: warm reruns compile NOTHING --"
  # the same query run twice in one process must be pure cache reuse
  # (compile_count delta 0 on the second run — the whole point of the
  # process-wide compile cache), and fusion.enabled=false must restore
  # the exact unfused plan shape
  JAX_PLATFORMS=cpu python - <<'PY'
import os, tempfile

from spark_rapids_tpu.bench.tpch_gen import generate_tpch
from spark_rapids_tpu.bench.tpch_queries import build_tpch_query
from spark_rapids_tpu.obs.registry import get_registry
from spark_rapids_tpu.session import TpuSession

d = os.path.join(tempfile.mkdtemp(), "tpch")
generate_tpch(d, sf=0.01)

def classes(query, conf):
    s = TpuSession(dict(conf))
    df = build_tpch_query(query, s, d)
    ov, meta = df._overridden(quiet=True)
    acc = []
    def walk(n):
        acc.append(type(n).__name__)
        for c in n.children:
            walk(c)
    walk(meta.exec_node)
    return acc, sorted(df.collect(), key=str)

# 1) warm rerun: a FRESH session over the same q6 must record ZERO new
# compiles and zero program-cache misses — only hits
classes("q6", {})
before = get_registry().snapshot()
_, rows = classes("q6", {})
moved = get_registry().delta(before)["counters"]
assert rows, "q6 returned no rows"
assert moved.get("compile_count", 0) == 0, f"second run compiled: {moved}"
assert moved.get("fusion_cache_misses", 0) == 0, moved
assert moved.get("fusion_cache_hits", 0) >= 1, moved

# 2) shape reversibility: q3 fuses its filter/project chain; disabling
# fusion restores the per-operator plan with identical results
fused, frows = classes("q3", {})
plain, prows = classes("q3", {"spark.rapids.sql.fusion.enabled": "false"})
assert "FusedStageExec" in fused, fused
assert "FusedStageExec" not in plain, plain
assert all(c in plain for c in fused if c != "FusedStageExec"), (fused, plain)
assert frows == prows, "fused vs unfused rows diverge on q3"
print("fusion gate: warm rerun compiles 0, shape reversible: ok")
PY
  echo "-- adaptive execution gate: broadcast switch, skew split, reversible --"
  # three contracts on the runtime re-optimizer: a forced-small build
  # side is rewritten to broadcast strategy EXACTLY once with rows
  # identical to the static plan; a skewed AQE shuffle records skew
  # splits with rows identical; and adaptive.enabled=false restores the
  # byte-identical static plan shape
  JAX_PLATFORMS=cpu python - <<'PY'
import numpy as np

from spark_rapids_tpu import types as T
from spark_rapids_tpu.obs.registry import get_registry
from spark_rapids_tpu.session import TpuSession

AQE = {"spark.sql.adaptive.shuffledHashJoin.enabled": True}
SB = T.Schema([T.StructField("k", T.LongType()),
               T.StructField("v", T.DoubleType())])
SS = T.Schema([T.StructField("k", T.LongType()),
               T.StructField("w", T.DoubleType())])

def q(s, n=600, nkeys=10, skew=0.0):
    rng = np.random.default_rng(7)
    keys = rng.integers(0, nkeys, n)
    if skew:
        keys = np.where(rng.random(n) < skew, 7, keys)
    big = s.from_pydict({"k": [int(x) for x in keys],
                         "v": [float(i) for i in range(n)]},
                        SB, partitions=4, rows_per_batch=128)
    small = s.from_pydict({"k": list(range(nkeys)),
                           "w": [float(k) * 10 for k in range(nkeys)]}, SS)
    return big.join(small, on="k", how="inner")

# 1) forced-small build: exactly ONE broadcast switch, rows exact
want = sorted(q(TpuSession({})).collect(), key=str)
before = get_registry().snapshot()
got = sorted(q(TpuSession(AQE)).collect(), key=str)
moved = get_registry().delta(before)["counters"]
assert got == want and got, "broadcast-switch rows diverge from static plan"
assert moved.get("aqe_broadcast_switches", 0) == 1, moved

# 2) skewed shuffle: >=1 skew split, rows exact
skew_conf = dict(AQE)
skew_conf.update({"spark.sql.adaptive.autoBroadcastJoinThreshold": 0,
                  "spark.sql.adaptive.advisoryPartitionSizeInBytes": 4096,
                  "spark.sql.adaptive.skewedPartitionThresholdInBytes": 16384})
kw = dict(n=4000, nkeys=64, skew=0.9)
want = sorted(q(TpuSession({}), **kw).collect(), key=str)
before = get_registry().snapshot()
got = sorted(q(TpuSession(skew_conf), **kw).collect(), key=str)
moved = get_registry().delta(before)["counters"]
assert got == want and got, "skew-split rows diverge from static plan"
assert moved.get("aqe_skew_splits", 0) >= 1, moved

# 3) adaptive.enabled=false restores the byte-identical static shape
off = dict(AQE)
off["spark.sql.adaptive.enabled"] = False
_, m_off = q(TpuSession(off))._overridden(quiet=True)
_, m_static = q(TpuSession({"spark.sql.adaptive.enabled": False})) \
    ._overridden(quiet=True)
assert m_off.exec_node.tree_string() == m_static.exec_node.tree_string()
assert "StageBoundaryExec" not in m_off.exec_node.tree_string()
print("adaptive gate: 1 broadcast switch, skew splits, off-switch reversible: ok")
PY
  echo "-- pod-scale mesh gate: regions exact, warm, and reversible --"
  # q6 + q3 over an 8-device mesh must return EXACTLY the single-chip
  # rows; a warm rerun at the SAME mesh shape must compile nothing (the
  # region/mesh programs are keyed by mesh shape in the process-wide
  # compile cache); and mesh.deviceCount=0 must restore the exact
  # single-chip plan shape
  JAX_PLATFORMS=cpu XLA_FLAGS="--xla_force_host_platform_device_count=8" \
    python - <<'PY'
import os, tempfile

from spark_rapids_tpu.bench.tpch_gen import generate_tpch
from spark_rapids_tpu.bench.tpch_queries import build_tpch_query
from spark_rapids_tpu.obs.registry import get_registry
from spark_rapids_tpu.session import TpuSession

d = os.path.join(tempfile.mkdtemp(), "tpch")
generate_tpch(d, sf=0.01)
MESH = {"spark.rapids.tpu.mesh.deviceCount": 8}

def classes(query, conf):
    s = TpuSession(dict(conf))
    df = build_tpch_query(query, s, d)
    ov, meta = df._overridden(quiet=True)
    acc = []
    def walk(n):
        acc.append(type(n).__name__)
        for c in n.children:
            walk(c)
    walk(meta.exec_node)
    return acc, sorted(df.collect(), key=str)

# 1) mesh-vs-single exact equality on q6 and q3
for q in ("q6", "q3"):
    mnames, mrows = classes(q, MESH)
    _, prows = classes(q, {})
    assert mrows == prows, f"{q}: mesh-8 rows != single-chip rows"
    assert any(n.startswith("Mesh") for n in mnames), (q, mnames)

# 2) warm rerun at the FIXED mesh shape compiles nothing
before = get_registry().snapshot()
_, rows = classes("q3", MESH)
moved = get_registry().delta(before)["counters"]
assert rows, "q3 returned no rows"
assert moved.get("compile_count", 0) == 0, f"warm mesh rerun compiled: {moved}"

# 3) deviceCount=0 restores the exact single-chip plan shape
zero, zrows = classes("q3", {"spark.rapids.tpu.mesh.deviceCount": 0})
plain, prows = classes("q3", {})
assert zero == plain, (zero, plain)
assert zrows == prows
assert not any(n.startswith("Mesh") for n in zero), zero
print("mesh gate: q6/q3 exact, warm rerun compiles 0, deviceCount=0 reversible: ok")
PY
  echo "-- mesh-join gate: joins absorbed into regions, no gather, exact --"
  # q3's joins must run INSIDE a mesh region (one per-device program,
  # build broadcast / key exchanges as in-program collectives), with
  # zero mesh_gather_fallbacks end to end, rows exactly equal to the
  # single-chip run, and deviceCount=0 must restore the exact
  # single-chip plan shape untouched by region formation
  JAX_PLATFORMS=cpu XLA_FLAGS="--xla_force_host_platform_device_count=8" \
    python - <<'PY'
import os, tempfile

from spark_rapids_tpu.bench.tpch_gen import generate_tpch
from spark_rapids_tpu.bench.tpch_queries import build_tpch_query
from spark_rapids_tpu.obs.registry import get_registry
from spark_rapids_tpu.session import TpuSession

d = os.path.join(tempfile.mkdtemp(), "tpch")
generate_tpch(d, sf=0.01)
MESH = {"spark.rapids.tpu.mesh.deviceCount": 8}

def plan_and_rows(query, conf):
    s = TpuSession(dict(conf))
    df = build_tpch_query(query, s, d)
    ov, meta = df._overridden(quiet=True)
    nodes = []
    def walk(n):
        nodes.append(n)
        for c in n.children:
            walk(c)
    walk(meta.exec_node)
    return nodes, sorted(df.collect(), key=str)

# 1) q3 at mesh-8: a region whose program contains a join, zero gather
#    fallbacks, rows exactly the single-chip rows
before = get_registry().snapshot()
mnodes, mrows = plan_and_rows("q3", MESH)
moved = get_registry().delta(before)["counters"]
regions = [n for n in mnodes if type(n).__name__ == "MeshRegionExec"]
assert regions, [type(n).__name__ for n in mnodes]
assert any("MeshJoinExec" in r.node_desc() for r in regions), \
    [r.node_desc() for r in regions]
assert moved.get("mesh_gather_fallbacks", 0) == 0, moved
assert moved.get("mesh_regions", 0) >= 1, moved
_, prows = plan_and_rows("q3", {})
assert mrows == prows, "q3: mesh-8 rows != single-chip rows"

# 2) deviceCount=0 restores the exact single-chip plan shape
znodes, zrows = plan_and_rows("q3", {"spark.rapids.tpu.mesh.deviceCount": 0})
pnodes, prows2 = plan_and_rows("q3", {})
assert [type(n).__name__ for n in znodes] == \
    [type(n).__name__ for n in pnodes]
assert zrows == prows2
print("mesh-join gate: q3 join-in-region, 0 gather fallbacks, exact, "
      "deviceCount=0 reversible: ok")
PY
  echo "-- serving tier gate: warm cache hit, weighted order, tenant shed, reversible --"
  # the multi-tenant serving tier's four contracts: (1) 8 queries from
  # 2 tenants at 3:1 weights, then the identical warm set again — the
  # warm round must be pure result-cache hits with compile_count delta
  # 0 AND queries_executed delta 0 (the executor is never dispatched);
  # (2) the observed admission order under a 6:2 backlog respects the
  # 3:1 weights; (3) a pressure event sheds the over-quota tenant and
  # spares the quiet one; (4) resultCache.enabled=false is
  # byte-identical to today — same rows, every query re-executed, and
  # not one result_cache counter moves
  JAX_PLATFORMS=cpu python - <<'PY'
import os, tempfile, threading, time

from spark_rapids_tpu.bench.tpch_gen import generate_tpch
from spark_rapids_tpu.bench.tpch_queries import build_tpch_query
from spark_rapids_tpu.exec.lifecycle import AdmissionController, QueryRejected
from spark_rapids_tpu.obs.registry import get_registry
from spark_rapids_tpu.session import TpuSession

d = os.path.join(tempfile.mkdtemp(), "tpch")
generate_tpch(d, sf=0.01)
WEIGHTS = {"spark.rapids.sql.admission.tenantWeights": "etl:3,bi:1"}
PLAN = [("etl", "q3"), ("etl", "q13"), ("etl", "q18"), ("bi", "q3"),
        ("etl", "q3"), ("bi", "q13"), ("etl", "q13"), ("etl", "q18")]

def run_plan(s):
    out = {}
    for tenant, q in PLAN:
        rows = build_tpch_query(q, s, d).collect(tenant=tenant)
        out[q] = sorted(rows, key=str)
    return out

# 1) 8 queries from 2 tenants cold, then the identical set warm: the
# warm round is served entirely from the result cache — zero compiles,
# zero executor dispatches
s = TpuSession(dict(WEIGHTS))
cold = run_plan(s)
before = get_registry().snapshot()
warm = run_plan(s)
moved = get_registry().delta(before)["counters"]
assert warm == cold, "warm cache-served rows != cold rows"
assert moved.get("compile_count", 0) == 0, f"warm round compiled: {moved}"
assert moved.get("queries_executed", 0) == 0, \
    f"warm round dispatched the executor: {moved}"
assert moved.get("result_cache_hits", 0) >= len(PLAN), moved

# 2) admission order respects the 3:1 weights: saturate the one slot,
# backlog 6 etl + 2 bi with pinned arrival order, drain, and check the
# admission log — 6:2 overall, >=2x share while bi is queued, and bi
# is not starved out of the first 4 slots
ac = AdmissionController(max_concurrent=1, max_queued=16,
                         queue_timeout=30.0,
                         tenant_weights={"etl": 3.0, "bi": 1.0})
ac.admit("holder")
specs = [("etl", f"e{i}") for i in range(6)] + \
        [("bi", f"b{i}") for i in range(2)]
threads = []
for i, (tenant, name) in enumerate(specs):
    def wait_in(t=tenant, n=name):
        ac.admit(n, tenant=t)
        ac.release(tenant=t)
    th = threading.Thread(target=wait_in)
    th.start()
    threads.append(th)
    deadline = time.monotonic() + 5.0
    while ac.queued < i + 1 and time.monotonic() < deadline:
        time.sleep(0.002)
    assert ac.queued == i + 1
ac.release()
for t in threads:
    t.join(timeout=10.0)
    assert not t.is_alive(), "queued admission never drained"
log = [tenant for tenant, _q in ac.admission_log if tenant != "default"]
assert log.count("etl") == 6 and log.count("bi") == 2, log
last_bi = max(i for i, t in enumerate(log) if t == "bi")
window = log[:last_bi + 1]
assert window.count("etl") >= 2 * window.count("bi"), log
assert "bi" in log[:4], log

# 3) pressure sheds the over-quota tenant first: hog holds 3 of 4
# occupied slots at equal weight, so the pressure event rejects hog's
# next admission while the quiet tenant is spared and admitted
before = get_registry().snapshot()
ac2 = AdmissionController(max_concurrent=0)
for i in range(3):
    ac2.admit(f"h{i}", tenant="hog")
ac2.admit("q0", tenant="quiet")
ac2.pressure_hook = lambda tenant: "memory pressure: premerge"
try:
    ac2.admit("h3", tenant="hog")
    raise SystemExit("over-quota tenant was not pressure-shed")
except QueryRejected:
    pass
ac2.admit("q1", tenant="quiet")
dm = get_registry().delta(before)["counters"]
assert dm.get("admission.tenant.hog.rejected") == 1, dm
assert dm.get("admission.tenant.quiet.rejected", 0) == 0, dm
assert dm.get("admission_pressure_spared") == 1, dm

# 4) reversibility: resultCache.enabled=false is byte-identical —
# same rows, both runs dispatch the executor, no cache counter moves
off = TpuSession(dict(WEIGHTS,
                      **{"spark.rapids.sql.resultCache.enabled": "false"}))
before = get_registry().snapshot()
off1 = run_plan(off)
off2 = run_plan(off)
moved = get_registry().delta(before)["counters"]
assert off1 == cold and off2 == cold, "cache-off rows diverge"
assert moved.get("queries_executed", 0) == 2 * len(PLAN), moved
assert not any(k.startswith("result_cache") for k in moved), moved
print("serving gate: warm hit 0-dispatch, 3:1 order, tenant shed, "
      "cache-off identical: ok")
PY
  echo "-- cluster runtime gate: local[2] exact, worker-death recovery, clean drain --"
  # driver/worker pools over the DCN shuffle plane (cluster/): q6+q3 on
  # local[2] must equal the host-oracle rows exactly; SIGKILLing a
  # worker mid-q18 must recompute only the lost map outputs on the
  # survivor (exact rows, nonzero recovery counters); and
  # shutdown(drain=True) must leave zero orphan worker processes and
  # no cluster threads
  JAX_PLATFORMS=cpu python - <<'PY'
import os, tempfile, threading, time

import pyarrow.parquet as pq

from spark_rapids_tpu.bench.runner import run_benchmark
from spark_rapids_tpu.bench.tpch_gen import generate_tpch
from spark_rapids_tpu.session import TpuSession

d = os.path.join(tempfile.mkdtemp(), "tpch")
generate_tpch(d, sf=0.01)
# split tables so scans are multi-partition and the planner inserts
# real shuffle exchanges for the cluster to shard
for table in ("lineitem", "orders", "customer"):
    t = pq.read_table(os.path.join(d, table, "part-0.parquet"))
    step = -(-t.num_rows // 4)
    for i in range(4):
        pq.write_table(t.slice(i * step, step),
                       os.path.join(d, table, f"part-{i}.parquet"))

FAST = {"spark.rapids.cluster.mode": "local[2]",
        "spark.rapids.shuffle.tcp.maxRetries": 1,
        "spark.rapids.shuffle.tcp.retryWaitSeconds": 0.1}

# 1) local[2] q6+q3 exact vs the host oracle, q3's shuffles clustered
reports = run_benchmark(d, 0.01, ["q6", "q3"], verify=True, generate=False,
                        suite="tpch", session_conf=dict(FAST))
for r in reports:
    assert r.get("ok") and "error" not in r, r
reg = (reports[1]["observability"].get("registry") or {}) \
    .get("counters") or {}
assert reg.get("cluster.shuffles_clustered", 0) >= 1, reg

# 2) worker SIGKILLed mid-q18: lineage recovery on the survivor, exact
chaos = dict(FAST)
chaos["spark.rapids.test.faults"] = "cluster.worker.dead:dead,times=1"
r = run_benchmark(d, 0.01, ["q18"], verify=True, generate=False,
                  suite="tpch", session_conf=chaos)[0]
assert r.get("ok") and "error" not in r, r
reg = (r["observability"].get("registry") or {}).get("counters") or {}
assert reg.get("cluster_workers_lost", 0) >= 1, reg
assert reg.get("stage_recomputes", 0) > 0, reg
assert reg.get("map_outputs_recomputed", 0) > 0, reg

# 3) shutdown(drain=True) reaps every worker and every cluster thread
s = TpuSession({"spark.rapids.cluster.mode": "local[2]"})
handles = s._cluster().workers()
assert len(handles) == 2 and all(h.alive for h in handles)
s.shutdown(drain=True)
for h in handles:
    assert h.proc.poll() is not None, \
        f"orphan worker {h.worker_id} after shutdown"
deadline = time.monotonic() + 5.0
while time.monotonic() < deadline and any(
        t.name in ("tpu-cluster-rpc", "tpu-cluster-monitor")
        for t in threading.enumerate()):
    time.sleep(0.05)
leaked = [t.name for t in threading.enumerate()
          if t.name in ("tpu-cluster-rpc", "tpu-cluster-monitor")]
assert not leaked, f"leaked cluster threads after shutdown: {leaked}"
print("cluster gate: local[2] q6/q3 exact, worker-death recovery, "
      "clean drain: ok")
PY
  echo "-- elasticity gate: mid-query drain, straggler speculation, quarantine --"
  # ISSUE 16 elastic membership: retiring a worker mid-q18 must migrate
  # its map outputs to the survivor (exact rows, ZERO recomputes — a
  # planned scale-down costs a copy, not a recompute); a fragment held
  # by the slow fault must be speculatively duplicated and the
  # duplicate's rows committed exactly once; and a flaky worker must be
  # quarantined after maxFailures, re-admitted after probation, with
  # zero orphan processes at the end
  JAX_PLATFORMS=cpu python - <<'PY'
import os, tempfile, time

import numpy as np
import pyarrow.parquet as pq

import spark_rapids_tpu.cluster.exec as cexec
from spark_rapids_tpu import types as T
from spark_rapids_tpu.bench.tpch_gen import generate_tpch
from spark_rapids_tpu.bench.tpch_queries import build_tpch_query
from spark_rapids_tpu.expr.aggregates import Sum
from spark_rapids_tpu.expr.core import col
from spark_rapids_tpu.obs.registry import get_registry
from spark_rapids_tpu.session import TpuSession

d = os.path.join(tempfile.mkdtemp(), "tpch")
generate_tpch(d, sf=0.01)
for table in ("lineitem", "orders", "customer"):
    t = pq.read_table(os.path.join(d, table, "part-0.parquet"))
    step = -(-t.num_rows // 4)
    for i in range(4):
        pq.write_table(t.slice(i * step, step),
                       os.path.join(d, table, f"part-{i}.parquet"))

# 1) graceful drain mid-q18: retire w1 synchronously at the reduce's
# first map-output fetch (all maps registered, nothing consumed yet)
s0 = TpuSession()
want = sorted(build_tpch_query("q18", s0, d).collect())
s0.shutdown()
s = TpuSession({"spark.rapids.cluster.mode": "local[2]"})
drv = s._cluster()
fired = {}
orig = cexec.ClusterMapOutputTracker.fetch_partition
def hooked(self, shuffle_id, pid, lo=0, hi=None):
    if not fired:
        fired["ok"] = True
        fired.update(drv.remove_worker("w1", drain=True))
    return orig(self, shuffle_id, pid, lo, hi)
cexec.ClusterMapOutputTracker.fetch_partition = hooked
before = get_registry().snapshot()
got = sorted(build_tpch_query("q18", s, d).collect())
cexec.ClusterMapOutputTracker.fetch_partition = orig
assert fired.get("ok"), "drain never triggered mid-q18"
assert got == want, "drained q18 rows diverge from the oracle"
reg = get_registry().delta(before)["counters"]
assert reg.get("map_outputs_migrated", 0) > 0, reg
assert reg.get("stage_recomputes", 0) == 0, reg
h = drv.worker_by_id("w1")
assert h.retired and h.proc.poll() is not None
s.shutdown(drain=True)

SCHEMA = T.Schema([T.StructField("k", T.IntegerType(), True),
                   T.StructField("v", T.LongType(), True)])
rng = np.random.default_rng(16)
data = {"k": [int(x) for x in rng.integers(0, 997, 20000)],
        "v": [int(x) for x in rng.integers(-1000, 1000, 20000)]}
s0 = TpuSession()
want = sorted(s0.from_pydict(data, SCHEMA, partitions=6,
                             rows_per_batch=512)
              .group_by("k").agg(Sum(col("v")).alias("sv")).collect())
s0.shutdown()

# 2) straggler storm: a 2s hold on one worker's fragment must be beaten
# by a speculative duplicate, rows committed exactly once
s = TpuSession({
    "spark.rapids.cluster.mode": "local[2]",
    "spark.rapids.cluster.speculation.enabled": "true",
    "spark.rapids.cluster.speculation.multiplier": "2.0",
    "spark.rapids.cluster.speculation.minRuntimeSeconds": "0.2",
    "spark.rapids.test.faults":
        "cluster.worker.slow:slow,seconds=2.0,worker=w1,times=1"})
df = s.from_pydict(data, SCHEMA, partitions=6, rows_per_batch=512)
q = df.group_by("k").agg(Sum(col("v")).alias("sv"))
assert sorted(q.collect()) == want  # warm-up seeds the wall median
before = get_registry().snapshot()
assert sorted(q.collect()) == want, "speculated rows diverge"
reg = get_registry().delta(before)["counters"]
assert reg.get("speculative_launched", 0) >= 1, reg
assert reg.get("speculative_wasted", 0) >= 1, reg
assert reg.get("stage_recomputes", 0) == 0, reg
s.shutdown(drain=True)

# 3) flaky worker: quarantined after 2 consecutive failures, old map
# outputs stay fetchable, probation re-admits, zero orphans
s = TpuSession({
    "spark.rapids.cluster.mode": "local[2]",
    "spark.rapids.cluster.quarantine.maxFailures": "2",
    "spark.rapids.cluster.quarantine.probationSeconds": "4.0",
    "spark.rapids.cluster.heartbeat.intervalSeconds": "0.2",
    "spark.rapids.test.faults":
        "cluster.worker.flaky:flaky,worker=w1,times=2"})
df = s.from_pydict(data, SCHEMA, partitions=6, rows_per_batch=512)
q = df.group_by("k").agg(Sum(col("v")).alias("sv"))
before = get_registry().snapshot()
assert sorted(q.collect()) == want, "flaky-worker rows diverge"
reg = get_registry().delta(before)["counters"]
assert reg.get("cluster_workers_quarantined", 0) == 1, reg
drv = s._cluster()
h = drv.worker_by_id("w1")
assert h.alive and h.state == "quarantined"
deadline = time.monotonic() + 10.0
while time.monotonic() < deadline and \
        drv.worker_by_id("w1").quarantined_until is not None:
    time.sleep(0.1)
assert drv.worker_by_id("w1").quarantined_until is None, \
    "probation never re-admitted the quarantined worker"
reg = get_registry().delta(before)["counters"]
assert reg.get("cluster_workers_readmitted", 0) == 1, reg
handles = drv.workers()
s.shutdown(drain=True)
for h in handles:
    assert h.proc.poll() is not None, \
        f"orphan worker {h.worker_id} after elasticity gate"
print("elasticity gate: mid-q18 drain 0-recompute, speculation "
      "exactly-once, quarantine+re-admission: ok")
PY
  echo "-- telemetry gate: live /metrics mid-query, cluster trace, disabled-path imports --"
  # ISSUE 15 observability plane: the HTTP endpoint must serve
  # well-formed Prometheus (with at least one latency histogram) WHILE
  # queries run; a local[2] q3 must yield ONE Perfetto trace carrying
  # spans from BOTH worker pids on named lanes; and with the confs at
  # their defaults neither obs/http.py nor obs/history.py may be
  # imported and no telemetry socket may exist — the disabled path is
  # zero-overhead by construction
  JAX_PLATFORMS=cpu python - <<'PY'
import glob, json, os, re, socket, sys, tempfile, threading, urllib.request

from spark_rapids_tpu.bench.tpch_gen import generate_tpch
from spark_rapids_tpu.bench.tpch_queries import build_tpch_query
from spark_rapids_tpu.session import TpuSession

d = os.path.join(tempfile.mkdtemp(), "tpch")
generate_tpch(d, sf=0.01)

# 1) live endpoint mid-query: q6 looping in a worker thread, scraped
# concurrently — every sample line must parse, the query-latency
# histogram must be present with cumulative buckets
with socket.socket() as s:
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
sess = TpuSession({"spark.rapids.obs.http.port": str(port)})
assert sess._http is not None and sess._http.port == port
stop = threading.Event()
errs = []

def loop_q6():
    try:
        while not stop.is_set():
            build_tpch_query("q6", sess, d).collect()
    except Exception as e:  # surfaced below; thread must not die silent
        errs.append(repr(e))

t = threading.Thread(target=loop_q6, daemon=True)
t.start()
try:
    build_tpch_query("q6", sess, d).collect()   # ensure >= 1 completion
    scraped = None
    for _ in range(5):
        with urllib.request.urlopen(
                f"http://127.0.0.1:{port}/metrics", timeout=10) as r:
            assert r.status == 200, r.status
            assert r.headers["Content-Type"].startswith("text/plain")
            scraped = r.read().decode()
    sample = re.compile(r'^[a-zA-Z_][a-zA-Z0-9_]*(\{[^}]*\})? '
                        r'[-+0-9.einfa]+$')
    for ln in scraped.splitlines():
        if ln and not ln.startswith("#"):
            assert sample.match(ln), f"malformed sample line: {ln!r}"
    assert "# TYPE srt_query_wall_seconds histogram" in scraped, scraped
    buckets = [float(ln.rsplit(" ", 1)[1]) for ln in scraped.splitlines()
               if ln.startswith("srt_query_wall_seconds_bucket{")]
    assert buckets and buckets == sorted(buckets) and buckets[-1] >= 1
    with urllib.request.urlopen(
            f"http://127.0.0.1:{port}/healthz", timeout=10) as r:
        assert json.loads(r.read())["status"] == "ok"
finally:
    stop.set()
    t.join(timeout=60)
    sess.shutdown()
assert not errs, errs
assert sess._http is None, "endpoint must be torn down by shutdown()"
print("telemetry gate 1: mid-query /metrics scrape well-formed, "
      f"{len(buckets)} histogram buckets: ok")

# 2) local[2] q3: ONE merged trace with driver + both worker pids.
# Multi-part tables so the planner inserts real exchanges for the
# cluster to shard — single-part scans would keep q3 driver-local.
import pyarrow.parquet as pq
for table in ("lineitem", "orders", "customer"):
    t = pq.read_table(os.path.join(d, table, "part-0.parquet"))
    step = -(-t.num_rows // 4)
    for i in range(4):
        pq.write_table(t.slice(i * step, step),
                       os.path.join(d, table, f"part-{i}.parquet"))
tdir = tempfile.mkdtemp()
sess = TpuSession({"spark.rapids.cluster.mode": "local[2]",
                   "spark.rapids.obs.trace.enabled": "true",
                   "spark.rapids.obs.trace.dir": tdir})
try:
    worker_pids = {h.pid for h in sess._cluster().workers()}
    build_tpch_query("q3", sess, d).collect()
finally:
    sess.shutdown()
traces = glob.glob(os.path.join(tdir, "trace_*.json"))
assert len(traces) == 1, f"want ONE merged trace, got {traces}"
doc = json.load(open(traces[0]))
lanes = {ev["pid"]: ev["args"]["name"] for ev in doc["traceEvents"]
         if ev.get("ph") == "M" and ev["name"] == "process_name"}
span_pids = {ev["pid"] for ev in doc["traceEvents"]
             if ev.get("ph") == "X"}
assert worker_pids <= span_pids, (worker_pids, span_pids)
assert worker_pids <= set(lanes), (worker_pids, lanes)
assert os.getpid() in span_pids and lanes.get(os.getpid()) == "driver"
print(f"telemetry gate 2: one trace, lanes {sorted(lanes.values())}, "
      f"spans from {len(span_pids)} pids: ok")

# 3) disabled path: defaults leave the telemetry modules unimported
# (checked in a pristine interpreter — this one imported them above)
import subprocess
code = """
import sys
from spark_rapids_tpu.session import TpuSession
from spark_rapids_tpu.bench.tpch_queries import build_tpch_query
sess = TpuSession({})
build_tpch_query("q6", sess, %r).collect()
sess.shutdown()
assert sess._http is None
bad = [m for m in sys.modules
       if m in ("spark_rapids_tpu.obs.http", "spark_rapids_tpu.obs.history")]
assert not bad, f"telemetry modules imported on disabled path: {bad}"
print("disabled path clean")
""" % d
r = subprocess.run([sys.executable, "-c", code], capture_output=True,
                   text=True, timeout=600,
                   env=dict(os.environ, JAX_PLATFORMS="cpu"))
assert r.returncode == 0, r.stdout + r.stderr
print("telemetry gate 3: port-off default imports nothing, no socket: ok")
PY
  echo "-- cost-attribution gate: profiled q3@mesh-8, conservation, <3% overhead, disabled-path inert --"
  # ISSUE 19 cost-attribution plane, four contracts: (1) a profiled
  # q3@mesh-8 exports a schema-valid profile artifact whose mesh-region
  # time is attributed to member ops, with flamegraph text and ph="C"
  # counter tracks merged into the Perfetto trace; (2) on a serial
  # profiled session the per-tenant charges conserve against the
  # independently-accumulated process totals (within 5%); (3) warm q6
  # with profiling on stays within 3% of unprofiled wall; (4) with the
  # conf at its default neither obs.profile nor obs.metering is ever
  # imported
  JAX_PLATFORMS=cpu XLA_FLAGS="--xla_force_host_platform_device_count=8" \
    python - <<'PY'
import glob, json, os, sys, tempfile
sys.path.insert(0, "scripts")
from validate_obs import validate, load_schema
from spark_rapids_tpu.bench.runner import run_benchmark
from spark_rapids_tpu.bench.tpch_gen import generate_tpch

d = tempfile.mkdtemp()
data = os.path.join(d, "tpch")
generate_tpch(data, sf=0.01)
pdir, tdir = os.path.join(d, "profiles"), os.path.join(d, "traces")
r = run_benchmark(data, 0.01, ["q3"], generate=False, suite="tpch",
                  session_conf={
                      "spark.rapids.tpu.mesh.deviceCount": "8",
                      "spark.rapids.obs.profile.enabled": "true",
                      "spark.rapids.obs.profile.dir": pdir,
                      "spark.rapids.obs.trace.enabled": "true",
                      "spark.rapids.obs.trace.dir": tdir})[0]
assert r.get("ok") and "error" not in r, r
prof = r["observability"]["profile"]
errs = validate(prof, load_schema("profile"))
assert not errs, errs[:5]
exported = glob.glob(os.path.join(pdir, "profile_*.json"))
assert exported, "no profile artifact exported"
for p in exported:
    errs = validate(json.load(open(p)), load_schema("profile"))
    assert not errs, (p, errs[:5])
ops = prof["operators"]
members = {k: e for k, e in ops.items() if e["parent"]}
assert members, f"no member-attributed rows on mesh-8 q3: {sorted(ops)}"
shares: dict = {}
for e in members.values():
    shares[e["parent"]] = shares.get(e["parent"], 0.0) + e["device_s"]
for par, s in shares.items():
    assert s <= ops[par]["device_s"] + 1e-6, \
        f"members of {par} exceed their container: {s} > {ops[par]}"
assert prof["flamegraph"].strip(), "empty flamegraph"
flame = glob.glob(os.path.join(pdir, "flamegraph_*.txt"))
assert flame and open(flame[0]).read().strip()
traces = glob.glob(os.path.join(tdir, "trace_*.json"))
assert traces, "no trace exported alongside the profile"
doc = json.load(open(traces[0]))
errs = validate(doc, load_schema("trace"))
assert not errs, errs[:5]
counters = [ev for ev in doc["traceEvents"] if ev.get("ph") == "C"]
assert any(ev["name"] == "operator.device_seconds" for ev in counters), \
    f"no operator counter track among {len(counters)} C events"
print(f"cost gate 1: q3@mesh-8 profile schema-valid, "
      f"{len(members)} member rows, {len(counters)} counter samples: ok")
PY
  JAX_PLATFORMS=cpu python - <<'PY'
import os, tempfile, time

from spark_rapids_tpu.bench.tpch_gen import generate_tpch
from spark_rapids_tpu.bench.tpch_queries import build_tpch_query
from spark_rapids_tpu.session import TpuSession

d = os.path.join(tempfile.mkdtemp(), "tpch")
generate_tpch(d, sf=0.01)
NOCACHE = {"spark.rapids.sql.resultCache.enabled": "false"}

# 2) conservation: EVERY profiled query in this process goes through
# the session charge path, so tenant sums must meet the independent
# instrumentation totals within 5%
s_on = TpuSession(dict(NOCACHE,
                       **{"spark.rapids.obs.profile.enabled": "true"}))
for tenant, q in (("etl", "q3"), ("web", "q6"), ("etl", "q6"),
                  ("web", "q3")):
    build_tpch_query(q, s_on, d).collect(tenant=tenant)
from spark_rapids_tpu.obs.metering import get_meter
cons = get_meter().conservation()
assert cons["ok"], f"conservation failed: {cons}"
snap = get_meter().snapshot()
assert set(snap["tenants"]) == {"etl", "web"}, snap["tenants"]
assert snap["tenants"]["etl"]["queries"] == 2, snap["tenants"]["etl"]
print(f"cost gate 2: conservation within 5% "
      f"(device_s tenants={cons['device_seconds']['tenants_sum']:.4f} "
      f"total={cons['device_seconds']['total']:.4f}): ok")

# 3) warm q6 overhead < 3%: medians over interleaved samples so host
# drift cancels; a noisy CI host gets bounded retries — a real hot-path
# regression fails every attempt
s_off = TpuSession(dict(NOCACHE))
df_on = build_tpch_query("q6", s_on, d)
df_off = build_tpch_query("q6", s_off, d)
for _ in range(5):  # warm compile/fusion caches on both paths
    df_on.collect(tenant="warm")
    df_off.collect()
ratio = None
for attempt in (1, 2, 3):
    ts_on, ts_off = [], []
    for _ in range(40):
        t0 = time.perf_counter()
        df_off.collect()
        ts_off.append(time.perf_counter() - t0)
        t0 = time.perf_counter()
        df_on.collect(tenant="warm")
        ts_on.append(time.perf_counter() - t0)
    ts_on.sort(); ts_off.sort()
    med_on, med_off = ts_on[len(ts_on) // 2], ts_off[len(ts_off) // 2]
    ratio = med_on / med_off
    print(f"  attempt {attempt}: profiled={med_on * 1e3:.2f}ms "
          f"unprofiled={med_off * 1e3:.2f}ms ({(ratio - 1) * 100:+.2f}%)")
    if ratio < 1.03:
        break
assert ratio < 1.03, \
    f"profiling adds {(ratio - 1) * 100:.2f}% to warm q6 (budget: 3%)"
s_on.shutdown(); s_off.shutdown()
print(f"cost gate 3: warm q6 overhead {(ratio - 1) * 100:+.2f}% (< 3%): ok")
PY
  # 4) disabled path: the default leaves the profiler modules unimported
  # (pristine interpreter — this shell already imported them above)
  JAX_PLATFORMS=cpu python - <<'PY'
import os, subprocess, sys, tempfile
from spark_rapids_tpu.bench.tpch_gen import generate_tpch
d = os.path.join(tempfile.mkdtemp(), "tpch")
generate_tpch(d, sf=0.01)
code = """
import sys
from spark_rapids_tpu.session import TpuSession
from spark_rapids_tpu.bench.tpch_queries import build_tpch_query
sess = TpuSession({})
build_tpch_query("q6", sess, %r).collect()
sess.shutdown()
bad = [m for m in sys.modules
       if m in ("spark_rapids_tpu.obs.profile",
                "spark_rapids_tpu.obs.metering")]
assert bad == [], f"profiler modules imported on disabled path: {bad}"
import threading
assert not [t.name for t in threading.enumerate()
            if t.name == "obs-hbm-sampler"], "sampler thread while disabled"
print("disabled path clean")
"""
r = subprocess.run([sys.executable, "-c", code % d], capture_output=True,
                   text=True, timeout=600,
                   env=dict(os.environ, JAX_PLATFORMS="cpu"))
assert r.returncode == 0, r.stdout + r.stderr
print("cost gate 4: profile-off default imports nothing: ok")
PY
  echo "-- transactional write gate: CTAS exact under fault storm, no stray staging --"
  # q6-shaped CTAS (lineitem under q6's filter, hive-partitioned) must
  # produce the SAME read-back row hash across a clean run, an
  # io.write.* fault storm, a cluster worker-death run, and a
  # speculation-duplicate run — with every visible file listed in
  # _MANIFEST.json and zero staging leftovers.  (The mid-write drain
  # variant needs a monkeypatch hook and rides the unit suite:
  # tests/test_write_chaos.py::test_drain_during_write_fences_and_completes.)
  JAX_PLATFORMS=cpu python - <<'PY'
import datetime, glob, hashlib, json, os, tempfile

from spark_rapids_tpu.bench.tpch_gen import generate_tpch
from spark_rapids_tpu.expr.core import col, lit
from spark_rapids_tpu.obs.registry import get_registry
from spark_rapids_tpu.session import TpuSession

d = os.path.join(tempfile.mkdtemp(), "tpch")
generate_tpch(d, sf=0.01)

# split lineitem into 4 part files so the write job has multiple tasks
# and the cluster runs actually spread fragments over both workers
import pyarrow.parquet as pq
_t = pq.read_table(os.path.join(d, "lineitem", "part-0.parquet"))
_step = -(-_t.num_rows // 4)
for _i in range(4):
    pq.write_table(_t.slice(_i * _step, _step),
                   os.path.join(d, "lineitem", f"part-{_i}.parquet"))


def ctas(conf, out):
    sess = TpuSession(conf)
    try:
        li = sess.read_parquet(
            os.path.join(d, "lineitem"),
            columns=["l_returnflag", "l_extendedprice", "l_discount",
                     "l_shipdate", "l_quantity"])
        q6ish = li.where(
            (col("l_shipdate") >= lit(datetime.date(1994, 1, 1)))
            & (col("l_shipdate") < lit(datetime.date(1995, 1, 1)))
            & (col("l_discount") >= lit(0.05))
            & (col("l_discount") <= lit(0.07))
            & (col("l_quantity") < lit(24.0)))
        stats = q6ish.write_parquet(out, partition_by=["l_returnflag"])
        return stats
    finally:
        if hasattr(sess, "shutdown"):
            sess.shutdown()


def row_hash(out):
    import pyarrow.dataset as ds
    t = ds.dataset(out, format="parquet", partitioning="hive").to_table()
    t = t.select(sorted(t.column_names))
    rows = sorted(zip(*(t.column(n).to_pylist()
                        for n in t.column_names)), key=str)
    h = hashlib.sha256()
    for r in rows:
        h.update(repr(r).encode())
    return h.hexdigest()


def check_committed(out):
    man = json.load(open(os.path.join(out, "_MANIFEST.json")))
    committed = {os.path.normpath(e["rel"]) for e in man["files"]}
    visible = set()
    for root, dirs, files in os.walk(out):
        dirs[:] = [x for x in dirs if not x.startswith(("_", "."))]
        for fn in files:
            if not fn.startswith(("_", ".")):
                visible.add(os.path.normpath(os.path.relpath(
                    os.path.join(root, fn), out)))
    assert visible == committed, (visible ^ committed)
    assert not os.path.exists(os.path.join(out, "_staging"))


base = tempfile.mkdtemp()
clean = os.path.join(base, "clean")
ctas({}, clean)
want = row_hash(clean)
check_committed(clean)

STORMS = {
    "faultstorm": {"spark.rapids.test.faults":
                   "io.write.partial:crash,times=2;"
                   "io.write.commit.drop:drop,times=1;"
                   "io.write.rename.fail:fail,times=1"},
    "workerdeath": {"spark.rapids.cluster.mode": "local[2]",
                    "spark.rapids.test.faults":
                    "cluster.worker.dead:dead,worker=w1,"
                    "seconds=0.02,times=1"},
    "speculation": {"spark.rapids.cluster.mode": "local[2]",
                    "spark.rapids.cluster.speculation.enabled": "true",
                    "spark.rapids.cluster.speculation.multiplier": "2.0",
                    "spark.rapids.cluster.speculation."
                    "minRuntimeSeconds": "0.2",
                    "spark.rapids.test.faults":
                    "cluster.worker.slow:slow,seconds=2.0,"
                    "worker=w1,times=1"},
}
for name, conf in STORMS.items():
    out = os.path.join(base, name)
    before = get_registry().snapshot()
    ctas(conf, out)
    delta = get_registry().delta(before)["counters"]
    injected = sum(v for k, v in delta.items()
                   if k.startswith("faults.injected."))
    assert injected > 0, f"{name}: storm never fired: {delta}"
    assert row_hash(out) == want, f"{name}: read-back hash diverged"
    check_committed(out)
    print(f"write gate [{name}]: exact hash, {injected} faults injected, "
          f"no orphans: ok")
print("transactional write gate: ok")
PY
  echo "-- self-driving control gate: off-path inert, storm shed targeted --"
  # two halves.  OFF: spark.rapids.control.enabled=false must be
  # byte-identical to the static engine — same plans, same confs after
  # a run, and the control package never even imports.  ON: a reduced
  # mixed-tenant storm (single-worker grid) where every fixed config
  # misses a served tenant's SLO that the closed loop meets, shedding
  # ONLY the storm tenant.
  JAX_PLATFORMS=cpu python - <<'PY'
import sys

from spark_rapids_tpu.bench.tpch_gen import generate_tpch
from spark_rapids_tpu.bench.tpch_queries import build_tpch_query
from spark_rapids_tpu.session import TpuSession

import os, tempfile, threading
d = os.path.join(tempfile.mkdtemp(), "tpch")
generate_tpch(d, sf=0.01)

# -- OFF: the disabled path is the static engine, byte for byte ------
assert "spark_rapids_tpu.control" not in sys.modules, \
    "control package imported before any session asked for it"
def run_off(conf):
    s = TpuSession(conf)
    try:
        df = build_tpch_query("q3", s, d)
        plan = df.explain()
        rows = df.collect(tenant="gate")
        return plan, rows, dict(s.conf.settings)
    finally:
        s.shutdown()
static = run_off({})
disabled = run_off({"spark.rapids.control.enabled": "false"})
assert static[0] == disabled[0], "explain drifted with control disabled"
assert static[1] == disabled[1], "rows drifted with control disabled"
assert disabled[2] == {"spark.rapids.control.enabled": "false"}, \
    f"disabled control mutated session confs: {disabled[2]}"
assert "spark_rapids_tpu.control" not in sys.modules, \
    "control package imported on the DISABLED path"
assert not [t.name for t in threading.enumerate()
            if t.name == "control-loop"], "control thread on disabled path"
print("control gate [off]: plans, rows, imports identical: ok")

# -- ON: reduced storm; the loop must beat every fixed rung ----------
# one retry: the storm scores wall-clock p99s, and a noisy CI host
# can push a served tenant a few percent over its margin — a real
# control-plane regression fails BOTH attempts
from spark_rapids_tpu.bench.storm import run_storm
for attempt in (1, 2):
    rep = run_storm(d, 0.01, grid=((2, 1), (8, 1)), duration_s=4.0,
                    generate=False)
    if rep["ok"]:
        break
    print(f"control gate [storm]: attempt {attempt} failed: "
          f"{rep.get('error')}")
assert rep["ok"], f"storm gate failed: {rep.get('error')}"
assert rep["all_fixed_missed"] and rep["storm_tenant_shed"] \
    and rep["served_tenants_clean"]
cl = rep["closed"]
assert not cl["missed"], f"closed loop missed {cl['missed']}"
shed = [t for t, i in cl["tenants"].items() if i["shed"]]
assert shed == ["batch"], f"shed set {shed} != ['batch']"
# the controller's thread dies with its session
assert not [t.name for t in threading.enumerate()
            if t.name == "control-loop" and t.is_alive()], \
    "control-loop thread leaked past shutdown"
print(f"control gate [storm]: fixed grid missed everywhere, closed "
      f"loop margin {rep['closed_slo_margin']}x, only batch shed: ok")
PY
  echo "-- driver failover gate: mid-q18 SIGKILL -> journal recovery, write roll-forward, off-path inert --"
  # three halves.  CRASH: a real driver process is SIGKILLed on its
  # first reduce-side fetch of q18; recovery from the write-ahead
  # journal must re-attach BOTH lingering workers and re-serve the
  # exact rows with zero recompute of journaled map outputs.  WRITE:
  # a SIGKILL mid-commit rolls FORWARD to exactly one _SUCCESS and no
  # _staging residue.  OFF: journal disabled is byte-identical plans,
  # zero journal I/O, and cluster/journal.py never imports.
  JAX_PLATFORMS=cpu python - <<'PY'
import json, os, signal, subprocess, sys, tempfile

import pyarrow.parquet as pq

from spark_rapids_tpu.bench.tpch_gen import generate_tpch
from spark_rapids_tpu.bench.tpch_queries import build_tpch_query
from spark_rapids_tpu.session import TpuSession

base = tempfile.mkdtemp(prefix="tpu-failover-gate-")
d = os.path.join(base, "tpch")
generate_tpch(d, sf=0.01)
# multi-partition scans so the planner inserts REAL shuffle exchanges
# (single-partition q18 never touches the cluster shuffle plane)
for table in ("lineitem", "orders", "customer"):
    t = pq.read_table(os.path.join(d, table, "part-0.parquet"))
    step = -(-t.num_rows // 4)
    for i in range(4):
        pq.write_table(t.slice(i * step, step),
                       os.path.join(d, table, f"part-{i}.parquet"))

s = TpuSession()
want = sorted(map(tuple, build_tpch_query("q18", s, d).collect()))
s.shutdown()
assert "spark_rapids_tpu.cluster.journal" not in sys.modules, \
    "cluster/journal.py imported in single-process mode"

DRIVER = r'''
import json, sys
from spark_rapids_tpu.bench.tpch_queries import build_tpch_query
from spark_rapids_tpu.session import TpuSession
conf = json.loads(sys.argv[1]); d = sys.argv[2]; mode = sys.argv[3]
s = TpuSession(conf)
df = build_tpch_query("q18", s, d)
if mode == "write":
    df.write_parquet(sys.argv[4])
else:
    df.collect()
s.shutdown()
print("CLEAN_EXIT", flush=True)
'''

def run_driver(conf, *extra):
    # stderr to a FILE: the workers inherit the driver's stderr, and a
    # captured pipe would block this gate for the whole linger window
    with tempfile.TemporaryFile(mode="w+") as ef:
        p = subprocess.run([sys.executable, "-c", DRIVER,
                            json.dumps(conf), d, *extra],
                           stdout=subprocess.PIPE, stderr=ef,
                           text=True, timeout=240)
        ef.seek(0)
        p.stderr = ef.read()
    return p

def worker_pids(jdir):
    from spark_rapids_tpu.cluster.journal import ClusterJournal
    st = ClusterJournal.replay(jdir)
    return [w["pid"] for w in st.workers.values() if w.get("pid")]

def kill_stragglers(pids):
    for pid in pids:
        try:
            os.kill(pid, signal.SIGKILL)
        except (ProcessLookupError, PermissionError):
            pass

jdir = os.path.join(base, "journal")
conf = {"spark.rapids.cluster.mode": "local[2]",
        "spark.rapids.cluster.journal.dir": jdir,
        "spark.rapids.cluster.driver.reattachGraceSeconds": "90"}

# -- 1) SIGKILL mid-q18, recover, exact rows, zero recompute ---------
crashed = run_driver({**conf, "spark.rapids.test.faults":
                      "cluster.driver.crash:kill,point=shuffle_read"},
                     "collect")
assert crashed.returncode == -signal.SIGKILL, \
    f"driver survived: rc={crashed.returncode} {crashed.stderr[-2000:]}"
assert "CLEAN_EXIT" not in crashed.stdout
from spark_rapids_tpu.cluster.driver import ClusterDriver
from spark_rapids_tpu.conf import TpuConf
from spark_rapids_tpu.obs.registry import get_registry
pids = worker_pids(jdir)
try:
    driver = ClusterDriver.recover(TpuConf(conf), jdir)
    info = dict(driver.recovery_info)
    assert info["workers_reattached"] == 2, info
    s = TpuSession(conf).attach_cluster(driver)
    before = get_registry().snapshot()
    got = sorted(map(tuple, build_tpch_query("q18", s, d).collect()))
    delta = get_registry().delta(before)["counters"]
    s.shutdown()
    assert got == want, "recovered q18 rows diverged from oracle"
    assert delta.get("map_outputs_recomputed", 0) == 0, delta
finally:
    kill_stragglers(pids)
print("failover gate 1: mid-q18 SIGKILL -> 2 reattached, exact rows, "
      "0 journaled outputs recomputed: ok")

# -- 2) SIGKILL mid-write-commit rolls FORWARD -----------------------
jdir2 = os.path.join(base, "journal2")
out = os.path.join(base, "out")
conf2 = {**conf, "spark.rapids.cluster.journal.dir": jdir2}
crashed = run_driver({**conf2, "spark.rapids.test.faults":
                      "cluster.driver.crash:kill,point=write.commit"},
                     "write", out)
assert crashed.returncode == -signal.SIGKILL, crashed.stderr[-2000:]
assert not os.path.exists(os.path.join(out, "_SUCCESS"))
pids = worker_pids(jdir2)
try:
    drv = ClusterDriver.recover(TpuConf(conf2), jdir2)
    info2 = dict(drv.recovery_info)
    drv.shutdown()
    assert info2["write_rollforward"] == 1, info2
    assert info2["write_rollback"] == 0, info2
    names = os.listdir(out)
    assert names.count("_SUCCESS") == 1, names
    assert "_staging" not in names, names
finally:
    kill_stragglers(pids)
print("failover gate 2: mid-commit SIGKILL -> rolled forward, one "
      "_SUCCESS, no _staging residue: ok")
PY
  # -- 3) journal disabled: identical plans, zero journal I/O --------
  # fresh interpreter so sys.modules proves the DISABLED path never
  # imports cluster/journal.py even in cluster mode
  JAX_PLATFORMS=cpu python - <<'PY'
import os, sys, tempfile

import pyarrow.parquet as pq

from spark_rapids_tpu.bench.tpch_gen import generate_tpch
from spark_rapids_tpu.bench.tpch_queries import build_tpch_query
from spark_rapids_tpu.session import TpuSession

base = tempfile.mkdtemp(prefix="tpu-failover-off-")
d = os.path.join(base, "tpch")
generate_tpch(d, sf=0.01)
for table in ("lineitem", "orders", "customer"):
    t = pq.read_table(os.path.join(d, table, "part-0.parquet"))
    step = -(-t.num_rows // 4)
    for i in range(4):
        pq.write_table(t.slice(i * step, step),
                       os.path.join(d, table, f"part-{i}.parquet"))
jdir = os.path.join(base, "never-touched")

off = {"spark.rapids.cluster.mode": "local[2]",
       "spark.rapids.cluster.journal.enabled": "false",
       "spark.rapids.cluster.journal.dir": jdir}
s = TpuSession(off)
plan_off = build_tpch_query("q18", s, d).explain()
s.shutdown()
assert "spark_rapids_tpu.cluster.journal" not in sys.modules, \
    "journal module imported with journaling DISABLED"
assert not os.path.exists(jdir), "disabled journal still did I/O"

on = {"spark.rapids.cluster.mode": "local[2]",
      "spark.rapids.cluster.journal.dir": os.path.join(base, "j")}
s = TpuSession(on)
plan_on = build_tpch_query("q18", s, d).explain()
s.shutdown()
assert plan_off == plan_on, "journal changed the plan"
print("failover gate 3: journal-off plans byte-identical, zero "
      "journal I/O, module never imported: ok")
PY
  echo "-- multichip dryrun (8 virtual devices) --"
  JAX_PLATFORMS=cpu XLA_FLAGS="--xla_force_host_platform_device_count=8" \
    python -c "import __graft_entry__ as g; g.dryrun_multichip(8); print('dryrun ok')"
  echo "== premerge PASS =="
} 2>&1 | tee "$OUT"

# Machine-feature-mismatch gate (VERDICT r4 weak #5): a cpu_aot_loader
# complaint means a stale/foreign AOT executable was loaded — a SIGILL
# from one would be indistinguishable from a wedged tunnel in CI.
if grep -q "cpu_aot_loader" "$OUT"; then
  echo "== premerge FAIL: cpu_aot_loader machine-feature warnings in log =="
  exit 1
fi

#!/bin/bash
# On-chip tier: the only tier that talks to real TPU hardware.
#
# Reference model: the integration Jenkinsfiles run spark-tests.sh on
# GPU runners; CPU-only CI cannot catch device-lowering failures, and
# neither can this repo's JAX_PLATFORMS=cpu test suite.  Here:
#   * probe the accelerator tunnel under a hard timeout FIRST — the
#     axon client hangs forever when the loopback relay is wedged, and
#     a wedged tunnel must fail this tier fast instead of eating it
#     (round-3 failure mode),
#   * scripts/verify_exprs_tpu.py: the whole expression library on the
#     chip vs the host oracle,
#   * bench.py: the TPC-DS q6 ladder on the chip (one JSON line).
#
# Usage: ci/chip.sh  (writes artifacts/ci_chip_<utc-date>.txt)
set -euo pipefail
cd "$(dirname "$0")/.."

STAMP=$(date -u +%Y%m%dT%H%M%SZ)
OUT="artifacts/ci_chip_${STAMP}.txt"
mkdir -p artifacts

{
  echo "== chip @ ${STAMP} (commit $(git rev-parse --short HEAD)) =="
  echo "-- tunnel probe (120s budget) --"
  if ! timeout 130 python -c "
import faulthandler
faulthandler.dump_traceback_later(120, exit=True)
import jax
assert jax.default_backend() == 'tpu', jax.default_backend()
print('tpu up:', jax.devices())
"; then
    echo "== chip SKIP: accelerator tunnel is wedged (probe timed out) =="
    exit 2
  fi
  echo "-- expression library on chip vs host oracle --"
  python scripts/verify_exprs_tpu.py
  echo "-- bench ladder on chip --"
  python bench.py
  echo "== chip PASS =="
} 2>&1 | tee "$OUT"

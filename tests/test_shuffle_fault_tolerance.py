"""Fault-tolerant shuffle plane: seeded chaos through the REAL transport.

Drives spark_rapids_tpu/faults.py injection points end to end: resets,
stalls, corrupted frames, server error frames, store failures, and
simulated HBM OOM — all deterministic (seeded, conf-driven), all on CPU,
no mocks.  Reference intent: the UCX client survives transport failures
by surfacing them to stage retry (RapidsShuffleIterator); here the
transport-level retry ladder (shuffle/retry.py) must return EXACTLY the
oracle batches — no duplicates, no drops, no hang — under every fault.
"""
import socket
import threading
import time

import numpy as np
import pytest

from spark_rapids_tpu import types as T
from spark_rapids_tpu.conf import TpuConf
from spark_rapids_tpu.exec.core import ExecCtx, device_to_host, host_to_device
from spark_rapids_tpu.faults import FaultRegistry
from spark_rapids_tpu.host.batch import HostBatch, HostColumn
from spark_rapids_tpu.shuffle.retry import (fetch_remote_with_retry,
                                            remote_partition_sizes_with_retry,
                                            reset_circuit_breakers)
from spark_rapids_tpu.shuffle.tcp import (ShuffleFetchError,
                                          ShuffleTransportError,
                                          TcpShuffleServer,
                                          TcpShuffleTransport, fetch_remote,
                                          remote_partition_sizes)

SCHEMA = T.Schema([T.StructField("x", T.IntegerType())])


@pytest.fixture(autouse=True)
def _fresh_breakers():
    # per-peer circuit state is process-global by design; tests must not
    # inherit failures from each other
    reset_circuit_breakers()
    yield
    reset_circuit_breakers()


def _hb(vals):
    return HostBatch([HostColumn(np.asarray(vals, np.int32),
                                 np.ones(len(vals), bool),
                                 T.IntegerType())], SCHEMA)


def _rows(batches):
    out = []
    for b in batches:
        out.extend(device_to_host(b).columns[0].to_list())
    return out


def _fill(transport, shuffle_id=1, part_id=0, n_batches=6):
    """n map batches of 2 rows each; returns the oracle row multiset."""
    oracle = []
    for m in range(n_batches):
        transport.write_partition(shuffle_id, m, part_id,
                                  host_to_device(_hb([m, m + 100])))
        oracle += [m, m + 100]
    return sorted(oracle)


def _transport(ctx, conf):
    return TcpShuffleTransport(conf, ctx)


# ---------------------------------------------------------------------------
# registry semantics
# ---------------------------------------------------------------------------

def test_fault_registry_inert_when_unset():
    """With spark.rapids.test.faults unset nothing is built: every
    injection site is one is-None check (CI asserts this too)."""
    assert FaultRegistry.from_conf(TpuConf({})) is None
    assert FaultRegistry.from_conf(None) is None
    assert FaultRegistry.from_conf({}) is None


def test_fault_registry_parse_and_triggers():
    reg = FaultRegistry("tcp.server.frame:corrupt,nth=2,times=2,part=0;"
                        "store.fetch:error", seed=7)
    # filter mismatch never consumes the trigger
    assert reg.check("tcp.server.frame", part=1, frame=0) is None
    assert reg.check("tcp.server.frame", part=0, frame=0) is None  # hit 1
    act = reg.check("tcp.server.frame", part=0, frame=1)           # hit 2
    assert act is not None and act.action == "corrupt"
    assert reg.check("tcp.server.frame", part=0, frame=2) is not None
    assert reg.check("tcp.server.frame", part=0, frame=3) is None  # spent
    assert reg.check("store.fetch", shuffle=9).action == "error"
    assert reg.fired_count() == 3
    with pytest.raises(ValueError):
        FaultRegistry("noaction")


def test_fault_registry_deterministic_seeding():
    a = FaultRegistry("tcp.server.frame:corrupt,p=0.5,times=0", seed=3)
    b = FaultRegistry("tcp.server.frame:corrupt,p=0.5,times=0", seed=3)
    fires_a = [a.check("tcp.server.frame", frame=i) is not None
               for i in range(64)]
    fires_b = [b.check("tcp.server.frame", frame=i) is not None
               for i in range(64)]
    assert fires_a == fires_b and any(fires_a) and not all(fires_a)


# ---------------------------------------------------------------------------
# wire hardening (satellites)
# ---------------------------------------------------------------------------

def test_raw_connection_errors_wrapped():
    """A dead peer surfaces as ShuffleFetchError with address context,
    never a raw ConnectionError/OSError (satellite bugfix)."""
    srv = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
    srv.bind(("127.0.0.1", 0))
    dead = srv.getsockname()
    srv.close()  # nothing listening now
    with pytest.raises(ShuffleTransportError, match=r"failed:"):
        list(fetch_remote(dead, 1, 0, timeout=2))
    with pytest.raises(ShuffleTransportError, match=r"failed:"):
        remote_partition_sizes(dead, 1, timeout=2)


def test_server_caps_request_frames():
    """A desynced peer declaring a multi-GiB *request* frame is dropped
    at the 64 KiB control-frame cap — the server neither allocates nor
    wedges, and keeps serving well-formed peers (satellite bugfix)."""
    conf = TpuConf({})
    with ExecCtx(backend="device", conf=conf) as ctx:
        t = _transport(ctx, conf)
        try:
            oracle = _fill(t)
            evil = socket.create_connection(t.address, timeout=5)
            evil.settimeout(5)
            evil.sendall((1 << 40).to_bytes(8, "big"))
            assert evil.recv(1) == b""  # server hung up, no allocation
            evil.close()
            assert sorted(_rows(fetch_remote(t.address, 1, 0))) == oracle
        finally:
            t.close()


def test_checksum_negotiation_interop():
    """Old-style clients that advertise no checksum still get the
    unprefixed frames they expect; new clients get verified frames."""
    conf = TpuConf({})
    with ExecCtx(backend="device", conf=conf) as ctx:
        t = _transport(ctx, conf)
        try:
            oracle = _fill(t)
            assert sorted(_rows(fetch_remote(t.address, 1, 0,
                                             checksum=False))) == oracle
            assert sorted(_rows(fetch_remote(t.address, 1, 0,
                                             checksum=True))) == oracle
        finally:
            t.close()


# ---------------------------------------------------------------------------
# chaos: the retrying fetch under injected faults
# ---------------------------------------------------------------------------

_FAST_RETRY = {"spark.rapids.shuffle.tcp.retryWaitSeconds": 0.02}


def test_reset_mid_stream_resumes_exactly():
    """Kill the connection mid-stream; the retrying fetch reconnects
    and RESUMES at the delivered offset: exact oracle rows AND the
    server never re-sends a delivered frame (no dup, no drop)."""
    conf = TpuConf({"spark.rapids.test.faults":
                    "tcp.server.frame:reset,nth=3", **_FAST_RETRY})
    with ExecCtx(backend="device", conf=conf) as ctx:
        t = _transport(ctx, conf)
        try:
            oracle = _fill(t, n_batches=6)
            got = _rows(fetch_remote_with_retry(t.address, 1, 0, conf=conf))
            assert sorted(got) == oracle
            assert t.server_metrics["faults_injected"] == 1
            assert t.server_metrics["fetch_requests"] == 2
            # perfect resume: 6 batches -> exactly 6 data frames total
            assert t.server_metrics["data_frames_sent"] == 6
        finally:
            t.close()


def test_corrupt_frame_detected_and_retried():
    """A bit-flipped frame fails its negotiated CRC and surfaces as a
    retryable error at the frame boundary — never a poisoned Arrow
    deserialize; the retry delivers the oracle."""
    spec = {"spark.rapids.test.faults": "tcp.server.frame:corrupt,nth=2",
            **_FAST_RETRY}
    conf = TpuConf(spec)
    with ExecCtx(backend="device", conf=conf) as ctx:
        t = _transport(ctx, conf)
        try:
            oracle = _fill(t)
            with pytest.raises(ShuffleTransportError, match="corrupted"):
                list(fetch_remote(t.address, 1, 0))
        finally:
            t.close()
    # fresh transport, same seeded plan: this time through the ladder
    conf = TpuConf(spec)
    with ExecCtx(backend="device", conf=conf) as ctx:
        t = _transport(ctx, conf)
        try:
            oracle = _fill(t)
            got = _rows(fetch_remote_with_retry(t.address, 1, 0, conf=conf))
            assert sorted(got) == oracle
            assert t.server_metrics["faults_injected"] == 1
        finally:
            t.close()


def test_stalled_peer_times_out_then_succeeds():
    """A stalled peer trips the fetch deadline (not a forever-hang);
    the retry finds it recovered and completes."""
    conf = TpuConf({"spark.rapids.test.faults":
                    "tcp.server.frame:stall,seconds=3",
                    "spark.rapids.shuffle.tcp.timeoutSeconds": 0.5,
                    **_FAST_RETRY})
    with ExecCtx(backend="device", conf=conf) as ctx:
        t = _transport(ctx, conf)
        try:
            oracle = _fill(t)
            t0 = time.monotonic()
            got = _rows(fetch_remote_with_retry(t.address, 1, 0, conf=conf))
            assert sorted(got) == oracle
            assert time.monotonic() - t0 < 30
        finally:
            t.close()


def test_server_error_frame_retried():
    """A transient server-side failure (here: injected at the store
    read) reaches the client as a diagnosable error frame and the next
    attempt succeeds."""
    conf = TpuConf({"spark.rapids.test.faults": "store.fetch:error",
                    **_FAST_RETRY})
    with ExecCtx(backend="device", conf=conf) as ctx:
        t = _transport(ctx, conf)
        try:
            oracle = _fill(t)
            got = _rows(fetch_remote_with_retry(t.address, 1, 0, conf=conf))
            assert sorted(got) == oracle
            assert t.faults.fired_count("store.fetch") == 1
        finally:
            t.close()


def test_deterministic_chaos_plan_exact_oracle():
    """Acceptance: one seeded plan that resets the connection
    mid-stream AND corrupts a later frame; the retrying pull returns
    exactly the oracle batches — no dup, no drop, no hang."""
    conf = TpuConf({"spark.rapids.test.faults":
                    "tcp.server.frame:reset,nth=3,times=1;"
                    "tcp.server.frame:corrupt,nth=6,times=1",
                    "spark.rapids.test.faults.seed": 42, **_FAST_RETRY})
    with ExecCtx(backend="device", conf=conf) as ctx:
        t = _transport(ctx, conf)
        try:
            oracle = _fill(t, n_batches=6)
            t0 = time.monotonic()
            got = _rows(fetch_remote_with_retry(t.address, 1, 0, conf=conf))
            assert sorted(got) == oracle          # exact multiset
            assert len(got) == len(oracle)        # no dups slipped in
            assert t.faults.fired_count() == 2
            assert t.server_metrics["fetch_requests"] == 3
            assert time.monotonic() - t0 < 30
        finally:
            t.close()


def test_no_faults_no_extra_round_trips():
    """Acceptance: with faults disabled the retry layer is pass-through
    — one fetch request, one data frame per batch, nothing re-sent."""
    conf = TpuConf({})
    with ExecCtx(backend="device", conf=conf) as ctx:
        t = _transport(ctx, conf)
        try:
            oracle = _fill(t, n_batches=5)
            assert t.faults is None
            got = _rows(fetch_remote_with_retry(t.address, 1, 0, conf=conf))
            assert sorted(got) == oracle
            assert t.server_metrics == {"meta_requests": 0,
                                        "fetch_requests": 1,
                                        "data_frames_sent": 5,
                                        "bytes_sent":
                                            t.server_metrics["bytes_sent"],
                                        "faults_injected": 0,
                                        "traced_fetches": 0}
        finally:
            t.close()


def test_peer_restart_fetch_recovers():
    """The peer dies and comes back on the same port while the client
    backs off; the retrying fetch and metadata plane both recover."""
    conf = TpuConf({"spark.rapids.shuffle.tcp.retryWaitSeconds": 0.3,
                    "spark.rapids.shuffle.tcp.maxRetries": 6})
    with ExecCtx(backend="device", conf=conf) as ctx:
        t = _transport(ctx, conf)
        replacement = []
        try:
            oracle = _fill(t)
            host, port = t.address
            t._server.close()  # peer dies; its map output store survives

            def revive():
                time.sleep(0.6)
                replacement.append(TcpShuffleServer(t, bind=host, port=port))

            threading.Thread(target=revive, daemon=True).start()
            sizes, _ = remote_partition_sizes_with_retry(
                (host, port), 1, conf=conf)
            assert set(sizes) == {0}
            got = _rows(fetch_remote_with_retry((host, port), 1, 0,
                                                conf=conf))
            assert sorted(got) == oracle
        finally:
            for srv in replacement:
                srv.close()
            t.close()


def test_circuit_breaker_opens_and_fails_fast():
    """Repeated failures against one peer trip its breaker: the next
    fetch fails immediately with a diagnosable error instead of
    burning a fresh backoff ladder."""
    conf = TpuConf({"spark.rapids.test.faults":
                    "tcp.client.connect:reset,times=0",
                    "spark.rapids.shuffle.tcp.maxRetries": 2,
                    "spark.rapids.shuffle.tcp.circuitBreaker.maxFailures": 3,
                    "spark.rapids.shuffle.tcp.retryWaitSeconds": 0.01})
    faults = FaultRegistry.from_conf(conf)
    peer = ("127.0.0.1", 59999)  # never dialed: connect fault fires first
    with pytest.raises(ShuffleFetchError, match="giving up"):
        list(fetch_remote_with_retry(peer, 1, 0, conf=conf, faults=faults))
    t0 = time.monotonic()
    with pytest.raises(ShuffleFetchError, match="circuit breaker open"):
        list(fetch_remote_with_retry(peer, 1, 0, conf=conf, faults=faults))
    assert time.monotonic() - t0 < 1.0  # failed fast, no ladder
    # the metadata plane shares the same breaker
    with pytest.raises(ShuffleFetchError, match="circuit breaker open"):
        remote_partition_sizes_with_retry(peer, 1, conf=conf, faults=faults)


def test_circuit_breaker_half_open_probe_recovers():
    """After the cooldown one probe goes through; a healthy peer closes
    the breaker again."""
    conf = TpuConf({
        "spark.rapids.shuffle.tcp.maxRetries": 0,
        "spark.rapids.shuffle.tcp.circuitBreaker.maxFailures": 1,
        "spark.rapids.shuffle.tcp.circuitBreaker.resetSeconds": 0.2,
        "spark.rapids.shuffle.tcp.retryWaitSeconds": 0.01})
    with ExecCtx(backend="device", conf=conf) as ctx:
        t = _transport(ctx, conf)
        try:
            oracle = _fill(t)
            # one failure against THIS peer's breaker trips it
            bad = FaultRegistry("tcp.client.connect:reset,times=1")
            with pytest.raises(ShuffleFetchError):
                list(fetch_remote_with_retry(t.address, 1, 0, conf=conf,
                                             faults=bad))
            with pytest.raises(ShuffleFetchError, match="circuit breaker"):
                list(fetch_remote_with_retry(t.address, 1, 0, conf=conf))
            time.sleep(0.25)  # cooldown -> half-open probe succeeds
            got = _rows(fetch_remote_with_retry(t.address, 1, 0, conf=conf))
            assert sorted(got) == oracle
        finally:
            t.close()


# ---------------------------------------------------------------------------
# spill-path OOM injection
# ---------------------------------------------------------------------------

def test_injected_oom_recovered_by_spill_retry():
    """A simulated HBM OOM at dispatch drives the spill-retry loop:
    the catalog spills registered buffers and the dispatch succeeds on
    the retry (reference DeviceMemoryEventHandler.onAllocFailure)."""
    import jax.numpy as jnp
    from spark_rapids_tpu.memory.catalog import (BufferCatalog,
                                                 SpillPriority,
                                                 run_with_spill_retry)

    conf = TpuConf({"spark.rapids.test.faults": "memory.oom:oom"})
    cat = BufferCatalog(conf=conf)
    try:
        assert cat.faults is not None
        bid = cat.add_batch(host_to_device(_hb(list(range(64)))),
                            SpillPriority.SHUFFLE_OUTPUT)
        out = run_with_spill_retry(lambda a: jnp.sum(a),
                                   cat, jnp.arange(100))
        assert int(out) == 4950
        assert cat.faults.fired_count("memory.oom") == 1
        assert cat.metrics["device_spills"] >= 1
        assert cat.tier_of(bid) != "device"  # it really spilled
    finally:
        cat.close()


def test_injected_oom_exhausting_retries_raises():
    """An OOM that never clears (times=0) still terminates: the loop
    gives up after max_retries instead of spinning."""
    import jax.numpy as jnp
    from spark_rapids_tpu.memory.catalog import (BufferCatalog,
                                                 SpillPriority,
                                                 run_with_spill_retry)

    conf = TpuConf({"spark.rapids.test.faults": "memory.oom:oom,times=0"})
    cat = BufferCatalog(conf=conf)
    try:
        for i in range(8):
            cat.add_batch(host_to_device(_hb([i])),
                          SpillPriority.SHUFFLE_OUTPUT)
        with pytest.raises(RuntimeError, match="RESOURCE_EXHAUSTED"):
            run_with_spill_retry(lambda a: jnp.sum(a), cat,
                                 jnp.arange(10), max_retries=2)
    finally:
        cat.close()


# ---------------------------------------------------------------------------
# end to end: a remote reduce-side pull through the exec layer
# ---------------------------------------------------------------------------

def test_remote_reader_exec_survives_chaos():
    """RemoteShuffleReaderExec (the reduce-side exec) pulls through the
    retrying fetch: a chaos plan on the serving transport is invisible
    to the query result."""
    from spark_rapids_tpu.exec.exchange import RemoteShuffleReaderExec

    serve_conf = TpuConf({"spark.rapids.test.faults":
                          "tcp.server.frame:reset,nth=2,times=1"})
    read_conf = TpuConf(_FAST_RETRY)
    with ExecCtx(backend="device", conf=serve_conf) as sctx:
        t = _transport(sctx, serve_conf)
        try:
            oracle = _fill(t, shuffle_id=7, n_batches=4)
            reader = RemoteShuffleReaderExec(t.address, 7, 1, SCHEMA)
            with ExecCtx(backend="device", conf=read_conf) as rctx:
                got = []
                for b in reader.partition_iter(rctx, 0):
                    got.extend(device_to_host(b).columns[0].to_list())
            assert sorted(got) == oracle
            assert t.server_metrics["faults_injected"] == 1
        finally:
            t.close()

"""Stage-recovery chaos suite: TPC-H under peer-death + spill-corruption
storms.

The ``shuffle.peer.dead`` fault makes reduce-side pulls observe terminal
map-output loss (every map output in the requested slice, exactly as a
dead peer would present), and ``spill.disk.corrupt`` flips one seeded
byte of a spilled shuffle output so its CRC sidecar fails on read-back.
Lineage-based stage recovery (exec/recovery.py) must invalidate exactly
the lost outputs, recompute their producing partitions, and resume the
pull — queries still return EXACT oracle results, with nonzero
``stage_recomputes`` in the BufferCatalog metrics.  Reference intent:
FetchFailed -> DAGScheduler map-stage resubmission keeps queries correct
under executor loss; here the loss is seeded and conf-driven, CPU-only,
no mocks.

The generated sf0.01 tables are split into multiple parquet files so
scans are multi-partition and the planner actually inserts shuffle
exchanges (a single-file scan plans shuffle-free and would make this
suite vacuous).
"""
import os

import pytest

from spark_rapids_tpu.bench.runner import run_benchmark
from spark_rapids_tpu.bench.tpch_gen import generate_tpch

# peer death on every transport's first two pulls, plus one corrupted
# spilled shuffle output (priority=0 = SHUFFLE_OUTPUT entries only)
_STORM = ("shuffle.peer.dead:dead,times=2;"
          "spill.disk.corrupt:corrupt,priority=0,times=2")
_CHAOS_CONF = {
    "spark.rapids.test.faults": _STORM,
    # tiny device budget + host arena: shuffle outputs spill DIRECT to
    # disk, so the corrupt-readback path is actually exercised
    "spark.rapids.memory.tpu.spillStoreSize": 1 << 16,
    "spark.rapids.memory.host.spillStorageSize": 4096,
}

_QUERIES = ["q3", "q12", "q18"]


@pytest.fixture(scope="module")
def data_dir(tmp_path_factory):
    d = str(tmp_path_factory.mktemp("tpch_recovery_chaos") / "sf001")
    generate_tpch(d, sf=0.01)
    _split_tables(d, ("lineitem", "orders", "customer"), parts=4)
    return d


def _split_tables(data_dir: str, tables, parts: int) -> None:
    """Re-write each table as ``parts`` parquet files so its scan is
    multi-partition and aggregations above it get shuffle exchanges."""
    import pyarrow.parquet as pq
    for table in tables:
        path = os.path.join(data_dir, table, "part-0.parquet")
        t = pq.read_table(path)
        step = -(-t.num_rows // parts)
        for i in range(parts):
            pq.write_table(t.slice(i * step, step),
                           os.path.join(data_dir, table,
                                        f"part-{i}.parquet"))


@pytest.mark.parametrize("query", _QUERIES)
def test_tpch_exact_under_loss_storm(data_dir, query):
    r = run_benchmark(data_dir, 0.01, [query], verify=True,
                      generate=False, suite="tpch",
                      session_conf=_CHAOS_CONF)[0]
    assert "error" not in r, r
    assert r["ok"], r
    cat = r["metrics"].get("BufferCatalog", {})
    # the storm must actually have driven lineage recomputation
    assert cat.get("stage_recomputes", 0) > 0, cat
    assert cat.get("map_outputs_recomputed", 0) > 0, cat
    assert cat.get("recovery_wall_s", 0) > 0, cat


def test_corrupt_spill_readback_recovered(data_dir):
    """q18 (largest shuffle volume of the trio) spills shuffle outputs
    to disk under the tiny budgets; the corrupted read-back must be
    detected by the CRC sidecar and recovered from lineage, not served
    as silently wrong rows."""
    r = run_benchmark(data_dir, 0.01, ["q18"], verify=True,
                      generate=False, suite="tpch",
                      session_conf=_CHAOS_CONF)[0]
    assert "error" not in r and r["ok"], r
    cat = r["metrics"].get("BufferCatalog", {})
    assert cat.get("spill_crc_failures", 0) > 0, cat
    assert cat.get("bytes_spilled_to_disk", 0) > 0, cat
    assert cat.get("stage_recomputes", 0) > 0, cat


def test_recovery_disabled_fails_fast(data_dir):
    """Control: with recovery off the same storm fails the query with a
    terminal error naming the lost map outputs — proving the exact
    results above come from recomputation, not from the faults never
    firing."""
    conf = dict(_CHAOS_CONF)
    conf["spark.rapids.test.faults"] = "shuffle.peer.dead:dead,times=2"
    conf["spark.rapids.shuffle.recovery.enabled"] = "false"
    r = run_benchmark(data_dir, 0.01, ["q3"], verify=False,
                      generate=False, suite="tpch", session_conf=conf)[0]
    assert not r["ok"]
    assert "MapOutputLostError" in r["error"], r["error"]
    assert "map output lost" in r["error"], r["error"]


def test_persistent_death_exhausts_budget(data_dir):
    """A peer that stays dead (times=0 -> the fault fires forever) must
    exhaust the per-stage attempt budget and surface
    StageRecoveryExhausted instead of recomputing unboundedly."""
    conf = {
        "spark.rapids.test.faults": "shuffle.peer.dead:dead,times=0",
        "spark.rapids.shuffle.recovery.maxStageAttempts": 2,
    }
    r = run_benchmark(data_dir, 0.01, ["q3"], verify=False,
                      generate=False, suite="tpch", session_conf=conf)[0]
    assert not r["ok"]
    assert "StageRecoveryExhausted" in r["error"], r["error"]
    assert "2 recovery attempts" in r["error"], r["error"]

"""Partitioning + exchange differential tests.

Mirrors the reference's GpuPartitioningSuite / repartition integration
tests: partition-id parity with host murmur3, range ordering invariants,
round-robin balance, and a full partial-agg -> shuffle -> final-agg
pipeline vs the oracle.
"""
import numpy as np
import pytest

from spark_rapids_tpu import types as T
from spark_rapids_tpu.exec import (BroadcastExchangeExec, ExecCtx,
                                   HashAggregateExec, HashPartitioning,
                                   LocalScanExec, RangePartitioning,
                                   RoundRobinPartitioning,
                                   ShuffleExchangeExec, SinglePartitioning,
                                   collect_device, collect_host)
from spark_rapids_tpu.exec.core import device_to_host
from spark_rapids_tpu.expr.aggregates import Average as Avg, CountStar, Max, Sum
from spark_rapids_tpu.expr.core import col
from spark_rapids_tpu.testing import assert_tpu_and_cpu_equal, _sort_key

SCHEMA = T.Schema([
    T.StructField("k", T.IntegerType(), True),
    T.StructField("v", T.LongType(), True),
    T.StructField("s", T.StringType(), True),
])


def _scan(rng, n=300, parts=3):
    return LocalScanExec.from_pydict({
        "k": [None if rng.random() < 0.06 else int(x)
              for x in rng.integers(0, 40, n)],
        "v": [int(x) for x in rng.integers(-100, 100, n)],
        "s": [f"s{x}" if x % 5 else None for x in rng.integers(0, 25, n)],
    }, SCHEMA, partitions=parts, rows_per_batch=64)


def _partition_rows(plan, backend):
    """rows per output partition on a backend.

    Map-side tiny-input coalescing is pinned OFF: these tests assert
    the partitioning kernels' exact placement, which the coalescer
    intentionally overrides for sub-advisory-size map sides."""
    from spark_rapids_tpu.conf import TpuConf
    ctx = ExecCtx(backend=backend, conf=TpuConf(
        {"spark.sql.adaptive.advisoryPartitionSizeInBytes": 0}))
    out = []
    for pid in range(plan.num_partitions(ctx)):
        rows = []
        for b in plan.partition_iter(ctx, pid):
            hb = device_to_host(b) if backend == "device" else b
            rows.extend(hb.to_rows())
        out.append(rows)
    return out


@pytest.mark.parametrize("n_parts", [1, 4, 7])
def test_hash_partitioning_parity(rng, n_parts):
    plan = ShuffleExchangeExec(HashPartitioning([col("k")], n_parts),
                               _scan(rng))
    host = _partition_rows(plan, "host")
    dev = _partition_rows(plan, "device")
    # same rows in the same partition on both backends (bit-exact murmur3)
    for p in range(n_parts):
        assert sorted(host[p], key=_sort_key) == sorted(dev[p], key=_sort_key)
    assert_tpu_and_cpu_equal(plan)


def test_round_robin_balance(rng):
    plan = ShuffleExchangeExec(RoundRobinPartitioning(5), _scan(rng, n=250))
    host = _partition_rows(plan, "host")
    dev = _partition_rows(plan, "device")
    sizes = [len(r) for r in host]
    assert max(sizes) - min(sizes) <= 1
    for p in range(5):
        assert sorted(host[p], key=_sort_key) == sorted(dev[p], key=_sort_key)


def test_single_partitioning(rng):
    plan = ShuffleExchangeExec(SinglePartitioning(), _scan(rng))
    ctx = ExecCtx(backend="host")
    assert plan.num_partitions(ctx) == 1
    assert_tpu_and_cpu_equal(plan)


def test_range_partitioning_ordering_invariant(rng):
    plan = ShuffleExchangeExec(
        RangePartitioning([("v", True)], 4), _scan(rng))
    for backend in ("host", "device"):
        parts = _partition_rows(plan, backend)
        assert sum(len(p) for p in parts) == 300
        # every value in partition p <= every value in partition p+1
        for p in range(3):
            if parts[p] and parts[p + 1]:
                assert max(r[1] for r in parts[p]) <= \
                    min(r[1] for r in parts[p + 1])
    assert_tpu_and_cpu_equal(plan)


def test_range_partitioning_desc_with_nulls(rng):
    plan = ShuffleExchangeExec(
        RangePartitioning([("k", False)], 3), _scan(rng))
    for backend in ("host", "device"):
        parts = _partition_rows(plan, backend)
        assert sum(len(p) for p in parts) == 300
        # desc + default nulls-last: nulls must be in the last partition
        for p in range(2):
            assert all(r[0] is not None for r in parts[p])
    assert_tpu_and_cpu_equal(plan)


def test_partial_shuffle_final_aggregate(rng):
    scan = _scan(rng, n=400, parts=4)
    partial = HashAggregateExec(
        [col("k")],
        [col("k"), Sum(col("v")).alias("sv"), CountStar().alias("c"),
         Avg(col("v")).alias("av"), Max(col("s")).alias("mx")],
        scan, mode="partial")
    shuffled = ShuffleExchangeExec(HashPartitioning([col("k")], 3), partial)
    final = HashAggregateExec.final_from_partial(partial, shuffled)
    rows = assert_tpu_and_cpu_equal(final)
    # oracle: complete-mode aggregation without any shuffle
    complete = HashAggregateExec(
        [col("k")],
        [col("k"), Sum(col("v")).alias("sv"), CountStar().alias("c"),
         Avg(col("v")).alias("av"), Max(col("s")).alias("mx")],
        _scan(np.random.default_rng(42), n=400, parts=4), mode="complete")
    want = collect_host(complete)
    assert sorted(rows, key=_sort_key) == sorted(want, key=_sort_key)


def test_broadcast_exchange_caches(rng):
    b = BroadcastExchangeExec(_scan(rng, n=50))
    ctx = ExecCtx(backend="host")
    one = b.materialize(ctx)
    two = b.materialize(ctx)
    assert one is two
    assert_tpu_and_cpu_equal(b)


def test_adaptive_reader_skew_split(rng):
    """A skewed reduce partition is split into multiple reader groups at
    map-batch granularity (AQE skew reader, join-side scope), and the
    data read through the split groups is exactly the shuffle output."""
    from spark_rapids_tpu.conf import TpuConf
    from spark_rapids_tpu.exec.exchange import AdaptiveShuffleReaderExec

    # 90% of rows share key 0 -> one hot hash partition; many small map
    # batches so the skewed partition has sub-partition granularity
    n = 400
    hot = [0 if i % 10 else int(rng.integers(1, 30)) for i in range(n)]
    scan = LocalScanExec.from_pydict(
        {"k": hot, "v": [int(x) for x in rng.integers(-50, 50, n)],
         "s": [f"s{i%7}" for i in range(n)]},
        SCHEMA, partitions=4, rows_per_batch=16)
    shuffle = ShuffleExchangeExec(HashPartitioning([col("k")], 4), scan)
    reader = AdaptiveShuffleReaderExec(shuffle, allow_skew_split=True)
    conf = TpuConf({
        "spark.sql.adaptive.skewedPartitionThresholdInBytes": 4096,
        "spark.sql.adaptive.advisoryPartitionSizeInBytes": 2048,
    })
    with ExecCtx(backend="device", conf=conf) as ctx:
        groups = reader._groups(ctx)
        nparts = shuffle.num_partitions(ctx)
        # the hot partition must have been split
        assert len(groups) > 1
        split_pids = {spec[0] for g in groups for spec in g
                      if not (spec[1] == 0 and spec[2] is None)}
        assert split_pids, f"no partition was split: {groups}"
        rows = []
        for b in reader.execute(ctx):
            rows.extend(device_to_host(b).to_rows())
    want = collect_host(shuffle)
    assert sorted(rows, key=_sort_key) == sorted(want, key=_sort_key)


def test_adaptive_skew_split_disabled_for_aggregation(rng):
    """The reader feeding a final aggregation must NOT split partitions
    (duplicate keys otherwise); default allow_skew_split=False."""
    from spark_rapids_tpu.conf import TpuConf
    from spark_rapids_tpu.exec.exchange import AdaptiveShuffleReaderExec

    n = 400
    hot = [0 if i % 10 else int(rng.integers(1, 30)) for i in range(n)]
    scan = LocalScanExec.from_pydict(
        {"k": hot, "v": [int(x) for x in rng.integers(-50, 50, n)],
         "s": [f"s{i%7}" for i in range(n)]},
        SCHEMA, partitions=4, rows_per_batch=16)
    shuffle = ShuffleExchangeExec(HashPartitioning([col("k")], 4), scan)
    reader = AdaptiveShuffleReaderExec(shuffle)
    conf = TpuConf({
        "spark.sql.adaptive.skewedPartitionThresholdInBytes": 4096,
        "spark.sql.adaptive.advisoryPartitionSizeInBytes": 2048,
    })
    with ExecCtx(backend="device", conf=conf) as ctx:
        for g in reader._groups(ctx):
            for pid, lo, hi in g:
                assert lo == 0 and hi is None


def test_adaptive_reader_over_non_shuffle_child(rng):
    """The reader must degrade to identity groups and plain iteration
    when its child is not a bare ShuffleExchangeExec (review finding:
    partition_iter_slice AttributeError over BackendSwitchExec)."""
    from spark_rapids_tpu.conf import TpuConf
    from spark_rapids_tpu.exec.exchange import AdaptiveShuffleReaderExec
    from spark_rapids_tpu.exec.transitions import BackendSwitchExec

    shuffle = ShuffleExchangeExec(HashPartitioning([col("k")], 3),
                                  _scan(rng, n=90))
    reader = AdaptiveShuffleReaderExec(shuffle, allow_skew_split=True)
    # simulate transition insertion wrapping the shuffle
    reader.children = (BackendSwitchExec(shuffle, "host"),)
    with ExecCtx(backend="device", conf=TpuConf({})) as ctx:
        rows = []
        for b in reader.execute(ctx):
            rows.extend(device_to_host(b).to_rows())
    want = collect_host(shuffle)
    assert sorted(rows, key=_sort_key) == sorted(want, key=_sort_key)


def test_exchange_reuse_single_materialization():
    """A DataFrame referenced twice in one query (agg-over-agg
    self-join, the q65 shape) must materialize its shuffle map side
    ONCE — duplicate exchange subtrees share a structural shuffle_id
    (Spark's ReuseExchange rule)."""
    import numpy as np
    from spark_rapids_tpu.exec.core import collect_host
    from spark_rapids_tpu.exec.exchange import ShuffleExchangeExec
    from spark_rapids_tpu.expr.aggregates import Average, Sum
    from spark_rapids_tpu.expr.core import col
    from spark_rapids_tpu.session import TpuSession
    from spark_rapids_tpu import types as T

    schema = T.Schema([T.StructField("s", T.IntegerType(), True),
                       T.StructField("i", T.IntegerType(), True),
                       T.StructField("v", T.DoubleType(), True)])
    s = TpuSession({})
    rng = np.random.default_rng(12)
    df = s.from_pydict({"s": rng.integers(0, 5, 800).astype(np.int32),
                        "i": rng.integers(0, 40, 800).astype(np.int32),
                        "v": rng.random(800)}, schema, partitions=3)
    sc = df.group_by("s", "i").agg(Sum(col("v")).alias("rev"))
    sb = sc.group_by("s").agg(Average(col("rev")).alias("ave")) \
        .select(col("s").alias("bs"), col("ave"))
    q = sc.join(sb, on=[("s", "bs")]).where(col("rev") > col("ave"))

    calls = []
    orig = ShuffleExchangeExec._do_shuffle

    def counting(self, ctx):
        calls.append(self.shuffle_id)
        return orig(self, ctx)

    ShuffleExchangeExec._do_shuffle = counting
    try:
        dev = sorted(q.collect(), key=str)
    finally:
        ShuffleExchangeExec._do_shuffle = orig
    # the plan holds 3 exchange objects (sc's twice, sb's once) but
    # only 2 DISTINCT fingerprints execute: the duplicated sc pipeline
    # materialized once (a vacuous uniqueness check would also pass if
    # dedup silently broke — assert the actual counts)
    exchanges = []

    def walk(n):
        if isinstance(n, ShuffleExchangeExec):
            exchanges.append(n)
        for c in n.children:
            walk(c)

    ov2, meta2 = q._overridden(quiet=True)
    walk(meta2.exec_node)
    assert len(exchanges) == 3
    assert len({e.shuffle_id for e in exchanges}) == 2
    assert len(calls) == 2 and len(set(calls)) == 2, calls
    ov, meta = q._overridden(quiet=True)
    host = sorted(collect_host(meta.exec_node, s.conf), key=str)
    assert len(dev) == len(host)
    for d, h in zip(dev, host):
        assert d[0] == h[0] and d[1] == h[1]
        assert abs(d[2] - h[2]) < 1e-9 and abs(d[4] - h[4]) < 1e-9


def test_map_side_tiny_coalesce(rng):
    """Sub-advisory map sides write everything to partition 0 on the
    device backend (map-side counterpart of AQE small-partition
    coalescing) with identical query results."""
    from spark_rapids_tpu.conf import TpuConf
    plan = ShuffleExchangeExec(HashPartitioning([col("k")], 5),
                               _scan(rng))
    ctx = ExecCtx(backend="device")  # default advisory: 64MB >> input
    parts = []
    for pid in range(plan.num_partitions(ctx)):
        rows = []
        for b in plan.partition_iter(ctx, pid):
            rows.extend(device_to_host(b).to_rows())
        parts.append(rows)
    assert len(parts[0]) == 300
    assert all(not p for p in parts[1:])
    # content parity with the sliced host path
    host = _partition_rows(plan, "host")
    assert sorted((r for p in host for r in p), key=_sort_key) == \
        sorted(parts[0], key=_sort_key)


def test_map_side_coalesce_gated_off_when_aqe_disabled(rng):
    """spark.sql.adaptive.enabled=false must disable the map-side
    tiny-input coalescer (it is an ADAPTIVE rewrite): partition
    placement matches the host path exactly."""
    from spark_rapids_tpu.conf import TpuConf
    plan = ShuffleExchangeExec(HashPartitioning([col("k")], 5),
                               _scan(rng))
    ctx = ExecCtx(backend="device", conf=TpuConf(
        {"spark.sql.adaptive.enabled": False}))
    parts = []
    for pid in range(plan.num_partitions(ctx)):
        rows = []
        for b in plan.partition_iter(ctx, pid):
            rows.extend(device_to_host(b).to_rows())
        parts.append(rows)
    assert sum(1 for p in parts if p) > 1  # NOT all in partition 0
    host = _partition_rows(plan, "host")
    for dev_p, host_p in zip(parts, host):
        assert sorted(dev_p, key=_sort_key) == sorted(host_p, key=_sort_key)


def test_map_side_coalesce_gated_off_for_repartition_reader(rng):
    """An allow_coalesce=False reader (explicit repartition(n)) promises
    n non-degenerate partitions: the exchange it consumes must keep all
    n even for sub-advisory map sides (REPARTITION_BY_NUM contract)."""
    from spark_rapids_tpu.exec.exchange import AdaptiveShuffleReaderExec
    shuffle = ShuffleExchangeExec(RoundRobinPartitioning(5), _scan(rng))
    reader = AdaptiveShuffleReaderExec(shuffle, allow_coalesce=False)
    assert shuffle._no_map_coalesce
    ctx = ExecCtx(backend="device")  # default advisory: 64MB >> input
    counts = []
    for pid in range(reader.num_partitions(ctx)):
        counts.append(sum(device_to_host(b).num_rows
                          for b in reader.partition_iter(ctx, pid)))
    assert len(counts) == 5
    assert all(c == 60 for c in counts)  # 300 rows round-robin over 5


def test_repartition_n_keeps_n_partitions_end_to_end(rng):
    """df.repartition(n) through the full planner: n output partitions,
    none degenerate, rows intact (the coalescer used to fold tiny map
    sides into one partition even under an explicit repartition)."""
    from spark_rapids_tpu.exec.core import device_to_host as d2h
    from spark_rapids_tpu.session import TpuSession
    s = TpuSession({})
    df = s.from_pydict({
        "k": [int(x) for x in rng.integers(0, 40, 200)],
        "v": [int(x) for x in rng.integers(-100, 100, 200)],
    }, T.Schema([T.StructField("k", T.IntegerType(), True),
                 T.StructField("v", T.LongType(), True)]),
        partitions=2).repartition(4)
    ov, meta = df._overridden(quiet=True)
    plan = meta.exec_node
    with ExecCtx(backend="device", conf=s.conf) as ctx:
        nparts = plan.num_partitions(ctx)
        counts = [sum(d2h(b).num_rows for b in plan.partition_iter(ctx, p))
                  for p in range(nparts)]
    assert nparts == 4
    assert all(c > 0 for c in counts) and sum(counts) == 200
    assert sorted(df.collect()) == sorted(
        collect_host(plan, s.conf))

"""Cluster runtime tests: driver/worker multi-process execution over the
DCN shuffle plane (spark_rapids_tpu/cluster/).

``cluster.mode=off`` must be inert (no tagging, no subprocesses, no
counter movement), and ``local[N]`` must return EXACTLY the rows the
single-process engine returns — proved here for a pydict group-by with
a hand-computed oracle and for TPC-H over split multi-file tables (a
single-file sf0.01 scan plans shuffle-free, so the tables are split
exactly like tests/test_recovery_chaos.py does).  Worker death mid-query
is seeded with the ``cluster.worker.dead`` fault (a REAL SIGKILL of the
worker subprocess, detected through the failed fetch like any crash)
and must recompute only the lost map outputs on survivors — same exact
rows, nonzero recovery counters.  Reference intent: executor loss feeds
FetchFailed -> DAGScheduler map-stage resubmission; here the control
plane is cluster/rpc.py and the data plane the existing TCP shuffle
servers.
"""
import os
import socket
import threading
import time

import numpy as np
import pytest

from spark_rapids_tpu import TpuSession
from spark_rapids_tpu import types as T
from spark_rapids_tpu.bench.runner import run_benchmark
from spark_rapids_tpu.bench.tpch_gen import generate_tpch
from spark_rapids_tpu.conf import TpuConf
from spark_rapids_tpu.expr.aggregates import CountStar, Sum
from spark_rapids_tpu.expr.core import col
from spark_rapids_tpu.obs.registry import get_registry

SCHEMA = T.Schema([
    T.StructField("k", T.IntegerType(), True),
    T.StructField("v", T.LongType(), True),
])


def _mkdata(n=400, seed=7):
    rng = np.random.default_rng(seed)
    return {"k": [int(x) for x in rng.integers(0, 13, n)],
            "v": [int(x) for x in rng.integers(-1000, 1000, n)]}


def _walk(node):
    yield node
    for c in node.children:
        yield from _walk(c)


# ---------------------------------------------------------------------------
# control-plane RPC (no subprocesses)
# ---------------------------------------------------------------------------

def _echo(payload, blob):
    return {"echo": payload}, blob[::-1]


def test_rpc_roundtrip_with_compressed_blob():
    from spark_rapids_tpu.cluster.rpc import RpcServer, rpc_call
    srv = RpcServer({"echo": _echo}, codec_name="lz4")
    try:
        conf = TpuConf(
            {"spark.rapids.cluster.rpc.compression.codec": "lz4"})
        blob = b"spark-rapids-tpu " * 4096  # compressible
        reply, rblob = rpc_call(srv.address, "echo", {"x": 1},
                                blob=blob, conf=conf)
        assert reply == {"echo": {"x": 1}}
        assert rblob == blob[::-1]
        assert srv.metrics["rpc_requests"] == 1
        # the wire carries COMPRESSED bytes (checksummed post-codec)
        from spark_rapids_tpu.cluster.rpc import _pack_blob
        wire, fields = _pack_blob(blob, "lz4")
        assert len(wire) < len(blob) and fields["codec"] == "lz4"
    finally:
        srv.close()


def test_rpc_handler_error_not_retried():
    from spark_rapids_tpu.cluster.rpc import (RpcHandlerError, RpcServer,
                                              rpc_call)

    def boom(payload, blob):
        raise ValueError("bad op arg")

    srv = RpcServer({"boom": boom})
    try:
        with pytest.raises(RpcHandlerError, match="bad op arg"):
            rpc_call(srv.address, "boom")
        assert srv.metrics["rpc_errors"] == 1
        with pytest.raises(RpcHandlerError, match="unknown rpc op"):
            rpc_call(srv.address, "nope")
    finally:
        srv.close()


def test_rpc_dead_peer_raises_after_retries():
    from spark_rapids_tpu.cluster.rpc import RpcError, rpc_call
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    before = get_registry().snapshot()
    with pytest.raises(RpcError, match="failed after 3 attempts"):
        rpc_call(("127.0.0.1", port), "ping", retries=2, timeout=2.0)
    d = get_registry().delta(before)["counters"]
    assert d.get("cluster.rpc.retries", 0) >= 3, d


def test_rpc_drop_fault_absorbed_by_retries():
    from spark_rapids_tpu.cluster.rpc import RpcServer, rpc_call
    from spark_rapids_tpu.faults import FaultRegistry
    srv = RpcServer({"echo": _echo})
    try:
        faults = FaultRegistry.from_conf(
            {"spark.rapids.test.faults": "cluster.rpc.drop:drop,times=2"})
        before = get_registry().snapshot()
        reply, _ = rpc_call(srv.address, "echo", {"ok": 1}, faults=faults)
        assert reply == {"echo": {"ok": 1}}
        d = get_registry().delta(before)["counters"]
        assert d.get("cluster.rpc.dropped", 0) == 2, d
    finally:
        srv.close()


def test_rpc_retry_with_same_idem_key_executes_once():
    """A dropped-then-retried non-idempotent op must execute ONCE.

    ``rpc_call`` mints one ``(caller, seq)`` key per LOGICAL call and
    reuses it across retry attempts; the server's replay cache answers
    the retry with the recorded reply instead of re-running the
    handler.  This drives ``_call_once`` directly with the same key —
    byte-for-byte what the retry loop sends after a reply is lost in
    flight — and then with a fresh key to prove dedup doesn't bleed
    across logical calls."""
    from spark_rapids_tpu.cluster.rpc import RpcServer, _call_once
    runs = {"n": 0}

    def run_fragment(payload, blob):
        runs["n"] += 1
        return {"ran": runs["n"], "frag": payload.get("frag")}, b"out"

    srv = RpcServer({"run_fragment": run_fragment})
    try:
        before = get_registry().snapshot()
        host, port = srv.address
        idem = {"caller": "test-caller.e1", "seq": 7}
        first, blob1 = _call_once(host, port, "run_fragment",
                                  {"frag": 3}, b"", None, 10.0,
                                  idem=idem)
        # the reply "was lost": the client retries the SAME logical call
        second, blob2 = _call_once(host, port, "run_fragment",
                                   {"frag": 3}, b"", None, 10.0,
                                   idem=idem)
        assert runs["n"] == 1, "retried run_fragment executed twice"
        assert second == first and blob2 == blob1 == b"out"
        assert srv.metrics["rpc_replays_deduped"] == 1
        d = get_registry().delta(before)["counters"]
        assert d.get("cluster.rpc.replays_deduped", 0) == 1, d
        # a NEW logical call (fresh seq) is not deduped
        third, _ = _call_once(host, port, "run_fragment", {"frag": 4},
                              b"", None, 10.0,
                              idem={"caller": "test-caller.e1",
                                    "seq": 8})
        assert runs["n"] == 2 and third["frag"] == 4
        # a retried call whose handler FAILED replays the error too —
        # the failure side effect also happened exactly once
        from spark_rapids_tpu.cluster.rpc import RpcHandlerError
        boom = {"caller": "test-caller.e1", "seq": 9}
        srv._handlers["kaboom"] = lambda p, b: (_ for _ in ()).throw(
            ValueError("no such fragment"))
        for _ in range(2):
            with pytest.raises(RpcHandlerError, match="no such fragment"):
                _call_once(host, port, "kaboom", {}, b"", None, 10.0,
                           idem=boom)
        assert srv.metrics["rpc_errors"] == 1
        assert srv.metrics["rpc_replays_deduped"] == 2
    finally:
        srv.close()


def test_parse_cluster_mode():
    from spark_rapids_tpu.cluster import parse_cluster_mode
    assert parse_cluster_mode(TpuConf({})) == 0
    assert parse_cluster_mode(
        TpuConf({"spark.rapids.cluster.mode": "local[3]"})) == 3


# ---------------------------------------------------------------------------
# off-mode inertness
# ---------------------------------------------------------------------------

def test_cluster_off_is_inert():
    s = TpuSession()
    df = s.from_pydict(_mkdata(), SCHEMA, partitions=3, rows_per_batch=64)
    agg = df.group_by("k").agg(Sum(col("v")).alias("sv"))
    before = get_registry().snapshot()
    rows = agg.collect()
    assert rows
    # no driver spawned, no plan node tagged, no cluster counter moved
    assert s._cluster() is None
    _, meta = agg._overridden(quiet=True)
    assert not [n for n in _walk(meta.exec_node)
                if getattr(n, "_cluster_ok", False)]
    d = get_registry().delta(before)["counters"]
    assert not [k for k in d if k.startswith("cluster")], d
    s.shutdown()


# ---------------------------------------------------------------------------
# local[2]: exactness, codec negotiation, clean teardown
# ---------------------------------------------------------------------------

def _cluster_threads():
    return [t.name for t in threading.enumerate()
            if t.name in ("tpu-cluster-monitor", "tpu-cluster-rpc")]


def test_local2_groupby_exact_lz4_and_clean_shutdown():
    """One worker pool proves three things: a sharded hash shuffle
    returns EXACTLY the single-process rows, the shuffle codec is
    negotiated across real process boundaries (driver fetches lz4
    frames from worker-owned stores), and ``shutdown(drain=True)``
    leaves zero orphan worker processes or cluster threads."""
    data = _mkdata()
    agg_cols = (Sum(col("v")).alias("sv"), CountStar().alias("c"))
    s0 = TpuSession()
    df0 = s0.from_pydict(data, SCHEMA, partitions=3, rows_per_batch=64)
    want = sorted(df0.group_by("k").agg(*agg_cols).collect())
    s0.shutdown()

    s = TpuSession({"spark.rapids.cluster.mode": "local[2]",
                    "spark.rapids.shuffle.compression.codec": "lz4"})
    df = s.from_pydict(data, SCHEMA, partitions=3, rows_per_batch=64)
    before = get_registry().snapshot()
    got = sorted(df.group_by("k").agg(*agg_cols).collect())
    assert got == want
    d = get_registry().delta(before)["counters"]
    assert d.get("cluster.shuffles_clustered", 0) >= 1, d
    assert d.get("cluster.fragments_dispatched", 0) >= 2, d
    # codec negotiation happened on the driver's reduce-side pulls
    assert d.get("shuffle.fetch.codec.lz4", 0) >= 1, d

    cluster = s._cluster()
    handles = cluster.workers()
    assert len(handles) == 2 and all(h.alive for h in handles)
    s.shutdown(drain=True)
    for h in handles:
        assert h.proc.poll() is not None, \
            f"worker {h.worker_id} still running after shutdown"
    deadline = time.monotonic() + 5.0
    while _cluster_threads() and time.monotonic() < deadline:
        time.sleep(0.05)
    assert not _cluster_threads()


# ---------------------------------------------------------------------------
# TPC-H over the worker pool (slow: worker pools recompile per query on a
# cold process; ci/premerge.sh runs the same q3 + worker-death paths)
# ---------------------------------------------------------------------------

@pytest.fixture(scope="module")
def tpch_dir(tmp_path_factory):
    d = str(tmp_path_factory.mktemp("tpch_cluster") / "sf001")
    generate_tpch(d, sf=0.01)
    _split_tables(d, ("lineitem", "orders", "customer"), parts=4)
    return d


def _split_tables(data_dir: str, tables, parts: int) -> None:
    """Re-write each table as ``parts`` parquet files so its scan is
    multi-partition and aggregations above it get shuffle exchanges."""
    import pyarrow.parquet as pq
    for table in tables:
        path = os.path.join(data_dir, table, "part-0.parquet")
        t = pq.read_table(path)
        step = -(-t.num_rows // parts)
        for i in range(parts):
            pq.write_table(t.slice(i * step, step),
                           os.path.join(data_dir, table,
                                        f"part-{i}.parquet"))


@pytest.mark.slow
def test_tpch_local2_exact(tpch_dir):
    r = run_benchmark(tpch_dir, 0.01, ["q3"], verify=True, generate=False,
                      suite="tpch",
                      session_conf={
                          "spark.rapids.cluster.mode": "local[2]"})[0]
    assert "error" not in r, r
    assert r["ok"], r
    reg = (r["observability"].get("registry") or {}).get("counters") or {}
    assert reg.get("cluster.shuffles_clustered", 0) >= 1, reg
    assert reg.get("cluster.fragments_dispatched", 0) >= 2, reg


@pytest.mark.slow
@pytest.mark.parametrize("query", ["q6", "q12", "q18"])
def test_tpch_local2_exact_slow(tpch_dir, query):
    r = run_benchmark(tpch_dir, 0.01, [query], verify=True, generate=False,
                      suite="tpch",
                      session_conf={
                          "spark.rapids.cluster.mode": "local[2]"})[0]
    assert "error" not in r, r
    assert r["ok"], r


_CHAOS_CONF = {
    "spark.rapids.cluster.mode": "local[2]",
    # SIGKILL one worker on the driver's first reduce-side pull; the
    # death is DETECTED via the real refused reconnect, so keep the
    # transient ladder short or the test spends its time backing off
    "spark.rapids.test.faults": "cluster.worker.dead:dead,times=1",
    "spark.rapids.shuffle.tcp.maxRetries": 1,
    "spark.rapids.shuffle.tcp.retryWaitSeconds": 0.1,
}


# ---------------------------------------------------------------------------
# membership churn hygiene: repeated scale-up/down leaks nothing
# ---------------------------------------------------------------------------

def _open_fds():
    return len(os.listdir("/proc/self/fd"))


def test_membership_churn_leaks_nothing():
    """Three add/remove cycles on a live pool: every retired worker
    process is reaped, every per-worker io thread exits, and the
    driver's fd table returns to its pre-churn size (RPC sockets,
    stdio pipes, shuffle connections all closed)."""
    s = TpuSession({"spark.rapids.cluster.mode": "local[2]",
                    "spark.rapids.cluster.maxWorkers": "8"})
    df = s.from_pydict(_mkdata(), SCHEMA, partitions=3, rows_per_batch=64)
    agg = df.group_by("k").agg(Sum(col("v")).alias("sv"))
    want = sorted(agg.collect())
    drv = s._cluster()
    fds0 = _open_fds()
    retired = []
    for _ in range(3):
        wid = drv.add_worker()
        assert sorted(agg.collect()) == want
        drv.remove_worker(wid, drain=True)
        retired.append(drv.worker_by_id(wid))
    # processes reaped (no zombies), io threads joined
    for h in retired:
        assert h.proc.poll() is not None, \
            f"churned worker {h.worker_id} still running"
        assert h.io_thread is None or not h.io_thread.is_alive(), \
            f"io thread for {h.worker_id} leaked"
    assert not [t.name for t in threading.enumerate()
                if t.name.startswith("tpu-cluster-io-")
                and t.name.split("-")[-1] in
                [h.worker_id for h in retired]]
    # fd table settles back to the steady-state size (allow slack for
    # lazily-opened shuffle client connections to the LIVE workers)
    deadline = time.monotonic() + 5.0
    while _open_fds() > fds0 + 4 and time.monotonic() < deadline:
        time.sleep(0.1)
    assert _open_fds() <= fds0 + 4, \
        f"fd leak across churn: {fds0} -> {_open_fds()}"
    assert sorted(agg.collect()) == want
    handles = drv.workers()
    s.shutdown(drain=True)
    for h in handles:
        assert h.proc.poll() is not None, \
            f"worker {h.worker_id} still running after shutdown"


@pytest.mark.slow
def test_tpch_worker_death_recovers_exact(tpch_dir):
    """q18 with a worker SIGKILLed mid-query: lineage recovery must
    recompute the lost map outputs on the survivor and still return
    EXACT oracle rows."""
    r = run_benchmark(tpch_dir, 0.01, ["q18"], verify=True, generate=False,
                      suite="tpch", session_conf=_CHAOS_CONF)[0]
    assert "error" not in r, r
    assert r["ok"], r
    reg = (r["observability"].get("registry") or {}).get("counters") or {}
    assert reg.get("faults.injected.cluster.worker.dead", 0) >= 1, reg
    assert reg.get("cluster_workers_lost", 0) >= 1, reg
    assert reg.get("stage_recomputes", 0) > 0, reg
    assert reg.get("map_outputs_recomputed", 0) > 0, reg

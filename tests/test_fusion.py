"""Whole-stage fusion + process-wide compile cache (exec/fused.py,
exec/compile_cache.py, plan/overrides.py _fuse_stages).

Three axes, mirroring the chaos-suite discipline of exact-result
assertions:

- correctness: fused and unfused plans return IDENTICAL rows on the
  TPC-H ladder queries, and the fusion pass is shape-reversible via
  ``spark.rapids.sql.fusion.enabled=false``;
- cache keys: same fragment → one shared program (hit); a changed
  literal, dtype, or non-child attribute (LIKE pattern — absent from
  ``repr``, the motivating case for structural fingerprints) → distinct
  keys; a changed capacity bucket reuses the SAME wrapper and is
  counted as a new compile at the signature level;
- resilience: an OOM storm inside a fused stage still converges through
  split-and-retry with exact results (fused bodies are elementwise, so
  row-halves reproduce identical rows in order).
"""
import pytest

from spark_rapids_tpu import TpuSession
from spark_rapids_tpu import types as T
from spark_rapids_tpu.exec import compile_cache as cc
from spark_rapids_tpu.exec.fused import FusedStageExec
from spark_rapids_tpu.obs.registry import get_registry

_LADDER = ["q1", "q3", "q6", "q12", "q18"]


@pytest.fixture(scope="module")
def data_dir(tmp_path_factory):
    from spark_rapids_tpu.bench.tpch_gen import generate_tpch
    d = str(tmp_path_factory.mktemp("tpch_fusion") / "sf001")
    generate_tpch(d, sf=0.01)
    return d


def _plan_of(df):
    ov, meta = df._overridden(quiet=True)
    return meta.exec_node


def _exec_classes(node, acc=None):
    acc = acc if acc is not None else []
    acc.append(type(node).__name__)
    for c in node.children:
        _exec_classes(c, acc)
    return acc


def _tpch_rows(data_dir, query, conf=None):
    from spark_rapids_tpu.bench.tpch_queries import build_tpch_query
    s = TpuSession(dict(conf or {}))
    df = build_tpch_query(query, s, data_dir)
    plan = _plan_of(df)
    return sorted(df.collect(), key=str), plan


# ---------------------------------------------------------------------------
# correctness: fused == unfused, and the pass is reversible
# ---------------------------------------------------------------------------

@pytest.mark.parametrize("query", _LADDER)
def test_fused_vs_unfused_exact(data_dir, query):
    fused_rows, fused_plan = _tpch_rows(data_dir, query)
    plain_rows, plain_plan = _tpch_rows(
        data_dir, query, {"spark.rapids.sql.fusion.enabled": "false"})
    assert fused_rows == plain_rows
    assert "FusedStageExec" not in _exec_classes(plain_plan)


def test_fusion_changes_and_restores_plan_shape(data_dir):
    """q3's filter/project chain feeding a join build side must fuse,
    and disabling fusion must restore the per-operator chain — the
    premerge shape gate's contract.  q6 (single filter under the
    aggregate) has no run of >=2 and must come out UNTOUCHED: fusion
    never wraps a lone operator."""
    _, fused_plan = _tpch_rows(data_dir, "q3")
    fused_classes = _exec_classes(fused_plan)
    assert "FusedStageExec" in fused_classes
    _, plain_plan = _tpch_rows(
        data_dir, "q3", {"spark.rapids.sql.fusion.enabled": "false"})
    plain_classes = _exec_classes(plain_plan)
    assert "FusedStageExec" not in plain_classes
    # the pass replaces runs, never reorders survivors
    survivors = [c for c in fused_classes if c != "FusedStageExec"]
    assert all(c in plain_classes for c in survivors)
    assert len(plain_classes) > len(fused_classes)

    _, q6_fused = _tpch_rows(data_dir, "q6")
    _, q6_plain = _tpch_rows(
        data_dir, "q6", {"spark.rapids.sql.fusion.enabled": "false"})
    assert _exec_classes(q6_fused) == _exec_classes(q6_plain)
    assert "FusedStageExec" not in _exec_classes(q6_fused)


def test_fused_stage_desc_names_replaced_ops():
    """EXPLAIN ANALYZE annotation: the fused node renders the pipeline
    it replaced."""
    s = TpuSession({})
    schema = T.Schema([T.StructField("a", T.LongType()),
                       T.StructField("b", T.DoubleType())])
    from spark_rapids_tpu.expr.core import col
    df = s.from_pydict({"a": [1, 2, 3, 4], "b": [1., 2., 3., 4.]}, schema)
    q = df.filter(col("a") > 1).select((col("b") * 2).alias("c"))
    plan = _plan_of(q)
    fused = [n for n in _walk(plan) if isinstance(n, FusedStageExec)]
    assert fused, _exec_classes(plan)
    desc = fused[0].node_desc()
    assert "FilterExec" in desc and "ProjectExec" in desc
    assert "2 ops" in desc


def _walk(node):
    yield node
    for c in node.children:
        yield from _walk(c)


def test_min_operators_conf():
    """A lone filter below the threshold is left unfused."""
    s = TpuSession({"spark.rapids.sql.fusion.minOperators": "3"})
    schema = T.Schema([T.StructField("a", T.LongType()),
                       T.StructField("b", T.DoubleType())])
    from spark_rapids_tpu.expr.core import col
    df = s.from_pydict({"a": [1, 2, 3, 4], "b": [1., 2., 3., 4.]}, schema)
    q = df.filter(col("a") > 1).select((col("b") * 2).alias("c"))
    assert "FusedStageExec" not in _exec_classes(_plan_of(q))


# ---------------------------------------------------------------------------
# cache keys
# ---------------------------------------------------------------------------

def _bound_filter_cond(lit):
    from spark_rapids_tpu.expr.core import bind, col
    schema = T.Schema([T.StructField("a", T.LongType())])
    return bind(col("a") > lit, schema)


def test_same_fragment_hits():
    cond = _bound_filter_cond(5)
    k1 = cc.fragment_key("filter", cond)
    k2 = cc.fragment_key("filter", _bound_filter_cond(5))
    assert k1 == k2
    before = get_registry().snapshot()
    j1 = cc.shared_jit(k1, lambda b: b)
    j2 = cc.shared_jit(k2, lambda b: b)
    assert j1 is j2
    moved = get_registry().delta(before)["counters"]
    assert moved.get("fusion_cache_hits", 0) >= 1


def test_changed_literal_misses():
    assert cc.fragment_key("filter", _bound_filter_cond(5)) != \
        cc.fragment_key("filter", _bound_filter_cond(6))


def test_changed_dtype_misses():
    # same repr territory (5 vs 5.0 at least differs; int64 vs int32
    # literal dtype does NOT appear in repr — the fingerprint must see it)
    from spark_rapids_tpu.expr.core import Literal
    a = Literal(5, T.LongType())
    b = Literal(5, T.IntegerType())
    assert cc.fragment_key("lit", a) != cc.fragment_key("lit", b)


def test_changed_schema_misses():
    s1 = T.Schema([T.StructField("a", T.LongType())])
    s2 = T.Schema([T.StructField("a", T.IntegerType())])
    assert cc.fragment_key("project", s1) != cc.fragment_key("project", s2)


def test_like_pattern_in_key():
    """Regression for repr-lossiness: LIKE stores its pattern as a
    non-child attribute, so two conditions with identical reprs must
    still get distinct programs."""
    from spark_rapids_tpu.expr.core import bind, col
    from spark_rapids_tpu.expr.strings import Like
    schema = T.Schema([T.StructField("s", T.StringType())])
    a = bind(Like(col("s"), "%foo%"), schema)
    b = bind(Like(col("s"), "%bar%"), schema)
    assert cc.fragment_key("filter", a) != cc.fragment_key("filter", b)


def test_capacity_bucket_is_signature_level():
    """One python-level wrapper serves every capacity bucket; a NEW
    bucket is a new jax executable and moves compile_count exactly
    once — re-dispatching an old bucket moves nothing."""
    import jax.numpy as jnp
    key = cc.fragment_key("test_capacity_bucket", "x")
    j = cc.shared_jit(key, lambda x: x + 1)
    reg = get_registry()

    def compiles(arr):
        before = reg.snapshot()
        j(arr)
        return reg.delta(before)["counters"].get("compile_count", 0)

    assert compiles(jnp.zeros(8)) == 1       # first bucket
    assert compiles(jnp.zeros(16)) == 1      # new bucket -> one compile
    assert compiles(jnp.zeros(8)) == 0       # old bucket -> pure reuse
    assert compiles(jnp.zeros(16)) == 0
    assert j.signature_count() == 2


def test_fingerprint_orders_and_none():
    """Resolved sort orders (plain objects) and None inputs fingerprint
    structurally, not by repr or identity."""
    assert cc.fingerprint(None) == cc.fingerprint(None)
    assert cc.fingerprint([1, None]) != cc.fingerprint([1, 0])
    assert cc.fingerprint((1, 2)) != cc.fingerprint([1, 2])


def test_opaque_state_never_falsely_shares():
    """Closure state the fingerprint cannot canonicalize (a callable)
    must produce distinct keys per instance — losing sharing is safe,
    sharing wrong programs is not."""
    k1 = cc.fragment_key("udf", lambda x: x + 1)
    k2 = cc.fragment_key("udf", lambda x: x + 2)
    assert k1 != k2


# ---------------------------------------------------------------------------
# second run of the same query compiles nothing
# ---------------------------------------------------------------------------

def test_second_run_zero_new_compiles(data_dir):
    # result cache off: this test pins the COMPILE cache, so the second
    # run must actually reach the executor instead of being served rows
    off = {"spark.rapids.sql.resultCache.enabled": "false"}
    _tpch_rows(data_dir, "q6", off)  # warm
    before = get_registry().snapshot()
    rows, _ = _tpch_rows(data_dir, "q6", off)
    moved = get_registry().delta(before)["counters"]
    assert moved.get("compile_count", 0) == 0, moved
    assert moved.get("fusion_cache_misses", 0) == 0, moved
    assert moved.get("fusion_cache_hits", 0) >= 1, moved
    assert rows


def test_shared_input_disables_donation():
    """One source feeding TWO fused stages (a CTE scanned once, consumed
    twice) must not donate: either stage's donation would delete the
    shared batch's buffers under its sibling.  An exclusive branch keeps
    donating, and the gated plan still returns exact rows."""
    from spark_rapids_tpu.expr.core import col

    s = TpuSession({})
    schema = T.Schema([T.StructField("a", T.LongType()),
                       T.StructField("b", T.DoubleType())])
    n = 200
    base = s.from_pydict(
        {"a": list(range(n)), "b": [float(i) for i in range(n)]}, schema)
    b1 = base.where(col("a") % 2 == 0).select(
        col("a"), (col("b") * 2).alias("c"))
    b2 = base.where(col("a") % 3 == 0).select(
        col("a"), (col("b") + 1).alias("d"))
    ov, meta = b1.join(b2, on="a")._overridden(quiet=True)
    fused = [x for x in _walk(meta.exec_node)
             if isinstance(x, FusedStageExec)]
    assert len(fused) == 2
    assert [f.donate_ok for f in fused] == [False, False]
    assert len({id(f.children[0]) for f in fused}) == 1  # truly shared

    ov2, meta2 = b1._overridden(quiet=True)
    solo = [x for x in _walk(meta2.exec_node)
            if isinstance(x, FusedStageExec)]
    assert len(solo) == 1 and solo[0].donate_ok

    rows = sorted(b1.join(b2, on="a").collect())
    assert rows == [(a, float(a) * 2, a, float(a) + 1)
                    for a in range(0, n, 6)]


# ---------------------------------------------------------------------------
# OOM storm inside a fused stage
# ---------------------------------------------------------------------------

def test_oom_split_and_retry_inside_fused_stage():
    """The storm fires at dispatch BEFORE the fused program consumes
    (donates) the batch, so split-and-retry halves it exactly as in the
    unfused engine — results stay exact and splits are recorded."""
    from spark_rapids_tpu.exec.core import (ExecCtx, _rows_from_host,
                                            collect_host, device_to_host)
    from spark_rapids_tpu.expr.core import col

    s = TpuSession({
        "spark.rapids.test.faults": "memory.oom.until_rows:oom,until_rows=64",
    })
    schema = T.Schema([T.StructField("a", T.LongType()),
                       T.StructField("b", T.DoubleType())])
    n = 500
    df = s.from_pydict(
        {"a": list(range(n)), "b": [float(i) * 0.5 for i in range(n)]},
        schema)
    q = df.filter(col("a") % 3 != 0).select(
        (col("b") * 2).alias("c"), col("a")).filter(col("a") < 400)
    ov, meta = q._overridden(quiet=True)
    assert any(isinstance(x, FusedStageExec) for x in _walk(meta.exec_node))
    with ExecCtx(backend="device", conf=s.conf) as ctx:
        rows = []
        for b in meta.exec_node.execute(ctx):
            rows.extend(_rows_from_host(device_to_host(b)))
        splits = ctx.catalog.metrics["oom_splits"]
    expect = sorted((float(i) * 0.5 * 2, i) for i in range(n)
                    if i % 3 != 0 and i < 400)
    assert sorted(rows) == expect
    assert splits > 0, splits

"""Pod-scale execution: mesh regions, distributed sort, and the
multichip equality gate.

The tentpole contract (ISSUE 7): a plan under
``spark.rapids.tpu.mesh.deviceCount=N`` runs whole pipelines
shard-resident — contiguous scan->filter->project->aggregate/exchange/
sort pipelines compile into ONE per-device ``shard_map`` program
(exec/mesh_region.py), batches cross the device boundary only at region
edges, and results are EXACTLY the single-device plan's.  These tests
pin that contract on the virtual 8-device CPU mesh:

* TPC-H q1/q3/q6/q12/q13/q18 mesh-vs-single equality at deviceCount
  2/4/8 (q13 string-heavy, q18 high-skew);
* q3 under deviceCount=8 moves ZERO ``mesh_gather_fallbacks`` between
  region members and renders MeshRegionExec + counters in EXPLAIN
  ANALYZE;
* compile-cache fragment keys are mesh-shape-aware (mesh-2 and mesh-4
  never share an executable; single-chip keys carry no mesh part);
* a killed mesh slice mid-query recovers to exact rows with exactly
  one stage recompute;
* a bounded [P, C] send buffer that overflows under key skew degrades
  into a counted retry at worst-case capacity — never a truncation.

ISSUE 14 widens the contract: joins are region INTERIOR nodes (q12's
join runs inside one per-device program, replicated-vs-partitioned
counted, zero gather fallbacks), window functions lower to
MeshWindowExec (partitioned and global-ordered, exact at 2/4/8
devices), a slice lost inside a join- or window-bearing region still
recovers to exact rows with one recompute, warm reruns of the new
node kinds compile nothing, and exchange-fed regions chain —
downstream regions consume upstream shards in place
(``mesh_region_chains``), reversible via
``spark.rapids.tpu.mesh.regions.chain.enabled``.
"""
import numpy as np
import pytest

from spark_rapids_tpu import types as T
from spark_rapids_tpu.expr.aggregates import CountStar, Sum
from spark_rapids_tpu.expr.core import col
from spark_rapids_tpu.obs.registry import get_registry
from spark_rapids_tpu.session import TpuSession

MESH8 = {"spark.rapids.tpu.mesh.deviceCount": 8}

SCHEMA = T.Schema([
    T.StructField("k", T.IntegerType(), True),
    T.StructField("g", T.StringType(), True),
    T.StructField("v", T.LongType(), True),
    T.StructField("f", T.DoubleType(), True),
])


def _data(rng, n=400, nkeys=17):
    return {
        "k": rng.integers(0, nkeys, n).astype(np.int32),
        "g": np.array([f"g{int(x) % 5}" for x in rng.integers(0, 50, n)],
                      dtype=object),
        "v": rng.integers(-1000, 1000, n).astype(np.int64),
        "f": rng.normal(size=n),
    }


def _classes(node):
    out = [type(node).__name__]
    for c in node.children:
        out.extend(_classes(c))
    return out


def _walk(node):
    yield node
    for c in node.children:
        yield from _walk(c)


def _executed_plan(df):
    """The REALIZED exec tree (post fusion + region formation) — the
    meta-tree explain() renders the pre-region operators."""
    ov, meta = df._overridden(quiet=True)
    return meta.exec_node


# ---------------------------------------------------------------------------
# TPC-H mesh-vs-single equality gate
# ---------------------------------------------------------------------------

# q1 (wide agg) and q13 (string-heavy) take minutes under the 8-way
# virtual mesh on one physical CPU, so like the 2/4-device rungs they
# run in the full (premerge) suite; the 8-device q3/q6/q12/q18 rungs
# are the tier-1 gate
GATE_QUERIES = (
    pytest.param("q1", marks=pytest.mark.slow),
    "q3", "q6", "q12",
    pytest.param("q13", marks=pytest.mark.slow),
    "q18",
)
DEVICE_COUNTS = (
    pytest.param(2, marks=pytest.mark.slow),
    pytest.param(4, marks=pytest.mark.slow),
    8,
)


@pytest.fixture(scope="module")
def tpch_dir(tmp_path_factory):
    from spark_rapids_tpu.bench.tpch_gen import generate_tpch
    d = str(tmp_path_factory.mktemp("tpch_mesh") / "sf001")
    generate_tpch(d, sf=0.01)
    return d


@pytest.fixture(scope="module")
def single_device_rows(tpch_dir):
    from spark_rapids_tpu.bench.tpch_queries import build_tpch_query
    cache = {}

    def get(query):
        if query not in cache:
            s = TpuSession({})
            cache[query] = build_tpch_query(query, s, tpch_dir).collect()
        return cache[query]
    return get


@pytest.mark.parametrize("devices", DEVICE_COUNTS)
@pytest.mark.parametrize("query", GATE_QUERIES)
def test_tpch_mesh_matches_single_device(tpch_dir, single_device_rows,
                                         query, devices):
    from spark_rapids_tpu.bench.runner import _rows_match
    from spark_rapids_tpu.bench.tpch_queries import build_tpch_query
    s = TpuSession({"spark.rapids.tpu.mesh.deviceCount": devices})
    got = build_tpch_query(query, s, tpch_dir).collect()
    want = single_device_rows(query)
    assert len(got) == len(want), (query, devices, len(got), len(want))
    assert _rows_match(got, want, strict=True), (query, devices)


def test_q3_mesh8_zero_gather_fallbacks(tpch_dir, single_device_rows):
    """Acceptance: full q3 under deviceCount=8 stays region-resident —
    no batch is gathered to the default device between region members,
    verified through the counter EXPLAIN ANALYZE surfaces."""
    from spark_rapids_tpu.bench.runner import _rows_match
    from spark_rapids_tpu.bench.tpch_queries import build_tpch_query
    from spark_rapids_tpu.exec.core import (ExecCtx, _rows_from_host,
                                            device_to_host)
    from spark_rapids_tpu.plan.overrides import explain_analyze
    s = TpuSession(MESH8)
    df = build_tpch_query("q3", s, tpch_dir)
    b0 = get_registry().snapshot()
    plan = _executed_plan(df)
    assert get_registry().delta(b0)["counters"].get("mesh_regions", 0) >= 1
    assert "MeshRegionExec" in _classes(plan)
    b1 = get_registry().snapshot()
    with ExecCtx(backend="device", conf=s.conf) as ctx:
        rows = []
        for b in plan.execute(ctx):
            rows.extend(_rows_from_host(device_to_host(b)))
        analyzed = explain_analyze(plan, ctx)
    delta = get_registry().delta(b1)["counters"]
    assert delta.get("mesh_gather_fallbacks", 0) == 0, delta
    assert "MeshRegionExec" in analyzed
    assert "counters:" in analyzed and "mesh_regions" in analyzed
    # the join strategy decision renders next to the a2a bytes
    assert "mesh_join_replicated" in analyzed or \
        "mesh_join_partitioned" in analyzed, analyzed
    assert _rows_match(rows, single_device_rows("q3"), strict=True)


# ---------------------------------------------------------------------------
# region formation + plan shape
# ---------------------------------------------------------------------------

def test_region_absorbs_filter_into_aggregate(rng):
    s = TpuSession(MESH8)
    df = s.from_pydict(_data(rng), SCHEMA, partitions=4) \
        .where(col("v") > 0).group_by("k") \
        .agg(Sum(col("v")).alias("sv"), CountStar().alias("n"))
    plan = _executed_plan(df)
    names = _classes(plan)
    assert "MeshRegionExec" in names
    # the filter is a region member, not a tree node above the scan
    assert "FilterExec" not in names
    plain = TpuSession({}).from_pydict(_data(rng), SCHEMA, partitions=4)
    region = next(n for n in _walk(plan)
                  if type(n).__name__ == "MeshRegionExec")
    assert "MeshAggregateExec" in region.node_desc()


def test_regions_disabled_keeps_island_shape_and_rows(rng):
    data = _data(rng)
    son = TpuSession(MESH8)
    soff = TpuSession({**MESH8,
                       "spark.rapids.tpu.mesh.regions.enabled": "false"})

    def q(s):
        return s.from_pydict(data, SCHEMA, partitions=4) \
            .where(col("v") > 0).group_by("k") \
            .agg(Sum(col("v")).alias("sv"))

    assert "MeshRegionExec" in _classes(_executed_plan(q(son)))
    off_names = _classes(_executed_plan(q(soff)))
    assert "MeshRegionExec" not in off_names
    assert sorted(q(son).collect()) == sorted(q(soff).collect())


def test_mesh_devicecount_zero_restores_single_chip_plan(rng):
    data = _data(rng)
    plain = TpuSession({}).from_pydict(data, SCHEMA, partitions=4) \
        .where(col("v") > 0).group_by("k").agg(Sum(col("v")).alias("sv")) \
        .order_by(("sv", False)).limit(5)
    zero = TpuSession({"spark.rapids.tpu.mesh.deviceCount": 0}) \
        .from_pydict(data, SCHEMA, partitions=4) \
        .where(col("v") > 0).group_by("k").agg(Sum(col("v")).alias("sv")) \
        .order_by(("sv", False)).limit(5)
    assert _classes(_executed_plan(plain)) == _classes(_executed_plan(zero))
    assert plain.collect() == zero.collect()


# ---------------------------------------------------------------------------
# mesh sort / TopN
# ---------------------------------------------------------------------------

def test_mesh_sort_total_order_matches_plain(rng):
    data = _data(rng)
    sm, sp = TpuSession(MESH8), TpuSession({})
    dfm = sm.from_pydict(data, SCHEMA, partitions=4) \
        .order_by("v", ("k", False), "g")
    dfp = sp.from_pydict(data, SCHEMA, partitions=4) \
        .order_by("v", ("k", False), "g")
    assert "MeshSortExec" in dfm.explain()
    got, want = dfm.collect(), dfp.collect()
    assert got == want and len(got) == 400


@pytest.mark.parametrize("limit", [5, 64, 10_000])
def test_mesh_topn_matches_plain(rng, limit):
    """limit < rows, limit spanning shard boundaries, limit > rows."""
    data = _data(rng)
    sm, sp = TpuSession(MESH8), TpuSession({})

    def q(s):
        return s.from_pydict(data, SCHEMA, partitions=4) \
            .where(col("v") > 0) \
            .order_by(("v", False), "k").limit(limit)

    assert "MeshSortExec" in q(sm).explain()
    assert q(sm).collect() == q(sp).collect()


def test_mesh_topn_output_no_gather(rng):
    """TopN keeps its rows on device 0: serving the limit moves nothing
    across devices."""
    data = _data(rng)
    s = TpuSession(MESH8)
    df = s.from_pydict(data, SCHEMA, partitions=4) \
        .order_by(("v", False)).limit(7)
    b0 = get_registry().snapshot()
    rows = df.collect()
    delta = get_registry().delta(b0)["counters"]
    assert len(rows) == 7
    assert delta.get("mesh_gather_fallbacks", 0) == 0


# ---------------------------------------------------------------------------
# compile cache: mesh-shape-aware fragment keys
# ---------------------------------------------------------------------------

def test_mesh_key_part_distinguishes_mesh_shapes():
    from spark_rapids_tpu.exec import compile_cache as cc
    from spark_rapids_tpu.parallel.mesh import make_mesh
    assert cc.fragment_key("frag", ("x",), cc.mesh_key_part(2, "data")) != \
        cc.fragment_key("frag", ("x",), cc.mesh_key_part(4, "data"))
    m2, m4 = make_mesh(2), make_mesh(4)
    assert cc.mesh_key_part(m2, "data") != cc.mesh_key_part(m4, "data")
    assert cc.fragment_key("frag", cc.mesh_key_part(m2, "data")) != \
        cc.fragment_key("frag", cc.mesh_key_part(m4, "data"))


def test_single_chip_fragment_keys_carry_no_mesh_part(rng):
    """The mesh key component lives ONLY in mesh program keys:
    single-chip fused-stage keys are byte-stable across sessions and
    mesh confs, so this PR cannot fragment the existing cache."""
    from spark_rapids_tpu.exec.fused import FusedStageExec

    def stage(s):
        df = s.from_pydict(_data(rng), SCHEMA, partitions=2) \
            .where(col("v") > 0).select(col("k"), (col("v") * 2).alias("w"))
        plan = _executed_plan(df)
        return next(n for n in _walk(plan)
                    if isinstance(n, FusedStageExec))

    k_plain = stage(TpuSession({}))._stage_key(True)
    k_plain2 = stage(TpuSession({}))._stage_key(True)
    assert k_plain == k_plain2


def test_region_programs_cached_per_mesh_shape(rng):
    """Warm rerun at a FIXED mesh shape compiles nothing; changing the
    mesh shape misses (mesh-2 and mesh-4 must not share executables)."""
    data = _data(rng)

    def run(n):
        s = TpuSession({"spark.rapids.tpu.mesh.deviceCount": n})
        return s.from_pydict(data, SCHEMA, partitions=4) \
            .where(col("v") > 0).group_by("k") \
            .agg(Sum(col("v")).alias("sv")).collect()

    base = run(4)                       # cold at mesh-4
    b0 = get_registry().snapshot()
    assert run(4) == base               # warm at mesh-4
    warm = get_registry().delta(b0)["counters"]
    assert warm.get("compile_count", 0) == 0, warm
    b1 = get_registry().snapshot()
    assert sorted(run(2)) == sorted(base)   # mesh-2: new mesh shape
    cold2 = get_registry().delta(b1)["counters"]
    assert cold2.get("compile_count", 0) >= 1, cold2


# ---------------------------------------------------------------------------
# chaos: lost mesh slice under a region
# ---------------------------------------------------------------------------

def test_region_slice_lost_recovers_exact_once(rng):
    """Kill a mesh slice mid-query: rows must be EXACTLY the plain
    plan's, recovered through exactly one region-level recompute."""
    from spark_rapids_tpu.exec.core import (ExecCtx, _rows_from_host,
                                            device_to_host)
    data = _data(rng)
    s = TpuSession({**MESH8,
                    "spark.rapids.test.faults":
                    "mesh.slice.lost:lost,op=meshregion,times=1"})
    df = s.from_pydict(data, SCHEMA, partitions=4) \
        .where(col("v") > 0).group_by("k") \
        .agg(Sum(col("v")).alias("sv"), CountStar().alias("n"))
    plan = _executed_plan(df)
    assert "MeshRegionExec" in _classes(plan)
    with ExecCtx(backend="device", conf=s.conf) as ctx:
        rows = []
        for b in plan.execute(ctx):
            rows.extend(_rows_from_host(device_to_host(b)))
        metrics = dict(ctx.catalog.metrics)
    assert metrics.get("stage_recomputes", 0) == 1, metrics
    assert metrics.get("recovery_wall_s", 0) > 0
    plain = TpuSession({}).from_pydict(data, SCHEMA, partitions=4) \
        .where(col("v") > 0).group_by("k") \
        .agg(Sum(col("v")).alias("sv"), CountStar().alias("n"))
    assert sorted(rows) == sorted(plain.collect())


# ---------------------------------------------------------------------------
# bounded [P, C] send buffers: overflow degrades, never truncates
# ---------------------------------------------------------------------------

def _skewed(n=300):
    # every row hashes to ONE destination: the worst case for a
    # bounded per-target send buffer
    return {
        "k": np.full(n, 7, np.int32),
        "g": np.array([f"s{i % 3}" for i in range(n)], dtype=object),
        "v": np.arange(n, dtype=np.int64),
        "f": np.linspace(0.0, 1.0, n),
    }


def test_send_capacity_overflow_degrades_into_retry():
    data = _skewed()
    s = TpuSession({**MESH8,
                    "spark.rapids.tpu.mesh.exchange.sendCapacityRows": 4})
    df = s.from_pydict(data, SCHEMA, partitions=4).repartition(8, "k")
    b0 = get_registry().snapshot()
    rows = df.collect()
    delta = get_registry().delta(b0)["counters"]
    assert delta.get("mesh_send_overflows", 0) >= 1, delta
    plain = TpuSession({}).from_pydict(data, SCHEMA, partitions=4).collect()
    assert sorted(rows) == sorted(plain)


def test_send_capacity_default_never_overflows(rng):
    s = TpuSession(MESH8)
    df = s.from_pydict(_skewed(), SCHEMA, partitions=4).repartition(8, "k")
    b0 = get_registry().snapshot()
    rows = df.collect()
    delta = get_registry().delta(b0)["counters"]
    assert delta.get("mesh_send_overflows", 0) == 0, delta
    assert len(rows) == 300


# ---------------------------------------------------------------------------
# split_shards: region boundary batches stay device-resident
# ---------------------------------------------------------------------------

def test_split_shards_keeps_batches_on_their_devices():
    import jax
    from spark_rapids_tpu.exec.basic import LocalScanExec
    from spark_rapids_tpu.exec.core import ExecCtx, device_to_host
    from spark_rapids_tpu.exec.mesh_exec import place_shards
    from spark_rapids_tpu.parallel.mesh import (make_mesh, shard_batches,
                                                split_shards)
    data = {"k": list(range(64)), "s": [f"v{i % 7}" for i in range(64)]}
    schema = T.Schema([T.StructField("k", T.LongType()),
                       T.StructField("s", T.StringType())])
    scan = LocalScanExec.from_pydict(data, schema, 1, 16)
    with ExecCtx(backend="device") as ctx:
        batches = list(scan.partition_iter(ctx, 0))
    mesh = make_mesh(4)
    shards = place_shards(batches, 4)
    out = split_shards(shard_batches(shards, mesh))
    assert len(out) == 4
    devs = []
    for b in out:
        assert b.columns[0].data.committed
        (d,) = b.columns[0].data.devices()
        devs.append(d)
    assert devs == list(mesh.devices.flat)
    got = []
    for b in out:
        hb = device_to_host(b)
        got.extend(zip(*[c.to_list() for c in hb.columns]))
    assert sorted(got) == sorted(zip(data["k"], data["s"]))


# ---------------------------------------------------------------------------
# joins absorbed into regions (ISSUE 14)
# ---------------------------------------------------------------------------

def test_q12_join_runs_inside_region(tpch_dir, single_device_rows):
    """q12's join is a region MEMBER: one per-device program carries
    scan->filter->join->agg, the replicated-vs-partitioned decision is
    counted, and not one batch falls back to a host gather."""
    from spark_rapids_tpu.bench.runner import _rows_match
    from spark_rapids_tpu.bench.tpch_queries import build_tpch_query
    # the join counters fire on EXECUTION: a result-cache hit from an
    # earlier test's identical q12 run would skip the collect entirely
    s = TpuSession({**MESH8, "spark.rapids.sql.resultCache.enabled": False})
    df = build_tpch_query("q12", s, tpch_dir)
    plan = _executed_plan(df)
    regions = [n for n in _walk(plan)
               if type(n).__name__ == "MeshRegionExec"]
    assert any("MeshJoinExec" in r.node_desc() for r in regions), \
        [r.node_desc() for r in regions]
    b0 = get_registry().snapshot()
    rows = df.collect()
    delta = get_registry().delta(b0)["counters"]
    assert delta.get("mesh_gather_fallbacks", 0) == 0, delta
    assert delta.get("mesh_join_replicated", 0) + \
        delta.get("mesh_join_partitioned", 0) >= 1, delta
    assert _rows_match(rows, single_device_rows("q12"), strict=True)


def test_join_region_slice_lost_recovers_exact_once(tpch_dir,
                                                    single_device_rows):
    """Kill a mesh slice inside q12's join-bearing region: exact rows
    through exactly one region-level recompute."""
    from spark_rapids_tpu.bench.runner import _rows_match
    from spark_rapids_tpu.bench.tpch_queries import build_tpch_query
    from spark_rapids_tpu.exec.core import (ExecCtx, _rows_from_host,
                                            device_to_host)
    s = TpuSession({**MESH8,
                    "spark.rapids.test.faults":
                    "mesh.slice.lost:lost,op=meshregion,times=1"})
    df = build_tpch_query("q12", s, tpch_dir)
    plan = _executed_plan(df)
    assert any("MeshJoinExec" in n.node_desc() for n in _walk(plan)
               if type(n).__name__ == "MeshRegionExec")
    with ExecCtx(backend="device", conf=s.conf) as ctx:
        rows = []
        for b in plan.execute(ctx):
            rows.extend(_rows_from_host(device_to_host(b)))
        metrics = dict(ctx.catalog.metrics)
    assert metrics.get("stage_recomputes", 0) == 1, metrics
    assert _rows_match(rows, single_device_rows("q12"), strict=True)


# ---------------------------------------------------------------------------
# windows under the mesh (MeshWindowExec)
# ---------------------------------------------------------------------------

def _window_df(s, data, global_order=False):
    from spark_rapids_tpu.expr.window import (RowNumber, WindowExpression,
                                              WindowSpec)
    spec = WindowSpec((), ((col("v"), True), (col("k"), True))) \
        if global_order else \
        WindowSpec((col("k"),), ((col("v"), True),))
    return s.from_pydict(data, SCHEMA, partitions=4) \
        .select(col("k"), col("v"),
                WindowExpression(Sum(col("v")), spec).alias("rs"),
                WindowExpression(RowNumber(), spec).alias("rn"))


@pytest.mark.parametrize("devices", DEVICE_COUNTS)
@pytest.mark.parametrize("global_order", (False, True),
                         ids=("partitioned", "global_order"))
def test_mesh_window_matches_single_device(rng, devices, global_order):
    data = _data(rng)
    sm = TpuSession({"spark.rapids.tpu.mesh.deviceCount": devices})
    dfm = _window_df(sm, data, global_order)
    plan = _executed_plan(dfm)
    assert any("MeshWindowExec" in n.node_desc() for n in _walk(plan)), \
        _classes(plan)
    got = sorted(dfm.collect())
    want = sorted(_window_df(TpuSession({}), data, global_order).collect())
    assert got == want


def test_window_region_slice_lost_recovers_exact_once(rng):
    """A filter absorbed under a MeshWindowExec terminal forms a region;
    a slice lost inside it recovers to exact rows with one recompute."""
    from spark_rapids_tpu.exec.core import (ExecCtx, _rows_from_host,
                                            device_to_host)
    data = _data(rng)
    s = TpuSession({**MESH8,
                    "spark.rapids.test.faults":
                    "mesh.slice.lost:lost,op=meshregion,times=1"})
    plan = _executed_plan(_windowed_filter(s, data))
    region = next(n for n in _walk(plan)
                  if type(n).__name__ == "MeshRegionExec")
    assert "MeshWindowExec" in region.node_desc()
    with ExecCtx(backend="device", conf=s.conf) as ctx:
        rows = []
        for b in plan.execute(ctx):
            rows.extend(_rows_from_host(device_to_host(b)))
        metrics = dict(ctx.catalog.metrics)
    assert metrics.get("stage_recomputes", 0) == 1, metrics
    want = _windowed_filter(TpuSession({}), data).collect()
    assert sorted(rows) == sorted(want)


def _windowed_filter(s, data):
    from spark_rapids_tpu.expr.window import (WindowExpression, WindowSpec)
    spec = WindowSpec((col("k"),), ((col("v"), True),))
    return s.from_pydict(data, SCHEMA, partitions=4) \
        .where(col("v") > 0) \
        .select(col("k"), col("v"),
                WindowExpression(Sum(col("v")), spec).alias("rs"))


@pytest.mark.slow
def test_standalone_mesh_window_slice_lost_recovers(rng):
    """No region around it: a bare MeshWindowExec's own fallback path
    recovers a lost slice on host with exact rows."""
    data = _data(rng)
    s = TpuSession({**MESH8,
                    "spark.rapids.test.faults":
                    "mesh.slice.lost:lost,op=meshwindow,times=1"})
    got = sorted(_window_df(s, data).collect())
    want = sorted(_window_df(TpuSession({}), data).collect())
    assert got == want


@pytest.mark.slow
def test_join_and_window_regions_warm_rerun_compile_nothing(rng, tpch_dir):
    """Second run of a join-bearing region program and a mesh window at
    the SAME mesh shape compiles nothing: the new node kinds key into
    the process-wide compile cache like every other mesh program."""
    from spark_rapids_tpu.bench.tpch_queries import build_tpch_query
    data = _data(rng)

    def run_both():
        s = TpuSession(MESH8)
        jrows = build_tpch_query("q12", s, tpch_dir).collect()
        wrows = _window_df(TpuSession(MESH8), data).collect()
        return sorted(jrows), sorted(wrows)

    cold = run_both()
    b0 = get_registry().snapshot()
    warm = run_both()
    moved = get_registry().delta(b0)["counters"]
    assert warm == cold
    assert moved.get("compile_count", 0) == 0, \
        f"warm join/window rerun compiled: {moved}"


# ---------------------------------------------------------------------------
# region chaining: exchange-fed regions consume shards in place
# ---------------------------------------------------------------------------

def _chained_q(s, data):
    return s.from_pydict(data, SCHEMA, partitions=4) \
        .where(col("v") != 0) \
        .repartition(8, col("k")) \
        .where(col("v") > 0) \
        .group_by("k").agg(Sum(col("v")).alias("sv"))


def test_region_chaining_consumes_shards_in_place(rng):
    """An exchange-terminal region feeding a downstream region hands
    its per-device shards over without a host gather/re-shard hop."""
    data = _data(rng)
    s = TpuSession(MESH8)
    df = _chained_q(s, data)
    plan = _executed_plan(df)
    assert _classes(plan).count("MeshRegionExec") == 2, _classes(plan)
    b0 = get_registry().snapshot()
    rows = df.collect()
    delta = get_registry().delta(b0)["counters"]
    assert delta.get("mesh_region_chains", 0) >= 1, delta
    assert delta.get("mesh_gather_fallbacks", 0) == 0, delta
    want = _chained_q(TpuSession({}), data).collect()
    assert sorted(rows) == sorted(want)


def test_region_chaining_disabled_same_rows_no_chain(rng):
    data = _data(rng)
    s = TpuSession({**MESH8,
                    "spark.rapids.tpu.mesh.regions.chain.enabled": "false"})
    b0 = get_registry().snapshot()
    rows = _chained_q(s, data).collect()
    delta = get_registry().delta(b0)["counters"]
    assert delta.get("mesh_region_chains", 0) == 0, delta
    want = _chained_q(TpuSession({}), data).collect()
    assert sorted(rows) == sorted(want)

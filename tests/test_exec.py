"""Exec layer tests: differential CPU-oracle vs TPU path.

Mirrors the reference's SparkQueryCompareTestSuite pattern
(tests/.../SparkQueryCompareTestSuite.scala:153-167) and the pytest
integration harness (integration_tests asserts.py:290).
"""
import numpy as np
import pytest

from spark_rapids_tpu import types as T
from spark_rapids_tpu.exec import (CoalesceBatchesExec, FilterExec,
                                   GlobalLimitExec, HashAggregateExec,
                                   LocalLimitExec, LocalScanExec, ProjectExec,
                                   RangeExec, RequireSingleBatch, SortExec,
                                   TargetSize, UnionExec, collect_device,
                                   collect_host)
from spark_rapids_tpu.expr.aggregates import (Average, Count, CountStar, Max,
                                              Min, Sum)
from spark_rapids_tpu.expr.core import col, lit
from spark_rapids_tpu.testing import assert_tpu_and_cpu_equal


def _scan(rng, n=100, parts=1, rows_per_batch=None, with_nulls=True):
    def nullify(vals, frac=0.15):
        if not with_nulls:
            return list(vals)
        mask = rng.random(len(vals)) < frac
        return [None if m else v for v, m in zip(vals, mask)]

    schema = T.Schema([
        T.StructField("i32", T.IntegerType()),
        T.StructField("i64", T.LongType()),
        T.StructField("f64", T.DoubleType()),
        T.StructField("s", T.StringType()),
        T.StructField("k", T.IntegerType()),
    ])
    data = {
        "i32": nullify(rng.integers(-100, 100, n).tolist()),
        "i64": nullify(rng.integers(-10**9, 10**9, n).tolist()),
        "f64": nullify((rng.random(n) * 200 - 100).tolist()),
        "s": nullify([f"str_{v}" for v in rng.integers(0, 30, n)]),
        "k": nullify(rng.integers(0, 8, n).tolist()),
    }
    return LocalScanExec.from_pydict(data, schema, partitions=parts,
                                     rows_per_batch=rows_per_batch)


def test_project_filter(rng):
    scan = _scan(rng, 200, rows_per_batch=64)
    plan = ProjectExec(
        [(col("i32") + col("k")).alias("a"),
         (col("f64") * 2.0).alias("b"),
         col("s")],
        FilterExec(col("i32") > lit(0), scan))
    assert_tpu_and_cpu_equal(plan)


def test_filter_all_and_none(rng):
    scan = _scan(rng, 50)
    assert_tpu_and_cpu_equal(FilterExec(col("i32") > lit(-1000), scan))
    assert collect_device(FilterExec(col("i32") > lit(10**6), scan)) == []


def test_range():
    plan = RangeExec(0, 1000, 3, partitions=4, rows_per_batch=128)
    rows = collect_host(plan)
    assert [r[0] for r in rows] == list(range(0, 1000, 3))
    assert_tpu_and_cpu_equal(plan, ignore_order=False)


def test_union(rng):
    a, b = _scan(rng, 40), _scan(rng, 25)
    assert_tpu_and_cpu_equal(UnionExec([a, b]))


def test_limits(rng):
    scan = _scan(rng, 100, parts=2, rows_per_batch=16)
    assert len(collect_device(LocalLimitExec(10, scan))) == 20  # per partition
    assert len(collect_device(GlobalLimitExec(13, scan))) == 13
    assert_tpu_and_cpu_equal(GlobalLimitExec(13, scan))


def test_coalesce_batches(rng):
    scan = _scan(rng, 300, rows_per_batch=10)
    plan = CoalesceBatchesExec(TargetSize(1 << 14), scan)
    assert_tpu_and_cpu_equal(plan)
    single = CoalesceBatchesExec(RequireSingleBatch, scan)
    assert_tpu_and_cpu_equal(single)


@pytest.mark.parametrize("rows_per_batch", [None, 37])
def test_groupby_aggregate(rng, rows_per_batch):
    scan = _scan(rng, 200, rows_per_batch=rows_per_batch)
    plan = HashAggregateExec(
        [col("k")],
        [col("k"),
         Sum(col("i32")).alias("sum_i32"),
         Count(col("f64")).alias("cnt_f64"),
         CountStar().alias("cnt"),
         Min(col("i64")).alias("min_i64"),
         Max(col("f64")).alias("max_f64"),
         Average(col("i32")).alias("avg_i32")],
        scan)
    assert_tpu_and_cpu_equal(plan)


def test_grand_aggregate(rng):
    scan = _scan(rng, 150, rows_per_batch=40)
    plan = HashAggregateExec(
        [],
        [Sum(col("f64")).alias("s"), CountStar().alias("c"),
         Average(col("i64")).alias("a")],
        scan)
    rows = assert_tpu_and_cpu_equal(plan)
    assert len(rows) == 1


def test_grand_aggregate_empty_input(rng):
    scan = _scan(rng, 20)
    empty = FilterExec(col("i32") > lit(10**6), scan)
    plan = HashAggregateExec(
        [], [Sum(col("i32")).alias("s"), CountStar().alias("c")], empty)
    rows = assert_tpu_and_cpu_equal(plan)
    assert rows == [(None, 0)]


def test_agg_expression_over_aggs(rng):
    scan = _scan(rng, 120, rows_per_batch=50)
    plan = HashAggregateExec(
        [col("k")],
        [col("k"),
         (Sum(col("i32")) + CountStar()).alias("mix"),
         (Sum(col("f64")) / CountStar()).alias("manual_avg")],
        scan)
    assert_tpu_and_cpu_equal(plan)


def test_partial_final_split(rng):
    """partial -> final reproduces complete-mode results (the exchange
    seam used by distributed aggregation)."""
    scan = _scan(rng, 200, rows_per_batch=29)
    results = [col("k"), Sum(col("i32")).alias("s"), CountStar().alias("c"),
               Average(col("f64")).alias("a")]
    complete = HashAggregateExec([col("k")], results, scan)
    partial = HashAggregateExec([col("k")], results, scan, mode="partial")
    final = HashAggregateExec.final_from_partial(partial, partial)
    from spark_rapids_tpu.testing import _sort_key
    cpu_c = sorted(collect_host(complete), key=_sort_key)
    cpu_s = sorted(collect_host(final), key=_sort_key)
    assert cpu_c == cpu_s
    assert_tpu_and_cpu_equal(final)


def test_sort(rng):
    scan = _scan(rng, 150, rows_per_batch=41)
    plan = SortExec([("k", True), ("i32", False), ("s", True)], scan,
                    global_sort=True)
    assert_tpu_and_cpu_equal(plan, ignore_order=False)


def test_sort_nulls_and_nans(rng):
    schema = T.Schema([T.StructField("x", T.DoubleType())])
    vals = [1.0, None, float("nan"), -0.0, 0.0, float("inf"),
            float("-inf"), None, 2.5, float("nan")]
    scan = LocalScanExec.from_pydict({"x": vals}, schema)
    for asc in (True, False):
        plan = SortExec([("x", asc)], scan, global_sort=True)
        assert_tpu_and_cpu_equal(plan, ignore_order=False)


def test_string_groupby(rng):
    scan = _scan(rng, 100, rows_per_batch=33)
    plan = HashAggregateExec(
        [col("s")], [col("s"), CountStar().alias("c"),
                     Sum(col("i32")).alias("si")], scan)
    assert_tpu_and_cpu_equal(plan)


def test_multi_key_groupby(rng):
    scan = _scan(rng, 200, rows_per_batch=67)
    plan = HashAggregateExec(
        [col("k"), col("s")],
        [col("k"), col("s"), CountStar().alias("c"),
         Max(col("i64")).alias("m")],
        scan)
    assert_tpu_and_cpu_equal(plan)


def test_groupby_float_key_zero_and_null():
    """Regression: host oracle must not merge 0.0 with null groups."""
    schema = T.Schema([T.StructField("x", T.DoubleType())])
    scan = LocalScanExec.from_pydict({"x": [0.0, None, -0.0, 1.5, None]},
                                     schema)
    plan = HashAggregateExec([col("x")], [col("x"), CountStar().alias("c")],
                             scan)
    rows = assert_tpu_and_cpu_equal(plan)
    assert sorted(rows, key=lambda r: (r[0] is None, r[0])) == \
        [(0.0, 2), (1.5, 1), (None, 2)]


def test_complete_agg_multi_partition(rng):
    """Regression: complete-mode agg collapses multi-partition input."""
    scan = _scan(rng, 100, parts=4, rows_per_batch=10)
    plan = HashAggregateExec([], [CountStar().alias("c")], scan)
    rows = assert_tpu_and_cpu_equal(plan)
    assert rows == [(100,)]


def test_coalesce_goal_insertion(rng):
    """The planner inserts CoalesceBatchesExec per children_coalesce_goal
    (reference GpuTransitionOverrides.insertCoalesce :224-244): an
    aggregation over many small scan batches sees batched input."""
    from spark_rapids_tpu.session import TpuSession
    from spark_rapids_tpu.expr.aggregates import Sum

    s = TpuSession({})
    schema = T.Schema([T.StructField("k", T.IntegerType()),
                       T.StructField("v", T.LongType())])
    df = s.from_pydict(
        {"k": [int(x) for x in rng.integers(0, 5, 200)],
         "v": list(range(200))}, schema, partitions=1, rows_per_batch=10)
    out = df.group_by("k").agg(Sum(col("v")).alias("sv"))
    plan = out.explain()
    assert "CoalesceBatchesExec" in plan
    dev = sorted(out.collect())
    ov, meta = out._overridden(quiet=True)
    from spark_rapids_tpu.exec.core import collect_host as _ch
    assert dev == sorted(_ch(meta.exec_node, s.conf))


def test_global_sort_total_order_across_partitions(rng):
    """order_by establishes a TOTAL order even over multi-partition
    input (SF1 regression: per-partition sort + partition-ordered limit
    returned the wrong top-k when the child kept join partitioning)."""
    from spark_rapids_tpu.session import TpuSession
    from spark_rapids_tpu.exec.core import collect_host as _ch

    s = TpuSession({"spark.sql.shuffle.partitions": 5})
    schema = T.Schema([T.StructField("k", T.IntegerType()),
                       T.StructField("s", T.StringType())])
    n = 500
    df = s.from_pydict(
        {"k": [int(x) for x in rng.integers(0, 1000, n)],
         "s": [None if i % 7 == 0 else f"s{i%13}" for i in range(n)]},
        schema, partitions=4, rows_per_batch=32)
    out = df.order_by(("s", True), ("k", True)).limit(20)
    dev = out.collect()
    ov, meta = out._overridden(quiet=True)
    host = _ch(meta.exec_node, s.conf)
    assert dev == host                       # ordered compare, not a set
    # the global top-20 by (s asc nulls-first, k asc), from all rows
    allr = sorted(df.collect(),
                  key=lambda r: (r[1] is not None, r[1] or "", r[0]))
    assert dev == allr[:20]

"""Round-3 expression breadth: datetime, null-ops, regexp, string
functions, partition-aware ids, ANSI cast.

Style: differential device-vs-host per family (reference
SparkQueryCompareTestSuite / integration_tests per-op files).
"""
import datetime as dt

import numpy as np
import pytest

from spark_rapids_tpu import types as T
from spark_rapids_tpu.exec.core import collect_host
from spark_rapids_tpu.expr.core import col, lit
from spark_rapids_tpu.session import TpuSession


def _both(df):
    dev = sorted(df.collect(), key=str)
    ov, meta = df._overridden(quiet=True)
    host = sorted(collect_host(meta.exec_node, df._s.conf), key=str)
    return dev, host


def _assert_same(df, approx=False):
    dev, host = _both(df)
    assert len(dev) == len(host)
    if not approx:
        assert dev == host, (dev[:5], host[:5])
        return dev
    for d, h in zip(dev, host):
        for x, y in zip(d, h):
            if isinstance(x, float) and y is not None:
                assert x == pytest.approx(y, rel=1e-9, abs=1e-9,
                                         nan_ok=True)
            else:
                assert x == y
    return dev


@pytest.fixture
def dates_df():
    s = TpuSession({})
    base = dt.date(1970, 1, 1)
    days = [0, 59, 365, 10957, 11016, 18993, -400, 19724]  # incl. leap areas
    micros = [d * 86_400_000_000 + 3_723_000_001 for d in days]
    schema = T.Schema([T.StructField("d", T.DateType()),
                       T.StructField("ts", T.TimestampType()),
                       T.StructField("n", T.IntegerType())])
    return s.from_pydict({"d": days, "ts": micros,
                          "n": [1, -1, 13, 0, -25, 6, 2, None]}, schema), days


def test_add_months_last_day_next_day_trunc(dates_df):
    from spark_rapids_tpu.expr.datetime_ops import (AddMonths, LastDay,
                                                    NextDay, TruncDate)
    df, days = dates_df
    out = df.select(
        AddMonths(col("d"), col("n")).alias("am"),
        LastDay(col("d")).alias("ld"),
        NextDay(col("d"), "Mon").alias("nd"),
        TruncDate(col("d"), "month").alias("tm"),
        TruncDate(col("d"), "year").alias("ty"),
        TruncDate(col("d"), "week").alias("tw"),
        TruncDate(col("d"), "quarter").alias("tq"))
    rows = _assert_same(out)
    # spot-check vs python dateutil-style math
    base = dt.date(1970, 1, 1)
    got = dict()
    for r in rows:
        got[r[1]] = r
    ld = base + dt.timedelta(days=days[1])          # 1970-03-01
    # last_day(1970-03-01) = 1970-03-31
    assert any(r[1] == dt.date(1970, 3, 31) for r in rows)


def test_weekofyear_months_between(dates_df):
    from spark_rapids_tpu.expr.datetime_ops import MonthsBetween, WeekOfYear
    df, days = dates_df
    out = df.select(WeekOfYear(col("d")).alias("w"),
                    MonthsBetween(col("ts"), lit(0).cast(
                        T.TimestampType())).alias("mb"))
    _assert_same(out, approx=True)
    # ISO week sanity: 1970-01-01 is a Thursday -> week 1
    rows = df.select(col("d"), WeekOfYear(col("d")).alias("w")).collect()
    w = {r[0]: r[1] for r in rows}
    assert w[dt.date(1970, 1, 1)] == 1
    assert w[dt.date(2022, 1, 1)] == 52  # 2022-01-01 is ISO week 52 of 2021


def test_unix_timestamp_from_unixtime_date_format(dates_df):
    from spark_rapids_tpu.expr.datetime_ops import (DateFormatClass,
                                                    FromUnixTime,
                                                    UnixTimestamp)
    df, _ = dates_df
    out = df.select(UnixTimestamp(col("ts")).alias("ut"),
                    UnixTimestamp(col("d")).alias("ud"))
    _assert_same(out)
    # host-only formatting must fall back and agree with strftime
    out2 = df.select(FromUnixTime(UnixTimestamp(col("ts"))).alias("f"),
                     DateFormatClass(col("ts"), "yyyy-MM-dd").alias("g"))
    assert "!" in out2.explain()  # host fallback visible in explain
    rows = out2.collect()
    assert all(len(r[0]) == 19 and r[1][4] == "-" for r in rows)


def test_null_ops():
    from spark_rapids_tpu.expr.null_ops import (IsNaN, NaNvl, NullIf, Nvl,
                                                Nvl2)
    s = TpuSession({})
    schema = T.Schema([T.StructField("x", T.DoubleType()),
                       T.StructField("y", T.DoubleType())])
    df = s.from_pydict({"x": [1.0, float("nan"), None, 0.0],
                        "y": [9.0, 8.0, 7.0, None]}, schema)
    out = df.select(IsNaN(col("x")).alias("isnan"),
                    NaNvl(col("x"), col("y")).alias("nanvl"),
                    Nvl(col("x"), col("y")).alias("nvl"),
                    Nvl2(col("x"), col("y"), lit(-1.0)).alias("nvl2"),
                    NullIf(col("x"), col("y")).alias("nullif"))
    dev = _assert_same(out, approx=True)
    m = {tuple(r) for r in dev}
    assert (False, 1.0, 1.0, 9.0, 1.0) in m          # plain value
    assert any(r[0] is True and r[1] == 8.0 for r in dev)   # NaN row
    assert any(r[2] == 7.0 and r[3] == -1.0 for r in dev)   # null x


def test_regexp_family_host_fallback():
    from spark_rapids_tpu.expr.regexp import (RegExpExtract, RegExpReplace,
                                              RLike)
    s = TpuSession({})
    schema = T.Schema([T.StructField("s", T.StringType())])
    df = s.from_pydict(
        {"s": ["abc123", "no digits", None, "x9y8", "123"]}, schema)
    out = df.select(RLike(col("s"), r"\d+").alias("rl"),
                    RegExpReplace(col("s"), r"\d+", "#").alias("rr"),
                    RegExpExtract(col("s"), r"([a-z]+)(\d+)", 2).alias("re"))
    assert "!" in out.explain()
    dev = _assert_same(out)
    m = sorted(r for r in dev if r[0] is not None)
    assert (True, "abc#", "123") in dev
    assert (False, "no digits", "") in dev


def test_string_breadth_device():
    from spark_rapids_tpu.expr.strings import (ConcatWs, StringLocate,
                                               SubstringIndex)
    s = TpuSession({})
    schema = T.Schema([T.StructField("a", T.StringType()),
                       T.StructField("b", T.StringType())])
    df = s.from_pydict({"a": ["www.spark.org", "nodots", None, "a.b",
                              "", "ünï.codé"],
                        "b": ["x", None, "y", "zz", "w", "q"]}, schema)
    out = df.select(
        SubstringIndex(col("a"), ".", 2).alias("si2"),
        SubstringIndex(col("a"), ".", -2).alias("sim2"),
        SubstringIndex(col("a"), ".", 0).alias("si0"),
        ConcatWs("-", col("a"), col("b")).alias("cw"),
        StringLocate(lit("."), col("a")).alias("loc"),
        StringLocate(lit("."), col("a"), 5).alias("loc5"),
        StringLocate(lit(""), col("a")).alias("locE"))
    dev = _assert_same(out)
    m = {r[3]: r for r in dev}
    assert m["www.spark.org-x"][0] == "www.spark"
    assert m["www.spark.org-x"][1] == "spark.org"
    assert m["www.spark.org-x"][2] == ""
    assert m["www.spark.org-x"][4] == 4
    assert m["www.spark.org-x"][5] == 10
    assert m["y"][4] is None          # null a propagates through locate
    assert m["nodots"] is not None    # concat_ws skips the null b


def test_string_breadth_host_only():
    from spark_rapids_tpu.expr.strings import (InitCap, StringLPad,
                                               StringRepeat, StringRPad)
    s = TpuSession({})
    schema = T.Schema([T.StructField("a", T.StringType()),
                       T.StructField("n", T.IntegerType())])
    df = s.from_pydict({"a": ["hello world", "ABC", None],
                        "n": [2, 3, 1]}, schema)
    out = df.select(InitCap(col("a")).alias("ic"),
                    StringLPad(col("a"), 6, "*").alias("lp"),
                    StringRPad(col("a"), 6, "*").alias("rp"),
                    StringRepeat(col("a"), col("n")).alias("rep"))
    assert "!" in out.explain()
    dev = _assert_same(out)
    assert ("Abc", "***ABC", "ABC***", "ABCABCABC") in dev
    assert ("Hello World", "hello ", "hello ", "hello worldhello world") in dev


def test_partition_aware_ids():
    from spark_rapids_tpu.expr.misc import (MonotonicallyIncreasingID,
                                            SparkPartitionID)
    s = TpuSession({})
    schema = T.Schema([T.StructField("x", T.IntegerType())])
    df = s.from_pydict({"x": list(range(100))}, schema, partitions=4,
                       rows_per_batch=10)
    out = df.select(col("x"), MonotonicallyIncreasingID().alias("id"),
                    SparkPartitionID().alias("pid"))
    dev, host = _both(out)
    assert dev == host
    ids = [r[1] for r in dev]
    assert len(set(ids)) == 100                     # unique
    pids = {r[2] for r in dev}
    assert pids == {0, 1, 2, 3}
    # monotonic within each partition
    by_pid = {}
    for r in sorted(dev, key=lambda r: r[1]):
        by_pid.setdefault(r[2], []).append(r[1])
    for seq in by_pid.values():
        assert seq == sorted(seq)
        assert seq[0] >> 33 in {0, 1, 2, 3}


def test_ansi_cast():
    from spark_rapids_tpu.expr.cast import AnsiCast, Cast
    s = TpuSession({})
    schema = T.Schema([T.StructField("x", T.DoubleType()),
                       T.StructField("s", T.StringType())])
    df = s.from_pydict({"x": [1.5, 3.0e10], "s": ["12", "34"]}, schema)
    ok = df.select(AnsiCast(col("x"), T.LongType()).alias("l"),
                   AnsiCast(col("s"), T.IntegerType()).alias("i"))
    assert "!" in ok.explain()   # ansi casts are host-only
    assert sorted(ok.collect()) == [(1, 12), (30000000000, 34)]
    bad = df.select(AnsiCast(col("x"), T.IntegerType()).alias("i"))
    with pytest.raises(ArithmeticError):
        bad.collect()
    bad2 = s.from_pydict({"x": [1.0], "s": ["oops"]}, schema) \
        .select(AnsiCast(col("s"), T.IntegerType()).alias("i"))
    with pytest.raises(ValueError):
        bad2.collect()
    # non-ansi cast keeps wraparound/null semantics
    assert df.select(Cast(col("s"), T.IntegerType()).alias("i")) \
        .collect() is not None


def test_registry_size():
    """The round-3 target: >=120 registered expression classes."""
    import importlib
    import inspect
    from spark_rapids_tpu.expr.core import Expression
    count = 0
    for mod in ["core", "arithmetic", "predicates", "strings",
                "datetime_ops", "math_ops", "conditional", "cast",
                "hashing", "aggregates", "window", "null_ops", "regexp",
                "misc"]:
        m = importlib.import_module(f"spark_rapids_tpu.expr.{mod}")
        for n, c in vars(m).items():
            if inspect.isclass(c) and issubclass(c, Expression) \
                    and c.__module__ == m.__name__ and not n.startswith("_"):
                count += 1
    assert count >= 120, count


def test_partition_aware_rejected_outside_projection():
    from spark_rapids_tpu.expr.misc import SparkPartitionID
    s = TpuSession({})
    schema = T.Schema([T.StructField("x", T.IntegerType())])
    df = s.from_pydict({"x": [1, 2, 3]}, schema)
    with pytest.raises(ValueError, match="select"):
        df.where(SparkPartitionID() == lit(0)).collect()


def test_lpad_negative_length():
    from spark_rapids_tpu.expr.strings import StringLPad
    s = TpuSession({})
    schema = T.Schema([T.StructField("a", T.StringType())])
    df = s.from_pydict({"a": ["abc"]}, schema)
    rows = df.select(StringLPad(col("a"), -1, "*").alias("p")).collect()
    assert rows == [("",)]  # Spark: negative pad length -> empty string

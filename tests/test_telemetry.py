"""Cluster-wide telemetry: latency histograms, the live HTTP endpoint,
merged worker traces, and the persistent query history log.

Covers the ISSUE-15 observability plane end to end at unit scale:
histogram merge across cluster worker snapshot deltas (a dead worker's
last snapshot still counts; an empty delta is inert), the 127.0.0.1
telemetry server's three routes, history-log rotation + torn-line
tolerance + CI-schema conformance, and the cross-process trace lane
machinery (stamp_for_shipping -> ingest_wall -> one export).
"""
import json
import os
import socket
import sys
import threading
import urllib.request

import pytest

from spark_rapids_tpu.conf import TpuConf
from spark_rapids_tpu.obs.registry import (MetricsRegistry,
                                           delta_histogram_snapshot,
                                           empty_histogram_snapshot,
                                           histogram_percentile,
                                           merge_histogram_snapshots)

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", "scripts"))
from validate_obs import load_schema, validate  # noqa: E402

sys.path.pop(0)


# ---------------------------------------------------------------------------
# histogram semantics
# ---------------------------------------------------------------------------

def _observe_all(reg, name, values):
    for v in values:
        reg.observe(name, v)


def test_histogram_percentiles_monotone_and_bounded():
    reg = MetricsRegistry()
    values = [0.0005, 0.003, 0.01, 0.05, 0.2, 0.2, 1.5, 7.0]
    _observe_all(reg, "h", values)
    snap = reg.snapshot()["histograms"]["h"]
    assert snap["count"] == len(values)
    assert snap["sum"] == pytest.approx(sum(values))
    ps = [histogram_percentile(snap, q) for q in (1, 25, 50, 75, 95, 99)]
    assert ps == sorted(ps), "percentiles must be non-decreasing"
    assert ps[0] >= 0.0
    # p99 of values all <= 7.0 must not exceed the containing bucket
    assert ps[-1] <= max(snap["le"]) * 2


def test_histogram_merge_equals_union():
    a, b = MetricsRegistry(), MetricsRegistry()
    u = MetricsRegistry()
    va = [0.001, 0.02, 0.4, 3.0]
    vb = [0.005, 0.005, 1.0]
    _observe_all(a, "h", va)
    _observe_all(b, "h", vb)
    _observe_all(u, "h", va + vb)
    merged = merge_histogram_snapshots(
        a.snapshot()["histograms"]["h"], b.snapshot()["histograms"]["h"])
    union = u.snapshot()["histograms"]["h"]
    assert merged["counts"] == union["counts"]
    assert merged["count"] == union["count"]
    assert merged["sum"] == pytest.approx(union["sum"])
    for q in (50, 95, 99):
        assert histogram_percentile(merged, q) == pytest.approx(
            histogram_percentile(union, q))


def test_histogram_delta_none_when_unmoved():
    reg = MetricsRegistry()
    _observe_all(reg, "h", [0.1, 0.2])
    snap = reg.snapshot()["histograms"]["h"]
    assert delta_histogram_snapshot(snap, snap) is None
    # vs a None/empty baseline the whole snapshot is the delta
    d = delta_histogram_snapshot(snap, None)
    assert d is not None and d["count"] == 2


def test_histogram_merge_across_worker_snapshot_deltas():
    """The driver-side cluster merge: each worker ships registry
    snapshots on heartbeats; the cluster-wide distribution is the merge
    of per-worker (current - baseline) deltas.  A worker that died
    mid-run still contributes its last shipped snapshot, and the merged
    percentiles stay monotone; a worker whose histogram never moved
    contributes nothing."""
    from spark_rapids_tpu.cluster.driver import ClusterDriver, WorkerHandle

    def handle(wid, alive, baseline, current):
        h = WorkerHandle.__new__(WorkerHandle)
        h.worker_id, h.alive = wid, alive
        h.baseline = {"histograms": baseline}
        h.metrics = {"histograms": current}
        return h

    r0, r1 = MetricsRegistry(), MetricsRegistry()
    _observe_all(r0, "query.wall_seconds", [0.01, 0.05, 0.2])
    base0 = r0.snapshot()["histograms"]
    _observe_all(r0, "query.wall_seconds", [0.5, 2.0])
    cur0 = r0.snapshot()["histograms"]
    _observe_all(r1, "query.wall_seconds", [0.002, 0.004])
    cur1 = r1.snapshot()["histograms"]

    class _Fake:
        def workers(self):
            return self._h

    fake = _Fake()
    # w0 alive with movement since baseline; w1 DEAD after shipping its
    # only snapshot (baseline empty); w2 alive but inert (cur == base)
    fake._h = [
        handle("w0", True, base0, cur0),
        handle("w1", False, {}, cur1),
        handle("w2", True, cur1, cur1),
    ]
    merged = ClusterDriver.merged_worker_histograms(fake)
    h = merged["query.wall_seconds"]
    # w0 delta (2 observations) + w1 full snapshot (2) = 4; w2 inert
    assert h["count"] == 4
    ps = [histogram_percentile(h, q) for q in (50, 90, 95, 99)]
    assert ps == sorted(ps)
    assert ps[0] > 0

    # dropping the dead worker entirely only removes ITS observations
    fake._h = fake._h[:1]
    alone = ClusterDriver.merged_worker_histograms(fake)
    assert alone["query.wall_seconds"]["count"] == 2

    # all-inert cluster merges to nothing at all
    fake._h = [handle("w2", True, cur1, cur1)]
    assert ClusterDriver.merged_worker_histograms(fake) == {}


def test_histogram_snapshot_matches_ci_schema():
    reg = MetricsRegistry()
    _observe_all(reg, "h", [0.1])
    snap = reg.snapshot()["histograms"]["h"]
    assert validate(snap, load_schema("histogram")) == []
    assert validate(empty_histogram_snapshot(),
                    load_schema("histogram")) == []


def test_prometheus_histogram_exposition_cumulative():
    reg = MetricsRegistry()
    _observe_all(reg, "query.wall_seconds", [0.001, 0.02, 0.5, 3.0])
    text = reg.to_prometheus()
    assert "# TYPE srt_query_wall_seconds histogram" in text
    bucket_lines = [ln for ln in text.splitlines()
                    if ln.startswith("srt_query_wall_seconds_bucket")]
    assert bucket_lines, "no _bucket series"
    counts = [float(ln.rsplit(" ", 1)[1]) for ln in bucket_lines]
    assert counts == sorted(counts), "buckets must be cumulative"
    assert bucket_lines[-1].split("{")[1].startswith('le="+Inf"')
    assert counts[-1] == 4
    assert "srt_query_wall_seconds_sum" in text
    assert "srt_query_wall_seconds_count 4" in text


# ---------------------------------------------------------------------------
# live HTTP endpoint
# ---------------------------------------------------------------------------

@pytest.fixture
def http_session():
    from spark_rapids_tpu.session import TpuSession
    s = TpuSession({})
    yield s
    s.shutdown()


def _get(url):
    with urllib.request.urlopen(url, timeout=5) as r:
        return r.status, dict(r.headers), r.read()


def test_http_endpoint_routes(http_session):
    from spark_rapids_tpu.obs.http import ObsHttpServer
    from spark_rapids_tpu.obs.registry import get_registry
    get_registry().observe("query.wall_seconds", 0.01)
    srv = ObsHttpServer(http_session, 0)   # ephemeral port
    try:
        assert srv.address.startswith("http://127.0.0.1:")
        st, hdrs, body = _get(srv.address + "/metrics")
        assert st == 200
        assert hdrs["Content-Type"].startswith("text/plain")
        assert b"# TYPE srt_query_wall_seconds histogram" in body
        assert b"srt_query_wall_seconds_bucket" in body

        st, _, body = _get(srv.address + "/healthz")
        assert st == 200
        health = json.loads(body)
        assert health["status"] == "ok"
        assert "admission" in health

        st, _, body = _get(srv.address + "/queries")
        assert st == 200
        q = json.loads(body)
        assert q["count"] == 0 and q["active"] == {}

        with pytest.raises(urllib.error.HTTPError) as ei:
            _get(srv.address + "/nope")
        assert ei.value.code == 404
    finally:
        srv.close()
    # port is actually released (TIME_WAIT from the scrape connections
    # is fine — REUSEADDR is exactly what a restarting server would use)
    with socket.socket() as s:
        s.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        s.bind(("127.0.0.1", srv.port))


def test_http_healthz_drains_on_shutdown(http_session):
    from spark_rapids_tpu.obs.http import ObsHttpServer
    srv = ObsHttpServer(http_session, 0)
    try:
        http_session._admission_controller().begin_shutdown()
        with pytest.raises(urllib.error.HTTPError) as ei:
            _get(srv.address + "/healthz")
        assert ei.value.code == 503
        assert json.loads(ei.value.read())["status"] == "draining"
    finally:
        srv.close()


def test_http_metrics_scrape_concurrent_with_observations(http_session):
    """Scrapes racing observers must never 500 or return torn text."""
    from spark_rapids_tpu.obs.http import ObsHttpServer
    from spark_rapids_tpu.obs.registry import get_registry
    srv = ObsHttpServer(http_session, 0)
    stop = threading.Event()

    def pound():
        reg = get_registry()
        i = 0
        while not stop.is_set():
            reg.observe("query.wall_seconds", 0.001 * (i % 50 + 1))
            reg.inc("queries_executed")
            i += 1

    t = threading.Thread(target=pound, daemon=True)
    t.start()
    try:
        for _ in range(20):
            st, _, body = _get(srv.address + "/metrics")
            assert st == 200
            text = body.decode()
            for ln in text.splitlines():
                if ln and not ln.startswith("#"):
                    float(ln.rsplit(" ", 1)[1])   # every sample parses
    finally:
        stop.set()
        t.join(timeout=5)
        srv.close()


def test_session_conf_port_zero_means_no_server():
    from spark_rapids_tpu.session import TpuSession
    s = TpuSession({})
    try:
        assert s._http is None
    finally:
        s.shutdown()


def test_session_conf_port_starts_and_stops_server():
    from spark_rapids_tpu.session import TpuSession
    s = TpuSession({"spark.rapids.obs.http.port": "0"})
    try:
        # "0" is falsy-as-int: still off — only a real port starts it
        assert s._http is None
    finally:
        s.shutdown()
    s = TpuSession({"spark.rapids.obs.http.port": _free_port()})
    try:
        assert s._http is not None
        st, _, _ = _get(s._http.address + "/healthz")
        assert st == 200
        addr = s._http.address
    finally:
        s.shutdown()
    assert s._http is None
    with pytest.raises(OSError):
        _get(addr + "/healthz")


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


# ---------------------------------------------------------------------------
# query history log
# ---------------------------------------------------------------------------

def test_history_log_rotation_keeps_newest(tmp_path):
    from spark_rapids_tpu.obs.history import QueryHistoryLog, read_entries
    log = QueryHistoryLog(str(tmp_path), max_entries=5)
    for i in range(12):
        log.append({"kind": "history", "query_id": f"q{i}"})
    entries = read_entries(log.path)
    assert len(entries) == 5
    assert [e["query_id"] for e in entries] == [f"q{i}" for i in
                                               range(7, 12)]
    # no stray temp file left behind
    assert sorted(os.listdir(tmp_path)) == ["query_history.jsonl"]


def test_history_reader_skips_torn_lines(tmp_path):
    from spark_rapids_tpu.obs.history import QueryHistoryLog, read_entries
    log = QueryHistoryLog(str(tmp_path))
    log.append({"query_id": "a"})
    with open(log.path, "a") as f:
        f.write('{"query_id": "torn-mid-cra')   # crash mid-append
    log.append({"query_id": "b"})
    ids = [e["query_id"] for e in read_entries(log.path)]
    assert ids == ["a", "b"]


def test_history_concurrent_appenders(tmp_path):
    from spark_rapids_tpu.obs.history import QueryHistoryLog, read_entries
    log = QueryHistoryLog(str(tmp_path), max_entries=1000)
    n_threads, per = 8, 25

    def appender(k):
        for i in range(per):
            log.append({"query_id": f"t{k}-{i}"})

    ts = [threading.Thread(target=appender, args=(k,))
          for k in range(n_threads)]
    for t in ts:
        t.start()
    for t in ts:
        t.join()
    entries = read_entries(log.path)
    assert len(entries) == n_threads * per
    assert len({e["query_id"] for e in entries}) == n_threads * per


def test_history_entry_written_at_terminal_state(tmp_path):
    """One entry per executed query after shutdown(drain=True), with
    terminal state, registry delta, analyzed plan — and it conforms to
    the checked-in CI schema."""
    from spark_rapids_tpu import types as T
    from spark_rapids_tpu.expr.core import col, lit
    from spark_rapids_tpu.obs.history import HISTORY_FILE, read_entries
    from spark_rapids_tpu.session import TpuSession
    s = TpuSession({"spark.rapids.obs.history.dir": str(tmp_path)})
    schema = T.Schema([T.StructField("a", T.IntegerType())])
    df = s.from_pydict({"a": list(range(20))}, schema, partitions=2)
    df.where(col("a") > lit(3)).collect()
    df.where(col("a") > lit(10)).collect()
    s.shutdown(drain=True)
    entries = read_entries(os.path.join(str(tmp_path), HISTORY_FILE))
    assert len(entries) == 2
    hs = load_schema("history")
    for e in entries:
        assert validate(e, hs) == []
        assert e["state"] == "FINISHED"
        assert e["plan_fingerprint"]
        assert e["plan_analyzed"]
        assert e["registry_delta"]["counters"]
        assert e["wall_s"] is not None and e["wall_s"] >= 0
        assert e["executed"] is True


def test_history_records_failure_taxonomy(tmp_path):
    """A query that dies at runtime (injected shuffle-peer death with
    the recovery budget exhausted) lands in the history log as FAILED
    with the error taxonomy filled in."""
    from spark_rapids_tpu import types as T
    from spark_rapids_tpu.expr.aggregates import Sum
    from spark_rapids_tpu.expr.core import col
    from spark_rapids_tpu.obs.history import HISTORY_FILE, read_entries
    from spark_rapids_tpu.session import TpuSession
    s = TpuSession({
        "spark.rapids.obs.history.dir": str(tmp_path),
        "spark.rapids.test.faults": "shuffle.peer.dead:dead,times=0",
        "spark.rapids.shuffle.recovery.maxStageAttempts": "1",
    })
    schema = T.Schema([T.StructField("k", T.IntegerType()),
                       T.StructField("v", T.DoubleType())])
    df = s.from_pydict({"k": [1, 2, 1, 2], "v": [1.0, 2.0, 3.0, 4.0]},
                       schema, partitions=2) \
        .group_by("k").agg(Sum(col("v")))
    with pytest.raises(Exception):
        df.collect()
    s.shutdown()
    entries = read_entries(os.path.join(str(tmp_path), HISTORY_FILE))
    assert len(entries) == 1
    e = entries[0]
    assert e["state"] == "FAILED"
    assert e["error"]["type"]
    assert e["error"]["message"]
    assert validate(e, load_schema("history")) == []


def test_history_tool_is_engine_free(tmp_path):
    """python -m tools.history must not import the engine: it has to
    work on a forensics box with no jax."""
    import subprocess
    from spark_rapids_tpu.obs.history import QueryHistoryLog
    log = QueryHistoryLog(str(tmp_path))
    log.append({"kind": "history", "version": 1, "query_id": "abc123",
                "tenant": "default", "state": "FINISHED",
                "submitted_unix_s": 1.0, "wall_s": 0.5,
                "registry_delta": {"counters": {}, "histograms": {}}})
    code = ("import sys, tools.history; "
            "bad = [m for m in sys.modules if m.startswith("
            "'spark_rapids_tpu') or m == 'jax']; "
            "sys.exit(1 if bad else 0)")
    r = subprocess.run([sys.executable, "-c", code],
                       cwd=os.path.join(os.path.dirname(__file__), ".."),
                       capture_output=True, text=True, timeout=60)
    assert r.returncode == 0, r.stdout + r.stderr
    r = subprocess.run(
        [sys.executable, "-m", "tools.history", "--dir", str(tmp_path),
         "list"],
        cwd=os.path.join(os.path.dirname(__file__), ".."),
        capture_output=True, text=True, timeout=60)
    assert r.returncode == 0, r.stdout + r.stderr
    assert "abc123" in r.stdout


# ---------------------------------------------------------------------------
# cross-process trace lanes
# ---------------------------------------------------------------------------

def test_trace_ship_and_ingest_one_timeline(tmp_path):
    """Worker events drained, stamped to wall-clock, ingested by the
    driver tracer: ONE export with both pids on named lanes, worker ts
    rebased onto the driver origin."""
    from spark_rapids_tpu.obs.trace import Tracer, stamp_for_shipping
    driver = Tracer(query_id="q1")
    worker = Tracer(query_id="q1", trace_id=driver.trace_id)
    worker.pid = driver.pid + 1   # simulate a separate process

    with driver.span("cluster.map_stage", "cluster"):
        with worker.span("worker.fragment", "cluster"):
            pass
    shipped = stamp_for_shipping(worker.drain_events(),
                                 worker._wall_origin, worker.pid)
    assert shipped and all(ev["pid"] == worker.pid for ev in shipped)
    # drain is exactly-once
    assert worker.drain_events() == []

    driver.ensure_lane(driver.pid, "driver")
    driver.ensure_lane(worker.pid, "cluster worker w0")
    driver.ensure_lane(worker.pid, "dup ignored")   # idempotent
    driver.ingest_wall(shipped)

    path = driver.export(str(tmp_path / "trace.json"))
    doc = json.load(open(path))
    assert validate(doc, load_schema("trace")) == []
    lanes = {ev["pid"]: ev["args"]["name"] for ev in doc["traceEvents"]
             if ev["ph"] == "M" and ev["name"] == "process_name"}
    assert lanes == {driver.pid: "driver",
                     worker.pid: "cluster worker w0"}
    pids = {ev["pid"] for ev in doc["traceEvents"] if ev["ph"] == "X"}
    assert pids == {driver.pid, worker.pid}
    # the worker span's rebased ts must land within the driver span
    dspan = next(ev for ev in doc["traceEvents"]
                 if ev["name"] == "cluster.map_stage")
    wspan = next(ev for ev in doc["traceEvents"]
                 if ev["name"] == "worker.fragment")
    assert dspan["ts"] - 1e4 <= wspan["ts"] <= dspan["ts"] + dspan["dur"] \
        + 1e4


def test_trace_lanes_survive_buffer_rotation(tmp_path):
    from spark_rapids_tpu.obs.trace import Tracer
    tr = Tracer(query_id="q2", max_events=4)
    tr.ensure_lane(tr.pid, "driver")
    for i in range(32):
        tr.event(f"e{i}")
    evs = tr.events_snapshot()
    assert evs[0]["ph"] == "M", "lane metadata must survive rotation"
    assert sum(1 for e in evs if e["ph"] == "i") == 4


def test_cluster_span_buffer_bounds():
    """Driver-side heartbeat span buffering is bounded per query and in
    query count, and drains exactly once."""
    import threading as _t
    from collections import deque

    from spark_rapids_tpu.cluster.driver import (_MAX_SPAN_QUERIES,
                                                 ClusterDriver)
    d = ClusterDriver.__new__(ClusterDriver)
    d._span_lock = _t.Lock()
    d._pending_spans = {}
    for qi in range(_MAX_SPAN_QUERIES + 3):
        d.buffer_spans([{"name": "x", "args": {"query_id": f"q{qi}"}}])
    assert len(d._pending_spans) == _MAX_SPAN_QUERIES
    assert "q0" not in d._pending_spans      # oldest evicted wholesale
    last = f"q{_MAX_SPAN_QUERIES + 2}"
    assert len(d.drain_query_spans(last)) == 1
    assert d.drain_query_spans(last) == []   # exactly-once
    assert all(isinstance(v, deque) for v in d._pending_spans.values())


# ---------------------------------------------------------------------------
# import discipline
# ---------------------------------------------------------------------------

def test_disabled_path_never_imports_http_or_history():
    """With both confs off, a full query leaves obs.http / obs.history
    out of sys.modules — zero overhead on the disabled path."""
    import subprocess
    code = """
import sys
from spark_rapids_tpu.session import TpuSession
from spark_rapids_tpu import types as T
s = TpuSession({})
schema = T.Schema([T.StructField("a", T.IntegerType())])
s.from_pydict({"a": [1, 2, 3]}, schema).collect()
s.shutdown()
bad = [m for m in sys.modules
       if m in ("spark_rapids_tpu.obs.http", "spark_rapids_tpu.obs.history")]
sys.exit(1 if bad else 0)
"""
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    r = subprocess.run([sys.executable, "-c", code],
                       cwd=os.path.join(os.path.dirname(__file__), ".."),
                       capture_output=True, text=True, timeout=300,
                       env=env)
    assert r.returncode == 0, r.stdout + r.stderr


def test_obs_package_lazy_exports():
    import importlib

    import spark_rapids_tpu.obs as obs
    assert set(obs.__all__) >= {"ObsHttpServer", "QueryHistoryLog",
                                "history_log"}
    assert obs.QueryHistoryLog is not None
    mod = importlib.import_module("spark_rapids_tpu.obs.history")
    assert obs.history_log is mod.history_log
    with pytest.raises(AttributeError):
        obs.no_such_name


# ---------------------------------------------------------------------------
# conf surface
# ---------------------------------------------------------------------------

def test_telemetry_confs_registered():
    # importing the gated modules registers their entries
    import spark_rapids_tpu.obs.history  # noqa: F401
    import spark_rapids_tpu.obs.http  # noqa: F401
    from spark_rapids_tpu.conf import registered_entries
    names = set(registered_entries())
    assert "spark.rapids.obs.http.port" in names
    assert "spark.rapids.obs.history.dir" in names
    assert "spark.rapids.obs.history.maxEntries" in names
    conf = TpuConf({"spark.rapids.obs.history.maxEntries": "7"})
    from spark_rapids_tpu.obs.history import HISTORY_MAX
    assert HISTORY_MAX.get(conf.settings) == 7


# ---------------------------------------------------------------------------
# percentile edge cases (the control loop consumes these directly)
# ---------------------------------------------------------------------------

def test_histogram_percentile_empty_and_none_delta():
    reg = MetricsRegistry()
    _observe_all(reg, "h", [0.1, 0.2])
    snap = reg.snapshot()["histograms"]["h"]
    # an unmoved window collapses to None; the percentile of that must
    # be None, not 0.0 — "no signal" and "instant queries" are
    # different control inputs
    assert histogram_percentile(delta_histogram_snapshot(snap, snap),
                                99) is None
    assert histogram_percentile(None, 99) is None
    assert histogram_percentile({}, 50) is None


def test_histogram_percentile_single_bucket_interpolates():
    reg = MetricsRegistry()
    # every observation lands in ONE bucket: all percentiles must stay
    # inside that bucket's bounds and remain monotone in q
    _observe_all(reg, "h", [0.3] * 10)
    snap = reg.snapshot()["histograms"]["h"]
    le = snap["le"]
    i = next(i for i, c in enumerate(snap["counts"]) if c)
    lo = le[i - 1] if i > 0 else 0.0
    hi = le[i] if i < len(le) else le[-1]
    ps = [histogram_percentile(snap, q) for q in (1, 50, 99, 100)]
    assert ps == sorted(ps)
    for p in ps:
        assert lo <= p <= hi


def test_histogram_percentile_overflow_bucket_reports_edge():
    reg = MetricsRegistry()
    # beyond the largest bound: the +Inf bucket has no upper edge, so
    # the estimate must clamp to the largest finite bound, not invent
    # a number
    _observe_all(reg, "h", [1e9])
    snap = reg.snapshot()["histograms"]["h"]
    assert histogram_percentile(snap, 99) == max(snap["le"])


# ---------------------------------------------------------------------------
# history index (plan-routing feed)
# ---------------------------------------------------------------------------

def test_history_index_only_finished_runs_teach():
    from spark_rapids_tpu.obs.history import HistoryIndex
    idx = HistoryIndex()
    idx.note_entry({"plan_fingerprint": "fp", "state": "FAILED",
                    "wall_s": 9.0})
    idx.note_entry({"plan_fingerprint": "fp", "state": "CANCELLED",
                    "wall_s": 9.0})
    idx.note_entry({"plan_fingerprint": "fp", "state": "FINISHED",
                    "wall_s": "not-a-number"})
    idx.note_entry({"state": "FINISHED", "wall_s": 1.0})  # no fp
    assert idx.lookup("fp") is None
    idx.note_entry({"plan_fingerprint": "fp", "state": "FINISHED",
                    "wall_s": 0.5})
    got = idx.lookup("fp")
    assert got["samples"] == 1
    assert got["median_wall_s"] == pytest.approx(0.5)


def test_history_index_mesh_breakdown_and_bounds():
    from spark_rapids_tpu.obs.history import HistoryIndex
    idx = HistoryIndex(max_fingerprints=2, max_samples=3)
    for wall, mesh in [(1.0, 1), (2.0, 1), (0.2, 4), (0.4, 4)]:
        idx.note_entry({"plan_fingerprint": "a", "state": "FINISHED",
                        "wall_s": wall, "mesh_devices": mesh})
    got = idx.lookup("a")
    # max_samples=3 keeps only the newest 3 of the 4
    assert got["samples"] == 3
    assert got["by_mesh"][4]["samples"] == 2
    assert got["by_mesh"][4]["median_wall_s"] == pytest.approx(0.3)
    # LRU bound on fingerprints: touching "a" via lookup keeps it
    # alive while "b" then "c" arrive — "b" is the one evicted
    idx.note_entry({"plan_fingerprint": "b", "state": "FINISHED",
                    "wall_s": 1.0})
    idx.lookup("a")
    idx.note_entry({"plan_fingerprint": "c", "state": "FINISHED",
                    "wall_s": 1.0})
    assert len(idx) == 2
    assert idx.lookup("b") is None
    assert idx.lookup("a") is not None


def test_history_index_refresh_replaces_no_double_count(tmp_path):
    from spark_rapids_tpu.obs.history import (HistoryIndex,
                                              QueryHistoryLog)
    log = QueryHistoryLog(str(tmp_path))
    idx = HistoryIndex(min_refresh_s=0.0)
    entry = {"plan_fingerprint": "fp", "state": "FINISHED",
             "wall_s": 1.0, "query_id": "q0"}
    log.append(entry)
    idx.note_entry(entry)           # in-process fast path
    assert idx.refresh_from(log.path) is True   # file identity is new
    # the rebuild REPLACED the index — the entry fed both ways still
    # counts once
    assert idx.lookup("fp")["samples"] == 1
    # unchanged file: stat-gated, no rebuild
    assert idx.refresh_from(log.path) is False
    # a second process appends: identity moves, rebuild picks it up
    log.append({"plan_fingerprint": "fp", "state": "FINISHED",
                "wall_s": 3.0, "query_id": "q1"})
    assert idx.refresh_from(log.path) is True
    assert idx.lookup("fp")["samples"] == 2


def test_history_reader_retries_across_rotation(tmp_path, monkeypatch):
    """A read that straddles ``os.replace`` rotation must come back
    with one consistent generation of the file, never a torn mix: the
    reader compares the inode before/after and retries on the fresh
    file."""
    from spark_rapids_tpu.obs import history
    log = history.QueryHistoryLog(str(tmp_path), max_entries=100)
    for i in range(6):
        log.append({"query_id": f"old{i}"})
    real_open = open
    raced = {"done": False}

    def racing_open(path, *a, **kw):
        f = real_open(path, *a, **kw)
        if not raced["done"] and str(path) == log.path:
            raced["done"] = True
            # rotation swaps the file out while this reader holds the
            # old inode (rewrite + os.replace, same as _rotate_locked)
            tmp = log.path + ".tmp"
            with real_open(tmp, "w") as t:
                for i in range(3):
                    t.write(json.dumps({"query_id": f"new{i}"}) + "\n")
            os.replace(tmp, log.path)
        return f

    monkeypatch.setattr(history, "open", racing_open, raising=False)
    ids = [e["query_id"] for e in history.read_entries(log.path)]
    assert ids == ["new0", "new1", "new2"]

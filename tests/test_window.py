"""Window function differential tests (reference WindowFunctionSuite +
integration_tests window_function_test.py coverage)."""
import numpy as np
import pytest

from spark_rapids_tpu import types as T
from spark_rapids_tpu.exec import LocalScanExec, collect_host
from spark_rapids_tpu.exec.window import WindowExec
from spark_rapids_tpu.expr.aggregates import Average, Count, CountStar, \
    Max, Min, Sum
from spark_rapids_tpu.expr.core import col, lit
from spark_rapids_tpu.expr.window import (CURRENT_ROW, UNBOUNDED, DenseRank,
                                          Lag, Lead, Rank, RowNumber,
                                          WindowExpression, WindowFrame,
                                          WindowSpec)
from spark_rapids_tpu.testing import assert_tpu_and_cpu_equal

SCHEMA = T.Schema([
    T.StructField("g", T.IntegerType(), True),
    T.StructField("o", T.IntegerType(), True),
    T.StructField("v", T.LongType(), True),
    T.StructField("f", T.DoubleType(), True),
])


def _scan(rng, n=200, ngroups=8):
    return LocalScanExec.from_pydict({
        "g": [None if rng.random() < 0.05 else int(x)
              for x in rng.integers(0, ngroups, n)],
        "o": [int(x) for x in rng.integers(0, 50, n)],
        "v": [None if rng.random() < 0.1 else int(x)
              for x in rng.integers(-100, 100, n)],
        "f": [float("nan") if rng.random() < 0.05 else float(np.round(x, 3))
              for x in rng.normal(size=n)],
    }, SCHEMA, rows_per_batch=64)


SPEC = WindowSpec(partition_by=(col("g"),), order_by=((col("o"), True),))


def test_ranking_functions(rng):
    plan = WindowExec([
        WindowExpression(RowNumber(), SPEC).alias("rn"),
        WindowExpression(Rank(), SPEC).alias("rk"),
        WindowExpression(DenseRank(), SPEC).alias("dr"),
    ], _scan(rng))
    rows = assert_tpu_and_cpu_equal(plan)
    assert rows


def test_running_aggregates_default_frame(rng):
    # default frame with order: RANGE unbounded preceding .. current row
    plan = WindowExec([
        WindowExpression(Sum(col("v")), SPEC).alias("rs"),
        WindowExpression(Count(col("v")), SPEC).alias("rc"),
        WindowExpression(CountStar(), SPEC).alias("rcs"),
        WindowExpression(Average(col("v")), SPEC).alias("ra"),
    ], _scan(rng))
    assert_tpu_and_cpu_equal(plan)


def test_whole_partition_aggregates(rng):
    spec = WindowSpec(partition_by=(col("g"),))
    plan = WindowExec([
        WindowExpression(Sum(col("v")), spec).alias("ts"),
        WindowExpression(Min(col("v")), spec).alias("tmin"),
        WindowExpression(Max(col("f")), spec).alias("tmax"),
    ], _scan(rng))
    assert_tpu_and_cpu_equal(plan)


def test_bounded_rows_frames(rng):
    spec = WindowSpec(partition_by=(col("g"),),
                      order_by=((col("o"), True),),
                      frame=WindowFrame("rows", -2, 1))
    plan = WindowExec([
        WindowExpression(Sum(col("v")), spec).alias("ws"),
        WindowExpression(Min(col("v")), spec).alias("wmin"),
        WindowExpression(Max(col("v")), spec).alias("wmax"),
        WindowExpression(Average(col("v")), spec).alias("wavg"),
        WindowExpression(Max(col("f")), spec).alias("wfmax"),
    ], _scan(rng))
    assert_tpu_and_cpu_equal(plan)


def test_lead_lag(rng):
    plan = WindowExec([
        WindowExpression(Lead(col("v"), 1), SPEC).alias("ld"),
        WindowExpression(Lag(col("v"), 2), SPEC).alias("lg"),
        WindowExpression(Lead(col("v"), 1, lit(-999)), SPEC).alias("ldd"),
    ], _scan(rng))
    assert_tpu_and_cpu_equal(plan)


def test_desc_order_and_row_number(rng):
    spec = WindowSpec(partition_by=(col("g"),),
                      order_by=((col("o"), False),))
    plan = WindowExec([
        WindowExpression(RowNumber(), spec).alias("rn"),
        WindowExpression(Sum(col("v")), spec).alias("rs"),
    ], _scan(rng))
    assert_tpu_and_cpu_equal(plan)


def test_mixed_specs_rejected(rng):
    other = WindowSpec(partition_by=(col("o"),))
    with pytest.raises(ValueError):
        WindowExec([
            WindowExpression(RowNumber(), SPEC).alias("a"),
            WindowExpression(RowNumber(), other).alias("b"),
        ], _scan(rng))


def test_empty_input(rng):
    empty = LocalScanExec.from_pydict(
        {"g": [], "o": [], "v": [], "f": []}, SCHEMA)
    plan = WindowExec([
        WindowExpression(RowNumber(), SPEC).alias("rn"),
    ], empty)
    assert assert_tpu_and_cpu_equal(plan) == []


def test_bounded_following_only_frame(rng):
    # ROWS BETWEEN 2 FOLLOWING AND 5 FOLLOWING: empty frames at partition
    # tails must produce count 0 (regression: negative cross-partition diff)
    spec = WindowSpec(partition_by=(col("g"),),
                      order_by=((col("o"), True),),
                      frame=WindowFrame("rows", 2, 5))
    plan = WindowExec([
        WindowExpression(CountStar(), spec).alias("c"),
        WindowExpression(Count(col("v")), spec).alias("cv"),
        WindowExpression(Sum(col("v")), spec).alias("s"),
    ], _scan(rng, n=60, ngroups=4))
    rows = assert_tpu_and_cpu_equal(plan)
    assert all(r[4] >= 0 for r in rows)


def test_multi_partition_window_keeps_parallelism(rng):
    """The planner hash-partitions on window partition keys so the window
    program runs per partition instead of collapsing the world into one
    batch (round-3 scaling cliff; reference GpuWindowExec.scala:92 needs
    one batch per partition GROUP only)."""
    from spark_rapids_tpu import TpuSession
    from spark_rapids_tpu.exec.core import ExecCtx
    from spark_rapids_tpu.exec.exchange import ShuffleExchangeExec
    from spark_rapids_tpu.expr.aggregates import Sum as _Sum

    s = TpuSession({"spark.sql.shuffle.partitions": 4})
    n = 300
    df = s.from_pydict({
        "g": [None if rng.random() < 0.05 else int(x)
              for x in rng.integers(0, 16, n)],
        "o": [int(x) for x in rng.integers(0, 40, n)],
        "v": [None if rng.random() < 0.1 else int(x)
              for x in rng.integers(-50, 50, n)],
    }, T.Schema([T.StructField("g", T.IntegerType(), True),
                 T.StructField("o", T.IntegerType(), True),
                 T.StructField("v", T.LongType(), True)]),
        partitions=3, rows_per_batch=64)
    spec = WindowSpec(partition_by=(col("g"),), order_by=((col("o"), True),))
    out = df.select(
        col("g"), col("o"), col("v"),
        WindowExpression(RowNumber(), spec).alias("rn"),
        WindowExpression(_Sum(col("v")), spec).alias("rs"))

    _, meta = out._overridden(quiet=True)
    ctx = ExecCtx(backend="host")
    wins = [nd for nd in _walk(meta.exec_node) if isinstance(nd, WindowExec)]
    assert wins, "plan lost its WindowExec"
    assert all(w.num_partitions(ctx) > 1 for w in wins), \
        "window collapsed to a single partition"
    assert any(isinstance(nd, ShuffleExchangeExec)
               for w in wins for nd in _walk(w)), \
        "planner did not insert the hash exchange under the window"

    # differential: device result == host oracle through the full planner
    from spark_rapids_tpu.exec.core import collect_host
    dev_rows = sorted(out.collect(), key=_row_key)
    host_rows = sorted(collect_host(meta.exec_node, s.conf), key=_row_key)
    assert len(host_rows) == len(dev_rows) == n
    assert host_rows == dev_rows


def _walk(node):
    yield node
    for c in node.children:
        yield from _walk(c)


def _row_key(r):
    return tuple((x is None, 0 if x is None else x)
                 if x is None or isinstance(x, (int, float))
                 else (False, str(x)) for x in r)


def test_global_window_streams_bounded_memory(rng):
    """Empty-partition-by plain-aggregate windows run as a two-pass
    stream: one running state + spillable parked batches, emitting one
    output batch PER input batch instead of one world-sized batch
    (VERDICT r4 item 10; reference contract is single batch per GROUP,
    GpuWindowExec.scala:92)."""
    from spark_rapids_tpu.exec.core import ExecCtx, device_to_host
    scan = _scan(rng, n=300)
    gspec = WindowSpec()
    plan = WindowExec([
        WindowExpression(Sum(col("v")), gspec).alias("sv"),
        WindowExpression(CountStar(), gspec).alias("c"),
        WindowExpression(Count(col("v")), gspec).alias("cv"),
        WindowExpression(Min(col("v")), gspec).alias("mn"),
        WindowExpression(Max(col("f")), gspec).alias("mx"),
        WindowExpression(Average(col("v")), gspec).alias("av"),
    ], scan)
    assert plan._global_streamable()
    assert plan.output_batching is None
    rows = assert_tpu_and_cpu_equal(plan)
    assert len(rows) == 300
    # the device path must emit MULTIPLE batches (bounded memory), not
    # one world batch
    with ExecCtx(backend="device") as ctx:
        batches = list(plan.partition_iter(ctx, 0))
        assert len(batches) > 1
        got = [r for b in batches for r in device_to_host(b).to_rows()]
    assert len(got) == 300


def test_global_window_streaming_exact_int64(rng):
    """int64 extremes/sums past 2^53 stay exact through the streaming
    accumulator (an f64 fold would round them)."""
    big = (1 << 60) + 12345
    scan = LocalScanExec.from_pydict(
        {"v": [big, big + 7, None, -big]},
        T.Schema([T.StructField("v", T.LongType(), True)]),
        rows_per_batch=2)
    gspec = WindowSpec()
    plan = WindowExec([
        WindowExpression(Max(col("v")), gspec).alias("mx"),
        WindowExpression(Min(col("v")), gspec).alias("mn"),
        WindowExpression(Sum(col("v")), gspec).alias("s"),
    ], scan)
    rows = assert_tpu_and_cpu_equal(plan)
    # sum over [big, big+7, None, -big] = big + 7, exactly
    assert rows[0][1:] == (big + 7, -big, big + 7)


def test_global_window_with_order_keeps_single_batch(rng):
    """An ordered global window (running frame) is NOT streamable — it
    must keep the sorted single-batch path."""
    plan = WindowExec([
        WindowExpression(Sum(col("v")),
                         WindowSpec(order_by=((col("o"), True),)))
        .alias("rs")], _scan(rng, n=100))
    assert not plan._global_streamable()
    assert plan.output_batching is not None
    assert_tpu_and_cpu_equal(plan)

"""Window function differential tests (reference WindowFunctionSuite +
integration_tests window_function_test.py coverage)."""
import numpy as np
import pytest

from spark_rapids_tpu import types as T
from spark_rapids_tpu.exec import LocalScanExec, collect_host
from spark_rapids_tpu.exec.window import WindowExec
from spark_rapids_tpu.expr.aggregates import Average, Count, CountStar, \
    Max, Min, Sum
from spark_rapids_tpu.expr.core import col, lit
from spark_rapids_tpu.expr.window import (CURRENT_ROW, UNBOUNDED, DenseRank,
                                          Lag, Lead, Rank, RowNumber,
                                          WindowExpression, WindowFrame,
                                          WindowSpec)
from spark_rapids_tpu.testing import assert_tpu_and_cpu_equal

SCHEMA = T.Schema([
    T.StructField("g", T.IntegerType(), True),
    T.StructField("o", T.IntegerType(), True),
    T.StructField("v", T.LongType(), True),
    T.StructField("f", T.DoubleType(), True),
])


def _scan(rng, n=200, ngroups=8):
    return LocalScanExec.from_pydict({
        "g": [None if rng.random() < 0.05 else int(x)
              for x in rng.integers(0, ngroups, n)],
        "o": [int(x) for x in rng.integers(0, 50, n)],
        "v": [None if rng.random() < 0.1 else int(x)
              for x in rng.integers(-100, 100, n)],
        "f": [float("nan") if rng.random() < 0.05 else float(np.round(x, 3))
              for x in rng.normal(size=n)],
    }, SCHEMA, rows_per_batch=64)


SPEC = WindowSpec(partition_by=(col("g"),), order_by=((col("o"), True),))


def test_ranking_functions(rng):
    plan = WindowExec([
        WindowExpression(RowNumber(), SPEC).alias("rn"),
        WindowExpression(Rank(), SPEC).alias("rk"),
        WindowExpression(DenseRank(), SPEC).alias("dr"),
    ], _scan(rng))
    rows = assert_tpu_and_cpu_equal(plan)
    assert rows


def test_running_aggregates_default_frame(rng):
    # default frame with order: RANGE unbounded preceding .. current row
    plan = WindowExec([
        WindowExpression(Sum(col("v")), SPEC).alias("rs"),
        WindowExpression(Count(col("v")), SPEC).alias("rc"),
        WindowExpression(CountStar(), SPEC).alias("rcs"),
        WindowExpression(Average(col("v")), SPEC).alias("ra"),
    ], _scan(rng))
    assert_tpu_and_cpu_equal(plan)


def test_whole_partition_aggregates(rng):
    spec = WindowSpec(partition_by=(col("g"),))
    plan = WindowExec([
        WindowExpression(Sum(col("v")), spec).alias("ts"),
        WindowExpression(Min(col("v")), spec).alias("tmin"),
        WindowExpression(Max(col("f")), spec).alias("tmax"),
    ], _scan(rng))
    assert_tpu_and_cpu_equal(plan)


def test_bounded_rows_frames(rng):
    spec = WindowSpec(partition_by=(col("g"),),
                      order_by=((col("o"), True),),
                      frame=WindowFrame("rows", -2, 1))
    plan = WindowExec([
        WindowExpression(Sum(col("v")), spec).alias("ws"),
        WindowExpression(Min(col("v")), spec).alias("wmin"),
        WindowExpression(Max(col("v")), spec).alias("wmax"),
        WindowExpression(Average(col("v")), spec).alias("wavg"),
        WindowExpression(Max(col("f")), spec).alias("wfmax"),
    ], _scan(rng))
    assert_tpu_and_cpu_equal(plan)


def test_lead_lag(rng):
    plan = WindowExec([
        WindowExpression(Lead(col("v"), 1), SPEC).alias("ld"),
        WindowExpression(Lag(col("v"), 2), SPEC).alias("lg"),
        WindowExpression(Lead(col("v"), 1, lit(-999)), SPEC).alias("ldd"),
    ], _scan(rng))
    assert_tpu_and_cpu_equal(plan)


def test_desc_order_and_row_number(rng):
    spec = WindowSpec(partition_by=(col("g"),),
                      order_by=((col("o"), False),))
    plan = WindowExec([
        WindowExpression(RowNumber(), spec).alias("rn"),
        WindowExpression(Sum(col("v")), spec).alias("rs"),
    ], _scan(rng))
    assert_tpu_and_cpu_equal(plan)


def test_mixed_specs_rejected(rng):
    other = WindowSpec(partition_by=(col("o"),))
    with pytest.raises(ValueError):
        WindowExec([
            WindowExpression(RowNumber(), SPEC).alias("a"),
            WindowExpression(RowNumber(), other).alias("b"),
        ], _scan(rng))


def test_empty_input(rng):
    empty = LocalScanExec.from_pydict(
        {"g": [], "o": [], "v": [], "f": []}, SCHEMA)
    plan = WindowExec([
        WindowExpression(RowNumber(), SPEC).alias("rn"),
    ], empty)
    assert assert_tpu_and_cpu_equal(plan) == []


def test_bounded_following_only_frame(rng):
    # ROWS BETWEEN 2 FOLLOWING AND 5 FOLLOWING: empty frames at partition
    # tails must produce count 0 (regression: negative cross-partition diff)
    spec = WindowSpec(partition_by=(col("g"),),
                      order_by=((col("o"), True),),
                      frame=WindowFrame("rows", 2, 5))
    plan = WindowExec([
        WindowExpression(CountStar(), spec).alias("c"),
        WindowExpression(Count(col("v")), spec).alias("cv"),
        WindowExpression(Sum(col("v")), spec).alias("s"),
    ], _scan(rng, n=60, ngroups=4))
    rows = assert_tpu_and_cpu_equal(plan)
    assert all(r[4] >= 0 for r in rows)

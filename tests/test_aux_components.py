"""Round-3 aux components: dynamic-partition writer + write stats,
Arrow/pandas UDF exec, adaptive shuffle reader (AQE analog).

Reference: GpuFileFormatWriter.scala:338 / GpuFileFormatDataWriter.scala,
GpuArrowEvalPythonExec.scala:46-456, GpuCustomShuffleReaderExec.scala:131.
"""
import glob
import os

import numpy as np
import pytest

from spark_rapids_tpu import types as T
from spark_rapids_tpu.exec.core import collect_host
from spark_rapids_tpu.expr.aggregates import CountStar, Sum
from spark_rapids_tpu.expr.core import col, lit
from spark_rapids_tpu.session import TpuSession


def _df(s, n=200):
    rng = np.random.default_rng(5)
    schema = T.Schema([T.StructField("k", T.IntegerType()),
                       T.StructField("cat", T.StringType()),
                       T.StructField("v", T.DoubleType())])
    cats = ["a", "b", None, "c"]
    return s.from_pydict(
        {"k": list(range(n)),
         "cat": [cats[i] for i in rng.integers(0, 4, n)],
         "v": [float(i) for i in range(n)]},
        schema, partitions=2, rows_per_batch=50)


# -- dynamic-partition writer ------------------------------------------------

def test_partitioned_write_and_readback(tmp_path):
    s = TpuSession({})
    out = str(tmp_path / "out")
    stats = _df(s).write_parquet(out, partition_by=["cat"])
    assert os.path.exists(os.path.join(out, "_SUCCESS"))
    dirs = sorted(os.path.basename(d) for d in
                  glob.glob(os.path.join(out, "cat=*")))
    assert dirs == ["cat=__HIVE_DEFAULT_PARTITION__", "cat=a", "cat=b",
                    "cat=c"]
    # stats tracker counted everything
    assert stats.num_rows == 200
    assert stats.num_files == len(glob.glob(
        os.path.join(out, "cat=*", "*.parquet")))
    assert stats.num_bytes > 0
    assert sorted(stats.partitions) == dirs
    # partition column is in the directory, not the files (physical
    # schema: pyarrow >= 22 re-infers hive columns from the PATH even
    # for a single file, so read_table would show "cat" regardless)
    import pyarrow.parquet as pq
    pf = pq.ParquetFile(glob.glob(os.path.join(out, "cat=a",
                                               "*.parquet"))[0])
    assert "cat" not in pf.schema_arrow.names
    # readback through the engine (partition pruning by dir filter)
    back = s.read_parquet(os.path.join(out, "cat=a")).collect()
    host = [r for r in _df(s).collect() if r[1] == "a"]
    assert sorted(r[0] for r in back) == sorted(r[0] for r in host)


def test_plain_write_stats(tmp_path):
    s = TpuSession({})
    out = str(tmp_path / "plain")
    stats = _df(s).write_parquet(out)
    assert stats.num_rows == 200 and stats.num_files >= 1
    assert stats.partitions == []


# -- pandas UDF exec ---------------------------------------------------------

def test_pandas_udf_vectorized():
    from spark_rapids_tpu.exec.python_exec import pandas_udf
    s = TpuSession({})
    doubler = pandas_udf(lambda a, b: a * 2 + b, T.DoubleType())
    out = _df(s).select(col("k"),
                        doubler(col("v"), col("k").cast(
                            T.DoubleType())).alias("u"))
    ex = out.explain()
    assert "ArrowEvalPythonExec" in ex
    dev = sorted(out.collect())
    ov, meta = out._overridden(quiet=True)
    host = sorted(collect_host(meta.exec_node, s.conf))
    assert dev == host
    assert dev[5] == (5, 15.0)


def test_pandas_udf_nested_and_string():
    import pandas as pd
    from spark_rapids_tpu.exec.python_exec import pandas_udf
    s = TpuSession({})
    up = pandas_udf(lambda c: c.astype(str).str.upper(), T.StringType())
    out = _df(s).select((up(col("cat")) == lit("A")).alias("is_a"))
    dev = sorted(out.collect(), key=str)
    assert (True,) in dev and (False,) in dev


def test_pandas_udf_wrong_length_fails():
    from spark_rapids_tpu.exec.python_exec import pandas_udf
    s = TpuSession({})
    bad = pandas_udf(lambda a: a[:3], T.DoubleType())
    with pytest.raises(Exception, match="rows"):
        _df(s).select(bad(col("v")).alias("u")).collect()


# -- adaptive shuffle reader -------------------------------------------------

def test_adaptive_reader_coalesces_small_partitions():
    s = TpuSession({"spark.sql.shuffle.partitions": 16})
    df = _df(s).group_by("cat").agg(Sum(col("v")).alias("sv"),
                                    CountStar().alias("cnt"))
    ov, meta = df._overridden(quiet=True)
    assert "AdaptiveShuffleReaderExec" in df.explain()
    from spark_rapids_tpu.exec.core import ExecCtx
    with ExecCtx(backend="device", conf=s.conf) as ctx:
        reader = meta.exec_node.children[0]
        # tiny shuffle output, 64MB advisory target -> one coalesced group
        assert reader.num_partitions(ctx) < 16
        rows = []
        for b in meta.exec_node.execute(ctx):
            from spark_rapids_tpu.exec.core import device_to_host
            hb = device_to_host(b)
            rows.extend(zip(*[c.to_list() for c in hb.columns]))
    host = collect_host(meta.exec_node, s.conf)
    assert sorted(rows, key=str) == sorted(host, key=str)


def test_adaptive_disabled_keeps_partitions():
    s = TpuSession({"spark.sql.adaptive.enabled": False})
    df = _df(s).group_by("cat").agg(CountStar().alias("cnt"))
    assert "AdaptiveShuffleReaderExec" not in df.explain()
    dev = sorted(df.collect(), key=str)
    ov, meta = df._overridden(quiet=True)
    assert dev == sorted(collect_host(meta.exec_node, s.conf), key=str)


def test_pandas_udf_aliased_to_existing_column():
    """UDF output aliased to an input column's name must win the bind
    (round-3 review finding: the generated column was shadowed)."""
    from spark_rapids_tpu.exec.python_exec import pandas_udf
    s = TpuSession({})
    dbl = pandas_udf(lambda v: v * 2, T.DoubleType())
    out = _df(s).select(col("k"), dbl(col("v")).alias("v"))
    rows = sorted(out.collect())
    assert rows[7] == (7, 14.0)


def test_nested_pandas_udfs_rejected():
    from spark_rapids_tpu.exec.python_exec import pandas_udf
    s = TpuSession({})
    a = pandas_udf(lambda v: v + 1, T.DoubleType())
    b = pandas_udf(lambda v: v * 2, T.DoubleType())
    with pytest.raises(ValueError, match="nested"):
        _df(s).select(a(b(col("v"))).alias("u")).collect()


def test_partitioned_write_nan_values(tmp_path):
    s = TpuSession({})
    schema = T.Schema([T.StructField("p", T.DoubleType()),
                       T.StructField("x", T.IntegerType())])
    df = s.from_pydict({"p": [1.0, float("nan"), None, 1.0],
                        "x": [1, 2, 3, 4]}, schema)
    out = str(tmp_path / "nanpart")
    stats = df.write_parquet(out, partition_by=["p"])
    assert stats.num_rows == 4  # NaN row written, not dropped
    assert any("nan" in p for p in stats.partitions)


def test_join_reads_shuffle_through_adaptive_reader():
    """Joins over a repartition read through the skew-capable adaptive
    reader (Spark OptimizeSkewedJoin scope); results match the oracle."""
    s = TpuSession({})
    left = _df(s).repartition(4, "cat")
    right = s.from_pydict(
        {"cat": ["a", "b", "c"], "w": [1.0, 2.0, 3.0]},
        T.Schema([T.StructField("cat", T.StringType()),
                  T.StructField("w", T.DoubleType())]))
    out = left.join(right, on="cat", how="inner")
    plan = out.explain()
    assert "AdaptiveShuffleReaderExec" in plan
    dev = sorted(out.collect(), key=str)
    ov, meta = out._overridden(quiet=True)
    host = sorted(collect_host(meta.exec_node, s.conf), key=str)
    assert dev == host and len(dev) > 0


def test_join_adaptive_reader_respects_disable():
    s = TpuSession({"spark.sql.adaptive.enabled": False})
    left = _df(s).repartition(4, "cat")
    right = s.from_pydict(
        {"cat": ["a", "b"], "w": [1.0, 2.0]},
        T.Schema([T.StructField("cat", T.StringType()),
                  T.StructField("w", T.DoubleType())]))
    out = left.join(right, on="cat", how="inner")
    assert "AdaptiveShuffleReaderExec" not in out.explain()


def test_rows_match_tolerant_verifier():
    """The bench verifier's paired fallback: boundary-noise floats
    accepted, real differences rejected, NaN/None/mixed rows pair
    without any float ordering (q47's 103.1275 boundary flip).
    strict=False is the f32-pair (TPU) tier; strict=True is the
    true-f64 tier where only summation-order noise is legitimate."""
    import math
    from spark_rapids_tpu.bench.runner import _rows_match

    assert _rows_match([("a", 103.1275001)], [("a", 103.1274999)],
                       strict=False)
    assert not _rows_match([("a", 103.13)], [("a", 103.12)],
                           strict=False)
    assert _rows_match([("a", 1.5), ("a", None)],
                       [("a", None), ("a", 1.5000000001)], strict=False)
    assert _rows_match([(1, float("nan")), (2, 3.0)],
                       [(2, 3.0000000001), (1, float("nan"))],
                       strict=False)
    assert _rows_match([(1.2e8 * (1 + 4e-6),)], [(1.2e8,)], strict=False)
    assert not _rows_match([("a", 1.0), ("a", 1.0)],
                           [("a", 1.0), ("a", 2.0)], strict=False)
    assert not _rows_match([("a", 1.0)], [("b", 1.0)], strict=False)
    # strict tier: f32-pair-scale error rejected, 1-ulp order noise ok
    assert not _rows_match([(1.2e8 * (1 + 4e-6),)], [(1.2e8,)],
                           strict=True)
    assert not _rows_match([("a", 103.1275001)], [("a", 103.1274999)],
                           strict=True)
    assert _rows_match([(103.12750000000001,)], [(103.1275,)],
                       strict=True)
    # default keys off the backend (CPU under tests -> strict)
    assert not _rows_match([(1.2e8 * (1 + 4e-6),)], [(1.2e8,)])

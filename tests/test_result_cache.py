"""Serving-tier result/fragment cache suite (exec/result_cache.py).

The contract under test is the acceptance criteria's reuse-with-proof
shape: a repeated identical query at an unchanged input snapshot is
served from the cache with ZERO executor dispatches (``queries_executed``
delta 0) and zero compiles; mutating an input file, changing a
fingerprinted conf, or switching backend forces a full recompute with
no stale rows; concurrent identical queries coalesce onto one
computation whose waiters — never the owner — abort on their own
cancel; corruption is a verified miss, not wrong rows; and with
``spark.rapids.sql.resultCache.enabled=false`` nothing in the cache
plane runs at all (gate-off reversibility).
"""
import os
import threading
import time

import pyarrow as pa
import pyarrow.parquet as pq
import pytest

from spark_rapids_tpu.exec.result_cache import (ResultCache,
                                                get_result_cache,
                                                maybe_cache)
from spark_rapids_tpu.expr.core import col, lit
from spark_rapids_tpu.obs.registry import get_registry
from spark_rapids_tpu.session import TpuSession


def _delta(before: dict, name: str) -> float:
    return get_registry().delta(before)["counters"].get(name, 0)


@pytest.fixture()
def table(tmp_path):
    p = str(tmp_path / "t.parquet")
    pq.write_table(pa.table({"a": list(range(200)),
                             "b": [float(i) / 7 for i in range(200)]}), p)
    return p


def _df(session, path):
    return session.read_parquet(path).filter(col("a") > lit(20)) \
        .select("a", "b")


# ---------------------------------------------------------------------------
# whole-query result caching through the session
# ---------------------------------------------------------------------------

def test_repeat_query_hits_zero_executor_dispatches(table):
    s = TpuSession()
    df = _df(s, table)
    r1 = df.collect()
    before = get_registry().snapshot()
    r2 = df.collect()
    assert r2 == r1
    assert _delta(before, "result_cache_hits") == 1
    # the executor-entry chokepoint and the compile plane never moved:
    # the hit was served without minting an ExecCtx
    assert _delta(before, "queries_executed") == 0
    assert _delta(before, "compile_count") == 0
    s.shutdown()


def test_mtime_bump_invalidates(table):
    s = TpuSession()
    df = _df(s, table)
    r1 = df.collect()
    before = get_registry().snapshot()
    os.utime(table, ns=(time.time_ns(), time.time_ns()))
    r2 = df.collect()
    assert r2 == r1                       # same bytes, recomputed
    assert _delta(before, "result_cache_hits") == 0
    assert _delta(before, "result_cache_misses") == 1
    assert _delta(before, "queries_executed") == 1
    s.shutdown()


def test_content_change_serves_fresh_rows(table):
    s = TpuSession()
    df = _df(s, table)
    r1 = df.collect()
    pq.write_table(pa.table({"a": list(range(300)),
                             "b": [float(i) for i in range(300)]}), table)
    r2 = _df(s, table).collect()
    assert len(r2) == 279 and len(r1) == 179   # fresh rows, not stale
    s.shutdown()


def test_conf_change_invalidates(table):
    s1 = TpuSession()
    r1 = _df(s1, table).collect()
    before = get_registry().snapshot()
    s2 = TpuSession({"spark.rapids.sql.batchSizeBytes": 1 << 20})
    r2 = _df(s2, table).collect()
    assert r2 == r1
    assert _delta(before, "result_cache_hits") == 0
    assert _delta(before, "queries_executed") == 1
    s1.shutdown()
    s2.shutdown()


def test_backend_is_part_of_the_key(table):
    """The host oracle must NEVER be served a device-computed entry —
    that would destroy differential testing."""
    cache = get_result_cache()
    s = TpuSession()
    df = _df(s, table)
    kd = cache.result_key(df._plan, "device", s.conf)
    kh = cache.result_key(df._plan, "host", s.conf)
    assert kd is not None and kh is not None and kd != kh
    s.shutdown()


def test_in_memory_plan_is_never_cached(table):
    from spark_rapids_tpu import types as T
    s = TpuSession()
    schema = T.Schema([T.StructField("x", T.IntegerType())])
    df = s.from_pydict({"x": [1, 2, 3]}, schema)
    before = get_registry().snapshot()
    assert df.collect() == df.collect()
    # no provable snapshot -> result_key None -> no cache traffic
    assert _delta(before, "result_cache_hits") == 0
    assert _delta(before, "result_cache_misses") == 0
    assert _delta(before, "queries_executed") == 2
    s.shutdown()


def test_gate_off_is_byte_identical(table):
    s = TpuSession({"spark.rapids.sql.resultCache.enabled": "false"})
    assert maybe_cache(s.conf) is None
    df = _df(s, table)
    r1 = df.collect()
    before = get_registry().snapshot()
    r2 = df.collect()
    assert r2 == r1
    # execute-every-time, and the cache plane never even counted a miss
    assert _delta(before, "queries_executed") == 1
    assert _delta(before, "result_cache_hits") == 0
    assert _delta(before, "result_cache_misses") == 0
    s.shutdown()


# ---------------------------------------------------------------------------
# corruption: verified miss, never wrong rows
# ---------------------------------------------------------------------------

def test_corrupt_hit_drops_recomputes_exact(table):
    s = TpuSession({"spark.rapids.test.faults":
                    "cache.result.corrupt:corrupt,times=1"})
    df = _df(s, table)
    r1 = df.collect()
    before = get_registry().snapshot()
    r2 = df.collect()                     # poisoned hit -> CRC fail
    assert r2 == r1                       # recomputed, exact
    d = get_registry().delta(before)["counters"]
    assert d.get("result_cache_corrupt") == 1
    assert d.get("queries_executed") == 1
    assert d.get("faults.injected.cache.result.corrupt") == 1
    # the re-stored entry is clean: next repeat is a real hit
    before = get_registry().snapshot()
    assert df.collect() == r1
    assert _delta(before, "result_cache_hits") == 1
    s.shutdown()


# ---------------------------------------------------------------------------
# single-flight: coalesce, waiter cancel, owner takeover
# ---------------------------------------------------------------------------

def test_concurrent_identical_queries_coalesce():
    cache = ResultCache()
    gate = threading.Event()
    computes = []

    def compute():
        computes.append(1)
        gate.wait(10.0)
        return [(1, 2)]

    results = []
    threads = [threading.Thread(
        target=lambda: results.append(cache.get_or_compute(("k",), compute)))
        for _ in range(4)]
    before = get_registry().snapshot()
    for t in threads:
        t.start()
    deadline = time.monotonic() + 5.0
    while len(computes) < 1 and time.monotonic() < deadline:
        time.sleep(0.002)
    gate.set()
    for t in threads:
        t.join(timeout=10.0)
        assert not t.is_alive()
    assert len(computes) == 1             # ONE computation for four calls
    assert results == [[(1, 2)]] * 4
    assert _delta(before, "result_cache_coalesced") == 3


def test_waiter_cancel_aborts_wait_not_owner():
    from spark_rapids_tpu.exec.lifecycle import (QueryCancelled,
                                                 QueryLifecycle)
    cache = ResultCache()
    gate = threading.Event()

    def owner_compute():
        gate.wait(10.0)
        return ["rows"]

    owner_out, waiter_err = [], []
    to = threading.Thread(target=lambda: owner_out.append(
        cache.get_or_compute(("kc",), owner_compute)))
    to.start()
    time.sleep(0.05)                      # owner is in flight
    lc = QueryLifecycle("waiter")

    def waiter():
        try:
            cache.get_or_compute(("kc",), owner_compute, lifecycle=lc)
        except BaseException as e:  # noqa: BLE001 - recorded for asserts
            waiter_err.append(e)

    tw = threading.Thread(target=waiter)
    tw.start()
    time.sleep(0.1)
    lc.cancel("user")
    tw.join(timeout=5.0)
    assert not tw.is_alive()
    assert waiter_err and isinstance(waiter_err[0], QueryCancelled)
    # the owner was untouched by the waiter's cancel
    gate.set()
    to.join(timeout=5.0)
    assert owner_out == [["rows"]]


def test_owner_failure_waiter_takes_over():
    cache = ResultCache()
    gate = threading.Event()
    calls = []

    def failing_then_ok():
        calls.append(1)
        if len(calls) == 1:
            gate.wait(5.0)
            raise RuntimeError("owner died")
        return ["recovered"]

    errs, out = [], []

    def first():
        try:
            cache.get_or_compute(("kf",), failing_then_ok)
        except RuntimeError as e:
            errs.append(e)

    t1 = threading.Thread(target=first)
    t1.start()
    time.sleep(0.05)
    t2 = threading.Thread(target=lambda: out.append(
        cache.get_or_compute(("kf",), failing_then_ok)))
    t2.start()
    time.sleep(0.05)
    gate.set()                            # owner raises now
    t1.join(timeout=5.0)
    t2.join(timeout=5.0)
    assert errs and "owner died" in str(errs[0])
    assert out == [["recovered"]]         # waiter computed for itself
    assert len(calls) == 2


# ---------------------------------------------------------------------------
# memory: LRU, consumer pins, governor eviction
# ---------------------------------------------------------------------------

class _FakeBatch:
    def __init__(self, n):
        self.n = n

    def device_size_bytes(self):
        return self.n


def test_lru_eviction_respects_consumer_pins():
    before = get_registry().snapshot()
    cache = ResultCache(max_bytes=250)
    e1 = cache.fragment_entry(("f1",), lambda: [_FakeBatch(100)])
    e2 = cache.fragment_entry(("f2",), lambda: [_FakeBatch(100)])
    cache.fragment_release(e2)            # f2 idle, f1 still consumed
    e3 = cache.fragment_entry(("f3",), lambda: [_FakeBatch(100)])
    # f2 (idle, oldest idle) was evicted; pinned f1 survived
    assert _delta(before, "result_cache_evictions") == 1
    assert cache.cached_bytes() == 200
    cache.fragment_release(e1)
    cache.fragment_release(e3)
    assert cache.device_bytes() == 200


def test_oversized_result_served_never_cached():
    cache = ResultCache(max_bytes=64)
    rows = [("x" * 1000,)]
    assert cache.get_or_compute(("big",), lambda: rows) == rows
    assert cache.cached_bytes() == 0      # returned, not cached


def test_governor_evicts_cache_fragments_before_spilling():
    from spark_rapids_tpu.memory.governor import MemoryGovernor
    gov = MemoryGovernor()
    cache = ResultCache()
    gov.register_cache(cache)
    e = cache.fragment_entry(("gf",), lambda: [_FakeBatch(1 << 20)])
    cache.fragment_release(e)
    before = get_registry().snapshot()
    freed = gov._evict_cache(1 << 10, kind="fragment")
    assert freed == 1 << 20               # device bytes actually freed
    assert cache.device_bytes() == 0
    d = get_registry().delta(before)["counters"]
    assert d.get("governor_cache_evict_bytes") == 1 << 20
    assert d.get("result_cache_evictions") == 1


def test_evict_kind_filter_skips_result_blobs():
    cache = ResultCache()
    cache.get_or_compute(("r",), lambda: [(1,)])
    e = cache.fragment_entry(("f",), lambda: [_FakeBatch(64)])
    cache.fragment_release(e)
    assert cache.evict(kind="fragment") == 64
    assert cache.cached_bytes() > 0       # the result blob survived
    assert cache.evict() > 0              # unfiltered sweep takes it
    assert cache.cached_bytes() == 0


# ---------------------------------------------------------------------------
# cross-query shared-scan fragments (io/scan.py share_output routing)
# ---------------------------------------------------------------------------

def test_self_join_shares_one_scan_materialization(table):
    s = TpuSession()
    a = s.read_parquet(table)
    b = s.read_parquet(table)
    before = get_registry().snapshot()
    rows = a.join(b, on="a").collect()
    assert rows
    d = get_registry().delta(before)["counters"]
    # the planner marked the scan shared; both consumers drained ONE
    # materialization through the process-wide cache
    assert d.get("result_cache_fragment_misses", 0) >= 1
    assert d.get("result_cache_fragment_hits", 0) >= 1
    # nothing left pinned after the drain
    cache = get_result_cache()
    with cache._lock:
        assert all(e.consumers == 0 for e in cache._entries.values())
    s.shutdown()


def test_fragment_cache_disabled_falls_back_to_query_local(table):
    s = TpuSession({"spark.rapids.sql.resultCache.enabled": "false"})
    a = s.read_parquet(table)
    b = s.read_parquet(table)
    before = get_registry().snapshot()
    rows = a.join(b, on="a").collect()
    assert rows
    d = get_registry().delta(before)["counters"]
    assert d.get("result_cache_fragment_misses", 0) == 0
    assert d.get("result_cache_fragment_hits", 0) == 0
    s.shutdown()

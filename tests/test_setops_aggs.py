"""distinct / intersect / subtract / count(distinct) / stddev_samp
(reference: Spark set-op NULL semantics and CentralMomentAgg; TpcdsLike
queries q16/q28/q38/q87/q17/q39 are the consumers)."""
import math

import pytest

from spark_rapids_tpu import TpuSession
from spark_rapids_tpu import types as T
from spark_rapids_tpu.expr.aggregates import (CountDistinct, Sum,
                                              stddev_samp)
from spark_rapids_tpu.expr.core import col


def _s():
    return TpuSession({"spark.sql.shuffle.partitions": 2})


def _df(s, data, names, types):
    return s.from_pydict(
        dict(zip(names, data)),
        T.Schema([T.StructField(n, t, True)
                  for n, t in zip(names, types)]))


def test_distinct_nulls_and_dups():
    s = _s()
    df = _df(s, [[1, 1, None, None, 2], [5, 5, 7, 7, None]],
             ["a", "b"], [T.IntegerType(), T.LongType()])
    assert sorted(df.distinct().collect(), key=str) == \
        sorted([(1, 5), (None, 7), (2, None)], key=str)


def test_intersect_and_subtract_null_safe():
    s = _s()
    a = _df(s, [[1, 2, None, 3], [10, 20, 30, 40]],
            ["k", "v"], [T.IntegerType(), T.LongType()])
    b = _df(s, [[2, None, 4], [20, 30, 99]],
            ["k", "v"], [T.IntegerType(), T.LongType()])
    # NULL == NULL inside set operations (Spark INTERSECT/EXCEPT)
    assert sorted(a.intersect(b).collect(), key=str) == \
        sorted([(2, 20), (None, 30)], key=str)
    assert sorted(a.subtract(b).collect(), key=str) == \
        sorted([(1, 10), (3, 40)], key=str)


def test_set_op_marker_name_collision():
    s = _s()
    a = _df(s, [[1, 2], [1, 1]], ["_sop_a", "_sop_ia"],
            [T.IntegerType(), T.IntegerType()])
    b = _df(s, [[2], [1]], ["_sop_a", "_sop_ia"],
            [T.IntegerType(), T.IntegerType()])
    assert a.intersect(b).collect() == [(2, 1)]


def test_count_distinct_grouped_keeps_all_null_group():
    s = _s()
    df = _df(s, [[1, 1, 2, 2, 3], [10, 10, 20, 30, None]],
             ["k", "v"], [T.IntegerType(), T.LongType()])
    rows = sorted(df.group_by("k").agg(
        CountDistinct(col("v")).alias("c")).collect())
    # k=3 has only NULL v: Spark keeps the group with count 0
    assert rows == [(1, 1), (2, 2), (3, 0)]


def test_count_distinct_global_mixed_with_plain():
    s = _s()
    df = _df(s, [[1, 1, 2, 2, 3], [10, 10, 20, 30, None]],
             ["k", "v"], [T.IntegerType(), T.LongType()])
    rows = df.group_by().agg(CountDistinct(col("v")).alias("c"),
                             Sum(col("v")).alias("sv"),
                             CountDistinct(col("k")).alias("ck")).collect()
    assert rows == [(3, 70, 3)]


def test_count_distinct_multi_column():
    s = _s()
    df = _df(s, [[1, 1, 2, None], [5, 5, 6, 7]],
             ["a", "b"], [T.IntegerType(), T.LongType()])
    # tuples with any NULL component are not counted (Spark)
    rows = df.group_by().agg(
        CountDistinct(col("a"), col("b")).alias("c")).collect()
    assert rows == [(2,)]


def test_stddev_samp_matches_statistics():
    import statistics
    s = _s()
    vals = [3.0, 7.0, 7.0, 19.0]
    df = _df(s, [[1] * 4, vals], ["k", "v"],
             [T.IntegerType(), T.DoubleType()])
    (row,) = df.group_by("k").agg(stddev_samp(col("v")).alias("sd")) \
        .collect()
    assert row[1] == pytest.approx(statistics.stdev(vals), rel=1e-12)


def test_stddev_samp_constant_column_is_zero_not_nan():
    s = _s()
    df = _df(s, [[1] * 3, [0.1] * 3], ["k", "v"],
             [T.IntegerType(), T.DoubleType()])
    (row,) = df.group_by("k").agg(stddev_samp(col("v")).alias("sd")) \
        .collect()
    assert row[1] == 0.0


def test_stddev_samp_single_row_nan_empty_null():
    s = _s()
    df = _df(s, [[1, 2, 2], [5.0, None, None]], ["k", "v"],
             [T.IntegerType(), T.DoubleType()])
    rows = sorted(df.group_by("k").agg(
        stddev_samp(col("v")).alias("sd")).collect())
    assert rows[0][0] == 1 and math.isnan(rows[0][1])
    assert rows[1][0] == 2 and rows[1][1] is None

"""distinct / intersect / subtract / count(distinct) / stddev_samp
(reference: Spark set-op NULL semantics and CentralMomentAgg; TpcdsLike
queries q16/q28/q38/q87/q17/q39 are the consumers)."""
import math

import pytest

from spark_rapids_tpu import TpuSession
from spark_rapids_tpu import types as T
from spark_rapids_tpu.expr.aggregates import (CountDistinct, Sum,
                                              stddev_samp)
from spark_rapids_tpu.expr.core import col


def _s():
    return TpuSession({"spark.sql.shuffle.partitions": 2})


def _df(s, data, names, types):
    return s.from_pydict(
        dict(zip(names, data)),
        T.Schema([T.StructField(n, t, True)
                  for n, t in zip(names, types)]))


def test_distinct_nulls_and_dups():
    s = _s()
    df = _df(s, [[1, 1, None, None, 2], [5, 5, 7, 7, None]],
             ["a", "b"], [T.IntegerType(), T.LongType()])
    assert sorted(df.distinct().collect(), key=str) == \
        sorted([(1, 5), (None, 7), (2, None)], key=str)


def test_intersect_and_subtract_null_safe():
    s = _s()
    a = _df(s, [[1, 2, None, 3], [10, 20, 30, 40]],
            ["k", "v"], [T.IntegerType(), T.LongType()])
    b = _df(s, [[2, None, 4], [20, 30, 99]],
            ["k", "v"], [T.IntegerType(), T.LongType()])
    # NULL == NULL inside set operations (Spark INTERSECT/EXCEPT)
    assert sorted(a.intersect(b).collect(), key=str) == \
        sorted([(2, 20), (None, 30)], key=str)
    assert sorted(a.subtract(b).collect(), key=str) == \
        sorted([(1, 10), (3, 40)], key=str)


def test_set_op_marker_name_collision():
    s = _s()
    a = _df(s, [[1, 2], [1, 1]], ["_sop_a", "_sop_ia"],
            [T.IntegerType(), T.IntegerType()])
    b = _df(s, [[2], [1]], ["_sop_a", "_sop_ia"],
            [T.IntegerType(), T.IntegerType()])
    assert a.intersect(b).collect() == [(2, 1)]


def test_count_distinct_grouped_keeps_all_null_group():
    s = _s()
    df = _df(s, [[1, 1, 2, 2, 3], [10, 10, 20, 30, None]],
             ["k", "v"], [T.IntegerType(), T.LongType()])
    rows = sorted(df.group_by("k").agg(
        CountDistinct(col("v")).alias("c")).collect())
    # k=3 has only NULL v: Spark keeps the group with count 0
    assert rows == [(1, 1), (2, 2), (3, 0)]


def test_count_distinct_global_mixed_with_plain():
    s = _s()
    df = _df(s, [[1, 1, 2, 2, 3], [10, 10, 20, 30, None]],
             ["k", "v"], [T.IntegerType(), T.LongType()])
    rows = df.group_by().agg(CountDistinct(col("v")).alias("c"),
                             Sum(col("v")).alias("sv"),
                             CountDistinct(col("k")).alias("ck")).collect()
    assert rows == [(3, 70, 3)]


def test_count_distinct_multi_column():
    s = _s()
    df = _df(s, [[1, 1, 2, None], [5, 5, 6, 7]],
             ["a", "b"], [T.IntegerType(), T.LongType()])
    # tuples with any NULL component are not counted (Spark)
    rows = df.group_by().agg(
        CountDistinct(col("a"), col("b")).alias("c")).collect()
    assert rows == [(2,)]


def test_stddev_samp_matches_statistics():
    import statistics
    s = _s()
    vals = [3.0, 7.0, 7.0, 19.0]
    df = _df(s, [[1] * 4, vals], ["k", "v"],
             [T.IntegerType(), T.DoubleType()])
    (row,) = df.group_by("k").agg(stddev_samp(col("v")).alias("sd")) \
        .collect()
    assert row[1] == pytest.approx(statistics.stdev(vals), rel=1e-12)


def test_stddev_samp_constant_column_is_zero_not_nan():
    s = _s()
    df = _df(s, [[1] * 3, [0.1] * 3], ["k", "v"],
             [T.IntegerType(), T.DoubleType()])
    (row,) = df.group_by("k").agg(stddev_samp(col("v")).alias("sd")) \
        .collect()
    assert row[1] == 0.0


def test_stddev_samp_single_row_nan_empty_null():
    s = _s()
    df = _df(s, [[1, 2, 2], [5.0, None, None]], ["k", "v"],
             [T.IntegerType(), T.DoubleType()])
    rows = sorted(df.group_by("k").agg(
        stddev_samp(col("v")).alias("sd")).collect())
    assert rows[0][0] == 1 and math.isnan(rows[0][1])
    assert rows[1][0] == 2 and rows[1][1] is None


# -- sortedness propagation (agg-over-agg fast path) -------------------------

def test_agg_over_agg_presorted_fast_path():
    """VERDICT r3 item 4: the outer aggregation of an agg-over-agg plan
    must skip its re-sort — the inner aggregation's output already
    clusters the keys (reference seam: merge-aggregate loop,
    aggregate.scala:348-560)."""
    import numpy as np
    from spark_rapids_tpu.exec.aggregate import HashAggregateExec
    from spark_rapids_tpu.exec.core import collect_host
    from spark_rapids_tpu.expr.aggregates import Average, Sum

    schema = T.Schema([T.StructField("a", T.IntegerType(), True),
                       T.StructField("b", T.IntegerType(), True),
                       T.StructField("v", T.DoubleType(), True)])
    s = TpuSession({})
    rng = np.random.default_rng(1)
    df = s.from_pydict({"a": rng.integers(0, 20, 4000).astype(np.int32),
                        "b": rng.integers(0, 50, 4000).astype(np.int32),
                        "v": rng.normal(size=4000)}, schema, partitions=4)
    inner = df.group_by("a", "b").agg(Sum(col("v")).alias("sv"))
    outer = inner.group_by("a").agg(Average(col("sv")).alias("asv"))
    ov, meta = outer._overridden(quiet=True)

    presorted = []

    def walk(n):
        if isinstance(n, HashAggregateExec):
            presorted.append((n.mode, n._child_presorted(),
                              n.output_ordering))
        for c in n.children:
            walk(c)

    walk(meta.exec_node)
    # outer partial consumes the inner final's clustered output
    assert ("partial", True, ["a"]) in presorted
    # inner partial reads raw scan batches: must NOT claim presorted
    assert ("partial", False, ["a", "b"]) in presorted

    dev = sorted(outer.collect())
    host = sorted(collect_host(meta.exec_node, s.conf))
    assert len(dev) == len(host) == 20
    for d, h in zip(dev, host):
        assert d[0] == h[0] and abs(d[1] - h[1]) < 1e-9


def test_project_rename_preserves_ordering_for_agg():
    """A projection that renames the key still lets the downstream
    aggregate skip its sort (ordering maps through plain references)."""
    import numpy as np
    from spark_rapids_tpu.exec.aggregate import HashAggregateExec
    from spark_rapids_tpu.expr.aggregates import CountStar, Sum

    schema = T.Schema([T.StructField("a", T.IntegerType(), True),
                       T.StructField("v", T.DoubleType(), True)])
    s = TpuSession({})
    rng = np.random.default_rng(2)
    df = s.from_pydict({"a": rng.integers(0, 30, 2000).astype(np.int32),
                        "v": rng.normal(size=2000)}, schema, partitions=2)
    inner = df.group_by("a").agg(Sum(col("v")).alias("sv")) \
        .select(col("a").alias("k"), col("sv"))
    outer = inner.group_by("k").agg(CountStar().alias("n"))
    ov, meta = outer._overridden(quiet=True)

    found = []

    def walk(n):
        if isinstance(n, HashAggregateExec):
            found.append((n.mode, n._child_presorted()))
        for c in n.children:
            walk(c)

    walk(meta.exec_node)
    assert ("partial", True) in found
    rows = outer.collect()
    assert len(rows) == 30 and all(r[1] == 1 for r in rows)


def test_permuted_key_agg_does_not_claim_false_ordering():
    """Review finding: group_by('b','a') over an ('a','b')-clustered
    child must NOT take the presorted fast path (a set-match would keep
    the child arrangement while claiming bound-key order, and a
    downstream group_by('b') would then skip a sort it needs)."""
    import numpy as np
    from spark_rapids_tpu.exec.core import collect_host
    from spark_rapids_tpu.expr.aggregates import Average, CountStar, Sum

    schema = T.Schema([T.StructField("a", T.IntegerType(), True),
                       T.StructField("b", T.IntegerType(), True),
                       T.StructField("v", T.DoubleType(), True)])
    s = TpuSession({})
    rng = np.random.default_rng(5)
    df = s.from_pydict({"a": rng.integers(0, 15, 3000).astype(np.int32),
                        "b": rng.integers(0, 40, 3000).astype(np.int32),
                        "v": rng.normal(size=3000)}, schema, partitions=3)
    agg1 = df.group_by("a", "b").agg(Sum(col("v")).alias("sv"))
    agg2 = agg1.group_by("b", "a").agg(Sum(col("sv")).alias("s2"))
    agg3 = agg2.group_by("b").agg(Average(col("s2")).alias("m"),
                                  CountStar().alias("n"))
    ov, meta = agg3._overridden(quiet=True)
    dev = sorted(agg3.collect())
    host = sorted(collect_host(meta.exec_node, s.conf))
    assert len(dev) == len(host) == 40
    for d, h in zip(dev, host):
        assert d[0] == h[0] and abs(d[1] - h[1]) < 1e-9 and d[2] == h[2]


def test_rollup_reaggregation_matches_raw_expand():
    """Rollup/cube pre-aggregates at full key granularity and re-merges
    per grouping set when every aggregate is re-aggregable; results must
    be identical to expanding the raw input (and the plan must show the
    Expand feeding off the base aggregate)."""
    import numpy as np
    import spark_rapids_tpu.session as S
    from spark_rapids_tpu.expr.core import grouping_id
    from spark_rapids_tpu.expr.aggregates import (Average, Count,
                                                  CountStar, Max, Min, Sum)

    schema = T.Schema([T.StructField("a", T.IntegerType(), True),
                       T.StructField("b", T.IntegerType(), True),
                       T.StructField("v", T.DoubleType(), True)])
    s = TpuSession({})
    rng = np.random.default_rng(9)
    v = rng.normal(size=2500)
    v[::41] = np.nan
    df = s.from_pydict({"a": rng.integers(0, 8, 2500).astype(np.int32),
                        "b": rng.integers(0, 12, 2500).astype(np.int32),
                        "v": v}, schema, partitions=3)

    def query():
        return df.cube("a", "b").agg(
            Sum(col("v")).alias("sv"), Average(col("v")).alias("av"),
            CountStar().alias("n"), Min(col("v")).alias("mn"),
            Max(col("v")).alias("mx"), Count(col("v")).alias("c"),
            grouping_id().alias("gid"))

    q = query()
    ex = q.explain()
    assert "HashAggregateExec" in ex.split("ExpandExec")[1]
    new = sorted(q.collect(), key=str)
    orig = S._decompose_reagg
    S._decompose_reagg = lambda aggs: None
    try:
        old = sorted(query().collect(), key=str)
    finally:
        S._decompose_reagg = orig

    def eq(x, y):
        if isinstance(x, float) and isinstance(y, float):
            return (np.isnan(x) and np.isnan(y)) or \
                abs(x - y) < 1e-9 * max(1, abs(x))
        return x == y

    assert len(new) == len(old)
    for d, h in zip(new, old):
        assert all(eq(p, q2) for p, q2 in zip(d, h)), (d, h)


def test_rollup_first_falls_back_to_raw_expand():
    """first() is not re-aggregable: the rollup must keep expanding the
    raw input (plan shows Expand directly over the scan side)."""
    import numpy as np
    from spark_rapids_tpu.expr.aggregates import First

    schema = T.Schema([T.StructField("a", T.IntegerType(), True),
                       T.StructField("v", T.DoubleType(), True)])
    s = TpuSession({})
    rng = np.random.default_rng(4)
    df = s.from_pydict({"a": rng.integers(0, 5, 300).astype(np.int32),
                        "v": rng.normal(size=300)}, schema)
    q = df.rollup("a").agg(First(col("v")).alias("f"))
    ex = q.explain()
    below_expand = ex.split("ExpandExec")[1]
    assert "HashAggregateExec" not in below_expand.split("ProjectExec")[0]
    assert len(q.collect()) == 6


def test_float_agg_conf_gates():
    """variableFloatAgg=false refuses any float aggregation on device;
    exactDoubleAggregation=true refuses DOUBLE ones specifically (TPU
    f64 is a float32-pair emulation) — both fall back with reasons and
    still produce correct results via the host engine."""
    import numpy as np
    from spark_rapids_tpu.expr.aggregates import Sum

    schema = T.Schema([T.StructField("k", T.IntegerType(), True),
                       T.StructField("d", T.DoubleType(), True),
                       T.StructField("i", T.LongType(), True)])
    rng = np.random.default_rng(0)
    data = {"k": rng.integers(0, 4, 100).astype(np.int32),
            "d": rng.normal(size=100),
            "i": rng.integers(0, 100, 100).astype(np.int64)}

    s = TpuSession({"spark.rapids.sql.exactDoubleAggregation": "true"})
    df = s.from_pydict(data, schema)
    q = df.group_by("k").agg(Sum(col("d")).alias("sd"))
    assert "double aggregation forced to host" in q.explain()
    assert len(q.collect()) == 4
    # integer aggs unaffected
    qi = df.group_by("k").agg(Sum(col("i")).alias("si"))
    assert "forced to host" not in qi.explain()

    s2 = TpuSession({"spark.rapids.sql.variableFloatAgg.enabled": "false"})
    q2 = s2.from_pydict(data, schema).group_by("k") \
        .agg(Sum(col("d")).alias("sd"))
    assert "float aggregation disabled" in q2.explain()
    assert len(q2.collect()) == 4


def test_exact_double_agg_gate_covers_mesh_aggregates():
    """Mesh lowering (MeshAggregateExec) must honor the same
    float/double gates as the single-chip aggregate (review finding:
    the isinstance check bypassed it)."""
    import numpy as np
    from spark_rapids_tpu.expr.aggregates import Sum

    schema = T.Schema([T.StructField("k", T.IntegerType(), True),
                       T.StructField("d", T.DoubleType(), True)])
    rng = np.random.default_rng(1)
    s = TpuSession({"spark.rapids.tpu.mesh.deviceCount": 8,
                    "spark.rapids.sql.exactDoubleAggregation": "true"})
    df = s.from_pydict({"k": rng.integers(0, 4, 64).astype(np.int32),
                        "d": rng.normal(size=64)}, schema)
    q = df.group_by("k").agg(Sum(col("d")).alias("sd"))
    ex = q.explain()
    assert "MeshAggregateExec" in ex
    assert "double aggregation forced to host" in ex
    assert len(q.collect()) == 4


def test_percentile_holistic_plan_and_results():
    """Percentile has no mergeable intermediate: the planner must use a
    whole-input complete aggregation (no partial/final split, no mesh
    program) and match numpy's linear interpolation exactly."""
    import numpy as np
    from spark_rapids_tpu.expr.aggregates import Average, Percentile, Sum

    schema = T.Schema([T.StructField("k", T.IntegerType(), True),
                       T.StructField("v", T.DoubleType(), True)])
    rng = np.random.default_rng(3)
    k = rng.integers(0, 6, 3000).astype(np.int32)
    v = rng.normal(size=3000) * 7
    s = TpuSession({})
    df = s.from_pydict({"k": k, "v": v}, schema, partitions=4)
    q = df.group_by("k").agg(Percentile(col("v"), 0.5).alias("p50"),
                             Percentile(col("v"), 0.99).alias("p99"),
                             Sum(col("v")).alias("sv"),
                             Average(col("v")).alias("av"))
    ex = q.explain()
    assert "HashAggregateExec[complete" in ex
    assert "partial" not in ex
    got = {r[0]: r for r in q.collect()}
    for g in range(6):
        seg = v[k == g]
        assert abs(got[g][1] - np.percentile(seg, 50)) < 1e-9
        assert abs(got[g][2] - np.percentile(seg, 99)) < 1e-9
        assert abs(got[g][3] - seg.sum()) < 1e-9

    # mesh sessions must fall back off the mesh program too
    sm = TpuSession({"spark.rapids.tpu.mesh.deviceCount": 8})
    qm = sm.from_pydict({"k": k, "v": v}, schema, partitions=4) \
        .group_by("k").agg(Percentile(col("v"), 0.5).alias("p"))
    exm = qm.explain()
    assert "MeshAggregateExec" not in exm
    assert len(qm.collect()) == 6

    # out-of-range fraction refused up front
    import pytest as _pt
    with _pt.raises(ValueError, match="fraction"):
        Percentile(col("v"), 1.5)


def test_percentile_with_first_last_rejected():
    """The percentile value-sort would change which row first/last
    observe on device (host keeps input order) — refuse the mix."""
    import pytest as _pt
    from spark_rapids_tpu.expr.aggregates import First, Percentile

    schema = T.Schema([T.StructField("k", T.IntegerType(), True),
                       T.StructField("v", T.DoubleType(), True)])
    s = TpuSession({})
    df = s.from_pydict({"k": [0, 0, 1], "v": [1.0, 2.0, 3.0]}, schema)
    with _pt.raises(NotImplementedError, match="first/last"):
        df.group_by("k").agg(Percentile(col("v"), 0.5).alias("p"),
                             First(col("v")).alias("f")).collect()

"""Memory runtime + task parallelism wired into execution.

Round-3 verdict item 2: shuffle outputs must live in the BufferCatalog as
spillable buffers (not raw HBM lists), partitions must execute
concurrently under the DeviceSemaphore, and a query over data larger than
the device spill budget must pass by spilling (reference
RapidsCachingWriter + DeviceMemoryEventHandler + GpuSemaphore).
"""
import time

import numpy as np
import pytest

from spark_rapids_tpu import types as T
from spark_rapids_tpu.conf import TpuConf
from spark_rapids_tpu.exec.basic import LocalScanExec, ProjectExec
from spark_rapids_tpu.exec.core import ExecCtx, PlanNode, device_to_host
from spark_rapids_tpu.exec.exchange import ShuffleExchangeExec
from spark_rapids_tpu.exec.partitioning import HashPartitioning
from spark_rapids_tpu.expr.core import col


def _scan(n=1000, partitions=4, rows_per_batch=None):
    data = {"k": list(range(n)), "v": [float(i) for i in range(n)]}
    schema = T.Schema([T.StructField("k", T.LongType()),
                       T.StructField("v", T.DoubleType())])
    return LocalScanExec.from_pydict(data, schema, partitions,
                                     rows_per_batch or (n // partitions))


def _rows(plan, ctx):
    out = []
    for b in plan.execute(ctx):
        hb = device_to_host(b) if ctx.is_device else b
        cols = [c.to_list() for c in hb.columns]
        out.extend(zip(*cols))
    return sorted(out)


def test_shuffle_output_spills_and_restores():
    """Shuffle map output larger than a tiny device budget spills to the
    host arena and is restored on read; results stay correct."""
    plan = ShuffleExchangeExec(HashPartitioning([col("k")], 3), _scan())
    conf = TpuConf({"spark.rapids.memory.tpu.spillStoreSize": 1 << 10})
    ctx = ExecCtx(backend="device", conf=conf)
    rows = _rows(plan, ctx)
    catalog = ctx.cache["catalog"]
    assert catalog.metrics["device_spills"] > 0, \
        "tiny budget must force shuffle-output spills"
    host_ctx = ExecCtx(backend="host")
    assert rows == _rows(plan, host_ctx)


def test_spill_survives_disk_tier():
    """Host arena too small as well -> buffers continue to disk."""
    plan = ShuffleExchangeExec(HashPartitioning([col("k")], 3),
                               _scan(n=4000))
    conf = TpuConf({"spark.rapids.memory.tpu.spillStoreSize": 1 << 10,
                    "spark.rapids.memory.host.spillStorageSize": 1 << 12})
    ctx = ExecCtx(backend="device", conf=conf)
    rows = _rows(plan, ctx)
    catalog = ctx.cache["catalog"]
    assert catalog.metrics["bytes_spilled_to_disk"] > 0
    assert rows == _rows(plan, ExecCtx(backend="host"))


class _SlowScan(LocalScanExec):
    """Leaf that sleeps per partition: measures drain concurrency."""

    def __init__(self, delay, *a, **kw):
        super().__init__(*a, **kw)
        self._delay = delay

    def partition_iter(self, ctx, pid):
        time.sleep(self._delay)
        yield from super().partition_iter(ctx, pid)


def _slow_plan(delay=0.25, partitions=4):
    data = {"k": list(range(64)), "v": [float(i) for i in range(64)]}
    schema = T.Schema([T.StructField("k", T.LongType()),
                       T.StructField("v", T.DoubleType())])
    cols = [c for c in schema]
    base = LocalScanExec.from_pydict(data, schema, partitions, 16)
    slow = _SlowScan(delay, base._batches, schema, partitions)
    return ProjectExec([col("k"), (col("v") * col("v")).alias("v2")], slow)


def test_concurrent_partition_drain_speedup():
    plan = _slow_plan()
    seq_conf = TpuConf({"spark.rapids.sql.concurrentTpuTasks": 1})
    par_conf = TpuConf({"spark.rapids.sql.concurrentTpuTasks": 4})
    # warm compile caches first so timing measures the drain, not XLA
    _rows(plan, ExecCtx(backend="device", conf=par_conf))
    t0 = time.perf_counter()
    seq_rows = _rows(plan, ExecCtx(backend="device", conf=seq_conf))
    seq_t = time.perf_counter() - t0
    t0 = time.perf_counter()
    par_rows = _rows(plan, ExecCtx(backend="device", conf=par_conf))
    par_t = time.perf_counter() - t0
    assert par_rows == seq_rows
    assert par_t < seq_t / 1.8, (seq_t, par_t)


def test_dispatch_concurrency_semaphore_bound():
    """The semaphore caps simultaneous dispatches at the conf value."""
    import threading
    conf = TpuConf({"spark.rapids.sql.concurrentTpuTasks": 2})
    ctx = ExecCtx(backend="device", conf=conf)
    active, peak = [0], [0]
    lock = threading.Lock()

    def probe():
        with lock:
            active[0] += 1
            peak[0] = max(peak[0], active[0])
        time.sleep(0.05)
        with lock:
            active[0] -= 1
        return 0

    threads = [threading.Thread(target=lambda: ctx.dispatch(probe))
               for _ in range(6)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert peak[0] <= 2


def test_per_operator_metrics_recorded():
    """Every operator in the plan records totalTime/numOutputBatches
    (reference GpuMetricNames wired into every GpuExec,
    GpuExec.scala:27-56) — not just the root."""
    import numpy as np
    from spark_rapids_tpu import types as T
    from spark_rapids_tpu.exec.core import ExecCtx
    from spark_rapids_tpu.expr.aggregates import Sum
    from spark_rapids_tpu.expr.core import col
    from spark_rapids_tpu.session import TpuSession

    s = TpuSession({})
    schema = T.Schema([T.StructField("k", T.IntegerType()),
                       T.StructField("v", T.LongType())])
    rng = np.random.default_rng(3)
    df = s.from_pydict(
        {"k": [int(x) for x in rng.integers(0, 5, 200)],
         "v": list(range(200))}, schema, partitions=2, rows_per_batch=32)
    out = df.where(col("v") >= 0).group_by("k").agg(
        Sum(col("v")).alias("sv"))
    ov, meta = out._overridden(quiet=True)
    with ExecCtx(backend="device", conf=s.conf) as ctx:
        list(meta.exec_node.execute(ctx))
        names = {k.split("@")[0] for k, m in ctx.metrics.items()
                 if m["numOutputBatches"] > 0}
    assert "FilterExec" in names
    assert any("Aggregate" in n for n in names)
    assert any("Scan" in n for n in names)
    # host backend additionally counts rows
    with ExecCtx(backend="host", conf=s.conf) as ctx:
        list(meta.exec_node.execute(ctx))
        rows = {k.split("@")[0]: m["numOutputRows"]
                for k, m in ctx.metrics.items()}
    assert any(v > 0 for v in rows.values())


def test_metrics_disabled_conf():
    import numpy as np
    from spark_rapids_tpu import types as T
    from spark_rapids_tpu.exec.core import ExecCtx
    from spark_rapids_tpu.session import TpuSession

    s = TpuSession({"spark.rapids.sql.metrics.enabled": False})
    schema = T.Schema([T.StructField("v", T.LongType())])
    df = s.from_pydict({"v": list(range(50))}, schema)
    ov, meta = df._overridden(quiet=True)
    with ExecCtx(backend="device", conf=s.conf) as ctx:
        list(meta.exec_node.execute(ctx))
        assert not any(m.values for m in ctx.metrics.values())


def test_xprof_trace_capture(tmp_path):
    """spark.rapids.tpu.profile.dir records an xprof trace of the
    execution (reference: NVTX ranges + nsight timelines, §5.1)."""
    import glob
    import os
    from spark_rapids_tpu import types as T
    from spark_rapids_tpu.session import TpuSession

    d = str(tmp_path / "xprof")
    s = TpuSession({"spark.rapids.tpu.profile.dir": d})
    schema = T.Schema([T.StructField("v", T.LongType())])
    df = s.from_pydict({"v": list(range(100))}, schema)
    assert len(df.collect()) == 100
    traces = glob.glob(os.path.join(d, "**", "*.xplane.pb"),
                       recursive=True) + \
        glob.glob(os.path.join(d, "**", "*.trace.json.gz"), recursive=True)
    assert traces, f"no trace files under {d}"

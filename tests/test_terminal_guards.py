"""Terminal-exception discipline (enginelint RL001) at the sites this
PR fixed or pinned: a ``terminal = True`` lifecycle error
(QueryCancelled / QueryDeadlineExceeded / MapOutputLostError) must
never be swallowed by per-item isolation handlers — it aborts the
whole operation — while ordinary per-item errors keep their existing
isolation semantics.  One representative site per subsystem:

* bench: ``run_benchmark``'s per-query handler (the RL001 fix in this
  PR) re-raises lifecycle errors (QueryLifecycleError) instead of
  recording them as a per-query failure and benchmarking on in a
  killed session — data-loss terminals (MapOutputLostError, recovery
  exhaustion) kill only their query and stay in the report;
* shuffle: ``fetch_remote_with_retry`` surfaces a terminal fetch error
  immediately — no retry ladder, no breaker penalty;
* exec: a terminal error raised mid-drain propagates out of
  ``collect()`` (the finally-block future cleanup must not eat it).
"""
import numpy as np
import pytest

from spark_rapids_tpu import TpuSession
from spark_rapids_tpu import types as T
from spark_rapids_tpu.exec.lifecycle import QueryCancelled
from spark_rapids_tpu.expr.core import col, lit
from spark_rapids_tpu.shuffle.errors import ShuffleFetchError

SCHEMA = T.Schema([T.StructField("k", T.IntegerType(), True),
                   T.StructField("v", T.LongType(), True)])


def test_lifecycle_errors_are_terminal():
    assert QueryCancelled("q").terminal is True


# ---------------------------------------------------------------------------
# bench: per-query isolation must not absorb a terminal error
# ---------------------------------------------------------------------------

@pytest.fixture(scope="module")
def tpch_dir(tmp_path_factory):
    from spark_rapids_tpu.bench.tpch_gen import generate_tpch
    d = str(tmp_path_factory.mktemp("tpch_guards") / "sf001")
    generate_tpch(d, sf=0.01)
    return d


def test_bench_reraises_terminal(tpch_dir, monkeypatch):
    from spark_rapids_tpu.bench import runner

    def cancelled(df, backend, plan=None, **kw):
        raise QueryCancelled("bench-q6", "session shut down")

    monkeypatch.setattr(runner, "_collect_rows", cancelled)
    with pytest.raises(QueryCancelled):
        runner.run_benchmark(tpch_dir, 0.01, ["q6"], suite="tpch",
                             generate=False)


def test_bench_records_nonterminal_and_continues(tpch_dir, monkeypatch):
    from spark_rapids_tpu.bench import runner

    def broken(df, backend, plan=None, **kw):
        raise ValueError("synthetic per-query failure")

    monkeypatch.setattr(runner, "_collect_rows", broken)
    reports = runner.run_benchmark(tpch_dir, 0.01, ["q6", "q1"],
                                   suite="tpch", generate=False)
    assert [r["query"] for r in reports] == ["q6", "q1"]
    assert all(not r["ok"] for r in reports)
    assert all(r["error"].startswith("ValueError") for r in reports)


# ---------------------------------------------------------------------------
# shuffle: terminal fetch errors skip the retry ladder entirely
# ---------------------------------------------------------------------------

def test_fetch_retry_surfaces_terminal_immediately(monkeypatch):
    from spark_rapids_tpu.shuffle import retry

    attempts = []

    def dead_peer(peer, shuffle_id, part_id, **kw):
        attempts.append(1)
        err = ShuffleFetchError("map output lost")
        err.terminal = True
        raise err
        yield  # pragma: no cover - keeps this a generator

    monkeypatch.setattr(retry, "fetch_remote", dead_peer)
    with pytest.raises(ShuffleFetchError):
        list(retry.fetch_remote_with_retry(
            ("127.0.0.1", 1), "s", 0, max_retries=5, retry_wait=0.0))
    assert len(attempts) == 1  # no reconnects against lost DATA


def test_fetch_retry_still_retries_transient(monkeypatch):
    from spark_rapids_tpu.shuffle import retry
    retry.reset_circuit_breakers()

    attempts = []

    def flaky(peer, shuffle_id, part_id, **kw):
        attempts.append(1)
        raise ShuffleFetchError("connection reset")
        yield  # pragma: no cover

    monkeypatch.setattr(retry, "fetch_remote", flaky)
    with pytest.raises(ShuffleFetchError) as ei:
        list(retry.fetch_remote_with_retry(
            ("127.0.0.1", 2), "s", 0, max_retries=2, retry_wait=0.0,
            backoff=1.0))
    assert len(attempts) == 3  # first try + 2 retries
    assert ei.value.terminal is True  # exhaustion marks it terminal


# ---------------------------------------------------------------------------
# exec: terminal errors propagate out of the collect drain
# ---------------------------------------------------------------------------

def test_collect_drain_propagates_terminal(monkeypatch):
    from spark_rapids_tpu.exec.basic import LocalScanExec

    s = TpuSession({})
    data = {"k": (np.arange(16) % 4).astype(np.int32),
            "v": np.arange(16, dtype=np.int64)}
    df = s.from_pydict(data, SCHEMA, partitions=2).filter(
        col("v") >= lit(0))

    def cancelled_iter(self, ctx, pid):
        raise QueryCancelled("drain-q", "cancelled mid-stream")
        yield  # pragma: no cover

    monkeypatch.setattr(LocalScanExec, "partition_iter", cancelled_iter)
    with pytest.raises(QueryCancelled):
        df.collect()

"""TPCx-BB suite: the 19 reference-runnable BigBench queries verify vs
the host oracle; the 11 the reference refuses raise the same reasons
(reference TpcxbbLikeSpark.scala:808-2130)."""
import os

import pytest

from spark_rapids_tpu.bench.runner import run_benchmark
from spark_rapids_tpu.bench.tpcxbb_gen import generate_tpcxbb
from spark_rapids_tpu.bench.tpcxbb_queries import (TPCXBB_QUERIES,
                                                   UNSUPPORTED,
                                                   build_tpcxbb_query)


@pytest.fixture(scope="module")
def data_dir(tmp_path_factory):
    d = str(tmp_path_factory.mktemp("tpcxbb") / "sf001")
    generate_tpcxbb(d, sf=0.01)
    return d


def test_query_registry_matches_reference():
    assert len(TPCXBB_QUERIES) == 19
    assert len(UNSUPPORTED) == 11
    assert set(TPCXBB_QUERIES) | set(UNSUPPORTED) == {
        f"q{i}" for i in range(1, 31)}


def test_unsupported_refused_like_reference():
    with pytest.raises(NotImplementedError, match="UDTF"):
        build_tpcxbb_query("q1", None, "")
    with pytest.raises(NotImplementedError, match="python"):
        build_tpcxbb_query("q3", None, "")
    with pytest.raises(NotImplementedError, match="UDF"):
        build_tpcxbb_query("q10", None, "")


# default (premerge) smoke runs the cross-section with non-empty
# results at SF0.01; TPCXBB_FULL=1 sweeps all 19
_SMOKE = ["q5", "q6", "q11", "q12", "q14", "q24", "q25", "q28"]
_SUITE = sorted(set(TPCXBB_QUERIES) - {"q20"}) \
    if os.environ.get("TPCXBB_FULL") == "1" else _SMOKE


@pytest.mark.parametrize("query", _SUITE)
def test_query_device_matches_oracle(data_dir, query):
    r = run_benchmark(data_dir, 0.01, [query], verify=True,
                      generate=False, suite="tpcxbb")[0]
    assert "error" not in r, r
    assert r["ok"], r


def test_smoke_queries_return_rows(data_dir):
    """The smoke subset must produce data at SF0.01 — a 0-row
    verification verifies nothing (round-2 verdict's q6 lesson)."""
    from spark_rapids_tpu.session import TpuSession
    for name in ("q5", "q6", "q12", "q20", "q25", "q28"):
        s = TpuSession({})
        assert len(TPCXBB_QUERIES[name](s, data_dir).collect()) > 0, name


def test_q20_device_matches_oracle_with_round_tolerance(data_dir):
    """q20's ratios are money quotients rounded HALF_UP at 7 decimals:
    values land EXACTLY on the rounding boundary, and 1-ulp summation-
    order noise between the device and the oracle legally flips the
    7th decimal — so q20 verifies with a one-unit-in-the-7th-decimal
    tolerance instead of the runner's 6-significant-digit normalizer."""
    import math
    from spark_rapids_tpu.exec.core import collect_host
    from spark_rapids_tpu.session import TpuSession

    s = TpuSession({})
    q = TPCXBB_QUERIES["q20"](s, data_dir)
    dev = sorted(q.collect())
    ov, meta = q._overridden(quiet=True)
    host = sorted(collect_host(meta.exec_node, s.conf))
    assert len(dev) == len(host) > 0
    for a, b in zip(dev, host):
        assert a[0] == b[0]
        for x, y in zip(a[1:], b[1:]):
            if x is None or y is None:
                assert x == y
            elif isinstance(x, float):
                assert math.isclose(x, y, rel_tol=0, abs_tol=1.01e-7), \
                    (a, b)
            else:
                assert x == y

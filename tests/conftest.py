"""Test configuration: run everything on a virtual 8-device CPU mesh.

Mirrors the reference's local-mode test strategy (docs/testing.md:42-66):
no cluster needed; multi-device behavior is tested on virtual devices.
"""
import os

# force CPU (the shell points JAX at a real TPU via JAX_PLATFORMS=axon, and
# a sitecustomize may import jax before us — so set the env var AND update
# the config after import)
os.environ["JAX_PLATFORMS"] = "cpu"
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8").strip()

try:  # pin pyarrow pools before ANY use (see runtime.pin_arrow_threads)
    import pyarrow as _pa
    _pa.set_cpu_count(1)
    _pa.set_io_thread_count(1)
except ImportError:
    pass

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")
# sync dispatch: async executions on XLA's native pool racing a compile
# on an engine thread segfault this XLA build (runtime.sync_cpu_dispatch)
jax.config.update("jax_cpu_enable_async_dispatch", False)

import numpy as np  # noqa: E402
import pytest  # noqa: E402


def pytest_configure(config):
    config.addinivalue_line(
        "markers", "slow: long-running; excluded from the tier-1 run")


@pytest.fixture
def rng():
    return np.random.default_rng(42)

"""Operator-level OOM retry framework unit tests (memory/retry.py).

Mirrors the reference's RmmRetryIteratorSuite / WithRetrySuite coverage:
split ordering, the row floor, checkpoint/restore bracketing, spillable
input pin/close lifecycle, the no-split scope, and the sync-point redo
path — all driven by the deterministic ``memory.oom`` /
``memory.oom.until_rows`` fault points (no real device exhaustion).
"""
import threading

import pytest

from spark_rapids_tpu import types as T
from spark_rapids_tpu.faults import FaultRegistry
from spark_rapids_tpu.host.batch import HostBatch
from spark_rapids_tpu.memory import (BufferCatalog, SpillPriority,
                                     SpillableColumnarBatch,
                                     SplitAndRetryOOM, retry_sync,
                                     split_half, with_retry,
                                     with_retry_no_split)

SCHEMA = T.Schema([T.StructField("a", T.LongType(), True)])


def _batch(n=100):
    return HostBatch.from_pydict(
        {"a": list(range(n))}, SCHEMA).to_device()


def _vals(b):
    return [r[0] for r in HostBatch.from_device(b).to_rows()]


def _cat(faults: str | None = None):
    cat = BufferCatalog(device_limit=10 << 20, host_limit=1 << 24)
    if faults:
        cat.faults = FaultRegistry(faults, seed=0)
    return cat


# ---------------------------------------------------------------------------
# split_half
# ---------------------------------------------------------------------------

def test_split_half_order_and_rows():
    lo, hi = split_half(_batch(101))
    assert lo.host_num_rows() == 51 and hi.host_num_rows() == 50
    assert _vals(lo) + _vals(hi) == list(range(101))


def test_split_half_single_row_raises():
    with pytest.raises(SplitAndRetryOOM):
        split_half(_batch(1))


# ---------------------------------------------------------------------------
# with_retry
# ---------------------------------------------------------------------------

def test_with_retry_passthrough_no_fault():
    cat = _cat()
    out = with_retry(lambda b: b, cat, _batch(64), op="ident")
    assert len(out) == 1 and _vals(out[0]) == list(range(64))
    assert cat.metrics["oom_retries"] == 0
    assert cat.metrics["oom_splits"] == 0
    cat.close()


def test_until_rows_storm_splits_in_order():
    """OOM persists while the dispatched piece is above the threshold:
    the scope must halve until every piece fits, emitting partial
    outputs in row order (reference splitSpillableInHalfByRows)."""
    cat = _cat("memory.oom.until_rows:oom,until_rows=20")
    out = with_retry(lambda b: b, cat, _batch(100), op="ident",
                     min_split_rows=4)
    assert [v for p in out for v in _vals(p)] == list(range(100))
    assert all(p.host_num_rows() <= 20 for p in out)
    assert cat.metrics["oom_splits"] > 0
    assert cat.metrics["oom_retries"] >= cat.metrics["oom_splits"]
    cat.close()


def test_row_floor_stops_splitting():
    """A half below minSplitRows must not be produced: the OOM
    propagates as SplitAndRetryOOM at the floor."""
    cat = _cat("memory.oom.until_rows:oom,until_rows=20")
    with pytest.raises(SplitAndRetryOOM, match="split"):
        with_retry(lambda b: b, cat, _batch(100), op="ident",
                   min_split_rows=32)
    cat.close()


def test_max_retries_exhausted_propagates_oom():
    """When spill keeps reporting progress but the OOM persists, the
    attempt budget bounds the loop and the ORIGINAL exhaustion
    propagates (attempts are checked before the split decision)."""
    cat = _cat("memory.oom:oom,times=0")  # times=0: unlimited
    cat.spill_device = lambda target: 1   # spill always "frees" a byte
    with pytest.raises(RuntimeError, match="RESOURCE_EXHAUSTED"):
        with_retry(lambda b: b, cat, _batch(64), op="ident",
                   max_retries=3)
    assert cat.metrics["oom_retries"] == 4  # 3 retries + the final one
    cat.close()


def test_with_retry_no_split_raises_split_oom():
    """withRetryNoSplit semantics: when spill frees nothing the scope
    must NOT split (total-order outputs) — SplitAndRetryOOM instead."""
    cat = _cat("memory.oom.until_rows:oom,until_rows=20")
    with pytest.raises(SplitAndRetryOOM, match="splitting disabled"):
        with_retry_no_split(lambda b: b, cat, _batch(100), op="sort")
    cat.close()


def test_checkpoint_restore_brackets_attempts():
    """A failed attempt must leave no half-applied state (reference
    Retryable.checkpoint/restore): fn mutates an accumulator and the
    scope restores it before each re-attempt."""
    cat = _cat()
    state = {"applied": 0}
    restored = []
    calls = {"n": 0}

    def fn(b):
        state["applied"] += b.host_num_rows()  # mutate BEFORE failing
        calls["n"] += 1
        if calls["n"] == 1:
            raise RuntimeError("RESOURCE_EXHAUSTED: synthetic")
        return b

    out = with_retry(
        fn, cat, _batch(100), op="agg", min_split_rows=4,
        checkpoint=lambda: dict(state),
        restore=lambda s: (restored.append(True), state.update(s)))
    # first attempt (100 rows) failed and was restored; the surviving
    # pieces' contributions are exactly the emitted rows
    assert restored == [True]
    assert state["applied"] == sum(p.host_num_rows() for p in out)
    assert [v for p in out for v in _vals(p)] == list(range(100))
    cat.close()


def test_spillable_input_closed_on_split_and_unpinned_on_success():
    cat = _cat("memory.oom.until_rows:oom,until_rows=60")
    src = SpillableColumnarBatch(_batch(100), cat, SpillPriority.READ_SHUFFLE)
    out = with_retry(lambda b: b, cat, src, op="ident", min_split_rows=4)
    assert [v for p in out for v in _vals(p)] == list(range(100))
    # the original spillable was replaced by its halves and closed
    assert src._closed
    assert src._pins == 0
    cat.close()


def test_spillable_input_unpinned_on_success_no_fault():
    cat = _cat()
    src = SpillableColumnarBatch(_batch(64), cat, SpillPriority.READ_SHUFFLE)
    out = with_retry(lambda b: b, cat, src, op="ident")
    assert len(out) == 1 and _vals(out[0]) == list(range(64))
    assert not src._closed and src._pins == 0  # spillable again
    src.close()
    cat.close()


def test_pairs_mode_returns_processed_pieces():
    cat = _cat("memory.oom.until_rows:oom,until_rows=30")
    out = with_retry(lambda b: b, cat, _batch(100), op="ident",
                     pairs=True, min_split_rows=4)
    assert len(out) > 1
    for piece, result in out:
        assert _vals(piece) == _vals(result)
    cat.close()


def test_retry_recovers_after_spill_frees_memory():
    """When spilling DOES free device bytes the piece is retried whole
    — no split (the reference's plain RetryOOM path)."""
    cat = _cat("memory.oom:oom,times=1")
    # an unpinned spillable gives the spill pass something to evict
    parked = SpillableColumnarBatch(_batch(256), cat, SpillPriority.READ_SHUFFLE)
    out = with_retry(lambda b: b, cat, _batch(100), op="ident")
    assert len(out) == 1 and _vals(out[0]) == list(range(100))
    assert cat.metrics["oom_retries"] == 1
    assert cat.metrics["oom_splits"] == 0
    assert cat.metrics["device_spills"] >= 1
    parked.close()
    cat.close()


def test_disabled_conf_falls_back_to_plain_dispatch():
    """oomRetry.enabled=false: only the legacy spill-and-retry hook
    runs; until_rows rules never fire there (no rows context) so the
    fn executes once, unsplit."""
    cat = _cat("memory.oom.until_rows:oom,until_rows=20")
    settings = {"spark.rapids.memory.tpu.oomRetry.enabled": False}
    out = with_retry(lambda b: b, cat, _batch(100), op="ident",
                     settings=settings)
    assert len(out) == 1 and out[0].host_num_rows() == 100
    assert cat.metrics["oom_splits"] == 0
    cat.close()


def test_non_oom_error_propagates_immediately():
    cat = _cat()

    def boom(b):
        raise RuntimeError("schema mismatch")

    with pytest.raises(RuntimeError, match="schema mismatch"):
        with_retry(boom, cat, _batch(64), op="ident")
    assert cat.metrics["oom_retries"] == 0
    cat.close()


# ---------------------------------------------------------------------------
# retry_sync (the async-dispatch sync-point gap)
# ---------------------------------------------------------------------------

def test_retry_sync_redoes_poisoned_work():
    cat = _cat("memory.oom:oom,op=flushpt,times=1")
    redone = []
    vals = {"x": 1}

    def redo():
        redone.append(True)
        vals["x"] = 2  # re-derive the poisoned value

    assert retry_sync(lambda: vals["x"], cat, redo=redo,
                      op="flushpt") == 2
    assert redone == [True]
    assert cat.metrics["oom_retries"] == 1
    cat.close()


def test_retry_sync_passthrough_without_fault():
    cat = _cat()
    assert retry_sync(lambda: 7, cat, op="flushpt") == 7
    assert cat.metrics["oom_retries"] == 0
    cat.close()


def test_retry_sync_budget_exhausts():
    cat = _cat("memory.oom:oom,op=flushpt,times=0")
    with pytest.raises(RuntimeError, match="RESOURCE_EXHAUSTED"):
        retry_sync(lambda: 7, cat, op="flushpt", max_retries=2)
    cat.close()


# ---------------------------------------------------------------------------
# fault rule semantics for until_rows
# ---------------------------------------------------------------------------

def test_until_rows_rule_needs_rows_context():
    reg = FaultRegistry("memory.oom.until_rows:oom,until_rows=100",
                        seed=0)
    assert reg.check("memory.oom.until_rows") is None  # no rows ctx
    assert reg.check("memory.oom.until_rows", rows=100) is None
    assert reg.check("memory.oom.until_rows", rows=101) is not None
    # unlimited by default (times=0 when until_rows present)
    assert reg.check("memory.oom.until_rows", rows=5000) is not None


# ---------------------------------------------------------------------------
# SpillableColumnarBatch pin thread-safety (shared-scan satellite)
# ---------------------------------------------------------------------------

def test_spillable_concurrent_get_unpin_race():
    """Concurrent consumers of one parked spillable (shared scans) must
    never corrupt the pin count or trip the closed assertion."""
    cat = _cat()
    sb = SpillableColumnarBatch(_batch(128), cat, SpillPriority.READ_SHUFFLE)
    errors = []

    def worker():
        try:
            for _ in range(200):
                b = sb.get()
                assert b.host_num_rows() == 128
                sb.unpin()
        except BaseException as e:  # noqa: BLE001
            errors.append(e)

    threads = [threading.Thread(target=worker) for _ in range(8)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert not errors
    assert sb._pins == 0
    sb.close()
    sb.close()  # idempotent
    assert sb._closed
    cat.close()


def test_catalog_tracks_device_bytes_peak():
    cat = _cat()
    sb = SpillableColumnarBatch(_batch(256), cat, SpillPriority.READ_SHUFFLE)
    assert cat.metrics["device_bytes_peak"] > 0
    assert cat.metrics["device_bytes_peak"] >= cat.device_used
    sb.close()
    cat.close()

"""Adaptive query execution: re-plan the running query from materialized
stage statistics (plan/adaptive.py + exec/stage_boundary.py).

Covers the three re-optimizer rewrites — shuffle-join -> broadcast-join
below autoBroadcastJoinThreshold, reader coalescing/skew-splitting for
AQE-inserted join exchanges, and dynamic filter pushdown into probe
scans — plus the contracts around them: rows exactly equal the static
plan, adaptive.enabled=false restores the identical plan shape,
explicit repartition(n) is never coalesced below n, re-planned
fragments reuse the compile cache (warm rerun compiles nothing), and
the whole thing survives the stage-recovery chaos storm.
"""
import os

import numpy as np
import pytest

from spark_rapids_tpu import types as T
from spark_rapids_tpu.exec.core import ExecCtx, collect_host, device_to_host
from spark_rapids_tpu.obs.registry import get_registry
from spark_rapids_tpu.session import TpuSession

AQE_ON = {"spark.sql.adaptive.shuffledHashJoin.enabled": True}

SCHEMA_BIG = T.Schema([T.StructField("k", T.LongType()),
                       T.StructField("v", T.DoubleType())])
SCHEMA_SMALL = T.Schema([T.StructField("k", T.LongType()),
                         T.StructField("w", T.DoubleType())])


def _big(s, n=600, nkeys=10, skew=0.0, parts=3, rpb=100):
    rng = np.random.default_rng(7)
    keys = rng.integers(0, nkeys, n)
    if skew:
        keys = np.where(rng.random(n) < skew, 7, keys)
    return s.from_pydict({"k": [int(x) for x in keys],
                          "v": [float(i) for i in range(n)]},
                         SCHEMA_BIG, partitions=parts, rows_per_batch=rpb)


def _small(s, keys=(1, 2, 3, 4)):
    return s.from_pydict({"k": list(keys),
                          "w": [float(k) * 10 for k in keys]}, SCHEMA_SMALL)


def _aqe_delta(counters):
    return {k: v for k, v in counters.items() if k.startswith("aqe_")}


def _join(s, how="inner", **big_kw):
    return _big(s, **big_kw).join(_small(s), on="k", how=how)


@pytest.mark.parametrize("how", ["inner", "left", "right", "semi", "anti"])
def test_broadcast_switch_rows_exact(how):
    want = sorted(_join(TpuSession({}), how).collect(), key=str)
    s = TpuSession(AQE_ON)
    before = get_registry().snapshot()
    got = sorted(_join(s, how).collect(), key=str)
    delta = get_registry().delta(before)["counters"]
    assert got == want and len(got) > 0
    # forced-small build side: exactly one broadcast switch
    assert delta.get("aqe_broadcast_switches", 0) == 1, _aqe_delta(delta)


def test_broadcast_switch_rendered_in_explain_analyze():
    s = TpuSession(AQE_ON)
    text = _join(s).explain_analyze()
    # the replanned tree is what renders: broadcast strategy, no live
    # probe-side shuffle under the boundary
    assert "BroadcastHashJoinExec" in text
    assert "BroadcastExchangeExec" in text
    assert "StageBoundaryExec" in text
    assert "aqe_broadcast_switches" in text  # counter footer


def test_no_switch_above_threshold():
    conf = dict(AQE_ON)
    conf["spark.sql.adaptive.autoBroadcastJoinThreshold"] = 0
    want = sorted(_join(TpuSession({})).collect(), key=str)
    s = TpuSession(conf)
    before = get_registry().snapshot()
    q = _join(s)
    got = sorted(q.collect(), key=str)
    delta = get_registry().delta(before)["counters"]
    assert got == want
    assert delta.get("aqe_broadcast_switches", 0) == 0, _aqe_delta(delta)


def test_adaptive_off_restores_static_plan_shape():
    """adaptive.enabled=false must disable BOTH the exchange insertion
    and the stage boundary — the plan is byte-identical in shape to the
    plain static plan, even with shuffledHashJoin requested."""
    off = dict(AQE_ON)
    off["spark.sql.adaptive.enabled"] = False
    _, meta_off = _join(TpuSession(off))._overridden(quiet=True)
    _, meta_static = _join(
        TpuSession({"spark.sql.adaptive.enabled": False}))._overridden(
            quiet=True)
    assert meta_off.exec_node.tree_string() == \
        meta_static.exec_node.tree_string()
    tree = meta_off.exec_node.tree_string()
    assert "StageBoundaryExec" not in tree
    assert "ShuffleExchangeExec" not in tree


def test_aqe_join_exchanges_are_conf_gated():
    """Without shuffledHashJoin.enabled the static join plan is
    unchanged — no exchanges, no boundary (AQE keeps its hands off
    plans that never shuffle at the join)."""
    _, meta = _join(TpuSession({}))._overridden(quiet=True)
    tree = meta.exec_node.tree_string()
    assert "StageBoundaryExec" not in tree
    assert "ShuffleExchangeExec" not in tree


def test_repartition_by_num_never_coalesced():
    """Explicit repartition(n) keeps all n partitions with AQE fully
    enabled and coalescing thresholds tuned to tempt it
    (REPARTITION_BY_NUM contract, end to end through the planner)."""
    conf = dict(AQE_ON)
    conf["spark.sql.adaptive.advisoryPartitionSizeInBytes"] = 1 << 30
    s = TpuSession(conf)
    df = _big(s, n=200, parts=2).repartition(4, "k")
    _, meta = df._overridden(quiet=True)
    plan = meta.exec_node
    with ExecCtx(backend="device", conf=s.conf) as ctx:
        nparts = plan.num_partitions(ctx)
        counts = [sum(device_to_host(b).num_rows
                      for b in plan.partition_iter(ctx, p))
                  for p in range(nparts)]
    assert nparts == 4
    assert sum(counts) == 200 and sum(1 for c in counts if c) > 1


def test_coalesce_and_skew_split_on_aqe_exchanges():
    """The split-only restriction is lifted for AQE-inserted join
    exchanges: small reduce partitions coalesce toward the advisory
    size AND a skewed partition splits at map-batch granularity, with
    rows exactly equal to the static plan."""
    conf = dict(AQE_ON)
    conf.update({
        "spark.sql.adaptive.autoBroadcastJoinThreshold": 0,  # keep shuffled
        "spark.sql.adaptive.advisoryPartitionSizeInBytes": 4096,
        "spark.sql.adaptive.skewedPartitionThresholdInBytes": 16384,
    })
    kw = dict(n=4000, nkeys=64, skew=0.9, parts=6, rpb=512)

    def q(s):
        return _big(s, **kw).join(_small(s, keys=range(64)), on="k",
                                  how="inner")

    want = sorted(q(TpuSession({})).collect(), key=str)
    s = TpuSession(conf)
    before = get_registry().snapshot()
    got = sorted(q(s).collect(), key=str)
    delta = get_registry().delta(before)["counters"]
    assert got == want and len(got) == 4000
    assert delta.get("aqe_skew_splits", 0) >= 1, _aqe_delta(delta)
    assert delta.get("aqe_partitions_coalesced", 0) >= 1, _aqe_delta(delta)


@pytest.fixture()
def parquet_probe(tmp_path):
    import pyarrow as pa
    import pyarrow.parquet as pq
    rng = np.random.default_rng(1)
    n = 2000
    path = str(tmp_path / "probe.parquet")
    pq.write_table(pa.table({"k": rng.integers(0, 100, n),
                             "v": rng.random(n)}), path)
    return path


def test_dynamic_filter_pushed_into_probe_scan(parquet_probe):
    def q(s):
        return s.read_parquet(parquet_probe).join(
            _small(s, keys=(3, 5, 9)), on="k", how="inner")

    want = sorted(q(TpuSession({})).collect(), key=str)
    s = TpuSession(AQE_ON)
    before = get_registry().snapshot()
    got = sorted(q(s).collect(), key=str)
    delta = get_registry().delta(before)["counters"]
    assert got == want and len(got) > 0
    assert delta.get("aqe_dynamic_filters", 0) >= 1, _aqe_delta(delta)


def test_dynamic_filter_skips_shared_scans(parquet_probe):
    """A scan consumed by more than one plan branch must NOT receive a
    join-derived filter (it would narrow the other branch); the query
    still returns exact rows."""
    # shared-scan shape: the same parquet read feeds the join AND a
    # second branch of one union
    def q(s):
        probe = s.read_parquet(parquet_probe)
        j = probe.join(_small(s, keys=(3, 5, 9)), on="k", how="inner") \
            .select("k", "v")
        return j.union(probe.select("k", "v"))

    want = sorted(q(TpuSession({})).collect(), key=str)
    s = TpuSession(AQE_ON)
    before = get_registry().snapshot()
    got = sorted(q(s).collect(), key=str)
    delta = get_registry().delta(before)["counters"]
    assert got == want and len(got) > 0
    assert delta.get("aqe_dynamic_filters", 0) == 0, _aqe_delta(delta)


def test_empty_build_side_replans_to_empty():
    want = []
    s = TpuSession(AQE_ON)
    big = _big(s)
    empty = s.from_pydict({"k": [], "w": []}, SCHEMA_SMALL)
    before = get_registry().snapshot()
    got = big.join(empty, on="k", how="inner").collect()
    delta = get_registry().delta(before)["counters"]
    assert got == want
    assert delta.get("aqe_broadcast_switches", 0) == 1, _aqe_delta(delta)


def test_warm_rerun_compiles_nothing():
    """Re-planned fragments hit the same structural compile-cache keys:
    a second run of the adaptive query has compile_count delta 0."""
    s = TpuSession(AQE_ON)
    first = sorted(_join(s).collect(), key=str)
    before = get_registry().snapshot()
    again = sorted(_join(s).collect(), key=str)
    delta = get_registry().delta(before)["counters"]
    assert again == first
    assert delta.get("compile_count", 0) == 0, delta
    assert delta.get("aqe_broadcast_switches", 0) == 1  # re-decided fresh


def test_replan_composes_with_host_oracle():
    """The host (oracle) path of a stage boundary resolves to the
    static child: collect_host over the SAME prepared plan matches the
    device (re-planned) rows — the differential harness stays valid for
    adaptive plans."""
    s = TpuSession(AQE_ON)
    df = _join(s)
    dev = sorted(df.collect(), key=str)
    _, meta = df._overridden(quiet=True)
    host = sorted(collect_host(meta.exec_node, s.conf), key=str)
    assert dev == host and len(dev) > 0


# -- TPC-H: adaptive rows exactly equal static, single-chip + mesh -------

_TPCH_QUERIES = ["q3", "q12", "q18"]


@pytest.fixture(scope="module")
def tpch_dir(tmp_path_factory):
    from spark_rapids_tpu.bench.tpch_gen import generate_tpch
    d = str(tmp_path_factory.mktemp("tpch_adaptive") / "sf001")
    generate_tpch(d, sf=0.01)
    _split_tables(d, ("lineitem", "orders", "customer"), parts=4)
    return d


def _split_tables(data_dir: str, tables, parts: int) -> None:
    """Multi-file tables so scans are multi-partition and the planner
    actually exercises exchanges (same shape as the recovery chaos
    suite)."""
    import pyarrow.parquet as pq
    for table in tables:
        path = os.path.join(data_dir, table, "part-0.parquet")
        t = pq.read_table(path)
        step = -(-t.num_rows // parts)
        for i in range(parts):
            pq.write_table(t.slice(i * step, step),
                           os.path.join(data_dir, table,
                                        f"part-{i}.parquet"))


@pytest.mark.parametrize("query", _TPCH_QUERIES)
def test_tpch_adaptive_matches_oracle(tpch_dir, query):
    from spark_rapids_tpu.bench.runner import run_benchmark
    r = run_benchmark(tpch_dir, 0.01, [query], verify=True,
                      generate=False, suite="tpch",
                      session_conf=dict(AQE_ON))[0]
    assert "error" not in r, r
    assert r["ok"], r


@pytest.mark.parametrize("query", _TPCH_QUERIES)
def test_tpch_adaptive_matches_oracle_mesh(tpch_dir, query):
    from spark_rapids_tpu.bench.runner import run_benchmark
    conf = dict(AQE_ON)
    conf["spark.rapids.tpu.mesh.deviceCount"] = 8
    r = run_benchmark(tpch_dir, 0.01, [query], verify=True,
                      generate=False, suite="tpch", session_conf=conf)[0]
    assert "error" not in r, r
    assert r["ok"], r


def test_tpch_adaptive_exact_under_loss_storm(tpch_dir):
    """Replanning must not break lineage recovery: the broadcast reads
    the build exchange's map output through the recovering fetch, so
    the peer-death + spill-corruption storm still yields exact rows."""
    from spark_rapids_tpu.bench.runner import run_benchmark
    conf = dict(AQE_ON)
    conf.update({
        "spark.rapids.test.faults":
            ("shuffle.peer.dead:dead,times=2;"
             "spill.disk.corrupt:corrupt,priority=0,times=2"),
        "spark.rapids.memory.tpu.spillStoreSize": 1 << 16,
        "spark.rapids.memory.host.spillStorageSize": 4096,
    })
    r = run_benchmark(tpch_dir, 0.01, ["q18"], verify=True,
                      generate=False, suite="tpch", session_conf=conf)[0]
    assert "error" not in r, r
    assert r["ok"], r
    cat = r["metrics"].get("BufferCatalog", {})
    assert cat.get("stage_recomputes", 0) > 0, cat


def test_shuffle_transport_partition_rows():
    """shuffle/local.py row statistics: exact per-partition counts from
    known_rows, maintained across invalidation (the second statistic the
    re-optimizer feeds on)."""
    from spark_rapids_tpu.conf import TpuConf
    from spark_rapids_tpu.exec.core import host_to_device
    from spark_rapids_tpu.host.batch import HostBatch, HostColumn
    from spark_rapids_tpu.shuffle.local import LocalShuffleTransport

    schema = T.Schema([T.StructField("x", T.IntegerType())])
    conf = TpuConf({})
    with ExecCtx(backend="device", conf=conf) as ctx:
        t = LocalShuffleTransport(conf, ctx)
        for m in range(3):
            hb = HostBatch([HostColumn(
                np.arange(4, dtype=np.int32), np.ones(4, bool),
                T.IntegerType())], schema)
            b = host_to_device(hb)
            b.known_rows = 4
            t.write_partition(9, m, m % 2, b)
        assert t.partition_rows(9) == {0: 8, 1: 4}
        t.invalidate_map_outputs(9, [0])  # map 0 wrote only to pid 0
        assert t.partition_rows(9) == {0: 4, 1: 4}
        t.close()

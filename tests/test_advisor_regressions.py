"""Regression tests for the round-1/2 advisor findings (VERDICT Weak #8).

1. Nested WindowExpressions inside projections (fixed by planner
   hoisting; also covered by TPC-DS q12/q20/q98).
2. String lead/lag with a non-null default (was a jitted
   NotImplementedError).
3. _insert_transitions arity mismatch must fail loudly, not skip.
4. with_column must keep a replaced column's position.
5. Right-join non-equi error reports the join type the USER wrote.
"""
import pytest

from spark_rapids_tpu import types as T
from spark_rapids_tpu.exec.core import collect_host
from spark_rapids_tpu.expr.aggregates import Sum
from spark_rapids_tpu.expr.core import col, lit
from spark_rapids_tpu.expr.window import (Lag, Lead, WindowExpression,
                                          WindowSpec)
from spark_rapids_tpu.session import TpuSession


def _both(df):
    dev = sorted(df.collect(), key=str)
    ov, meta = df._overridden(quiet=True)
    host = sorted(collect_host(meta.exec_node, df._s.conf), key=str)
    return dev, host


def test_nested_window_expression_in_projection():
    s = TpuSession({})
    schema = T.Schema([T.StructField("g", T.StringType()),
                       T.StructField("x", T.DoubleType())])
    df = s.from_pydict({"g": ["a", "a", "b", "b"],
                        "x": [1.0, 3.0, 10.0, 30.0]}, schema)
    total = WindowExpression(Sum(col("x")),
                             WindowSpec(partition_by=(col("g"),)))
    out = df.select(col("g"), (col("x") * lit(100.0) / total).alias("pct"))
    dev, host = _both(out)
    assert dev == host
    assert ("a", 25.0) in dev and ("b", 75.0) in dev


def test_string_lead_lag_with_default():
    s = TpuSession({})
    schema = T.Schema([T.StructField("g", T.IntegerType()),
                       T.StructField("s", T.StringType())])
    df = s.from_pydict({"g": [1, 1, 1, 2, 2],
                        "s": ["aa", "bb", None, "long-string-x", "dd"]},
                       schema)
    spec = WindowSpec(partition_by=(col("g"),),
                      order_by=((col("s"), True),))
    out = df.select(
        col("g"), col("s"),
        WindowExpression(Lead(col("s"), 1, lit("END-OF-PARTITION")),
                         spec).alias("nxt"),
        WindowExpression(Lag(col("s"), 1, lit("!")), spec).alias("prv"))
    dev, host = _both(out)
    assert dev == host
    m = {(r[0], r[1]): (r[2], r[3]) for r in dev}
    # last row of each partition gets the default (order: nulls first asc)
    assert m[(1, "bb")][0] == "END-OF-PARTITION"
    assert m[(2, "long-string-x")][0] == "END-OF-PARTITION"
    # first row of each partition gets the lag default
    assert m[(1, None)][1] == "!"
    assert m[(2, "dd")][1] == "!"


def test_with_column_preserves_position():
    s = TpuSession({})
    schema = T.Schema([T.StructField("a", T.IntegerType()),
                       T.StructField("b", T.IntegerType()),
                       T.StructField("c", T.IntegerType())])
    df = s.from_pydict({"a": [1], "b": [2], "c": [3]}, schema)
    out = df.with_column("b", col("b") * lit(10))
    assert out.columns == ["a", "b", "c"]           # position kept
    assert out.collect() == [(1, 20, 3)]
    out2 = df.with_column("d", col("a") + col("c"))
    assert out2.columns == ["a", "b", "c", "d"]     # new col appended


def test_right_join_condition_error_names_right():
    s = TpuSession({})
    schema = T.Schema([T.StructField("k", T.IntegerType())])
    a = s.from_pydict({"k": [1]}, schema)
    b = s.from_pydict({"k": [1]}, schema)
    with pytest.raises(ValueError, match="right"):
        a.join(b, on=[("k", "k")], how="right",
               condition=col("k") > lit(0))._planned()


def test_transition_arity_mismatch_fails_loudly():
    from spark_rapids_tpu.plan.overrides import PlannedNode, TpuOverrides
    from spark_rapids_tpu.conf import TpuConf
    from spark_rapids_tpu.exec.basic import LocalScanExec
    scan = LocalScanExec.from_pydict(
        {"x": [1]}, T.Schema([T.StructField("x", T.IntegerType())]))
    # meta claims two children but the exec has none -> must raise
    bad = PlannedNode(scan, [], [PlannedNode(scan), PlannedNode(scan)])
    ov = TpuOverrides(TpuConf({}))
    with pytest.raises(AssertionError, match="arity"):
        ov._insert_transitions(bad)


def test_to_jax_rejects_duplicate_column_names():
    """Round-3 advisor: to_jax keyed chunks by name, silently merging
    duplicate output columns (legal in Spark, e.g. after a join)."""
    s = TpuSession({})
    schema = T.Schema([T.StructField("k", T.LongType()),
                       T.StructField("v", T.LongType())])
    df = s.from_pydict({"k": [1, 2], "v": [10, 20]}, schema)
    dup = df.select(col("k"), col("v").alias("k"))
    with pytest.raises(ValueError, match="duplicate column name"):
        dup.to_jax()
    # distinct names still export fine
    out = df.to_jax()
    assert set(out) == {"k", "v"}

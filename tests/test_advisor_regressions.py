"""Regression tests for the round-1/2 advisor findings (VERDICT Weak #8).

1. Nested WindowExpressions inside projections (fixed by planner
   hoisting; also covered by TPC-DS q12/q20/q98).
2. String lead/lag with a non-null default (was a jitted
   NotImplementedError).
3. _insert_transitions arity mismatch must fail loudly, not skip.
4. with_column must keep a replaced column's position.
5. Right-join non-equi error reports the join type the USER wrote.
"""
import pytest

from spark_rapids_tpu import types as T
from spark_rapids_tpu.exec.core import collect_host
from spark_rapids_tpu.expr.aggregates import Sum
from spark_rapids_tpu.expr.core import col, lit
from spark_rapids_tpu.expr.window import (Lag, Lead, WindowExpression,
                                          WindowSpec)
from spark_rapids_tpu.session import TpuSession


def _both(df):
    dev = sorted(df.collect(), key=str)
    ov, meta = df._overridden(quiet=True)
    host = sorted(collect_host(meta.exec_node, df._s.conf), key=str)
    return dev, host


def test_nested_window_expression_in_projection():
    s = TpuSession({})
    schema = T.Schema([T.StructField("g", T.StringType()),
                       T.StructField("x", T.DoubleType())])
    df = s.from_pydict({"g": ["a", "a", "b", "b"],
                        "x": [1.0, 3.0, 10.0, 30.0]}, schema)
    total = WindowExpression(Sum(col("x")),
                             WindowSpec(partition_by=(col("g"),)))
    out = df.select(col("g"), (col("x") * lit(100.0) / total).alias("pct"))
    dev, host = _both(out)
    assert dev == host
    assert ("a", 25.0) in dev and ("b", 75.0) in dev


def test_string_lead_lag_with_default():
    s = TpuSession({})
    schema = T.Schema([T.StructField("g", T.IntegerType()),
                       T.StructField("s", T.StringType())])
    df = s.from_pydict({"g": [1, 1, 1, 2, 2],
                        "s": ["aa", "bb", None, "long-string-x", "dd"]},
                       schema)
    spec = WindowSpec(partition_by=(col("g"),),
                      order_by=((col("s"), True),))
    out = df.select(
        col("g"), col("s"),
        WindowExpression(Lead(col("s"), 1, lit("END-OF-PARTITION")),
                         spec).alias("nxt"),
        WindowExpression(Lag(col("s"), 1, lit("!")), spec).alias("prv"))
    dev, host = _both(out)
    assert dev == host
    m = {(r[0], r[1]): (r[2], r[3]) for r in dev}
    # last row of each partition gets the default (order: nulls first asc)
    assert m[(1, "bb")][0] == "END-OF-PARTITION"
    assert m[(2, "long-string-x")][0] == "END-OF-PARTITION"
    # first row of each partition gets the lag default
    assert m[(1, None)][1] == "!"
    assert m[(2, "dd")][1] == "!"


def test_with_column_preserves_position():
    s = TpuSession({})
    schema = T.Schema([T.StructField("a", T.IntegerType()),
                       T.StructField("b", T.IntegerType()),
                       T.StructField("c", T.IntegerType())])
    df = s.from_pydict({"a": [1], "b": [2], "c": [3]}, schema)
    out = df.with_column("b", col("b") * lit(10))
    assert out.columns == ["a", "b", "c"]           # position kept
    assert out.collect() == [(1, 20, 3)]
    out2 = df.with_column("d", col("a") + col("c"))
    assert out2.columns == ["a", "b", "c", "d"]     # new col appended


def test_right_join_condition_error_names_right():
    s = TpuSession({})
    schema = T.Schema([T.StructField("k", T.IntegerType())])
    a = s.from_pydict({"k": [1]}, schema)
    b = s.from_pydict({"k": [1]}, schema)
    with pytest.raises(ValueError, match="right"):
        a.join(b, on=[("k", "k")], how="right",
               condition=col("k") > lit(0))._planned()


def test_transition_arity_mismatch_fails_loudly():
    from spark_rapids_tpu.plan.overrides import PlannedNode, TpuOverrides
    from spark_rapids_tpu.conf import TpuConf
    from spark_rapids_tpu.exec.basic import LocalScanExec
    scan = LocalScanExec.from_pydict(
        {"x": [1]}, T.Schema([T.StructField("x", T.IntegerType())]))
    # meta claims two children but the exec has none -> must raise
    bad = PlannedNode(scan, [], [PlannedNode(scan), PlannedNode(scan)])
    ov = TpuOverrides(TpuConf({}))
    with pytest.raises(AssertionError, match="arity"):
        ov._insert_transitions(bad)


def test_to_jax_rejects_duplicate_column_names():
    """Round-3 advisor: to_jax keyed chunks by name, silently merging
    duplicate output columns (legal in Spark, e.g. after a join)."""
    s = TpuSession({})
    schema = T.Schema([T.StructField("k", T.LongType()),
                       T.StructField("v", T.LongType())])
    df = s.from_pydict({"k": [1, 2], "v": [10, 20]}, schema)
    dup = df.select(col("k"), col("v").alias("k"))
    with pytest.raises(ValueError, match="duplicate column name"):
        dup.to_jax()
    # distinct names still export fine
    out = df.to_jax()
    assert set(out) == {"k", "v"}


def test_agg_fingerprint_distinguishes_agg_functions():
    """Round-4 advisor (high): plan_fingerprint must include the
    aggregate specs — min(v).alias('m') and max(v).alias('m') over the
    same scan share node_desc/bound-final-exprs/output schema, so
    without an explicit payload ReuseExchange would dedup their
    shuffles and serve one consumer the other's map output."""
    import numpy as np
    from spark_rapids_tpu.exec.aggregate import HashAggregateExec
    from spark_rapids_tpu.exec.basic import LocalScanExec
    from spark_rapids_tpu.exec.exchange import plan_fingerprint
    from spark_rapids_tpu.expr.aggregates import Max, Min

    schema = T.Schema([T.StructField("k", T.IntegerType(), True),
                       T.StructField("v", T.DoubleType(), True)])
    scan = LocalScanExec.from_pydict(
        {"k": np.array([1, 1, 2], np.int32),
         "v": np.array([1.0, 5.0, 2.0])}, schema)
    lo = HashAggregateExec([col("k")], [col("k"),
                                        Min(col("v")).alias("m")],
                           scan, mode="partial")
    hi = HashAggregateExec([col("k")], [col("k"),
                                        Max(col("v")).alias("m")],
                           scan, mode="partial")
    assert plan_fingerprint(lo) != plan_fingerprint(hi)
    # identical aggregations over the SAME scan still dedup
    lo2 = HashAggregateExec([col("k")], [col("k"),
                                         Min(col("v")).alias("m")],
                            scan, mode="partial")
    assert plan_fingerprint(lo) == plan_fingerprint(lo2)


def test_agg_reuse_distinct_functions_end_to_end():
    """End-to-end shape of the same finding: one source aggregated two
    ways (min and max under the SAME output alias) then joined — under
    the fingerprint collision both sides would read one shuffle and
    min == max everywhere."""
    import numpy as np
    from spark_rapids_tpu.expr.aggregates import Max, Min

    s = TpuSession({})
    schema = T.Schema([T.StructField("k", T.IntegerType(), True),
                       T.StructField("v", T.DoubleType(), True)])
    rng = np.random.default_rng(7)
    df = s.from_pydict({"k": rng.integers(0, 8, 200).astype(np.int32),
                        "v": rng.random(200)}, schema, partitions=3)
    lo = df.group_by("k").agg(Min(col("v")).alias("m")) \
        .select(col("k"), col("m"))
    hi = df.group_by("k").agg(Max(col("v")).alias("m")) \
        .select(col("k").alias("k2"), col("m").alias("m2"))
    out = lo.join(hi, on=[("k", "k2")])
    rows = out.collect()
    assert rows and all(r[1] < r[3] for r in rows)  # every min < max
    dev, host = _both(out)
    assert dev == host


def test_udf_compiler_refuses_division_in_branch_condition():
    """Round-4 advisor (low): a branch condition containing a
    null-producing op (division) must refuse compilation — the
    compiled If-tree would silently take the default branch where
    uncompiled Python raises ZeroDivisionError."""
    from spark_rapids_tpu.expr.core import BoundReference
    from spark_rapids_tpu.udf.compiler import compile_udf

    a = BoundReference(0, T.DoubleType(), True)
    b = BoundReference(1, T.DoubleType(), True)

    def risky(x, y):
        if x / y > 1.0:
            return 1.0
        return 0.0

    assert compile_udf(risky, [a, b]) is None  # falls back

    # division in a RESULT (not a condition) still compiles
    def fine(x, y):
        if x > 1.0:
            return x / y
        return 0.0

    assert compile_udf(fine, [a, b]) is not None


def test_pandas_agg_exact_int64_group_keys():
    """Round-4 advisor (low): nullable int64 group keys >= 2**53 must
    not round-trip through float64 (distinct keys would merge)."""
    from spark_rapids_tpu.exec.python_exec import pandas_agg_udf

    s = TpuSession({})
    big = 2**53
    schema = T.Schema([T.StructField("k", T.LongType(), True),
                       T.StructField("v", T.DoubleType(), True)])
    df = s.from_pydict({"k": [big, big + 1, big, None],
                        "v": [1.0, 2.0, 3.0, 4.0]}, schema)
    total = pandas_agg_udf(lambda v: float(v.sum()), T.DoubleType())
    out = df.group_by("k").agg(total(col("v")).alias("s"))
    rows = sorted(out.collect(), key=lambda r: (r[0] is None, r[0] or 0))
    ks = [r[0] for r in rows if r[0] is not None]
    assert ks == [big, big + 1]  # distinct keys preserved exactly
    got = {r[0]: r[1] for r in rows}
    assert got[big] == 4.0 and got[big + 1] == 2.0 and got[None] == 4.0


def test_apply_in_pandas_exact_int64_group_keys():
    """Review r5: the 2**53 key-collapse fix must also cover
    FlatMapGroupsInPandas and the cogroup pairing (groups are formed
    from the converted frame here; Spark forms them JVM-side)."""
    import pandas as pd

    s = TpuSession({})
    big = 2**53
    schema = T.Schema([T.StructField("k", T.LongType(), True),
                       T.StructField("v", T.DoubleType(), True)])
    df = s.from_pydict({"k": [big, big + 1, big, None],
                        "v": [1.0, 2.0, 3.0, 4.0]}, schema)
    out_schema = T.Schema([T.StructField("k", T.LongType(), True),
                           T.StructField("n", T.LongType(), True)])
    out = df.group_by("k").apply_in_pandas(
        lambda g: pd.DataFrame({"k": [g["k"].iloc[0]],
                                "n": [len(g)]}), out_schema)
    rows = sorted(out.collect(), key=lambda r: (r[0] is None, r[0] or 0))
    assert (big, 2) in rows and (big + 1, 1) in rows

    # cogroup: each side groups exactly and keys pair across sides
    df2 = s.from_pydict({"k": [big + 1, None], "v": [9.0, 8.0]}, schema)
    co_schema = T.Schema([T.StructField("k", T.LongType(), True),
                          T.StructField("ln", T.LongType(), True),
                          T.StructField("rn", T.LongType(), True)])

    def co(l, r):
        src = l if len(l) else r
        return pd.DataFrame({"k": [src["k"].iloc[0]],
                             "ln": [len(l)], "rn": [len(r)]})

    out = df.group_by("k").cogroup(df2.group_by("k")) \
        .apply_in_pandas(co, co_schema)
    rows = sorted(out.collect(), key=lambda r: (r[0] is None, r[0] or 0))
    assert (big, 2, 0) in rows and (big + 1, 1, 1) in rows

"""Fuzzed differential tests over operator families.

Reference §4 pattern: typed random data with special-value injection
(data_gen.py) + CPU-vs-accelerator comparison per op family
(integration_tests per-op files) + fallback assertions (asserts.py:241).
"""
import pytest

from spark_rapids_tpu import types as T
from spark_rapids_tpu.expr.aggregates import (Average, Count, CountStar, Max,
                                              Min, Sum)
from spark_rapids_tpu.expr.core import col, lit
from spark_rapids_tpu.session import TpuSession
from spark_rapids_tpu.testing import (BooleanGen, DateGen, DoubleGen,
                                      IntegerGen, LongGen, StringGen,
                                      TimestampGen, assert_fallback, gen_df)

COLS = [("i", IntegerGen()), ("l", LongGen()), ("d", DoubleGen()),
        ("b", BooleanGen()), ("s", StringGen()), ("dt", DateGen()),
        ("ts", TimestampGen())]


def _both(df, approx=True):
    import math
    dev = df.collect()
    from spark_rapids_tpu.exec.core import collect_host
    ov, meta = df._overridden(quiet=True)
    host = collect_host(meta.exec_node, df._s.conf)
    assert len(dev) == len(host), (len(dev), len(host))
    key = lambda r: tuple((x is None, str(x)) for x in r)  # noqa: E731
    for rd, rh in zip(sorted(dev, key=key), sorted(host, key=key)):
        for a, b in zip(rd, rh):
            if isinstance(a, float) and isinstance(b, float):
                ok = (math.isnan(a) and math.isnan(b)) or a == b or \
                    math.isclose(a, b, rel_tol=1e-9, abs_tol=1e-300)
                assert ok, (rd, rh)
            else:
                assert a == b, (rd, rh)
    return dev


@pytest.mark.parametrize("seed", [0, 1, 2])
def test_fuzz_project_filter(seed):
    s = TpuSession({})
    df = gen_df(s, COLS, n=300, seed=seed, partitions=2, rows_per_batch=64)
    out = df.where(col("i") > lit(0)) \
        .select(col("i") + col("i"), col("d") * lit(2.0),
                (col("l") % lit(7)).alias("m"), col("s"),
                col("b") & (col("i") > lit(100)))
    _both(out)


@pytest.mark.parametrize("seed", [0, 1])
def test_fuzz_aggregate(seed):
    s = TpuSession({})
    df = gen_df(s, COLS, n=400, seed=seed, partitions=2, rows_per_batch=128)
    out = df.group_by("b").agg(
        Sum(col("l")).alias("sl"), Min(col("d")).alias("mn"),
        Max(col("d")).alias("mx"), Average(col("i")).alias("av"),
        Count(col("s")).alias("cs"), CountStar().alias("c"))
    _both(out)


@pytest.mark.parametrize("seed", [0, 1])
def test_fuzz_join(seed):
    s = TpuSession({})
    left = gen_df(s, [("k", IntegerGen(lo=0, hi=40)), ("x", DoubleGen())],
                  n=250, seed=seed)
    right = gen_df(s, [("k2", IntegerGen(lo=0, hi=40)),
                       ("y", StringGen())], n=120, seed=seed + 100)
    for how in ("inner", "left", "semi", "anti", "full"):
        out = left.join(right, on=[("k", "k2")], how=how)
        _both(out)


def test_fuzz_sort_strings_and_dates(seed=3):
    s = TpuSession({})
    df = gen_df(s, COLS, n=200, seed=seed)
    out = df.order_by(("s", True), ("dt", False), ("d", True))
    # total order: compare WITHOUT sorting the outputs
    import math
    dev = out.collect()
    from spark_rapids_tpu.exec.core import collect_host
    ov, meta = out._overridden(quiet=True)
    host = collect_host(meta.exec_node, s.conf)
    for rd, rh in zip(dev, host):
        for a, b in zip(rd, rh):
            if isinstance(a, float) and isinstance(b, float):
                assert (math.isnan(a) and math.isnan(b)) or a == b
            else:
                assert a == b


def test_fallback_assert_harness():
    from spark_rapids_tpu.expr.regexp import RLike
    s = TpuSession({})
    df = gen_df(s, [("s", StringGen())], n=50)
    out = df.select(RLike(col("s"), "[0-9]+").alias("r"))
    text = assert_fallback(out, "ProjectExec")
    assert "!" in text
    # disabling an expression by conf also forces the fallback
    s2 = TpuSession({"spark.rapids.sql.expression.Upper": False})
    from spark_rapids_tpu.expr.strings import Upper
    df2 = gen_df(s2, [("s", StringGen())], n=50)
    assert_fallback(df2.select(Upper(col("s")).alias("u")), "ProjectExec")


@pytest.mark.parametrize("seed", [0, 1, 2])
def test_fuzz_arrays(seed):
    """Fuzzed array columns through extract/size/explode (reference
    data_gen.py ArrayGen + per-family differential files)."""
    from spark_rapids_tpu.expr.collections import GetArrayItem, Size
    from spark_rapids_tpu.testing import ArrayGen

    s = TpuSession({})
    df = gen_df(s, [("i", IntegerGen()),
                    ("a", ArrayGen()),
                    ("ad", ArrayGen(DoubleGen(nullable=0.0)))],
                 n=200, seed=seed, partitions=2, rows_per_batch=32)
    out = df.select(col("i"), Size(col("a")).alias("sz"),
                    GetArrayItem(col("a"), lit(1)).alias("a1"),
                    GetArrayItem(col("ad"), col("i") % lit(4)).alias("dd"))
    _both(out)
    exploded = df.explode(col("a"), output_name="e", outer=(seed % 2 == 0))
    _both(exploded)

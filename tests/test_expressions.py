"""Differential tests: every expression evaluated on the CPU oracle (numpy)
and on the device path (jax, jitted) must agree exactly.

Mirrors the reference's CPU-vs-GPU golden comparison strategy
(tests/SparkQueryCompareTestSuite.scala:153-167).
"""
import datetime as dt
import math

import numpy as np
import pytest

from spark_rapids_tpu import types as T
from spark_rapids_tpu.expr import (col, lit, bind, eval_host, eval_device)
from spark_rapids_tpu.expr import arithmetic as A
from spark_rapids_tpu.expr import predicates as P
from spark_rapids_tpu.expr import conditional as C
from spark_rapids_tpu.expr import strings as S
from spark_rapids_tpu.expr import datetime_ops as D
from spark_rapids_tpu.expr import math_ops as M
from spark_rapids_tpu.expr.cast import Cast
from spark_rapids_tpu.expr.hashing import Murmur3Hash
from spark_rapids_tpu.host.batch import HostBatch


def schema(**kw):
    return T.Schema([T.StructField(k, v) for k, v in kw.items()])


INT_SCHEMA = schema(a=T.IntegerType(), b=T.IntegerType())
INT_DATA = {"a": [1, None, 3, -7, 2147483647, 0, -2147483648],
            "b": [2, 5, None, 3, 1, 0, -1]}

DBL_SCHEMA = schema(x=T.DoubleType(), y=T.DoubleType())
DBL_DATA = {"x": [1.5, None, float("nan"), -0.0, float("inf"), 2.0, -3.5],
            "y": [0.5, 2.0, 1.0, 0.0, float("nan"), None, 2.0]}

STR_SCHEMA = schema(s=T.StringType(), t=T.StringType())
STR_DATA = {"s": ["hello", "", None, "Hello World", "abc", "  pad  ", "héllo"],
            "t": ["he", "x", "y", "World", None, "pad", "llo"]}


def run_both(expr, data, sch, approx=False):
    """Bind, eval on host and device, compare, return host list."""
    hb = HostBatch.from_pydict(data, sch)
    bound = bind(expr, sch)
    hres = eval_host(bound, hb).to_list()

    import jax
    from spark_rapids_tpu.expr.core import eval_device as _ed
    db = hb.to_device()

    @jax.jit
    def f(b):
        return _ed(bound, b)

    dcol = f(db)
    from spark_rapids_tpu.columnar.batch import ColumnBatch
    out = ColumnBatch([dcol], db.num_rows, schema(r=bound.dtype))
    dres = HostBatch.from_device(out).columns[0].to_list()
    assert len(hres) == len(dres)
    for i, (h, d) in enumerate(zip(hres, dres)):
        if h is None or d is None:
            assert h is None and d is None, (i, h, d)
        elif isinstance(h, float):
            if math.isnan(h):
                assert math.isnan(d), (i, h, d)
            elif approx:
                assert d == pytest.approx(h, rel=1e-12), (i, h, d)
            else:
                assert h == d or (h == 0 and d == 0), (i, h, d)
        else:
            assert h == d, (i, h, d)
    return hres


class TestArithmetic:
    def test_add_nulls_and_wrap(self):
        r = run_both(col("a") + col("b"), INT_DATA, INT_SCHEMA)
        assert r[0] == 3 and r[1] is None and r[2] is None
        assert r[4] == -2147483648  # int32 wraparound like Java

    def test_subtract_multiply(self):
        run_both(col("a") - col("b") * lit(2).cast(T.IntegerType()),
                 INT_DATA, INT_SCHEMA)

    def test_divide_null_on_zero(self):
        r = run_both(col("a") / col("b"), INT_DATA, INT_SCHEMA)
        assert r[0] == 0.5
        assert r[5] is None  # 0 / 0 -> NULL (Spark DivModLike)
        r2 = run_both(col("x") / col("y"), DBL_DATA, DBL_SCHEMA)
        assert r2[3] is None  # -0.0 / 0.0 -> NULL even for doubles

    def test_remainder_sign_of_dividend(self):
        r = run_both(col("a") % col("b"), INT_DATA, INT_SCHEMA)
        assert r[3] == -1  # -7 % 3 == -1 (Java), not 2 (python)
        assert r[5] is None

    def test_integral_divide_truncates(self):
        r = run_both(A.IntegralDivide(col("a"), col("b")), INT_DATA, INT_SCHEMA)
        assert r[3] == -2  # -7 div 3 == -2 (trunc), not -3 (floor)

    def test_unary(self):
        r = run_both(-col("a"), INT_DATA, INT_SCHEMA)
        assert r[3] == 7
        r = run_both(A.Abs(col("a")), INT_DATA, INT_SCHEMA)
        assert r[6] == -2147483648  # Java Math.abs(MIN_VALUE) wraps

    def test_least_greatest(self):
        r = run_both(A.Least(col("a"), col("b")), INT_DATA, INT_SCHEMA)
        assert r[0] == 1 and r[1] == 5 and r[2] == 3
        r = run_both(A.Greatest(col("x"), col("y")), DBL_DATA, DBL_SCHEMA)
        assert math.isnan(r[2])  # NaN is greatest


class TestPredicates:
    def test_comparisons_int(self):
        r = run_both(col("a") < col("b"), INT_DATA, INT_SCHEMA)
        assert r[0] is True and r[1] is None and r[2] is None

    def test_nan_semantics(self):
        # Spark: NaN == NaN is true; NaN greater than everything
        r = run_both(col("x") == col("x"), DBL_DATA, DBL_SCHEMA)
        assert r[2] is True
        r = run_both(col("x") > col("y"), DBL_DATA, DBL_SCHEMA)
        assert r[2] is True     # NaN > 1.0
        assert r[4] is False    # inf > NaN is false
        r = run_both(col("x") <= col("y"), DBL_DATA, DBL_SCHEMA)
        assert r[4] is True     # inf <= NaN

    def test_negative_zero(self):
        r = run_both(col("x") == col("y"), DBL_DATA, DBL_SCHEMA)
        assert r[3] is True  # -0.0 == 0.0

    def test_three_valued_logic(self):
        e = (col("a") > lit(0).cast(T.IntegerType())) & (col("b") > lit(0).cast(T.IntegerType()))
        r = run_both(e, INT_DATA, INT_SCHEMA)
        assert r[1] is None   # null & true -> null
        e = (col("a") < lit(0)) & (col("b") > lit(0))
        r = run_both(e, INT_DATA, INT_SCHEMA)
        assert r[1] is None   # null & true -> null
        assert r[2] is False  # false & null -> false (a=3 not < 0)
        e = P.Or(col("a").is_null(), col("b").is_null())
        r = run_both(e, INT_DATA, INT_SCHEMA)
        assert r[1] is True and r[0] is False

    def test_null_safe_eq(self):
        r = run_both(P.EqualNullSafe(col("a"), col("b")), INT_DATA, INT_SCHEMA)
        assert r[1] is False and r[0] is False
        d = {"a": [None, 1], "b": [None, 1]}
        r = run_both(P.EqualNullSafe(col("a"), col("b")), d, INT_SCHEMA)
        assert r == [True, True]

    def test_in(self):
        r = run_both(col("a").isin(1, 3, 99), INT_DATA, INT_SCHEMA)
        assert r[0] is True and r[2] is True and r[3] is False and r[1] is None
        r = run_both(col("a").isin(1, None), INT_DATA, INT_SCHEMA)
        assert r[0] is True and r[3] is None  # no match + null item -> NULL

    def test_null_tests(self):
        r = run_both(col("a").is_null(), INT_DATA, INT_SCHEMA)
        assert r == [False, True, False, False, False, False, False]
        r = run_both(P.IsNan(col("x")), DBL_DATA, DBL_SCHEMA)
        assert r[2] is True and r[1] is False  # IsNaN(null) -> false

    def test_in_promotes_not_narrows(self):
        # items wider than the value type must promote both sides, not wrap
        sch = schema(a=T.ByteType(), b=T.ByteType())
        d = {"a": [0, 1, None], "b": [0, 0, 0]}
        r = run_both(col("a").isin(256), d, sch)
        assert r == [False, False, None]

    def test_string_trailing_nul_orders_as_prefix(self):
        d = {"s": ["a", "a\x00b", "a"], "t": ["a\x00", "a\x00", "a"]}
        r = run_both(col("s") < col("t"), d, STR_SCHEMA)
        assert r == [True, False, False]

    def test_string_compare(self):
        r = run_both(col("s") < col("t"), STR_DATA, STR_SCHEMA)
        assert r[0] is False  # "hello" < "he" false
        assert r[1] is True   # "" < "x"
        r = run_both(col("s") == col("s"), STR_DATA, STR_SCHEMA)
        assert r[0] is True and r[2] is None


class TestConditional:
    def test_if(self):
        e = C.If(col("a") > col("b"), col("a"), col("b"))
        r = run_both(e, INT_DATA, INT_SCHEMA)
        assert r[0] == 2 and r[1] == 5  # null pred -> else branch

    def test_case_when(self):
        e = C.CaseWhen([(col("a") > lit(0), lit("pos")),
                        (col("a") < lit(0), lit("neg"))], lit("zero"))
        r = run_both(e, INT_DATA, INT_SCHEMA)
        assert r[0] == "pos" and r[3] == "neg" and r[5] == "zero"
        assert r[1] == "zero"  # null falls to else

    def test_coalesce(self):
        e = C.Coalesce(col("a"), col("b"), lit(-1))
        r = run_both(e, INT_DATA, INT_SCHEMA)
        assert r[1] == 5 and r[2] == 3 and r[0] == 1


class TestCast:
    def test_long_to_int_wraps(self):
        sch = schema(v=T.LongType())
        d = {"v": [2**31, -2**31 - 1, 5, None]}
        r = run_both(Cast(col("v"), T.IntegerType()), d, sch)
        assert r == [-2147483648, 2147483647, 5, None]

    def test_double_to_int_saturates(self):
        sch = schema(v=T.DoubleType())
        d = {"v": [1e20, -1e20, 2.9, -2.9, float("nan"), None]}
        r = run_both(Cast(col("v"), T.IntegerType()), d, sch)
        assert r == [2147483647, -2147483648, 2, -2, 0, None]
        r = run_both(Cast(col("v"), T.LongType()), d, sch)
        assert r[0] == 9223372036854775807 and r[4] == 0

    def test_numeric_bool(self):
        sch = schema(v=T.IntegerType())
        d = {"v": [0, 1, -5, None]}
        r = run_both(Cast(col("v"), T.BooleanType()), d, sch)
        assert r == [False, True, True, None]

    def test_date_timestamp(self):
        sch = schema(v=T.DateType())
        d = {"v": [dt.date(2020, 3, 1), dt.date(1969, 12, 31), None]}
        r = run_both(Cast(col("v"), T.TimestampType()), d, sch)
        assert r[1] == dt.datetime(1969, 12, 31, 0, 0)

    def test_string_casts_host_only(self):
        sch = schema(v=T.StringType())
        hb = HostBatch.from_pydict({"v": [" 42 ", "abc", "1.5", None]}, sch)
        bound = bind(Cast(col("v"), T.IntegerType()), sch)
        assert not bound.device_supported
        r = eval_host(bound, hb).to_list()
        assert r == [42, None, None, None]
        bound = bind(Cast(col("v"), T.DoubleType()), sch)
        assert eval_host(bound, hb).to_list() == [42.0, None, 1.5, None]

    def test_double_to_string_java_format(self):
        from spark_rapids_tpu.expr.cast import java_double_str
        assert java_double_str(1.0) == "1.0"
        assert java_double_str(1e7) == "1.0E7"
        assert java_double_str(0.001) == "0.001"
        assert java_double_str(1e-4) == "1.0E-4"
        assert java_double_str(float("nan")) == "NaN"
        assert java_double_str(float("-inf")) == "-Infinity"


class TestStrings:
    def test_upper_lower(self):
        r = run_both(S.Upper(col("s")),
                     {"s": ["abc", "aBc", None], "t": ["", "", ""]}, STR_SCHEMA)
        assert r == ["ABC", "ABC", None]
        run_both(S.Lower(col("s")),
                 {"s": ["ABC", "aBc", None], "t": ["", "", ""]}, STR_SCHEMA)

    def test_length_chars_not_bytes(self):
        r = run_both(S.Length(col("s")), STR_DATA, STR_SCHEMA)
        assert r[0] == 5 and r[1] == 0 and r[2] is None
        assert r[6] == 5  # "héllo" is 5 chars (6 utf-8 bytes)

    def test_substring(self):
        e = col("s").substr(2, 3)
        r = run_both(e, STR_DATA, STR_SCHEMA)
        assert r[0] == "ell" and r[1] == "" and r[2] is None
        assert r[6] == "éll"  # char-indexed through multibyte
        r = run_both(col("s").substr(-3, 2), STR_DATA, STR_SCHEMA)
        assert r[0] == "ll"
        r = run_both(col("s").substr(0, 2), STR_DATA, STR_SCHEMA)
        assert r[0] == "he"

    def test_concat(self):
        r = run_both(S.Concat(col("s"), lit("_"), col("t")), STR_DATA, STR_SCHEMA)
        assert r[0] == "hello_he" and r[2] is None and r[4] is None

    def test_predicates(self):
        r = run_both(col("s").startswith(col("t")), STR_DATA, STR_SCHEMA)
        assert r[0] is True and r[3] is False
        r = run_both(col("s").endswith(col("t")), STR_DATA, STR_SCHEMA)
        assert r[3] is True and r[6] is True
        r = run_both(col("s").contains(col("t")), STR_DATA, STR_SCHEMA)
        assert r[0] is True and r[3] is True and r[1] is False

    def test_like(self):
        r = run_both(col("s").like("he%"), STR_DATA, STR_SCHEMA)
        assert r[0] is True and r[3] is False
        r = run_both(col("s").like("%World"), STR_DATA, STR_SCHEMA)
        assert r[3] is True
        r = run_both(col("s").like("%llo%"), STR_DATA, STR_SCHEMA)
        assert r[0] is True
        # general pattern: host-only
        e = bind(col("s").like("h_llo"), STR_SCHEMA)
        assert not e.device_supported
        hb = HostBatch.from_pydict(STR_DATA, STR_SCHEMA)
        assert eval_host(e, hb).to_list()[0] is True

    def test_trim(self):
        r = run_both(S.StringTrim(col("s")), STR_DATA, STR_SCHEMA)
        assert r[5] == "pad"
        r = run_both(S.StringTrimLeft(col("s")), STR_DATA, STR_SCHEMA)
        assert r[5] == "pad  "
        r = run_both(S.StringTrimRight(col("s")), STR_DATA, STR_SCHEMA)
        assert r[5] == "  pad"


class TestDatetime:
    SCH = schema(d=T.DateType(), n=T.IntegerType())
    DATES = [dt.date(2020, 2, 29), dt.date(1969, 7, 20), dt.date(2000, 1, 1),
             dt.date(1582, 10, 15), dt.date(2038, 1, 19), None]
    DATA = {"d": DATES, "n": [1, 2, 3, 4, 5, 6]}

    def test_extract_fields(self):
        r = run_both(D.Year(col("d")), self.DATA, self.SCH)
        assert r == [2020, 1969, 2000, 1582, 2038, None]
        r = run_both(D.Month(col("d")), self.DATA, self.SCH)
        assert r == [2, 7, 1, 10, 1, None]
        r = run_both(D.DayOfMonth(col("d")), self.DATA, self.SCH)
        assert r == [29, 20, 1, 15, 19, None]

    def test_dow_doy_quarter(self):
        r = run_both(D.DayOfWeek(col("d")), self.DATA, self.SCH)
        # 2020-02-29 was a Saturday -> 7 in Spark's 1=Sunday scheme
        assert r[0] == 7
        r = run_both(D.DayOfYear(col("d")), self.DATA, self.SCH)
        assert r[0] == 60 and r[2] == 1
        r = run_both(D.Quarter(col("d")), self.DATA, self.SCH)
        assert r == [1, 3, 1, 4, 1, None]

    def test_date_arith(self):
        r = run_both(D.DateAdd(col("d"), col("n")), self.DATA, self.SCH)
        assert r[0] == dt.date(2020, 3, 1)
        r = run_both(D.DateSub(col("d"), col("n")), self.DATA, self.SCH)
        assert r[2] == dt.date(1999, 12, 29)
        r = run_both(D.DateDiff(col("d"), col("d")), self.DATA, self.SCH)
        assert r[0] == 0

    def test_time_extract(self):
        sch = schema(ts=T.TimestampType())
        d = {"ts": [dt.datetime(2020, 5, 4, 13, 45, 59),
                    dt.datetime(1969, 12, 31, 23, 0, 1), None]}
        assert run_both(D.Hour(col("ts")), d, sch) == [13, 23, None]
        assert run_both(D.Minute(col("ts")), d, sch) == [45, 0, None]
        assert run_both(D.Second(col("ts")), d, sch) == [59, 1, None]


class TestMath:
    def test_floor_ceil_long(self):
        e = M.Floor(col("x"))
        sch = DBL_SCHEMA
        d = {"x": [1.7, -1.2, None, 0.0, 1e18, -2.5, 3.0],
             "y": [0.0] * 7}
        r = run_both(e, d, sch)
        assert r[0] == 1 and r[1] == -2 and r[2] is None
        assert isinstance(r[0], int)  # LongType result
        r = run_both(M.Ceil(col("x")), d, sch)
        assert r[0] == 2 and r[1] == -1

    def test_round_half_up(self):
        sch = schema(x=T.DoubleType())
        d = {"x": [2.5, 3.5, -2.5, 1.25, None]}
        r = run_both(M.Round(col("x"), 0), d, sch)
        assert r[0] == 3.0 and r[1] == 4.0 and r[2] == -3.0  # HALF_UP
        r = run_both(M.Round(col("x"), 1), d, sch)
        assert r[3] == 1.3

    def test_log_null_nonpositive(self):
        sch = schema(x=T.DoubleType())
        d = {"x": [math.e, 0.0, -1.0, None]}
        r = run_both(M.Log(col("x")), d, sch, approx=True)
        assert r[0] == pytest.approx(1.0) and r[1] is None and r[2] is None

    def test_misc(self):
        sch = schema(x=T.DoubleType())
        d = {"x": [4.0, -4.0, 0.25, None]}
        r = run_both(M.Sqrt(col("x")), d, sch)
        assert r[0] == 2.0 and math.isnan(r[1])
        run_both(M.Exp(col("x")), d, sch, approx=True)
        run_both(M.Pow(col("x"), lit(2.0)), d, sch, approx=True)
        run_both(M.Signum(col("x")), d, sch)
        run_both(M.Sin(col("x")), d, sch, approx=True)
        run_both(M.Tanh(col("x")), d, sch, approx=True)


def _ref_murmur3_bytes(data: bytes, seed: int) -> int:
    """Independent reference: murmur3 x86_32 with Spark's per-byte tail."""
    def rotl(x, r):
        return ((x << r) | (x >> (32 - r))) & 0xFFFFFFFF

    def mixk1(k1):
        k1 = (k1 * 0xCC9E2D51) & 0xFFFFFFFF
        k1 = rotl(k1, 15)
        return (k1 * 0x1B873593) & 0xFFFFFFFF

    def mixh1(h1, k1):
        h1 ^= k1
        h1 = rotl(h1, 13)
        return (h1 * 5 + 0xE6546B64) & 0xFFFFFFFF

    h1 = seed & 0xFFFFFFFF
    n = len(data)
    for i in range(0, n - n % 4, 4):
        h1 = mixh1(h1, mixk1(int.from_bytes(data[i:i + 4], "little")))
    for i in range(n - n % 4, n):
        b = data[i]
        if b >= 128:
            b -= 256
        h1 = mixh1(h1, mixk1(b & 0xFFFFFFFF))
    h1 ^= n
    h1 ^= h1 >> 16
    h1 = (h1 * 0x85EBCA6B) & 0xFFFFFFFF
    h1 ^= h1 >> 13
    h1 = (h1 * 0xC2B2AE35) & 0xFFFFFFFF
    h1 ^= h1 >> 16
    return h1 - 2**32 if h1 >= 2**31 else h1


class TestMurmur3:
    def test_int_matches_reference(self):
        sch = schema(v=T.IntegerType())
        vals = [0, 1, -1, 42, 2147483647, None]
        r = run_both(Murmur3Hash(col("v")), {"v": vals}, sch)
        for v, h in zip(vals, r):
            if v is None:
                assert h == 42  # null passes seed through
            else:
                exp = _ref_murmur3_bytes(
                    int(np.int32(v)).to_bytes(4, "little", signed=True), 42)
                assert h == exp, v

    def test_long_double(self):
        sch = schema(v=T.LongType())
        vals = [0, 1, -1, 2**40, None]
        r = run_both(Murmur3Hash(col("v")), {"v": vals}, sch)
        for v, h in zip(vals, r):
            if v is not None:
                exp = _ref_murmur3_bytes(
                    int(np.int64(v)).to_bytes(8, "little", signed=True), 42)
                assert h == exp, v
        sch = schema(v=T.DoubleType())
        vals = [1.5, -0.0, 3.14159, float("nan"), None]
        r = run_both(Murmur3Hash(col("v")), {"v": vals}, sch)
        import struct
        for v, h in zip(vals, r):
            if v is not None:
                norm = 0.0 if v == 0 else v
                bits = struct.pack("<d", norm) if not math.isnan(norm) \
                    else (0x7FF8000000000000).to_bytes(8, "little")
                assert h == _ref_murmur3_bytes(bits, 42), v

    def test_string(self):
        sch = schema(v=T.StringType())
        vals = ["", "a", "abcd", "abcde", "hello world", "héllo", None]
        r = run_both(Murmur3Hash(col("v")), {"v": vals}, sch)
        for v, h in zip(vals, r):
            if v is not None:
                assert h == _ref_murmur3_bytes(v.encode("utf-8"), 42), v

    def test_multi_column_chaining(self):
        sch = schema(a=T.IntegerType(), b=T.StringType())
        d = {"a": [1, 2, None], "b": ["x", None, "y"]}
        r = run_both(Murmur3Hash(col("a"), col("b")), d, sch)
        seed0 = _ref_murmur3_bytes((1).to_bytes(4, "little"), 42)
        assert r[0] == _ref_murmur3_bytes(b"x", seed0 & 0xFFFFFFFF)

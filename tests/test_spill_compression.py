"""Disk-tier spill compression (memory/catalog.py).

``spark.rapids.memory.spill.compression.codec`` runs the shuffle codec
ladder over disk-tier spill files — the RapidsDiskStore-compression
analog.  The .crc sidecar is computed over the COMPRESSED bytes (what
the disk actually stores), so read-back verifies exactly what was
written; a corrupted compressed file must degrade into the existing
lost-tier path (SpillCorruptionError), never inflate into garbage rows.
"""
import glob
import os

import pytest

from spark_rapids_tpu import types as T
from spark_rapids_tpu.conf import TpuConf
from spark_rapids_tpu.host.batch import HostBatch
from spark_rapids_tpu.memory import BufferCatalog, SpillPriority
from spark_rapids_tpu.memory.catalog import SpillCorruptionError

SCHEMA = T.Schema([
    T.StructField("a", T.LongType(), True),
    T.StructField("s", T.StringType(), True),
])


def _batch(rng, n=256):
    # s repeats heavily -> compressible payload
    return HostBatch.from_pydict({
        "a": [int(x) for x in rng.integers(-1000, 1000, n)],
        "s": [f"str{x}" if x % 7 else None
              for x in rng.integers(0, 9, n)],
    }, SCHEMA).to_device()


def _rows(b):
    return HostBatch.from_device(b).to_rows()


def _conf(tmp_path, codec="lz4"):
    return TpuConf({
        "spark.rapids.memory.spill.compression.codec": codec,
        "spark.rapids.memory.spill.dir": str(tmp_path),
    })


def test_compressed_spill_through_to_disk_roundtrip(rng, tmp_path):
    b1, b2 = _batch(rng), _batch(rng)
    w1, w2 = _rows(b1), _rows(b2)
    size = b1.device_size_bytes()
    # host arena fits ~one batch -> second host spill pushes first to disk
    cat = BufferCatalog(device_limit=1, host_limit=size + 4096,
                        conf=_conf(tmp_path))
    id1 = cat.add_batch(b1, priority=0)
    id2 = cat.add_batch(b2, priority=1)
    assert cat.tier_of(id1) == "disk"
    assert cat.metrics["spill_raw_bytes"] > 0
    assert cat.metrics["spill_compressed_bytes"] > 0
    # the repeated strings must actually compress
    assert cat.metrics["spill_compressed_bytes"] < \
        cat.metrics["spill_raw_bytes"]
    # the disk file holds the COMPRESSED size, not the raw size
    (path,) = glob.glob(os.path.join(str(tmp_path), "buf_*.bin"))
    assert os.path.getsize(path) == cat.metrics["spill_compressed_bytes"]
    got1 = cat.acquire(id1)
    assert _rows(got1) == w1
    cat.release(id1)
    got2 = cat.acquire(id2)
    assert _rows(got2) == w2
    cat.release(id2)
    assert cat.metrics["spill_crc_failures"] == 0
    cat.close()


def test_compressed_direct_to_disk_roundtrip(rng, tmp_path):
    """Oversized buffer: device->disk fallthrough (host arena too small)
    takes the OTHER disk-write path; it must compress identically."""
    b = _batch(rng, n=4096)
    want = _rows(b)
    cat = BufferCatalog(device_limit=1, host_limit=4096,
                        conf=_conf(tmp_path))
    bid = cat.add_batch(b, SpillPriority.SHUFFLE_OUTPUT)
    assert cat.tier_of(bid) == "disk"
    assert 0 < cat.metrics["spill_compressed_bytes"] < \
        cat.metrics["spill_raw_bytes"]
    got = cat.acquire(bid)
    assert _rows(got) == want
    cat.release(bid)
    cat.close()


def test_corrupt_compressed_spill_detected_as_lost(rng, tmp_path):
    """One flipped byte in the compressed file: the sidecar CRC (over
    the compressed bytes) must catch it BEFORE any inflate runs, and
    the buffer lands in the lost tier."""
    b = _batch(rng)
    cat = BufferCatalog(device_limit=1, host_limit=4096,
                        conf=_conf(tmp_path))
    bid = cat.add_batch(b, SpillPriority.SHUFFLE_OUTPUT)
    assert cat.tier_of(bid) == "disk"
    (path,) = glob.glob(os.path.join(str(tmp_path), "buf_*.bin"))
    raw = bytearray(open(path, "rb").read())
    raw[len(raw) // 2] ^= 0xFF
    with open(path, "wb") as f:
        f.write(raw)
    with pytest.raises(SpillCorruptionError):
        cat.acquire(bid)
    assert cat.metrics["spill_crc_failures"] == 1
    assert cat.tier_of(bid) == "lost"
    # lost stays lost: a second acquire is the same terminal error,
    # not a second CRC count
    with pytest.raises(SpillCorruptionError):
        cat.acquire(bid)
    assert cat.metrics["spill_crc_failures"] == 1
    cat.close()


def test_spill_codec_none_writes_raw(rng, tmp_path):
    """codec=none keeps the streaming write path: no compression
    counters move and the file holds the raw aligned bytes."""
    b = _batch(rng)
    want = _rows(b)
    cat = BufferCatalog(device_limit=1, host_limit=4096,
                        conf=_conf(tmp_path, codec="none"))
    bid = cat.add_batch(b, SpillPriority.SHUFFLE_OUTPUT)
    assert cat.tier_of(bid) == "disk"
    assert cat.metrics["spill_compressed_bytes"] == 0
    assert cat.metrics["spill_raw_bytes"] == 0
    got = cat.acquire(bid)
    assert _rows(got) == want
    cat.release(bid)
    cat.close()

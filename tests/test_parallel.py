"""Mesh-parallel exchange + distributed aggregation vs CPU oracle.

Mirrors the reference's transport-mock strategy (RapidsShuffleClientSuite:
protocol correctness without a network): here the 8-device CPU mesh stands
in for a TPU slice and results are checked against the single-threaded
host oracle.
"""
import jax
import jax.numpy as jnp
import numpy as np
import pytest

from spark_rapids_tpu import types as T
from spark_rapids_tpu.columnar.batch import ColumnBatch
from spark_rapids_tpu.host.batch import HostBatch
from spark_rapids_tpu.ops.segmented import AggSpec
from spark_rapids_tpu.parallel import (
    make_mesh, shard_batches, unshard_batch,
    make_hash_exchange, make_distributed_groupby,
)
from spark_rapids_tpu.parallel.mesh_shuffle import partition_ids_for_keys

SCHEMA = T.Schema([
    T.StructField("k", T.IntegerType(), True),
    T.StructField("v", T.LongType(), True),
    T.StructField("f", T.DoubleType(), True),
])


def _make_shards(rng, p=8, n_per=50, cap=64, nkeys=13):
    shards_h, shards_d = [], []
    for _ in range(p):
        k = rng.integers(0, nkeys, n_per).astype(np.int32)
        v = rng.integers(-100, 100, n_per).astype(np.int64)
        f = rng.normal(size=n_per)
        kv = np.ones(n_per, bool)
        kv[rng.integers(0, n_per, 3)] = False  # some null keys
        hb = HostBatch.from_pydict(
            {"k": np.where(kv, k, 0), "v": v, "f": f}, SCHEMA)
        hb.columns[0].validity[:] = kv
        shards_h.append(hb)
        shards_d.append(hb.to_device(capacity=cap))
    return shards_h, shards_d


def test_hash_exchange_routes_all_rows(rng):
    p = 8
    mesh = make_mesh(p)
    shards_h, shards_d = _make_shards(rng, p=p)
    stacked = shard_batches(shards_d, mesh)
    ex = make_hash_exchange(mesh, SCHEMA, [0])
    out = ex(stacked)
    outs = [b for b in unshard_batch(out)]
    total_in = sum(b.num_rows for b in shards_h)
    total_out = sum(b.host_num_rows() for b in outs)
    assert total_out == total_in
    # every row of one key lands on exactly one device, and the partition
    # choice matches the host-side murmur3 pmod
    def rk(r):
        return tuple((x is None, x) for x in r)
    all_in_rows = sorted(
        (r for hb in shards_h for r in hb.to_rows()), key=rk)
    all_out_rows = sorted(
        (r for b in outs for r in HostBatch.from_device(b).to_rows()), key=rk)
    assert all_in_rows == all_out_rows
    for d, b in enumerate(outs):
        hb = HostBatch.from_device(b)
        n = hb.num_rows
        if n == 0:
            continue
        pid = np.asarray(jax.device_get(
            partition_ids_for_keys(b, [0], p)))[:n]
        assert (pid == d).all()


def test_distributed_groupby_matches_oracle(rng):
    p = 8
    mesh = make_mesh(p)
    shards_h, shards_d = _make_shards(rng, p=p)
    stacked = shard_batches(shards_d, mesh)
    specs = [AggSpec("sum", 1), AggSpec("count", 2), AggSpec("min", 1),
             AggSpec("max", 2)]
    gb = make_distributed_groupby(mesh, SCHEMA, [0], specs)
    out = gb(stacked)
    got = sorted(
        (r for b in unshard_batch(out)
         for r in HostBatch.from_device(b).to_rows()),
        key=lambda r: (r[0] is None, r[0]))

    # oracle: single-host groupby over the concatenated shards
    big = HostBatch.concat(shards_h)
    import collections
    acc = collections.defaultdict(lambda: [0, False, 0, None, None])
    ks = big.columns[0]
    vs = big.columns[1]
    fs = big.columns[2]
    for i in range(big.num_rows):
        key = int(ks.data[i]) if ks.validity[i] else None
        a = acc[key]
        if vs.validity[i]:
            a[0] += int(vs.data[i]); a[1] = True
            a[3] = int(vs.data[i]) if a[3] is None else min(a[3], int(vs.data[i]))
        if fs.validity[i]:
            a[2] += 1
            a[4] = float(fs.data[i]) if a[4] is None else max(a[4], float(fs.data[i]))
    want = sorted(((k, a[0] if a[1] else None, a[2], a[3], a[4])
                   for k, a in acc.items()),
                  key=lambda r: (r[0] is None, r[0]))
    assert len(got) == len(want)
    for g, w in zip(got, want):
        assert g[0] == w[0] and g[1] == w[1] and g[2] == w[2] and g[3] == w[3]
        assert g[4] == pytest.approx(w[4])


def test_distributed_grand_aggregate(rng):
    p = 8
    mesh = make_mesh(p)
    shards_h, shards_d = _make_shards(rng, p=p)
    stacked = shard_batches(shards_d, mesh)
    specs = [AggSpec("sum", 1), AggSpec("count_star", 0)]
    gb = make_distributed_groupby(mesh, SCHEMA, [], specs)
    out = gb(stacked)
    rows = [r for b in unshard_batch(out)
            for r in HostBatch.from_device(b).to_rows()]
    assert len(rows) == 1
    big = HostBatch.concat(shards_h)
    vs = big.columns[1]
    assert rows[0][0] == int(vs.data[vs.validity].sum())
    assert rows[0][1] == big.num_rows

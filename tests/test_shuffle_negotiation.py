"""Shuffle-plane negotiation tests (cluster-runtime satellites).

Two behaviors a multi-process deployment depends on:

1. A refused dial (ConnectionRefusedError anywhere on the error chain)
   means no process is listening YET — the normal state of a worker
   still binding its shuffle server — so the retry ladder must retry it
   WITHOUT charging the per-peer circuit breaker (shuffle/retry.py).
   Otherwise N concurrent reduce fetches trip the breaker during a
   startup race and turn a would-succeed query into a terminal failure.

2. Codec negotiation across processes (shuffle/tcp.py): the client
   advertises the codecs it can decode; a server whose store compresses
   with something else must answer with an error FRAME (plus a
   ``codec_rejects`` metric), not undecodable bytes.  A matched fetch
   counts ``shuffle.fetch.codec.<name>`` so operators can see which
   codec actually moves bytes.
"""
import socket

import pytest

from spark_rapids_tpu import types as T
from spark_rapids_tpu.conf import TpuConf
from spark_rapids_tpu.obs.registry import get_registry
from spark_rapids_tpu.shuffle.errors import ShuffleFetchError

SCHEMA = T.Schema([
    T.StructField("k", T.LongType(), True),
    T.StructField("v", T.LongType(), True),
])


def _dead_port() -> int:
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def test_conn_refused_retries_without_charging_breaker():
    from spark_rapids_tpu.shuffle.retry import (_breaker,
                                                fetch_remote_with_retry,
                                                reset_circuit_breakers)
    reset_circuit_breakers()
    addr = ("127.0.0.1", _dead_port())
    before = get_registry().snapshot()
    with pytest.raises(ShuffleFetchError, match="giving up"):
        list(fetch_remote_with_retry(addr, 7, 0, device=False,
                                     max_retries=2, retry_wait=0.01))
    d = get_registry().delta(before)["counters"]
    # every attempt was classified as conn-refused ...
    assert d.get("shuffle.fetch.conn_refused", 0) >= 3, d
    # ... and NONE of them charged the breaker
    assert d.get("shuffle.breaker.opens", 0) == 0, d
    assert _breaker(addr).failures == 0
    reset_circuit_breakers()


def test_conn_refused_metadata_plane():
    from spark_rapids_tpu.shuffle.retry import (
        _breaker, remote_partition_sizes_with_retry,
        reset_circuit_breakers)
    reset_circuit_breakers()
    addr = ("127.0.0.1", _dead_port())
    before = get_registry().snapshot()
    with pytest.raises(ShuffleFetchError, match="giving up"):
        remote_partition_sizes_with_retry(addr, 7, max_retries=1,
                                          retry_wait=0.01)
    d = get_registry().delta(before)["counters"]
    assert d.get("shuffle.fetch.conn_refused", 0) >= 2, d
    assert _breaker(addr).failures == 0
    reset_circuit_breakers()


def test_codec_mismatch_rejected_with_error_frame(monkeypatch):
    """Server store compresses lz4; a client that can only decode
    ``none`` must get a terminal error frame naming the codec — and the
    server counts the reject — instead of bytes it cannot inflate."""
    import spark_rapids_tpu.shuffle.tcp as tcp
    from spark_rapids_tpu.exec.core import ExecCtx, host_to_device
    from spark_rapids_tpu.host.batch import HostBatch
    conf = TpuConf({"spark.rapids.shuffle.compression.codec": "lz4"})
    with ExecCtx(backend="device", conf=conf) as ctx:
        t = tcp.TcpShuffleTransport(conf, ctx)
        try:
            hb = HostBatch.from_pydict({"k": [1, 2], "v": [3, 4]}, SCHEMA)
            t.write_partition(1, 0, 0, host_to_device(hb))
            monkeypatch.setattr(tcp, "_client_codecs", lambda: ["none"])
            with pytest.raises(ShuffleFetchError) as ei:
                list(tcp.fetch_remote(t.address, 1, 0, device=False))
            assert "lz4" in str(ei.value)
            assert "not accepted" in str(ei.value)
            assert t.server_metrics.get("codec_rejects", 0) == 1
        finally:
            t.close()


def test_codec_match_roundtrips_and_counts():
    from spark_rapids_tpu.exec.core import ExecCtx, host_to_device
    from spark_rapids_tpu.host.batch import HostBatch
    from spark_rapids_tpu.shuffle.tcp import TcpShuffleTransport, fetch_remote
    conf = TpuConf({"spark.rapids.shuffle.compression.codec": "lz4"})
    with ExecCtx(backend="device", conf=conf) as ctx:
        t = TcpShuffleTransport(conf, ctx)
        try:
            hb = HostBatch.from_pydict({"k": [1, 2], "v": [3, 4]}, SCHEMA)
            t.write_partition(1, 0, 0, host_to_device(hb))
            before = get_registry().snapshot()
            got = list(fetch_remote(t.address, 1, 0, device=False))
            assert len(got) == 1
            assert got[0].to_pydict() == {"k": [1, 2], "v": [3, 4]}
            d = get_registry().delta(before)["counters"]
            assert d.get("shuffle.fetch.codec.lz4", 0) >= 1, d
            assert t.server_metrics.get("codec_rejects", 0) == 0
        finally:
            t.close()

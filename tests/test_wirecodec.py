"""Wire-codec differential tests: every encoding must round-trip
host->encode->device-decode->host bit-exactly against the raw path.

Reference test model: the compression codec round-trip tests over the
shuffle path (TableCompressionCodec, SURVEY §4); here the codec rides
the scan/backend-switch H2D path, so the round trip is
pyarrow.RecordBatch -> ColumnBatch(codec) -> to_arrow."""
import numpy as np
import pyarrow as pa
import pytest

from spark_rapids_tpu.columnar import wirecodec as wc
from spark_rapids_tpu.columnar.batch import ColumnBatch


def roundtrip(rb):
    got = ColumnBatch.from_arrow(rb, codec=True).to_arrow()
    want = ColumnBatch.from_arrow(rb, codec=False).to_arrow()
    assert got.schema == want.schema
    for i, name in enumerate(rb.schema.names):
        gl, wl = got.column(i).to_pylist(), want.column(i).to_pylist()
        assert len(gl) == len(wl), name
        for g, w in zip(gl, wl):
            if isinstance(g, float) and isinstance(w, float) \
                    and np.isnan(g) and np.isnan(w):
                continue
            assert g == w, (name, g, w)
    return got


def test_pack_bits_host_all_widths():
    rng = np.random.default_rng(0)
    for bits in range(1, 33):
        n = 1000
        vals = rng.integers(0, 1 << bits, size=n, dtype=np.uint64) \
            .astype(np.uint32)
        words = wc.pack_bits_host(vals, bits, 1024)
        assert words.dtype == np.uint32
        assert words.size == (1024 * bits + 31) // 32
        # decode on host via the same bit math the device uses
        stream = np.unpackbits(words.view(np.uint8), bitorder="little")
        got = np.zeros(n, np.uint32)
        for b in range(bits):
            got |= stream[b::bits][:n].astype(np.uint32) << np.uint32(b)
        np.testing.assert_array_equal(got, vals)


@pytest.mark.parametrize("dtype,lo,hi", [
    (np.int32, 0, 100), (np.int32, -5, 300000), (np.int64, 0, 17),
    (np.int64, -2**40, -2**40 + 1000), (np.int8, -128, 127),
    (np.int64, -2**62, 2**62),  # range too wide: raw path
])
def test_int_columns(dtype, lo, hi):
    rng = np.random.default_rng(1)
    vals = rng.integers(lo, hi, size=2000, dtype=np.int64).astype(dtype)
    mask = rng.random(2000) < 0.1
    arr = pa.array(np.ma.masked_array(vals, mask))
    roundtrip(pa.record_batch([arr], names=["c"]))


def test_timestamp_micros_divisor():
    # second-aligned micros: range > 2^32 but divisor 1e6 shrinks it
    rng = np.random.default_rng(2)
    secs = rng.integers(1_500_000_000, 1_600_000_000, size=4096)
    micros = secs * 1_000_000
    got = {}
    desc = wc.encode_fixed(
        micros, None, 4096,
        lambda a: got.setdefault("leaf", a) is None and 0 or 0,
        lambda v: got.setdefault("i64", []).append(v) or len(got["i64"]) - 1)
    assert desc[0] == "bits"
    assert desc[5] == 1_000_000  # static divisor recovered
    arr = pa.array(micros, type=pa.int64())
    roundtrip(pa.record_batch([arr], names=["ts"]))


def test_money_doubles_cents():
    rng = np.random.default_rng(3)
    cents = rng.integers(0, 3_000_000, size=4096)
    vals = (cents * 0.01).astype(np.float64)
    # exactness precondition of the cents path
    assert (np.rint(vals / 0.01) * 0.01 == vals).all()
    arr = pa.array(vals)
    roundtrip(pa.record_batch([arr], names=["price"]))


def test_doubles_raw_fallbacks():
    cases = {
        "arbitrary": np.array([1.23456789, np.pi, -0.125]),
        "nan": np.array([1.0, np.nan, 2.0]),
        "inf": np.array([np.inf, -np.inf, 0.0]),
        "negzero": np.array([-0.0, 1.0, 2.0]),
    }
    for name, vals in cases.items():
        rb = pa.record_batch([pa.array(vals)], names=[name])
        got = roundtrip(rb)
        back = np.asarray(got.column(0), dtype=np.float64)
        if name == "negzero":
            assert np.signbit(back[0]), "raw path must preserve -0.0"


def test_bool_and_validity_bitpack():
    rng = np.random.default_rng(4)
    vals = rng.random(5000) < 0.5
    mask = rng.random(5000) < 0.3
    arr = pa.array(np.ma.masked_array(vals, mask))
    roundtrip(pa.record_batch([arr], names=["b"]))
    # all-null column
    arr2 = pa.array([None] * 100, type=pa.int32())
    roundtrip(pa.record_batch([arr2], names=["n"]))


def test_dict_strings():
    rng = np.random.default_rng(5)
    cats = ["Books", "Electronics", "Home & Garden", "Música", ""]
    vals = [cats[i] for i in rng.integers(0, len(cats), size=8192)]
    vals[17] = None
    arr = pa.array(vals, type=pa.string())
    rb = pa.record_batch([arr], names=["cat"])
    # dictionary path must actually engage at this cardinality
    assert wc.maybe_dict_arrow(arr, len(arr)) is not None
    roundtrip(rb)


def test_high_cardinality_strings_stay_raw():
    vals = [f"unique-{i}" for i in range(8192)]
    arr = pa.array(vals, type=pa.string())
    assert wc.maybe_dict_arrow(arr, len(arr)) is None
    roundtrip(pa.record_batch([arr], names=["s"]))


def test_empty_and_single_row():
    for vals in ([], [42]):
        arr = pa.array(vals, type=pa.int64())
        roundtrip(pa.record_batch([arr], names=["x"]))


def test_mixed_schema_roundtrip():
    rng = np.random.default_rng(6)
    n = 4096
    rb = pa.record_batch([
        pa.array(rng.integers(0, 2**17, n, dtype=np.int64)),
        pa.array((rng.integers(0, 10**6, n) * 0.01)),
        pa.array(rng.random(n)),          # arbitrary doubles: raw
        pa.array(["ab", "cd", "ef", None] * (n // 4), type=pa.string()),
        pa.array(rng.random(n) < 0.5),
    ], names=["k", "price", "noise", "tag", "flag"])
    roundtrip(rb)


def _pack_bits_reference(vals, bits, cap):
    """The pre-optimization n x bits bit-matrix formulation, kept here
    as the oracle for the word-level accumulation rewrite."""
    n = vals.shape[0]
    nwords = (cap * bits + 31) // 32
    u = vals.astype(np.uint32)
    bm = ((u[:, None] >> np.arange(bits, dtype=np.uint32)[None, :]) & 1) \
        .astype(np.uint8)
    stream = np.zeros(nwords * 32, np.uint8)
    stream[:n * bits] = bm.reshape(-1)
    return np.packbits(stream, bitorder="little").view(np.uint32)


@pytest.mark.parametrize("bits", [1, 2, 3, 5, 7, 11, 12, 13, 17, 20, 24, 31])
def test_pack_bits_word_accumulation_matches_bit_matrix(rng, bits):
    """The word-level shift/or rewrite is bit-for-bit identical to the
    old bit-matrix packer for every width and ragged length."""
    for n in (0, 1, 7, 31, 32, 33, 1000, 4097):
        cap = max(n, 1)
        vals = rng.integers(0, 1 << bits, n, dtype=np.uint64)
        got = wc.pack_bits_host(vals, bits, cap)
        want = _pack_bits_reference(vals, bits, cap)
        assert got.dtype == np.uint32
        assert np.array_equal(got, want), (bits, n)


def test_pack_bits_peak_memory_is_linear():
    """Peak temporaries must stay O(n) bytes, not O(n*bits): the old
    bit-matrix spiked ~n*bits*2 bytes of uint8 staging (~120 MB for a
    4M-row 24-bit column)."""
    import tracemalloc
    bits, n = 24, 1 << 20
    vals = np.random.default_rng(0).integers(
        0, 1 << bits, n, dtype=np.uint64)
    tracemalloc.start()
    tracemalloc.reset_peak()
    out = wc.pack_bits_host(vals, bits, n)
    _, peak = tracemalloc.get_traced_memory()
    tracemalloc.stop()
    # old matrix formulation alone: n*bits ~ 25 MB of uint8 plus the
    # 32-aligned stream copy; the rewrite's budget is a few n*8-byte
    # temporaries.  40 MB bounds the new path with slack while failing
    # the old one (~50+ MB).
    assert peak < 40 << 20, f"peak {peak >> 20} MB"
    assert out.nbytes == ((n * bits + 31) // 32) * 4

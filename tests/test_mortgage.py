"""Mortgage benchmark suite: generator + the reference's four jobs
verify vs the host oracle (reference MortgageSpark.scala Run /
SimpleAggregates / AggregatesWithPercentiles / AggregatesWithJoin;
test model: mortgage_test.py's assert_results_equal)."""
import numpy as np
import pytest

from spark_rapids_tpu import types as T
from spark_rapids_tpu.bench.mortgage import MORTGAGE_QUERIES
from spark_rapids_tpu.bench.mortgage_gen import generate_mortgage
from spark_rapids_tpu.bench.runner import run_benchmark
from spark_rapids_tpu.exec.core import collect_host
from spark_rapids_tpu.session import TpuSession


@pytest.fixture(scope="module")
def data_dir(tmp_path_factory):
    d = str(tmp_path_factory.mktemp("mortgage") / "sf02")
    generate_mortgage(d, sf=0.2)
    return d


def _norm(rows):
    return [tuple(round(x, 6) if isinstance(x, float) else x for x in r)
            for r in rows]


@pytest.mark.parametrize("name", sorted(MORTGAGE_QUERIES))
def test_mortgage_job_device_matches_oracle(data_dir, name):
    s = TpuSession({})
    q = MORTGAGE_QUERIES[name](s, data_dir)
    dev = q.collect()
    assert len(dev) > 0
    ov, meta = q._overridden(quiet=True)
    host = collect_host(meta.exec_node, s.conf)
    assert _norm(dev) == _norm(host)


def test_mortgage_etl_delinquency_windows(data_dir):
    """The 12-month window expansion must find real delinquency
    transitions: some loans are ever_90, and their delinquency_12
    class is > 0 somewhere."""
    s = TpuSession({})
    rows = MORTGAGE_QUERIES["etl"](s, data_dir).collect()
    # run_etl output: ever_30=6, ever_90=7, ever_180=8, delinquency_12=9
    ever90 = [r[7] for r in rows]
    ever180 = [r[8] for r in rows]
    d12 = [r[9] for r in rows if r[9] is not None]
    assert any(ever90), "generator should produce 90-day delinquents"
    assert any(ever180), "generator should produce 180-day delinquents"
    assert any(v and v > 0 for v in d12)


def test_mortgage_percentiles_are_exact(data_dir):
    """Percentile columns must equal numpy's linear interpolation over
    the same groups (the engine's holistic percentile path)."""
    s = TpuSession({})
    from spark_rapids_tpu.bench.mortgage import read_performance
    from spark_rapids_tpu.expr.core import col
    from spark_rapids_tpu.expr.hashing import Murmur3Hash
    from spark_rapids_tpu.expr.strings import Hex
    base = read_performance(s, data_dir).with_column(
        "loan_id_hash", Hex(Murmur3Hash(col("loan_id")))) \
        .select(col("loan_id_hash"), col("interest_rate")).collect()
    by_k = {}
    for k, v in base:
        by_k.setdefault(k, []).append(v)
    got = MORTGAGE_QUERIES["percentiles"](s, data_dir).collect()
    for row in got[:50]:
        k = row[0]
        want = np.percentile(by_k[k], 50)
        assert abs(row[4] - round(want, 4)) < 1e-9, (k, row[4], want)


def test_mortgage_via_runner(data_dir):
    r = run_benchmark(data_dir, 0.2, ["simple_agg"], verify=True,
                      generate=False, suite="mortgage")[0]
    assert "error" not in r, r
    assert r["ok"], r


def test_train_pipeline(tmp_path):
    """BASELINE config 5: mortgage ETL -> interop.to_jax columnar
    handoff -> jitted training loop (reference docs/ml-integration.md,
    ColumnarRdd.scala:42-49).  Verified: loss strictly decreases and
    the model beats the majority-class baseline."""
    from spark_rapids_tpu.bench.mortgage import (generate_mortgage,
                                                 train_pipeline)
    from spark_rapids_tpu.session import TpuSession
    d = str(tmp_path / "m")
    generate_mortgage(d, sf=0.01)
    rec = train_pipeline(TpuSession({}), d, steps=100)
    assert rec["ok"], rec
    assert rec["loss_final"] < rec["loss0"]
    assert rec["accuracy"] >= rec["majority_baseline"]
    assert rec["rows"] > 0 and rec["features"] == 6

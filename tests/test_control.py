"""Self-driving control plane (ISSUE 18): pure rules, loop wiring, and
chaos behavior.

The rule tests drive control/rules.py against synthetic signals with no
engine, no threads, and no jax — AIMD up/down/bounds, SLO
shed-and-restore hysteresis, watermark retreat-and-heal, fleet
hysteresis + cooldown.  The loop tests run a real control-enabled
TpuSession (tiny pydict queries) and assert actuation, reversibility
(disabled = byte-identical plans + untouched counters; stop() restores
every knob), thread lifecycle (no leak after shutdown), and the two
chaos points: frozen signals decay to no-ops (control.signal.stale)
and dropped actuations re-derive next tick (control.actuate.drop).
"""
import threading
import time

import pytest

import spark_rapids_tpu.types as T
from spark_rapids_tpu.control.rules import (Decision, FleetRule,
                                            SloTracker, WatermarkRule,
                                            aimd_admission)
from spark_rapids_tpu.obs.registry import get_registry
from spark_rapids_tpu.session import TpuSession

SCHEMA = T.Schema([T.StructField("a", T.LongType())])


def _session(extra=None, interval="0.05"):
    conf = {"spark.rapids.control.enabled": "true",
            "spark.rapids.control.intervalSeconds": interval}
    conf.update(extra or {})
    return TpuSession(conf)


def _df(s, n=64):
    return s.from_pydict({"a": list(range(n))}, SCHEMA)


# ---------------------------------------------------------------------------
# AIMD admission rule
# ---------------------------------------------------------------------------

def test_aimd_decreases_multiplicatively_on_congestion():
    d = aimd_admission(8, queue_wait_p99=0.01, congested=True, active=8,
                       min_cap=1, max_cap=16, queue_wait_target=0.25)
    assert d.action == "decrease" and d.detail["to"] == 4
    # and again: 4 -> 2 -> 1, clamped at min_cap
    d = aimd_admission(2, queue_wait_p99=None, congested=True, active=2,
                       min_cap=1, max_cap=16, queue_wait_target=0.25)
    assert d.detail["to"] == 1
    assert aimd_admission(1, queue_wait_p99=None, congested=True,
                          active=1, min_cap=1, max_cap=16,
                          queue_wait_target=0.25) is None


def test_aimd_increases_additively_when_healthy_but_queued():
    d = aimd_admission(4, queue_wait_p99=0.5, congested=False, active=4,
                       min_cap=1, max_cap=16, queue_wait_target=0.25)
    assert d.action == "increase" and d.detail["to"] == 5
    # at max_cap: no further increase
    assert aimd_admission(16, queue_wait_p99=0.5, congested=False,
                          active=16, min_cap=1, max_cap=16,
                          queue_wait_target=0.25) is None
    # healthy and fast: no decision at all
    assert aimd_admission(4, queue_wait_p99=0.01, congested=False,
                          active=1, min_cap=1, max_cap=16,
                          queue_wait_target=0.25) is None
    # no traffic (None p99): no decision
    assert aimd_admission(4, queue_wait_p99=None, congested=False,
                          active=0, min_cap=1, max_cap=16,
                          queue_wait_target=0.25) is None


def test_aimd_bounds_an_unbounded_cap_only_on_congestion():
    assert aimd_admission(0, queue_wait_p99=2.0, congested=False,
                          active=9, min_cap=1, max_cap=16,
                          queue_wait_target=0.25) is None
    d = aimd_admission(0, queue_wait_p99=None, congested=True, active=9,
                       min_cap=1, max_cap=16, queue_wait_target=0.25)
    assert d.action == "bound" and 1 <= d.detail["to"] <= 16


def test_aimd_idempotent_rederivation():
    """The control.actuate.drop recovery story: deriving twice from the
    same signals yields the same decision (no internal state)."""
    kw = dict(queue_wait_p99=0.4, congested=True, active=8, min_cap=1,
              max_cap=16, queue_wait_target=0.25)
    d1, d2 = aimd_admission(8, **kw), aimd_admission(8, **kw)
    assert d1.detail == d2.detail and d1.action == d2.action


# ---------------------------------------------------------------------------
# SLO shed/restore hysteresis
# ---------------------------------------------------------------------------

def test_slo_shed_requires_consecutive_violations():
    t = SloTracker({"web": 1.0}, violation_ticks=3, recovery_ticks=2)
    assert t.observe({"web": 5.0}) == []
    assert t.observe({"web": 0.5}) == []        # streak broken
    assert t.observe({"web": 5.0}) == []
    assert t.observe({"web": 5.0}) == []
    out = t.observe({"web": 5.0})               # third consecutive
    assert [d.action for d in out] == ["shed"]
    assert "web" in t.shed and t.any_violating()


def test_slo_restore_requires_consecutive_health():
    t = SloTracker({"web": 1.0}, violation_ticks=1, recovery_ticks=3)
    assert [d.action for d in t.observe({"web": 2.0})] == ["shed"]
    t.observe({"web": 0.1})
    t.observe({"web": 2.0})                     # relapse resets streak
    t.observe({"web": 0.1})
    t.observe({"web": 0.1})
    out = t.observe({"web": 0.1})
    assert [d.action for d in out] == ["restore"]
    assert t.shed == {} and not t.any_violating()


def test_slo_silence_counts_as_healthy():
    """A shed tenant that stops sending traffic (p99=None) must still
    recover — otherwise a shed is a permanent ban."""
    t = SloTracker({"web": 1.0}, violation_ticks=1, recovery_ticks=2)
    t.observe({"web": 9.0})
    assert "web" in t.shed
    t.observe({"web": None})
    out = t.observe({"web": None})
    assert [d.action for d in out] == ["restore"]


def test_slo_only_configured_tenants_tracked():
    t = SloTracker({"web": 1.0}, violation_ticks=1)
    t.observe({"web": 0.1, "batch": 99.0})      # batch has no SLO
    assert t.shed == {} and t.status().keys() == {"web"}


# ---------------------------------------------------------------------------
# watermark adaptation
# ---------------------------------------------------------------------------

def test_watermark_steps_down_on_slow_spill_and_heals_back():
    r = WatermarkRule(base_high=0.85, base_low=0.70,
                      spill_p99_target=0.25, step=0.05, min_high=0.50,
                      heal_ticks=2)
    d = r.observe(spill_p99=1.0, grant_timeouts=0, grant_waits=3)
    assert d.action == "lower" and r.high == pytest.approx(0.80)
    assert r.low == pytest.approx(0.65)          # conf gap preserved
    # grant timeout alone is also a slow-tier signal
    d = r.observe(spill_p99=None, grant_timeouts=1, grant_waits=0)
    assert d.action == "lower" and r.high == pytest.approx(0.75)
    # healthy for heal_ticks: one step back up, never above base
    assert r.observe(spill_p99=0.01, grant_timeouts=0,
                     grant_waits=0) is None
    d = r.observe(spill_p99=0.01, grant_timeouts=0, grant_waits=0)
    assert d.action == "raise" and r.high == pytest.approx(0.80)
    for _ in range(10):
        r.observe(spill_p99=0.01, grant_timeouts=0, grant_waits=0)
    assert r.high == pytest.approx(0.85) and r.at_base()


def test_watermark_clamped_at_min_high():
    r = WatermarkRule(base_high=0.85, base_low=0.70, min_high=0.75,
                      step=0.2)
    assert r.observe(spill_p99=9.0, grant_timeouts=1,
                     grant_waits=0).detail["high_to"] == 0.75
    # already at the clamp: a worse signal produces NO decision (the
    # rule never oscillates against its own bound)
    assert r.observe(spill_p99=99.0, grant_timeouts=5,
                     grant_waits=9) is None


# ---------------------------------------------------------------------------
# fleet sizing
# ---------------------------------------------------------------------------

def test_fleet_scale_up_needs_sustained_overload_and_respects_max():
    r = FleetRule(min_workers=1, max_workers=3, up_ticks=2,
                  down_ticks=4, cooldown_s=0.0)
    assert r.observe(worker_count=1, overloaded=True, idle=False) is None
    d = r.observe(worker_count=1, overloaded=True, idle=False)
    assert d.action == "add_worker"
    # at max: no scale-up however overloaded
    for _ in range(5):
        assert r.observe(worker_count=3, overloaded=True,
                         idle=False) is None or False


def test_fleet_scale_down_slower_and_respects_min():
    r = FleetRule(min_workers=1, max_workers=0, up_ticks=2,
                  down_ticks=3, cooldown_s=0.0)
    for _ in range(2):
        assert r.observe(worker_count=2, overloaded=False,
                         idle=True) is None
    d = r.observe(worker_count=2, overloaded=False, idle=True)
    assert d.action == "remove_worker"
    for _ in range(10):
        assert r.observe(worker_count=1, overloaded=False,
                         idle=True) is None    # at minWorkers


def test_fleet_cooldown_blocks_flapping():
    r = FleetRule(min_workers=1, max_workers=0, up_ticks=1,
                  down_ticks=1, cooldown_s=100.0)
    now = 1000.0
    d = r.observe(worker_count=1, overloaded=True, idle=False, now=now)
    assert d.action == "add_worker"
    # immediately idle: inside the cooldown nothing fires either way
    assert r.observe(worker_count=2, overloaded=False, idle=True,
                     now=now + 1) is None
    assert r.observe(worker_count=2, overloaded=True, idle=False,
                     now=now + 2) is None
    # past the cooldown the idle streak fires again
    d = r.observe(worker_count=2, overloaded=False, idle=True,
                  now=now + 101)
    assert d is not None and d.action == "remove_worker"


def test_decision_to_dict_round_trip():
    d = Decision("admission", "decrease", "why", {"from": 8, "to": 4})
    out = d.to_dict()
    assert out["rule"] == "admission" and out["detail"]["to"] == 4
    assert out["applied"] is False and out["dropped"] is False


# ---------------------------------------------------------------------------
# loop wiring against a live session (no cluster, tiny queries)
# ---------------------------------------------------------------------------

def test_loop_thread_lifecycle_and_no_leak():
    s = _session()
    try:
        assert s._control.running
        assert any(t.name == "control-loop"
                   for t in threading.enumerate())
    finally:
        s.shutdown()
    assert s._control is None
    assert not any(t.name == "control-loop"
                   for t in threading.enumerate())


def test_stop_restores_cap_hook_and_sheds():
    s = _session({"spark.rapids.sql.admission.maxConcurrentQueries": "4"})
    try:
        control = s._control
        adm = s._admission_controller()
        prev_hook = control._prev_hook
        # simulate learned state
        adm.set_max_concurrent(2)
        control.slo.shed["web"] = "test shed"
        control.stop()
        assert adm.max_concurrent == 4, "cap not restored to conf"
        assert adm.pressure_hook is prev_hook
        assert control.slo.shed == {}
    finally:
        s.shutdown()


def test_slo_shed_targets_only_violating_tenant():
    """The composed pressure hook returns a reason for the shed tenant
    and defers (None) for everyone else — admission's over-share gate
    then sheds only the violator; neighbors are never even 'spared'."""
    s = _session({"spark.rapids.control.slo.batch.p99Seconds": "0.001",
                  "spark.rapids.control.slo.web.p99Seconds": "60"})
    try:
        control = s._control
        control.slo.shed["batch"] = "p99 over SLO (test)"
        assert control._pressure_hook("batch")
        assert control._pressure_hook("web") is None
        assert control._pressure_hook("default") is None
        from spark_rapids_tpu.exec.lifecycle import QueryRejected
        # batch dominates the running set BEFORE the shed lands (a
        # just-shed idle tenant is also rejected — total=0 counts as
        # over-share — but the interesting property is mid-traffic)
        control.slo.shed.clear()
        adm = s._admission_controller()
        for i in range(3):
            adm.admit(f"b{i}", tenant="batch")
        adm.admit("w-warm", tenant="web")
        before = get_registry().snapshot()
        control.slo.shed["batch"] = "p99 over SLO (test)"
        with pytest.raises(QueryRejected, match="over SLO"):
            adm.admit("b3", tenant="batch")
        # web flows untouched, and is NOT counted as pressure-spared
        # (the hook returned None for it, not a reason)
        adm.admit("w0", tenant="web")
        d = get_registry().delta(before)["counters"]
        assert d.get("admission.tenant.batch.rejected") == 1
        assert d.get("admission.tenant.web.rejected", 0) == 0
        assert d.get("admission.tenant.web.pressure_spared", 0) == 0
    finally:
        s.shutdown()


def test_tick_derives_aimd_from_real_histograms():
    """Synthetic congestion: a governor grant timeout in the window
    halves the cap; the decision is traced and recorded."""
    s = _session({"spark.rapids.sql.admission.maxConcurrentQueries": "8",
                  "spark.rapids.control.intervalSeconds": "999"})
    try:
        control = s._control
        control.tick()                          # baseline snapshot
        get_registry().inc("governor_grant_timeouts")
        applied = control.tick()
        acts = [(d.rule, d.action) for d in applied]
        assert ("admission", "decrease") in acts, acts
        assert s._admission_controller().max_concurrent == 4
        assert any(d["rule"] == "admission"
                   for d in control.status()["decisions"])
    finally:
        s.shutdown()


def test_e2e_histogram_feeds_slo_and_sheds_then_restores():
    """End-to-end: slow observed walls for a tenant with a tiny SLO
    shed it after violationTicks; silence restores it."""
    s = _session({"spark.rapids.control.slo.batch.p99Seconds": "0.0001",
                  "spark.rapids.control.slo.violationTicks": "2",
                  "spark.rapids.control.slo.recoveryTicks": "2",
                  "spark.rapids.control.intervalSeconds": "999"})
    try:
        control = s._control
        control.tick()
        reg = get_registry()
        for _ in range(2):
            reg.observe("query.tenant.batch.e2e_seconds", 0.5)
            control.tick()
        assert "batch" in control.slo.shed
        st = control.status()
        assert st["slo"]["batch"]["shed"] is True
        # window drains (windowTicks of silence) -> healthy -> restore
        for _ in range(2 + control.window_ticks):
            control.tick()
        assert control.slo.shed == {}
    finally:
        s.shutdown()


# ---------------------------------------------------------------------------
# chaos: frozen signals and dropped actuations
# ---------------------------------------------------------------------------

def test_stale_signal_fault_decays_to_noops_no_oscillation():
    """With the registry snapshot frozen (control.signal.stale firing
    every tick), deltas are empty: the loop must settle — no decision
    churn, no deadlock — and the staleness is counted."""
    s = _session({"spark.rapids.control.intervalSeconds": "999",
                  "spark.rapids.test.faults":
                      "control.signal.stale:stale,times=0"})
    try:
        control = s._control
        control.tick()
        get_registry().inc("governor_grant_timeouts")   # invisible: frozen
        before = get_registry().snapshot()
        decisions = []
        for _ in range(8):
            decisions.extend(control.tick())
        assert decisions == [], [d.to_dict() for d in decisions]
        d = get_registry().delta(before)["counters"]
        assert d.get("control_signal_stale", 0) >= 8
        assert s._admission_controller().max_concurrent == \
            control._base_cap
    finally:
        s.shutdown()


def test_dropped_actuation_rederives_next_tick():
    """control.actuate.drop loses the first decision in flight; the
    SAME decision re-derives from fresh signals next tick and lands.
    Dropped decisions are recorded as dropped, never applied."""
    s = _session({"spark.rapids.sql.admission.maxConcurrentQueries": "8",
                  "spark.rapids.control.intervalSeconds": "999",
                  "spark.rapids.test.faults":
                      "control.actuate.drop:drop,times=1,rule=admission"})
    try:
        control = s._control
        control.tick()
        adm = s._admission_controller()
        get_registry().inc("governor_grant_timeouts")
        applied = control.tick()          # the admission decision drops
        assert "admission" not in [d.rule for d in applied]
        assert adm.max_concurrent == 8, "dropped decision must not act"
        dropped = [d for d in control.decisions if d.dropped]
        assert [d.rule for d in dropped] == ["admission"]
        assert not dropped[0].applied
        # congestion persists in the sliding window: re-derived + applied
        applied = control.tick()
        assert ("admission", "decrease") in [(d.rule, d.action)
                                             for d in applied]
        assert adm.max_concurrent == 4
    finally:
        s.shutdown()


def test_loop_survives_a_bad_tick():
    """A tick that raises is counted and the thread keeps ticking."""
    s = _session(interval="0.02")
    try:
        control = s._control
        original = control._signals
        calls = {"n": 0}

        def boom():
            calls["n"] += 1
            if calls["n"] == 1:
                raise RuntimeError("injected signal failure")
            return original()

        control._signals = boom
        deadline = time.monotonic() + 5.0
        while calls["n"] < 3 and time.monotonic() < deadline:
            time.sleep(0.02)
        assert calls["n"] >= 3, "loop died after a bad tick"
        assert control.running
    finally:
        s.shutdown()


# ---------------------------------------------------------------------------
# reversibility: disabled = byte-identical
# ---------------------------------------------------------------------------

def test_disabled_is_byte_identical_to_static():
    import sys
    assert "spark_rapids_tpu.control.loop" not in sys.modules or True
    s = TpuSession({})
    try:
        df = _df(s)
        ov, meta = df._overridden(quiet=True)
        plan_off = ov.explain(meta)
        before = get_registry().snapshot()
        rows = df.collect()
        assert len(rows) == 64
        d = get_registry().delta(before)["counters"]
        assert not any(k.startswith("control") for k in d), d
        # the conf object itself is untouched by planning
        assert "spark.rapids.control.enabled" not in s.conf.settings
    finally:
        s.shutdown()
    # same plan text as a control-enabled session whose router has
    # learned nothing (no history dir): routing must be a strict no-op
    s2 = _session()
    try:
        df2 = _df(s2)
        conf = s2._routed_conf(df2._plan)
        assert conf is s2.conf, "no-history routing must not fork conf"
        ov2, meta2 = df2._overridden(quiet=True)
        assert ov2.explain(meta2) == plan_off
    finally:
        s2.shutdown()


# ---------------------------------------------------------------------------
# history-driven routing
# ---------------------------------------------------------------------------

def test_route_express_after_min_samples(tmp_path):
    s = _session({"spark.rapids.obs.history.dir": str(tmp_path),
                  "spark.rapids.control.route.expressWallSeconds": "10",
                  "spark.rapids.control.route.minSamples": "3",
                  "spark.rapids.control.intervalSeconds": "999"})
    try:
        df = _df(s)
        # below minSamples: unrouted
        for _ in range(2):
            df.collect()
        assert s._routed_conf(df._plan) is s.conf
        df.collect()
        conf = s._routed_conf(df._plan)
        assert conf is not s.conf
        assert conf.settings["spark.rapids.control.express"] == "true"
        assert conf.settings["spark.rapids.tpu.mesh.deviceCount"] == "1"
        assert conf.settings["spark.sql.adaptive.enabled"] == "false"
        # the routed run still returns correct rows
        assert len(df.collect()) == 64
        # route decisions: audited once (on change), counted per query
        kinds = [d["action"] for d in s._control.status()["decisions"]
                 if d["rule"] == "route"]
        assert kinds == ["express"]
        assert s._control.status()["route"]["indexed_fingerprints"] >= 1
    finally:
        s.shutdown()


def test_route_learns_from_history_file_of_other_process(tmp_path):
    """Entries written by another process (simulated: direct file
    append) are picked up via the stat-gated refresh."""
    import json as _json

    from spark_rapids_tpu.obs.history import HISTORY_FILE
    s = _session({"spark.rapids.obs.history.dir": str(tmp_path),
                  "spark.rapids.control.route.minSamples": "2",
                  "spark.rapids.control.intervalSeconds": "999"})
    try:
        df = _df(s)
        fp = s._control._fingerprint(df._plan)
        assert fp
        p = tmp_path / HISTORY_FILE
        with open(p, "w") as f:
            for _ in range(3):
                f.write(_json.dumps({
                    "plan_fingerprint": fp, "state": "FINISHED",
                    "wall_s": 0.01, "mesh_devices": 1}) + "\n")
        idx = s._control._history_index
        idx.min_refresh_s = 0.0
        conf = s._routed_conf(df._plan)
        assert conf is not s.conf
        assert conf.settings["spark.rapids.control.express"] == "true"
    finally:
        s.shutdown()


def test_express_marker_skips_stage_boundaries():
    from spark_rapids_tpu.conf import TpuConf
    from spark_rapids_tpu.plan.overrides import TpuOverrides

    conf = TpuConf({"spark.rapids.control.express": "true",
                    "spark.sql.adaptive.enabled": "true"})
    ov = TpuOverrides(conf)

    # the express marker must win over adaptive=true: the method
    # returns before touching the plan at all (exec_node=None would
    # blow up inside the AQE splitter, so surviving proves the
    # early return)
    class _Root:
        exec_node = None
    root = _Root()
    ov._insert_stage_boundaries(root)
    assert root.exec_node is None


# ---------------------------------------------------------------------------
# /control endpoint + degraded healthz
# ---------------------------------------------------------------------------

def test_control_endpoint_and_degraded_healthz():
    import json as _json
    import urllib.request

    from spark_rapids_tpu.obs.http import ObsHttpServer
    s = _session({"spark.rapids.control.slo.batch.p99Seconds": "0.001",
                  "spark.rapids.control.intervalSeconds": "999"})
    s._http = ObsHttpServer(s, 0)   # conf port 0 = off; bind ephemeral
    try:
        base = s._http.address
        body = _json.loads(urllib.request.urlopen(
            base + "/control", timeout=5).read())
        assert body["enabled"] is True
        assert body["admission"]["max_concurrent"] is not None
        assert "batch" in body["slo"]
        # shed the tenant: healthz flips to degraded WITH the name
        s._control.slo.shed["batch"] = "test"
        health = s._http.health()
        assert health["status"] == "degraded"
        assert health["shed_tenants"] == ["batch"]
        body = _json.loads(urllib.request.urlopen(
            base + "/control", timeout=5).read())
        assert body["shed_tenants"] == {"batch": "test"}
    finally:
        s.shutdown()


def test_control_endpoint_stub_when_disabled():
    from spark_rapids_tpu.obs.http import ObsHttpServer
    s = TpuSession({})
    s._http = ObsHttpServer(s, 0)
    try:
        assert s._http.control() == {"enabled": False}
        assert s._http.health()["status"] == "ok"
    finally:
        s.shutdown()


def test_control_confs_registered_and_slo_parser():
    from spark_rapids_tpu.conf import _REGISTRY
    from spark_rapids_tpu.control import parse_tenant_slos
    for key in ("spark.rapids.control.enabled",
                "spark.rapids.control.intervalSeconds",
                "spark.rapids.control.admission.maxConcurrent",
                "spark.rapids.control.governor.watermarkStep",
                "spark.rapids.control.fleet.cooldownSeconds"):
        assert key in _REGISTRY, key
    slos = parse_tenant_slos({
        "spark.rapids.control.slo.web.p99Seconds": "1.5",
        "spark.rapids.control.slo.batch.p99Seconds": "30",
        "spark.rapids.control.slo.bad.p99Seconds": "nope",
        "spark.rapids.control.slo.violationTicks": "3",
        "unrelated": "x"})
    assert slos == {"web": 1.5, "batch": 30.0}

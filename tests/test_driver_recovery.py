"""Driver crash recovery: write-ahead journal + worker re-attach +
resumable queries (spark_rapids_tpu/cluster/{journal,driver}.py).

Each scenario runs a REAL driver process (a python subprocess building
a local[2] session with the journal on), SIGKILLs it at a seeded
``cluster.driver.crash`` point — mid-dispatch, mid-shuffle-read,
mid-write-commit, during a drain — and then recovers in THIS process
with ``ClusterDriver.recover(conf, journal_dir)``: the journal
replays, the orphaned workers (lingering on
``driver.reattachGraceSeconds``) RECONNECT with their map-output
inventories, and the re-run query must return exactly the oracle rows.
The resumable-query contract is asserted through the registry: map
outputs the journal proved complete are claimed
(``cluster.map_outputs_resumed``), never recomputed
(``map_outputs_recomputed`` == 0).  Interrupted write commits roll
forward to exactly one ``_SUCCESS`` with zero ``_staging`` residue.
"""
import json
import os
import signal
import subprocess
import sys
import tempfile
import time

import numpy as np
import pytest

from spark_rapids_tpu import TpuSession
from spark_rapids_tpu import types as T
from spark_rapids_tpu.expr.aggregates import CountStar, Sum
from spark_rapids_tpu.expr.core import col
from spark_rapids_tpu.obs.registry import get_registry

SCHEMA = T.Schema([
    T.StructField("k", T.IntegerType(), True),
    T.StructField("v", T.LongType(), True),
])


def _mkdata(n=400, seed=7):
    rng = np.random.default_rng(seed)
    return {"k": [int(x) for x in rng.integers(0, 13, n)],
            "v": [int(x) for x in rng.integers(-1000, 1000, n)]}


def _oracle():
    s = TpuSession()
    df = s.from_pydict(_mkdata(), SCHEMA, partitions=4, rows_per_batch=64)
    want = sorted(df.group_by("k").agg(Sum(col("v")).alias("sv"),
                                       CountStar().alias("c")).collect())
    s.shutdown()
    return want


def _base_conf(journal_dir: str, grace: float = 60.0) -> dict:
    return {
        "spark.rapids.cluster.mode": "local[2]",
        "spark.rapids.cluster.journal.dir": journal_dir,
        "spark.rapids.cluster.driver.reattachGraceSeconds": str(grace),
    }


#: the driver-under-test: builds a session from argv conf, runs the
#: same deterministic group-by the oracle uses, and (mode-dependent)
#: collects, writes parquet, or drains a worker.  The seeded
#: cluster.driver.crash fault SIGKILLs it somewhere in the middle.
_DRIVER_SCRIPT = r"""
import json, sys, time
import numpy as np
from spark_rapids_tpu import TpuSession
from spark_rapids_tpu import types as T
from spark_rapids_tpu.expr.aggregates import CountStar, Sum
from spark_rapids_tpu.expr.core import col

conf = json.loads(sys.argv[1])
mode = sys.argv[2]
SCHEMA = T.Schema([T.StructField("k", T.IntegerType(), True),
                   T.StructField("v", T.LongType(), True)])
rng = np.random.default_rng(7)
data = {"k": [int(x) for x in rng.integers(0, 13, 400)],
        "v": [int(x) for x in rng.integers(-1000, 1000, 400)]}
s = TpuSession(conf)
df = s.from_pydict(data, SCHEMA, partitions=4, rows_per_batch=64)
agg = df.group_by("k").agg(Sum(col("v")).alias("sv"),
                           CountStar().alias("c"))
if mode == "write":
    agg.write_parquet(sys.argv[3])
elif mode == "drain":
    agg.collect()                 # a full query journals + completes
    s._cluster().remove_worker("w0")
elif mode == "sleep":
    agg.collect()
    print("QUERY_DONE", flush=True)
    time.sleep(120)
else:
    agg.collect()
s.shutdown()
print("CLEAN_EXIT", flush=True)
"""


def _run_driver(conf: dict, mode: str, *extra,
                timeout: float = 120.0) -> subprocess.CompletedProcess:
    # stderr goes to a real FILE, never a pipe: the workers inherit the
    # driver's stderr, so a captured pipe would keep run() blocked on
    # EOF until every LINGERING worker exits — long after the SIGKILL
    # this harness is built to observe.  A file has no reader to block
    # on and still preserves the diagnostics.
    with tempfile.TemporaryFile(mode="w+") as ef:
        proc = subprocess.run(
            [sys.executable, "-c", _DRIVER_SCRIPT, json.dumps(conf),
             mode, *extra],
            stdout=subprocess.PIPE, stderr=ef, text=True,
            timeout=timeout)
        ef.seek(0)
        proc.stderr = ef.read()
    return proc


def _journal_worker_pids(journal_dir: str) -> list:
    from spark_rapids_tpu.cluster.journal import ClusterJournal
    state = ClusterJournal.replay(journal_dir)
    return [w["pid"] for w in state.workers.values()
            if w.get("status") == "alive" and w.get("pid")]


def _pid_alive(pid: int) -> bool:
    try:
        os.kill(pid, 0)
        return True
    except ProcessLookupError:
        return False
    except PermissionError:
        return True


def _kill_stragglers(pids) -> None:
    for pid in pids:
        try:
            os.kill(pid, signal.SIGKILL)
        except (ProcessLookupError, PermissionError):
            pass


def _recover_and_rerun(journal_dir: str, conf: dict):
    """The recovery half of every crash scenario: rebuild the driver
    from the journal, attach it to a fresh session, re-run the oracle
    query, and return (driver, rows, registry counter delta)."""
    from spark_rapids_tpu.cluster.driver import ClusterDriver
    from spark_rapids_tpu.conf import TpuConf
    driver = ClusterDriver.recover(TpuConf(conf), journal_dir)
    s = TpuSession(conf).attach_cluster(driver)
    try:
        df = s.from_pydict(_mkdata(), SCHEMA, partitions=4,
                           rows_per_batch=64)
        before = get_registry().snapshot()
        rows = sorted(df.group_by("k").agg(
            Sum(col("v")).alias("sv"),
            CountStar().alias("c")).collect())
        delta = get_registry().delta(before)["counters"]
        info = dict(driver.recovery_info or {})
        return rows, delta, info
    finally:
        s.shutdown()


def _crash_scenario(tmp_path, point: str, want):
    journal_dir = str(tmp_path / "journal")
    conf = _base_conf(journal_dir)
    crashed = _run_driver(
        {**conf,
         "spark.rapids.test.faults":
             f"cluster.driver.crash:kill,point={point}"}, "query")
    assert crashed.returncode == -signal.SIGKILL, \
        f"driver survived {point}: rc={crashed.returncode} " \
        f"stderr={crashed.stderr[-2000:]}"
    assert "CLEAN_EXIT" not in crashed.stdout
    pids = _journal_worker_pids(journal_dir)
    try:
        rows, delta, info = _recover_and_rerun(journal_dir, conf)
        assert rows == want
        assert info["epoch"] == 2
        assert info["workers_reattached"] == 2, info
        assert info["workers_replaced"] == 0, info
        # zero recompute of journaled-complete map outputs
        assert delta.get("map_outputs_recomputed", 0) == 0, delta
        # the recovered driver's shutdown reaps the RE-ATTACHED workers
        # too (no pipe to them — the shutdown RPC + kill must suffice)
        deadline = time.monotonic() + 15.0
        while time.monotonic() < deadline \
                and any(_pid_alive(p) for p in pids):
            time.sleep(0.2)
        orphans = [p for p in pids if _pid_alive(p)]
        assert not orphans, f"orphan workers after shutdown: {orphans}"
        return delta, info
    finally:
        _kill_stragglers(pids)


# ---------------------------------------------------------------------------
# the four crash points
# ---------------------------------------------------------------------------

def test_crash_mid_dispatch_recovers_exact(tmp_path):
    """SIGKILL at the top of the first dispatch round: nothing but the
    shuffle_open is journaled, so the resumed query recomputes the
    stage cleanly — exact rows, both workers re-attached, epoch 2."""
    delta, info = _crash_scenario(tmp_path, "dispatch", _oracle())
    assert delta.get("cluster.fragments_dispatched", 0) >= 1, delta


def test_crash_mid_shuffle_read_resumes_without_recompute(tmp_path):
    """SIGKILL on the first reduce-side fetch: the map stage was fully
    dispatched AND journaled, so the resumed query must CLAIM every
    journaled map output from the lingering workers — the dispatch
    frontier is empty and nothing recomputes."""
    delta, info = _crash_scenario(tmp_path, "shuffle_read", _oracle())
    assert delta.get("cluster.shuffles_resumed", 0) >= 1, delta
    assert delta.get("cluster.map_outputs_resumed", 0) >= 4, delta
    # the whole map stage came from the claim: no fragment re-ran for
    # the resumed shuffle (the counter stays 0 because the one shuffle
    # in this plan resumed wholesale)
    assert delta.get("cluster.fragments_dispatched", 0) == 0, delta


def test_crash_mid_write_commit_rolls_forward(tmp_path):
    """SIGKILL right after the first staged-file rename of a write
    commit.  The rename plan hit the journal BEFORE any rename ran, so
    recovery rolls the commit FORWARD: exactly one _SUCCESS, a full
    manifest, zero _staging residue, no double-commit."""
    journal_dir = str(tmp_path / "journal")
    out = str(tmp_path / "out")
    conf = _base_conf(journal_dir)
    crashed = _run_driver(
        {**conf,
         "spark.rapids.test.faults":
             "cluster.driver.crash:kill,point=write.commit"},
        "write", out)
    assert crashed.returncode == -signal.SIGKILL, crashed.stderr[-2000:]
    pids = _journal_worker_pids(journal_dir)
    try:
        assert not os.path.exists(os.path.join(out, "_SUCCESS"))
        from spark_rapids_tpu.cluster.driver import ClusterDriver
        from spark_rapids_tpu.conf import TpuConf
        driver = ClusterDriver.recover(TpuConf(conf), journal_dir)
        try:
            info = driver.recovery_info
            assert info["write_rollforward"] == 1, info
            assert info["write_rollback"] == 0, info
        finally:
            driver.shutdown()
        success = [f for f in os.listdir(out) if f == "_SUCCESS"]
        assert len(success) == 1
        assert not os.path.exists(os.path.join(out, "_staging"))
        assert os.path.exists(os.path.join(out, "_MANIFEST.json"))
        # the rolled-forward directory serves the exact oracle rows
        s = TpuSession()
        got = sorted(tuple(r) for r in s.read_parquet(out).collect())
        s.shutdown()
        want = sorted(tuple(r) for r in _oracle())
        assert got == want
    finally:
        _kill_stragglers(pids)


def test_crash_during_drain_recovers_membership(tmp_path):
    """SIGKILL inside remove_worker after the drain fence went up: the
    half-drained worker was never told to exit, so BOTH workers linger
    and re-attach; the resumed cluster serves the query exactly."""
    journal_dir = str(tmp_path / "journal")
    conf = _base_conf(journal_dir)
    crashed = _run_driver(
        {**conf,
         "spark.rapids.test.faults":
             "cluster.driver.crash:kill,point=drain"}, "drain")
    assert crashed.returncode == -signal.SIGKILL, crashed.stderr[-2000:]
    pids = _journal_worker_pids(journal_dir)
    try:
        rows, delta, info = _recover_and_rerun(journal_dir, conf)
        assert rows == _oracle()
        assert info["workers_reattached"] == 2, info
        assert delta.get("map_outputs_recomputed", 0) == 0, delta
    finally:
        _kill_stragglers(pids)


# ---------------------------------------------------------------------------
# linger semantics
# ---------------------------------------------------------------------------

def test_linger_expiry_self_terminates(tmp_path):
    """With a short grace, orphaned workers serve their shuffle outputs
    for the window and then exit on their own — no daemon leak when no
    driver ever comes back."""
    journal_dir = str(tmp_path / "journal")
    conf = _base_conf(journal_dir, grace=2.0)
    proc = subprocess.Popen(
        [sys.executable, "-c", _DRIVER_SCRIPT, json.dumps(conf),
         "sleep"], stdout=subprocess.PIPE, text=True)
    try:
        line = ""
        deadline = time.monotonic() + 90.0
        while time.monotonic() < deadline:
            line = proc.stdout.readline()
            if "QUERY_DONE" in line:
                break
        assert "QUERY_DONE" in line
        pids = _journal_worker_pids(journal_dir)
        assert len(pids) == 2 and all(_pid_alive(p) for p in pids)
        proc.kill()
        proc.wait(timeout=10)
        # workers notice the gone driver (stdin EOF), linger ~2s, exit
        deadline = time.monotonic() + 20.0
        while time.monotonic() < deadline \
                and any(_pid_alive(p) for p in pids):
            time.sleep(0.2)
        leftovers = [p for p in pids if _pid_alive(p)]
        _kill_stragglers(leftovers)
        assert not leftovers, f"workers outlived linger: {leftovers}"
    finally:
        if proc.poll() is None:
            proc.kill()
        _kill_stragglers(_journal_worker_pids(journal_dir))


def test_zero_grace_workers_exit_with_driver(tmp_path):
    """reattachGraceSeconds=0 (the default) keeps the legacy contract:
    driver death takes the workers down immediately — no linger."""
    journal_dir = str(tmp_path / "journal")
    conf = _base_conf(journal_dir, grace=0.0)
    proc = subprocess.Popen(
        [sys.executable, "-c", _DRIVER_SCRIPT, json.dumps(conf),
         "sleep"], stdout=subprocess.PIPE, text=True)
    try:
        line = ""
        deadline = time.monotonic() + 90.0
        while time.monotonic() < deadline:
            line = proc.stdout.readline()
            if "QUERY_DONE" in line:
                break
        assert "QUERY_DONE" in line
        pids = _journal_worker_pids(journal_dir)
        proc.kill()
        proc.wait(timeout=10)
        deadline = time.monotonic() + 15.0
        while time.monotonic() < deadline \
                and any(_pid_alive(p) for p in pids):
            time.sleep(0.2)
        leftovers = [p for p in pids if _pid_alive(p)]
        _kill_stragglers(leftovers)
        assert not leftovers
    finally:
        if proc.poll() is None:
            proc.kill()
        _kill_stragglers(_journal_worker_pids(journal_dir))


# ---------------------------------------------------------------------------
# shutdown vs monitor-thread race (regression)
# ---------------------------------------------------------------------------

def test_shutdown_gates_late_death_verdicts():
    """A death verdict landing DURING shutdown must not start output
    migration against a worker the shutdown is already retiring: after
    shutdown, mark_worker_lost is a no-op, record_worker_failure
    tolerates, and remove_worker refuses outright."""
    from spark_rapids_tpu.cluster.driver import ClusterDriver
    from spark_rapids_tpu.conf import TpuConf
    driver = ClusterDriver(TpuConf(
        {"spark.rapids.cluster.mode": "local[1]",
         "spark.rapids.cluster.journal.enabled": "false"}))
    wid = driver.workers()[0].worker_id
    driver.shutdown()
    before = get_registry().snapshot()
    driver.mark_worker_lost(wid, "late verdict")
    assert driver.record_worker_failure(wid, "late verdict") == "tolerated"
    with pytest.raises(RuntimeError, match="shut down"):
        driver.remove_worker(wid)
    d = get_registry().delta(before)["counters"]
    assert d.get("cluster_workers_lost", 0) == 0, d
    assert d.get("map_outputs_migrated", 0) == 0, d


# ---------------------------------------------------------------------------
# recovery preconditions
# ---------------------------------------------------------------------------

def test_recover_requires_journal_dir():
    from spark_rapids_tpu.cluster.driver import ClusterDriver
    from spark_rapids_tpu.conf import TpuConf
    with pytest.raises(ValueError, match="journal"):
        ClusterDriver.recover(TpuConf(
            {"spark.rapids.cluster.mode": "local[2]"}))


def test_recover_replaces_dead_workers(tmp_path):
    """Recovery with NO surviving workers (grace 0: they died with the
    driver) spawns a fresh pool — workers_replaced == N, and queries
    run; the journaled map outputs reconcile away instead of wedging
    the claim path."""
    journal_dir = str(tmp_path / "journal")
    conf = _base_conf(journal_dir, grace=0.0)
    crashed = _run_driver(
        {**conf,
         "spark.rapids.test.faults":
             "cluster.driver.crash:kill,point=shuffle_read"}, "query")
    assert crashed.returncode == -signal.SIGKILL, crashed.stderr[-2000:]
    pids = _journal_worker_pids(journal_dir)
    deadline = time.monotonic() + 15.0
    while time.monotonic() < deadline \
            and any(_pid_alive(p) for p in pids):
        time.sleep(0.2)
    _kill_stragglers(pids)
    rows, delta, info = _recover_and_rerun(journal_dir, conf)
    assert rows == _oracle()
    assert info["workers_reattached"] == 0, info
    assert info["workers_replaced"] == 2, info
    # nothing survived to claim; the journaled entries were dropped by
    # reconciliation and the stage recomputed from scratch
    assert info["entries_dropped"] >= 1, info
    assert delta.get("cluster.map_outputs_resumed", 0) == 0, delta

"""Transactional write-plane chaos matrix: exactly-once partitioned
parquet commits under seeded faults (io/writer.py, exec/write_exec.py,
cluster/exec.py dispatch_write_fragments).

Every case asserts EXACT rows on read-back (pyarrow dataset with hive
partition inference — an oracle independent of the engine's scan) and
the zero-orphans invariant: after a committed job, every visible file
in the output directory is listed in ``_MANIFEST.json`` and no
``_staging`` tree remains.  Chaos cases additionally prove the fault
actually fired (``faults.injected.*`` delta > 0) so a renamed injection
point can never turn a case vacuous.

Storms covered: task death mid-write (``io.write.partial`` crash and
truncate actions), commit-message loss (``io.write.commit.drop``),
rename failure with retry and with exhaustion -> rollback
(``io.write.rename.fail``), OOM split-retry inside the write fragment
(``memory.oom``), cluster worker death mid-write
(``cluster.worker.dead``), duplicate speculative attempts, and a
graceful drain during the write (fencing).  Reference intent: Spark's
HadoopMapReduceCommitProtocol + OutputCommitCoordinator keep
speculative/failed task attempts from ever publishing partial output.
"""
import glob
import json
import os

import numpy as np
import pytest

from spark_rapids_tpu import TpuSession
from spark_rapids_tpu import types as T
from spark_rapids_tpu.expr.aggregates import Sum
from spark_rapids_tpu.expr.core import col
from spark_rapids_tpu.obs.registry import get_registry

SCHEMA = T.Schema([
    T.StructField("k", T.IntegerType(), True),
    T.StructField("cat", T.StringType(), True),
    T.StructField("v", T.DoubleType(), True),
])


def _mkdata(n=2000, seed=7):
    rng = np.random.default_rng(seed)
    cats = ["a", "b", "c", "d"]
    return {"k": [int(x) for x in rng.integers(0, 10000, n)],
            "cat": [cats[i] for i in rng.integers(0, 4, n)],
            "v": [float(i) for i in range(n)]}


def _expected(data):
    return sorted(zip(data["k"], data["cat"], data["v"]), key=str)


def _readback(out):
    """Oracle read-back: pyarrow dataset with hive partition inference
    (default ignore_prefixes skips ``_``/``.`` paths, like the engine)."""
    import pyarrow.dataset as ds
    t = ds.dataset(out, format="parquet", partitioning="hive").to_table()
    cols = {n: t.column(n).to_pylist() for n in t.column_names}
    return sorted(zip(cols["k"], cols["cat"], cols["v"]), key=str)


def _assert_no_orphans(out):
    """Zero-orphans invariant: visible files == committed manifest,
    no staging tree left behind."""
    with open(os.path.join(out, "_MANIFEST.json")) as f:
        man = json.load(f)
    committed = {os.path.normpath(e["rel"]) for e in man["files"]}
    visible = set()
    for root, dirs, files in os.walk(out):
        dirs[:] = [d for d in dirs if not d.startswith(("_", "."))]
        for fn in files:
            if fn.startswith(("_", ".")):
                continue
            visible.add(os.path.normpath(
                os.path.relpath(os.path.join(root, fn), out)))
    assert visible == committed, \
        f"orphans: {visible - committed}, missing: {committed - visible}"
    assert not os.path.exists(os.path.join(out, "_staging")), \
        "staging tree survived a committed job"


def _write(s, data, out, partition_by=("cat",), partitions=4):
    df = s.from_pydict(data, SCHEMA, partitions=partitions,
                       rows_per_batch=256)
    return df.write_parquet(out, partition_by=list(partition_by))


# ---------------------------------------------------------------------------
# case 1: clean CTAS — baseline control for the whole matrix
# ---------------------------------------------------------------------------

def test_clean_ctas_exact_and_no_orphans(tmp_path):
    data = _mkdata()
    s = TpuSession({})
    out = str(tmp_path / "clean")
    stats = _write(s, data, out)
    assert stats.num_rows == len(data["k"])
    assert _readback(out) == _expected(data)
    _assert_no_orphans(out)
    # full CRC read-back verification against the committed manifest
    from spark_rapids_tpu.io.writer import verify_manifest
    man = verify_manifest(out, full=True)
    assert man["num_rows"] == len(data["k"])


# ---------------------------------------------------------------------------
# case 2/3: task attempt dies mid-write (crash / truncated file)
# ---------------------------------------------------------------------------

@pytest.mark.parametrize("action", ["crash", "truncate"])
def test_partial_write_retries_exact(tmp_path, action):
    data = _mkdata()
    s = TpuSession({"spark.rapids.test.faults":
                    f"io.write.partial:{action},times=1"})
    out = str(tmp_path / f"partial_{action}")
    before = get_registry().snapshot()
    _write(s, data, out)
    d = get_registry().delta(before)["counters"]
    assert d.get("faults.injected.io.write.partial", 0) == 1, d
    assert d.get("write.task_attempt_failures", 0) >= 1, d
    assert _readback(out) == _expected(data)
    _assert_no_orphans(out)


# ---------------------------------------------------------------------------
# case 4: the attempt's commit message never reaches the coordinator
# ---------------------------------------------------------------------------

def test_commit_message_drop_reattempts(tmp_path):
    data = _mkdata()
    s = TpuSession({"spark.rapids.test.faults":
                    "io.write.commit.drop:drop,times=1"})
    out = str(tmp_path / "drop")
    before = get_registry().snapshot()
    _write(s, data, out)
    d = get_registry().delta(before)["counters"]
    assert d.get("faults.injected.io.write.commit.drop", 0) == 1, d
    assert d.get("write.commit_msgs_dropped", 0) == 1, d
    # the task re-attempted and the retry won
    assert _readback(out) == _expected(data)
    _assert_no_orphans(out)


# ---------------------------------------------------------------------------
# case 5: transient rename failure — retried inside the commit
# ---------------------------------------------------------------------------

def test_rename_failure_retry_succeeds(tmp_path):
    data = _mkdata()
    s = TpuSession({"spark.rapids.test.faults":
                    "io.write.rename.fail:fail,times=1"})
    out = str(tmp_path / "renametry")
    before = get_registry().snapshot()
    _write(s, data, out)
    d = get_registry().delta(before)["counters"]
    assert d.get("faults.injected.io.write.rename.fail", 0) == 1, d
    assert d.get("write.rename_retries", 0) >= 1, d
    assert d.get("write.jobs_committed", 0) == 1, d
    assert _readback(out) == _expected(data)
    _assert_no_orphans(out)


# ---------------------------------------------------------------------------
# case 6: rename failure exhausts retries — full rollback, then a clean
# rerun over the SAME directory succeeds
# ---------------------------------------------------------------------------

def test_rename_exhaustion_rolls_back_then_clean_rerun(tmp_path):
    data = _mkdata()
    out = str(tmp_path / "rollback")
    s = TpuSession({"spark.rapids.test.faults":
                    "io.write.rename.fail:fail,times=0"})
    before = get_registry().snapshot()
    with pytest.raises(Exception, match="rename"):
        _write(s, data, out)
    d = get_registry().delta(before)["counters"]
    assert d.get("faults.injected.io.write.rename.fail", 0) >= 1, d
    assert d.get("write.jobs_commit_failed", 0) == 1, d
    assert d.get("write.jobs_aborted", 0) == 1, d
    # the directory is observed UNTOUCHED: no data files, no success
    # marker, no manifest, no staging leftovers (abort removed them)
    assert glob.glob(os.path.join(out, "**", "*.parquet"),
                     recursive=True) == []
    assert not os.path.exists(os.path.join(out, "_SUCCESS"))
    assert not os.path.exists(os.path.join(out, "_MANIFEST.json"))
    assert not os.path.exists(os.path.join(out, "_staging"))
    # a later clean job over the same path commits exactly
    s2 = TpuSession({})
    _write(s2, data, out)
    assert _readback(out) == _expected(data)
    _assert_no_orphans(out)


# ---------------------------------------------------------------------------
# case 7: OOM split-retry INSIDE the write fragment — the memory
# machinery splits and retries, the attempt still commits exactly once
# ---------------------------------------------------------------------------

def test_oom_split_retry_inside_write_fragment(tmp_path):
    data = _mkdata()
    s = TpuSession({"spark.rapids.test.faults":
                    "memory.oom:oom,op=agg_flush,times=1",
                    "spark.sql.shuffle.partitions": 4})
    df = s.from_pydict(data, SCHEMA, partitions=4, rows_per_batch=256)
    agg = df.group_by("cat").agg(Sum(col("v")).alias("sv"))
    want = sorted(agg.collect(), key=str)
    out = str(tmp_path / "oomwrite")
    before = get_registry().snapshot()
    agg.write_parquet(out)
    d = get_registry().delta(before)["counters"]
    assert d.get("faults.injected.memory.oom", 0) >= 1, d
    assert d.get("write.jobs_committed", 0) == 1, d
    import pyarrow.parquet as pq
    t = pq.read_table(out)
    got = sorted(zip(t.column("cat").to_pylist(),
                     t.column("sv").to_pylist()), key=str)
    assert got == want
    _assert_no_orphans(out)


# ---------------------------------------------------------------------------
# case 8: crashed jobs leave only garbage-collectable staging — the
# next job's GC sweeps them
# ---------------------------------------------------------------------------

def test_stale_staging_gc_on_next_job(tmp_path):
    from spark_rapids_tpu.io.writer import staging_attempt_dir
    data = _mkdata()
    out = str(tmp_path / "gc")
    # plant a crashed job's leftover attempt dir (what a dead driver or
    # killed worker leaves behind: `_`-prefixed, invisible to scans)
    stale = staging_attempt_dir(out, "deadjob0", 0, 0)
    os.makedirs(stale)
    with open(os.path.join(stale, "part-00000-deadjob0-a00.parquet"),
              "wb") as f:
        f.write(b"partial")
    s = TpuSession({})
    before = get_registry().snapshot()
    _write(s, data, out)
    d = get_registry().delta(before)["counters"]
    assert d.get("write.staging_dirs_gced", 0) >= 1, d
    assert _readback(out) == _expected(data)
    _assert_no_orphans(out)


# ---------------------------------------------------------------------------
# case 9: duplicate attempts of one write task — exactly one committed
# manifest (the attempt-id satellite's regression)
# ---------------------------------------------------------------------------

def test_duplicate_attempt_single_commit(tmp_path):
    from spark_rapids_tpu.io.writer import WriteCommitCoordinator
    coord = WriteCommitCoordinator(str(tmp_path / "dup"), "parquet")
    a0 = coord.next_attempt(3)
    a1 = coord.next_attempt(3)
    assert (a0, a1) == (0, 1), "attempt ids must be distinguishable"
    before = get_registry().snapshot()
    won0 = coord.register({"task": 3, "attempt": a0, "worker": "w0",
                           "files": [], "partitions": []})
    won1 = coord.register({"task": 3, "attempt": a1, "worker": "w1",
                           "files": [], "partitions": []})
    assert won0 and not won1, "first writer must win, duplicate discarded"
    assert coord.winner(3)["attempt"] == a0
    d = get_registry().delta(before)["counters"]
    assert d.get("write.attempts_won", 0) == 1, d
    assert d.get("write.attempts_discarded", 0) == 1, d


# ---------------------------------------------------------------------------
# case 10: cluster worker killed mid-write — surviving worker re-runs
# the tasks, commit stays exact
# ---------------------------------------------------------------------------

def test_cluster_worker_death_mid_write(tmp_path):
    data = _mkdata(8000)
    s = TpuSession({
        "spark.rapids.cluster.mode": "local[2]",
        "spark.rapids.cluster.heartbeat.intervalSeconds": "0.2",
        "spark.rapids.test.faults":
            "cluster.worker.dead:dead,worker=w1,seconds=0.02,times=1",
    })
    try:
        out = str(tmp_path / "wdead")
        before = get_registry().snapshot()
        _write(s, data, out, partitions=4)
        d = get_registry().delta(before)["counters"]
        assert d.get("faults.injected.cluster.worker.dead", 0) == 1, d
        assert d.get("cluster.write_fragments_dispatched", 0) >= 1, d
        assert d.get("write.jobs_committed", 0) == 1, d
        assert _readback(out) == _expected(data)
        _assert_no_orphans(out)
    finally:
        s.shutdown(drain=True)


# ---------------------------------------------------------------------------
# case 11: straggler speculation during the write — the duplicate
# attempt's manifests are discarded, exactly one winner per task
# ---------------------------------------------------------------------------

def test_speculative_duplicate_write_exact(tmp_path):
    data = _mkdata(8000)
    s = TpuSession({
        "spark.rapids.cluster.mode": "local[2]",
        "spark.rapids.cluster.speculation.enabled": "true",
        "spark.rapids.cluster.speculation.multiplier": "2.0",
        "spark.rapids.cluster.speculation.minRuntimeSeconds": "0.2",
        "spark.rapids.test.faults":
            "cluster.worker.slow:slow,seconds=2.0,worker=w1,times=1",
    })
    try:
        out = str(tmp_path / "spec")
        before = get_registry().snapshot()
        _write(s, data, out, partitions=4)
        d = get_registry().delta(before)["counters"]
        assert d.get("faults.injected.cluster.worker.slow", 0) == 1, d
        assert d.get("speculative_launched", 0) >= 1, d
        # exactly one winning manifest per task: 4 child partitions
        assert d.get("write.attempts_won", 0) == 4, d
        assert _readback(out) == _expected(data)
        _assert_no_orphans(out)
    finally:
        s.shutdown(drain=True)


# ---------------------------------------------------------------------------
# case 12: graceful drain DURING the write — the drained worker is
# fenced out of the commit, survivors finish the job
# ---------------------------------------------------------------------------

def test_drain_during_write_fences_and_completes(tmp_path, monkeypatch):
    import spark_rapids_tpu.io.writer as writer
    data = _mkdata(8000)
    s = TpuSession({
        "spark.rapids.cluster.mode": "local[2]",
        "spark.rapids.cluster.heartbeat.intervalSeconds": "0.2",
    })
    try:
        drv = s._cluster()
        fired: dict = {}
        orig = writer.WriteCommitCoordinator.register

        def hooked(self, manifest):
            # retire w1 synchronously at its FIRST commit registration:
            # the drain fences w1 in this coordinator, so this very
            # manifest must be rejected and the task re-dispatched
            if manifest.get("worker") == "w1" and not fired:
                fired["ok"] = True
                fired.update(drv.remove_worker("w1", drain=True))
            return orig(self, manifest)

        monkeypatch.setattr(writer.WriteCommitCoordinator, "register",
                            hooked)
        out = str(tmp_path / "drain")
        before = get_registry().snapshot()
        _write(s, data, out, partitions=4)
        assert fired.get("ok"), "drain never triggered mid-write"
        d = get_registry().delta(before)["counters"]
        assert d.get("cluster_workers_drained", 0) == 1, d
        assert d.get("write.attempts_fenced", 0) >= 1, d
        assert d.get("write.jobs_committed", 0) == 1, d
        assert _readback(out) == _expected(data)
        _assert_no_orphans(out)
        h = drv.worker_by_id("w1")
        assert h.retired and not h.alive
    finally:
        s.shutdown(drain=True)


# ---------------------------------------------------------------------------
# case 13: CTAS-then-read — a committed write invalidates result-cache
# entries that scanned the replaced files (stale hits are impossible)
# ---------------------------------------------------------------------------

def test_ctas_then_read_invalidates_result_cache(tmp_path):
    data1 = _mkdata(500, seed=1)
    data2 = _mkdata(500, seed=2)
    s = TpuSession({})
    out = str(tmp_path / "cachedir")
    _write(s, data1, out, partition_by=())
    first = sorted(s.read_parquet(out).collect(), key=str)
    assert len(first) == 500
    # read again so the result cache demonstrably holds the entry
    assert sorted(s.read_parquet(out).collect(), key=str) == first
    before = get_registry().snapshot()
    _write(s, data2, out, partition_by=())
    d = get_registry().delta(before)["counters"]
    assert d.get("result_cache_invalidated", 0) >= 1, d
    # fresh read sees the newly committed rows, never the stale cache
    again = sorted(s.read_parquet(out).collect(), key=str)
    k2 = set(data2["k"])
    assert any(r[0] in k2 for r in again)
    assert len(again) > len(first)  # append semantics: both jobs visible


# ---------------------------------------------------------------------------
# case 14: verifyCrcOnScan — post-commit corruption is detected at scan
# time instead of served as silently wrong rows
# ---------------------------------------------------------------------------

def test_verify_crc_on_scan_detects_corruption(tmp_path):
    from spark_rapids_tpu.io.writer import WriteIntegrityError
    data = _mkdata(500)
    s = TpuSession({"spark.rapids.io.write.verifyCrcOnScan": "true"})
    out = str(tmp_path / "crc")
    _write(s, data, out, partition_by=())
    assert sorted(s.read_parquet(out).collect(), key=str) == \
        sorted(zip(data["k"], data["cat"], data["v"]), key=str)
    # flip one byte of a committed file behind the manifest's back
    victim = glob.glob(os.path.join(out, "*.parquet"))[0]
    with open(victim, "r+b") as f:
        f.seek(100)
        b = f.read(1)
        f.seek(100)
        f.write(bytes([b[0] ^ 0xFF]))
    with pytest.raises(WriteIntegrityError, match="CRC32"):
        s.read_parquet(out).collect()

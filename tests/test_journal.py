"""Durability edge cases for the write-ahead cluster journal
(spark_rapids_tpu/cluster/journal.py) — pure file-level tests, no
cluster subprocesses.

The contracts proved here are exactly what ClusterDriver.recover leans
on: a torn tail (crash mid-write) heals at open and replay never sees
the fragment; a CRC-corrupt record mid-file stops replay at the last
good record with everything after it counted (order is the correctness
contract — skip-and-continue could interleave two torn writes);
snapshot compaction is replay-equivalent to the uncompacted log; and
concurrent appenders through the group-commit gate lose no records.
"""
import json
import os
import threading
import zlib

import pytest

from spark_rapids_tpu.cluster.journal import (LOG_NAME, SNAPSHOT_NAME,
                                              ClusterJournal, JournalState)


def _read_log(d):
    with open(os.path.join(d, LOG_NAME), "rb") as f:
        return f.read()


def _sample_records(n_workers=2, sid="shuf-a"):
    recs = [{"k": "driver_start", "epoch": 1}]
    for i in range(n_workers):
        recs.append({"k": "worker_ready", "wid": f"w{i}", "pid": 100 + i,
                     "rpc": ["127.0.0.1", 9000 + i],
                     "shuffle": ["127.0.0.1", 9100 + i]})
    recs.append({"k": "shuffle_open", "sid": sid, "fp": "f" * 40,
                 "num_parts": 4, "ncpids": 2, "conf_fp": "c" * 40})
    recs.append({"k": "map_register", "sid": sid, "wid": "w0",
                 "shuffle": ["127.0.0.1", 9100],
                 "entries": [[0, 0, 0, 10, 5, 0], [0, 1, 1, 12, 6, 0]]})
    recs.append({"k": "frontier", "sid": sid, "done": [0]})
    return recs


class TestTornTail:
    def test_torn_tail_healed_on_open(self, tmp_path):
        d = str(tmp_path)
        j = ClusterJournal(d)
        for r in _sample_records():
            j.append(r.pop("k"), **r)
        j.close()
        intact = _read_log(d)
        # crash mid-write: the last record loses its second half
        with open(os.path.join(d, LOG_NAME), "r+b") as f:
            f.truncate(len(intact) - 7)
        state = ClusterJournal.replay(d)
        assert state.truncated_records == 1
        assert state.epoch == 1  # the intact prefix replays fine
        # heal-on-open truncates back to the last intact record and
        # new appends chain cleanly after it
        j2 = ClusterJournal(d)
        assert j2.metrics["journal_truncated_records"] == 1
        j2.append("frontier", sid="shuf-a", done=[1])
        j2.close()
        state = ClusterJournal.replay(d)
        assert state.truncated_records == 0
        # the torn record (frontier done=[0]) is gone for good — only
        # the post-heal append landed; the register before it survived
        assert state.shuffles["shuf-a"]["done"] == {1}
        assert len(state.shuffles["shuf-a"]["entries"]) == 2

    def test_tail_without_newline_dropped(self, tmp_path):
        d = str(tmp_path)
        j = ClusterJournal(d)
        j.append("driver_start", epoch=3)
        j.close()
        with open(os.path.join(d, LOG_NAME), "ab") as f:
            f.write(b"deadbeef {\"k\":\"driver_start\",\"epoch\":9}")
        state = ClusterJournal.replay(d)
        assert state.epoch == 3
        assert state.truncated_records == 1


class TestCorruptRecord:
    def test_crc_corrupt_stops_at_last_good(self, tmp_path):
        d = str(tmp_path)
        j = ClusterJournal(d)
        for r in _sample_records():
            j.append(r.pop("k"), **r)
        j.close()
        lines = _read_log(d).splitlines(keepends=True)
        assert len(lines) == 6
        # flip one payload byte of the 4th record: CRC mismatch
        bad = bytearray(lines[3])
        bad[12] ^= 0xFF
        lines[3] = bytes(bad)
        with open(os.path.join(d, LOG_NAME), "wb") as f:
            f.writelines(lines)
        state = ClusterJournal.replay(d)
        # the corrupt record AND both records after it are dropped —
        # never skip-and-continue past a corruption
        assert state.truncated_records == 3
        assert state.epoch == 1
        assert len(state.workers) == 2
        assert "shuf-a" not in state.shuffles  # shuffle_open was #4

    def test_garbage_frame_is_rejected(self):
        from spark_rapids_tpu.cluster.journal import _parse
        payload = json.dumps({"k": "driver_start"}).encode()
        good = b"%08x %s\n" % (zlib.crc32(payload) & 0xFFFFFFFF, payload)
        assert _parse(good) == {"k": "driver_start"}
        assert _parse(b"not a frame\n") is None
        assert _parse(b"zzzzzzzz " + payload + b"\n") is None
        assert _parse(good[:-1]) is None  # no terminator


class TestSnapshotCompaction:
    def test_compaction_replay_equivalence(self, tmp_path):
        ref, compact = str(tmp_path / "ref"), str(tmp_path / "compact")
        # tiny bound: the compacting journal snapshots many times over
        jr = ClusterJournal(ref, max_bytes=1 << 30)
        jc = ClusterJournal(compact, max_bytes=4096)
        for r in _sample_records():
            jr.append(r["k"], **{k: v for k, v in r.items() if k != "k"})
            jc.append(r["k"], **{k: v for k, v in r.items() if k != "k"})
        for i in range(200):
            rec = {"k": "map_register", "sid": "shuf-a", "wid": "w1",
                   "shuffle": ["127.0.0.1", 9101],
                   "entries": [[1_000_000 + i, i % 4, i, 100, 50, 0]]}
            jr.append(rec["k"], **{k: v for k, v in rec.items()
                                   if k != "k"})
            jc.append(rec["k"], **{k: v for k, v in rec.items()
                                   if k != "k"})
        jr.close()
        jc.close()
        assert jc.metrics["journal_snapshots"] >= 1
        assert os.path.exists(os.path.join(compact, SNAPSHOT_NAME))
        a = ClusterJournal.replay(ref)
        b = ClusterJournal.replay(compact)
        assert a.epoch == b.epoch
        assert a.workers == b.workers
        assert a.shuffles.keys() == b.shuffles.keys()
        sa, sb = a.shuffles["shuf-a"], b.shuffles["shuf-a"]
        assert sa["entries"] == sb["entries"]
        assert sa["epochs"] == sb["epochs"]
        assert sa["done"] == sb["done"]

    def test_snapshot_drops_settled_write_jobs(self, tmp_path):
        st = JournalState()
        for job, fin in (("j1", "write_commit_done"),
                         ("j2", "write_abort"), ("j3", None)):
            st.apply({"k": "write_start", "job": job,
                      "path": "/tmp/x", "fmt": "parquet"})
            if fin:
                st.apply({"k": fin, "job": job})
        doc = st.to_json()
        assert set(doc["write_jobs"]) == {"j3"}
        back = JournalState.from_json(doc)
        assert set(back.write_jobs) == {"j3"}

    def test_state_json_roundtrip(self):
        st = JournalState()
        for r in _sample_records():
            st.apply(r)
        st.apply({"k": "map_invalidate", "sid": "shuf-a",
                  "epochs": {"1": 2}})
        back = JournalState.from_json(st.to_json())
        assert back.epoch == st.epoch
        assert back.workers == st.workers
        s0, s1 = st.shuffles["shuf-a"], back.shuffles["shuf-a"]
        assert s0["entries"] == s1["entries"]
        assert s0["epochs"] == s1["epochs"]
        assert s0["done"] == s1["done"]

    def test_idempotent_replay(self):
        """Re-applying every record (a compaction race duplicating the
        snapshot's contents into the tail) changes nothing."""
        st = JournalState()
        recs = _sample_records()
        for r in recs:
            st.apply(r)
        snap = st.to_json()
        for r in recs:
            st.apply(r)
        assert st.to_json() == snap


class TestGroupCommit:
    def test_concurrent_appenders_lose_nothing(self, tmp_path):
        d = str(tmp_path)
        j = ClusterJournal(d)
        j.append("driver_start", epoch=1)
        j.append("shuffle_open", sid="s", fp="f", num_parts=8,
                 ncpids=64, conf_fp="c")
        n_threads, per = 8, 50
        barrier = threading.Barrier(n_threads)

        def worker(t):
            barrier.wait()
            for i in range(per):
                mid = t * per + i
                j.append("map_register", sid="s", wid=f"w{t}",
                         shuffle=["127.0.0.1", 9100 + t],
                         entries=[[mid, mid % 8, i, 10, 5, 0]])

        ts = [threading.Thread(target=worker, args=(t,))
              for t in range(n_threads)]
        for t in ts:
            t.start()
        for t in ts:
            t.join()
        j.close()
        assert j.metrics["journal_appends"] == 2 + n_threads * per
        # group commit: far fewer fsyncs than appends (the leader's
        # fsync covers every record buffered behind it) — but at least
        # one, and no record lost
        assert 1 <= j.metrics["journal_group_commits"] \
            <= j.metrics["journal_appends"]
        state = ClusterJournal.replay(d)
        assert state.truncated_records == 0
        assert len(state.shuffles["s"]["entries"]) == n_threads * per

    def test_append_after_close_is_noop(self, tmp_path):
        d = str(tmp_path)
        j = ClusterJournal(d)
        j.append("driver_start", epoch=1)
        j.close()
        j.append("driver_start", epoch=99)  # swallowed, not crashed
        assert ClusterJournal.replay(d).epoch == 1


class TestFaultPoints:
    def test_torn_fault_heals_like_a_real_crash(self, tmp_path):
        from spark_rapids_tpu.faults import FaultRegistry
        d = str(tmp_path)
        # tear the LAST group commit: a crash inside the final write
        faults = FaultRegistry("cluster.journal.torn:fail,nth=6")
        j = ClusterJournal(d, faults=faults)
        for r in _sample_records():
            j.append(r.pop("k"), **r)
        j.close()
        state = ClusterJournal.replay(d)
        # one record was cut in half mid-"syscall"; the prefix replays
        assert state.truncated_records == 1
        assert state.epoch == 1
        j2 = ClusterJournal(d)
        assert j2.metrics["journal_truncated_records"] == 1
        j2.close()

    def test_fsync_fail_degrades_not_fails(self, tmp_path):
        from spark_rapids_tpu.faults import FaultRegistry
        d = str(tmp_path)
        faults = FaultRegistry("cluster.journal.fsync.fail:fail,times=100")
        j = ClusterJournal(d, faults=faults)
        for r in _sample_records():
            j.append(r.pop("k"), **r)  # must not raise
        j.close()
        assert j.metrics["journal_fsync_failures"] >= 1
        assert j.metrics["journal_fsyncs"] == 0
        # flush-only durability: a clean process still replays fully
        state = ClusterJournal.replay(d)
        assert state.truncated_records == 0
        assert len(state.workers) == 2


class TestDoneCpids:
    def test_done_requires_surviving_entries(self):
        st = JournalState()
        st.apply({"k": "shuffle_open", "sid": "s", "fp": "f",
                  "num_parts": 2, "ncpids": 3, "conf_fp": "c"})
        st.apply({"k": "map_register", "sid": "s", "wid": "w0",
                  "shuffle": [], "entries": [[0, 0, 0, 1, 1, 0]]})
        st.apply({"k": "map_register", "sid": "s", "wid": "w1",
                  "shuffle": [], "entries": [[1_000_000, 1, 0, 1, 1, 0]]})
        st.apply({"k": "frontier", "sid": "s", "done": [0, 1, 2]})
        # cpid 2 journaled no maps: the frontier alone proves it done;
        # cpid 1 loses its only entry to an invalidation -> not done
        st.apply({"k": "map_invalidate", "sid": "s",
                  "epochs": {"1000000": 1}})
        assert st.shuffle_done_cpids("s") == {0, 2}

"""enginelint (tools/enginelint): rule catalog, suppression syntax, and
the live-tree meta-gate.

Each rule gets a positive (flagged) and negative (clean) synthetic
snippet through :func:`lint_source` with an engine-looking path — the
rules scope themselves by path, so the snippets never touch real
engine files.  The meta-test lints the REAL spark_rapids_tpu tree and
asserts it is clean under ``--strict`` semantics: zero unsuppressed
findings and zero suppressions without a written reason — the same
gate ci/premerge.sh runs.
"""
import os
import textwrap

from tools.enginelint import lint_source, run_lint

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _active(findings, rule=None):
    return [f for f in findings
            if not f.suppressed and (rule is None or f.rule == rule)]


def _lint(src, rel="spark_rapids_tpu/exec/snippet.py"):
    return lint_source(textwrap.dedent(src), rel)


# ---------------------------------------------------------------------------
# RL001: broad except swallowing terminal lifecycle exceptions
# ---------------------------------------------------------------------------

def test_rl001_flags_bare_and_broad_except():
    src = """
    def f():
        try:
            g()
        except Exception:
            pass
        try:
            g()
        except (ValueError, BaseException):
            log()
        try:
            g()
        except:
            pass
    """
    hits = _active(_lint(src), "RL001")
    assert len(hits) == 3


def test_rl001_passes_guarded_handlers():
    src = """
    def f():
        try:
            g()
        except Exception as e:
            if getattr(e, "terminal", False):
                raise
            log(e)
        try:
            g()
        except ValueError:
            pass
        try:
            g()
        except Exception as e:
            reraise_terminal(e)
    """
    assert _active(_lint(src), "RL001") == []


def test_rl001_outside_engine_tree_ignored():
    src = "try:\n    g()\nexcept Exception:\n    pass\n"
    assert _active(lint_source(src, "tools/somewhere.py"), "RL001") == []


# ---------------------------------------------------------------------------
# RL002: raw jax.jit at import time
# ---------------------------------------------------------------------------

def test_rl002_flags_module_scope_and_decorator_jit():
    src = """
    import jax
    from jax import jit

    _k = jax.jit(lambda x: x + 1)

    @jax.jit
    def f(x):
        return x

    @jit
    def g(x):
        return x

    class C:
        h = jax.jit(lambda x: x)
    """
    hits = _active(_lint(src), "RL002")
    assert len(hits) == 4


def test_rl002_passes_call_time_and_compile_cache():
    src = """
    import jax

    def build():
        return jax.jit(lambda x: x)  # call time: guarded by the caller
    """
    assert _active(_lint(src), "RL002") == []
    modscope = "import jax\n_k = jax.jit(lambda x: x)\n"
    assert _active(lint_source(
        modscope, "spark_rapids_tpu/exec/compile_cache.py"), "RL002") == []


# ---------------------------------------------------------------------------
# RL003: host syncs in exec hot paths
# ---------------------------------------------------------------------------

def test_rl003_flags_sync_calls_in_exec():
    src = """
    import jax

    def pull(batches):
        n = jax.device_get(batches[0])
        batches[1].block_until_ready()
        return n
    """
    assert len(_active(_lint(src), "RL003")) == 2


def test_rl003_whitelisted_modules_and_other_layers_pass():
    src = "import jax\n\ndef f(x):\n    return jax.device_get(x)\n"
    for rel in ("spark_rapids_tpu/exec/transitions.py",
                "spark_rapids_tpu/exec/core.py",
                "spark_rapids_tpu/shuffle/tcp.py"):
        assert _active(lint_source(src, rel), "RL003") == []


# ---------------------------------------------------------------------------
# RL004: unbounded loops without a cancellation checkpoint
# ---------------------------------------------------------------------------

def test_rl004_flags_unbounded_dispatch_loop():
    src = """
    def drain(q):
        while True:
            item = q.get()
            handle(item)
    """
    assert len(_active(_lint(src), "RL004")) == 1


def test_rl004_passes_checkpointed_and_budgeted_loops():
    src = """
    def drain(q, lifecycle):
        while True:
            lifecycle.check()
            handle(q.get())

    def pull(ctx):
        while True:
            ctx.check_cancel()
            step()

    def retry(fn):
        attempts = 0
        while True:
            try:
                return fn()
            except OSError:
                attempts += 1
                if attempts > 3:
                    raise
    """
    assert _active(_lint(src), "RL004") == []


def test_rl004_scoped_to_dispatch_layers():
    src = "def f():\n    while True:\n        step()\n"
    assert _active(lint_source(
        src, "spark_rapids_tpu/plan/overrides.py"), "RL004") == []
    assert _active(lint_source(
        src, "spark_rapids_tpu/exec/lifecycle.py"), "RL004") == []


# ---------------------------------------------------------------------------
# RL005: fault point names vs the faults.py registry (cross-file)
# ---------------------------------------------------------------------------

def test_rl005_both_directions(tmp_path):
    pkg = tmp_path / "spark_rapids_tpu"
    pkg.mkdir()
    (pkg / "faults.py").write_text(
        'KNOWN_POINTS = frozenset({"tcp.reset", "never.fired"})\n')
    (pkg / "shuffle.py").write_text(textwrap.dedent("""
        def serve(faults):
            faults.check("tcp.reset", {})
            faults.check("tcp.tpyo", {})
    """))
    findings = _active(run_lint([str(tmp_path)], root=str(tmp_path)),
                       "RL005")
    assert len(findings) == 2
    blob = "\n".join(f.message for f in findings)
    assert "tcp.tpyo" in blob and "not registered" in blob
    assert "never.fired" in blob and "no faults.check() call" in blob


def test_rl005_silent_without_faults_file(tmp_path):
    pkg = tmp_path / "spark_rapids_tpu"
    pkg.mkdir()
    (pkg / "shuffle.py").write_text(
        'def serve(faults):\n    faults.check("tcp.reset", {})\n')
    assert _active(run_lint([str(tmp_path)], root=str(tmp_path)),
                   "RL005") == []


# ---------------------------------------------------------------------------
# suppression syntax
# ---------------------------------------------------------------------------

def test_suppression_same_line_with_reason():
    src = """
    def f():
        try:
            g()
        except Exception:  # enginelint: disable=RL001 (diag best-effort)
            pass
    """
    findings = _lint(src)
    (f,) = [f for f in findings if f.rule == "RL001"]
    assert f.suppressed and f.reason == "diag best-effort"


def test_suppression_preceding_comment_line():
    src = """
    def f():
        try:
            g()
        # enginelint: disable=RL001 (cleanup must not mask)
        except Exception:
            pass
    """
    (f,) = [f for f in _lint(src) if f.rule == "RL001"]
    assert f.suppressed and f.reason == "cleanup must not mask"


def test_suppression_without_reason_is_tracked():
    src = """
    def f():
        try:
            g()
        except Exception:  # enginelint: disable=RL001
            pass
    """
    (f,) = [f for f in _lint(src) if f.rule == "RL001"]
    assert f.suppressed and f.reason is None  # --strict fails this


def test_suppression_is_per_rule():
    src = """
    import jax

    def f(x):
        try:
            return jax.device_get(x)
        except Exception:  # enginelint: disable=RL003 (wrong rule)
            pass
    """
    findings = _lint(src)
    rl001 = [f for f in findings if f.rule == "RL001"]
    assert rl001 and not rl001[0].suppressed


def test_trailing_comment_of_previous_statement_does_not_leak():
    src = """
    def f():
        g()  # enginelint: disable=RL004 (about g, not the loop)
        while True:
            step()
    """
    (f,) = [f for f in _lint(src) if f.rule == "RL004"]
    assert not f.suppressed


# ---------------------------------------------------------------------------
# meta-gate: the live tree lints clean under --strict semantics
# ---------------------------------------------------------------------------

def test_live_tree_lints_clean_strict():
    findings = run_lint([os.path.join(_REPO, "spark_rapids_tpu")],
                        root=_REPO)
    assert findings, "lint saw no files — wrong path?"
    active = [f.render() for f in findings if not f.suppressed]
    assert active == [], "\n".join(active)
    unreasoned = [f.render() for f in findings
                  if f.suppressed and not f.reason]
    assert unreasoned == [], "\n".join(unreasoned)


def test_cli_strict_exits_zero_on_live_tree():
    from tools.enginelint.__main__ import main
    assert main([os.path.join(_REPO, "spark_rapids_tpu"),
                 "--strict"]) == 0

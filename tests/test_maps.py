"""MapType (host-only) + GetMapValue with fallback tagging.

Reference: GetMapValue (complexTypeExtractors) and the
unsupported-type degradation model (RapidsMeta.willNotWorkOnGpu): map
columns run on the host with explain reasons; once projected away the
plan returns to the device.
"""
import numpy as np
import pytest

from spark_rapids_tpu import types as T
from spark_rapids_tpu.exec.core import collect_host
from spark_rapids_tpu.expr.collections import GetMapValue
from spark_rapids_tpu.expr.core import col, lit
from spark_rapids_tpu.session import TpuSession

SCHEMA = T.Schema([
    T.StructField("k", T.IntegerType()),
    T.StructField("m", T.MapType(T.StringType(), T.LongType())),
])


def _df(s, n=30):
    return s.from_pydict(
        {"k": list(range(n)),
         "m": [None if i % 7 == 3 else
               {"a": i, "b": i * 10} if i % 2 else {"a": i}
               for i in range(n)]},
        SCHEMA, partitions=2, rows_per_batch=8)


def test_map_roundtrip_and_fallback_tagging():
    s = TpuSession({})
    df = _df(s)
    plan = df.explain()
    assert "map columns are host-only" in plan
    rows = sorted(df.collect())
    assert rows[3][1] is None
    assert rows[1][1] == {"a": 1, "b": 10}


def test_get_map_value():
    s = TpuSession({})
    out = _df(s).select(col("k"),
                        GetMapValue(col("m"), lit("b")).alias("b"))
    rows = sorted(out.collect())
    ov, meta = out._overridden(quiet=True)
    host = sorted(collect_host(meta.exec_node, s.conf))
    assert rows == host
    assert rows[1] == (1, 10)      # has "b"
    assert rows[2] == (2, None)    # missing key -> null
    assert rows[3] == (3, None)    # null map -> null


def test_plan_returns_to_device_after_dropping_map():
    """Projecting the map away puts downstream operators back on the
    device (transition inserted at the boundary)."""
    from spark_rapids_tpu.expr.aggregates import Sum
    s = TpuSession({})
    out = _df(s).select(col("k"), GetMapValue(col("m"), lit("a"))
                        .alias("a")) \
        .where(col("a") >= lit(0)) \
        .group_by().agg(Sum(col("a")).alias("sa"))
    plan = out.explain()
    assert "BackendSwitch" in plan or "*" in plan.splitlines()[0]
    rows = out.collect()
    ov, meta = out._overridden(quiet=True)
    assert rows == collect_host(meta.exec_node, s.conf)


def test_map_arrow_roundtrip(tmp_path):
    import pyarrow.parquet as pq
    s = TpuSession({})
    table = _df(s).to_arrow()
    p = str(tmp_path / "m.parquet")
    pq.write_table(table, p)
    back = s.read_parquet(p)
    rows = sorted(back.collect())
    assert rows == sorted(_df(s).collect())


def test_device_plan_above_dropped_map_column():
    """A device node directly above a map-carrying host child must not
    force a map upload (review repro: df.select(k) over a map scan
    crashed in host_to_device)."""
    s = TpuSession({})
    out = _df(s).select(col("k")).where(col("k") > lit(5))
    rows = sorted(out.collect())
    assert rows == [(i,) for i in range(6, 30)]
    ov, meta = out._overridden(quiet=True)
    assert rows == sorted(collect_host(meta.exec_node, s.conf))


def test_get_map_value_date_values():
    """Date/timestamp map values get the engine encodings through
    HostColumn.from_values (review repro: raw datetime.date crashed the
    int buffer assignment)."""
    import datetime as dt
    schema = T.Schema([
        T.StructField("m", T.MapType(T.StringType(), T.DateType()))])
    s = TpuSession({})
    df = s.from_pydict(
        {"m": [{"d": dt.date(2020, 1, i + 1)} for i in range(5)]}, schema)
    out = df.select(GetMapValue(col("m"), lit("d")).alias("d"))
    rows = sorted(out.collect())
    assert rows[0] == (dt.date(2020, 1, 1),)


# -- device map decomposition (VERDICT r3 item 9) ----------------------------

NUM_SCHEMA = T.Schema([
    T.StructField("k", T.IntegerType()),
    T.StructField("m", T.MapType(T.IntegerType(), T.LongType())),
])


def _num_df(s, n=40):
    return s.from_pydict(
        {"k": list(range(n)),
         "m": [None if i % 7 == 3 else
               {1: i, 2: i * 10} if i % 2 else {1: i}
               for i in range(n)]},
        NUM_SCHEMA, partitions=2, rows_per_batch=8)


def test_map_decomposition_runs_extractions_on_device():
    """Numeric-key maps whose every use is an extraction are split into
    array columns at the scan; GetMapValue becomes a device MapLookup
    and explain shows no map fallback above the split (reference:
    GetMapValue on device, complexTypeExtractors.scala)."""
    s = TpuSession({})
    df = _num_df(s)
    out = df.select(col("k"),
                    GetMapValue(col("m"), lit(np.int32(2))).alias("b"))
    ex = out.explain()
    assert "MapDecomposeExec" in ex
    assert "GetMapValue" not in ex
    assert "* ProjectExec" in ex          # extraction on the device
    rows = sorted(out.collect())
    ov, meta = out._overridden(quiet=True)
    assert rows == sorted(collect_host(meta.exec_node, s.conf))
    assert rows[1] == (1, 10)
    assert rows[2] == (2, None)   # missing key
    assert rows[3] == (3, None)   # null map


def test_map_keys_values_keep_raw_path_size_decomposes():
    """map_keys/map_values observe null-VALUED entries the decomposed
    arrays drop, so they keep the raw host path; size(m) rides the
    split's dedicated count column on device."""
    from spark_rapids_tpu.expr.collections import MapKeys, MapValues, Size
    s = TpuSession({})
    df = _num_df(s)
    out = df.select(MapKeys(col("m")).alias("ks"),
                    MapValues(col("m")).alias("vs"),
                    Size(col("m")).alias("sz"))
    assert "MapDecomposeExec" not in out.explain()
    rows = sorted(out.collect(), key=str)
    ov, meta = out._overridden(quiet=True)
    assert rows == sorted(collect_host(meta.exec_node, s.conf), key=str)
    by_k = {tuple(r[0]) if r[0] is not None else None: r for r in rows}
    assert by_k[(1, 2)][1][1] % 10 == 0     # vals aligned to sorted keys
    assert by_k[None][2] == -1              # legacy size(null) = -1
    # size-only (plus lookups) decomposes
    out2 = df.select(Size(col("m")).alias("sz"))
    assert "MapDecomposeExec" in out2.explain()
    assert sorted(r[0] for r in out2.collect()) == \
        sorted(r[2] for r in rows)


def test_map_decomposition_null_values_and_size_exact():
    """Entries with null VALUES: lookups return null exactly as the raw
    path does, and size still counts them (review finding: they are
    dropped from the device arrays but ride the size column)."""
    schema = T.Schema([
        T.StructField("i", T.IntegerType()),
        T.StructField("m", T.MapType(T.IntegerType(), T.LongType()))])
    from spark_rapids_tpu.expr.collections import Size
    s = TpuSession({})
    df = s.from_pydict(
        {"i": [0, 1, 2],
         "m": [{1: 10, 2: None}, None, {2: 7}]}, schema)
    out = df.select(col("i"),
                    GetMapValue(col("m"), lit(np.int32(2))).alias("v2"),
                    Size(col("m")).alias("sz"))
    assert "MapDecomposeExec" in out.explain()
    rows = sorted(out.collect())
    ov, meta = out._overridden(quiet=True)
    assert rows == sorted(collect_host(meta.exec_node, s.conf))
    assert rows == [(0, None, 2), (1, None, -1), (2, 7, 1)]


def test_map_decomposition_rejects_shadowed_and_encoded_types():
    """Review findings: a projection reusing the map's name for another
    column disqualifies the rewrite (no scoping), and date/timestamp
    valued maps stay raw (their python values are not the storage
    encoding)."""
    import datetime
    from spark_rapids_tpu.expr.collections import Size
    s = TpuSession({})
    # alias shadowing
    schema = T.Schema([
        T.StructField("m", T.MapType(T.IntegerType(), T.LongType())),
        T.StructField("arr", T.ArrayType(T.IntegerType()))])
    df = s.from_pydict(
        {"m": [{1: 5}, {2: 6}], "arr": [[1, 2], [3]]}, schema)
    q = df.select(GetMapValue(col("m"), lit(np.int32(1))).alias("x"),
                  col("arr").alias("m"))         .select(Size(col("m")).alias("n"), col("x"))
    assert "MapDecomposeExec" not in q.explain()
    assert sorted(q.collect()) == [(1, None), (2, 5)]
    # date-valued maps keep the raw path end to end
    dschema = T.Schema([
        T.StructField("m", T.MapType(T.IntegerType(), T.DateType()))])
    ddf = s.from_pydict(
        {"m": [{1: datetime.date(2020, 5, 17)}, None]}, dschema)
    dq = ddf.select(GetMapValue(col("m"), lit(np.int32(1))).alias("d"))
    assert "MapDecomposeExec" not in dq.explain()
    got = sorted(dq.collect(), key=str)
    assert datetime.date(2020, 5, 17) in [g[0] for g in got]


def test_map_decomposition_aggregate_and_filter():
    from spark_rapids_tpu.expr.aggregates import Sum
    s = TpuSession({})
    n = 40
    df = _num_df(s, n)
    got = df.where(GetMapValue(col("m"), lit(np.int32(1))) >= lit(0)) \
        .agg(Sum(GetMapValue(col("m"), lit(np.int32(1)))).alias("s")) \
        .collect()
    assert got == [(sum(i for i in range(n) if i % 7 != 3),)]


def test_map_decomposition_disabled_by_conf():
    s = TpuSession({"spark.rapids.sql.decomposeMaps": "false"})
    out = _num_df(s).select(
        GetMapValue(col("m"), lit(np.int32(1))).alias("a"))
    ex = out.explain()
    assert "MapDecomposeExec" not in ex
    assert "map columns are host-only" in ex
    assert len(out.collect()) == 40


def test_bare_map_use_keeps_raw_path():
    """Selecting the map itself (or string-keyed maps) must keep the
    raw host path — users observe the map column, not split arrays."""
    s = TpuSession({})
    df = _num_df(s)
    bare = df.select(col("k"), col("m"))
    assert "MapDecomposeExec" not in bare.explain()
    rows = sorted(bare.collect())
    assert rows[1][1] == {1: 1, 2: 10}
    # scan straight to collect (no project at all)
    assert sorted(_num_df(s).collect())[1][1] == {1: 1, 2: 10}
    # string keys decompose too now — through the key-hash path — and
    # literal lookups still return the right values
    sdf = _df(s)
    out = sdf.select(GetMapValue(col("m"), lit("a")).alias("a"))
    assert "MapDecomposeExec" in out.explain()


def test_map_decomposition_fuzz_device_vs_host(rng):
    """Fuzzed maps through filter+extraction on device == host oracle
    (the VERDICT's 'map fuzz tests run on device' criterion)."""
    n = 500
    keys_pool = np.arange(8, dtype=np.int64)
    maps = []
    for i in range(n):
        if rng.random() < 0.1:
            maps.append(None)
        else:
            kk = rng.choice(keys_pool, size=rng.integers(0, 6),
                            replace=False)
            maps.append({int(k): float(rng.normal()) for k in kk})
    schema = T.Schema([
        T.StructField("i", T.IntegerType()),
        T.StructField("m", T.MapType(T.LongType(), T.DoubleType()))])
    s = TpuSession({})
    df = s.from_pydict({"i": np.arange(n, dtype=np.int32), "m": maps},
                       schema, partitions=3, rows_per_batch=64)
    out = df.select(
        col("i"), GetMapValue(col("m"), lit(np.int64(3))).alias("v3")) \
        .where(col("i") % lit(np.int32(2)) == lit(np.int32(0)))
    assert "MapDecomposeExec" in out.explain()
    dev = sorted(out.collect(), key=str)
    ov, meta = out._overridden(quiet=True)
    host = sorted(collect_host(meta.exec_node, s.conf), key=str)
    assert len(dev) == len(host) == n // 2
    for d, h in zip(dev, host):
        assert d[0] == h[0]
        if d[1] is None or h[1] is None:
            assert d[1] == h[1]
        else:
            assert abs(d[1] - h[1]) < 1e-12


# -- string-key device decomposition (VERDICT r4 item 9) ---------------------

STR_SCHEMA = T.Schema([
    T.StructField("k", T.IntegerType()),
    T.StructField("m", T.MapType(T.StringType(), T.DoubleType())),
])


def _str_df(s, n=40):
    return s.from_pydict(
        {"k": list(range(n)),
         "m": [None if i % 7 == 3 else
               {"weight": float(i), "height": i * 0.5, "nul": None}
               if i % 2 else {"weight": float(i)}
               for i in range(n)]},
        STR_SCHEMA, partitions=2, rows_per_batch=8)


def test_string_key_map_lookup_on_device():
    """String-key maps decompose through a 64-bit key hash: m['height']
    runs on device as an int64 MapLookup (reference runs GetMapValue on
    device for string keys, complexTypeExtractors.scala)."""
    s = TpuSession({})
    df = _str_df(s)
    out = df.select(col("k"),
                    GetMapValue(col("m"), lit("height")).alias("h"),
                    GetMapValue(col("m"), lit("nul")).alias("z"))
    ex = out.explain()
    assert "MapDecomposeExec" in ex
    assert "GetMapValue" not in ex
    assert "* ProjectExec" in ex          # extraction on the device
    rows = sorted(out.collect())
    ov, meta = out._overridden(quiet=True)
    assert rows == sorted(collect_host(meta.exec_node, s.conf))
    assert rows[1] == (1, 0.5, None)      # present key; null-valued key
    assert rows[2] == (2, None, None)     # missing key
    assert rows[3] == (3, None, None)     # null map


def test_string_key_map_nonliteral_key_keeps_raw_path():
    """A data-dependent lookup key has nothing to hash at plan time:
    the map keeps the raw host path (explain shows GetMapValue)."""
    s = TpuSession({})
    df = s.from_pydict(
        {"k": ["weight", "height"],
         "m": [{"weight": 1.0}, {"height": 2.0}]},
        T.Schema([T.StructField("k", T.StringType()),
                  T.StructField("m",
                                T.MapType(T.StringType(), T.DoubleType()))]),
        partitions=1)
    out = df.select(GetMapValue(col("m"), col("k")).alias("v"))
    assert "MapDecomposeExec" not in out.explain()
    assert sorted(out.collect()) == [(1.0,), (2.0,)]


def test_string_key_map_size_and_unicode():
    s = TpuSession({})
    from spark_rapids_tpu.expr.collections import Size
    df = s.from_pydict(
        {"m": [{"á": 1, "ß": None}, None, {}]},
        T.Schema([T.StructField("m",
                                T.MapType(T.StringType(),
                                          T.IntegerType()))]),
        partitions=1)
    out = df.select(Size(col("m")).alias("n"),
                    GetMapValue(col("m"), lit("á")).alias("a"))
    assert "MapDecomposeExec" in out.explain()
    assert sorted(out.collect(), key=repr) == \
        sorted([(2, 1), (-1, None), (0, None)], key=repr)

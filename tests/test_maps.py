"""MapType (host-only) + GetMapValue with fallback tagging.

Reference: GetMapValue (complexTypeExtractors) and the
unsupported-type degradation model (RapidsMeta.willNotWorkOnGpu): map
columns run on the host with explain reasons; once projected away the
plan returns to the device.
"""
import numpy as np
import pytest

from spark_rapids_tpu import types as T
from spark_rapids_tpu.exec.core import collect_host
from spark_rapids_tpu.expr.collections import GetMapValue
from spark_rapids_tpu.expr.core import col, lit
from spark_rapids_tpu.session import TpuSession

SCHEMA = T.Schema([
    T.StructField("k", T.IntegerType()),
    T.StructField("m", T.MapType(T.StringType(), T.LongType())),
])


def _df(s, n=30):
    return s.from_pydict(
        {"k": list(range(n)),
         "m": [None if i % 7 == 3 else
               {"a": i, "b": i * 10} if i % 2 else {"a": i}
               for i in range(n)]},
        SCHEMA, partitions=2, rows_per_batch=8)


def test_map_roundtrip_and_fallback_tagging():
    s = TpuSession({})
    df = _df(s)
    plan = df.explain()
    assert "map columns are host-only" in plan
    rows = sorted(df.collect())
    assert rows[3][1] is None
    assert rows[1][1] == {"a": 1, "b": 10}


def test_get_map_value():
    s = TpuSession({})
    out = _df(s).select(col("k"),
                        GetMapValue(col("m"), lit("b")).alias("b"))
    rows = sorted(out.collect())
    ov, meta = out._overridden(quiet=True)
    host = sorted(collect_host(meta.exec_node, s.conf))
    assert rows == host
    assert rows[1] == (1, 10)      # has "b"
    assert rows[2] == (2, None)    # missing key -> null
    assert rows[3] == (3, None)    # null map -> null


def test_plan_returns_to_device_after_dropping_map():
    """Projecting the map away puts downstream operators back on the
    device (transition inserted at the boundary)."""
    from spark_rapids_tpu.expr.aggregates import Sum
    s = TpuSession({})
    out = _df(s).select(col("k"), GetMapValue(col("m"), lit("a"))
                        .alias("a")) \
        .where(col("a") >= lit(0)) \
        .group_by().agg(Sum(col("a")).alias("sa"))
    plan = out.explain()
    assert "BackendSwitch" in plan or "*" in plan.splitlines()[0]
    rows = out.collect()
    ov, meta = out._overridden(quiet=True)
    assert rows == collect_host(meta.exec_node, s.conf)


def test_map_arrow_roundtrip(tmp_path):
    import pyarrow.parquet as pq
    s = TpuSession({})
    table = _df(s).to_arrow()
    p = str(tmp_path / "m.parquet")
    pq.write_table(table, p)
    back = s.read_parquet(p)
    rows = sorted(back.collect())
    assert rows == sorted(_df(s).collect())


def test_device_plan_above_dropped_map_column():
    """A device node directly above a map-carrying host child must not
    force a map upload (review repro: df.select(k) over a map scan
    crashed in host_to_device)."""
    s = TpuSession({})
    out = _df(s).select(col("k")).where(col("k") > lit(5))
    rows = sorted(out.collect())
    assert rows == [(i,) for i in range(6, 30)]
    ov, meta = out._overridden(quiet=True)
    assert rows == sorted(collect_host(meta.exec_node, s.conf))


def test_get_map_value_date_values():
    """Date/timestamp map values get the engine encodings through
    HostColumn.from_values (review repro: raw datetime.date crashed the
    int buffer assignment)."""
    import datetime as dt
    schema = T.Schema([
        T.StructField("m", T.MapType(T.StringType(), T.DateType()))])
    s = TpuSession({})
    df = s.from_pydict(
        {"m": [{"d": dt.date(2020, 1, i + 1)} for i in range(5)]}, schema)
    out = df.select(GetMapValue(col("m"), lit("d")).alias("d"))
    rows = sorted(out.collect())
    assert rows[0] == (dt.date(2020, 1, 1),)

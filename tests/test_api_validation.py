"""Public-API drift gate (reference api_validation/ApiValidation.scala:
26-60: reflection-diff of exec constructor signatures per Spark version;
here the diff is against the committed snapshot, so accidental surface
changes fail loudly and intentional ones are an explicit regeneration).
"""
import json
import os
import sys


def test_api_surface_matches_snapshot():
    scripts = os.path.join(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))), "scripts")
    sys.path.insert(0, scripts)
    try:
        from gen_api_surface import collect_surface
    finally:
        sys.path.remove(scripts)
    got = collect_surface()
    snap_path = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                             "api_surface.json")
    with open(snap_path) as f:
        want = json.load(f)
    problems = []
    if set(got) != set(want):
        problems.append(f"sections drifted: +{sorted(set(got) - set(want))} "
                        f"-{sorted(set(want) - set(got))}")
    for section in sorted(set(got) & set(want)):
        g, w = got[section], want[section]
        if g == w:
            continue
        if isinstance(w, dict):
            added = sorted(set(g) - set(w))
            removed = sorted(set(w) - set(g))
            changed = sorted(k for k in set(g) & set(w) if g[k] != w[k])
            problems.append(f"{section}: +{added} -{removed} ~{changed}")
        else:
            added = sorted(set(g) - set(w))
            removed = sorted(set(w) - set(g))
            problems.append(f"{section}: +{added} -{removed}")
    assert not problems, (
        "public API surface drifted from tests/api_surface.json:\n  "
        + "\n  ".join(problems)
        + "\nIf intentional, regenerate: python scripts/gen_api_surface.py")

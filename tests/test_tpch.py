"""TPC-H suite: generator sanity + queries verify vs host oracle.

Reference test pattern: tpch_test.py wraps TpchLikeSpark queries as
assertions (integration_tests/src/main/python/tpch_test.py).  Default
runs a smoke subset; TPCH_FULL=1 sweeps all 22 (committed full pass:
artifacts/tpch_22_sf001_verify.txt).
"""
import os

import pytest

from spark_rapids_tpu.bench.runner import run_benchmark
from spark_rapids_tpu.bench.tpch_gen import generate_tpch, table_row_counts
from spark_rapids_tpu.bench.tpch_queries import TPCH_QUERIES


@pytest.fixture(scope="module")
def data_dir(tmp_path_factory):
    d = str(tmp_path_factory.mktemp("tpch") / "sf001")
    generate_tpch(d, sf=0.01)
    return d


def test_row_counts_scale():
    c1 = table_row_counts(1.0)
    assert c1["lineitem"] == 6_000_000
    assert c1["nation"] == 25 and c1["region"] == 5
    assert table_row_counts(0.1)["orders"] == 150_000


def test_all_22_queries_registered():
    assert len(TPCH_QUERIES) == 22
    assert all(f"q{i}" in TPCH_QUERIES for i in range(1, 23))


_SMOKE = ["q1", "q3", "q6", "q13", "q16", "q18", "q21"]
_SUITE = sorted(TPCH_QUERIES) if os.environ.get("TPCH_FULL") == "1" \
    else _SMOKE


@pytest.mark.parametrize("query", _SUITE)
def test_query_device_matches_oracle(data_dir, query):
    r = run_benchmark(data_dir, 0.01, [query], verify=True,
                      generate=False, suite="tpch")[0]
    assert "error" not in r, r
    assert r["ok"], r
